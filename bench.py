#!/usr/bin/env python
"""Thin wrapper: the benchmark lives in chandy_lamport_tpu/bench.py so it
works both from a repo checkout (this script) and from an installed package
(``python -m chandy_lamport_tpu bench``). Prints ONE JSON line on stdout and
exits 0 in every environment; see the package module for the fallback
ladder."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from chandy_lamport_tpu.bench import main

if __name__ == "__main__":
    sys.exit(main())
