#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line with the north-star metric.

Metric (BASELINE.md / BASELINE.json): node-ticks/sec/chip on the 1k-node
scale-free graph with multiple concurrent snapshot initiators per instance
(config 4 of the ladder). node-ticks = Σ over instances of N × ticks
executed; throughput comes from the vmap instance axis while each tick's
sequential source fold preserves the reference scheduler semantics
(sim.go:71-95).

The reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` is reported against the BASELINE.json north-star target of
10M node-ticks/sec/chip (value 1.0 == target met).

Runs on whatever jax.devices() offers (the driver runs it on one real TPU
chip); uses the fast counter-based delay sampler — no x64 required. All
diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

import argparse
import json
import sys
import time

import jax
import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=1024)
    p.add_argument("--attach", type=int, default=2, help="scale-free out-arcs per node")
    p.add_argument("--batch", type=int, default=2048, help="vmap'd instances")
    p.add_argument("--phases", type=int, default=32, help="storm phases (ticks with traffic)")
    p.add_argument("--snapshots", type=int, default=8, help="concurrent initiators per instance")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--scheduler", choices=["sync", "exact"], default="sync",
                   help="sync = vectorized simultaneous delivery (production "
                        "path); exact = reference-semantics sequential fold")
    p.add_argument("--target", type=float, default=10e6,
                   help="north-star node-ticks/sec/chip (BASELINE.json)")
    args = p.parse_args()

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import UniformJaxDelay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"N={args.nodes} B={args.batch} phases={args.phases}")

    spec = scale_free(args.nodes, args.attach, seed=3,
                      tokens=args.phases + 10)
    cfg = SimConfig(queue_capacity=16, max_snapshots=max(8, args.snapshots),
                    max_recorded=16)
    runner = BatchedRunner(spec, cfg, UniformJaxDelay(seed=17), batch=args.batch,
                           scheduler=args.scheduler)
    topo = runner.topo
    log(f"graph: {topo.n} nodes, {topo.e} edges, max out-degree {topo.d}")
    prog = storm_program(
        topo, phases=args.phases, amount=1,
        snapshot_phases=staggered_snapshots(topo, args.snapshots, 1, 2))

    # warmup: compile + one full execution
    t0 = time.perf_counter()
    final = runner.run_storm(runner.init_batch(), prog)
    jax.block_until_ready(final)
    log(f"warmup (compile + run): {time.perf_counter() - t0:.1f}s")
    summary = BatchedRunner.summarize(final)
    log(f"summary: {summary}")
    if summary["error_lanes"]:
        log("ERROR: lanes with error flags — results invalid")
        sys.exit(1)
    if summary["snapshots_completed"] != summary["snapshots_started"]:
        log("ERROR: incomplete snapshots")
        sys.exit(1)

    times = []
    node_ticks = []
    for r in range(args.repeats):
        state = runner.init_batch()
        jax.block_until_ready(state)
        t0 = time.perf_counter()
        final = runner.run_storm(state, prog)
        jax.block_until_ready(final)
        dt = time.perf_counter() - t0
        total_ticks = int(np.asarray(jax.device_get(final.time)).sum())
        times.append(dt)
        node_ticks.append(total_ticks * topo.n)
        log(f"run {r}: {dt:.3f}s, {total_ticks} total ticks "
            f"-> {node_ticks[-1] / dt / 1e6:.2f}M node-ticks/s")

    best = max(nt / dt for nt, dt in zip(node_ticks, times))
    print(json.dumps({
        "metric": "node_ticks_per_sec_per_chip",
        "value": round(best, 1),
        "unit": "node-ticks/s/chip",
        "vs_baseline": round(best / args.target, 3),
    }))


if __name__ == "__main__":
    main()
