"""chandy_lamport_tpu — a TPU-native distributed-snapshot simulation framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
Chandy-Lamport distributed snapshot simulator (a single-process Go
discrete-time simulator of token-passing nodes, validated by 21 golden
snapshot fixtures). Instead of translating the Go object graph, the system
state is a pytree of dense arrays advanced by jitted state-transition kernels,
batched with ``vmap`` over independent simulation instances and sharded with
``shard_map`` over a ``jax.sharding.Mesh``.

Layers (mirroring reference layers L0-L4, SURVEY.md §1):
  - ``core.spec``      message/snapshot/event types (reference common.go)
  - ``core.parity``    pure-Python oracle, bit-exact vs the Go reference
  - ``core.state``     string-id graphs -> dense edge encoding + array state
  - ``core.dense``     single-instance JAX backend over that state
  - ``ops``            gorand PRNG, ring buffers, the jitted tick kernels
  - ``models``         graph generators, delay models, storm workloads
  - ``parallel``       mesh/sharding: instance-parallel + graph-sharded modes
  - ``utils``          fixture parsers, golden comparison, tracing, checkpoint
"""

from chandy_lamport_tpu.config import SimConfig, MAX_DELAY
from chandy_lamport_tpu.core.spec import (
    Message,
    MsgSnapshot,
    GlobalSnapshot,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.api import run_events_file, run_events, make_backend

__version__ = "0.1.0"

__all__ = [
    "SimConfig",
    "MAX_DELAY",
    "Message",
    "MsgSnapshot",
    "GlobalSnapshot",
    "PassTokenEvent",
    "SnapshotEvent",
    "TickEvent",
    "run_events_file",
    "run_events",
    "make_backend",
    "__version__",
]
