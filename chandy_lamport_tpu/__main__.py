import sys

from chandy_lamport_tpu.cli import main

sys.exit(main())
