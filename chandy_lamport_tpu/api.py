"""Public API: run event scripts against any backend.

The reference's only entry point is ``go test`` driving
``readTopologyFile`` + ``readEventsFile`` (test_common.go:29,79). This module
is the framework's equivalent front door, with the backend made explicit
(SimulatorBackend seam, SURVEY.md §7.2.7):

  - ``parity``  pure-Python oracle (core/parity.py)
  - ``jax``     dense jitted single-instance kernel (ops/tick.py)

Both accept any DelayModel; bit-exact golden reproduction requires
``GoExactDelay(REFERENCE_TEST_SEED + 1)`` (snapshot_test.go:20).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from chandy_lamport_tpu.config import MAX_DELAY, REFERENCE_TEST_SEED, SimConfig
from chandy_lamport_tpu.core.spec import Event, GlobalSnapshot
from chandy_lamport_tpu.models.delay import DelayModel, GoExactDelay
from chandy_lamport_tpu.utils.fixtures import (
    TopologySpec,
    read_events_file,
    read_topology_file,
)


def make_backend(name: str, topology: TopologySpec, delay_model: DelayModel,
                 config: Optional[SimConfig] = None, trace: bool = False,
                 exact_impl: str = "cascade", faults=None):
    if name == "parity":
        if exact_impl != "cascade":
            raise ValueError(
                "exact_impl is a jax-backend knob (the parity oracle has "
                "one reference-literal implementation); use backend='jax'")
        if faults is not None:
            raise ValueError(
                "the fault adversary is a jax-backend feature (the parity "
                "oracle is the uninjured reference); use backend='jax'")
        from chandy_lamport_tpu.core.parity import ParitySim

        sim = ParitySim(delay_model,
                        max_delay=getattr(delay_model, "max_delay", MAX_DELAY),
                        trace=trace)
        for nid, tokens in topology.nodes:
            sim.add_node(nid, tokens)
        for src, dest in topology.links:
            sim.add_link(src, dest)
        return sim
    if name == "jax":
        from chandy_lamport_tpu.core.dense import DenseSim

        jtrace = None
        if trace:
            # the device flight recorder (utils/tracing.JaxTrace): events
            # are captured INSIDE the jitted kernels as packed ring writes
            # and decoded host-side into the same epoch format the parity
            # logger prints — sim.trace.pretty() on either backend
            from chandy_lamport_tpu.utils.tracing import JaxTrace

            jtrace = JaxTrace()
        return DenseSim(topology, delay_model, config or SimConfig(),
                        exact_impl=exact_impl, faults=faults, trace=jtrace)
    raise ValueError(f"unknown backend {name!r} (expected 'parity' or 'jax')")


def run_events(backend_name: str, topology: TopologySpec, events: List[Event],
               delay_model: DelayModel, config: Optional[SimConfig] = None,
               trace: bool = False, exact_impl: str = "cascade", faults=None):
    """Run a parsed event script to completion; returns (snapshots, sim).

    ``exact_impl`` (jax backend only): "cascade" (default), "wave", or
    "fold" — the bit-identical formulations of the reference scheduler
    (ops/tick.TickKernel docstring; "wave" requires a position-addressable
    delay sampler such as FixedDelay's or HashJaxDelay's streams).
    ``faults`` (jax backend only): a models/faults.JaxFaults adversary —
    the zero-rate engine is the golden-parity differential oracle
    (tests/test_faults.py)."""
    sim = make_backend(backend_name, topology, delay_model, config,
                       trace=trace, exact_impl=exact_impl, faults=faults)
    if backend_name == "parity":
        from chandy_lamport_tpu.core.parity import run_events as _run

        return _run(sim, events), sim
    return sim.run_events(events), sim


def run_events_file(top_path: str, events_path: str, backend: str = "parity",
                    seed: int = REFERENCE_TEST_SEED + 1,
                    delay_model: Optional[DelayModel] = None,
                    config: Optional[SimConfig] = None,
                    trace: bool = False, exact_impl: str = "cascade",
                    faults=None) -> Tuple[List[GlobalSnapshot], object]:
    """Parse fixture files and run them — the ``runTest`` equivalent
    (snapshot_test.go:11-44) minus the assertions."""
    topology = read_topology_file(top_path)
    events = read_events_file(events_path)
    dm = delay_model if delay_model is not None else GoExactDelay(seed)
    return run_events(backend, topology, events, dm, config, trace=trace,
                      exact_impl=exact_impl, faults=faults)
