"""Benchmark harness — north-star metric with hardened backend handling.

Metric (BASELINE.md / BASELINE.json): node-ticks/sec/chip on the 1k-node
scale-free graph with multiple concurrent snapshot initiators per instance
(config 4 of the ladder). node-ticks = Σ over instances of N × ticks
executed; throughput comes from the vmap instance axis while each tick
preserves deterministic scheduler semantics (reference hot loop:
/root/reference/chandy_lamport/sim.go:71-95).

The reference publishes no performance numbers (BASELINE.md), so
``vs_baseline`` is reported against the BASELINE.json north-star target of
10M node-ticks/sec/chip (value 1.0 == target met).

Structure (the round-1 bench died when the TPU plugin failed to init —
one un-guarded ``jax.devices()`` zeroed the whole perf axis; this is the
fix):

* ``main()`` — orchestrator. Never imports jax. Runs the measurement in a
  subprocess and, when the backend fails to initialize or the attempt hangs,
  walks a fallback ladder that only abandons the TPU after giving it every
  realistic shot (the round-3 official number was a CPU fallback because one
  900s hang skipped straight past the TPU):

    1. ``probe`` — a tiny jit in a subprocess with a short timeout. Answers
       "is the device tunnel alive?" in ~15s instead of discovering a hang
       after the full-attempt budget. A hung probe is retried once (tunnel
       flakes are often transient), then re-asked with jax's automatic
       platform choice (covers the round-1 plugin-init failure).
    2. ``default`` — the full-size measurement. Retried once after a hang:
       the persistent XLA compilation cache (enabled below) makes the
       second attempt skip the multi-minute compile that dominated the
       first, so a retry fits where the original attempt timed out.
    3. ``tpu-small`` — a reduced-batch TPU attempt (batch<=256, 1 repeat).
       A small TPU number beats a CPU number: it keeps the platform axis
       honest even when the tunnel can't sustain the full-size window.
    4. ``cpu`` — last resort, reduced workload, clearly labeled.

  ALWAYS prints exactly one JSON line on stdout and exits 0. The JSON
  carries ``platform`` / ``device_kind`` so a CPU fallback can never
  masquerade as a TPU number.
* ``worker`` mode (``--worker``) — the actual measurement; exit 3 means
  "backend init failed, retry me elsewhere", any other nonzero exit is a
  real failure (not retried on another platform).
* ``probe`` mode (``--probe``) — jax.devices() + a tiny jit, then one JSON
  line {"probe": "ok", "platform": ...}. Run under a short timeout.

Every subprocess gets ``JAX_COMPILATION_CACHE_DIR`` pointed at an in-repo
cache directory, so repeat invocations (the orchestrator's retry, or the
driver re-running the bench) skip XLA compilation entirely.

All diagnostics go to stderr; stdout carries exactly the one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

EXIT_BACKEND_INIT = 3  # worker: backend unavailable -> orchestrator retries

# Platforms whose plugins are known-unusable for the bench: experimental
# device tunnels observed to HANG jax.devices() for the full probe budget
# rather than fail fast (the BENCH_r05 ladder burned 120s+600s per
# invocation re-discovering this). When the probe subprocess sees one of
# these SELECTED, it arms a short watchdog and prints a "dead" verdict
# instead of letting the orchestrator's timeout expire; the orchestrator
# records it in the probe-verdict cache and skips straight to the cpu
# rung (no tpu-blind attempt — the hang is structural, not a flake).
UNUSABLE_PLATFORMS = ("axon",)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# persistent XLA compilation cache: the bench's dominant warmup cost is the
# multi-minute XLA compile of the storm program; caching it in-repo means a
# retry after a hang (or the driver's next invocation) pays seconds, not
# minutes. Overridable so tests can isolate.
CACHE_DIR = os.environ.get("CLSIM_CACHE_DIR",
                           os.path.join(_PKG_ROOT, ".xla_cache"))
# probe-verdict cache: the round-5 runs burned >12 minutes re-discovering a
# dead device tunnel (probe/probe-retry/probe-auto at 120s each + the 600s
# tpu-blind attempt). The ladder's verdict is cached here with a timestamp;
# within the TTL a live verdict is reused outright (zero probe subprocesses)
# and a dead verdict shrinks the re-probe and tpu-blind budgets to a quick
# re-check. --no-probe-cache opts out.
PROBE_CACHE_PATH = os.path.join(CACHE_DIR, "probe_verdict.json")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _enable_compile_cache(platform: str) -> None:
    """Turn on jax's persistent compilation cache (call before the first
    jit, after the backend platform is known). TPU-only: XLA:CPU AOT cache
    entries record host machine features and reloading them warns about
    possible SIGILL on feature mismatch — CPU compiles are fast anyway."""
    if platform != "tpu":
        return
    import jax

    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        # default thresholds skip "cheap" compiles; the storm program's
        # per-shape compiles are exactly what we want cached, always
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as exc:  # cache is an optimization, never a failure
        log(f"compilation cache unavailable: {type(exc).__name__}: {exc}")


def fused_emulated(runner) -> bool:
    """True when the runner's fused megatick dispatches run as
    interpret-mode (CPU-emulated) Pallas — stamped into every JSON row
    next to fused_tick so a CPU-gauge fused row can never be mistaken
    for a TPU fused win while the tunnel stays dead (TPU-blind since
    r03). False whenever fused_tick resolved "off" (nothing fused ran)
    or the kernels compiled for real hardware."""
    if getattr(runner, "fused", "off") != "on":
        return False
    kern = getattr(runner, "kernel", runner)
    return bool(getattr(kern, "_pl_interpret", False))


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="bench")
    p.add_argument("--nodes", type=int, default=1024)
    p.add_argument("--graph", choices=["sf", "ring", "er"], default="sf",
                   help="topology family: scale-free (config 4), ring "
                        "(config 2), Erdős–Rényi avg-degree 3 (config 3)")
    p.add_argument("--attach", type=int, default=2, help="scale-free out-arcs per node")
    p.add_argument("--batch", type=int, default=2048, help="vmap'd instances")
    p.add_argument("--phases", type=int, default=32, help="storm phases (ticks with traffic)")
    p.add_argument("--snapshots", type=int, default=8, help="concurrent initiators per instance")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                   default="cascade",
                   help="bit-exact tick formulation when --scheduler exact "
                        "(ops/tick.TickKernel): 'wave' parallelizes same-"
                        "tick markers across destinations — bit-identical "
                        "for the hash/fixed samplers, fastest at marker-"
                        "heavy shapes")
    p.add_argument("--scheduler", choices=["sync", "exact"], default="sync",
                   help="sync = vectorized simultaneous delivery (production "
                        "path); exact = reference-semantics sequential fold")
    p.add_argument("--megatick", type=int, default=1,
                   help="--scheduler exact: K-tick fusion depth for the "
                        "multi-tick loops (the drain advances K scan-fused "
                        "ticks per loop iteration, drained stretches fast-"
                        "forward in O(1); ops/tick.TickKernel docstring). "
                        "Default 1: the bench is the BATCHED path, where "
                        "the fused scan's masked lax.cond computes both "
                        "branches per step under vmap — the measured "
                        "sf-256 B=64 wave gauge ran 2.2x faster unfused "
                        "(the same asymmetry behind BatchedRunner's "
                        "megatick=1 default; K>1 pays only on the "
                        "dispatch-bound single-instance path). "
                        "Semantics-preserving either way")
    p.add_argument("--queue-engine", choices=["auto", "gather", "mask"],
                   default="auto",
                   help="ring-queue addressing (ops/tick.TickKernel): "
                        "'gather' = O(E) packed-plane head gathers + append "
                        "scatters, 'mask' = the O(E·C) one-hot formulation, "
                        "'auto' (default) = backend-resolved (gather on "
                        "TPU, mask on CPU where XLA serializes scatters — "
                        "ops/tick.resolve_queue_engine). Bit-identical "
                        "results; the JSON row's queue_engine field "
                        "records the RESOLVED engine")
    p.add_argument("--kernel-engine", choices=["auto", "xla", "pallas"],
                   default="auto",
                   help="tick-kernel engine (chandy_lamport_tpu.kernels): "
                        "'xla' = the stock-XLA tick formulations, 'pallas' "
                        "= the fused Pallas ring-queue + segment-reduction "
                        "kernels (interpret-mode emulation off-TPU), 'auto' "
                        "(default) = pallas only where compiled Pallas "
                        "exists (TPU), xla elsewhere with a logged reason "
                        "(kernels.resolve_kernel_engine). Bit-identical "
                        "results; the JSON row's kernel_engine field "
                        "records the RESOLVED engine")
    p.add_argument("--fused-tick", choices=["auto", "on", "off"],
                   default="auto",
                   help="one-kernel megatick (kernels/megatick.py): 'on' = "
                        "run every exact-path multi-tick/drain/flush loop "
                        "as ONE Pallas kernel scanning K full ticks with "
                        "the whole state VMEM-resident (requires "
                        "--kernel-engine pallas and --megatick > 1; raises "
                        "naming the first unmet requirement otherwise), "
                        "'off' = the split per-stage kernels, 'auto' "
                        "(default) = fuse exactly when the requirements "
                        "hold and the working set fits the VMEM budget "
                        "(megatick.resolve_fused_tick). Bit-identical "
                        "results; the JSON row's fused_tick field records "
                        "the RESOLUTION ('on'/'off')")
    p.add_argument("--fused-tile", choices=["auto", "on", "off"],
                   default="auto",
                   help="tiled-state layout of the fused megatick "
                        "(kernels/megatick.resolve_fused_tile): 'on' = "
                        "stream the [E, C] ring planes HBM->VMEM per step "
                        "so fused execution survives states past the "
                        "12 MB VMEM budget, 'off' = rings stay in the "
                        "VMEM carry (refusing shapes that overflow), "
                        "'auto' (default) = tile exactly when the "
                        "resident layout would not fit. Bit-identical "
                        "results; the JSON row's fused_tile field records "
                        "the RESOLUTION")
    p.add_argument("--fused-block-edges", type=int, default=0,
                   help="fault-plane DMA block width for the fused "
                        "megatick's double-buffered HBM->VMEM edge-mask "
                        "stream (kernels/megatick.plan_edge_blocks); 0 = "
                        "the default 512-edge blocks")
    p.add_argument("--comm-engine", choices=["auto", "dense", "sparse"],
                   default="auto",
                   help="--graphshard only: cross-shard traffic engine "
                        "(parallel/graphshard): 'dense' = full-plane "
                        "psum/all_gather + incidence matmuls, 'sparse' = "
                        "boundary-edge halo exchange over ppermute with "
                        "O(E_local) segment reductions, 'auto' (default) = "
                        "ops/tick.resolve_comm_engine. Bit-identical "
                        "results; the JSON row records the RESOLVED engine "
                        "plus the analytic comm_bytes_model. With "
                        "--graphshard, --megatick K also fuses K drain "
                        "ticks per dispatch inside the shard_map body")
    p.add_argument("--capacity", type=int, default=0,
                   help="per-edge queue slots; 0 = size to the workload "
                        "(SimConfig.for_workload)")
    p.add_argument("--max-recorded", type=int, default=0,
                   help="per-edge recorded-arrival log slots L (0 = derived "
                        "from the snapshot count by SimConfig.for_workload); "
                        "ERR_RECORD_OVERFLOW + the doubling retry keep a "
                        "small L honest")
    p.add_argument("--record-dtype", choices=["int16", "int32"],
                   default="int16",
                   help="log_amt[L,E] dtype; int16 halves it (amounts >= "
                        "2^15 flag ERR_VALUE_OVERFLOW; the bench sends "
                        "amount=1)")
    p.add_argument("--window-dtype", choices=["uint16", "int32"],
                   default="int32",
                   help="rec_start/rec_end[S,E] dtype; uint16 stores the "
                        "window counters mod 2^16 (decode-identical, "
                        "SimConfig docstring) and halves the top profile "
                        "line (the every-tick window-counter writes); "
                        "default stays int32 until the TPU A/B "
                        "(tools/r4_measure.py step 6) confirms the win")
    p.add_argument("--layouts", choices=["auto", "default"], default="auto",
                   help="jit-boundary array layouts: 'auto' lets XLA keep "
                        "its loop-preferred [B,S,E] layouts across the "
                        "dispatch boundary (kills the {0,2,1}<->{0,1,2} "
                        "transpose copies, 22%% of a bare round-3 tick — "
                        "timed states are built directly in the compiled "
                        "layouts); 'default' forces row-major boundaries "
                        "(the round-3/4 behavior) for A/B")
    p.add_argument("--delay", choices=["uniform", "hash"], default="hash",
                   help="fast-path delay sampler: the fused counter-hash "
                        "HashJaxDelay (default — same distribution as the "
                        "threefry UniformJaxDelay, ~10%% faster at the "
                        "bench shape) or 'uniform' for the threefry stream")
    p.add_argument("--graphshard", type=int, default=0, metavar="K",
                   help="measure the graph-sharded runner (one giant "
                        "instance over a K-device 'graph' mesh, "
                        "parallel/graphshard) instead of the vmap-batched "
                        "kernel; K=1 on a single chip quantifies the "
                        "collective-formulation tax vs the unsharded sync "
                        "path at the same shape (VERDICT r3 #4). --batch "
                        "is ignored (B=1). Node count must divide by K.")
    p.add_argument("--snapshot-timeout", type=int, default=0, metavar="T",
                   help="snapshot supervisor (SimConfig.snapshot_timeout): "
                        "abort + retry snapshot attempts not completed "
                        "within T ticks; 0 = off (the default bench regime)")
    p.add_argument("--snapshot-retries", type=int, default=3,
                   help="retry budget per snapshot before "
                        "ERR_SNAPSHOT_TIMEOUT")
    p.add_argument("--snapshot-every", type=int, default=0, metavar="K",
                   help="snapshot daemon cadence in ticks (0 = off)")
    p.add_argument("--target", type=float, default=10e6,
                   help="north-star node-ticks/sec/chip (BASELINE.json)")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture a jax.profiler trace of one timed run into DIR")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="orchestrator: full-size-attempt wall-clock limit (s)")
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="orchestrator: TPU liveness-probe limit (s); first "
                        "device contact through the tunnel takes ~15-60s")
    p.add_argument("--assume-tpu", action="store_true",
                   help="skip the liveness-probe ladder and go straight to "
                        "the full-size TPU attempt. For callers that just "
                        "probed themselves (tools/probe_loop.py fires the "
                        "measurement plan only on a live probe) — saves "
                        "40-120s of a short tunnel window per row. A "
                        "tunnel that wedges mid-plan then costs one "
                        "full-size worker timeout plus the labeled cpu "
                        "fallback row, which the plan's tunnel-loss "
                        "detector turns into an abort.")
    p.add_argument("--no-probe-cache", action="store_true",
                   help="ignore (and don't write) the cached probe verdict "
                        "— always run the full liveness-probe ladder")
    p.add_argument("--probe-cache-ttl", type=float, default=900.0,
                   help="seconds a cached probe verdict stays fresh: a live "
                        "verdict is reused without probing, a dead one "
                        "shrinks the re-probe + tpu-blind budgets")
    p.add_argument("--stream", action="store_true",
                   help="measure the streaming job engine "
                        "(BatchedRunner.run_stream) instead of the storm "
                        "metric: a heavy-tailed queue of --jobs jobs driven "
                        "through --batch lane slots, reported as jobs/s "
                        "with the gang-admission (static-batching) baseline "
                        "and occupancy/refill counters in the same row")
    p.add_argument("--jobs", type=int, default=0,
                   help="--stream: queue length (0 = 3x --batch)")
    p.add_argument("--stretch", type=int, default=4,
                   help="--stream: lane substeps per jitted step between "
                        "harvest/refill points")
    p.add_argument("--drain-chunk", type=int, default=32,
                   help="--stream: drain ticks per lane substep slice")
    p.add_argument("--dup-rate", type=float, default=0.0, metavar="R",
                   help="--stream: fraction of the queue that repeats a "
                        "Zipf-drawn scenario-library job byte-for-byte "
                        "(models/workloads.stream_jobs dup_rate)")
    p.add_argument("--prefix-overlap", type=float, default=0.0, metavar="R",
                   help="--stream: fraction of the queue that extends a "
                        "Zipf-drawn library job with a distinguishing "
                        "tail — near-duplicates the exact-match memo "
                        "plane cannot serve but memo=prefix can fork "
                        "(models/workloads.stream_jobs prefix_overlap; "
                        "mutually exclusive with --dup-rate)")
    p.add_argument("--memo", choices=["off", "admit", "full", "prefix"],
                   default="off",
                   help="--stream: ALSO drive the queue through the memo "
                        "plane at this level and report effective jobs/s "
                        "(served = executed + coalesced + forked) A/B "
                        "against the memo-off arm on the same "
                        "content-keyed pool; memo=prefix additionally "
                        "runs a memo=full arm so prefix_speedup isolates "
                        "the fork plane's win over exact-match memo")
    p.add_argument("--serve", action="store_true",
                   help="measure the online serving front-end "
                        "(chandy_lamport_tpu/serving.serve_run) instead of "
                        "the storm metric: a seeded Poisson/Zipf request "
                        "schedule (--jobs requests at --rate per step) "
                        "served live, reported as effective jobs/s with "
                        "occupancy, admit p50/p99, deadline misses and the "
                        "cold-vs-warm executable-cache warmup drop in the "
                        "same row")
    p.add_argument("--serve-policy", choices=["edf", "fifo"], default="edf",
                   help="--serve: admission ordering knob "
                        "(config.ENGINE_KNOBS serve_policy); the row also "
                        "carries the fifo baseline at the same schedule")
    p.add_argument("--rate", type=float, default=2.0,
                   help="--serve: open-loop Poisson arrival rate "
                        "(requests per stream step)")
    p.add_argument("--tenants", type=int, default=4,
                   help="--serve: Zipf-weighted tenant population")
    p.add_argument("--priorities", type=int, default=2,
                   help="--serve: priority classes")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="measure the crash-tolerant serving fleet "
                        "(serving/fleet.fleet_run): N spawned workers over "
                        "one WAL admission spool serving --jobs requests "
                        "at the --rate/--tenants/--priorities schedule, "
                        "reported as served jobs/s with goodput, the "
                        "latency percentiles and the WAL conservation "
                        "audit (lost/double-served must be 0) in the row; "
                        "requires --graph ring (the picklable worker "
                        "recipe reconstructs a ring-stream engine)")
    p.add_argument("--fleet-crashes", type=int, default=0, metavar="K",
                   help="--fleet: SIGKILL a live worker K times on a "
                        "fixed schedule mid-run — the degraded-mode SLO "
                        "row; leases expire, in-flight requests are "
                        "redelivered, and the audit must still balance")
    p.add_argument("--fleet-lease-ttl", type=float, default=4.0,
                   help="--fleet: lease expiry (s) before a silent "
                        "worker's in-flight requests are redelivered")
    p.add_argument("--trace", action="store_true",
                   help="arm the device flight recorder (utils/tracing.py) "
                        "during the measurement; the row gains trace_"
                        "capacity/trace_events/trace_dropped plus a "
                        "trace_overhead_pct computed against one untraced "
                        "baseline run at the same shape")
    p.add_argument("--trace-capacity", type=int, default=0, metavar="K",
                   help="ring slots per lane (0 = JaxTrace default when "
                        "--trace is set); implies --trace when > 0")
    p.add_argument("--telemetry", metavar="PATH",
                   help="append the result row as schema-versioned JSONL "
                        "telemetry (tools/analyze.py --telemetry)")
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--probe", action="store_true", help=argparse.SUPPRESS)
    return p


# ---------------------------------------------------------------------------
# probe: is the device tunnel alive? (runs in a subprocess, short timeout)
# ---------------------------------------------------------------------------

def run_probe() -> int:
    """Tiny jit on whatever platform CLSIM_PLATFORM selects; one JSON line."""
    import jax

    platform = os.environ.get("CLSIM_PLATFORM")
    if platform == "auto":
        jax.config.update("jax_platforms", "")
    elif platform:
        jax.config.update("jax_platforms", platform)
    # known-unusable platform fail-fast: the plugin may have selected
    # itself programmatically at import time (jax_platforms is set by the
    # time we read it), and its jax.devices() HANGS rather than failing —
    # arm a watchdog that declares the platform dead well inside the
    # orchestrator's probe timeout, so the ladder learns the verdict in
    # ~20s instead of burning the 120s probe + 600s tpu-blind budgets
    selected = (jax.config.jax_platforms or "").split(",")[0].strip().lower()
    watchdog = None
    if selected in UNUSABLE_PLATFORMS:
        deadline = float(os.environ.get("CLSIM_PROBE_DEADLINE", "20"))

        def _declare_dead():
            print(json.dumps({
                "probe": "dead", "platform": selected,
                "reason": f"experimental platform {selected!r} selected and "
                          f"unresponsive for {deadline:.0f}s (known to hang "
                          "jax.devices() rather than fail fast)"}),
                flush=True)
            os._exit(0)

        log(f"probe: known-unusable platform {selected!r} selected; "
            f"arming {deadline:.0f}s watchdog")
        watchdog = threading.Timer(deadline, _declare_dead)
        watchdog.daemon = True
        watchdog.start()
    try:
        dev = jax.devices()[0]
        _enable_compile_cache(dev.platform)
        import jax.numpy as jnp

        val = int(jax.jit(lambda x: x + 1)(jnp.int32(41)))
        assert val == 42
    except Exception as exc:
        log(f"probe failed: {type(exc).__name__}: {exc}")
        return EXIT_BACKEND_INIT
    finally:
        if watchdog is not None:
            watchdog.cancel()
    print(json.dumps({"probe": "ok", "platform": dev.platform,
                      "device_kind": dev.device_kind}), flush=True)
    return 0


# ---------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess under the orchestrator)
# ---------------------------------------------------------------------------

def _memory_stats(dev, state_bytes_model: int | None = None) -> dict:
    """The HBM axis of the north-star metric ("max concurrent snapshots in
    HBM"), with explicit provenance per field:

      hbm_peak_bytes / hbm_limit_bytes — the device allocator's own stats
        (authoritative; the remote tunnel reports 0/absent, VERDICT r3 #3);
      hbm_live_bytes — Σ nbytes over jax.live_arrays() on this device after
        the run: the resident state the process actually holds (a floor for
        peak, and nonzero even when the tunnel hides allocator stats);
      hbm_state_bytes_model — instance_footprint_bytes × batch, the
        capacity-planning model BASELINE.md's max-batch numbers use.
    """
    out = {}
    try:
        stats = dev.memory_stats() or {}
        out["hbm_peak_bytes"] = int(stats.get("peak_bytes_in_use", 0))
        out["hbm_limit_bytes"] = int(stats.get("bytes_limit", 0))
        # the axon tunnel has only ever reported the two keys above as
        # absent/0 (VERDICT r3 #3); if its PJRT plugin exposes allocator
        # stats under DIFFERENT names, capture them all — zeros included,
        # since learning the key set is the whole point — so the next
        # live window reveals what the plugin actually reports
        extra = {k: int(v) for k, v in stats.items()
                 if isinstance(v, (int, float))
                 and k not in ("peak_bytes_in_use", "bytes_limit")}
        if extra:
            out["hbm_allocator_stats"] = extra
    except Exception:
        pass
    try:
        import jax

        total = 0
        for a in jax.live_arrays():
            try:
                # per-device accounting: sum only the shards resident on
                # THIS device (a sharded array's .nbytes is its global size)
                for sh in a.addressable_shards:
                    if sh.device == dev:
                        total += int(sh.data.nbytes)
            except Exception:
                if dev in a.devices():
                    total += int(getattr(a, "nbytes", 0))
        out["hbm_live_bytes"] = total
    except Exception:
        pass
    if state_bytes_model is not None:
        out["hbm_state_bytes_model"] = int(state_bytes_model)
    return out


def run_worker(args) -> int:
    import jax

    # The env var JAX_PLATFORMS is not enough here: this image's TPU plugin
    # (axon) programmatically sets jax_platforms at import time, overriding
    # the environment. The orchestrator passes its platform choice via
    # CLSIM_PLATFORM and the worker forces it through jax.config, which
    # always wins.
    platform = os.environ.get("CLSIM_PLATFORM")
    if platform == "auto":
        jax.config.update("jax_platforms", "")  # jax picks best available
    elif platform:
        jax.config.update("jax_platforms", platform)
    try:
        dev = jax.devices()[0]
    except Exception as exc:  # backend init is exactly the retryable failure
        log(f"backend init failed: {type(exc).__name__}: {exc}")
        return EXIT_BACKEND_INIT
    _enable_compile_cache(dev.platform)

    import numpy as np

    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"graph={args.graph} N={args.nodes} B={args.batch} "
        f"phases={args.phases} scheduler={args.scheduler}")

    tokens = args.phases + 10
    if args.graph == "ring":
        spec = ring_topology(args.nodes, tokens=tokens)
    elif args.graph == "er":
        spec = erdos_renyi(args.nodes, 3.0, seed=3, tokens=tokens)
    else:
        spec = scale_free(args.nodes, args.attach, seed=3, tokens=tokens)

    import dataclasses

    from chandy_lamport_tpu.core.state import (
        ERR_QUEUE_OVERFLOW,
        ERR_RECORD_OVERFLOW,
        decode_error_bits,
        decode_errors,
    )
    from chandy_lamport_tpu.utils.metrics import instance_footprint_bytes

    # capacity sized to the workload (the round-2 bench ran with C=16, which
    # cannot hold the sf-1024 storm's hub-edge backlog — 4/2048 lanes fired
    # ERR_QUEUE_OVERFLOW and the whole perf axis recorded 0.0), plus
    # doubling retries below as the belt to that suspender: queue capacity
    # on ERR_QUEUE_OVERFLOW, recorded-message capacity on ERR_RECORD_OVERFLOW
    # (a ring's marker circles the whole graph, recording a token per tick
    # on every edge — small graphs legitimately need M much larger than the
    # scale-free default)
    cfg = SimConfig.for_workload(snapshots=args.snapshots,
                                 max_recorded=args.max_recorded,
                                 record_dtype=args.record_dtype,
                                 window_dtype=args.window_dtype,
                                 split_markers=args.scheduler == "sync",
                                 snapshot_timeout=args.snapshot_timeout,
                                 snapshot_retries=args.snapshot_retries,
                                 snapshot_every=args.snapshot_every)
    if args.capacity:
        cfg = dataclasses.replace(cfg, queue_capacity=args.capacity)
    trace = None
    if args.trace or args.trace_capacity:
        from chandy_lamport_tpu.utils.tracing import JaxTrace

        trace = JaxTrace(capacity=args.trace_capacity)

    if args.graphshard:
        return run_graphshard_worker(args, dev, spec, cfg)
    if args.fleet:
        return run_fleet_worker(args, dev, spec, cfg)
    if args.serve:
        return run_serve_worker(args, dev, spec, cfg)
    if args.stream:
        return run_stream_worker(args, dev, spec, cfg)

    runner = summary = None
    for cap_try in range(4):
        runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                               batch=args.batch, scheduler=args.scheduler,
                               exact_impl=args.exact_impl,
                               auto_layouts=args.layouts == "auto",
                               megatick=args.megatick,
                               queue_engine=args.queue_engine,
                               kernel_engine=args.kernel_engine, trace=trace,
                               fused_tick=args.fused_tick,
                               fused_block_edges=args.fused_block_edges,
                               fused_tile=args.fused_tile)
        topo = runner.topo
        log(f"graph: {topo.n} nodes, {topo.e} edges, max out-degree "
            f"{topo.d}; queue_capacity={cfg.queue_capacity}")
        per = instance_footprint_bytes(topo.n, topo.e, cfg)
        log(f"per-instance state: {per / 1e6:.3f} MB; "
            f"batch resident {per * args.batch / 1e9:.2f} GB")
        prog = storm_program(
            topo, phases=args.phases, amount=1,
            snapshot_phases=staggered_snapshots(topo, args.snapshots, 1, 2,
                                                max_phases=args.phases))

        # warmup: compile + one full execution (doubles as the validity check)
        # init_batch_device: state is built ON device — shipping the multi-GB
        # numpy state through a remote-device tunnel was the round-2
        # bottleneck (~16 s per repeat, 30x the actual simulation time)
        t0 = time.perf_counter()
        try:
            # compile-from-shapes first: the warmup state is then BORN in
            # the executable's chosen layouts — no relayout dispatch, no
            # transient double residency at near-HBM-limit batches
            fmts0 = runner.prepare_storm(prog)
            final = runner.run_storm(runner.init_batch_device(formats=fmts0),
                                     prog)
            jax.block_until_ready(final)
        except Exception as exc:
            # device OOM surfaces as RESOURCE_EXHAUSTED locally, but through
            # the remote-compile tunnel it arrives as INTERNAL with the XLA
            # message text — match the text, not just the status code
            oom = any(pat in str(exc) for pat in (
                "RESOURCE_EXHAUSTED", "Ran out of memory",
                "Exceeded hbm capacity"))
            if oom and args.batch > 1:
                # out of HBM: halve the batch and retry (the result JSON
                # reports the batch that actually ran, so a shrunken run is
                # visibly labeled — tools/ladder.py marks it _CLAMPED).
                # summary must not survive from an earlier failed try: the
                # post-loop guard relies on it reflecting THIS runner.
                summary = None
                args.batch //= 2
                log(f"device OOM; retrying with batch={args.batch}")
                continue
            raise
        log(f"warmup (compile + run): {time.perf_counter() - t0:.1f}s")
        summary = BatchedRunner.summarize(final)
        # with auto layouts, the warmup compile recorded the storm
        # program's chosen state input formats — timed states are built
        # directly in these, so every timed dispatch is boundary-copy-free
        fmts = runner.storm_state_formats()
        # free the warmup state NOW: holding it across the timed loop's
        # fresh init doubles state residency and OOMs the large configs
        # (config 5: 9 GB resident -> 18 GB transient)
        del final
        log(f"summary: {summary}")
        bits = summary["error_bits"]
        if not bits:
            break
        for name, msg in zip(decode_error_bits(bits), decode_errors(bits)):
            log(f"error bit {name}: {msg}")
        recoverable = ERR_QUEUE_OVERFLOW | ERR_RECORD_OVERFLOW
        if (bits & ~recoverable) or cap_try == 3:
            log("ERROR: lanes with error flags — results invalid")
            return 1
        if bits & ERR_QUEUE_OVERFLOW:
            cfg = dataclasses.replace(cfg,
                                      queue_capacity=2 * cfg.queue_capacity)
        if bits & ERR_RECORD_OVERFLOW:
            cfg = dataclasses.replace(cfg, max_recorded=2 * cfg.max_recorded)
        log(f"retrying with queue_capacity={cfg.queue_capacity}, "
            f"max_recorded={cfg.max_recorded}")
    if summary is None or summary["error_bits"]:
        log("ERROR: no clean warmup (repeated OOM, or error flags at the "
            "final capacity)")
        return 1
    if summary["snapshots_completed"] != summary["snapshots_started"]:
        log("ERROR: incomplete snapshots")
        return 1

    times, node_ticks = [], []
    mem = {}
    for r in range(args.repeats):
        state = runner.init_batch_device(formats=fmts)
        jax.block_until_ready(state)
        profiling = args.profile and r == args.repeats - 1
        if profiling:
            jax.profiler.start_trace(args.profile)
        t0 = time.perf_counter()
        final = runner.run_storm(state, prog)
        jax.block_until_ready(final)
        dt = time.perf_counter() - t0
        if profiling:
            jax.profiler.stop_trace()
            log(f"profile trace written to {args.profile}")
        total_ticks = int(np.asarray(jax.device_get(final.time)).sum())
        if r == args.repeats - 1:
            # capture while the final state is still resident — after the
            # del below, live_bytes would see an empty device
            mem = _memory_stats(dev, instance_footprint_bytes(
                topo.n, topo.e, cfg) * args.batch)
        del state, final  # same double-residency guard, per repeat
        times.append(dt)
        node_ticks.append(total_ticks * topo.n)
        ticks_per_lane = total_ticks / args.batch
        log(f"run {r}: {dt:.3f}s, {total_ticks} total ticks "
            f"({ticks_per_lane:.1f}/lane, {dt / ticks_per_lane * 1e3:.2f}ms "
            f"per batched tick) -> {node_ticks[-1] / dt / 1e6:.2f}M node-ticks/s")

    best = max(nt / dt for nt, dt in zip(node_ticks, times))
    trace_extra = {}
    if trace is not None:
        # trace overhead: one untraced run at the same shape (compile is
        # a second executable, but the persistent cache absorbs repeats).
        # A separate runner — trace_capacity=0 compiles every trace op away
        # (the bit-identity guarantee tests/test_trace.py pins down).
        base_cfg = dataclasses.replace(runner.config, trace_capacity=0)
        base = BatchedRunner(spec, base_cfg, make_fast_delay(args.delay, 17),
                             batch=args.batch, scheduler=args.scheduler,
                             exact_impl=args.exact_impl,
                             auto_layouts=args.layouts == "auto",
                             megatick=args.megatick,
                             queue_engine=args.queue_engine,
                             kernel_engine=args.kernel_engine,
                             fused_tick=args.fused_tick,
                             fused_block_edges=args.fused_block_edges,
                               fused_tile=args.fused_tile)
        fmtb = base.prepare_storm(prog)
        fb = base.run_storm(base.init_batch_device(formats=fmtb), prog)
        jax.block_until_ready(fb)
        del fb  # warmup done; same double-residency guard
        sb = base.init_batch_device(formats=base.storm_state_formats())
        jax.block_until_ready(sb)
        t0 = time.perf_counter()
        fb = base.run_storm(sb, prog)
        jax.block_until_ready(fb)
        dt0 = time.perf_counter() - t0
        base_rate = (int(np.asarray(jax.device_get(fb.time)).sum())
                     * topo.n / dt0)
        del sb, fb
        trace_extra = {
            "trace_capacity": runner.config.trace_capacity,
            "trace_events": summary["trace_events"],
            "trace_dropped": summary["trace_dropped"],
            # recording-rate cost vs the compiled-away baseline; negative
            # values are timing noise, not a speedup
            "trace_overhead_pct": round((base_rate / best - 1.0) * 100, 1),
            "untraced_node_ticks_per_sec": round(base_rate, 1),
        }
        log(f"trace overhead: {trace_extra['trace_overhead_pct']}% "
            f"(untraced {base_rate / 1e6:.2f}M vs traced "
            f"{best / 1e6:.2f}M node-ticks/s)")
    result = {
        "metric": "node_ticks_per_sec_per_chip",
        "value": round(best, 1),
        "unit": "node-ticks/s/chip",
        "vs_baseline": round(best / args.target, 3),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scheduler": (args.scheduler if args.scheduler == "sync"
                      else f"exact/{args.exact_impl}"),
        **({"megatick": args.megatick} if args.scheduler == "exact" else {}),
        "queue_engine": runner.queue_engine,
        "kernel_engine": runner.kernel_engine,
        "fused_tick": runner.fused,
        "fused_tile": runner.fused_tile,
        # interpret-mode honesty: True means the fused kernels ran as
        # CPU-emulated Pallas (TPU-blind since r03) — a gauge row, not a
        # TPU fused win
        "fused_emulated": fused_emulated(runner),
        "graph": args.graph,
        "nodes": args.nodes,
        "batch": args.batch,
        "phases": args.phases,
        "repeats": args.repeats,
        "queue_capacity": cfg.queue_capacity,
        "record_dtype": cfg.record_dtype,
        "max_recorded": cfg.max_recorded,
        "delay": args.delay,
        "layouts": runner.layouts_effective,
        # a valid row ran with zero error bits, and says so in names, not
        # raw ints (core/state.decode_error_bits)
        "error_bits": summary["error_bits"],
        "errors_decoded": summary["errors_decoded"],
        # lane-clock dispersion at the end of the run (utils/metrics
        # .straggler_waste): the fraction of the batch's tick budget spent
        # waiting on the slowest lane — the quantity --stream reclaims
        "straggler_waste": summary["straggler_waste"],
        # supervisor lifecycle per run (utils/metrics.snapshot_lifecycle):
        # even the supervisor-off default row carries the counters (all
        # zero churn) so the ladder's round-trip can rely on the field
        "snapshot_lifecycle": summary["snapshot_lifecycle"],
        "recovery_line_age": summary["snapshot_lifecycle"][
            "recovery_line_age_max"],
        **({"snapshot_timeout": args.snapshot_timeout,
            "snapshot_retries": args.snapshot_retries,
            "snapshot_every": args.snapshot_every}
           if (args.snapshot_timeout or args.snapshot_every) else {}),
    }
    # the analytic roofline the measured rate reads against (and the
    # static sibling of tools/staticcheck's per-arm HLO cost rows)
    from chandy_lamport_tpu.utils.metrics import tick_cost_model

    result["cost_model"] = tick_cost_model(
        topo.n, topo.e, cfg, batch=args.batch,
        queue_engine=runner.queue_engine)
    result.update(trace_extra)
    result.update(mem)
    if dev.platform != "tpu":
        # an honest CPU/fallback number must not read as the chip's
        # capability — point at the recorded device measurements. A
        # deliberate CPU run (CLSIM_PLATFORM=cpu from the operator, not
        # the orchestrator's fallback chain) is labeled as such.
        deliberate = platform == "cpu" and "CLSIM_FALLBACK" not in os.environ
        result["note"] = (
            ("deliberate CPU run; " if deliberate
             else "non-TPU fallback (device tunnel down?); ")
            + "measured TPU rows live in BASELINE_MEASURED.jsonl "
              "/ BASELINE.md")
        result.update(_best_recorded_tpu())
    _write_telemetry(args, "bench_run", result)
    print(json.dumps(result), flush=True)
    return 0


def _write_telemetry(args, kind: str, row: dict) -> None:
    """Append the row as schema-versioned JSONL (utils/tracing.
    TelemetryWriter) when --telemetry is set. Best-effort — telemetry
    must never fail a measurement that already succeeded."""
    if not getattr(args, "telemetry", None):
        return
    try:
        from chandy_lamport_tpu.utils.tracing import TelemetryWriter

        with TelemetryWriter(args.telemetry) as tw:
            tw.write(kind, row)
    except OSError as exc:
        log(f"telemetry not written: {exc}")


def _best_recorded_tpu() -> dict:
    """On a fallback row, surface the best PREVIOUSLY RECORDED TPU
    measurement machine-readably (field names say recorded, not measured
    — a tunnel-down round should still carry the chip's known capability
    next to the honest fallback number)."""
    path = os.path.join(_PKG_ROOT, "BASELINE_MEASURED.jsonl")
    best = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (row.get("platform") == "tpu"
                        and row.get("metric") == "node_ticks_per_sec_per_chip"
                        and "_CLAMPED" not in str(row.get("config", ""))
                        and isinstance(row.get("value"), (int, float))
                        and (best is None
                             or row["value"] > best["value"])):
                    best = row
    except OSError:
        return {}
    if not best:
        return {}
    return {"best_recorded_tpu_value": best["value"],
            "best_recorded_tpu_config": best.get("config"),
            "best_recorded_tpu_vs_baseline": best.get("vs_baseline")}


def run_stream_worker(args, dev, spec, cfg) -> int:
    """--stream: the streaming-engine benchmark. A heavy-tailed queue of J
    jobs (models/workloads.stream_jobs — Pareto-tailed phase counts, the
    distribution where static batching waits on every cohort's slowest
    member) is driven through the B lane slots twice on the SAME
    executable: continuous admission (run_stream's default) and gang
    admission (refill only when every lane is idle — static batching with
    identical step overhead, so the speedup isolates the refill win, not
    dispatch differences). Reported as jobs/s with occupancy / refill /
    straggler counters from both drives in one row."""
    import time as _time

    import jax

    from chandy_lamport_tpu.models.workloads import stream_jobs
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner

    trace = None
    if args.trace or args.trace_capacity:
        from chandy_lamport_tpu.utils.tracing import JaxTrace

        trace = JaxTrace(capacity=args.trace_capacity)
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                           batch=args.batch, scheduler=args.scheduler,
                           exact_impl=args.exact_impl,
                           megatick=args.megatick,
                           queue_engine=args.queue_engine,
                           kernel_engine=args.kernel_engine, trace=trace,
                           fused_tick=args.fused_tick,
                           fused_block_edges=args.fused_block_edges,
                               fused_tile=args.fused_tile)
    jcount = args.jobs or 3 * args.batch
    jobs = stream_jobs(spec, jcount, seed=17, base_phases=4,
                       tail_alpha=1.1, max_phases=max(args.phases, 8),
                       dup_rate=args.dup_rate,
                       prefix_overlap=args.prefix_overlap)
    # memo A/B fairness: EVERY arm runs the identical content-keyed pool,
    # so the only difference between arms is the memo plane itself. Under
    # memo=prefix the PREFIX runner must pack (first-phase fault/delay
    # identity + the digest chains) and the off/full arms consume that
    # same pool — packing per-arm would compare different computations.
    memo_runner = None
    if args.memo != "off":
        memo_runner = BatchedRunner(spec, cfg,
                                    make_fast_delay(args.delay, 17),
                                    batch=args.batch,
                                    scheduler=args.scheduler,
                                    exact_impl=args.exact_impl,
                                    megatick=args.megatick,
                                    queue_engine=args.queue_engine,
                                    kernel_engine=args.kernel_engine,
                                    fused_tick=args.fused_tick,
                                    fused_block_edges=args.fused_block_edges,
                                    fused_tile=args.fused_tile,
                                    trace=trace, memo=args.memo)
    packer = memo_runner if memo_runner is not None else runner
    pool = packer.pack_jobs(jobs,
                            content_keys=True if args.memo != "off" else None)
    log(f"stream: {jcount} jobs over {args.batch} slots, pooled phase "
        f"table {pool.do_tick.shape[0]} rows, stretch={args.stretch}, "
        f"drain_chunk={args.drain_chunk}, dup_rate={args.dup_rate}, "
        f"prefix_overlap={args.prefix_overlap}, memo={args.memo}")

    def drive(admission):
        t0 = _time.perf_counter()
        state, stream = runner.run_stream(
            pool, stretch=args.stretch, drain_chunk=args.drain_chunk,
            admission=admission)
        jax.block_until_ready(state)
        return _time.perf_counter() - t0, state, stream

    # warmup both admission modes (compile; correctness gate on the stream
    # results — no faults armed, so any error bit invalidates the row)
    t0 = _time.perf_counter()
    _, _, stream_w = drive("stream")
    _, _, _gang_w = drive("gang")
    log(f"warmup (compile + 2 runs): {_time.perf_counter() - t0:.1f}s")
    bad = [r for r in runner.stream_results(stream_w) if r["error"]]
    if bad:
        log(f"ERROR: {len(bad)} jobs retired with error bits "
            f"(first: {bad[0]}) — results invalid")
        return 1
    if runner.summarize_stream(stream_w)["jobs_done"] != jcount:
        log("ERROR: stream drive did not retire every job")
        return 1

    best = {}
    summaries = {}
    for admission in ("stream", "gang"):
        times = []
        for r in range(args.repeats):
            dt, state, stream = drive(admission)
            times.append(dt)
            log(f"{admission} run {r}: {dt:.3f}s -> "
                f"{jcount / dt:.1f} jobs/s")
        best[admission] = jcount / min(times)
        summaries[admission] = runner.summarize_stream(stream)
    mem = _memory_stats(dev)

    speedup = best["stream"] / best["gang"] if best["gang"] else 0.0
    ss, sg = summaries["stream"], summaries["gang"]
    result = {
        "metric": "stream_jobs_per_sec",
        "value": round(best["stream"], 2),
        "unit": "jobs/s",
        "jobs_per_sec_gang": round(best["gang"], 2),
        # the headline: continuous admission vs static batching on the
        # same executable (ISSUE-6 acceptance gate: >= 1.3x heavy-tailed)
        "speedup_vs_static": round(speedup, 3),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scheduler": (args.scheduler if args.scheduler == "sync"
                      else f"exact/{args.exact_impl}"),
        "queue_engine": runner.queue_engine,
        "kernel_engine": runner.kernel_engine,
        "fused_tick": runner.fused,
        "fused_tile": runner.fused_tile,
        # interpret-mode honesty: True means the fused kernels ran as
        # CPU-emulated Pallas (TPU-blind since r03) — a gauge row, not a
        # TPU fused win
        "fused_emulated": fused_emulated(runner),
        "graph": args.graph,
        "nodes": args.nodes,
        "batch": args.batch,
        "jobs": jcount,
        "stretch": args.stretch,
        "drain_chunk": args.drain_chunk,
        "repeats": args.repeats,
        "delay": args.delay,
        "occupancy": ss["occupancy"],
        "occupancy_gang": sg["occupancy"],
        "refills": ss["refills"],
        "refills_gang": sg["refills"],
        "straggler_wasted_steps": ss["straggler_wasted_steps"],
        "straggler_wasted_steps_gang": sg["straggler_wasted_steps"],
        "stream_steps": ss["steps"],
        "gang_steps": sg["steps"],
        "memo": args.memo,
        "dup_rate": args.dup_rate,
        # served == executed without the memo plane, so the off arm's
        # effective rate IS its execution rate (the memo arm overrides)
        "effective_jobs_per_sec": round(best["stream"], 2),
    }
    from chandy_lamport_tpu.utils.metrics import tick_cost_model

    result["cost_model"] = tick_cost_model(
        runner.topo.n, runner.topo.e, cfg, batch=args.batch,
        queue_engine=runner.queue_engine)
    if memo_runner is not None:
        # memo arm: same pool, same knobs, memo plane on — the headline is
        # effective jobs SERVED per second vs the memo-off arm above

        def drive_memo():
            t0 = _time.perf_counter()
            state, stream = memo_runner.run_stream(
                pool, stretch=args.stretch, drain_chunk=args.drain_chunk)
            jax.block_until_ready(state)
            return _time.perf_counter() - t0, state, stream

        dt_w, _, stream_mw = drive_memo()        # compile + audit warmup
        served = len(memo_runner.stream_results(stream_mw))
        log(f"memo warmup: {dt_w:.1f}s, served {served}/{jcount}")
        if served != jcount:
            log("ERROR: memo drive did not serve every job")
            return 1
        mtimes = []
        for r in range(args.repeats):
            dt, _, stream_m = drive_memo()
            mtimes.append(dt)
            log(f"memo run {r}: {dt:.3f}s -> {served / dt:.1f} "
                f"effective jobs/s")
        sm = memo_runner.summarize_stream(stream_m)
        eff_memo = served / min(mtimes)
        result.update({
            "effective_jobs_per_sec": round(eff_memo, 2),
            "effective_jobs_per_sec_off": result["value"],
            # the tentpole's acceptance number: served-throughput multiple
            # of the memo plane over the identical memo-off executable
            "memo_speedup": round(eff_memo / best["stream"], 3)
            if best["stream"] else 0.0,
            "cache_hits": sm["cache_hits"],
            "coalesced_jobs": sm["coalesced_jobs"],
            "ff_skipped_ticks": sm["ff_skipped_ticks"],
            "shadow_checks": sm["shadow_checks"],
            "memo_hit_rate": sm["memo_hit_rate"],
            "memo_steps": sm["steps"],
        })
        if args.memo == "prefix":
            # the fork plane's acceptance denominator: an exact-match
            # memo=full arm on the SAME pool. At dup_rate 0 it can
            # coalesce nothing, so prefix_speedup isolates what forking
            # from cached prefixes buys over the best exact-match plane.
            full_runner = BatchedRunner(
                spec, cfg, make_fast_delay(args.delay, 17),
                batch=args.batch, scheduler=args.scheduler,
                exact_impl=args.exact_impl, megatick=args.megatick,
                queue_engine=args.queue_engine,
                kernel_engine=args.kernel_engine,
                fused_tick=args.fused_tick,
                fused_block_edges=args.fused_block_edges,
                fused_tile=args.fused_tile, trace=trace, memo="full")

            def drive_full():
                t0 = _time.perf_counter()
                state, stream = full_runner.run_stream(
                    pool, stretch=args.stretch,
                    drain_chunk=args.drain_chunk)
                jax.block_until_ready(state)
                return _time.perf_counter() - t0, state, stream

            dt_fw, _, stream_fw = drive_full()
            served_f = len(full_runner.stream_results(stream_fw))
            log(f"full-arm warmup: {dt_fw:.1f}s, served "
                f"{served_f}/{jcount}")
            if served_f != jcount:
                log("ERROR: memo=full arm did not serve every job")
                return 1
            ftimes = []
            for r in range(args.repeats):
                dt, _, _ = drive_full()
                ftimes.append(dt)
                log(f"full run {r}: {dt:.3f}s -> {served_f / dt:.1f} "
                    f"effective jobs/s")
            eff_full = served_f / min(ftimes)
            hist: dict = {}
            for d in getattr(memo_runner, "_fork_depths", []):
                hist[str(int(d))] = hist.get(str(int(d)), 0) + 1
            result.update({
                "prefix_overlap": args.prefix_overlap,
                "prefix_hits": sm["prefix_hits"],
                "forked_jobs": sm["forked_jobs"],
                "fork_depth_mean": sm["fork_depth_mean"],
                "fork_depth_hist": hist,
                "prefix_evictions": sm["prefix_evictions"],
                "effective_jobs_per_sec_full": round(eff_full, 2),
                # the ISSUE-20 acceptance number: fork-served throughput
                # as a multiple of exact-match memo on the same queue
                "prefix_speedup": round(eff_memo / eff_full, 3)
                if eff_full else 0.0,
            })
    if trace is not None:
        from chandy_lamport_tpu.utils.tracing import trace_counts

        tr_rec, tr_drop = trace_counts(state)
        result["trace_capacity"] = runner.config.trace_capacity
        result["trace_events"], result["trace_dropped"] = tr_rec, tr_drop
    result.update(mem)
    if dev.platform != "tpu":
        deliberate = (os.environ.get("CLSIM_PLATFORM") == "cpu"
                      and "CLSIM_FALLBACK" not in os.environ)
        result["note"] = (
            ("deliberate CPU run; " if deliberate
             else "non-TPU fallback (device tunnel down?); ")
            + "stream-vs-gang speedup is platform-relative, not a chip "
              "throughput claim")
    _write_telemetry(args, "bench_stream", result)
    print(json.dumps(result), flush=True)
    return 0


def run_serve_worker(args, dev, spec, cfg) -> int:
    """--serve: the online serving metric (chandy_lamport_tpu/serving).
    One seeded Poisson/Zipf schedule served twice per policy arm — the
    COLD pass pays the fresh trace+compile and persists the jax.export
    artifact; the WARM pass simulates a restarted server (fresh runner,
    fresh ExecutableCache over the same directory) and must load the
    lowered program from disk. The warmup drop between the two is the
    row's restart-skips-recompile evidence; occupancy, admit p50/p99 and
    deadline misses come from the timed edf arm, with the fifo baseline's
    numbers alongside."""
    import tempfile
    import time as _time

    import jax

    from chandy_lamport_tpu.models.workloads import serve_workload
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.serving import ExecutableCache, serve_run

    rcount = args.jobs or 3 * args.batch
    requests = serve_workload(spec, rcount, seed=17, rate=args.rate,
                              tenants=args.tenants,
                              priorities=args.priorities,
                              dup_rate=args.dup_rate,
                              max_phases=max(args.phases, 8))
    log(f"serve: {rcount} requests over {args.batch} slots at rate "
        f"{args.rate}/step, tenants={args.tenants}, "
        f"dup_rate={args.dup_rate}, policy={args.serve_policy}")

    def mk_runner():
        return BatchedRunner(spec, cfg, make_fast_delay(args.delay, 17),
                             batch=args.batch, scheduler=args.scheduler,
                             exact_impl=args.exact_impl,
                             megatick=args.megatick,
                             queue_engine=args.queue_engine,
                             kernel_engine=args.kernel_engine,
                             fused_tick=args.fused_tick,
                             fused_block_edges=args.fused_block_edges,
                               fused_tile=args.fused_tile)

    cache_dir = tempfile.mkdtemp(prefix="clsim-serve-exec-")

    def drive(policy, exec_cache):
        runner = mk_runner()
        t0 = _time.perf_counter()
        state, stream, report = serve_run(runner, requests, policy=policy,
                                          stretch=args.stretch,
                                          drain_chunk=args.drain_chunk,
                                          exec_cache=exec_cache)
        jax.block_until_ready(state)
        wall = _time.perf_counter() - t0
        rows = runner.stream_results(stream)
        return wall, report, rows

    # cold pass: fresh process-equivalent (empty cache dir), persists the
    # lowered artifact; doubles as the correctness gate
    wall_cold, rep_cold, rows = drive(args.serve_policy,
                                      ExecutableCache(cache_dir))
    bad = [r for r in rows if r["error"]]
    if bad:
        log(f"ERROR: {len(bad)} requests retired with error bits "
            f"(first: {bad[0]}) — results invalid")
        return 1
    if len(rows) != rcount - rep_cold["refused_total"]:
        log("ERROR: serve drive did not serve every accepted request")
        return 1
    log(f"cold: warmup {rep_cold['warmup_s']:.1f}s "
        f"({rep_cold['warmup_source']}, persisted="
        f"{rep_cold['warmup_persisted']}), serve wall "
        f"{rep_cold['wall_s']:.2f}s")

    # warm pass: a RESTARTED server — new runner, new ExecutableCache over
    # the same directory; the memory plane is empty, so a 'disk' warmup
    # source proves the artifact round-trip
    best = None
    rep_warm = None
    for r in range(args.repeats):
        wall, rep, _ = drive(args.serve_policy, ExecutableCache(cache_dir))
        served_s = rep["served_total"] / rep["wall_s"] if rep["wall_s"] \
            else 0.0
        log(f"warm run {r}: warmup {rep['warmup_s']:.1f}s "
            f"({rep['warmup_source']}), {served_s:.1f} effective jobs/s")
        if best is None or rep["wall_s"] < best:
            best, rep_warm = rep["wall_s"], rep
    # fifo baseline at the same schedule (warm cache; one run)
    _, rep_fifo, _ = drive("fifo", ExecutableCache(cache_dir))
    mem = _memory_stats(dev)

    result = {
        "metric": "serve_effective_jobs_per_sec",
        "value": round(rep_warm["served_total"] / rep_warm["wall_s"], 2)
        if rep_warm["wall_s"] else 0.0,
        "unit": "jobs/s",
        "serve_policy": args.serve_policy,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scheduler": (args.scheduler if args.scheduler == "sync"
                      else f"exact/{args.exact_impl}"),
        "graph": args.graph, "nodes": args.nodes, "batch": args.batch,
        "requests": rcount, "rate": args.rate, "tenants": args.tenants,
        "dup_rate": args.dup_rate, "repeats": args.repeats,
        "stretch": args.stretch, "drain_chunk": args.drain_chunk,
        "occupancy": rep_warm["occupancy"],
        "admit_p50": rep_warm["admit_p50"],
        "admit_p99": rep_warm["admit_p99"],
        "deadline_misses": rep_warm["deadline_misses"],
        "memo_hit_rate": rep_warm["memo_hit_rate"],
        "served_total": rep_warm["served_total"],
        "refused_total": rep_warm["refused_total"],
        "steps": rep_warm["steps"],
        # the restart-skips-recompile evidence: cold pays the fresh
        # trace+compile, warm deserializes the persisted StableHLO
        "warmup_cold_s": rep_cold["warmup_s"],
        "warmup_warm_s": rep_warm["warmup_s"],
        "warmup_warm_source": rep_warm["warmup_source"],
        "warmup_drop": round(
            1.0 - rep_warm["warmup_s"] / rep_cold["warmup_s"], 3)
        if rep_cold["warmup_s"] else 0.0,
        # the fifo baseline's service quality at the identical schedule
        "deadline_misses_fifo": rep_fifo["deadline_misses"],
        "admit_p99_fifo": rep_fifo["admit_p99"],
        "occupancy_fifo": rep_fifo["occupancy"],
    }
    from chandy_lamport_tpu.core.state import DenseTopology
    from chandy_lamport_tpu.ops.tick import resolve_queue_engine
    from chandy_lamport_tpu.utils.metrics import tick_cost_model

    topo = DenseTopology(spec)
    result["cost_model"] = tick_cost_model(
        topo.n, topo.e, cfg, batch=args.batch,
        queue_engine=resolve_queue_engine(args.queue_engine))
    result.update(mem)
    if dev.platform != "tpu":
        deliberate = (os.environ.get("CLSIM_PLATFORM") == "cpu"
                      and "CLSIM_FALLBACK" not in os.environ)
        result["note"] = (
            ("deliberate CPU run; " if deliberate
             else "non-TPU fallback (device tunnel down?); ")
            + "serving throughput is platform-relative, not a chip "
              "throughput claim")
    _write_telemetry(args, "bench_serve", result)
    print(json.dumps(result), flush=True)
    return 0


def run_fleet_worker(args, dev, spec, cfg) -> int:
    """--fleet N: the HA serving-fleet metric (serving/fleet.fleet_run).
    One seeded Poisson/Zipf schedule admitted into a fresh WAL spool and
    served by N spawned workers; --fleet-crashes K adds the degraded-mode
    arm (the supervisor SIGKILLs a live worker K times on a fixed
    schedule — leases expire, in-flight requests are redelivered, the
    conservation audit must still balance). The row is one SLO-ladder
    point: served jobs/s, goodput, request-latency percentiles, the
    takeover/restart books and the audit verdict; tools/analyze.py
    --slo-ladder draws the knee curve from a JSONL stream of these."""
    import tempfile
    import time as _time

    from chandy_lamport_tpu.models.workloads import (
        crash_schedule,
        serve_workload,
    )
    from chandy_lamport_tpu.serving.fleet import fleet_run

    if args.graph != "ring":
        log("--fleet requires --graph ring: the spawn-crossing worker "
            "recipe reconstructs a ring-stream engine")
        return 1
    rcount = args.jobs or 3 * args.batch
    requests = serve_workload(spec, rcount, seed=17, rate=args.rate,
                              tenants=args.tenants,
                              priorities=args.priorities,
                              dup_rate=args.dup_rate,
                              max_phases=max(args.phases, 4))
    log(f"fleet: {rcount} requests, {args.fleet} worker(s), "
        f"rate {args.rate}/step, crashes={args.fleet_crashes}, "
        f"lease_ttl={args.fleet_lease_ttl}s")
    run_dir = tempfile.mkdtemp(prefix="clsim-fleet-")
    recipe = {"kind": "ring-stream", "n": args.nodes,
              "tokens": args.phases + 10, "snapshots": args.snapshots,
              "max_recorded": cfg.max_recorded,
              "batch": args.batch, "scheduler": args.scheduler,
              "delay": args.delay,
              "memo_cache": os.path.join(run_dir, "memo.jsonl")}
    kills = crash_schedule(args.fleet_crashes, 2.0, start_s=4.0)
    t0 = _time.perf_counter()
    rep = fleet_run(requests, spool_path=os.path.join(run_dir, "wal.jsonl"),
                    workers=args.fleet, recipe=recipe,
                    lease_ttl=args.fleet_lease_ttl,
                    crash_schedule=kills, restart_backoff=0.2,
                    stretch=args.stretch, drain_chunk=args.drain_chunk,
                    max_wall_s=420.0)
    wall = _time.perf_counter() - t0
    if rep["timed_out"]:
        log("ERROR: fleet run hit max_wall_s before every request was "
            "terminal — results invalid")
        return 1
    if rep["audit"]["lost"] or rep["audit"]["double_served"]:
        log(f"ERROR: WAL audit failed — lost={rep['audit']['lost']}, "
            f"double_served={rep['audit']['double_served']}")
        return 1
    log(f"fleet: served {rep['served']}/{rcount} in {rep['wall_s']:.1f}s "
        f"(goodput {rep['goodput']:.2f}), deaths="
        f"{rep['books']['worker_deaths']} takeovers="
        f"{rep['books']['takeovers']} restarts={rep['books']['restarts']}")
    mem = _memory_stats(dev)
    result = {
        "metric": "fleet_served_jobs_per_sec",
        "value": round(rep["served"] / rep["wall_s"], 2)
        if rep["wall_s"] else 0.0,
        "unit": "jobs/s",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scheduler": (args.scheduler if args.scheduler == "sync"
                      else f"exact/{args.exact_impl}"),
        "graph": args.graph, "nodes": args.nodes, "batch": args.batch,
        "requests": rcount, "rate": args.rate, "tenants": args.tenants,
        "workers": args.fleet, "crashes_injected": args.fleet_crashes,
        "lease_ttl_s": args.fleet_lease_ttl,
        "stretch": args.stretch, "drain_chunk": args.drain_chunk,
        "served": rep["served"], "goodput": rep["goodput"],
        "shed": len(rep["shed"]), "poisoned": len(rep["poisoned"]),
        "lat_p50_s": rep["lat_p50_s"], "lat_p99_s": rep["lat_p99_s"],
        "lat_max_s": rep["lat_max_s"],
        "worker_deaths": rep["books"]["worker_deaths"],
        "takeovers": rep["books"]["takeovers"],
        "restarts": rep["books"]["restarts"],
        "cache_served": sum(1 for v in rep["results"].values()
                            if v.get("served_from") == "fleet-cache"),
        "audit_lost": rep["audit"]["lost"],
        "audit_double_served": rep["audit"]["double_served"],
        "wall_total_s": round(wall, 2),
        "serve_wall_s": rep["wall_s"],
        "serve_schema": rep["serve_schema"],
    }
    result.update(mem)
    if dev.platform != "tpu":
        deliberate = (os.environ.get("CLSIM_PLATFORM") == "cpu"
                      and "CLSIM_FALLBACK" not in os.environ)
        result["note"] = (
            ("deliberate CPU run; " if deliberate
             else "non-TPU fallback (device tunnel down?); ")
            + "fleet throughput is platform-relative, not a chip "
              "throughput claim")
    _write_telemetry(args, "bench_fleet", result)
    print(json.dumps(result), flush=True)
    return 0


def run_graphshard_worker(args, dev, spec, cfg) -> int:
    """--graphshard K: one giant instance over a K-device graph mesh
    (parallel/graphshard), per-shard uniform delay streams, same storm
    workload and metric as the batched path. The interesting numbers:
    K=1 on a real chip vs the unsharded sync kernel at B=1 (the
    collective-formulation tax) and K=8 on the CPU mesh (relative
    per-tick cost of the cross-shard psum/all_gather traffic). The
    channel state it shards is the reference's per-arc queue map
    (queue.go:6-28)."""
    import dataclasses
    import time as _time

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from chandy_lamport_tpu.core.state import decode_error_bits, decode_errors
    from chandy_lamport_tpu.models.workloads import (
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.parallel.graphshard import GraphShardedRunner

    devs = jax.devices()
    if args.graphshard > len(devs):
        log(f"--graphshard {args.graphshard} > {len(devs)} devices")
        return 1
    if args.nodes % args.graphshard:
        log(f"--nodes {args.nodes} not divisible by {args.graphshard} shards")
        return 1
    mesh = Mesh(np.array(devs[:args.graphshard]), ("graph",))
    runner = GraphShardedRunner(spec, cfg, mesh, seed=17,
                                queue_engine=args.queue_engine,
                                comm_engine=args.comm_engine,
                                kernel_engine=args.kernel_engine,
                                fused_tick=args.fused_tick,
                                fused_tile=args.fused_tile,
                                megatick=args.megatick)
    topo = runner.topo
    log(f"graphshard: {topo.n} nodes / {args.graphshard} shards "
        f"({runner.nl} nodes, {runner.em} edge slots per shard), "
        f"{topo.e} edges")
    prog = storm_program(
        topo, phases=args.phases, amount=1,
        snapshot_phases=staggered_snapshots(topo, args.snapshots, 1, 2,
                                            max_phases=args.phases))
    amounts, snap = np.asarray(prog.amounts), np.asarray(prog.snap)

    from chandy_lamport_tpu.core.state import (
        ERR_QUEUE_OVERFLOW,
        ERR_RECORD_OVERFLOW,
    )

    recoverable = ERR_QUEUE_OVERFLOW | ERR_RECORD_OVERFLOW
    for cap_try in range(3):
        t0 = _time.perf_counter()
        final = runner.run_storm(runner.init_state(), amounts, snap)
        jax.block_until_ready(final)
        log(f"warmup (compile + run): {_time.perf_counter() - t0:.1f}s")
        bits = int(np.asarray(jax.device_get(final.error)))
        del final  # double-residency guard (same as the batched path)
        if not bits:
            break
        for name, msg in zip(decode_error_bits(bits), decode_errors(bits)):
            log(f"error bit {name}: {msg}")
        if (bits & ~recoverable) or cap_try == 2:
            # a non-capacity bit is a real failure — doubling capacities
            # would just recompile the giant-instance kernel to fail again
            log("ERROR: error flags — results invalid")
            return 1
        cfg = dataclasses.replace(cfg, queue_capacity=2 * cfg.queue_capacity,
                                  max_recorded=2 * cfg.max_recorded)
        log(f"retrying with queue_capacity={cfg.queue_capacity}, "
            f"max_recorded={cfg.max_recorded}")
        runner = GraphShardedRunner(spec, cfg, mesh, seed=17,
                                    queue_engine=args.queue_engine,
                                    comm_engine=args.comm_engine,
                                    kernel_engine=args.kernel_engine,
                                    fused_tick=args.fused_tick,
                                    fused_tile=args.fused_tile,
                                    megatick=args.megatick)

    times, ticks_seen = [], []
    mem = {}
    for r in range(args.repeats):
        state = runner.init_state()
        jax.block_until_ready(state)
        t0 = _time.perf_counter()
        final = runner.run_storm(state, amounts, snap)
        jax.block_until_ready(final)
        dt = _time.perf_counter() - t0
        ticks = int(np.asarray(jax.device_get(final.time)))
        if r == args.repeats - 1:   # capture while the state is resident
            mem = _memory_stats(dev)
        del state, final  # double-residency guard, per repeat
        times.append(dt)
        ticks_seen.append(ticks)
        log(f"run {r}: {dt:.3f}s, {ticks} ticks "
            f"({dt / ticks * 1e3:.2f}ms per tick) -> "
            f"{ticks * topo.n / dt / 1e6:.2f}M node-ticks/s")

    # aggregate throughput spreads over K devices; the headline metric is
    # per-chip, so divide — a K=8 run must not read 8x better per chip
    best = max(t * topo.n / dt for t, dt in zip(ticks_seen, times))
    per_chip = best / args.graphshard
    result = {
        "metric": "node_ticks_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "node-ticks/s/chip",
        "vs_baseline": round(per_chip / args.target, 3),
        "value_aggregate": round(best, 1),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "scheduler": "sync",
        "queue_engine": runner.queue_engine,
        "kernel_engine": runner.kernel_engine,
        "fused_tick": runner.fused,
        "fused_tile": runner.fused_tile,
        "fused_emulated": fused_emulated(runner),
        "comm_engine": runner.comm_engine,
        "megatick": runner.megatick,
        # analytic per-shard per-tick bytes for both engines at THIS
        # partition's cut (utils/metrics.comm_bytes_model)
        "comm_bytes_model": runner.comm_model(),
        "mode": "graphshard",
        "graphshard": args.graphshard,
        "graph": args.graph,
        "nodes": args.nodes,
        "batch": 1,
        "phases": args.phases,
        "repeats": args.repeats,
        "queue_capacity": cfg.queue_capacity,
        "record_dtype": cfg.record_dtype,
        "max_recorded": cfg.max_recorded,
        "per_tick_ms": round(times[-1] / ticks_seen[-1] * 1e3, 3),
        "error_bits": bits,
        "errors_decoded": decode_error_bits(bits),
        # one giant instance — there is no lane dispersion to waste by
        # construction; carried so every bench row has the field
        "straggler_waste": 0.0,
    }
    result.update(mem)
    if dev.platform != "tpu":
        result["note"] = ("non-TPU graphshard row (CPU-mesh relative cost "
                         "only); measured TPU rows live in "
                         "BASELINE_MEASURED.jsonl / BASELINE.md")
    _write_telemetry(args, "bench_graphshard", result)
    print(json.dumps(result), flush=True)
    return 0


# ---------------------------------------------------------------------------
# orchestrator: probe, then attempts with platform fallback; exit 0 always
# ---------------------------------------------------------------------------

def _spawn(name, mode, env_overrides, extra, timeout, argv):
    """Run one subprocess attempt.

    Returns (parsed_json|None, timed_out, retryable, backend_init):
    ``retryable`` is True for hangs and backend-init/crash exits (worth
    another attempt elsewhere); ``backend_init`` is True only for the
    clean EXIT_BACKEND_INIT exit (plugin failed to initialize — the one
    failure a CLSIM_PLATFORM=auto rescue can actually fix; signal deaths
    are tunnel wedges, where a rescue would hang identically). A clean
    other nonzero exit is a real measurement failure (invalid results,
    repeated OOM) that a different-platform retry would only mask."""
    env = dict(os.environ)
    env.update(env_overrides)
    # the child must find the package regardless of the parent's cwd (the
    # repo-root wrapper's sys.path edit doesn't reach a subprocess)
    env["PYTHONPATH"] = _PKG_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "chandy_lamport_tpu.bench",
           mode] + argv + extra
    log(f"--- attempt '{name}' (timeout {timeout:.0f}s): {' '.join(cmd)}")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        log(f"attempt '{name}' timed out after {timeout:.0f}s")
        return None, True, True, False
    dt = time.perf_counter() - t0
    out = proc.stdout.decode(errors="replace").strip().splitlines()
    if proc.returncode == 0 and out:
        try:
            parsed = json.loads(out[-1])
            parsed["attempt"] = name
            log(f"attempt '{name}' ok in {dt:.0f}s")
            return parsed, False, False, False
        except json.JSONDecodeError:
            log(f"attempt '{name}': unparseable stdout {out[-1]!r}")
            return None, False, False, False
    retryable = proc.returncode in (EXIT_BACKEND_INIT, -6, -9, -11)
    log(f"attempt '{name}' failed rc={proc.returncode} after {dt:.0f}s "
        f"(retryable={retryable})")
    return None, False, retryable, proc.returncode == EXIT_BACKEND_INIT


def _load_probe_cache(ttl: float):
    """The cached probe verdict, or None when absent/stale/unreadable.
    Entries: {"platform": str|None, "env": {...}, "ts": unix-seconds,
    "dead_platform": str|None} — ``dead_platform`` names a known-unusable
    platform the probe watchdog declared dead (UNUSABLE_PLATFORMS)."""
    try:
        with open(PROBE_CACHE_PATH) as f:
            data = json.load(f)
        age = time.time() - float(data["ts"])
        if not 0 <= age <= ttl:
            return None
        data["age"] = age
        return data
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _store_probe_cache(platform, env, dead_platform=None) -> None:
    """Record the ladder's verdict (atomic tmp + os.replace; best-effort —
    the cache is an optimization, never a failure)."""
    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        tmp = PROBE_CACHE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"platform": platform, "env": env,
                       "dead_platform": dead_platform,
                       "ts": time.time()}, f)
        os.replace(tmp, PROBE_CACHE_PATH)
    except OSError as exc:
        log(f"probe cache not written: {exc}")


def _find_live_platform(args):
    """Liveness probe ladder. Returns (platform|None, env_overrides,
    recently_dead, dead_platform) — ``recently_dead`` is True when a fresh
    cached verdict already said the tunnel was down (main() shrinks the
    tpu-blind budget on its strength); ``dead_platform`` names a
    known-unusable platform the probe watchdog declared dead
    (UNUSABLE_PLATFORMS — main() then skips tpu-blind outright, since the
    hang is structural, and falls straight to the cpu rung).

    The TPU plugin has been observed to HANG in jax.devices() (not just
    fail fast) when the device tunnel is down — and transient tunnel flakes
    recover within a minute. So: probe, retry a hung probe once, then ask
    jax's automatic platform choice (covers the round-1 plugin-init
    failure, where JAX_PLATFORMS='' would have worked). The verdict is
    cached (PROBE_CACHE_PATH): within --probe-cache-ttl a live verdict
    skips the ladder entirely, a dead-PLATFORM verdict short-circuits with
    zero probe subprocesses, and a generic dead verdict caps each probe at
    30s — re-discovering the same dead tunnel cost the round-5 bench >12
    minutes per invocation."""

    def _dead_verdict(probe, env):
        """A watchdog 'dead' line from any probe leg ends the ladder:
        retrying or asking jax's auto choice re-selects the same plugin
        and hangs identically."""
        dead = probe.get("platform") or "?"
        log(f"probe declared platform {dead!r} unusable: "
            f"{probe.get('reason')}")
        if not args.no_probe_cache:
            _store_probe_cache(None, env, dead_platform=dead)
        return None, {}, True, dead

    cached = None if args.no_probe_cache \
        else _load_probe_cache(args.probe_cache_ttl)
    if cached is not None and cached.get("platform"):
        log(f"probe verdict reused from cache ({cached['age']:.0f}s old): "
            f"platform={cached['platform']}")
        return cached["platform"], dict(cached.get("env") or {}), False, None
    if cached is not None and cached.get("dead_platform"):
        log(f"probe verdict reused from cache ({cached['age']:.0f}s old): "
            f"platform {cached['dead_platform']!r} is unusable — skipping "
            "the probe ladder entirely")
        return None, {}, True, cached["dead_platform"]
    recently_dead = cached is not None
    probe_timeout = args.probe_timeout
    if recently_dead:
        probe_timeout = min(probe_timeout, 30.0)
        log(f"cached verdict ({cached['age']:.0f}s old) says no platform "
            f"answered; re-checking with {probe_timeout:.0f}s probes")
    probe, timed_out, _, _ = _spawn("probe", "--probe", {}, [],
                                 probe_timeout, [])
    if probe is not None and probe.get("probe") == "dead":
        return _dead_verdict(probe, {})
    if probe is None and timed_out and not recently_dead:
        probe, timed_out, _, _ = _spawn("probe-retry", "--probe", {}, [],
                                     probe_timeout, [])
        if probe is not None and probe.get("probe") == "dead":
            return _dead_verdict(probe, {})
    if probe is not None:
        if not args.no_probe_cache:
            _store_probe_cache(probe.get("platform"), {})
        return probe.get("platform"), {}, recently_dead, None
    auto_env = {"CLSIM_PLATFORM": "auto"}
    probe, _, _, _ = _spawn("probe-auto", "--probe", auto_env, [],
                         probe_timeout, [])
    if probe is not None and probe.get("probe") == "dead":
        return _dead_verdict(probe, auto_env)
    if probe is not None:
        if not args.no_probe_cache:
            _store_probe_cache(probe.get("platform"), auto_env)
        return probe.get("platform"), auto_env, recently_dead, None
    if not args.no_probe_cache:
        _store_probe_cache(None, {})
    return None, {}, recently_dead, None


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = _parser().parse_args(argv)
    if args.probe:
        return run_probe()
    if args.worker:
        return run_worker(args)

    argv = [a for a in argv if a not in ("--worker", "--probe",
                                         "--assume-tpu")]
    recently_dead, dead_platform = False, None
    if args.assume_tpu:
        platform, env = "tpu", {}
        log("probe skipped (--assume-tpu): caller vouches for the tunnel")
    else:
        platform, env, recently_dead, dead_platform = \
            _find_live_platform(args)
        log(f"probe verdict: platform={platform}")

    plan = []
    if platform == "tpu" and args.assume_tpu:
        # no probe ran. One full-size attempt; then (a) after a crash-type
        # failure, one CLSIM_PLATFORM=auto rescue — the round-1 plugin-init
        # failure that the skipped ladder's 'probe-auto' leg exists for;
        # (b) after a HANG, fall straight through to the cpu row (the
        # 'crash' gate skips tpu-auto), so a wedged tunnel costs one
        # full-size worker timeout plus the cpu fallback, not the
        # three-attempt TPU ladder
        plan.append(("default", env, [], args.timeout, None))
        # a transient signal death (OOM-kill, segfault) with a live tunnel
        # deserves one same-env full-size retry — cheap with the compile
        # cache — exactly as the probed ladder classifies it; only a HANG
        # means "wedged, fall straight to cpu"
        plan.append(("default-retry", env, [],
                     min(args.timeout, max(args.timeout / 2, 450.0)),
                     "signal"))
        plan.append(("tpu-auto", {"CLSIM_PLATFORM": "auto"}, [],
                     min(args.timeout, 600.0), "crash"))
    elif platform == "tpu":
        plan.append(("default", env, [], args.timeout, None))
        # a hang or transient crash mid-measurement can still happen (tunnel
        # dropped during the window); with the persistent compilation cache
        # the retry skips the multi-minute compile, so a shorter budget
        # suffices — still capped by the operator's --timeout
        plan.append(("default-retry", env, [],
                     min(args.timeout, max(args.timeout / 2, 450.0)),
                     "retryable"))
        small = ["--batch", str(min(args.batch, 256)), "--repeats", "1"]
        plan.append(("tpu-small", env, small,
                     min(args.timeout, 480.0), None))
    elif platform is not None:
        # a live non-TPU platform (CPU dev box, or a deliberate
        # CLSIM_PLATFORM=cpu run — the probe inherits it) still gets the
        # full-size attempt before any clamped fallback
        plan.append(("default", env, [], args.timeout, None))
    elif dead_platform is not None:
        # the probe watchdog positively identified a known-unusable
        # platform (UNUSABLE_PLATFORMS) — its hang is structural, not a
        # tunnel flake, so a blind full-size attempt would burn its whole
        # budget discovering the same thing; fall straight to the cpu rung
        log(f"skipping tpu-blind: platform {dead_platform!r} is "
            "known-unusable (probe watchdog verdict) — falling straight "
            "to the cpu rung")
    else:
        # every probe hung: the tunnel may still recover mid-window (hung
        # device calls complete when it does), so spend one full-size
        # attempt on it before conceding — the official number must not be
        # a CPU fallback just because the tunnel napped through the probes.
        # Budget is trimmed so the whole ladder (3 probes + this + the CPU
        # fallback) stays inside the ~25-minute envelope the round-3 driver
        # was observed to tolerate — and trimmed hard (120s) when a fresh
        # cached verdict ALREADY burned a full ladder on this dead tunnel
        plan.append(("tpu-blind", {}, [],
                     min(args.timeout, 120.0 if recently_dead else 600.0),
                     None))
    # last resort: CPU with a reduced workload so it finishes; the JSON line
    # carries platform=cpu so this can never masquerade as a TPU number
    cpu_args = ["--nodes", str(min(args.nodes, 256)),
                "--batch", str(min(args.batch, 64)),
                "--phases", str(min(args.phases, 16)),
                "--repeats", "1"]
    plan.append(("cpu", {"CLSIM_PLATFORM": "cpu", "CLSIM_FALLBACK": "1"},
                 cpu_args, min(args.timeout, 480.0), None))

    # gate per entry: None = always runs; "retryable" = only after a hang
    # or any crash-type failure (timeout, EXIT_BACKEND_INIT, signal
    # death); "signal" = only after a signal death (transient OOM-kill /
    # segfault — same-env retry is worthwhile, a hang is not, it means
    # the tunnel wedged); "crash" = only after EXIT_BACKEND_INIT — the
    # plugin-init failure a CLSIM_PLATFORM=auto rescue can actually fix.
    prev_retryable = prev_backend_init = prev_signal = False
    for name, env_overrides, extra, timeout, gate in plan:
        if gate == "retryable" and not prev_retryable:
            # a clean rc!=0 failure is deterministic — a same-size retry
            # would fail identically
            log(f"skipping '{name}' (previous failure was not retryable)")
            continue
        if gate == "signal" and not prev_signal:
            log(f"skipping '{name}' (previous failure was not a "
                "signal death)")
            continue
        if gate == "crash" and not prev_backend_init:
            log(f"skipping '{name}' (previous failure was not a "
                "backend-init crash)")
            continue
        parsed, timed_out, retryable, backend_init = _spawn(
            name, "--worker", env_overrides, extra, timeout, argv)
        if parsed is not None:
            print(json.dumps(parsed), flush=True)
            return 0
        prev_retryable = timed_out or retryable
        prev_backend_init = backend_init
        prev_signal = retryable and not timed_out and not backend_init
        if not (timed_out or retryable):
            # a clean measurement failure (invalid results, repeated OOM) —
            # a smaller or different-platform attempt would only mask it
            # with a success-shaped number for a workload that failed
            break
    # every environment gets a parseable line and exit 0
    print(json.dumps({
        "metric": "node_ticks_per_sec_per_chip",
        "value": 0.0,
        "unit": "node-ticks/s/chip",
        "vs_baseline": 0.0,
        "platform": "none",
        "error": "all benchmark attempts failed (see stderr)",
        "note": "measured TPU rows live in BASELINE_MEASURED.jsonl / "
                "BASELINE.md",
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
