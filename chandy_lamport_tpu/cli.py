"""Command-line interface — the framework's operational front door.

The reference has no CLI (``go test`` is its only entry point, SURVEY.md §3);
this covers the same ground and the scale workflows the reference lacks:

  run    execute a .top + .events fixture pair on any backend, print the
         collected snapshots in .snap format (round-trips through the golden
         parser)
  test   run every reference golden case end-to-end and report pass/fail —
         the CLI twin of the pytest suite
  storm  batched scale run (instances x storm program) with aggregate
         metrics, optional checkpointing
  bench  the node-ticks/sec benchmark (same engine as /bench.py)

Usage: python -m chandy_lamport_tpu <command> [args]
"""

from __future__ import annotations

import argparse
import json
import sys

from chandy_lamport_tpu.config import REFERENCE_TEST_SEED, SimConfig


def _cmd_run(args) -> int:
    from chandy_lamport_tpu.api import run_events_file

    snaps, sim = run_events_file(args.topology, args.events,
                                 backend=args.backend, seed=args.seed,
                                 trace=args.trace,
                                 exact_impl=args.exact_impl)
    for snap in snaps:
        print(snap.id)
        for nid in sorted(snap.token_map):
            print(f"{nid} {snap.token_map[nid]}")
        for m in snap.messages:
            print(f"{m.src} {m.dest} {m.message}")
        print()
    if args.trace:
        print(sim.trace.pretty(), file=sys.stderr)
    return 0


def _cmd_test(args) -> int:
    from chandy_lamport_tpu.api import run_events_file
    from chandy_lamport_tpu.utils.compare import (
        SnapshotMismatch,
        assert_snapshots_equal,
        check_tokens,
        sort_snapshots,
    )
    from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

    if (args.backend == "jax"
            and getattr(args, "exact_impl", "cascade") == "wave"):
        # one clear refusal instead of seven per-case failures: the golden
        # suite replays the Go-exact stream, which the wave formulation
        # refuses by design (order-dependent draws; ops/tick.TickKernel).
        # jax-only: the parity backend ignores exact_impl entirely, so
        # ``test --backend parity --exact-impl wave`` runs (ADVICE r5 #2)
        print("the golden suite replays the order-dependent Go-exact "
              "delay stream; exact_impl='wave' cannot serve it — use "
              "cascade or fold (tests/test_wave.py carries the wave's "
              "conformance evidence)", file=sys.stderr)
        return 2

    # the parity oracle has one reference-literal implementation and
    # api.make_backend refuses the knob there — drop it so parity runs
    # are impl-flag agnostic
    impl = (getattr(args, "exact_impl", "cascade")
            if args.backend == "jax" else "cascade")
    failures = 0
    for top, events, snaps in REFERENCE_TESTS:
        name = events.removesuffix(".events")
        try:
            actual, sim = run_events_file(
                fixture_path(top), fixture_path(events),
                backend=args.backend, exact_impl=impl)
            assert len(actual) == len(snaps), (
                f"{len(actual)} snapshots, expected {len(snaps)}")
            check_tokens(sim.node_tokens(), actual)
            expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
            for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
                assert_snapshots_equal(e, a)
            print(f"PASS {name}")
        except (SnapshotMismatch, AssertionError, ValueError, OSError,
                RuntimeError) as exc:  # RuntimeError covers DenseBackendError
            failures += 1
            print(f"FAIL {name}: {exc}")
    print(f"{len(REFERENCE_TESTS) - failures}/{len(REFERENCE_TESTS)} passed")
    if args.backend == "jax":
        # which device actually ran the goldens — an on-device conformance
        # claim (tools/r5_measure.py) must be checkable from this output
        import jax

        dev = jax.devices()[0]
        print(f"platform: {dev.platform} ({dev.device_kind})")
    return 1 if failures else 0


def _cmd_storm(args) -> int:
    import jax

    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.metrics import (
        conservation_delta,
        progress_counters,
    )

    gen = {"ring": lambda: ring_topology(args.nodes, tokens=args.phases + 10),
           "er": lambda: erdos_renyi(args.nodes, 3.0, args.seed,
                                     tokens=args.phases + 10),
           "sf": lambda: scale_free(args.nodes, 2, args.seed,
                                    tokens=args.phases + 10)}[args.graph]
    spec = gen()
    cfg = SimConfig.for_workload(
        snapshots=args.snapshots, max_recorded=args.max_recorded,
        record_dtype=args.record_dtype, window_dtype=args.window_dtype,
        reduce_mode=args.reduce_mode,
        split_markers=args.scheduler == "sync",
        **({"queue_capacity": args.queue_capacity}
           if args.queue_capacity else {}))
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, args.seed),
                           batch=args.batch, scheduler=args.scheduler,
                           exact_impl=args.exact_impl,
                           check_every=args.check_every,
                           megatick=args.megatick)
    prog = storm_program(
        runner.topo, phases=args.phases, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, args.snapshots, 1, 2,
                                            max_phases=args.phases))
    final = runner.run_storm(runner.init_batch(), prog)
    jax.block_until_ready(final)
    counters = {k: int(v) for k, v in progress_counters(
        final, cfg, runner.topo.n).items()}
    expected = int(runner.topo.tokens0.sum()) * args.batch
    counters["conservation_delta"] = int(
        conservation_delta(final, cfg, expected))
    if args.checkpoint:
        from chandy_lamport_tpu.utils.checkpoint import save_state

        save_state(args.checkpoint, final,
                   meta={"nodes": runner.topo.n, "batch": args.batch,
                         "scheduler": args.scheduler})
        counters["checkpoint"] = args.checkpoint
    print(json.dumps(counters))
    return 0 if counters["error_bits"] == 0 else 1


def _cmd_bench(args) -> int:
    from chandy_lamport_tpu.bench import main as bench_main

    return bench_main(args.bench_args)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="chandy_lamport_tpu",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--platform", default=None,
                   help="force the JAX platform (e.g. cpu, tpu). This image's "
                        "TPU plugin registers itself programmatically, so the "
                        "JAX_PLATFORMS env var alone cannot override it; "
                        "CLSIM_PLATFORM works too")
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("run", help="run a .top + .events pair")
    pr.add_argument("topology")
    pr.add_argument("events")
    pr.add_argument("--backend", choices=["parity", "jax"], default="parity")
    pr.add_argument("--seed", type=int, default=REFERENCE_TEST_SEED + 1)
    pr.add_argument("--trace", action="store_true")
    pr.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="jax backend: which bit-identical formulation of "
                         "the reference scheduler runs the script "
                         "(ops/tick.TickKernel docstring; 'wave' needs a "
                         "position-addressable sampler, so it refuses the "
                         "default Go-exact stream)")
    pr.set_defaults(fn=_cmd_run)

    pt = sub.add_parser("test", help="run the reference golden suite")
    pt.add_argument("--backend", choices=["parity", "jax"], default="parity")
    pt.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="jax backend: run the golden suite through this "
                         "formulation of the reference scheduler (the "
                         "goldens replay the Go-exact stream, which 'wave' "
                         "refuses by design)")
    pt.set_defaults(fn=_cmd_test)

    ps = sub.add_parser("storm", help="batched scale run")
    ps.add_argument("--graph", choices=["ring", "er", "sf"], default="sf")
    ps.add_argument("--nodes", type=int, default=256)
    ps.add_argument("--batch", type=int, default=128)
    ps.add_argument("--phases", type=int, default=32)
    ps.add_argument("--snapshots", type=int, default=8)
    ps.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    ps.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="--scheduler exact: the bit-exact tick formulation "
                         "(ops/tick.TickKernel; 'wave' needs the hash/"
                         "uniform-free samplers — i.e. --delay hash)")
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--megatick", type=int, default=8,
                    help="K-tick fusion depth for the exact path's multi-"
                         "tick loops (drain + tick-N stretches; ops/tick."
                         "TickKernel docstring); 1 disables the fusion")
    ps.add_argument("--queue-capacity", type=int, default=0,
                    help="per-edge ring slots; 0 = size to the workload "
                         "(SimConfig.for_workload)")
    ps.add_argument("--max-recorded", type=int, default=0,
                    help="per-edge log slots L; 0 = derived "
                         "(SimConfig.for_workload)")
    ps.add_argument("--window-dtype", choices=["int32", "uint16"],
                    default="int32",
                    help="rec_start/rec_end plane dtype (uint16 = modular "
                         "counters, SimConfig docstring)")
    ps.add_argument("--record-dtype", choices=["int32", "int16"],
                    default="int32")
    ps.add_argument("--reduce-mode", choices=["auto", "matmul", "segsum"],
                    default="auto")
    ps.add_argument("--check-every", type=int, default=0,
                    help="evaluate the token-conservation invariant inside "
                         "the run every K phases (0 = off); violations set "
                         "the sticky ERR_CONSERVATION bit")
    ps.add_argument("--delay", choices=["uniform", "hash"],
                    default="hash",
                    help="fast-path delay sampler (same default as bench "
                         "--delay)")
    ps.add_argument("--checkpoint", help="save final state to this .npz")
    ps.set_defaults(fn=_cmd_storm)

    pb = sub.add_parser("bench", help="node-ticks/sec benchmark")
    pb.add_argument("bench_args", nargs=argparse.REMAINDER)
    pb.set_defaults(fn=_cmd_bench)

    args = p.parse_args(argv)
    import os

    platform = args.platform or os.environ.get("CLSIM_PLATFORM")
    if platform:
        # env var too: the bench subcommand runs its measurement in worker
        # subprocesses that read CLSIM_PLATFORM (the parent's jax.config
        # doesn't reach them)
        os.environ["CLSIM_PLATFORM"] = platform
        import jax

        jax.config.update("jax_platforms", platform)
    if getattr(args, "backend", None) == "jax":
        # the bit-exact Go-PRNG delay stream needs 64-bit integers under jit
        import jax

        jax.config.update("jax_enable_x64", True)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
