"""Command-line interface — the framework's operational front door.

The reference has no CLI (``go test`` is its only entry point, SURVEY.md §3);
this covers the same ground and the scale workflows the reference lacks:

  run    execute a .top + .events fixture pair on any backend, print the
         collected snapshots in .snap format (round-trips through the golden
         parser)
  test   run every reference golden case end-to-end and report pass/fail —
         the CLI twin of the pytest suite
  trace  run a fixture pair with the device flight recorder armed
         (utils/tracing.py): print the decoded protocol timeline in the
         reference Logger's format, optionally export Perfetto JSON and
         schema-versioned telemetry JSONL
  storm  batched scale run (instances x storm program) with aggregate
         metrics, optional checkpointing
  stream continuous lane scheduling: drive a queue of J heterogeneous jobs
         through B lane slots, refilling each slot the moment its job
         retires (parallel/batch.run_stream); prints jobs/s + occupancy
  serve  online multi-tenant serving (chandy_lamport_tpu/serving): a
         seeded Poisson/Zipf open-loop request schedule admitted live
         under the serve_policy knob (EDF within priority class / fifo)
         with per-tenant quotas, ingest-time memo serving, per-interval
         telemetry JSONL and a persistent executable cache that lets a
         restarted server skip the cold compile
  bench  the node-ticks/sec benchmark (same engine as /bench.py)

Usage: python -m chandy_lamport_tpu <command> [args]
"""

from __future__ import annotations

import argparse
import json
import sys

from chandy_lamport_tpu.config import REFERENCE_TEST_SEED, SimConfig


def _cmd_run(args) -> int:
    from chandy_lamport_tpu.api import run_events_file

    snaps, sim = run_events_file(args.topology, args.events,
                                 backend=args.backend, seed=args.seed,
                                 trace=args.trace,
                                 exact_impl=args.exact_impl)
    for snap in snaps:
        print(snap.id)
        for nid in sorted(snap.token_map):
            print(f"{nid} {snap.token_map[nid]}")
        for m in snap.messages:
            print(f"{m.src} {m.dest} {m.message}")
        print()
    if args.trace:
        print(sim.trace.pretty(), file=sys.stderr)
    return 0


def _cmd_test(args) -> int:
    from chandy_lamport_tpu.api import run_events_file
    from chandy_lamport_tpu.utils.compare import (
        SnapshotMismatch,
        assert_snapshots_equal,
        check_tokens,
        sort_snapshots,
    )
    from chandy_lamport_tpu.utils.fixtures import read_snapshot_file
    from chandy_lamport_tpu.utils.goldens import REFERENCE_TESTS, fixture_path

    if (args.backend == "jax"
            and getattr(args, "exact_impl", "cascade") == "wave"):
        # one clear refusal instead of seven per-case failures: the golden
        # suite replays the Go-exact stream, which the wave formulation
        # refuses by design (order-dependent draws; ops/tick.TickKernel).
        # jax-only: the parity backend ignores exact_impl entirely, so
        # ``test --backend parity --exact-impl wave`` runs (ADVICE r5 #2)
        print("the golden suite replays the order-dependent Go-exact "
              "delay stream; exact_impl='wave' cannot serve it — use "
              "cascade or fold (tests/test_wave.py carries the wave's "
              "conformance evidence)", file=sys.stderr)
        return 2

    # the parity oracle has one reference-literal implementation and
    # api.make_backend refuses the knob there — drop it so parity runs
    # are impl-flag agnostic
    impl = (getattr(args, "exact_impl", "cascade")
            if args.backend == "jax" else "cascade")
    failures = 0
    for top, events, snaps in REFERENCE_TESTS:
        name = events.removesuffix(".events")
        try:
            actual, sim = run_events_file(
                fixture_path(top), fixture_path(events),
                backend=args.backend, exact_impl=impl)
            assert len(actual) == len(snaps), (
                f"{len(actual)} snapshots, expected {len(snaps)}")
            check_tokens(sim.node_tokens(), actual)
            expected = [read_snapshot_file(fixture_path(f)) for f in snaps]
            for e, a in zip(sort_snapshots(expected), sort_snapshots(actual)):
                assert_snapshots_equal(e, a)
            print(f"PASS {name}")
        except (SnapshotMismatch, AssertionError, ValueError, OSError,
                RuntimeError) as exc:  # RuntimeError covers DenseBackendError
            failures += 1
            print(f"FAIL {name}: {exc}")
    print(f"{len(REFERENCE_TESTS) - failures}/{len(REFERENCE_TESTS)} passed")
    if args.backend == "jax":
        # which device actually ran the goldens — an on-device conformance
        # claim (tools/r5_measure.py) must be checkable from this output
        import jax

        dev = jax.devices()[0]
        print(f"platform: {dev.platform} ({dev.device_kind})")
    return 1 if failures else 0


def _cmd_trace(args) -> int:
    """Run a fixture with the flight recorder armed on the jax backend and
    print the decoded timeline — what the ``run --trace`` path does on the
    parity backend, but captured INSIDE the jitted kernels."""
    from chandy_lamport_tpu.api import run_events_file

    snaps, sim = run_events_file(args.topology, args.events,
                                 backend="jax", seed=args.seed, trace=True)
    recorded, dropped = sim.trace.counts()
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(sim.trace.perfetto(), f)
        print(f"wrote perfetto trace: {args.perfetto}", file=sys.stderr)
    if args.telemetry:
        from chandy_lamport_tpu.utils.tracing import TelemetryWriter

        with TelemetryWriter(args.telemetry) as tw:
            tw.write("trace_run", {
                "topology": args.topology, "events": args.events,
                "seed": args.seed, "snapshots": len(snaps),
                "trace_events": recorded, "trace_dropped": dropped})
            for ev in sim.trace.events:
                tw.write("event", {"tick": ev.tick, "event": ev.kind_name,
                                   "actor": ev.actor,
                                   "payload": ev.payload})
        print(f"wrote telemetry: {args.telemetry}", file=sys.stderr)
    print(sim.trace.pretty())
    print(f"# {recorded} events recorded, {dropped} dropped",
          file=sys.stderr)
    return 0


def _cmd_storm(args) -> int:
    import numpy as np

    import jax

    from chandy_lamport_tpu.core.state import decode_error_bits
    from chandy_lamport_tpu.models.workloads import (
        StormProgram,
        erdos_renyi,
        ring_topology,
        scale_free,
        staggered_snapshots,
        storm_program,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.checkpoint import load_state, save_state
    from chandy_lamport_tpu.utils.metrics import (
        conservation_delta,
        progress_counters,
    )

    gen = {"ring": lambda: ring_topology(args.nodes, tokens=args.phases + 10),
           "er": lambda: erdos_renyi(args.nodes, 3.0, args.seed,
                                     tokens=args.phases + 10),
           "sf": lambda: scale_free(args.nodes, 2, args.seed,
                                    tokens=args.phases + 10)}[args.graph]
    spec = gen()
    cfg = SimConfig.for_workload(
        snapshots=args.snapshots, max_recorded=args.max_recorded,
        record_dtype=args.record_dtype, window_dtype=args.window_dtype,
        reduce_mode=args.reduce_mode,
        split_markers=args.scheduler == "sync",
        snapshot_timeout=args.snapshot_timeout,
        snapshot_retries=args.snapshot_retries,
        snapshot_every=args.snapshot_every,
        **({"queue_capacity": args.queue_capacity}
           if args.queue_capacity else {}))
    faults = None
    if any((args.fault_drop, args.fault_dup, args.fault_jitter,
            args.fault_crash, args.marker_fault_drop, args.marker_fault_dup,
            args.marker_fault_jitter)):
        from chandy_lamport_tpu.models.faults import JaxFaults

        faults = JaxFaults(
            args.fault_seed if args.fault_seed is not None else args.seed,
            drop_rate=args.fault_drop, dup_rate=args.fault_dup,
            jitter_rate=args.fault_jitter, crash_rate=args.fault_crash,
            crash_mode=args.crash_mode, crash_len=args.crash_len,
            crash_period=args.crash_period,
            marker_drop_rate=args.marker_fault_drop,
            marker_dup_rate=args.marker_fault_dup,
            marker_jitter_rate=args.marker_fault_jitter)
    # an armed adversary quarantines by default: an injured lane freezes
    # with its decoded bits surfaced instead of poisoning the aggregates
    quarantine = args.quarantine or faults is not None
    trace = None
    if args.trace or args.trace_capacity:
        from chandy_lamport_tpu.utils.tracing import JaxTrace

        trace = JaxTrace(capacity=args.trace_capacity)
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, args.seed),
                           batch=args.batch, scheduler=args.scheduler,
                           exact_impl=args.exact_impl,
                           check_every=args.check_every,
                           megatick=args.megatick,
                           kernel_engine=args.kernel_engine, faults=faults,
                           quarantine=quarantine, trace=trace,
                           fused_tick=args.fused_tick,
                           fused_block_edges=args.fused_block_edges)
    prog = storm_program(
        runner.topo, phases=args.phases, amount=1,
        snapshot_phases=staggered_snapshots(runner.topo, args.snapshots, 1, 2,
                                            max_phases=args.phases))
    meta_base = {"nodes": runner.topo.n, "batch": args.batch,
                 "scheduler": args.scheduler, "phases": args.phases,
                 "delay": args.delay, "seed": args.seed}
    if faults is not None:
        meta_base["faults"] = faults.describe()

    state = runner.init_batch()
    start_phase = 0
    if args.resume_from:
        # the `like` template is a fresh state from the SAME flags — shape/
        # treedef validation rejects a checkpoint from a different run shape
        state, meta = load_state(args.resume_from, state)
        start_phase = int(meta.get("next_phase", 0))
        print(f"resumed from {args.resume_from} at phase {start_phase}",
              file=sys.stderr)
    if args.checkpoint_every:
        if not args.checkpoint:
            print("--checkpoint-every needs --checkpoint PATH (the file "
                  "the periodic snapshots land in)", file=sys.stderr)
            return 2
        # chunked execution: K phases per dispatch, atomic checkpoint after
        # each chunk. Bit-identical to the single dispatch (same ticks,
        # same state-carried streams); a kill between chunks resumes via
        # --resume-from to the same final state.
        k = args.checkpoint_every
        for chunk, lo in enumerate(range(start_phase, args.phases, k)):
            hi = min(lo + k, args.phases)
            sub = StormProgram(np.asarray(prog.amounts)[lo:hi],
                               np.asarray(prog.snap)[lo:hi])
            state = runner.run_storm(state, sub, drain=False)
            jax.block_until_ready(state)
            save_state(args.checkpoint, state,
                       meta={**meta_base, "next_phase": hi})
            if args.kill_after_chunk is not None \
                    and chunk == args.kill_after_chunk:
                # deterministic mid-run "preemption" for the resume tests:
                # die right after a checkpoint landed, before the drain
                print(json.dumps({"killed_after_phase": hi,
                                  "checkpoint": args.checkpoint}))
                return 17
        final = runner.drain(state)
    else:
        sub = (prog if not start_phase
               else StormProgram(np.asarray(prog.amounts)[start_phase:],
                                 np.asarray(prog.snap)[start_phase:]))
        final = runner.run_storm(state, sub)
    jax.block_until_ready(final)
    counters = {k: int(v) for k, v in progress_counters(
        final, cfg, runner.topo.n).items()}
    counters["errors_decoded"] = decode_error_bits(counters["error_bits"])
    expected = int(runner.topo.tokens0.sum()) * args.batch
    counters["conservation_delta"] = int(
        conservation_delta(final, cfg, expected))
    # supervisor lifecycle row (initiated/completed/aborted/retried/
    # failed/stale_markers + recovery-line age), always present so a
    # supervisor-off run visibly reports zero churn
    counters["snapshot_lifecycle"] = BatchedRunner.summarize(
        final)["snapshot_lifecycle"]
    errs = np.asarray(jax.device_get(final.error))
    if faults is not None:
        summary = BatchedRunner.summarize(final)
        counters["fault_events"] = summary["fault_events"]
        counters["fault_skew"] = summary["fault_skew"]
        counters["quarantined_lanes"] = int((errs != 0).sum())
        # per-lane decode for the injured lanes (first 16), so a crashed
        # lane's fate is readable straight off the JSON row
        counters["lane_errors"] = {
            int(i): decode_error_bits(int(errs[i]))
            for i in np.flatnonzero(errs)[:16]}
    if trace is not None:
        from chandy_lamport_tpu.utils.tracing import trace_counts

        tr_rec, tr_drop = trace_counts(final)
        counters["trace_events"], counters["trace_dropped"] = tr_rec, tr_drop
    if args.checkpoint:
        save_state(args.checkpoint, final,
                   meta={**meta_base, "next_phase": args.phases,
                         "drained": True})
        counters["checkpoint"] = args.checkpoint
    if args.telemetry:
        from chandy_lamport_tpu.utils.tracing import TelemetryWriter

        with TelemetryWriter(args.telemetry) as tw:
            tw.write("storm_run", {**meta_base, **counters})
        counters["telemetry"] = args.telemetry
    print(json.dumps(counters))
    if counters["error_bits"] == 0:
        return 0
    # an armed adversary EXPECTS casualties: the run succeeds when every
    # injured lane was quarantined (frozen + decoded above) rather than
    # silently poisoning the aggregates
    return 0 if (faults is not None and quarantine) else 1


def _cmd_stream(args) -> int:
    import time

    import jax

    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        stream_jobs,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.utils.checkpoint import load_state

    if args.checkpoint_every and not args.checkpoint:
        print("--checkpoint-every needs --checkpoint PATH (the file the "
              "periodic (state, stream) snapshots land in)", file=sys.stderr)
        return 2
    tokens = args.max_phases + 10
    gen = {"ring": lambda: ring_topology(args.nodes, tokens=tokens),
           "er": lambda: erdos_renyi(args.nodes, 3.0, args.seed,
                                     tokens=tokens),
           "sf": lambda: scale_free(args.nodes, 2, args.seed,
                                    tokens=tokens)}[args.graph]
    spec = gen()
    cfg = SimConfig.for_workload(snapshots=args.snapshots,
                                 split_markers=args.scheduler == "sync")
    faults = None
    if any((args.fault_drop, args.fault_dup, args.fault_jitter)):
        from chandy_lamport_tpu.models.faults import JaxFaults

        faults = JaxFaults(
            args.fault_seed if args.fault_seed is not None else args.seed,
            drop_rate=args.fault_drop, dup_rate=args.fault_dup,
            jitter_rate=args.fault_jitter)
    trace = None
    if args.trace or args.trace_capacity:
        from chandy_lamport_tpu.utils.tracing import JaxTrace

        trace = JaxTrace(capacity=args.trace_capacity)
    guards = None
    if args.guards:
        from chandy_lamport_tpu.utils.guards import RuntimeGuards

        guards = RuntimeGuards()
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, args.seed),
                           batch=args.batch, scheduler=args.scheduler,
                           kernel_engine=args.kernel_engine,
                           fused_tick=args.fused_tick,
                           faults=faults, quarantine=faults is not None,
                           trace=trace, memo=args.memo,
                           memo_cache=args.memo_cache,
                           prefix_cache=args.prefix_cache, guards=guards)
    jcount = args.jobs or 3 * args.batch
    jobs = stream_jobs(spec, jcount, seed=args.seed,
                       base_phases=args.base_phases,
                       tail_alpha=args.tail_alpha,
                       max_phases=args.max_phases,
                       dup_rate=args.dup_rate,
                       prefix_overlap=args.prefix_overlap)
    pool = runner.pack_jobs(jobs)
    state = stream = None
    if args.resume_from:
        # same-flags `like` template: shape/treedef validation rejects a
        # checkpoint from a different queue or batch shape
        like = (runner.init_batch(), runner.init_stream(pool))
        (state, stream), meta = load_state(args.resume_from, like)
        print(f"resumed from {args.resume_from} at {meta}", file=sys.stderr)
    t0 = time.perf_counter()
    state, stream = runner.run_stream(
        pool, stretch=args.stretch, drain_chunk=args.drain_chunk,
        admission=args.admission, state=state, stream=stream,
        checkpoint=args.checkpoint, checkpoint_every=args.checkpoint_every,
        kill_after_saves=args.kill_after_saves)
    jax.block_until_ready(state.time)
    wall = time.perf_counter() - t0
    done = int(stream.jobs_done)
    served = len(runner.stream_results(stream))
    if args.kill_after_saves is not None and served < jcount:
        # deterministic mid-queue "preemption" for the resume tests: die
        # right after that many checkpoints landed
        print(json.dumps({"killed_after_steps": int(stream.steps),
                          "jobs_done": done,
                          "checkpoint": args.checkpoint}))
        return 17
    row = runner.summarize_stream(stream)
    row.update({"graph": args.graph, "nodes": runner.topo.n,
                "batch": args.batch, "jobs": jcount,
                "admission": args.admission, "scheduler": args.scheduler,
                "memo": runner.memo, "dup_rate": args.dup_rate,
                "prefix_overlap": args.prefix_overlap,
                "wall_seconds": round(wall, 3),
                "jobs_per_sec": round(done / wall, 2) if wall > 0 else 0.0,
                # jobs SERVED per second: executed + memo-served — the
                # number the memo plane actually multiplies
                "effective_jobs_per_sec":
                    round(served / wall, 2) if wall > 0 else 0.0})
    errored = [r for r in runner.stream_results(stream) if r["error"]]
    row["jobs_errored"] = len(errored)
    if errored:
        # per-job decode for the injured jobs (first 16) — readable
        # straight off the JSON row, like storm's lane_errors
        row["job_errors"] = {r["job"]: r["errors_decoded"]
                             for r in errored[:16]}
    if trace is not None:
        from chandy_lamport_tpu.utils.tracing import trace_counts

        tr_rec, tr_drop = trace_counts(state)
        row["trace_events"], row["trace_dropped"] = tr_rec, tr_drop
    if guards is not None:
        row["guards"] = guards.books()
    if args.telemetry:
        from chandy_lamport_tpu.utils.tracing import TelemetryWriter

        with TelemetryWriter(args.telemetry) as tw:
            tw.write("stream_run", row)
            for r in runner.stream_results(stream):
                tw.write("stream_job", r)
        row["telemetry"] = args.telemetry
    print(json.dumps(row))
    # an armed adversary EXPECTS casualties (quarantined + harvested with
    # their error bits); without one any errored job is a failure
    return 0 if (faults is not None or not errored) else 1


def _cmd_serve(args) -> int:
    from chandy_lamport_tpu.models.workloads import (
        erdos_renyi,
        ring_topology,
        scale_free,
        serve_workload,
    )
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    from chandy_lamport_tpu.serving import ExecutableCache, serve_run
    from chandy_lamport_tpu.utils.checkpoint import load_state

    if args.checkpoint_every and not args.checkpoint:
        print("--checkpoint-every needs --checkpoint PATH (the file the "
              "periodic (state, stream) snapshots land in)", file=sys.stderr)
        return 2
    tokens = args.max_phases + 10
    gen = {"ring": lambda: ring_topology(args.nodes, tokens=tokens),
           "er": lambda: erdos_renyi(args.nodes, 3.0, args.seed,
                                     tokens=tokens),
           "sf": lambda: scale_free(args.nodes, 2, args.seed,
                                    tokens=tokens)}[args.graph]
    spec = gen()
    cfg = SimConfig.for_workload(snapshots=args.snapshots,
                                 split_markers=args.scheduler == "sync")
    guards = None
    if args.guards:
        from chandy_lamport_tpu.utils.guards import RuntimeGuards

        guards = RuntimeGuards()
    runner = BatchedRunner(spec, cfg, make_fast_delay(args.delay, args.seed),
                           batch=args.batch, scheduler=args.scheduler,
                           kernel_engine=args.kernel_engine,
                           fused_tick=args.fused_tick,
                           memo_cache=args.memo_cache,
                           memo_cache_entries=args.memo_cache_entries,
                           memo_cache_bytes=args.memo_cache_bytes,
                           guards=guards)
    rcount = args.requests or 3 * args.batch
    quotas = ([int(x) for x in args.quota.split(",")] if args.quota
              else None)
    reqs = serve_workload(spec, rcount, seed=args.seed, rate=args.rate,
                          tenants=args.tenants, priorities=args.priorities,
                          deadline_slack=tuple(args.deadline_slack),
                          dup_rate=args.dup_rate,
                          base_phases=args.base_phases,
                          tail_alpha=args.tail_alpha,
                          max_phases=args.max_phases)
    state = stream = None
    if args.resume_from:
        # same-flags `like` template (shape/treedef validation rejects a
        # checkpoint from a different queue, tenant or batch shape); the
        # serving books (deadline misses, per-tenant counts) ride the
        # carry, so the resumed accounting is bit-exact
        tenants = max(args.tenants, len(quotas) if quotas else 0)
        pool = runner.pack_jobs([r.events for r in reqs],
                                content_keys=True)
        like = (runner.init_batch(),
                runner.init_stream(
                    pool, args.results_capacity, tenants=tenants,
                    tenant_quota=(list(quotas)
                                  + [0] * (tenants - len(quotas))
                                  if quotas else None)))
        (state, stream), meta = load_state(args.resume_from, like)
        print(f"resumed from {args.resume_from} at {meta}", file=sys.stderr)
    telemetry = None
    if args.telemetry:
        from chandy_lamport_tpu.utils.tracing import TelemetryWriter

        telemetry = TelemetryWriter(args.telemetry)
    try:
        state, stream, report = serve_run(
            runner, reqs, policy=args.serve_policy, quotas=quotas,
            stretch=args.stretch, drain_chunk=args.drain_chunk,
            results_capacity=args.results_capacity, state=state,
            stream=stream, checkpoint=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            kill_after_saves=args.kill_after_saves,
            telemetry=telemetry,
            telemetry_interval=args.telemetry_interval,
            exec_cache=(ExecutableCache(args.exec_cache)
                        if args.exec_cache else None))
        if report["killed"]:
            # deterministic mid-queue "preemption" for the resume tests:
            # die right after that many checkpoints landed
            print(json.dumps({"killed_after_steps": report["steps"],
                              "checkpoint": args.checkpoint}))
            return 17
        rows = runner.stream_results(stream)
        if telemetry is not None:
            for r in rows:
                telemetry.write("serve_job", r)
    finally:
        if telemetry is not None:
            telemetry.close()
    row = runner.summarize_stream(stream)
    row.update(report)
    row.update({"graph": args.graph, "nodes": runner.topo.n,
                "batch": args.batch, "rate": args.rate,
                "dup_rate": args.dup_rate, "scheduler": args.scheduler,
                "serve_policy": args.serve_policy})
    errored = [r for r in rows if r["error"]]
    row["jobs_errored"] = len(errored)
    if errored:
        row["job_errors"] = {r["job"]: r["errors_decoded"]
                             for r in errored[:16]}
    if guards is not None:
        row["guards"] = guards.books()
    if args.telemetry:
        row["telemetry"] = args.telemetry
    print(json.dumps(row))
    return 0 if not errored else 1


def _cmd_bench(args) -> int:
    from chandy_lamport_tpu.bench import main as bench_main

    return bench_main(args.bench_args)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="chandy_lamport_tpu",
                                description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--platform", default=None,
                   help="force the JAX platform (e.g. cpu, tpu). This image's "
                        "TPU plugin registers itself programmatically, so the "
                        "JAX_PLATFORMS env var alone cannot override it; "
                        "CLSIM_PLATFORM works too")
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("run", help="run a .top + .events pair")
    pr.add_argument("topology")
    pr.add_argument("events")
    pr.add_argument("--backend", choices=["parity", "jax"], default="parity")
    pr.add_argument("--seed", type=int, default=REFERENCE_TEST_SEED + 1)
    pr.add_argument("--trace", action="store_true")
    pr.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="jax backend: which bit-identical formulation of "
                         "the reference scheduler runs the script "
                         "(ops/tick.TickKernel docstring; 'wave' needs a "
                         "position-addressable sampler, so it refuses the "
                         "default Go-exact stream)")
    pr.set_defaults(fn=_cmd_run)

    pt = sub.add_parser("test", help="run the reference golden suite")
    pt.add_argument("--backend", choices=["parity", "jax"], default="parity")
    pt.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="jax backend: run the golden suite through this "
                         "formulation of the reference scheduler (the "
                         "goldens replay the Go-exact stream, which 'wave' "
                         "refuses by design)")
    pt.set_defaults(fn=_cmd_test)

    pv = sub.add_parser("trace", help="run a fixture with the device flight "
                                      "recorder armed; print the decoded "
                                      "timeline")
    pv.add_argument("topology")
    pv.add_argument("events")
    pv.add_argument("--seed", type=int, default=REFERENCE_TEST_SEED + 1)
    pv.add_argument("--perfetto", metavar="PATH",
                    help="write Chrome/Perfetto trace-event JSON "
                         "(load at ui.perfetto.dev)")
    pv.add_argument("--telemetry", metavar="PATH",
                    help="write the decoded events as schema-versioned "
                         "JSONL (tools/analyze.py --telemetry)")
    # backend="jax" so main()'s x64 hook below arms the Go-exact stream
    pv.set_defaults(fn=_cmd_trace, backend="jax")

    ps = sub.add_parser("storm", help="batched scale run")
    ps.add_argument("--graph", choices=["ring", "er", "sf"], default="sf")
    ps.add_argument("--nodes", type=int, default=256)
    ps.add_argument("--batch", type=int, default=128)
    ps.add_argument("--phases", type=int, default=32)
    ps.add_argument("--snapshots", type=int, default=8)
    ps.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    ps.add_argument("--exact-impl", choices=["cascade", "wave", "fold"],
                    default="cascade",
                    help="--scheduler exact: the bit-exact tick formulation "
                         "(ops/tick.TickKernel; 'wave' needs the hash/"
                         "uniform-free samplers — i.e. --delay hash)")
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--megatick", type=int, default=8,
                    help="K-tick fusion depth for the exact path's multi-"
                         "tick loops (drain + tick-N stretches; ops/tick."
                         "TickKernel docstring); 1 disables the fusion")
    ps.add_argument("--queue-capacity", type=int, default=0,
                    help="per-edge ring slots; 0 = size to the workload "
                         "(SimConfig.for_workload)")
    ps.add_argument("--max-recorded", type=int, default=0,
                    help="per-edge log slots L; 0 = derived "
                         "(SimConfig.for_workload)")
    ps.add_argument("--window-dtype", choices=["int32", "uint16"],
                    default="int32",
                    help="rec_start/rec_end plane dtype (uint16 = modular "
                         "counters, SimConfig docstring)")
    ps.add_argument("--record-dtype", choices=["int32", "int16"],
                    default="int32")
    ps.add_argument("--reduce-mode", choices=["auto", "matmul", "segsum"],
                    default="auto")
    ps.add_argument("--kernel-engine", choices=["auto", "xla", "pallas"],
                    default="auto",
                    help="tick-kernel engine (chandy_lamport_tpu.kernels): "
                         "'pallas' = the fused ring-queue + segment-"
                         "reduction kernels (interpret-mode emulation off-"
                         "TPU), 'auto' = pallas only on TPU; bit-identical "
                         "results")
    ps.add_argument("--fused-tick", choices=["auto", "on", "off"],
                    default="auto",
                    help="one-kernel megatick (kernels/megatick.py): 'on' "
                         "runs exact-path multi-tick/drain/flush loops as "
                         "ONE Pallas kernel scanning K ticks VMEM-resident "
                         "(needs --kernel-engine pallas and --megatick > "
                         "1), 'auto' fuses exactly when eligible and the "
                         "working set fits the VMEM budget; bit-identical "
                         "results")
    ps.add_argument("--fused-block-edges", type=int, default=0,
                    help="fault-plane DMA block width for the fused "
                         "megatick's HBM->VMEM mask stream (0 = default)")
    ps.add_argument("--check-every", type=int, default=0,
                    help="evaluate the token-conservation invariant inside "
                         "the run every K phases (0 = off); violations set "
                         "the sticky ERR_CONSERVATION bit")
    ps.add_argument("--delay", choices=["uniform", "hash"],
                    default="hash",
                    help="fast-path delay sampler (same default as bench "
                         "--delay)")
    ps.add_argument("--checkpoint", help="save final state to this .npz "
                                         "(atomic tmp-then-replace write)")
    ps.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                    help="checkpoint to --checkpoint after every K phases "
                         "(chunked dispatch, bit-identical to the single "
                         "dispatch); a killed run resumes via --resume-from "
                         "to a bit-identical final state")
    ps.add_argument("--resume-from", metavar="PATH",
                    help="resume a storm from a checkpoint written by "
                         "--checkpoint-every (pass the SAME storm flags; "
                         "shape/structure mismatches are rejected with a "
                         "CheckpointError)")
    ps.add_argument("--fault-drop", type=float, default=0.0, metavar="R",
                    help="fault adversary (models/faults.py): per-(edge, "
                         "tick) token-drop probability")
    ps.add_argument("--fault-dup", type=float, default=0.0, metavar="R",
                    help="per-(edge, tick) token-duplicate probability")
    ps.add_argument("--fault-jitter", type=float, default=0.0, metavar="R",
                    help="per-(edge, tick) extra-delay jitter (front stall) "
                         "probability")
    ps.add_argument("--fault-crash", type=float, default=0.0, metavar="R",
                    help="per-(node, window) crash probability "
                         "(--crash-mode picks pause/lossy semantics)")
    ps.add_argument("--fault-seed", type=int, default=None,
                    help="adversary stream seed (default: --seed)")
    ps.add_argument("--crash-mode", choices=["pause", "lossy"],
                    default="pause",
                    help="crash semantics: 'pause' = preemption (memory "
                         "survives, resume is the recovery); 'lossy' = "
                         "restart restores from the last completed "
                         "Chandy-Lamport snapshot, or quarantines with "
                         "ERR_FAULT_UNRECOVERED when none exists")
    ps.add_argument("--crash-len", type=int, default=2,
                    help="crash window length in ticks")
    ps.add_argument("--crash-period", type=int, default=32,
                    help="crash window cadence in ticks")
    ps.add_argument("--marker-fault-drop", type=float, default=0.0,
                    metavar="R",
                    help="marker-plane adversary (models/faults.py): "
                         "per-(edge, tick) MARKER-drop probability — the "
                         "control-plane loss the snapshot supervisor "
                         "(--snapshot-timeout) recovers from")
    ps.add_argument("--marker-fault-dup", type=float, default=0.0,
                    metavar="R",
                    help="per-(edge, tick) marker-duplicate probability")
    ps.add_argument("--marker-fault-jitter", type=float, default=0.0,
                    metavar="R",
                    help="per-(edge, tick) marker-front stall probability")
    ps.add_argument("--snapshot-timeout", type=int, default=0, metavar="T",
                    help="snapshot supervisor (SimConfig.snapshot_timeout): "
                         "abort + re-initiate (fresh epoch, doubling "
                         "deadline) any snapshot attempt not completed "
                         "within T ticks; 0 = off")
    ps.add_argument("--snapshot-retries", type=int, default=3,
                    help="re-initiations per snapshot before the slot is "
                         "marked failed and the lane raises "
                         "ERR_SNAPSHOT_TIMEOUT")
    ps.add_argument("--snapshot-every", type=int, default=0, metavar="K",
                    help="snapshot daemon: initiate a snapshot every K "
                         "ticks (rotating initiator) while slots remain, "
                         "keeping the lossy-crash recovery line fresh; "
                         "0 = off")
    ps.add_argument("--quarantine", action="store_true",
                    help="freeze a lane the moment its error bits fire "
                         "(auto-enabled whenever a fault rate is set)")
    ps.add_argument("--kill-after-chunk", type=int, default=None,
                    help=argparse.SUPPRESS)  # resume-test hook: exit 17
    #                                          right after that chunk's
    #                                          checkpoint lands
    ps.add_argument("--trace", action="store_true",
                    help="arm the device flight recorder (per-lane event "
                         "ring, utils/tracing.py); adds trace_events/"
                         "trace_dropped to the JSON row")
    ps.add_argument("--trace-capacity", type=int, default=0, metavar="K",
                    help="ring slots per lane (0 = JaxTrace default when "
                         "--trace is set); implies --trace when > 0")
    ps.add_argument("--telemetry", metavar="PATH",
                    help="append the run's JSON row as schema-versioned "
                         "JSONL telemetry (tools/analyze.py --telemetry)")
    ps.set_defaults(fn=_cmd_storm)

    pq = sub.add_parser("stream", help="continuous-lane streaming run "
                                       "(job queue over B slots)")
    pq.add_argument("--graph", choices=["ring", "er", "sf"], default="sf")
    pq.add_argument("--nodes", type=int, default=256)
    pq.add_argument("--batch", type=int, default=64,
                    help="lane slots B (device batch width)")
    pq.add_argument("--jobs", type=int, default=0,
                    help="queued jobs J (0 = 3x batch)")
    pq.add_argument("--base-phases", type=int, default=4,
                    help="heavy-tailed job lengths: Pareto(base, alpha) "
                         "phases per job (models/workloads.stream_jobs)")
    pq.add_argument("--tail-alpha", type=float, default=1.1)
    pq.add_argument("--max-phases", type=int, default=32)
    pq.add_argument("--dup-rate", type=float, default=0.0, metavar="R",
                    help="fraction of the queue that repeats a Zipf-drawn "
                         "scenario-library job byte-for-byte "
                         "(models/workloads.stream_jobs) — the traffic "
                         "shape the memo plane serves for free")
    pq.add_argument("--memo", choices=["off", "admit", "full", "prefix"],
                    default="off",
                    help="memo plane (config.ENGINE_KNOBS): 'admit' "
                         "coalesces duplicate jobs onto one lane + serves "
                         "persistent-cache hits; 'full' adds transition "
                         "fast-forwarding; 'prefix' adds speculative "
                         "forking of near-duplicates from checkpointed "
                         "prefix boundaries. 'off' is bit-identical to the "
                         "pre-memo engine; every served summary is "
                         "bit-identical to solo execution")
    pq.add_argument("--memo-cache", metavar="PATH",
                    help="persistent content-addressed summary cache "
                         "(JSON lines; utils/memocache.py) — hits across "
                         "runs are served without burning a lane")
    pq.add_argument("--prefix-overlap", type=float, default=0.0,
                    metavar="R",
                    help="fraction of the queue that extends a shared base "
                         "scenario with a unique tail — NEAR-duplicates "
                         "(models/workloads.stream_jobs prefix_overlap), "
                         "the traffic shape memo=prefix forks for free")
    pq.add_argument("--prefix-cache", metavar="PATH",
                    help="persistent prefix-checkpoint store for "
                         "memo=prefix (JSON lines; utils/memocache."
                         "PrefixCache) — forks across runs resume from "
                         "the deepest checkpointed boundary on disk")
    pq.add_argument("--snapshots", type=int, default=8)
    pq.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    pq.add_argument("--kernel-engine", choices=["auto", "xla", "pallas"],
                    default="auto",
                    help="tick-kernel engine (chandy_lamport_tpu.kernels); "
                         "bit-identical results")
    pq.add_argument("--fused-tick", choices=["auto", "on", "off"],
                    default="auto",
                    help="one-kernel megatick knob (kernels/megatick.py); "
                         "bit-identical results")
    pq.add_argument("--seed", type=int, default=0)
    pq.add_argument("--delay", choices=["uniform", "hash"], default="hash")
    pq.add_argument("--admission", choices=["stream", "gang"],
                    default="stream",
                    help="'stream' refills a slot the moment its job "
                         "retires; 'gang' waits for every slot to idle — "
                         "the static-batching baseline on the same "
                         "executable")
    pq.add_argument("--stretch", type=int, default=4,
                    help="script phases advanced per jitted stream step")
    pq.add_argument("--drain-chunk", type=int, default=32,
                    help="drain ticks per stream step for quiescing lanes")
    pq.add_argument("--fault-drop", type=float, default=0.0, metavar="R",
                    help="fault adversary: per-(edge, tick) token-drop "
                         "probability, armed per JOB (each job replays its "
                         "own stream wherever it lands)")
    pq.add_argument("--fault-dup", type=float, default=0.0, metavar="R")
    pq.add_argument("--fault-jitter", type=float, default=0.0, metavar="R")
    pq.add_argument("--fault-seed", type=int, default=None,
                    help="adversary stream seed (default: --seed)")
    pq.add_argument("--checkpoint", help="save the combined (state, stream) "
                                         "carry to this .npz")
    pq.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                    help="checkpoint to --checkpoint every K stream steps; "
                         "a killed run resumes via --resume-from to a "
                         "bit-identical finish (admission order and per-job "
                         "streams live in the saved carry)")
    pq.add_argument("--resume-from", metavar="PATH",
                    help="resume a streaming run from a checkpoint written "
                         "by --checkpoint-every (pass the SAME flags)")
    pq.add_argument("--kill-after-saves", type=int, default=None,
                    help=argparse.SUPPRESS)  # resume-test hook: exit 17
    #                                          after that many checkpoints
    pq.add_argument("--trace", action="store_true",
                    help="arm the device flight recorder (lane-admit/"
                         "harvest land in the per-lane rings)")
    pq.add_argument("--trace-capacity", type=int, default=0, metavar="K",
                    help="ring slots per lane (0 = JaxTrace default when "
                         "--trace is set); implies --trace when > 0")
    pq.add_argument("--telemetry", metavar="PATH",
                    help="append a stream_run row plus one stream_job row "
                         "per harvested job as schema-versioned JSONL")
    pq.add_argument("--guards", action="store_true",
                    help="arm the runtime contract sentry "
                         "(utils/guards.RuntimeGuards): the steady-state "
                         "loop runs under jax.transfer_guard('disallow') + "
                         "jax.checking_leaks with a compile-event counter; "
                         "adds a guards (compiles + per-site transfer) "
                         "books dict to the JSON row")
    pq.set_defaults(fn=_cmd_stream)

    pz = sub.add_parser("serve", help="online multi-tenant serving over "
                                      "the stream engine "
                                      "(chandy_lamport_tpu/serving)")
    pz.add_argument("--graph", choices=["ring", "er", "sf"], default="sf")
    pz.add_argument("--nodes", type=int, default=256)
    pz.add_argument("--batch", type=int, default=64,
                    help="lane slots B (device batch width)")
    pz.add_argument("--requests", type=int, default=0,
                    help="request count J (0 = 3x batch)")
    pz.add_argument("--rate", type=float, default=2.0,
                    help="open-loop Poisson arrival rate in requests per "
                         "stream step (models/workloads.serve_workload)")
    pz.add_argument("--tenants", type=int, default=4,
                    help="Zipf-weighted tenant population")
    pz.add_argument("--priorities", type=int, default=2,
                    help="priority classes (higher class admits first "
                         "under edf)")
    pz.add_argument("--deadline-slack", type=int, nargs=2,
                    default=[64, 256], metavar=("LO", "HI"),
                    help="per-request deadline = arrival + uniform[LO, HI] "
                         "stream steps; misses are counted in the carry")
    pz.add_argument("--quota", metavar="N,N,...",
                    help="per-tenant admission caps, comma-separated in "
                         "tenant order (0 = unlimited); requests over "
                         "quota are refused at ingest, never starving "
                         "other tenants")
    pz.add_argument("--serve-policy", choices=["edf", "fifo"],
                    default="edf",
                    help="admission ordering (config.ENGINE_KNOBS): 'edf' "
                         "= earliest deadline first within priority "
                         "class; 'fifo' = arrival order (the baseline)")
    pz.add_argument("--dup-rate", type=float, default=0.0, metavar="R",
                    help="fraction of requests repeating a Zipf-drawn "
                         "scenario-library job byte-for-byte — served "
                         "from the memo plane without burning a lane")
    pz.add_argument("--base-phases", type=int, default=4)
    pz.add_argument("--tail-alpha", type=float, default=1.1)
    pz.add_argument("--max-phases", type=int, default=32)
    pz.add_argument("--snapshots", type=int, default=8)
    pz.add_argument("--scheduler", choices=["sync", "exact"], default="sync")
    pz.add_argument("--kernel-engine", choices=["auto", "xla", "pallas"],
                    default="auto")
    pz.add_argument("--fused-tick", choices=["auto", "on", "off"],
                    default="auto")
    pz.add_argument("--seed", type=int, default=0)
    pz.add_argument("--delay", choices=["uniform", "hash"], default="hash")
    pz.add_argument("--stretch", type=int, default=4)
    pz.add_argument("--drain-chunk", type=int, default=32)
    pz.add_argument("--results-capacity", type=int, default=0,
                    help="results-ring slots (0 = one per request; must "
                         "cover every executed job)")
    pz.add_argument("--memo-cache", metavar="PATH",
                    help="persistent content-addressed summary cache — "
                         "warm digests are served at INGEST, without a "
                         "lane (utils/memocache.py)")
    pz.add_argument("--memo-cache-entries", type=int, default=0,
                    help="summary-cache LRU capacity in entries (0 = "
                         "unbounded)")
    pz.add_argument("--memo-cache-bytes", type=int, default=0,
                    help="summary-cache LRU capacity in serialized bytes "
                         "(0 = unbounded)")
    pz.add_argument("--exec-cache", metavar="DIR",
                    help="shape-bucketed executable cache directory "
                         "(serving/executables.py): jax.export artifacts "
                         "let a restarted server skip the cold compile at "
                         "a seen shape bucket")
    pz.add_argument("--checkpoint", help="save the combined (state, stream) "
                                         "carry to this .npz")
    pz.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                    help="checkpoint every K stream steps; a killed server "
                         "resumes via --resume-from bit-exactly (admission "
                         "is a memoryless function of the saved carry)")
    pz.add_argument("--resume-from", metavar="PATH",
                    help="resume a killed serve run (pass the SAME flags)")
    pz.add_argument("--kill-after-saves", type=int, default=None,
                    help=argparse.SUPPRESS)  # resume-test hook: exit 17
    #                                          after that many checkpoints
    pz.add_argument("--telemetry", metavar="PATH",
                    help="schema-versioned JSONL: one serve_interval row "
                         "per --telemetry-interval steps (occupancy, "
                         "admit p50/p99, deadline misses, memo hits, "
                         "per-tenant books), a final serve_run row and "
                         "one serve_job row per served request")
    pz.add_argument("--telemetry-interval", type=int, default=64,
                    metavar="K")
    pz.add_argument("--guards", action="store_true",
                    help="arm the runtime contract sentry "
                         "(utils/guards.RuntimeGuards) around the serve "
                         "loop; adds a guards books dict to the JSON row")
    pz.set_defaults(fn=_cmd_serve)

    pb = sub.add_parser(
        "bench", help="node-ticks/sec benchmark",
        description="Forwards everything after 'bench' to bench.py "
                    "(--scheduler, --stream, --graphshard P with "
                    "--comm-engine dense|sparse|auto and --megatick K, "
                    "--queue-engine, ...); one JSON row on stdout.")
    pb.add_argument("bench_args", nargs=argparse.REMAINDER)
    pb.set_defaults(fn=_cmd_bench)

    args = p.parse_args(argv)
    import os

    platform = args.platform or os.environ.get("CLSIM_PLATFORM")
    if platform:
        # env var too: the bench subcommand runs its measurement in worker
        # subprocesses that read CLSIM_PLATFORM (the parent's jax.config
        # doesn't reach them)
        os.environ["CLSIM_PLATFORM"] = platform
        import jax

        jax.config.update("jax_platforms", platform)
    if getattr(args, "backend", None) == "jax":
        # the bit-exact Go-PRNG delay stream needs 64-bit integers under jit
        import jax

        jax.config.update("jax_enable_x64", True)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
