"""Framework configuration.

The reference hard-codes four constants (``debug`` common.go:10, ``maxDelay``
sim.go:10, ``seed`` snapshot_test.go:9, ``testDir`` test_common.go:20). The
TPU framework additionally needs static capacities because everything the Go
code grows dynamically (per-link queues, active-snapshot maps, recorded-message
lists) must become fixed-shape HBM arrays for XLA.
"""

import dataclasses

# Max random delay added to packet delivery (reference sim.go:10).
# Delay drawn as 1 + Intn(MAX_DELAY) ticks relative to current time
# (reference sim.go:100-102): receive_time = time + 1 + Intn(5).
MAX_DELAY = 5

# Fixed seed used by the reference test suite (reference snapshot_test.go:9,20:
# rand.Seed(seed + 1)).
REFERENCE_TEST_SEED = 8053172852482175523

# Declarative registry of the backend-resolved engine knobs: knob name ->
# accepted spellings, "auto" first. Every knob follows the same pattern —
# a resolve_<knob>() that turns "auto" into a concrete engine per backend
# (ops/tick.resolve_queue_engine / resolve_comm_engine,
# kernels.resolve_kernel_engine), a --<knob> CLI/bench flag, and a
# <knob> field stamped into the bench worker JSON rows so sweep results
# record which engine actually ran. The spelling sets live ONLY here:
# SimConfig.__post_init__ and the runner kwarg checks validate against
# these rows (tools/staticcheck's knob-pattern rule enforces the whole
# pattern per row).
ENGINE_KNOBS = {
    "queue_engine": ("auto", "gather", "mask"),
    "comm_engine": ("auto", "dense", "sparse"),
    "kernel_engine": ("auto", "xla", "pallas"),
    # memoization plane (utils/memocache.resolve_memo): "off" keeps the
    # PR 5 stream step bit-identical (no digesting, no signature leaf
    # ops); "admit" content-addresses jobs at pack/admit time — exact
    # duplicates coalesce onto one representative lane and the
    # persistent summary cache serves repeats without burning a lane;
    # "full" adds transition fast-forwarding over the per-lane state
    # signature; "prefix" layers rolling per-phase-boundary digests on
    # the admit plane — near-duplicate jobs (shared script prefix,
    # divergent tail) fork from a checkpointed lane state at the deepest
    # cached prefix boundary instead of running the prefix cold
    # (utils/memocache.PrefixCache). Spellings are ordered
    # weakest-first, not "auto"-first: there is no backend-dependent
    # resolution, only an explicit opt-in ladder ("prefix" sits beside
    # "full", not above it — it trades the sig fast-forward for the
    # fork plane).
    "memo": ("off", "admit", "full", "prefix"),
    # serving admission policy (serving/admission.resolve_serve_policy):
    # "edf" (default) orders the eligible queue by priority class then
    # earliest deadline first; "fifo" is the arrival-order baseline the
    # serve bench A/Bs against. No backend resolution — pure validation,
    # like "memo".
    "serve_policy": ("edf", "fifo"),
    # one-kernel megatick (kernels/megatick.resolve_fused_tick): "auto"
    # executes the whole K-tick run_ticks/drain scan inside a single
    # VMEM-resident Pallas kernel whenever it applies (kernel_engine
    # resolved to pallas, megatick > 1, ring markers, cascade/wave,
    # working set within the VMEM budget — resident or tiled; the
    # supervisor/recorder refusals are lifted, both trace in-kernel) and
    # falls back to the PR 9 split kernels otherwise; "on" raises naming
    # ALL unmet requirements instead of silently splitting; "off" always
    # splits. Bit-identical every way.
    "fused_tick": ("auto", "on", "off"),
    # tiled megatick state (kernels/megatick.resolve_fused_tile): "auto"
    # streams the [E, C] ring planes HBM->VMEM in double-buffered edge
    # blocks exactly when the fused kernel's resident working set
    # overflows the VMEM budget — the shapes that used to silently fall
    # back to the split path — and keeps everything resident otherwise;
    # "on"/"off" force the layout (the differential tests pin
    # tiled==resident bit-identity that way). Moot when fused_tick
    # resolves "off".
    "fused_tile": ("auto", "on", "off"),
}


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static capacities and knobs for the dense/JAX backend.

    The Go reference uses unbounded structures; these capacities bound them
    with overflow flags checked in debug mode (SURVEY.md §7.1.3). Defaults
    comfortably cover every reference fixture (max in-flight per edge observed
    across all fixtures is small; 10 snapshots max in 10nodes.events).
    """

    queue_capacity: int = 16       # per-edge ring buffer slots (C)
    max_snapshots: int = 16        # concurrent snapshot slots (S)
    # Per-edge recorded-arrival LOG slots (L). Recording is stored as ONE
    # shared append log per edge (``log_amt[L, E]``) plus per-(snapshot,
    # edge) window counters — every slot recording an edge records the
    # same arrival stream, and each (s, e) records a contiguous window of
    # it, so the log carries the union of all windows instead of S
    # separate [M] buffers. L bounds recorded arrivals per edge across
    # ALL still-undecoded windows (ERR_RECORD_OVERFLOW past it).
    max_recorded: int = 32
    max_delay: int = MAX_DELAY
    max_ticks: int = 100_000       # drain-loop budget (guards non-strongly-connected graphs)
    # dtype of the per-edge arrival log ``log_amt[L, E]``; int16 halves it
    # and roughly doubles the max batch; amounts beyond the dtype's range
    # fire ERR_VALUE_OVERFLOW instead of truncating silently.
    record_dtype: str = "int32"
    # dtype of the per-(snapshot, edge) window-counter planes rec_start/
    # rec_end. "uint16" stores them modulo 2^16 — sound because a window's
    # LENGTH is bounded by max_recorded (ERR_RECORD_OVERFLOW past it) and
    # the log index only needs j % L, so with L a power of two dividing
    # 2^16 the modular counters decode identically; the i32 per-edge
    # rec_cnt/min_prot keep overflow detection exact. Halves the top
    # device-profile line (the every-tick [S, E] window-counter writes).
    window_dtype: str = "int32"
    # dtype for 0/1 COUNT incidence matmuls (ops/tick.count_dtype): "auto"
    # picks bf16 on TPU when the degree bound proves counts exact (<= 256),
    # f32 otherwise; "bfloat16"/"float32" force either side of the gate
    # (forced bf16 is rejected when the degree bound breaks exactness).
    # CI exercises the forced-bf16 numerics on the CPU mesh.
    count_dtype: str = "auto"
    # How the dense sync kernel reduces per-edge quantities to per-node
    # sums (token credits, marker arrival counts): "matmul" uses [N, E]
    # incidence matmuls on the MXU (fastest at small/medium graphs — 50M
    # vs 38M node-ticks/s at the 1k-node bench — but O(N*E) FLOPs and the
    # HLO-embedded constants break remote compilation around 8k nodes);
    # "segsum" uses O(E) integer prefix-sum segment reductions (exact at
    # any scale, no large constants). "auto" picks by graph size.
    reduce_mode: str = "auto"
    # How the graph-sharded runner (parallel/graphshard.py) moves per-tick
    # state across shards: "dense" exchanges the full [N] credit / [S, N]
    # arrival / [S, N] created planes via psum + all_gather and spreads
    # them through [N_local, Em] incidence matmuls; "sparse" reduces local
    # edge contributions with O(E_local) segment sums and exchanges only
    # the packed boundary rows — one lax.ppermute per neighbor pair over a
    # static ring schedule — so bytes scale with the partition CUT, not N
    # (utils/metrics.comm_bytes_model gives both curves). "auto" defers to
    # ops/tick.resolve_comm_engine (currently "sparse" everywhere). Both
    # engines are bit-identical to the unsharded sync kernel; a runner
    # kwarg overrides this per-instance.
    comm_engine: str = "auto"
    # Tick-kernel engine (chandy_lamport_tpu/kernels): "xla" keeps the
    # stock-XLA tick formulations; "pallas" routes the ring-queue
    # head/select/pop/append chain and the edge->node segment reductions
    # through the hand-fused Pallas kernels (interpret-mode emulation
    # off-TPU, so CI exercises the kernel bodies everywhere); "auto"
    # resolves to "pallas" only where compiled Pallas is supported (TPU),
    # "xla" elsewhere with a logged reason (kernels.resolve_kernel_engine).
    # Bit-identical results either way; runner kwargs override this
    # per-instance.
    kernel_engine: str = "auto"
    # One-kernel megatick (kernels/megatick.py): fuse the exact path's
    # whole K-tick scan — tick body, fault gates and all — into a single
    # VMEM-resident Pallas kernel so queue/node state never round-trips
    # HBM between ticks. "auto" engages it exactly where it applies and
    # splits otherwise (resolve_fused_tick documents the gate), "on"
    # raises when it cannot, "off" keeps the PR 9 split kernels. Runner
    # kwargs override per-instance; bit-identical either way
    # (tests/test_megatick_fused.py).
    fused_tick: str = "auto"
    # Tiled megatick state layout (kernels/megatick.resolve_fused_tile):
    # "auto" keeps the fused kernel's [E, C] ring-queue planes in HBM and
    # streams them through the double-buffered block pipeline whenever
    # the resident working set would overflow the VMEM budget, unlocking
    # fused execution on graphs far past it; "on"/"off" force the
    # layout. Bit-identical either way (tests/test_megatick_tiled.py).
    fused_tile: str = "auto"
    # Snapshot supervisor (ops/tick.TickKernel._supervise): with
    # snapshot_timeout > 0, a started snapshot that has not completed
    # within that many ticks of its (re-)initiation is aborted IN TRACE —
    # slot released, recorded windows cleared, channels un-frozen — and
    # re-initiated from the remembered initiator under a fresh marker
    # EPOCH (stragglers of the dead attempt are rejected as stale), with
    # the deadline doubling per retry (capped at 16x). After
    # snapshot_retries failed attempts the slot is marked failed and the
    # lane raises ERR_SNAPSHOT_TIMEOUT. 0 disables the supervisor — the
    # kernels trace zero supervisor ops (the faults=None contract).
    snapshot_timeout: int = 0
    snapshot_retries: int = 3
    # Snapshot daemon: with snapshot_every > 0 the tick kernels initiate a
    # snapshot every that-many ticks from a rotating initiator while free
    # slots remain (next_sid < max_snapshots), so lossy crashes always
    # find a recent recovery line (recovery-line age is surfaced by
    # utils/metrics.snapshot_lifecycle). Size max_snapshots to
    # run_length / snapshot_every.
    snapshot_every: int = 0
    # Device flight recorder (utils/tracing.py): capacity K of the
    # per-lane event trace ring riding on DenseState (three i32 [K]
    # planes tr_meta/tr_data/tr_tick + counters). 0 (the default) gives
    # zero-size planes and zero trace ops in the kernels — runners bump
    # it to JaxTrace.DEFAULT_CAPACITY when tracing is requested with the
    # knob left at 0. The ring WRAPS on overflow: the oldest events are
    # overwritten and the loss is surfaced as a dropped-events count
    # (max(0, tr_count - K)) in summarize()/storm JSON, never silently.
    trace_capacity: int = 0

    def __post_init__(self):
        if self.queue_capacity <= 0 or self.max_snapshots <= 0 or self.max_recorded <= 0:
            raise ValueError("capacities must be positive")
        if self.record_dtype not in ("int32", "int16"):
            raise ValueError("record_dtype must be 'int32' or 'int16'")
        if self.window_dtype not in ("int32", "uint16"):
            raise ValueError("window_dtype must be 'int32' or 'uint16'")
        if self.window_dtype == "uint16" and (
                self.max_recorded > 32768
                or self.max_recorded & (self.max_recorded - 1)):
            # strictly below 2^16: a completely full window (length == L)
            # must not alias length 0 under the mod-2^16 decode
            raise ValueError(
                "window_dtype='uint16' needs max_recorded to be a power of "
                "two <= 32768 (modular window decode requires L | 2^16 and "
                "full-window lengths < 2^16)")
        if self.count_dtype not in ("auto", "bfloat16", "float32"):
            raise ValueError("count_dtype must be 'auto', 'bfloat16' or 'float32'")
        if self.reduce_mode not in ("auto", "matmul", "segsum"):
            raise ValueError("reduce_mode must be 'auto', 'matmul' or 'segsum'")
        for knob in ("comm_engine", "kernel_engine", "fused_tick",
                     "fused_tile"):
            allowed = ENGINE_KNOBS[knob]
            if getattr(self, knob) not in allowed:
                raise ValueError(
                    f"{knob} must be one of {', '.join(map(repr, allowed))}")
        if (self.snapshot_timeout < 0 or self.snapshot_retries < 0
                or self.snapshot_every < 0):
            raise ValueError(
                "snapshot_timeout/snapshot_retries/snapshot_every must be "
                ">= 0 (0 disables the supervisor / daemon)")
        if self.trace_capacity < 0:
            raise ValueError(
                "trace_capacity must be >= 0 (0 disables the flight "
                "recorder)")

    @classmethod
    def for_workload(cls, *, snapshots: int, max_delay: int = MAX_DELAY,
                     sends_per_edge_per_phase: int = 1, hol_slack: int = 8,
                     split_markers: bool = False,
                     **overrides) -> "SimConfig":
        """A SimConfig whose queue capacity is sized to the workload instead
        of guessed (the round-2 bench zeroed itself because the default C=16
        could not hold the storm's worst-case per-edge backlog).

        Per-edge in-flight is bounded by three terms:
          markers   — each snapshot id crosses an edge at most once (a node
                      broadcasts an id only on first receipt, node.go:154-156),
                      so <= ``snapshots`` marker slots;
          tokens    — a message is undeliverable for at most ``max_delay``
                      ticks after its send tick (receive_time = t + 1 +
                      Intn(max_delay), sim.go:100-102), so a steady
                      ``sends_per_edge_per_phase`` rate keeps at most
                      rate x (max_delay + 1) tokens pending delay;
          HOL slack — head-of-line blocking (sim.go:82-92: one delivery per
                      source per tick, eligible messages wait behind
                      ineligible heads) plus marker-cascade bursts let the
                      backlog transiently exceed the steady-state bound;
                      ``hol_slack`` covers it (measured: the sf-1024 bench
                      storm peaks ~17 on hub edges with snapshots=8).

        ``split_markers=True`` drops the marker term: the sync scheduler's
        split representation (TickKernel marker_mode="split") keeps markers
        in their own [S, E] planes, so the ring only ever holds tokens —
        at the bench workload that takes C from 24 to 16 and cuts every
        [E, C] array's traffic by a third (measured +5% node-ticks/s).
        Pass it only for sync-scheduler runs; the exact scheduler's ring
        mode needs the marker slots.

        The result is rounded up to a multiple of 8 (friendlier [E, C] lane
        tiling) with a floor of 16. Overflow still flags ERR_QUEUE_OVERFLOW —
        this sizes away the default-workload failure, it does not remove the
        check.
        """
        analytic = ((0 if split_markers else snapshots)
                    + sends_per_edge_per_phase * (max_delay + 1))
        c = max(16, analytic + hol_slack)
        overrides.setdefault("max_snapshots", max(8, snapshots))
        # per-edge log capacity: the union of all snapshots' recording
        # windows on one edge — bounded by (window span ~ marker transit)
        # x send rate, summed over staggered snapshots when windows are
        # disjoint; 4 slots per snapshot with a floor of 32 covers every
        # measured workload, and ERR_RECORD_OVERFLOW + the bench's
        # doubling retry keep any shortfall honest
        if not overrides.get("max_recorded"):
            derived = max(32, 4 * snapshots)
            if overrides.get("window_dtype") == "uint16":
                # the modular window planes need L to be a power of two
                # (an EXPLICIT non-power-of-two override still raises);
                # clamp at the mod-2^16 decode bound — past snapshots=8192
                # the derivation would otherwise hand __post_init__ a value
                # the caller never chose. A clamped L stays honest through
                # ERR_RECORD_OVERFLOW at runtime.
                derived = min(1 << (derived - 1).bit_length(), 32768)
            overrides["max_recorded"] = derived
        # an explicit queue_capacity override wins over the derived size
        capacity = overrides.pop("queue_capacity", (c + 7) // 8 * 8)
        return cls(queue_capacity=capacity, max_delay=max_delay, **overrides)


DEFAULT_CONFIG = SimConfig()
