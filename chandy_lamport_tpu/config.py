"""Framework configuration.

The reference hard-codes four constants (``debug`` common.go:10, ``maxDelay``
sim.go:10, ``seed`` snapshot_test.go:9, ``testDir`` test_common.go:20). The
TPU framework additionally needs static capacities because everything the Go
code grows dynamically (per-link queues, active-snapshot maps, recorded-message
lists) must become fixed-shape HBM arrays for XLA.
"""

import dataclasses

# Max random delay added to packet delivery (reference sim.go:10).
# Delay drawn as 1 + Intn(MAX_DELAY) ticks relative to current time
# (reference sim.go:100-102): receive_time = time + 1 + Intn(5).
MAX_DELAY = 5

# Fixed seed used by the reference test suite (reference snapshot_test.go:9,20:
# rand.Seed(seed + 1)).
REFERENCE_TEST_SEED = 8053172852482175523


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static capacities and knobs for the dense/JAX backend.

    The Go reference uses unbounded structures; these capacities bound them
    with overflow flags checked in debug mode (SURVEY.md §7.1.3). Defaults
    comfortably cover every reference fixture (max in-flight per edge observed
    across all fixtures is small; 10 snapshots max in 10nodes.events).
    """

    queue_capacity: int = 16       # per-edge ring buffer slots (C)
    max_snapshots: int = 16        # concurrent snapshot slots (S)
    max_recorded: int = 32         # recorded messages per (snapshot, edge) (M)
    max_delay: int = MAX_DELAY
    max_ticks: int = 100_000       # drain-loop budget (guards non-strongly-connected graphs)
    # dtype of the recorded-message buffer rec_data[S, E, M] — the dominant
    # per-instance HBM term (utils/metrics.instance_footprint_bytes). int16
    # halves it and roughly doubles the max batch; amounts beyond the dtype's
    # range fire ERR_VALUE_OVERFLOW instead of truncating silently.
    record_dtype: str = "int32"

    def __post_init__(self):
        if self.queue_capacity <= 0 or self.max_snapshots <= 0 or self.max_recorded <= 0:
            raise ValueError("capacities must be positive")
        if self.record_dtype not in ("int32", "int16"):
            raise ValueError("record_dtype must be 'int32' or 'int16'")


DEFAULT_CONFIG = SimConfig()
