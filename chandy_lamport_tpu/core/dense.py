"""DenseSim — the JAX backend driver around the jitted tick kernel.

Pairs the dense array state (core/state.py) with the jitted kernel
(ops/tick.py) behind the same interface the parity backend exposes, so the
two are drop-in interchangeable through api.run_events / run_events_file and
differential tests can compare them on identical inputs.

Event scripts are orchestrated from the host (events are few and happen
between ticks, reference test_common.go:79-140); ticks, the drain loop and
the flush run fully under jit. Snapshot decode back to GlobalSnapshot happens
once at the end from a single device_get.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Union

import jax
import numpy as np

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import (
    Event,
    GlobalSnapshot,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.core.state import (
    DenseState,
    DenseTopology,
    decode_errors,
    decode_snapshot,
    init_state,
)
from chandy_lamport_tpu.models.delay import DelayModel
from chandy_lamport_tpu.ops.delay_jax import JaxDelay, from_host_model
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.utils.fixtures import TopologySpec


class DenseBackendError(RuntimeError):
    """Raised when the kernel's sticky error bitmask is non-zero after a run
    (the jit-compatible stand-in for the reference's log.Fatal calls)."""


class DenseTraceView:
    """Host-side view of the device flight-recorder ring — the dense
    backend's answer to the parity backend's EpochTrace surface: ``events``
    decodes the ring (utils/tracing.decode_trace), ``pretty()`` renders the
    reference Logger's epoch format (so dense and parity traces diff
    directly), ``perfetto()`` exports Chrome/Perfetto trace-event JSON and
    ``counts()`` returns (recorded, dropped)."""

    def __init__(self, sim: "DenseSim"):
        self._sim = sim

    @property
    def events(self):
        from chandy_lamport_tpu.utils.tracing import decode_trace

        return decode_trace(self._sim._host())

    def counts(self):
        from chandy_lamport_tpu.utils.tracing import trace_counts

        return trace_counts(self._sim._host())

    def pretty(self) -> str:
        from chandy_lamport_tpu.utils.tracing import trace_pretty

        return trace_pretty(self.events, self._sim.topo)

    def perfetto(self) -> dict:
        from chandy_lamport_tpu.utils.tracing import trace_to_perfetto

        return trace_to_perfetto(self.events, self._sim.topo)


class DenseSim:
    """Single-instance dense simulator on the JAX backend."""

    def __init__(self, topology: TopologySpec,
                 delay_model: Union[DelayModel, JaxDelay],
                 config: Optional[SimConfig] = None,
                 exact_impl: str = "cascade", megatick: int = 8,
                 queue_engine: str = "auto",
                 kernel_engine: Optional[str] = None, faults=None,
                 trace=None, fused_tick: Optional[str] = None,
                 fused_block_edges: int = 0):
        """``megatick``: K-tick fusion depth for ``tick N`` events and the
        drain loop (ops/tick.TickKernel docstring); semantics-preserving,
        1 restores the reference-literal one-iteration-per-tick loops (the
        oracle configuration the megatick differentials compare against).
        ``queue_engine``: ring-queue addressing (TickKernel docstring) —
        "gather" O(E) gathers/scatters, "mask" one-hot, or "auto"
        (default, backend-resolved); bit-identical results.
        ``kernel_engine``: tick-kernel engine ("xla" / "pallas" / "auto",
        chandy_lamport_tpu.kernels) — None (default) defers to the
        config's knob; bit-identical results.
        ``faults``: models/faults.JaxFaults or None — arm the deterministic
        fault adversary (TickKernel docstring); None compiles the hooks
        away entirely.
        ``trace``: utils/tracing.JaxTrace or None — arm the device flight
        recorder; ``self.trace`` then exposes the decoded timeline
        (DenseTraceView). None compiles every trace op away.
        ``fused_tick``: one-kernel megatick knob ("auto"/"on"/"off",
        kernels/megatick.py) — None defers to the config's knob;
        ``self.fused`` exposes the resolution. ``fused_block_edges``
        overrides the fault-plane DMA block width (0 = default)."""
        self.config = config or SimConfig()
        self.topo = DenseTopology(topology)
        self.delay = (delay_model if isinstance(delay_model, JaxDelay)
                      else from_host_model(delay_model))
        # the flush length must cover the sampler's actual max delay
        # (test_common.go:135-137 flushes maxDelay+1 ticks)
        if self.delay.max_delay != self.config.max_delay:
            self.config = dataclasses.replace(
                self.config, max_delay=self.delay.max_delay)
        if trace is not None and self.config.trace_capacity == 0:
            from chandy_lamport_tpu.utils.tracing import JaxTrace

            self.config = dataclasses.replace(
                self.config,
                trace_capacity=getattr(trace, "capacity", 0)
                or JaxTrace.DEFAULT_CAPACITY)
        self.kernel = TickKernel(self.topo, self.config, self.delay,
                                 exact_impl=exact_impl, megatick=megatick,
                                 queue_engine=queue_engine,
                                 kernel_engine=kernel_engine, faults=faults,
                                 trace=trace, fused_tick=fused_tick,
                                 fused_block_edges=fused_block_edges)
        self.kernel_engine = self.kernel.kernel_engine
        self.fused = self.kernel.fused
        # same surface as ParitySim: ``sim.trace`` is the timeline view
        # when armed, None otherwise
        self.trace = DenseTraceView(self) if self.kernel._trace_on else None
        self.state: DenseState = init_state(
            self.topo, self.config, self.delay.init_state(),
            fault_key=int(faults.init_state()) if faults is not None else 0)
        self._host_cache: Optional[DenseState] = None
        # host mirror of state.next_sid (ids are allocated sequentially,
        # sim.go:107-108) so collection knows which slots this run started
        self._next_sid = 0

    # -- event execution ---------------------------------------------------

    def process_event(self, event: Event) -> None:
        self._host_cache = None
        if isinstance(event, PassTokenEvent):
            src = self._node_index(event.src)
            dest = self._node_index(event.dest)
            e = self.topo.edge_index.get((src, dest))
            if e is None:
                raise ValueError(f"no link {event.src} -> {event.dest}")
            self.state = self.kernel.inject_send(
                self.state, np.int32(e), np.int32(event.tokens))
        elif isinstance(event, SnapshotEvent):
            node = self._node_index(event.node_id)
            self._next_sid += 1
            self.state = self.kernel.inject_snapshot(self.state, np.int32(node))
        elif isinstance(event, TickEvent):
            self.state = self.kernel.run_ticks(self.state, np.int32(event.n))
        else:
            raise TypeError(f"unknown event: {event!r}")

    def run_events(self, events: List[Event]) -> List[GlobalSnapshot]:
        """Execute a script + drain + flush; mirrors parity.run_events /
        reference test_common.go:79-140."""
        started: List[int] = []
        for ev in events:
            if isinstance(ev, SnapshotEvent):
                started.append(self._next_sid)
            self.process_event(ev)
        self.state = self.kernel.drain_and_flush(self.state)
        self._host_cache = None
        self.check_errors()
        host = self._host()
        return [decode_snapshot(self.topo, host, s) for s in started]

    # -- introspection (same surface as ParitySim) -------------------------

    def node_tokens(self):
        host = self._host()
        return {nid: int(host.tokens[i]) for i, nid in enumerate(self.topo.ids)}

    def total_tokens(self) -> int:
        """Node balances + in-flight non-marker tokens (the conserved
        quantity, test_common.go:298-328)."""
        host = self._host()
        total = int(host.tokens.sum())
        C = self.config.queue_capacity
        for e in range(self.topo.e):
            head, length = int(host.q_head[e]), int(host.q_len[e])
            for k in range(length):
                slot = (head + k) % C
                if not int(host.q_meta[e, slot]) & 1:
                    total += int(host.q_data[e, slot])
        return total

    def check_errors(self) -> None:
        bits = int(self._host().error)
        if bits:
            raise DenseBackendError(
                "dense backend error(s): " + "; ".join(decode_errors(bits)))

    # -- internals ---------------------------------------------------------

    def _node_index(self, node_id: str) -> int:
        idx = self.topo.index.get(node_id)
        if idx is None:
            raise ValueError(f"node {node_id} does not exist")
        return idx

    def _host(self) -> DenseState:
        if self._host_cache is None:
            self._host_cache = jax.device_get(self.state)
        return self._host_cache
