"""Parity backend: a pure-Python, single-instance oracle simulator.

This is the reference semantics (SURVEY.md §7.0) distilled into plain Python
with zero concurrency: the Go version's goroutines/WaitGroups/mutexes exist
only to *collect* results and collapse into simple counters here (the whole
simulation is already single-threaded-deterministic in the reference — ticks
run on one goroutine, sim.go:71-95).

Its roles: (1) de-risk every semantic question before any JAX is written,
(2) serve as the differential-testing oracle for the dense/JAX backend,
(3) provide the trace mode (utils/tracing.py) matching the reference Logger.

Bit-exactness-critical rules replicated (citations into the reference):
  R1 lexicographic node/dest iteration everywhere      sim.go:76,78; node.go:98
  R2 at most ONE delivery per source per tick, first eligible head in sorted
     dest order, sequential fold across sorted sources (mid-tick cascades
     visible to later sources)                          sim.go:71-95
  R3 per-channel FIFO with head-of-line blocking       queue.go; sim.go:82-84
  R4 PRNG draw order: one draw per send (node.go:130), one per outbound link
     in sorted-dest order on marker broadcast (node.go:98-107)
  R5 snapshot ids allocated in event order             sim.go:107-108
  R6 marker-source link excluded from recording on marker-triggered snapshot
     creation                                          node.go:61-69
  R7 tokens frozen at snapshot creation; debit at send time
     node.go:77,120
  R8 finalize when links_remaining hits 0, checked after EVERY marker
     receipt (including immediately after creation)    node.go:165-170
  R9 recorded messages flattened in sorted-src order — a deliberate,
     golden-compatible determinization of Go's random map order
     (node.go:188-195; SURVEY.md §2.2)
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Tuple

from chandy_lamport_tpu.config import MAX_DELAY
from chandy_lamport_tpu.core.spec import (
    Event,
    GlobalSnapshot,
    Message,
    MsgSnapshot,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.delay import DelayModel
from chandy_lamport_tpu.utils.tracing import EpochTrace


class _LocalSnapshot:
    """Per-(node, snapshot) recording state (reference node.go:34-43)."""

    __slots__ = ("id", "num_tokens", "incoming", "recording", "links_remaining",
                 "done", "msg_snapshots")

    def __init__(self, snapshot_id: int, num_tokens: int,
                 recording: Dict[str, bool], links_remaining: int):
        self.id = snapshot_id
        self.num_tokens = num_tokens          # frozen at creation (node.go:77)
        self.incoming: Dict[str, List[Message]] = {}
        self.recording = recording            # src id -> still recording?
        self.links_remaining = links_remaining
        self.done = False
        self.msg_snapshots: List[MsgSnapshot] = []


class _Node:
    """Protocol participant (reference node.go:14-22), dict/deque state."""

    def __init__(self, node_id: str, tokens: int, sim: "ParitySim"):
        self.sim = sim
        self.id = node_id
        self.tokens = tokens
        # dest id -> FIFO of (src, dest, Message, receive_time); append right,
        # pop left == reference Push/PushFront + Pop/Back (queue.go:18-24)
        self.outbound: Dict[str, Deque[Tuple[str, str, Message, int]]] = {}
        self.inbound_srcs: List[str] = []
        self.active: Dict[int, _LocalSnapshot] = {}

    # -- topology ---------------------------------------------------------
    def add_outbound_link(self, dest: "_Node") -> None:
        """reference node.go:87-94 (self-links silently ignored)."""
        if dest is self:
            return
        self.outbound[dest.id] = deque()
        dest.inbound_srcs.append(self.id)

    # -- sends ------------------------------------------------------------
    def send_tokens(self, num_tokens: int, dest: str) -> None:
        """reference node.go:112-131: debit at send, one PRNG draw."""
        if self.tokens < num_tokens:
            raise ValueError(
                f"node {self.id} attempted to send {num_tokens} tokens "
                f"when it only has {self.tokens}")
        msg = Message(is_marker=False, data=num_tokens)
        self.sim.trace.sent(self, dest, msg)
        self.tokens -= num_tokens
        if dest not in self.outbound:
            raise ValueError(f"unknown dest {dest} from node {self.id}")
        self.outbound[dest].append((self.id, dest, msg, self.sim.receive_time()))

    def send_to_neighbors(self, msg: Message) -> None:
        """reference node.go:97-109: sorted-dest order, one draw per link."""
        for dest in sorted(self.outbound):
            self.sim.trace.sent(self, dest, msg)
            self.outbound[dest].append((self.id, dest, msg, self.sim.receive_time()))

    # -- snapshot protocol ------------------------------------------------
    def create_local_snapshot(self, snapshot_id: int, src_link: str) -> None:
        """reference node.go:58-84. src_link=='' => initiator (record ALL
        inbound links); marker-triggered => exclude the marker's link (R6)."""
        recording = {src: True for src in self.inbound_srcs}
        links = len(self.inbound_srcs)
        if src_link:
            recording[src_link] = False
            links -= 1
        self.active[snapshot_id] = _LocalSnapshot(
            snapshot_id, self.tokens, recording, links)

    def start_snapshot(self, snapshot_id: int) -> None:
        """reference node.go:198-212 (minus the dead inboundBuffers block)."""
        if snapshot_id not in self.active:
            self.create_local_snapshot(snapshot_id, "")
        self.send_to_neighbors(Message(is_marker=True, data=snapshot_id))

    def handle_packet(self, src: str, msg: Message) -> None:
        """reference node.go:140-146."""
        if msg.is_marker:
            self.handle_marker(src, msg)
        else:
            self.handle_token(src, msg)

    def handle_marker(self, src: str, msg: Message) -> None:
        """reference node.go:149-171 (finalize check after every receipt, R8)."""
        sid = msg.data
        snap = self.active.get(sid)
        if snap is None:
            self.create_local_snapshot(sid, src)
            self.start_snapshot(sid)
        else:
            snap.recording[src] = False
            snap.links_remaining -= 1
        snap = self.active[sid]
        if snap.links_remaining == 0 and not snap.done:
            self._finalize_snapshot(sid)
            self.sim.notify_completed(self.id, sid)

    def handle_token(self, src: str, msg: Message) -> None:
        """reference node.go:174-185: credit first, then record into every
        active snapshot still recording this link."""
        self.tokens += msg.data
        for snap in self.active.values():
            if snap.recording.get(src):
                snap.incoming.setdefault(src, []).append(msg)

    def _finalize_snapshot(self, snapshot_id: int) -> None:
        """reference node.go:188-195, flattened in sorted-src order (R9)."""
        snap = self.active[snapshot_id]
        for src in sorted(snap.incoming):
            for m in snap.incoming[src]:
                snap.msg_snapshots.append(MsgSnapshot(src, self.id, m))
        snap.done = True


class ParitySim:
    """The simulation runtime (reference sim.go), minus all concurrency."""

    def __init__(self, delay_model: DelayModel, max_delay: int = MAX_DELAY,
                 trace: bool = False):
        self.time = 0
        self.next_snapshot_id = 0
        self.nodes: Dict[str, _Node] = {}
        self.delay_model = delay_model
        self.max_delay = max_delay
        # snapshot id -> count of nodes completed; complete at len(nodes)
        # (replaces the reference's per-snapshot WaitGroup, sim.go:116-117)
        self.completed_counts: Dict[int, int] = {}
        self.trace = EpochTrace(enabled=trace)
        self.trace.new_epoch()  # epoch 0 exists before any tick (test_common.go:35)

    # -- construction -----------------------------------------------------
    def add_node(self, node_id: str, tokens: int) -> None:
        self.nodes[node_id] = _Node(node_id, tokens, self)

    def add_link(self, src: str, dest: str) -> None:
        if src not in self.nodes:
            raise ValueError(f"node {src} does not exist")
        if dest not in self.nodes:
            raise ValueError(f"node {dest} does not exist")
        self.nodes[src].add_outbound_link(self.nodes[dest])

    # -- events -----------------------------------------------------------
    def process_event(self, event: Event) -> None:
        """reference sim.go:58-68 (+ tick, which the reference test harness
        issues directly, test_common.go:109-117)."""
        if isinstance(event, PassTokenEvent):
            self.nodes[event.src].send_tokens(event.tokens, event.dest)
        elif isinstance(event, SnapshotEvent):
            self.start_snapshot(event.node_id)
        elif isinstance(event, TickEvent):
            for _ in range(event.n):
                self.tick()
        else:
            raise TypeError(f"unknown event: {event!r}")

    # -- the hot loop -----------------------------------------------------
    def tick(self) -> None:
        """reference sim.go:71-95 — R1/R2/R3 exactly: sequential fold over
        sorted sources; per source scan sorted dests; deliver the first
        eligible queue head; break (per source) only on delivery."""
        self.time += 1
        self.trace.new_epoch()
        for src_id in sorted(self.nodes):
            node = self.nodes[src_id]
            for dest in sorted(node.outbound):
                q = node.outbound[dest]
                if q:
                    s, d, msg, rt = q[0]
                    if rt <= self.time:
                        q.popleft()
                        self.trace.received(self.nodes[d], s, msg)
                        self.nodes[d].handle_packet(s, msg)
                        break

    def receive_time(self) -> int:
        """reference sim.go:100-102."""
        return self.delay_model.receive_time(self.time)

    # -- snapshot lifecycle ----------------------------------------------
    def start_snapshot(self, node_id: str) -> int:
        """reference sim.go:105-123 (id allocation order = event order, R5)."""
        sid = self.next_snapshot_id
        self.next_snapshot_id += 1
        self.trace.start_snapshot(self.nodes[node_id], sid)
        self.completed_counts[sid] = 0
        self.nodes[node_id].start_snapshot(sid)
        return sid

    def notify_completed(self, node_id: str, snapshot_id: int) -> None:
        """reference sim.go:126-131."""
        self.trace.end_snapshot(self.nodes[node_id], snapshot_id)
        self.completed_counts[snapshot_id] += 1

    def snapshot_complete(self, snapshot_id: int) -> bool:
        return self.completed_counts.get(snapshot_id, -1) == len(self.nodes)

    def collect_snapshot(self, snapshot_id: int) -> GlobalSnapshot:
        """reference sim.go:134-173; the goroutine fan-out collapses into a
        gather in sorted-node order (per-destination order preserved, which is
        all the golden comparator requires, test_common.go:253-284)."""
        assert self.snapshot_complete(snapshot_id), "collect before completion"
        token_map: Dict[str, int] = {}
        messages: List[MsgSnapshot] = []
        for nid in sorted(self.nodes):
            local = self.nodes[nid].active[snapshot_id]
            token_map[nid] = local.num_tokens
            messages.extend(local.msg_snapshots)
        return GlobalSnapshot(snapshot_id, token_map, messages)

    # -- introspection ----------------------------------------------------
    def node_tokens(self) -> Dict[str, int]:
        return {nid: n.tokens for nid, n in self.nodes.items()}

    def total_tokens(self) -> int:
        """Node balances + in-flight (non-marker) tokens: the conserved
        quantity (test_common.go:298-328 counts both)."""
        total = sum(n.tokens for n in self.nodes.values())
        for n in self.nodes.values():
            for q in n.outbound.values():
                total += sum(m.data for _, _, m, _ in q if not m.is_marker)
        return total

    def pending_snapshot_ids(self) -> List[int]:
        return [sid for sid in self.completed_counts if not self.snapshot_complete(sid)]


def run_events(sim: ParitySim, events: List[Event]) -> List[GlobalSnapshot]:
    """Execute an event script + drain, reference test_common.go:79-140:
    run all events; tick while any snapshot incomplete (the reference's
    nondeterministic-count drain loop is outcome-equivalent to this minimal
    deterministic one — extra ticks deliver nothing relevant and draw no
    randomness, SURVEY.md §3.5); then max_delay+1 flush ticks; then collect
    in snapshot-id order."""
    started: List[int] = []
    for ev in events:
        if isinstance(ev, SnapshotEvent):
            started.append(sim.next_snapshot_id)
        sim.process_event(ev)
    guard = 0
    while sim.pending_snapshot_ids():
        sim.tick()
        guard += 1
        if guard > 1_000_000:
            raise RuntimeError(
                f"snapshots never completed: {sim.pending_snapshot_ids()} "
                "(graph not strongly connected?)")
    for _ in range(sim.max_delay + 1):
        sim.tick()
    return [sim.collect_snapshot(sid) for sid in started]
