"""Protocol data types — the semantic contract shared by every backend.

These mirror the reference's L0 types (common.go) but are plain frozen Python
dataclasses; the dense/JAX backend encodes the same information as arrays
(core/dense.py) and decodes back to these types at the API boundary.

Reference citations:
  - Message            common.go:28-39  (one struct for tokens AND markers)
  - MsgSnapshot        common.go:20-24
  - GlobalSnapshot     common.go:13-17
  - PassTokenEvent     common.go:58-62
  - SnapshotEvent      common.go:66-68
  - "tick" is a command in .events files (test_common.go:109-117), modeled
    here as TickEvent so an event script is a single typed list.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Union


@dataclasses.dataclass(frozen=True)
class Message:
    """A message on a channel: a token transfer or a snapshot marker.

    ``data`` is the token count for tokens, the snapshot id for markers
    (reference common.go:28-31). ``str()`` matches the Go rendering
    ``token(n)`` / ``marker(n)`` (common.go:33-39), which the golden-file
    format round-trips through.
    """

    is_marker: bool
    data: int

    def __str__(self) -> str:
        return f"marker({self.data})" if self.is_marker else f"token({self.data})"


@dataclasses.dataclass(frozen=True)
class MsgSnapshot:
    """A message recorded in-flight on the channel src->dest during a snapshot
    (reference common.go:20-24)."""

    src: str
    dest: str
    message: Message


@dataclasses.dataclass
class GlobalSnapshot:
    """The output of the Chandy-Lamport algorithm (reference common.go:13-17).

    ``token_map`` maps node id -> tokens frozen at that node's snapshot point;
    ``messages`` are all recorded in-flight messages. Cross-destination
    ordering of ``messages`` is not part of the contract (the golden
    comparator only requires per-destination order, test_common.go:253-284);
    our backends emit them grouped by lexicographically sorted destination
    node, each destination's messages in arrival order.
    """

    id: int
    token_map: Dict[str, int]
    messages: List[MsgSnapshot]


@dataclasses.dataclass(frozen=True)
class PassTokenEvent:
    """Injected event: src sends ``tokens`` tokens to dest (common.go:58-62)."""

    src: str
    dest: str
    tokens: int


@dataclasses.dataclass(frozen=True)
class SnapshotEvent:
    """Injected event: start the snapshot protocol at ``node_id``
    (common.go:66-68)."""

    node_id: str


@dataclasses.dataclass(frozen=True)
class TickEvent:
    """Advance simulation time by ``n`` steps (.events ``tick [N]`` command,
    test_common.go:109-117)."""

    n: int = 1


Event = Union[PassTokenEvent, SnapshotEvent, TickEvent]
