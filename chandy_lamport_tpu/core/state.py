"""Dense array encoding of the simulator — topology and state pytree.

This is the SURVEY.md §7.1.3/§7.1.4 design: every unbounded Go structure
(per-link ``container/list`` queues, ``activeSnapshots`` maps, recorded
message lists) becomes a fixed-shape HBM array with explicit capacities from
``SimConfig``, and every string-keyed map iteration becomes index order over
lexicographically-ranked dense indices.

Topology encoding (static per run, baked into the jitted kernel):
  - node index = rank of node id under lexicographic sort (so Go's sorted map
    iteration, reference sim.go:76 / common.go:135-146, is plain index order);
  - edges sorted by (src_rank, dest_rank) — per-source contiguous and
    dest-sorted, which makes both the tick's sorted-dest scan (sim.go:78) and
    the marker broadcast order (node.go:98) a linear walk;
  - ``edge_table[N, D]`` pads each source's outbound edges to the max
    out-degree D with -1.

State encoding (the jit carry; one instance — batching vmaps the whole tuple):
  - per-edge ring buffers replace the FIFO queues (queue.go:6-28):
    ``q_meta/q_data[E, C]`` + ``q_head[E]`` + ``q_len[E]``, append at
    (head+len) % C, pop at head — FIFO with head-of-line blocking intact.
    The per-slot payload is PACKED into two int32 planes: ``q_meta`` carries
    ``rtime << 1 | is_marker`` (pack_meta; rtime bounded by RTIME_PACK_LIMIT,
    guarded by ERR_VALUE_OVERFLOW at push) and ``q_data`` keeps the full-
    range token amount / snapshot id, so a head's eligibility+kind read is
    ONE [E] gather of q_meta (plus one of q_data for the payload) instead of
    the former three O(E·C) one-hot mask reductions over separate
    marker/rtime/data planes — HBM traffic per tick scales with edge count,
    not queue capacity (ops/tick.TickKernel queue_engine docstring);
  - snapshot slot s holds snapshot id s (ids are allocated sequentially from
    0, reference sim.go:107-108, so slot==id while id < S);
  - ``recording[S, E]`` replaces per-snapshot ``isLinkRecording`` maps
    (node.go:39); the ``incomingMessages`` lists (node.go:38) become ONE
    shared per-edge arrival log plus per-(snapshot, edge) window counters
    (see the "Recording as windows" paragraph below) — only token amounts
    are stored because only non-marker messages are ever recorded
    (node.go:174-185);
  - ``completed[S]`` replaces the per-snapshot WaitGroup (sim.go:17);
  - ``error`` is a sticky bitmask replacing Go's log.Fatal / unbounded growth
    (checked on the host after a run; SURVEY.md §5 "sanitizer" equivalent).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Tuple

import numpy as np

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import GlobalSnapshot, Message, MsgSnapshot
from chandy_lamport_tpu.utils.fixtures import TopologySpec

# error bitmask flags
ERR_QUEUE_OVERFLOW = 1
ERR_SNAPSHOT_OVERFLOW = 2
ERR_RECORD_OVERFLOW = 4
ERR_TOKEN_UNDERFLOW = 8
ERR_TICK_LIMIT = 16
ERR_VALUE_OVERFLOW = 32
ERR_CONSERVATION = 64
ERR_FAULT_UNRECOVERED = 128
ERR_SNAPSHOT_TIMEOUT = 256

# fault_counts[7] event-class indices (models/faults.py adversary): message
# drops, message duplicates, per-(edge, tick) extra-delay jitter stalls,
# node crash restarts, and the MARKER-plane classes (control-plane drops/
# duplicates/jitter stalls — the faults the snapshot supervisor exists to
# survive) — per-lane evidence that an injected fault class actually fired
# (tools/chaos_smoke.py asserts on these)
FC_DROP, FC_DUP, FC_JITTER, FC_CRASH = 0, 1, 2, 3
FC_MDROP, FC_MDUP, FC_MJITTER = 4, 5, 6
NUM_FAULT_CLASSES = 7

# largest token amount the sync scheduler's f32 incidence matmuls carry
# exactly; amounts at or beyond this fire ERR_VALUE_OVERFLOW instead of
# silently violating conservation (the exact scheduler is pure-integer and
# unaffected)
F32_EXACT_LIMIT = 1 << 24

# largest receive time the packed ring-slot plane can carry: q_meta stores
# rtime << 1 | is_marker in one int32, so rtime loses the sign bit and one
# payload bit. rtime = time + 1 + delay, so this binds total simulated time
# (~10^9 ticks — four orders of magnitude past the max_ticks drain budget);
# push sites fire ERR_VALUE_OVERFLOW at the bound instead of wrapping.
RTIME_PACK_LIMIT = 1 << 30


def pack_meta(rtime, marker):
    """One packed ring-slot metadata word: ``rtime << 1 | is_marker``.
    THE layout definition — every producer (scalar push, batched append,
    both runners) and consumer (head gathers, pops, metrics, decode) goes
    through pack_meta/meta_rtime/meta_marker so the encoding cannot drift.
    Works on numpy and jnp operands (and python-bool ``marker``)."""
    return rtime * 2 + marker


def meta_rtime(meta):
    """Delivery-eligible time of a packed slot word."""
    return meta >> 1


def meta_marker(meta):
    """Marker bit of a packed slot word (bool)."""
    return (meta & 1) == 1


def pack_marker_data(sid, epoch, max_snapshots: int):
    """Ring-mode marker payload word: ``epoch * S + sid`` — (sid, epoch)
    packed into the full-range ``q_data`` slot. Epoch 0 packs to the bare
    sid, so a supervisor that never fires (and every pre-supervisor
    golden) carries bit-identical ring content. THE payload definition —
    producers (_push_marker/_broadcast_markers) and the delivery-side
    decode (marker_data_sid/marker_data_epoch) share it so the encoding
    cannot drift."""
    return epoch * max_snapshots + sid


def marker_data_sid(data, max_snapshots: int):
    """Snapshot id of a packed marker payload word."""
    return data % max_snapshots


def marker_data_epoch(data, max_snapshots: int):
    """Epoch of a packed marker payload word (stale-arrival rejection:
    ops/tick.TickKernel._reject_stale compares it to ``snap_epoch``)."""
    return data // max_snapshots

class ErrorBit(NamedTuple):
    """One ERROR_REGISTRY row: the ERR_ constant's name, its bit, and the
    long diagnostic message ``decode_errors`` surfaces for it."""

    name: str
    bit: int
    message: str


# THE declarative error-bit registry: exactly one row per ERR_ constant
# above, binding name, bit and diagnostic text in one place. Everything
# that touches the error plane derives from it — the decode dicts below,
# NUM_ERROR_BITS (which sizes graphshard's _por bit-plane reduction), and
# the CLI/bench/soak output that prints the short names.
# tools/staticcheck's err-bit-registry rule enforces the invariants:
# distinct power-of-two bits with no gaps, row/constant agreement both
# ways, and NUM_ERROR_BITS = len(ERROR_REGISTRY) rather than a second
# literal that can drift.
ERROR_REGISTRY: Tuple[ErrorBit, ...] = (
    ErrorBit("ERR_QUEUE_OVERFLOW", ERR_QUEUE_OVERFLOW,
             "per-edge queue capacity exceeded (raise SimConfig.queue_capacity)"),
    ErrorBit("ERR_SNAPSHOT_OVERFLOW", ERR_SNAPSHOT_OVERFLOW,
             "concurrent snapshot slots exceeded (raise SimConfig.max_snapshots)"),
    ErrorBit("ERR_RECORD_OVERFLOW", ERR_RECORD_OVERFLOW,
             "recorded-message capacity exceeded (raise SimConfig.max_recorded)"),
    ErrorBit("ERR_TOKEN_UNDERFLOW", ERR_TOKEN_UNDERFLOW,
             "node sent more tokens than it had (reference log.Fatal, node.go:113-116)"),
    ErrorBit("ERR_TICK_LIMIT", ERR_TICK_LIMIT,
             "drain loop hit max_ticks (graph not strongly connected?)"),
    ErrorBit("ERR_VALUE_OVERFLOW", ERR_VALUE_OVERFLOW,
             "a value-range bound was exceeded: token amount "
             ">= 2^24 on the sync scheduler's f32 reductions "
             "(use scheduler='exact'), a recorded amount beyond "
             "the configured record_dtype range (use "
             "record_dtype='int32'), or an edge's token-push "
             "counter reached the FIFO merge-key bound "
             "(ops/tick.merge_key_limit — fewer tokens per edge "
             "or a smaller max_snapshots), or a receive time "
             "reached the packed ring-slot bound "
             "(state.RTIME_PACK_LIMIT, ~10^9 simulated ticks)"),
    ErrorBit("ERR_CONSERVATION", ERR_CONSERVATION,
             "in-run token-conservation check failed "
             "(node balances + in-flight != initial total; "
             "BatchedRunner check_every — the reference's "
             "checkTokens invariant, test_common.go:298-328, "
             "evaluated inside the jit run)"),
    ErrorBit("ERR_FAULT_UNRECOVERED", ERR_FAULT_UNRECOVERED,
             "a lossy node crash restarted with no completed "
             "Chandy-Lamport snapshot to restore from "
             "(models/faults.py crash_mode='lossy': the "
             "node's un-snapshotted balance is gone; "
             "quarantine the lane or schedule snapshots "
             "ahead of the crash windows)"),
    ErrorBit("ERR_SNAPSHOT_TIMEOUT", ERR_SNAPSHOT_TIMEOUT,
             "a snapshot attempt missed its "
             "SimConfig.snapshot_timeout deadline "
             "snapshot_retries times in a row and was marked "
             "failed by the supervisor (sustained marker loss "
             "beyond the retry budget — raise the timeout/"
             "retries, or lower the marker fault rates)"),
)

# number of live bits in the error plane — graphshard._por and the decode
# tables size themselves from this, so adding a registry row widens them all
NUM_ERROR_BITS = len(ERROR_REGISTRY)

ERROR_NAMES = {row.bit: row.message for row in ERROR_REGISTRY}

# short symbol-style names for user-facing output (CLI counters, bench JSON
# rows, soak logs) — the long ERROR_NAMES messages stay the diagnostic text
ERROR_BIT_NAMES = {row.bit: row.name for row in ERROR_REGISTRY}

# Checkpoint-format version history: one row per breaking layout change of
# the serialized state pytree (utils/checkpoint.py reads/writes the
# header). The row text says what changed and why an older file must error
# rather than load; versions are consecutive from 1 and the live version
# IS the last row, so the supported-range error message stays truthful
# (tools/staticcheck's ckpt-history rule enforces both, and its
# ckpt-version-literal rule keeps restated version literals out of the
# rest of the tree).
CHECKPOINT_FORMAT_HISTORY: Tuple[Tuple[int, str], ...] = (
    (1, "round-2 DenseState (q_seq/seq_next/m_seq/rec_len/rec_data leaves)"),
    (2, "window-log/merge-key state (tok_pushed/mk_cnt/m_key/rec_cnt/"
        "min_prot/log_amt/rec_start/rec_end) + three-word hash-delay state"),
    (3, "packed ring slots: q_marker/q_data/q_rtime became "
        "q_meta (rtime << 1 | is_marker) + full-range q_data"),
    (4, "fault-adversary leaves (fault_key/fault_skew/fault_counts) join "
        "the carry; writes became atomic (tmp-then-os.replace)"),
    (5, "snapshot-supervisor leaves (snap_epoch/snap_deadline/snap_retries/"
        "snap_initiator/snap_failed/snap_done_time + stale_markers); "
        "fault_counts widens to [7] with the marker-plane classes"),
    (6, "streaming-engine leaves (job_id/prog_cursor/admit_tick): per-lane "
        "job identity resumes mid-queue admission bit-exactly"),
    (7, "flight-recorder leaves (tr_meta/tr_data/tr_tick/tr_count/tr_on): "
        "the device trace ring and its dropped-events accounting survive "
        "a kill mid-run"),
    (8, "memo-plane leaf (sig: per-lane rolling state signature for "
        "transition fast-forwarding) + StreamState memo counters "
        "(cache_hits/coalesced_jobs/ff_skipped_ticks/shadow_checks): a "
        "kill mid-stream resumes the fast-forward memo and hit "
        "accounting bit-exactly"),
    (9, "serving-plane StreamState leaves (deadline_misses + per-tenant "
        "tenant_served/tenant_quota books): a killed serve run resumes "
        "its deadline-miss and fairness accounting bit-exactly"),
    (10, "prefix-fork StreamState counters (prefix_hits/forked_jobs/"
         "fork_depth_sum): a killed memo=\"prefix\" run resumes its "
         "speculative-fork accounting bit-exactly"),
)
CHECKPOINT_FORMAT_VERSION = CHECKPOINT_FORMAT_HISTORY[-1][0]


class DenseTopology:
    """Static graph arrays; node index = lexicographic rank of the node id."""

    def __init__(self, spec: TopologySpec):
        self.ids: List[str] = sorted(spec.node_ids)
        if len(set(self.ids)) != len(self.ids):
            raise ValueError("duplicate node ids in topology")
        self.index: Dict[str, int] = {nid: i for i, nid in enumerate(self.ids)}
        self.n = len(self.ids)
        tokens0 = dict(spec.nodes)
        self.tokens0 = np.array([tokens0[nid] for nid in self.ids], dtype=np.int32)

        for src, dest in spec.links:
            if src not in self.index:
                raise ValueError(f"node {src} does not exist")  # sim.go:49-54
            if dest not in self.index:
                raise ValueError(f"node {dest} does not exist")
        # self-links silently ignored (node.go:88-90); duplicate arcs collapse
        # (outboundLinks is a map, node.go:91-93)
        edges = sorted({(self.index[s], self.index[d])
                        for s, d in spec.links if s != d})
        self.e = len(edges)
        self.edge_src = np.array([s for s, _ in edges], dtype=np.int32)
        self.edge_dst = np.array([d for _, d in edges], dtype=np.int32)
        self.edge_index: Dict[Tuple[int, int], int] = {
            (s, d): i for i, (s, d) in enumerate(edges)}

        out_count = np.bincount(self.edge_src, minlength=self.n)
        self.in_degree = np.bincount(self.edge_dst, minlength=self.n).astype(np.int32)
        self.d = int(out_count.max()) if self.e else 1
        self.edge_table = np.full((self.n, self.d), -1, dtype=np.int32)
        fill = np.zeros(self.n, dtype=np.int64)
        for i, (s, _) in enumerate(edges):
            self.edge_table[s, fill[s]] = i  # dest-sorted within each row
            fill[s] += 1
        # dst-sorted edge permutation + per-node segment bounds (edges are
        # (src,dst) sorted; a stable sort by dst preserves src order within
        # each dst group). Shared by the decode-time sorted-src flattening
        # of recorded messages (SURVEY.md §2.2 R9) and TickKernel's
        # segment-sum reductions — one computation so the two cannot drift.
        self.by_dst = np.argsort(self.edge_dst, kind="stable")
        self.dst_bounds = np.concatenate(
            [[0], np.cumsum(np.bincount(self.edge_dst, minlength=self.n))])
        self.in_edges: List[np.ndarray] = np.split(
            self.by_dst, self.dst_bounds[1:-1])


class DenseState(NamedTuple):
    """The jit carry. Shapes: N nodes, E edges, C queue slots, S snapshot
    slots, L recorded-arrival log slots per edge.

    **Recording as windows.** HandleToken (node.go:174-185) appends the
    arriving amount to EVERY snapshot still recording the channel — i.e.
    all recording slots observe the same per-edge arrival stream, and each
    (s, e) records exactly the arrivals between its recording start
    (CreateLocalSnapshot) and stop (marker receipt): a contiguous window
    of that stream. So instead of S separate [M] buffers rewritten by a
    dense [S, M, E] masked select every tick (the former top line of the
    device profile at 5.2 ms/tick), recording is ONE ring log per edge —
    ``log_amt[L, E]`` appended at ``rec_cnt % L`` — plus window counters
    ``rec_start/rec_end[S, E]`` (in ``rec_cnt`` units); recorded amounts
    are read from the log window at decode time, so no per-slot amount
    state exists. Appends happen only while at least one slot records the edge,
    so L bounds the union of all windows; overwriting an undecoded
    window's data (``rec_cnt - min_prot > L``, where ``min_prot`` is the
    earliest window start on the edge) fires ERR_RECORD_OVERFLOW.

    **Packed ring slots.** Each ring slot is two int32 words: ``q_meta``
    = ``rtime << 1 | is_marker`` (pack_meta/meta_rtime/meta_marker) and
    ``q_data`` = the token amount or snapshot id. Packing the marker bit
    into the rtime word drops one whole [E, C] plane (the former bool
    ``q_marker``) and makes a head's eligibility+kind read a single
    gather. Bounds: rtime < RTIME_PACK_LIMIT (2^30 — four orders of
    magnitude past the max_ticks drain budget; push sites fire
    ERR_VALUE_OVERFLOW at the bound), while ``q_data`` keeps the full
    int32 range so token amounts/snapshot ids are never narrowed.

    Channel state exists in two representations, selected by the kernel's
    ``marker_mode`` (ops/tick.TickKernel):

    - **ring** (the exact scheduler): tokens AND markers share the ring
      buffers ``q_meta/q_data`` in push order, exactly like the reference's
      per-link FIFO (queue.go:6-28); ``m_*`` stay zero.
    - **split** (the sync scheduler): the ring carries only tokens, and
      markers — of which each (snapshot, edge) pair ever holds at most ONE
      (a node broadcasts an id only on first receipt, node.go:154-156) —
      live in the dense ``m_*[S, E]`` planes. Per-channel FIFO order
      between the two needs no per-slot sequence plane: tokens among
      themselves are ordered by the ring itself, so a marker's position
      is fully described by ``m_key = tokens_pushed_before * KEYMULT +
      marker_ord`` (``tok_pushed``/``mk_cnt`` counters at push time;
      KEYMULT = next power of two above max_snapshots, so keys are
      unique per edge and sorted by push order). The marker with the
      smallest key is the marker front; it is the CHANNEL front iff all
      ``tokens_pushed_before`` earlier tokens have been popped
      (``tok_pushed - q_len >= m_key // KEYMULT``); head-of-line
      blocking applies to that front. The win: ring CONTENT is written
      only when tokens are sent (per storm phase), not on every tick's
      marker traffic — the dense per-tick [E, C] rewrite was >50% of
      sync-tick time on TPU, and the former [E, C] sequence plane was
      another whole ring array of traffic.

    **Tiled-megatick block layout** (kernels/megatick.py, fused_tile).
    The [E, C] ring planes dominate the working set (C is sized to the
    workload's worst-case backlog), so the TILED fused kernel evicts
    exactly them from the VMEM carry: ``q_meta``/``q_data`` ride as HBM
    operands reshaped [RNB, REB, C] (plan_edge_blocks; ring slots
    contiguous last, so one block = one DMA descriptor), streamed
    through the same 2-slot double-buffered async-copy pipeline as the
    fault planes, once per kernel step. Inside the kernel the carry's
    q_meta/q_data slots are REPURPOSED: q_meta [2, A+1, E] holds the
    step's deferred-append buffers (rows :A: ring column + packed meta
    per append ordinal; A = megatick.ring_append_slots, the per-edge
    per-tick append census) and the pre-extracted ring-head row (row A:
    head_meta/head_data), q_data [A, E] the append payloads. Every
    other plane — all [N], [S, N], [S, E], [L, E] node/bookkeeping
    state — stays VMEM-resident; q_head/q_len remain live [E] vectors,
    so pop/route/eligibility math never touches the streamed blocks.
    Outside the kernel the DenseState shapes above are unchanged — the
    repurposing exists only between pallas_call entry and exit.
    """

    time: Any          # i32 []
    tokens: Any        # i32 [N]
    q_meta: Any        # i32 [E, C]   rtime << 1 | is_marker (pack_meta;
    #                    marker bit only ever set in ring mode)
    q_data: Any        # i32 [E, C]   token amount | snapshot id (ring mode)
    q_head: Any        # i32 [E]
    q_len: Any         # i32 [E]
    tok_pushed: Any    # i32 [E]      tokens ever pushed (split-mode order)
    mk_cnt: Any        # i32 [E]      markers ever pushed (split-mode order)
    m_pending: Any     # bool [S, E]  marker in flight (split mode)
    m_rtime: Any       # i32 [S, E]
    m_key: Any         # i32 [S, E]   FIFO merge key (docstring above)
    next_sid: Any      # i32 []
    started: Any       # bool [S]
    has_local: Any     # bool [S, N]
    frozen: Any        # i32 [S, N]   tokens frozen at snapshot creation
    rem: Any           # i32 [S, N]   links still being recorded
    done_local: Any    # bool [S, N]
    recording: Any     # bool [S, E]
    rec_cnt: Any       # i32 [E]     arrivals ever appended to the edge log
    min_prot: Any      # i32 [E]     earliest window start (BIG = none yet)
    log_amt: Any       # i32 [L, E]  per-edge ring log of recorded amounts
    rec_start: Any     # i32 [S, E]  rec_cnt at recording start
    rec_end: Any       # i32 [S, E]  rec_cnt at recording stop
    completed: Any     # i32 [S]      nodes finalized for this snapshot
    delay_state: Any   # sampler-specific pytree
    # fault-adversary state (models/faults.py; checkpoint format v4 leaves):
    # the adversary itself is stateless — a counter hash over (key, time,
    # index) — so its whole carry is the per-lane stream key plus the books
    # it keeps so conservation stays checkable under injected faults
    fault_key: Any     # u32 [] per-lane adversary stream key (0 = disarmed)
    fault_skew: Any    # i32 [] token delta the adversary injected
    #                    (duplicates - drops + crash-restore deltas);
    #                    conservation_delta subtracts it
    fault_counts: Any  # i32 [7] fault events by class (FC_DROP/FC_DUP/
    #                    FC_JITTER/FC_CRASH + marker-plane FC_MDROP/
    #                    FC_MDUP/FC_MJITTER)
    # snapshot-supervisor state (SimConfig.snapshot_timeout/_every;
    # checkpoint format v5 leaves). An ATTEMPT of snapshot slot s is
    # identified by (s, snap_epoch[s]): the supervisor's abort bumps the
    # epoch, so ring markers of a superseded attempt — which cannot be
    # plucked out of FIFO ring buffers — are rejected on delivery and
    # tallied in stale_markers instead of corrupting the fresh cut (the
    # split representation clears its pending planes in place, so
    # staleness is structurally impossible there).
    snap_epoch: Any     # i32 [S] current attempt epoch per slot
    snap_deadline: Any  # i32 [S] abort tick of the live attempt (0 = unarmed)
    snap_retries: Any   # i32 [S] re-initiations consumed
    snap_initiator: Any  # i32 [S] initiator node (re-initiation target; -1)
    snap_failed: Any    # bool [S] retries exhausted (ERR_SNAPSHOT_TIMEOUT);
    #                     a failed slot no longer gates the drain loop
    snap_done_time: Any  # i32 [S] tick the snapshot completed on all nodes
    #                     (-1 until then; recovery-line age metric)
    stale_markers: Any  # i32 [] superseded-epoch marker arrivals rejected
    # streaming-engine state (parallel/batch.run_stream; checkpoint format
    # v6 leaves). A batched run's lanes stop being one-shot: the streaming
    # driver retires a lane the moment its job is quiescent-and-complete
    # (or quarantined), harvests its summary into the results ring, and
    # scatters a FRESH job into the slot — so these three per-lane words
    # are the whole identity of "which job is this lane running, and how
    # far along is it". They ride the carry (not host bookkeeping) so a
    # checkpoint taken mid-queue resumes the admission state bit-exactly.
    job_id: Any        # i32 [] pool index of the job this lane is running
    #                    (-1 = idle slot: never admitted, or harvested and
    #                    the queue was empty). Non-streaming runs leave -1.
    prog_cursor: Any   # i32 [] next phase row in the pooled ScriptOps
    #                    table; past the job's end it encodes the retire
    #                    stages (end=drain, end+1=flush, end+2=done)
    admit_tick: Any    # i32 [] stream step at which the job was admitted
    #                    (occupancy/latency accounting; 0 for lane 0 jobs)
    # device flight-recorder ring (utils/tracing.py; checkpoint format v7
    # leaves). K = SimConfig.trace_capacity slots per lane of packed event
    # words written by .at[] scatters inside the tick kernels; K = 0 (the
    # default) makes these zero-size and the kernels contain zero trace
    # ops (the faults=None bit-identity contract).
    tr_meta: Any       # i32 [K] actor << 5 | kind (tracing.pack_event)
    tr_data: Any       # i32 [K] event payload (amount / sid / class / job)
    tr_tick: Any       # i32 [K] s.time at record
    tr_count: Any      # i32 [] events EVER recorded (write pos = count % K;
    #                    dropped-to-wrap = max(0, count - K))
    tr_on: Any         # i32 [] runtime arm flag (1 = record; armed-idle
    #                    profiling and pre-roll muting set 0)
    # memo-plane state (parallel/batch memo="full"; checkpoint format v8
    # leaf). A rolling uint32 fingerprint over the SEMANTIC per-lane
    # leaves (tokens, ring content/occupancy, snapshot planes, delay and
    # fault stream state, cursor scalars — everything except time,
    # admit_tick and the trace ring), recomputed inside the jitted
    # stream step. The host fast-forward memo watches it: when a
    # draining lane's signature recurs at the same program cursor, the
    # lane is provably cycling and whole multiples of the observed
    # period are credited to ``time`` without re-ticking. 0 whenever
    # memo != "full" (the leaf is carried untouched — zero ops).
    sig: Any           # u32 [] rolling per-lane state signature
    error: Any         # i32 [] sticky bitmask


def init_state(topo: DenseTopology, cfg: SimConfig, delay_state: Any,
               fault_key: int = 0) -> DenseState:
    """Fresh host-side (numpy) state; jnp conversion happens on first jit
    call. ``fault_key`` arms the fault adversary's per-lane stream
    (models/faults.py; 0 = disarmed)."""
    n, e = topo.n, topo.e
    c, s, m = cfg.queue_capacity, cfg.max_snapshots, cfg.max_recorded
    i32, b = np.int32, np.bool_
    return DenseState(
        time=np.int32(0),
        tokens=topo.tokens0.copy(),
        q_meta=np.zeros((e, c), i32),
        q_data=np.zeros((e, c), i32),
        q_head=np.zeros(e, i32),
        q_len=np.zeros(e, i32),
        tok_pushed=np.zeros(e, i32),
        mk_cnt=np.zeros(e, i32),
        m_pending=np.zeros((s, e), b),
        m_rtime=np.zeros((s, e), i32),
        m_key=np.zeros((s, e), i32),
        next_sid=np.int32(0),
        started=np.zeros(s, b),
        has_local=np.zeros((s, n), b),
        frozen=np.zeros((s, n), i32),
        rem=np.zeros((s, n), i32),
        done_local=np.zeros((s, n), b),
        recording=np.zeros((s, e), b),
        rec_cnt=np.zeros(e, i32),
        min_prot=np.full(e, np.iinfo(np.int32).max, i32),
        log_amt=np.zeros((m, e), np.dtype(cfg.record_dtype)),
        rec_start=np.zeros((s, e), np.dtype(cfg.window_dtype)),
        rec_end=np.zeros((s, e), np.dtype(cfg.window_dtype)),
        completed=np.zeros(s, i32),
        delay_state=delay_state,
        fault_key=np.uint32(fault_key),
        fault_skew=np.int32(0),
        fault_counts=np.zeros(NUM_FAULT_CLASSES, i32),
        snap_epoch=np.zeros(s, i32),
        snap_deadline=np.zeros(s, i32),
        snap_retries=np.zeros(s, i32),
        snap_initiator=np.full(s, -1, i32),
        snap_failed=np.zeros(s, b),
        snap_done_time=np.full(s, -1, i32),
        stale_markers=np.int32(0),
        job_id=np.int32(-1),
        prog_cursor=np.int32(0),
        admit_tick=np.int32(0),
        tr_meta=np.zeros(cfg.trace_capacity, i32),
        tr_data=np.zeros(cfg.trace_capacity, i32),
        tr_tick=np.zeros(cfg.trace_capacity, i32),
        tr_count=np.int32(0),
        tr_on=np.int32(1),
        sig=np.uint32(0),
        error=np.int32(0),
    )


def recorded_window(host: DenseState, sid: int, eidx: int) -> List[int]:
    """The amounts snapshot ``sid`` recorded on edge ``eidx``, in arrival
    order: the [rec_start, rec_end) window of the edge's ring log
    (rec_end falls back to the live rec_cnt for a still-recording channel
    of an incomplete snapshot). THE definition of window decode — used by
    decode_snapshot and every test oracle comparison.

    With SimConfig.window_dtype="uint16" the window planes hold the
    counters modulo 2^16: the length recovers as (end - start) mod 2^16
    (window lengths are bounded by L, guarded by ERR_RECORD_OVERFLOW via
    the still-i32 rec_cnt/min_prot), and log positions as
    (start + k) mod L — identical to the absolute-counter decode because
    L divides 2^16 (enforced by SimConfig)."""
    lcap = host.log_amt.shape[-2]
    start = int(host.rec_start[sid, eidx])
    end = (int(host.rec_cnt[eidx]) if host.recording[sid, eidx]
           else int(host.rec_end[sid, eidx]))
    if np.dtype(host.rec_start.dtype) != np.int32:   # modular window planes
        bits = 8 * np.dtype(host.rec_start.dtype).itemsize
        length = (end - start) & ((1 << bits) - 1)
        return [int(host.log_amt[(start + k) % lcap, eidx])
                for k in range(length)]
    return [int(host.log_amt[j % lcap, eidx]) for j in range(start, end)]


def decode_snapshot(topo: DenseTopology, host: DenseState, sid: int) -> GlobalSnapshot:
    """Array state -> GlobalSnapshot, the reference's CollectSnapshot
    (sim.go:134-173) as a pure gather: token map from the frozen balances,
    messages per node over its inbound edges in src-rank order, each edge's
    recordings in arrival order (golden-compatible, test_common.go:253-284)
    via ``recorded_window``."""
    token_map = {nid: int(host.frozen[sid, i]) for i, nid in enumerate(topo.ids)}
    messages: List[MsgSnapshot] = []
    for nidx, nid in enumerate(topo.ids):
        for eidx in topo.in_edges[nidx]:
            src = topo.ids[int(topo.edge_src[eidx])]
            for amt in recorded_window(host, sid, eidx):
                messages.append(MsgSnapshot(
                    src, nid, Message(is_marker=False, data=amt)))
    return GlobalSnapshot(sid, token_map, messages)


def decode_errors(error_bits: int) -> List[str]:
    return [msg for bit, msg in ERROR_NAMES.items() if error_bits & bit]


def decode_error_bits(mask: int) -> List[str]:
    """Short ERR_* names for a bitmask — THE spelling for every place a raw
    error int reaches user-facing output (cli counters, bench JSON rows,
    soak logs); pair with decode_errors for the long diagnostic text."""
    mask = int(mask)
    return [name for bit, name in ERROR_BIT_NAMES.items() if mask & bit]
