"""Independent pure-Python oracle for the SYNC scheduler semantics.

The dense `_sync_tick` (ops/tick.py) is heavily vectorized; this module is a
deliberately naive re-implementation of the same scheduler contract, written
with dicts and lists, used only by differential tests. The contract:

  1. Per tick, every source delivers the head of its first (dest-sorted)
     outbound channel whose head is eligible (receive_time <= time); at most
     one delivery per source; per-channel FIFO and head-of-line blocking as
     in the reference (sim.go:71-95, queue.go).
  2. Within a tick, all token deliveries apply before all marker deliveries
     ("tokens-then-markers"): credits land first; a token is recorded into
     every snapshot slot that was recording its channel at tick START.
  3. Marker deliveries are processed grouped by ascending snapshot id. A
     node's first marker(s) for an id create its local snapshot excluding
     ALL of this tick's marker channels for that id (k simultaneous markers
     -> links_remaining = in_degree - k), then the node broadcasts markers
     on its outbound edges in edge order; queued broadcasts for multiple ids
     on one edge stack in ascending id order. Later markers decrement
     links_remaining. Finalization fires as soon as links_remaining == 0.
  4. Snapshot initiation (between ticks) allocates ids in node-index order
     and records ALL inbound channels (sim.go:105-123 semantics).

Delay model: any host-side DelayModel; differential tests use FixedDelay so
the oracle and the dense kernel see identical receive times (counter-based
streams cannot be replicated host-side).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Tuple

from chandy_lamport_tpu.core.state import DenseTopology
from chandy_lamport_tpu.models.delay import DelayModel


class SyncOracle:
    """Sequential reference implementation of the sync scheduler."""

    def __init__(self, topo: DenseTopology, delay: DelayModel):
        self.topo = topo
        self.delay = delay
        self.time = 0
        self.tokens = [int(t) for t in topo.tokens0]
        # per edge: FIFO of (is_marker, data, receive_time)
        self.queues: List[Deque[Tuple[bool, int, int]]] = [
            deque() for _ in range(topo.e)]
        self.next_sid = 0
        # per sid: per-node dicts
        self.frozen: Dict[int, Dict[int, int]] = {}
        self.rem: Dict[int, Dict[int, int]] = {}
        self.recording: Dict[int, set] = {}       # sid -> set of edge ids
        self.recorded: Dict[int, Dict[int, List[int]]] = {}  # sid -> edge -> amounts
        self.done: Dict[int, set] = {}
        self.completed: Dict[int, int] = {}

    # -- injection ---------------------------------------------------------

    def bulk_send(self, amounts: List[int]) -> None:
        """amounts[e] > 0 enqueues one token message on edge e; every edge
        draws a receive time in edge order (matching draw_many's
        one-draw-per-edge fast-path semantics under a fixed delay)."""
        for e in range(self.topo.e):
            rt = self.delay.receive_time(self.time)
            if amounts[e] > 0:
                src = int(self.topo.edge_src[e])
                self.tokens[src] -= amounts[e]
                assert self.tokens[src] >= 0, "underflow in oracle workload"
                self.queues[e].append((False, int(amounts[e]), rt))

    def start_snapshots(self, nodes: List[int]) -> List[int]:
        """Initiate at the given nodes; ids allocated in node-index order."""
        sids = []
        for node in sorted(set(nodes)):
            sid = self.next_sid
            self.next_sid += 1
            sids.append(sid)
            self.frozen[sid] = {node: self.tokens[node]}
            self.rem[sid] = {node: int(self.topo.in_degree[node])}
            self.recording[sid] = {e for e in range(self.topo.e)
                                   if int(self.topo.edge_dst[e]) == node}
            self.recorded[sid] = {}
            self.done[sid] = set()
            self.completed[sid] = 0
            self._broadcast({node: [sid]})
        return sids

    def _broadcast(self, sids_by_node: Dict[int, List[int]]) -> None:
        """Push marker(sid) on every outbound edge of each node; multiple
        sids on one edge stack in ascending sid order; one delay draw per
        (sid-slot, edge) in sid-major order (draw_many((S, E)) layout)."""
        for sid in sorted({s for sids in sids_by_node.values() for s in sids}):
            for e in range(self.topo.e):
                rt = self.delay.receive_time(self.time)
                src = int(self.topo.edge_src[e])
                if src in sids_by_node and sid in sids_by_node[src]:
                    self.queues[e].append((True, sid, rt))

    # -- the tick ----------------------------------------------------------

    def tick(self) -> None:
        self.time += 1
        # 1. choose deliveries: first eligible head per source in edge order
        delivered: List[Tuple[int, bool, int]] = []   # (edge, is_marker, data)
        chosen_src = set()
        for e in range(self.topo.e):                  # edges sorted (src, dst)
            src = int(self.topo.edge_src[e])
            if src in chosen_src:
                continue
            if self.queues[e] and self.queues[e][0][2] <= self.time:
                is_marker, data, _ = self.queues[e].popleft()
                delivered.append((e, is_marker, data))
                chosen_src.add(src)
        # 2. tokens first: credit + record against tick-start recording sets
        rec_at_start = {sid: set(edges) for sid, edges in self.recording.items()}
        for e, is_marker, data in delivered:
            if is_marker:
                continue
            dst = int(self.topo.edge_dst[e])
            self.tokens[dst] += data
            for sid, edges in rec_at_start.items():
                if e in edges:
                    self.recorded[sid].setdefault(e, []).append(data)
        # 3. markers grouped by ascending sid
        marker_edges: Dict[int, List[int]] = {}
        for e, is_marker, data in delivered:
            if is_marker:
                marker_edges.setdefault(data, []).append(e)
        to_broadcast: Dict[int, List[int]] = {}
        for sid in sorted(marker_edges):
            arrivals: Dict[int, List[int]] = {}
            for e in marker_edges[sid]:
                arrivals.setdefault(int(self.topo.edge_dst[e]), []).append(e)
            for node, edges in arrivals.items():
                self.recording[sid] -= set(edges)
                if node not in self.frozen[sid]:
                    # create: freeze post-credit balance, record all other
                    # inbound channels, schedule re-broadcast
                    self.frozen[sid][node] = self.tokens[node]
                    self.rem[sid][node] = int(self.topo.in_degree[node]) - len(edges)
                    for e2 in range(self.topo.e):
                        if (int(self.topo.edge_dst[e2]) == node
                                and e2 not in edges):
                            self.recording[sid].add(e2)
                    to_broadcast.setdefault(node, []).append(sid)
                else:
                    self.rem[sid][node] -= len(edges)
        self._broadcast(to_broadcast)
        # 4. finalize
        for sid in list(self.frozen):
            for node, r in self.rem[sid].items():
                if r == 0 and node not in self.done[sid]:
                    self.done[sid].add(node)
                    self.completed[sid] += 1

    # -- drain -------------------------------------------------------------

    def drain_and_flush(self, max_ticks: int = 100_000) -> None:
        guard = 0
        while any(c < self.topo.n for c in self.completed.values()):
            self.tick()
            guard += 1
            if guard > max_ticks:
                raise RuntimeError("oracle drain did not converge")
        for _ in range(self.delay.max_delay + 1):
            self.tick()
