"""Pallas tick kernels: fused ring-queue ops + edge->node reductions.

The tick inner loop's hot ops — the ring-queue head-read -> route -> pop ->
append chain and the CSR-ordered edge->node segment reductions — as
hand-written Pallas kernels, selectable per kernel via
``SimConfig.kernel_engine`` (plumbed through TickKernel / DenseSim /
BatchedRunner / GraphShardedRunner / bench / CLI, the queue_engine /
comm_engine knob pattern):

  "xla"    — the stock-XLA formulations (ops/tick.py), unchanged;
  "pallas" — the kernels in this package;
  "auto"   — "pallas" only where COMPILED Pallas is supported (TPU),
             "xla" everywhere else with a logged reason
             (resolve_kernel_engine below).

Off-TPU the kernels still run — under ``interpret=True`` emulation — so
tier-1 CI exercises the exact kernel bodies on the CPU mesh and the
bit-identity bar (tests/test_pallas_kernels.py) is enforced everywhere,
while ``auto`` never selects the (slow) emulation for production runs.

Block shapes and the VMEM budget
--------------------------------
Every kernel here is a single-program ``pl.pallas_call`` whose operands are
whole-array VMEM blocks (no grid): the packed ring planes ``q_meta`` /
``q_data`` are ``[E, C]`` i32, everything else is ``[E]`` / ``[N]`` vectors,
so one fused queue step touches ``4*E*C + ~6*E`` 4-byte words of VMEM —
about 0.8 MB at the bench shape (E~2k, C=24) and ~6.5 MB at the 8k-node
ladder config (E~16k), inside the ~16 MB/core budget
(``pltpu.CompilerParams(vmem_limit_bytes=...)`` is left at its default).
The win over the stock-XLA path is not the arithmetic — it is that the
head gather, eligibility test, per-source prefix-count selection and pop
read the ``[E, C]`` planes ONCE from HBM and keep every intermediate
(one-hot hit masks, cumsums, selection masks) VMEM-resident, where XLA
materializes them as separate HBM-level tensors between fusions. Shapes
past ``E*C ~ 4M`` words need a real edge-blocked grid (the CSR layout's
``dst_lo/dst_hi`` bounds are the natural block boundaries) — future work,
called out here so ``auto`` can gate on footprint when it lands.

The one-kernel megatick (``megatick.py``, SimConfig.fused_tick) extends
the same argument from one queue step to the WHOLE K-tick loop: the
entire DenseState rides as VMEM operands of a single kernel whose body
is a ``lax.scan`` of K full ticks, so state crosses HBM twice per K
ticks instead of per stage per tick. Its budget line item on top of the
state bytes is the streamed fault-plane scratch: ``2 slots · 8 rows ·
NB·EB · 4 B`` of double-buffered VMEM plus a K-resident ``[K, 2, N]``
node plane — ``megatick.plan_edge_blocks`` picks the edge-block width
EB (default 512 -> 16 KB per DMA) and ``megatick.fused_vmem_bytes``
totals the working set against ``megatick.FUSED_VMEM_BUDGET`` (12 MB of
the ~16, the rest headroom for the tick body's intermediates); the
``fused_tick='auto'`` gate (``megatick.resolve_fused_tick``) splits
whenever that total doesn't fit. At the bench shape (E~2k, C=24, K=8)
the carry is the ~1 MB state and the streaming scratch ~0.26 MB —
comfortably resident; the 8k ladder (E~16k) fits until C pushes the
``[E, C]`` rings past the budget, at which point auto falls back loudly.

Inside the kernel bodies only TPU-lowerable jnp ops are used for the
``[E, C]`` work (``broadcasted_iota`` one-hot selects, ``cumsum``,
``where`` — no scatter); the segment kernels use the same exclusive
prefix-sum + bounds-take formulation as the XLA segsum path, so
bit-identity with the XLA engine is by construction, not by accident.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

# bound from the declarative knob table so the spelling set lives in one
# place (config.ENGINE_KNOBS); kept exported under the historical name
from chandy_lamport_tpu.config import ENGINE_KNOBS as _ENGINE_KNOBS

KERNEL_ENGINES = _ENGINE_KNOBS["kernel_engine"]


def resolve_kernel_engine(engine: str, backend: str | None = None) -> str:
    """Resolve the tick-kernel engine knob (SimConfig.kernel_engine):
    "auto" picks "pallas" only where compiled Pallas is supported — TPU —
    and falls back to "xla" with a logged reason everywhere else (Pallas
    runs off-TPU only as interpret-mode emulation, orders of magnitude
    slower than XLA's native lowering, so auto must never select it for a
    production run; an explicit "pallas" still gets the emulated kernels,
    which is how CI pins bit-identity from the CPU mesh). ``backend``
    defaults to the live jax backend; parameterized so CI can pin the TPU
    decision from the CPU mesh (the resolve_queue_engine pattern)."""
    if engine not in KERNEL_ENGINES:
        raise ValueError(f"unknown kernel_engine {engine!r}")
    if engine != "auto":
        return engine
    if backend is None:
        import jax

        backend = jax.default_backend()
    if backend == "tpu":
        return "pallas"
    logger.info(
        "kernel_engine='auto' resolved to 'xla': backend %r has no compiled "
        "Pallas support (Pallas would run as interpret-mode emulation; pass "
        "kernel_engine='pallas' explicitly to exercise the kernels anyway)",
        backend)
    return "xla"


def pallas_interpret(backend: str | None = None) -> bool:
    """Whether Pallas kernels must run under ``interpret=True`` here:
    everywhere except TPU (the only backend with compiled Pallas support
    in this image). One definition, so every caller builds kernels for
    the same regime the resolver assumed."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    return backend != "tpu"


from chandy_lamport_tpu.kernels import megatick, queue, segment  # noqa: E402
from chandy_lamport_tpu.kernels.megatick import (  # noqa: E402
    resolve_fused_tick,
)

__all__ = [
    "KERNEL_ENGINES",
    "megatick",
    "pallas_interpret",
    "queue",
    "resolve_fused_tick",
    "resolve_kernel_engine",
    "segment",
]
