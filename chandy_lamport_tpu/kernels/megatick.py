"""One-kernel megatick: the entire exact tick body, K ticks per kernel.

PR 9 kernelized the ring-queue step and the segment reductions as
*separate* ``pl.pallas_call``s, so a K-tick megatick still round-trips
every working plane through HBM between pop -> route -> reduce -> spread
-> append, K times. This module fuses the WHOLE tick body — head gather,
eligibility, per-source first-eligible select, pop, routing, CSR segment
reduce, node update/spread, ring append, error-bit fold — into one
kernel, then ``lax.scan``s K megaticks INSIDE it, so queue/node state
never leaves VMEM between ticks: one HBM read of the carry at kernel
entry, one write at exit, regardless of K.

The tick body itself is not re-derived here: ``fused_scan`` traces the
caller's ``step_fn`` (ops/tick.TickKernel binds its stock-XLA cascade /
wave tick, the formulation every engine arm is differentially pinned
against) inside the kernel, so bit-identity with the split-kernel and
XLA paths is by construction — the same jaxpr, executed VMEM-resident.

Fault masks as input planes
---------------------------
The PR 9 split had to hop out of the kernel for the fault gates. Here
the adversary moves in-kernel as masked lanes driven by PRECOMPUTED
per-(tick, edge) fault planes: the stateless (fault_key, time, index)
hash makes every mask for times t+1..t+K computable before the kernel
launches (TickKernel._fault_planes), and the in-kernel scan consumes
row j exactly when the j-th tick really executes — the quiescence /
drain / quarantine gates are monotone, so ticks always run on a step
prefix and the time<->row correspondence cannot slip. Semantics are
byte-for-byte the hash-at-tick-time path's (tests/test_megatick_fused).

Edge blocks, double buffering and the VMEM budget
-------------------------------------------------
The per-(tick, edge) planes are the one input that scales with K·E, so
they stay in HBM (``pltpu.ANY``) and stream through a double-buffered
async-copy pipeline over EDGE BLOCKS: the [K, R, E] plane is padded to
NB·EB edges and laid out [K, NB, R, EB]; while the scan executes tick j
out of VMEM slot ``j % 2``, the NB block copies for tick j+1 are already
in flight into slot ``(j+1) % 2`` (one DMA semaphore per (slot, block)).
The block size EB is chosen against the VMEM budget documented in
``kernels/__init__.py``: carry ≈ state bytes (q planes 8·E·C B dominate,
plus the [L, E] log and [S, E] window planes), streaming scratch adds
``2 · R · NB · EB · 4`` B, and the whole working set must clear
``FUSED_VMEM_BUDGET`` (12 MB of the ~16 MB/core, the rest left for the
tick body's intermediates) — ``plan_edge_blocks`` / ``fused_vmem_bytes``
below are that arithmetic, and ``resolve_fused_tick`` is the single
gate deciding fused vs split (the ``fused_tick`` ENGINE_KNOBS row).

Tiled state: ring planes past the VMEM ceiling
----------------------------------------------
The carry's dominant planes are the [E, C] ring queues (``q_meta`` /
``q_data`` — 8·E·C bytes of the working set), and a graph whose rings
alone overflow ``FUSED_VMEM_BUDGET`` used to silently fall back to the
split path. The ``fused_tile`` knob (``resolve_fused_tile``) moves the
rings OUT of the VMEM carry: they stay in HBM (``pltpu.ANY`` operands)
and stream through the same double-buffered async-copy pipeline as the
fault planes, in ``plan_edge_blocks`` edge blocks of [EB, C] — while
every [N]-node and [E]-vector plane stays VMEM-resident. Per step the
kernel needs the rings for exactly two things, and both tile:

  heads    ``_head_fields`` reads slot ``q_head[e]`` of every edge once
           per tick (before any in-tick write can land on a head slot —
           supervisor re-initiations carry receive times > time, so a
           pre-extracted head is never selected stale). The head gather
           for step j+1 rides the SAME block pass as step j's commit,
           so rings are read once, not twice, per step; step 0's heads
           are gathered outside the kernel (``ring_heads``).
  appends  ``_append_rows`` writes at most ``ring_append_slots`` rows
           per edge per tick (bounded by the marker-broadcast /
           supervisor / fault-dup census below). The tick body defers
           them into dense [A, E] pos/meta/data planes riding the carry
           in place of the ring planes (``_append_rows_deferred``), and
           ``RingStream.commit_and_heads`` applies them block-by-block
           in ordinal order — a read-modify-write pass whose write-back
           DMA overlaps the next block's load. Ordinal order preserves
           the eager path's write order (overflow wraps clobber
           identically), and q_len/q_head/error stay eagerly updated on
           the resident [E] vectors, so the tick is bit-identical by
           construction, exactly like the resident path.

A quiet/condition-false step commits an all-inactive buffer — the block
pass rewrites identical bytes — so the DMA schedule is unconditional
and uniform across the scan (no copies inside ``lax.cond`` branches).
Tiling also lifts the old supervisor/recorder refusals: both are masked
lane ops over resident planes and simply trace with the stock tick.

Off-TPU everything runs under ``interpret=True`` like the PR 9 kernels,
so CPU tier-1 exercises the fused body, the DMA pipeline included.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_i32 = jnp.int32

# The fused working set must clear this: ~16 MB/core VMEM minus ~4 MB
# left for the tick body's intermediates (one-hot masks, cumsums — the
# same headroom argument as the split kernels' budget note).
FUSED_VMEM_BUDGET = 12 * 1024 * 1024
# Default edge-block width for the streamed fault planes: 512 edges x
# R=8 rows x 4 B = 16 KB per block copy — large enough to amortize DMA
# issue overhead, small enough that NB stays >= 2 on every graph the
# test tree runs (so the pipeline's block loop is genuinely exercised).
DEFAULT_BLOCK_EDGES = 512


def plan_edge_blocks(e: int, block_edges: int = 0) -> tuple[int, int]:
    """(NB, EB) for streaming an [.., E]-last plane in EB-edge blocks:
    EB = ``block_edges`` (0 -> DEFAULT_BLOCK_EDGES, clamped to E so tiny
    graphs get one exact block), NB = ceil(E / EB). The plane is padded
    to NB·EB edges; callers slice the pad back off after each copy."""
    if e <= 0:
        raise ValueError(f"need at least one edge, got E={e}")
    eb = int(block_edges) if block_edges else DEFAULT_BLOCK_EDGES
    eb = max(1, min(eb, e))
    nb = -(-e // eb)
    return nb, eb


def pytree_bytes(tree) -> int:
    """Total array bytes of a pytree — the carry side of the VMEM math."""
    return sum(x.size * jnp.dtype(x.dtype).itemsize
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "dtype"))


def ring_append_slots(*, max_snapshots: int, max_in_degree: int,
                      timeout_armed: bool, every_armed: bool,
                      faulted: bool) -> int:
    """A — the per-edge, per-tick ring-append bound the tiled kernel's
    deferred-append planes are sized to. Census of every in-tick
    ``_append_rows`` caller (all appends on edge e are broadcasts by
    src(e), plus the fault dup):

      marker fold / waves   a node first-receives at most min(S, in_deg)
                            distinct snapshot ids in one tick (one
                            delivery per in-edge per tick), each
                            broadcasting once on every out-edge;
      supervisor retries    _sup_reinitiate_ring re-broadcasts per
                            retried slot — all S slots can retry in one
                            tick with one initiator (+S, timeout armed);
      snapshot daemon       one _inject_snapshot per tick (+1, armed);
      fault duplication     the final dup re-append delivers at most one
                            duplicate per edge per tick (+1, faulted).

    Undersizing would drop appends silently, so _append_rows_deferred
    also flags ERR_QUEUE_OVERFLOW if a cursor ever passes A — a bound
    violation is loud, never corrupt."""
    a = min(int(max_snapshots), max(int(max_in_degree), 1))
    if timeout_armed:
        a += int(max_snapshots)
    if every_armed:
        a += 1
    if faulted:
        a += 1
    return max(a, 1)


def fused_vmem_bytes(state_bytes: int, e: int, n: int, length: int,
                     faulted: bool, block_edges: int = 0, *,
                     tiled: bool = False, queue_capacity: int = 0,
                     append_slots: int = 0) -> int:
    """The fused kernel's resident working set: the carry (state + loop
    scalars) + the double-buffered edge-plane scratch (2 slots x 8 rows
    x NB·EB i32) + the K-resident node plane (length x 2 x N i32).
    Fault-free kernels stream nothing — carry only.

    ``tiled=True`` is the ring-streaming layout (module docstring): the
    [E, C] ring planes leave the carry for HBM, replaced by the [A, E]
    deferred-append pos/meta/data planes (``append_slots`` = A,
    ring_append_slots) and the two [E] head vectors, plus the
    double-buffered 2-slot x 2-plane x [EB, C] ring scratch."""
    total = state_bytes + 64        # + packed loop scalars
    if tiled:
        if queue_capacity <= 0:
            raise ValueError("tiled working set needs queue_capacity")
        nb, eb = plan_edge_blocks(e, block_edges)
        total -= 2 * e * queue_capacity * 4      # rings leave the carry
        total += 2 * 2 * eb * queue_capacity * 4  # ring DMA scratch
        total += 3 * max(int(append_slots), 1) * e * 4  # deferred appends
        total += 2 * e * 4                        # head_meta/head_data
    if faulted:
        nb, eb = plan_edge_blocks(e, block_edges)
        total += 2 * 8 * nb * eb * 4
        total += length * 2 * n * 4
    return total


def resolve_fused_tick(fused_tick: str, *, kernel_engine: str,
                       megatick: int, marker_mode: str, exact_impl: str,
                       supervised: bool, traced: bool,
                       vmem_bytes: int,
                       tiled_vmem_bytes: int | None = None,
                       budget: int = FUSED_VMEM_BUDGET
                       ) -> tuple[str, str]:
    """Resolve the ``fused_tick`` knob (config.ENGINE_KNOBS) to a
    concrete ("on"|"off", reason). "auto" turns on exactly when the
    one-kernel megatick applies:

      * ``kernel_engine == "pallas"`` and ``megatick > 1`` — the fusion
        IS the K-tick scan; K=1 has nothing to keep resident;
      * ring markers + cascade/wave — the vectorized exact formulations
        (the fold is the reference-literal specification form, and the
        split representation never runs the exact tick);
      * the working set fits the VMEM budget (fused_vmem_bytes) — either
        resident outright, or via the tiled ring-streaming layout:
        ``tiled_vmem_bytes`` is the fused_vmem_bytes(tiled=True) figure
        when ring streaming is available (None when ``fused_tile`` is
        forced "off"), and an over-budget resident set is only a refusal
        when the tiled set is over (or unavailable) too.

    The historical supervisor/recorder refusals are LIFTED: both are
    masked lane ops over VMEM-resident planes (the supervisor's deadline
    arithmetic on the [S] window vectors, the recorder's event-ring
    scatters on the [L, E] log) and trace inside the kernel with the
    stock tick — the ``supervised`` / ``traced`` parameters remain in
    the signature as documentation of that audit, not as gates.

    "on" RAISES naming ALL unmet requirements at once instead of
    silently splitting — the explicit spelling is the CI/profiling
    override, must never lie about what ran, and must not make users
    discover requirements one error at a time. "off" always splits;
    "auto" reports the first unmet requirement as its reason."""
    if fused_tick not in ("auto", "on", "off"):
        raise ValueError(f"unknown fused_tick {fused_tick!r}")
    if fused_tick == "off":
        return "off", "fused_tick='off'"
    del supervised, traced  # lifted refusals — see docstring
    unmet = []
    if kernel_engine != "pallas":
        unmet.append(f"kernel_engine={kernel_engine!r} (the fused "
                     f"megatick is a Pallas kernel)")
    if megatick <= 1:
        unmet.append(f"megatick={megatick} (nothing to fuse below K=2)")
    if marker_mode != "ring":
        unmet.append(f"marker_mode={marker_mode!r} (the exact tick only "
                     f"runs on the ring representation)")
    if exact_impl not in ("cascade", "wave"):
        unmet.append(f"exact_impl={exact_impl!r} (the fold is the "
                     f"reference-literal specification form)")
    if vmem_bytes > budget:
        if tiled_vmem_bytes is None:
            unmet.append(f"working set {vmem_bytes} B exceeds the "
                         f"{budget} B VMEM budget and fused_tile='off' "
                         f"forbids streaming the ring planes")
        elif tiled_vmem_bytes > budget:
            unmet.append(f"working set {vmem_bytes} B exceeds the "
                         f"{budget} B VMEM budget even with the ring "
                         f"planes streamed ({tiled_vmem_bytes} B tiled)")
    if not unmet:
        return "on", "fused megatick engaged"
    if fused_tick == "on":
        raise ValueError(
            f"fused_tick='on' impossible — {len(unmet)} unmet "
            f"requirement(s): " + "; ".join(unmet))
    return "off", unmet[0]


def resolve_fused_tile(fused_tile: str, *, fused: str, vmem_bytes: int,
                       tiled_vmem_bytes: int,
                       budget: int = FUSED_VMEM_BUDGET) -> tuple[str, str]:
    """Resolve the ``fused_tile`` knob (config.ENGINE_KNOBS) to a
    concrete ("on"|"off", reason) AFTER resolve_fused_tick: tiling is a
    layout of the fused kernel, so it is "off" whenever the fused
    megatick itself is. "auto" tiles exactly when the resident working
    set overflows the budget (the shapes that used to silently refuse);
    a set that fits stays fully VMEM-resident — tiling it would add ring
    DMA traffic for nothing. Explicit "on"/"off" force the layout either
    way (the differential tests pin tiled==resident bit-identity on
    small shapes that way)."""
    if fused_tile not in ("auto", "on", "off"):
        raise ValueError(f"unknown fused_tile {fused_tile!r}")
    if fused != "on":
        return "off", "fused megatick off — no kernel to tile"
    if fused_tile == "off":
        return "off", "fused_tile='off'"
    if fused_tile == "on":
        return "on", "fused_tile='on'"
    if vmem_bytes > budget:
        return "on", (f"resident working set {vmem_bytes} B exceeds the "
                      f"{budget} B VMEM budget — ring planes stream "
                      f"({tiled_vmem_bytes} B resident tiled)")
    return "off", (f"resident working set {vmem_bytes} B fits the "
                   f"{budget} B VMEM budget — rings stay resident")


def _pack_edge_plane(plane, nb: int, eb: int):
    """[K, R, E] -> [K, NB, R, EB] (zero-padded on E): the DMA layout —
    one copy descriptor per (tick, block), blocks contiguous last."""
    k, r, e = plane.shape
    pad = nb * eb - e
    if pad:
        plane = jnp.pad(plane, ((0, 0), (0, 0), (0, pad)))
    return jnp.transpose(plane.reshape(k, r, nb, eb), (0, 2, 1, 3))


def _pack_ring_plane(plane, rnb: int, reb: int):
    """[E, C] -> [RNB, REB, C] (zero-padded on E): the tiled ring DMA
    layout — one block copy descriptor per edge block, ring slots
    contiguous last. Pads are never written (deferred-append pos rows
    are -1 there) so they stay zero across the whole scan."""
    e, c = plane.shape
    pad = rnb * reb - e
    if pad:
        plane = jnp.pad(plane, ((0, pad), (0, 0)))
    return plane.reshape(rnb, reb, c)


def ring_heads(q_meta, q_data, q_head):
    """Outer-trace head gather: slot ``q_head[e]`` of each [E, C] ring
    plane, via the same one-hot integer contraction the in-kernel block
    pass uses, so step 0's pre-extracted heads are exact matches of the
    heads steps 1..K-1 gather inside the kernel (integers: the one-hot
    sum reproduces the slot value bit-for-bit)."""
    c = q_meta.shape[-1]
    hit = q_head[:, None] == jnp.arange(c, dtype=_i32)[None, :]
    head_meta = jnp.sum(jnp.where(hit, q_meta, 0), axis=-1, dtype=_i32)
    head_data = jnp.sum(jnp.where(hit, q_data, 0), axis=-1, dtype=_i32)
    return head_meta, head_data


class RingStream:
    """The tiled ring-plane streamer living inside the fused kernel.

    Owns the [RNB, REB, C] HBM output refs of ``q_meta``/``q_data`` (the
    kernel copies the input rings into them once at entry, then the scan
    mutates them in place through this class), the double-buffered
    2-slot x 2-plane [REB, C] VMEM scratch, and one DMA semaphore pair
    per (slot, plane) for loads and for write-backs.

    ``commit_and_heads`` is the once-per-step block pass (module
    docstring): per edge block it folds the step's [A, E] deferred
    appends into the block in ordinal order, gathers the NEXT step's
    ring heads from the modified block (reading rings exactly once per
    step), and writes the block back — with the write-back DMA of block
    b-1 overlapping block b+1's load. The schedule is hazard-checked:
    block b computes out of slot b%2; before loading block b+1 into slot
    (b+1)%2 the pass waits block b-1's write-back (same slot), so a slot
    is never reloaded while its previous write-back is still draining;
    the final drain waits the last two write-backs, so the next step's
    loads always read fully-landed blocks.
    """

    def __init__(self, qm_ref, qd_ref, scratch, lsem, wsem, *, e: int,
                 rnb: int, reb: int, c: int):
        self.qm_ref = qm_ref
        self.qd_ref = qd_ref
        self.scratch = scratch
        self.lsem = lsem
        self.wsem = wsem
        self.e = e
        self.rnb = rnb
        self.reb = reb
        self.c = c

    def _load(self, b: int, slot: int):
        return [pltpu.make_async_copy(
            ref.at[b], self.scratch.at[slot, p], self.lsem.at[slot, p])
            for p, ref in enumerate((self.qm_ref, self.qd_ref))]

    def _store(self, b: int, slot: int):
        return [pltpu.make_async_copy(
            self.scratch.at[slot, p], ref.at[b], self.wsem.at[slot, p])
            for p, ref in enumerate((self.qm_ref, self.qd_ref))]

    def _pad_rows(self, v, fill: int):
        """[.., E] -> [.., RNB, REB]: the per-block view of an edge
        vector/plane (pad rows get ``fill``; -1 for append positions so
        pads never match a ring column, 0 for everything else)."""
        pad = self.rnb * self.reb - self.e
        if pad:
            widths = [(0, 0)] * (v.ndim - 1) + [(0, pad)]
            v = jnp.pad(v, widths, constant_values=fill)
        return v.reshape(v.shape[:-1] + (self.rnb, self.reb))

    def commit_and_heads(self, pos_buf, meta_buf, data_buf, q_head):
        """Apply one step's deferred appends ([A, E] pos/meta/data
        planes, pos < 0 = inactive slot) and return the next step's
        (head_meta, head_data) [E] vectors, in one block pass."""
        a = pos_buf.shape[0]
        pos = self._pad_rows(jnp.asarray(pos_buf, _i32), -1)
        meta = self._pad_rows(jnp.asarray(meta_buf, _i32), 0)
        data = self._pad_rows(jnp.asarray(data_buf, _i32), 0)
        qh = self._pad_rows(jnp.asarray(q_head, _i32), 0)
        col = lax.broadcasted_iota(_i32, (self.reb, self.c), 1)
        hm_parts, hd_parts = [], []
        for cp in self._load(0, 0):
            cp.start()
        for b in range(self.rnb):
            slot = b % 2
            for cp in self._load(b, slot):
                cp.wait()
            qm_blk = self.scratch[slot, 0]
            qd_blk = self.scratch[slot, 1]
            # the step's appends, in ordinal (program) order — later
            # ordinals clobber earlier ones exactly like the eager
            # path's sequential writes (overflow wraps included)
            for j in range(a):
                pj = pos[j, b]
                hit = (pj[:, None] == col) & (pj >= 0)[:, None]
                qm_blk = jnp.where(hit, meta[j, b][:, None], qm_blk)
                qd_blk = jnp.where(hit, data[j, b][:, None], qd_blk)
            # next step's heads, from the block AS MODIFIED — one ring
            # read per step, and the one-hot sum matches ring_heads
            hit_h = qh[b][:, None] == col
            hm_parts.append(jnp.sum(jnp.where(hit_h, qm_blk, 0), axis=-1,
                                    dtype=_i32))
            hd_parts.append(jnp.sum(jnp.where(hit_h, qd_blk, 0), axis=-1,
                                    dtype=_i32))
            self.scratch[slot, 0] = qm_blk
            self.scratch[slot, 1] = qd_blk
            for cp in self._store(b, slot):
                cp.start()
            if b + 1 < self.rnb:
                if b >= 1:
                    for cp in self._store(b - 1, (b + 1) % 2):
                        cp.wait()
                for cp in self._load(b + 1, (b + 1) % 2):
                    cp.start()
        for b in range(max(self.rnb - 2, 0), self.rnb):
            for cp in self._store(b, b % 2):
                cp.wait()
        head_meta = jnp.concatenate(hm_parts)[:self.e]
        head_data = jnp.concatenate(hd_parts)[:self.e]
        return head_meta, head_data


def fused_scan(step_fn, carry, edge_plane, aux_plane, *, length: int,
               interpret: bool, block_edges: int = 0, consts=None,
               ring=None):
    """Run ``length`` steps of ``step_fn`` inside ONE Pallas kernel with
    the whole ``carry`` pytree VMEM-resident between steps.

    ``step_fn(carry, ep_slice, aux_slice) -> carry`` is traced into the
    kernel body; ``ep_slice`` is the step's [R, E] row of ``edge_plane``
    ([length, R, E] i32, or None), delivered through the double-buffered
    HBM->VMEM block pipeline described in the module docstring;
    ``aux_slice`` is the step's row of ``aux_plane`` ([length, ...] or
    None), which stays fully VMEM-resident (node-sized, cheap).

    ``consts`` (optional pytree) carries the step body's loop-invariant
    arrays — topology tables, permutations — which a Pallas kernel body
    cannot close over (captured-constant error): they ride as VMEM
    operands, are read once, and are handed to the step as a fourth
    argument, ``step_fn(carry, ep, aux, consts)``.

    ``ring`` (optional ``(q_meta, q_data)`` pair of [E, C] i32 planes)
    is the tiled-state layout (module docstring): both planes ride as
    HBM (``pltpu.ANY``) operands AND outputs — the kernel DMA-copies the
    inputs into the outputs once at entry, then mutates the outputs in
    place through a ``RingStream`` handed to the step as a fifth
    argument, ``step_fn(carry, ep, aux, consts, rs)``. The call then
    returns ``(carry, (q_meta', q_data'))`` instead of just the carry.

    Zero-size carry leaves (representation planes the exact tick never
    touches — split-mode marker planes, a disarmed trace ring) bypass
    the kernel and are reattached verbatim: step_fn must not write them
    (a disarmed plane is zero-size exactly because its feature is off;
    an ARMED trace ring is a live leaf and rides the carry normally).
    """
    leaves, treedef = jax.tree_util.tree_flatten(carry)
    live = [i for i, x in enumerate(leaves) if jnp.size(x) > 0]
    scalars = [jnp.ndim(leaves[i]) == 0 for i in live]
    ins = [jnp.reshape(leaves[i], (1,)) if s else leaves[i]
           for i, s in zip(live, scalars)]
    out_shape = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in ins)

    n_aux = 0
    if aux_plane is not None:
        aux_leaves, aux_def = jax.tree_util.tree_flatten(aux_plane)
        n_aux = len(aux_leaves)
    n_const = 0
    if consts is not None:
        const_leaves, const_def = jax.tree_util.tree_flatten(consts)
        n_const = len(const_leaves)
    e = nb = eb = r = 0
    if edge_plane is not None:
        k, r, e = edge_plane.shape
        assert k == length
        nb, eb = plan_edge_blocks(e, block_edges)
        edge_plane = _pack_edge_plane(jnp.asarray(edge_plane, _i32), nb, eb)
    re_ = rc = rnb = reb = 0
    ring_ops = None
    if ring is not None:
        qm0, qd0 = ring
        re_, rc = qm0.shape
        rnb, reb = plan_edge_blocks(re_, block_edges)
        ring_ops = [_pack_ring_plane(jnp.asarray(qm0, _i32), rnb, reb),
                    _pack_ring_plane(jnp.asarray(qd0, _i32), rnb, reb)]
        out_shape = out_shape + tuple(
            jax.ShapeDtypeStruct((rnb, reb, rc), _i32) for _ in range(2))

    def unpack_carry(refs):
        vals = [ref[0] if s else ref[...] for ref, s in zip(refs, scalars)]
        # dead (zero-size) leaves become in-kernel zeros — a leaf from
        # the outer trace would be a captured constant, which Pallas
        # rejects; the caller's originals are reattached after the call
        full = [jnp.zeros(jnp.shape(x), x.dtype) for x in leaves]
        for i, v in zip(live, vals):
            full[i] = v
        return jax.tree_util.tree_unflatten(treedef, full)

    def pack_carry(c, out_refs):
        out = jax.tree_util.tree_leaves(c)
        for ref, i, s in zip(out_refs, live, scalars):
            ref[...] = jnp.reshape(out[i], (1,)) if s else out[i]

    def kernel(*refs):
        n_in = len(ins)
        in_refs = refs[:n_in]
        aux_vals = [a[...] for a in refs[n_in:n_in + n_aux]]
        cv = [c[...] for c in
              refs[n_in + n_aux:n_in + n_aux + n_const]]
        pos = n_in + n_aux + n_const
        ep_ref = None
        if edge_plane is not None:
            ep_ref = refs[pos]
            pos += 1
        ring_in = refs[pos:pos + 2] if ring is not None else None
        n_out = n_in + (2 if ring is not None else 0)
        out_all = refs[len(refs) - n_out:]
        out_refs = out_all[:n_in]
        ring_out = out_all[n_in:]

        c0 = unpack_carry(in_refs)
        const_tree = (jax.tree_util.tree_unflatten(const_def, cv)
                      if consts is not None else None)

        def body(c, j, ep_vmem, rs):
            ep = None
            if ep_vmem is not None:
                # [NB, R, EB] -> [R, E]: undo the block layout, drop pad
                ep = jnp.transpose(ep_vmem, (1, 0, 2)).reshape(-1, nb * eb)
                ep = ep[:, :e]
            ax = None
            if aux_plane is not None:
                ax = jax.tree_util.tree_unflatten(
                    aux_def, [a[j] for a in aux_vals])
            if ring is not None:
                return step_fn(c, ep, ax, const_tree, rs)
            if consts is not None:
                return step_fn(c, ep, ax, const_tree)
            return step_fn(c, ep, ax)

        if ep_ref is None and ring is None:
            def step(c, j):
                return body(c, j, None, None), None

            c, _ = lax.scan(step, c0, jnp.arange(length, dtype=_i32))
            pack_carry(c, out_refs)
            return

        def inner(ep_scratch=None, ep_sem=None, rg_scratch=None,
                  rg_lsem=None, rg_wsem=None, rg_csem=None):
            rs = None
            if ring is not None:
                # one HBM->HBM copy of each input ring into its output
                # ref at kernel entry: the scan owns the output copy and
                # mutates it in place via RingStream's block passes
                cin = [pltpu.make_async_copy(ring_in[p], ring_out[p],
                                             rg_csem.at[p])
                       for p in range(2)]
                for cp in cin:
                    cp.start()
                for cp in cin:
                    cp.wait()
                rs = RingStream(ring_out[0], ring_out[1], rg_scratch,
                                rg_lsem, rg_wsem, e=re_, rnb=rnb,
                                reb=reb, c=rc)

            if ep_ref is not None:
                def copies(j, slot):
                    return [pltpu.make_async_copy(
                        ep_ref.at[j, b], ep_scratch.at[slot, b],
                        ep_sem.at[slot, b])
                        for b in range(nb)]

                for cp in copies(jnp.int32(0), jnp.int32(0)):
                    cp.start()

            def step(c, j):
                ep_vmem = None
                if ep_ref is not None:
                    slot = lax.rem(j, jnp.int32(2))
                    for cp in copies(j, slot):
                        cp.wait()
                    # prefetch tick j+1 into the other slot while tick
                    # j executes (the last step re-fetches its own row:
                    # the copy is started so the post-scan drain stays
                    # uniform, its data is never read)
                    nxt = jnp.minimum(j + 1, length - 1)
                    for cp in copies(nxt, lax.rem(j + 1, jnp.int32(2))):
                        cp.start()
                    ep_vmem = ep_scratch[slot]
                return body(c, j, ep_vmem, rs), None

            c, _ = lax.scan(step, c0, jnp.arange(length, dtype=_i32))
            if ep_ref is not None:
                for cp in copies(jnp.int32(length - 1),
                                 lax.rem(jnp.int32(length),
                                         jnp.int32(2))):
                    cp.wait()
            pack_carry(c, out_refs)

        scopes = {}
        if ep_ref is not None:
            scopes["ep_scratch"] = pltpu.VMEM((2, nb, r, eb), _i32)
            scopes["ep_sem"] = pltpu.SemaphoreType.DMA((2, nb))
        if ring is not None:
            scopes["rg_scratch"] = pltpu.VMEM((2, 2, reb, rc), _i32)
            scopes["rg_lsem"] = pltpu.SemaphoreType.DMA((2, 2))
            scopes["rg_wsem"] = pltpu.SemaphoreType.DMA((2, 2))
            scopes["rg_csem"] = pltpu.SemaphoreType.DMA((2,))
        pl.run_scoped(inner, **scopes)

    # carry + aux ride as ordinary whole-array VMEM operands; only the
    # K-scaling edge plane and the tiled ring planes stay in ANY (HBM)
    # behind their DMA pipelines.
    operands = list(ins)
    if aux_plane is not None:
        operands += [jnp.asarray(a, _i32) for a in aux_leaves]
    if consts is not None:
        operands += list(const_leaves)
    in_spec_list = [pl.BlockSpec(memory_space=pltpu.VMEM)
                    for _ in operands]
    if edge_plane is not None:
        operands.append(edge_plane)
        in_spec_list.append(pl.BlockSpec(memory_space=pltpu.ANY))
    call_kwargs = {}
    if ring is not None:
        operands += ring_ops
        in_spec_list += [pl.BlockSpec(memory_space=pltpu.ANY)] * 2
        call_kwargs["out_specs"] = tuple(
            [pl.BlockSpec(memory_space=pltpu.VMEM)] * len(ins)
            + [pl.BlockSpec(memory_space=pltpu.ANY)] * 2)

    outs = pl.pallas_call(
        kernel,
        in_specs=in_spec_list,
        out_shape=out_shape,
        interpret=interpret,
        **call_kwargs)(*operands)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    ring_result = None
    if ring is not None:
        qm2, qd2 = outs[-2:]
        outs = outs[:-2]
        ring_result = (qm2.reshape(rnb * reb, rc)[:re_],
                       qd2.reshape(rnb * reb, rc)[:re_])
    full = list(leaves)
    for x, i, s in zip(outs, live, scalars):
        full[i] = jnp.reshape(x, ()) if s else x
    carry_out = jax.tree_util.tree_unflatten(treedef, full)
    if ring is not None:
        return carry_out, ring_result
    return carry_out


def hbm_round_trip_model(state_bytes: int, plane_bytes: int, length: int,
                         fused: bool, *, ring_bytes: int = 0,
                         tiled: bool = False) -> int:
    """Analytic HBM traffic of one K-tick dispatch — what a compiled TPU
    kernel would actually move, the metric the cost plane pins next to
    the backend-dependent ``bytes_accessed`` (interpret-mode Pallas
    inlines the kernel body into stock HLO, so XLA's byte count cannot
    see the fusion; this model can). Split kernels re-read and re-write
    the carry every tick (a deliberately conservative FLOOR — the real
    split path round-trips per STAGE, not per tick); the fused kernel
    reads the carry once, writes it once, and streams each fault-plane
    row exactly once.

    ``tiled`` is the ring-streaming layout: the non-ring carry still
    round-trips once, but the [E, C] ring planes (``ring_bytes`` =
    2·E·C·4) move per STEP — the entry copy-in reads + writes them once,
    then every step's commit_and_heads block pass loads and writes back
    every block once: ``2·ring·(K+1)`` ring bytes total. Tiled fused
    traffic therefore grows with K through the ring term only — still
    far below the split path's full-carry-per-tick round trip whenever
    the rings don't utterly dominate the state, and the price paid for
    running shapes the resident layout cannot hold at all."""
    if fused and tiled:
        return (2 * (state_bytes - ring_bytes) + plane_bytes
                + 2 * ring_bytes * (max(length, 1) + 1))
    if fused:
        return 2 * state_bytes + plane_bytes
    return 2 * state_bytes * max(length, 1) + plane_bytes
