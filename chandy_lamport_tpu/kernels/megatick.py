"""One-kernel megatick: the entire exact tick body, K ticks per kernel.

PR 9 kernelized the ring-queue step and the segment reductions as
*separate* ``pl.pallas_call``s, so a K-tick megatick still round-trips
every working plane through HBM between pop -> route -> reduce -> spread
-> append, K times. This module fuses the WHOLE tick body — head gather,
eligibility, per-source first-eligible select, pop, routing, CSR segment
reduce, node update/spread, ring append, error-bit fold — into one
kernel, then ``lax.scan``s K megaticks INSIDE it, so queue/node state
never leaves VMEM between ticks: one HBM read of the carry at kernel
entry, one write at exit, regardless of K.

The tick body itself is not re-derived here: ``fused_scan`` traces the
caller's ``step_fn`` (ops/tick.TickKernel binds its stock-XLA cascade /
wave tick, the formulation every engine arm is differentially pinned
against) inside the kernel, so bit-identity with the split-kernel and
XLA paths is by construction — the same jaxpr, executed VMEM-resident.

Fault masks as input planes
---------------------------
The PR 9 split had to hop out of the kernel for the fault gates. Here
the adversary moves in-kernel as masked lanes driven by PRECOMPUTED
per-(tick, edge) fault planes: the stateless (fault_key, time, index)
hash makes every mask for times t+1..t+K computable before the kernel
launches (TickKernel._fault_planes), and the in-kernel scan consumes
row j exactly when the j-th tick really executes — the quiescence /
drain / quarantine gates are monotone, so ticks always run on a step
prefix and the time<->row correspondence cannot slip. Semantics are
byte-for-byte the hash-at-tick-time path's (tests/test_megatick_fused).

Edge blocks, double buffering and the VMEM budget
-------------------------------------------------
The per-(tick, edge) planes are the one input that scales with K·E, so
they stay in HBM (``pltpu.ANY``) and stream through a double-buffered
async-copy pipeline over EDGE BLOCKS: the [K, R, E] plane is padded to
NB·EB edges and laid out [K, NB, R, EB]; while the scan executes tick j
out of VMEM slot ``j % 2``, the NB block copies for tick j+1 are already
in flight into slot ``(j+1) % 2`` (one DMA semaphore per (slot, block)).
The block size EB is chosen against the VMEM budget documented in
``kernels/__init__.py``: carry ≈ state bytes (q planes 8·E·C B dominate,
plus the [L, E] log and [S, E] window planes), streaming scratch adds
``2 · R · NB · EB · 4`` B, and the whole working set must clear
``FUSED_VMEM_BUDGET`` (12 MB of the ~16 MB/core, the rest left for the
tick body's intermediates) — ``plan_edge_blocks`` / ``fused_vmem_bytes``
below are that arithmetic, and ``resolve_fused_tick`` is the single
gate deciding fused vs split (the ``fused_tick`` ENGINE_KNOBS row).

Off-TPU everything runs under ``interpret=True`` like the PR 9 kernels,
so CPU tier-1 exercises the fused body, the DMA pipeline included.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_i32 = jnp.int32

# The fused working set must clear this: ~16 MB/core VMEM minus ~4 MB
# left for the tick body's intermediates (one-hot masks, cumsums — the
# same headroom argument as the split kernels' budget note).
FUSED_VMEM_BUDGET = 12 * 1024 * 1024
# Default edge-block width for the streamed fault planes: 512 edges x
# R=8 rows x 4 B = 16 KB per block copy — large enough to amortize DMA
# issue overhead, small enough that NB stays >= 2 on every graph the
# test tree runs (so the pipeline's block loop is genuinely exercised).
DEFAULT_BLOCK_EDGES = 512


def plan_edge_blocks(e: int, block_edges: int = 0) -> tuple[int, int]:
    """(NB, EB) for streaming an [.., E]-last plane in EB-edge blocks:
    EB = ``block_edges`` (0 -> DEFAULT_BLOCK_EDGES, clamped to E so tiny
    graphs get one exact block), NB = ceil(E / EB). The plane is padded
    to NB·EB edges; callers slice the pad back off after each copy."""
    if e <= 0:
        raise ValueError(f"need at least one edge, got E={e}")
    eb = int(block_edges) if block_edges else DEFAULT_BLOCK_EDGES
    eb = max(1, min(eb, e))
    nb = -(-e // eb)
    return nb, eb


def pytree_bytes(tree) -> int:
    """Total array bytes of a pytree — the carry side of the VMEM math."""
    return sum(x.size * jnp.dtype(x.dtype).itemsize
               for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "dtype"))


def fused_vmem_bytes(state_bytes: int, e: int, n: int, length: int,
                     faulted: bool, block_edges: int = 0) -> int:
    """The fused kernel's resident working set: the carry (state + loop
    scalars) + the double-buffered edge-plane scratch (2 slots x 8 rows
    x NB·EB i32) + the K-resident node plane (length x 2 x N i32).
    Fault-free kernels stream nothing — carry only."""
    total = state_bytes + 64        # + packed loop scalars
    if faulted:
        nb, eb = plan_edge_blocks(e, block_edges)
        total += 2 * 8 * nb * eb * 4
        total += length * 2 * n * 4
    return total


def resolve_fused_tick(fused_tick: str, *, kernel_engine: str,
                       megatick: int, marker_mode: str, exact_impl: str,
                       supervised: bool, traced: bool,
                       vmem_bytes: int,
                       budget: int = FUSED_VMEM_BUDGET
                       ) -> tuple[str, str]:
    """Resolve the ``fused_tick`` knob (config.ENGINE_KNOBS) to a
    concrete ("on"|"off", reason). "auto" turns on exactly when the
    one-kernel megatick applies:

      * ``kernel_engine == "pallas"`` and ``megatick > 1`` — the fusion
        IS the K-tick scan; K=1 has nothing to keep resident;
      * ring markers + cascade/wave — the vectorized exact formulations
        (the fold is the reference-literal specification form, and the
        split representation never runs the exact tick);
      * supervisor and flight recorder off — both paths fall back to
        the split kernels (documented contract: composition is via
        fallback, bit-identical by the megatick differentials; the
        fault adversary, by contrast, runs genuinely in-kernel via the
        precomputed mask planes);
      * the working set fits the VMEM budget (fused_vmem_bytes).

    "on" RAISES on the first unmet requirement instead of silently
    splitting — the explicit spelling is the CI/profiling override and
    must never lie about what ran. "off" always splits."""
    if fused_tick not in ("auto", "on", "off"):
        raise ValueError(f"unknown fused_tick {fused_tick!r}")
    if fused_tick == "off":
        return "off", "fused_tick='off'"
    why = None
    if kernel_engine != "pallas":
        why = (f"kernel_engine={kernel_engine!r} (the fused megatick is "
               f"a Pallas kernel)")
    elif megatick <= 1:
        why = f"megatick={megatick} (nothing to fuse below K=2)"
    elif marker_mode != "ring":
        why = (f"marker_mode={marker_mode!r} (the exact tick only runs "
               f"on the ring representation)")
    elif exact_impl not in ("cascade", "wave"):
        why = (f"exact_impl={exact_impl!r} (the fold is the reference-"
               f"literal specification form)")
    elif supervised:
        why = ("snapshot supervisor armed (supervised runs keep the "
               "split kernels)")
    elif traced:
        why = ("flight recorder armed (traced runs keep the split "
               "kernels)")
    elif vmem_bytes > budget:
        why = (f"working set {vmem_bytes} B exceeds the "
               f"{budget} B VMEM budget")
    if why is None:
        return "on", "fused megatick engaged"
    if fused_tick == "on":
        raise ValueError(f"fused_tick='on' impossible: {why}")
    return "off", why


def _pack_edge_plane(plane, nb: int, eb: int):
    """[K, R, E] -> [K, NB, R, EB] (zero-padded on E): the DMA layout —
    one copy descriptor per (tick, block), blocks contiguous last."""
    k, r, e = plane.shape
    pad = nb * eb - e
    if pad:
        plane = jnp.pad(plane, ((0, 0), (0, 0), (0, pad)))
    return jnp.transpose(plane.reshape(k, r, nb, eb), (0, 2, 1, 3))


def fused_scan(step_fn, carry, edge_plane, aux_plane, *, length: int,
               interpret: bool, block_edges: int = 0, consts=None):
    """Run ``length`` steps of ``step_fn`` inside ONE Pallas kernel with
    the whole ``carry`` pytree VMEM-resident between steps.

    ``step_fn(carry, ep_slice, aux_slice) -> carry`` is traced into the
    kernel body; ``ep_slice`` is the step's [R, E] row of ``edge_plane``
    ([length, R, E] i32, or None), delivered through the double-buffered
    HBM->VMEM block pipeline described in the module docstring;
    ``aux_slice`` is the step's row of ``aux_plane`` ([length, ...] or
    None), which stays fully VMEM-resident (node-sized, cheap).

    ``consts`` (optional pytree) carries the step body's loop-invariant
    arrays — topology tables, permutations — which a Pallas kernel body
    cannot close over (captured-constant error): they ride as VMEM
    operands, are read once, and are handed to the step as a fourth
    argument, ``step_fn(carry, ep, aux, consts)``.

    Zero-size carry leaves (representation planes the exact tick never
    touches — split-mode marker planes, a disarmed trace ring) bypass
    the kernel and are reattached verbatim: step_fn must not write them
    (the resolve_fused_tick gate guarantees the recorder is off).
    """
    leaves, treedef = jax.tree_util.tree_flatten(carry)
    live = [i for i, x in enumerate(leaves) if jnp.size(x) > 0]
    scalars = [jnp.ndim(leaves[i]) == 0 for i in live]
    ins = [jnp.reshape(leaves[i], (1,)) if s else leaves[i]
           for i, s in zip(live, scalars)]
    out_shape = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in ins)

    n_aux = 0
    if aux_plane is not None:
        aux_leaves, aux_def = jax.tree_util.tree_flatten(aux_plane)
        n_aux = len(aux_leaves)
    n_const = 0
    if consts is not None:
        const_leaves, const_def = jax.tree_util.tree_flatten(consts)
        n_const = len(const_leaves)
    e = nb = eb = 0
    if edge_plane is not None:
        k, r, e = edge_plane.shape
        assert k == length
        nb, eb = plan_edge_blocks(e, block_edges)
        edge_plane = _pack_edge_plane(jnp.asarray(edge_plane, _i32), nb, eb)

    def unpack_carry(refs):
        vals = [ref[0] if s else ref[...] for ref, s in zip(refs, scalars)]
        # dead (zero-size) leaves become in-kernel zeros — a leaf from
        # the outer trace would be a captured constant, which Pallas
        # rejects; the caller's originals are reattached after the call
        full = [jnp.zeros(jnp.shape(x), x.dtype) for x in leaves]
        for i, v in zip(live, vals):
            full[i] = v
        return jax.tree_util.tree_unflatten(treedef, full)

    def pack_carry(c, out_refs):
        out = jax.tree_util.tree_leaves(c)
        for ref, i, s in zip(out_refs, live, scalars):
            ref[...] = jnp.reshape(out[i], (1,)) if s else out[i]

    def kernel(*refs):
        n_in = len(ins)
        in_refs = refs[:n_in]
        aux_vals = [a[...] for a in refs[n_in:n_in + n_aux]]
        cv = [c[...] for c in
              refs[n_in + n_aux:n_in + n_aux + n_const]]
        ep_ref = (refs[n_in + n_aux + n_const]
                  if edge_plane is not None else None)
        out_refs = refs[len(refs) - len(ins):]

        c0 = unpack_carry(in_refs)
        const_tree = (jax.tree_util.tree_unflatten(const_def, cv)
                      if consts is not None else None)

        def body(c, j, ep_vmem):
            ep = None
            if ep_vmem is not None:
                # [NB, R, EB] -> [R, E]: undo the block layout, drop pad
                ep = jnp.transpose(ep_vmem, (1, 0, 2)).reshape(-1, nb * eb)
                ep = ep[:, :e]
            ax = None
            if aux_plane is not None:
                ax = jax.tree_util.tree_unflatten(
                    aux_def, [a[j] for a in aux_vals])
            if consts is not None:
                return step_fn(c, ep, ax, const_tree)
            return step_fn(c, ep, ax)

        if ep_ref is None:
            def step(c, j):
                return body(c, j, None), None

            c, _ = lax.scan(step, c0, jnp.arange(length, dtype=_i32))
            pack_carry(c, out_refs)
            return

        def inner(scratch, sem):
            def copies(j, slot):
                return [pltpu.make_async_copy(
                    ep_ref.at[j, b], scratch.at[slot, b], sem.at[slot, b])
                    for b in range(nb)]

            for cp in copies(jnp.int32(0), jnp.int32(0)):
                cp.start()

            def step(c, j):
                slot = lax.rem(j, jnp.int32(2))
                for cp in copies(j, slot):
                    cp.wait()
                # prefetch tick j+1 into the other slot while tick j
                # executes (the last step re-fetches its own row: the
                # copy is started so the post-scan drain stays uniform,
                # its data is never read)
                nxt = jnp.minimum(j + 1, length - 1)
                for cp in copies(nxt, lax.rem(j + 1, jnp.int32(2))):
                    cp.start()
                return body(c, j, scratch[slot]), None

            c, _ = lax.scan(step, c0, jnp.arange(length, dtype=_i32))
            for cp in copies(jnp.int32(length - 1),
                             lax.rem(jnp.int32(length), jnp.int32(2))):
                cp.wait()
            pack_carry(c, out_refs)

        pl.run_scoped(
            inner,
            scratch=pltpu.VMEM((2, nb, r, eb), _i32),
            sem=pltpu.SemaphoreType.DMA((2, nb)))

    # carry + aux ride as ordinary whole-array VMEM operands; only the
    # K-scaling edge plane stays in ANY (HBM) behind the DMA pipeline.
    operands = list(ins)
    if aux_plane is not None:
        operands += [jnp.asarray(a, _i32) for a in aux_leaves]
    if consts is not None:
        operands += list(const_leaves)
    in_spec_list = [pl.BlockSpec(memory_space=pltpu.VMEM)
                    for _ in operands]
    if edge_plane is not None:
        operands.append(edge_plane)
        in_spec_list.append(pl.BlockSpec(memory_space=pltpu.ANY))

    outs = pl.pallas_call(
        kernel,
        in_specs=in_spec_list,
        out_shape=out_shape,
        interpret=interpret)(*operands)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    full = list(leaves)
    for x, i, s in zip(outs, live, scalars):
        full[i] = jnp.reshape(x, ()) if s else x
    return jax.tree_util.tree_unflatten(treedef, full)


def hbm_round_trip_model(state_bytes: int, plane_bytes: int, length: int,
                         fused: bool) -> int:
    """Analytic HBM traffic of one K-tick dispatch — what a compiled TPU
    kernel would actually move, the metric the cost plane pins next to
    the backend-dependent ``bytes_accessed`` (interpret-mode Pallas
    inlines the kernel body into stock HLO, so XLA's byte count cannot
    see the fusion; this model can). Split kernels re-read and re-write
    the carry every tick (a deliberately conservative FLOOR — the real
    split path round-trips per STAGE, not per tick); the fused kernel
    reads the carry once, writes it once, and streams each fault-plane
    row exactly once."""
    if fused:
        return 2 * state_bytes + plane_bytes
    return 2 * state_bytes * max(length, 1) + plane_bytes
