"""Fused Pallas ring-queue kernels (package docstring: block shapes/VMEM).

Three entry points mirroring ops/tick.TickKernel's queue primitives —
``head_fields`` (head gather + meta decode), ``queue_step`` (the fully
fused head-read -> eligibility -> per-source selection -> pop used by the
fault-free exact tick), ``select_pop`` (selection + pop over an externally
gated eligibility mask, the fault-adversary path), and ``append_rows``
(the batched routed append with overflow flagging). All are bit-identical
to the XLA formulations by construction: same one-hot/prefix-sum math,
same error-bit reductions, just VMEM-resident between the pieces.

Inside the kernels the ``[E, C]`` planes are addressed with
``broadcasted_iota`` one-hot masks (TPU has no in-kernel scatter; a
VMEM-resident one-hot select costs no HBM traffic, which is what made the
mask engine lose at the XLA level). Every scalar operand rides in as a
``(1,)`` array (TPU scalars must be >= 1-D).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from chandy_lamport_tpu.core.state import (
    ERR_QUEUE_OVERFLOW,
    ERR_VALUE_OVERFLOW,
    RTIME_PACK_LIMIT,
    meta_marker,
    meta_rtime,
)

_i32 = jnp.int32


def _head_one_hot(q_meta, q_data, q_head):
    """VMEM one-hot head gather: (head_meta, head_data), both [E] i32."""
    cc = jax.lax.broadcasted_iota(_i32, q_meta.shape, q_meta.ndim - 1)
    hit = cc == q_head[..., None]
    head_meta = jnp.sum(jnp.where(hit, q_meta, 0), axis=-1, dtype=_i32)
    head_data = jnp.sum(jnp.where(hit, q_data, 0), axis=-1, dtype=_i32)
    return head_meta, head_data


def _select(elig, src_first):
    """First eligible edge per source, in dest order: the O(E) exclusive
    prefix-count formulation (edges are per-source contiguous)."""
    elig_i = elig.astype(_i32)
    before = jnp.cumsum(elig_i, axis=-1) - elig_i
    return elig & (before == jnp.take(before, src_first, axis=-1))


def _head_fields_kernel(qm_ref, qd_ref, qh_ref, rt_ref, mk_ref, data_ref):
    head_meta, head_data = _head_one_hot(qm_ref[...], qd_ref[...],
                                         qh_ref[...])
    rt_ref[...] = meta_rtime(head_meta)
    mk_ref[...] = meta_marker(head_meta)
    data_ref[...] = head_data


def head_fields(q_meta, q_data, q_head, *, interpret: bool):
    """Every ring head's (rtime, is_marker, data) — TickKernel._head_fields
    as one fused VMEM pass over the packed planes."""
    e = q_head.shape[-1]
    return pl.pallas_call(
        _head_fields_kernel,
        out_shape=(jax.ShapeDtypeStruct((e,), _i32),
                   jax.ShapeDtypeStruct((e,), jnp.bool_),
                   jax.ShapeDtypeStruct((e,), _i32)),
        interpret=interpret,
    )(q_meta, q_data, q_head)


def _queue_step_kernel(qm_ref, qd_ref, qh_ref, ql_ref, t_ref, sf_ref,
                       tok_ref, mk_ref, data_ref, nh_ref, nl_ref,
                       *, capacity: int):
    ql = ql_ref[...]
    qh = qh_ref[...]
    head_meta, head_data = _head_one_hot(qm_ref[...], qd_ref[...], qh)
    head_mk = meta_marker(head_meta)
    elig = (ql > 0) & (meta_rtime(head_meta) <= t_ref[0])
    sel = _select(elig, sf_ref[...])
    tok_ref[...] = sel & ~head_mk
    mk_ref[...] = sel & head_mk
    data_ref[...] = head_data
    nh_ref[...] = (qh + sel) % capacity
    nl_ref[...] = ql - sel.astype(_i32)


def queue_step(q_meta, q_data, q_head, q_len, time, src_first,
               *, capacity: int, interpret: bool):
    """THE fused queue step (fault-free _select_and_pop): head gather +
    meta decode + eligibility + per-source prefix-count selection + pop in
    ONE pass over the packed [E, C] planes. Returns (tok_pend, mk_pend,
    head_data, new_q_head, new_q_len)."""
    e = q_head.shape[-1]
    return pl.pallas_call(
        functools.partial(_queue_step_kernel, capacity=capacity),
        out_shape=(jax.ShapeDtypeStruct((e,), jnp.bool_),
                   jax.ShapeDtypeStruct((e,), jnp.bool_),
                   jax.ShapeDtypeStruct((e,), _i32),
                   jax.ShapeDtypeStruct((e,), _i32),
                   jax.ShapeDtypeStruct((e,), _i32)),
        interpret=interpret,
    )(q_meta, q_data, q_head, q_len, jnp.reshape(time, (1,)).astype(_i32),
      src_first)


def _select_pop_kernel(qh_ref, ql_ref, elig_ref, sf_ref,
                       sel_ref, nh_ref, nl_ref, *, capacity: int):
    sel = _select(elig_ref[...], sf_ref[...])
    sel_ref[...] = sel
    nh_ref[...] = (qh_ref[...] + sel) % capacity
    nl_ref[...] = ql_ref[...] - sel.astype(_i32)


def select_pop(q_head, q_len, elig, src_first, *, capacity: int,
               interpret: bool):
    """Selection + pop over an externally gated eligibility mask (the
    fault-adversary path, where jitter/crash gates edit ``elig`` between
    the head read and the selection). Returns (sel, new_q_head,
    new_q_len)."""
    e = q_head.shape[-1]
    return pl.pallas_call(
        functools.partial(_select_pop_kernel, capacity=capacity),
        out_shape=(jax.ShapeDtypeStruct((e,), jnp.bool_),
                   jax.ShapeDtypeStruct((e,), _i32),
                   jax.ShapeDtypeStruct((e,), _i32)),
        interpret=interpret,
    )(q_head, q_len, elig, src_first)


def _append_rows_kernel(qm_ref, qd_ref, qh_ref, ql_ref, tp_ref, act_ref,
                        meta_ref, rt_ref, data_ref,
                        om_ref, od_ref, err_ref,
                        *, capacity: int, key_limit: int,
                        flag_queue_overflow: bool):
    qm = qm_ref[...]
    active = act_ref[...]
    ql = ql_ref[...]
    err = (jnp.any(active & (tp_ref[...] >= key_limit))
           | jnp.any(active & (rt_ref[...] >= RTIME_PACK_LIMIT))
           ).astype(_i32) * ERR_VALUE_OVERFLOW
    if flag_queue_overflow:
        err = err | (jnp.any(active & (ql >= capacity)).astype(_i32)
                     * ERR_QUEUE_OVERFLOW)
    pos = (qh_ref[...] + ql) % capacity
    cc = jax.lax.broadcasted_iota(_i32, qm.shape, qm.ndim - 1)
    hit = active[..., None] & (cc == pos[..., None])
    om_ref[...] = jnp.where(hit, meta_ref[...][..., None], qm)
    od_ref[...] = jnp.where(hit, data_ref[...][..., None], qd_ref[...])
    err_ref[...] = jnp.reshape(err, (1,))


def append_rows(q_meta, q_data, q_head, q_len, tok_pushed, active,
                meta_e, rt_e, data_e, *, capacity: int, key_limit: int,
                flag_queue_overflow: bool = True, interpret: bool):
    """The batched routed ring append (TickKernel._append_rows /
    GraphShardedRunner._append_active): one fused pass computing the tail
    positions, the one-hot routed writes of BOTH packed planes, and the
    overflow error bits (queue overflow gated off for the sharded twin,
    which books it elsewhere). ``meta_e`` is the pre-packed slot word
    (state.pack_meta), ``rt_e`` the raw receive times for the
    RTIME_PACK_LIMIT check. Returns (q_meta', q_data', err_bits[1]);
    the q_len/tok_pushed advances are elementwise adds the caller keeps."""
    return pl.pallas_call(
        functools.partial(_append_rows_kernel, capacity=capacity,
                          key_limit=key_limit,
                          flag_queue_overflow=flag_queue_overflow),
        out_shape=(jax.ShapeDtypeStruct(q_meta.shape, q_meta.dtype),
                   jax.ShapeDtypeStruct(q_data.shape, q_data.dtype),
                   jax.ShapeDtypeStruct((1,), _i32)),
        interpret=interpret,
    )(q_meta, q_data, q_head, q_len, tok_pushed, active, meta_e, rt_e,
      data_e)
