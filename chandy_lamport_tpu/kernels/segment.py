"""Pallas edge->node segment reductions over the CSR-style sorted-edge
layout (package docstring: block shapes/VMEM).

The XLA segsum formulation (ops/tick.TickKernel._segment_sums) is an
exclusive prefix sum plus two bounds-takes; these kernels keep exactly
that math — so bit-identity with the XLA engine is by construction — but
fuse the by-destination permutation gather, the cumsum and the bounds
gathers into one VMEM-resident pass instead of three HBM-level tensors.
``spread`` is the inverse direction (node flag -> incident edges), one
fused gather. Operands may carry leading batch axes (the [S, E] snapshot
planes); all work is along the trailing axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_i32 = jnp.int32


def _cast(x):
    """Match XLA's cumsum dtype promotion (bool -> i32) so out_shape and
    the kernel body agree with the stock path bit-for-bit."""
    return x.astype(_i32) if x.dtype == jnp.bool_ else x


def _bounded_sums(xs, lo, hi):
    cs = jnp.cumsum(xs, axis=-1)
    cs0 = jnp.concatenate([jnp.zeros_like(cs[..., :1]), cs], axis=-1)
    return jnp.take(cs0, hi, axis=-1) - jnp.take(cs0, lo, axis=-1)


def _sum_segments_kernel(xs_ref, lo_ref, hi_ref, out_ref):
    out_ref[...] = _bounded_sums(xs_ref[...], lo_ref[...], hi_ref[...])


def sum_segments(xs, lo, hi, *, interpret: bool):
    """[..., E] -> [..., N] per-segment sums; ``xs`` already in segment
    order (the _sum_by_src case: edges are src-sorted as laid out)."""
    xs = _cast(xs)
    return pl.pallas_call(
        _sum_segments_kernel,
        out_shape=jax.ShapeDtypeStruct(xs.shape[:-1] + lo.shape, xs.dtype),
        interpret=interpret,
    )(xs, lo, hi)


def _sum_by_perm_kernel(x_ref, perm_ref, lo_ref, hi_ref, out_ref):
    xs = jnp.take(x_ref[...], perm_ref[...], axis=-1)
    out_ref[...] = _bounded_sums(xs, lo_ref[...], hi_ref[...])


def sum_by_perm(x_e, perm, lo, hi, *, interpret: bool):
    """[..., E] -> [..., N]: permute into segment order (``by_dst``) then
    segment-sum, fused — the _sum_by_dst case (token credits, marker
    arrival counts)."""
    x_e = _cast(x_e)
    return pl.pallas_call(
        _sum_by_perm_kernel,
        out_shape=jax.ShapeDtypeStruct(x_e.shape[:-1] + lo.shape,
                                       x_e.dtype),
        interpret=interpret,
    )(x_e, perm, lo, hi)


def _spread_kernel(x_ref, idx_ref, out_ref):
    out_ref[...] = jnp.take(x_ref[...], idx_ref[...], axis=-1)


def spread(x_n, idx_e, *, interpret: bool):
    """[..., N] -> [..., E]: broadcast a per-node quantity to incident
    edges (``idx_e`` = edge_dst for _spread_dst, edge_src for
    _spread_src)."""
    return pl.pallas_call(
        _spread_kernel,
        out_shape=jax.ShapeDtypeStruct(x_n.shape[:-1] + idx_e.shape,
                                       x_n.dtype),
        interpret=interpret,
    )(x_n, idx_e)
