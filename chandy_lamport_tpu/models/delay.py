"""Pluggable message-delay models.

The reference's only nondeterminism is the random delivery delay
``receiveTime = time + 1 + rand.Intn(maxDelay)`` drawn from Go's global PRNG
(reference sim.go:100-102). The delay model is the seam between the bit-exact
path (Go PRNG, draw-order-sensitive) and the fast batched TPU path
(counter-based jax.random, draw-order-free) — see SURVEY.md §5.

Host-side models (this module) expose ``receive_time(now) -> int`` for the
parity backend; the JAX backend carries the equivalent state in its pytree
(ops/tick.py).
"""

from __future__ import annotations

import numpy as np

from chandy_lamport_tpu.config import MAX_DELAY
from chandy_lamport_tpu.ops.gorand import GoRand


class DelayModel:
    def receive_time(self, now: int) -> int:
        raise NotImplementedError


class GoExactDelay(DelayModel):
    """Bit-exact reference delays: now + 1 + GoRand.Intn(max_delay).

    Matches reference sim.go:100-102 with the global PRNG seeded as in
    snapshot_test.go:20 (rand.Seed(seed+1) — the caller passes the already
    incremented seed).
    """

    def __init__(self, seed: int, max_delay: int = MAX_DELAY, **gorand_kwargs):
        self.seed = seed
        self.gorand_kwargs = gorand_kwargs
        self.rng = GoRand(seed, **gorand_kwargs)
        self.max_delay = max_delay

    def receive_time(self, now: int) -> int:
        return now + 1 + self.rng.intn(self.max_delay)


class FixedDelay(DelayModel):
    """Deterministic constant delay — for unit tests and docs examples."""

    def __init__(self, delay: int = 1):
        if delay < 1:
            raise ValueError("delay must be >= 1 (messages are never delivered same-tick)")
        self.delay = delay
        self.max_delay = delay

    def receive_time(self, now: int) -> int:
        return now + self.delay


class NumpyUniformDelay(DelayModel):
    """Fast host-side uniform delays in {1..max_delay} — same distribution as
    the reference, different stream. Used for property tests and as the
    host-side twin of the TPU counter-based model."""

    def __init__(self, seed: int, max_delay: int = MAX_DELAY):
        self.rng = np.random.default_rng(seed)
        self.max_delay = max_delay

    def receive_time(self, now: int) -> int:
        return now + 1 + int(self.rng.integers(0, self.max_delay))
