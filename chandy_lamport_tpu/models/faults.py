"""Deterministic, replayable fault adversary for the dense/batched kernels.

The simulator's reference semantics assume a kind world: reliable FIFO
channels, no crashes (queue.go never loses a message; nodes never stop).
Chandy-Lamport exists precisely because the real world is not kind — a
completed snapshot IS a consistent recovery line — so this module makes the
framework injure itself on purpose, the way JBotSim argues distributed
algorithms must be tested under dynamic/lossy networks (PAPERS.md), with the
replay discipline of the packet-level-simulation memoization work: every
fault is a pure function of (stream key, tick, index), in the same
stateless counter-hash style as ops/delay_jax.HashJaxDelay, so a faulted run
can be reproduced bit-exactly from its seed alone — no fault log, no state
beyond the per-lane key carried in ``DenseState.fault_key``.

Fault classes (applied by ops/tick.TickKernel under a single per-tick mask;
``faults=None`` at kernel construction compiles the hooks away entirely, so
the fault-free path stays bit-identical to the uninstrumented kernels):

  drop     a TOKEN selected for delivery is popped but lost (the amount is
           neither credited nor recorded).
  dup      a delivered token is ALSO re-enqueued on its edge with a fresh
           receive time drawn from the FAULT stream (never the delay
           sampler's, so the sampler stream is fault-invariant).
  jitter   per-(edge, tick) delivery stall: the channel adds a tick of
           latency to whatever is at its front (markers included). Under
           FIFO head-of-line semantics this is exactly extra-delay jitter
           applied at the head; the per-tick hash is independent, so a
           stalled head is delivered with probability 1 eventually.
  crash    per-node down windows. While down, a node receives nothing (its
           inbound edges are ineligible; in-flight messages WAIT — channels
           stay lossless). ``crash_mode``:
             "pause"  preemption semantics — node memory survives, recovery
                      is simply resuming (the TPU-preemption shape);
             "lossy"  node memory is destroyed: at the restart tick the
                      balance is restored from the last COMPLETED
                      Chandy-Lamport snapshot's frozen value (the
                      protocol's own artifact as the recovery line), or —
                      with no completed snapshot to roll back to — zeroed
                      with ERR_FAULT_UNRECOVERED raised for the lane.

Marker-plane classes (``marker_drop_rate``/``marker_dup_rate``/
``marker_jitter_rate``): the same drop/dup/jitter programs aimed at the
protocol's CONTROL plane. PR 3 exempted markers ("dropping one wedges the
snapshot unrecoverably"); the snapshot supervisor
(SimConfig.snapshot_timeout, ops/tick.TickKernel._supervise) removes that
excuse — a marker loss now stalls ONE attempt, which times out, is aborted
under a bumped epoch and re-initiated. Marker faults move no tokens, so
they never touch ``fault_skew``; their evidence is the FC_MDROP/FC_MDUP/
FC_MJITTER tallies plus the supervisor's retry/stale counters.

Bookkeeping: every injected token delta (dup - drop, crash-restore deltas)
accumulates in ``DenseState.fault_skew``, so token conservation remains an
exact in-run invariant under faults: utils.metrics.conservation_delta
subtracts the skew, and a zero delta on a heavily-faulted lane is evidence
the adversary's books balance (tools/chaos_smoke.py asserts exactly that).
"""

from __future__ import annotations

import jax.numpy as jnp

from chandy_lamport_tpu.config import MAX_DELAY
from chandy_lamport_tpu.ops.delay_jax import _lowbias32

_u32 = jnp.uint32

# per-class hash domains: every (class, tick, index) triple draws a distinct
# word, so the classes' streams never alias each other
(_CLS_DROP, _CLS_DUP, _CLS_JITTER, _CLS_CRASH, _CLS_DUP_DELAY,
 _CLS_MDROP, _CLS_MDUP, _CLS_MJITTER, _CLS_MDUP_DELAY) = range(1, 10)


def _word(key, cls: int, time, idx):
    """One u32 fault word for (key, class, time, idx) — pure, order-free
    (the replay property), a handful of fused VPU ops (the hot-path
    property). ``time``/``idx`` may be any integer arrays; broadcasting
    follows jnp rules."""
    t = _lowbias32(jnp.asarray(time).astype(_u32) * _u32(2654435769))
    h = _lowbias32(jnp.asarray(idx).astype(_u32) ^ t)
    return _lowbias32(h ^ key ^ _u32((cls * 0x9E3779B9) & 0xFFFFFFFF))


class JaxFaults:
    """Seeded fault program. Rates are static Python floats resolved at
    trace time: a zero-rate class contributes all-False masks (the
    instrumented-but-idle differential oracle), while ``faults=None`` at
    TickKernel construction removes the instrumentation entirely.

    ``crash_start`` switches the crash schedule from hashed periodic
    windows to ONE deterministic window [crash_start, crash_start +
    crash_len) — the targeting handle tests and the chaos smoke use to
    place a crash exactly before/after a snapshot completes."""

    def __init__(self, seed: int, *, drop_rate: float = 0.0,
                 dup_rate: float = 0.0, jitter_rate: float = 0.0,
                 crash_rate: float = 0.0, crash_len: int = 2,
                 crash_period: int = 32, crash_mode: str = "pause",
                 crash_start: int | None = None,
                 marker_drop_rate: float = 0.0,
                 marker_dup_rate: float = 0.0,
                 marker_jitter_rate: float = 0.0,
                 max_delay: int = MAX_DELAY):
        for name, r in (("drop_rate", drop_rate), ("dup_rate", dup_rate),
                        ("jitter_rate", jitter_rate),
                        ("crash_rate", crash_rate),
                        ("marker_drop_rate", marker_drop_rate),
                        ("marker_dup_rate", marker_dup_rate),
                        ("marker_jitter_rate", marker_jitter_rate)):
            if not 0.0 <= r <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {r}")
        if crash_mode not in ("pause", "lossy"):
            raise ValueError(f"unknown crash_mode {crash_mode!r}")
        if crash_len < 1 or crash_period < 2 or crash_len >= crash_period:
            raise ValueError(
                "need 1 <= crash_len < crash_period (a window must end "
                "before the next can start, or restarts never fire)")
        self.seed = int(seed)
        self.drop_rate = float(drop_rate)
        self.dup_rate = float(dup_rate)
        self.jitter_rate = float(jitter_rate)
        self.crash_rate = float(crash_rate)
        self.crash_len = int(crash_len)
        self.crash_period = int(crash_period)
        self.crash_mode = crash_mode
        self.crash_start = None if crash_start is None else int(crash_start)
        self.marker_drop_rate = float(marker_drop_rate)
        self.marker_dup_rate = float(marker_dup_rate)
        self.marker_jitter_rate = float(marker_jitter_rate)
        self.max_delay = int(max_delay)

    @property
    def crashes(self) -> bool:
        """True when crash windows can ever fire — the gate that disables
        the exact path's quiescence fast-forward (a lossy restart mutates
        balances even on a drained lane, so empty rings no longer prove a
        tick is a pure time increment)."""
        return self.crash_rate > 0.0 or self.crash_start is not None

    def describe(self) -> dict:
        """JSON-able fault program (storm CLI / chaos smoke provenance)."""
        return {"seed": self.seed, "drop": self.drop_rate,
                "dup": self.dup_rate, "jitter": self.jitter_rate,
                "crash": self.crash_rate, "crash_len": self.crash_len,
                "crash_period": self.crash_period,
                "crash_mode": self.crash_mode,
                "crash_start": self.crash_start,
                "marker_drop": self.marker_drop_rate,
                "marker_dup": self.marker_dup_rate,
                "marker_jitter": self.marker_jitter_rate}

    # -- stream keys (carried in DenseState.fault_key) ---------------------

    def _base_key(self) -> int:
        # host-side python mirror of _lowbias32 so keys are plain ints
        x = (self.seed ^ 0x243F6A88) & 0xFFFFFFFF
        for mul in (0x7FEB352D, 0x846CA68B):
            x ^= x >> 16
            x = (x * mul) & 0xFFFFFFFF
        x ^= x >> 16
        return x

    def init_state(self):
        """Single-instance stream key — nonzero (0 means disarmed)."""
        return jnp.uint32(self._base_key() | 1)

    def init_batch_state(self, batch: int):
        """Per-lane keys: odd base + stride-2 ramp — injective mod 2^32 and
        never zero, so no two lanes share a fault stream and every lane is
        armed. Tests disarm chosen lanes by zeroing their key."""
        base = self._base_key() | 1
        return (_u32(base)
                + _u32(2) * jnp.arange(batch, dtype=_u32))

    # -- per-tick masks (under jit; key may be a scalar per vmap lane) -----

    def _rate_mask(self, key, cls: int, rate: float, time, idx):
        armed = key != _u32(0)
        if rate <= 0.0:
            return jnp.zeros(jnp.shape(idx), bool)
        if rate >= 1.0:
            return jnp.broadcast_to(armed, jnp.shape(idx))
        thresh = _u32(min(int(rate * 2.0**32), 2**32 - 1))
        return armed & (_word(key, cls, time, idx) < thresh)

    def edge_masks(self, key, time, num_edges: int):
        """This tick's per-edge fault program: (drop, dup, jitter) bool [E]
        masks plus the dup re-enqueue delay words (raw u32 [E]; the kernel
        folds them into its delay budget so duplicates always land inside
        the drain flush window)."""
        idx = jnp.arange(num_edges, dtype=_u32)
        return (self._rate_mask(key, _CLS_DROP, self.drop_rate, time, idx),
                self._rate_mask(key, _CLS_DUP, self.dup_rate, time, idx),
                self._rate_mask(key, _CLS_JITTER, self.jitter_rate, time,
                                idx),
                _word(key, _CLS_DUP_DELAY, time, idx))

    def marker_masks(self, key, time, num_edges: int):
        """The marker-plane twin of ``edge_masks``: (drop, dup, jitter)
        bool [E] masks for this tick's MARKER deliveries plus the dup
        re-enqueue delay words (raw u32 [E]). Distinct hash classes, so
        the token and marker programs never alias; zero rates contribute
        all-False masks without hashing (the armed-but-idle oracle)."""
        idx = jnp.arange(num_edges, dtype=_u32)
        return (self._rate_mask(key, _CLS_MDROP, self.marker_drop_rate,
                                time, idx),
                self._rate_mask(key, _CLS_MDUP, self.marker_dup_rate,
                                time, idx),
                self._rate_mask(key, _CLS_MJITTER, self.marker_jitter_rate,
                                time, idx),
                _word(key, _CLS_MDUP_DELAY, time, idx))

    def down_nodes(self, key, time, num_nodes: int):
        """[N] bool: nodes down (crashed) at ``time``. Deterministic-window
        mode gates each node by the crash rate hashed once (window 0);
        periodic mode re-draws each node per window, so crashes recur."""
        idx = jnp.arange(num_nodes, dtype=_u32)
        if not self.crashes:
            return jnp.zeros(num_nodes, bool)
        time = jnp.asarray(time, jnp.int32)
        if self.crash_start is not None:
            in_window = ((time >= self.crash_start)
                         & (time < self.crash_start + self.crash_len))
            gate = self._rate_mask(key, _CLS_CRASH,
                                   self.crash_rate or 1.0, 0, idx)
            return gate & in_window
        window = time // self.crash_period
        gate = self._rate_mask(key, _CLS_CRASH, self.crash_rate, window, idx)
        return gate & ((time % self.crash_period) < self.crash_len)

    def restarted(self, key, time, num_nodes: int):
        """[N] bool: nodes whose crash window ended exactly at ``time``
        (down at time-1, up now) — the restore point for lossy crashes."""
        prev = self.down_nodes(key, time - 1, num_nodes)
        now = self.down_nodes(key, time, num_nodes)
        return prev & ~now & (jnp.asarray(time, jnp.int32) >= 1)
