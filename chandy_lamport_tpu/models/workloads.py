"""Synthetic topologies and traffic programs for scale testing + benchmarks.

The reference ships 4 hand-written topologies capped at 10 nodes
(test_data/, SURVEY.md §4.3); the BASELINE.md config ladder needs graphs at
256-8k nodes. All generators embed a Hamiltonian ring so every graph is
strongly connected — snapshot completion requires reaching every node
(reference sim.go:116-117 waits on ALL nodes).

A ``StormProgram`` is the scale analogue of 10nodes.events (every tick, every
node sends tokens ahead; snapshots staggered over ticks): per phase, every
node sends on one outbound edge (round-robin over its out-links, so the
whole phase is one vectorized bulk_send), and snapshot initiations fire on a
schedule. Executed by ``BatchedRunner.run_storm`` fully under jit.
"""

from __future__ import annotations

import random
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from chandy_lamport_tpu.core.spec import (
    Event,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.core.state import DenseTopology
from chandy_lamport_tpu.utils.fixtures import TopologySpec


def _ids(n: int) -> List[str]:
    # zero-padded so lexicographic order == numeric order at any scale
    width = len(str(n))
    return [f"N{str(i + 1).zfill(width)}" for i in range(n)]


def ring_topology(n: int, tokens: int = 100) -> TopologySpec:
    """Unidirectional ring — the shape of the reference's largest fixture
    (10nodes.top) at arbitrary scale."""
    ids = _ids(n)
    nodes = [(nid, tokens) for nid in ids]
    links = [(ids[i], ids[(i + 1) % n]) for i in range(n)]
    return TopologySpec(nodes, links)


def erdos_renyi(n: int, avg_degree: float, seed: int,
                tokens: int = 100) -> TopologySpec:
    """Ring + uniformly random extra arcs up to the requested mean
    out-degree (BASELINE.md config 3)."""
    rng = random.Random(seed)
    ids = _ids(n)
    nodes = [(nid, tokens) for nid in ids]
    links = {(ids[i], ids[(i + 1) % n]) for i in range(n)}
    extra = max(0, int(n * avg_degree) - n)
    while len(links) < n + extra:
        a, b = rng.randrange(n), rng.randrange(n)
        if a != b:
            links.add((ids[a], ids[b]))
    return TopologySpec(nodes, sorted(links))


def scale_free(n: int, attach: int, seed: int,
               tokens: int = 100) -> TopologySpec:
    """Ring + preferential attachment (Barabási–Albert flavor): each node
    adds ``attach`` outbound arcs to degree-weighted targets
    (BASELINE.md config 4 — hubs concentrate traffic, stressing the
    per-edge queues unevenly)."""
    rng = random.Random(seed)
    ids = _ids(n)
    nodes = [(nid, tokens) for nid in ids]
    links = {(ids[i], ids[(i + 1) % n]) for i in range(n)}
    degree = [1] * n
    targets = list(range(n))  # degree-weighted sampling pool
    for i in range(n):
        for _ in range(attach):
            j = targets[rng.randrange(len(targets))]
            if j != i and (ids[i], ids[j]) not in links:
                links.add((ids[i], ids[j]))
                degree[j] += 1
                targets.append(j)
    return TopologySpec(nodes, sorted(links))


def stream_jobs(spec: TopologySpec, count: int, seed: int,
                base_phases: int = 4, tail_alpha: float = 1.1,
                max_phases: int = 64, amount: int = 1,
                snapshots_per_job: int = 1,
                dup_rate: float = 0.0,
                prefix_overlap: float = 0.0) -> List[List[Event]]:
    """A heavy-tailed job mix for the streaming engine
    (parallel/batch.run_stream): ``count`` event-list jobs whose phase
    counts follow a Pareto(``tail_alpha``) tail over ``base_phases``
    (clamped to ``max_phases``), so a few jobs run an order of magnitude
    longer than the median — the distribution where static batching pays
    the whole batch's wall clock for its slowest member. Each phase sends
    ``amount`` tokens over one link (rotating through the link list with a
    per-job offset, so traffic stays shallow per node and no balance ever
    underflows for any sane phase cap); each job initiates
    ``snapshots_per_job`` snapshots, the first early (phase 1) and the
    rest spread, from a per-job rotating initiator. Deterministic in
    ``seed``.

    ``dup_rate``: fraction of the jobs that are byte-identical repeats
    drawn from the remaining unique "scenario library" — production
    streams replay a small library of scenarios far more often than they
    invent new ones, and repeats are exactly what the memo plane
    (``memo`` runner knob) serves for free. A library of
    ``max(1, round(count * (1 - dup_rate)))`` unique jobs is generated
    first; each repeat slot then draws a library index Zipf-style
    (weight 1/(k+1), so early scenarios dominate — the hot-set shape)
    and the draws are shuffled in among the originals. dup_rate 0 (the
    default) reproduces the historical all-unique mix bit-for-bit.

    ``prefix_overlap``: the NEAR-duplicate traffic shape (memo="prefix"
    plane) — every one of the ``count`` jobs copies a base scenario from
    a library of ``max(1, round(count * (1 - prefix_overlap)))``
    verbatim (Zipf-drawn, hot bases dominate) and appends one unique
    closing tail (a single-token send over the job's own link plus a
    tick run whose length encodes the job index — never more than one
    token moves, so no balance can underflow — making every job's
    whole-script digest distinct: dup_rate is exactly 0 and plain memo
    coalescing can serve NOTHING), which means jobs
    drawing the same base share its full phase-boundary digest chain
    and only diverge at the last phase. Mutually exclusive with
    ``dup_rate``; both are separate axes of the same library idea."""
    if count < 1:
        raise ValueError("count must be >= 1")
    if not 0.0 <= dup_rate < 1.0:
        raise ValueError("dup_rate must be in [0, 1)")
    if not 0.0 <= prefix_overlap < 1.0:
        raise ValueError("prefix_overlap must be in [0, 1)")
    if prefix_overlap:
        if dup_rate:
            raise ValueError(
                "prefix_overlap and dup_rate are mutually exclusive "
                "traffic shapes — arm one")
        nbase = max(1, round(count * (1.0 - prefix_overlap)))
        library = stream_jobs(spec, nbase, seed, base_phases=base_phases,
                              tail_alpha=tail_alpha, max_phases=max_phases,
                              amount=amount,
                              snapshots_per_job=snapshots_per_job)
        rng = random.Random(seed + 0x9EF1)
        weights = [1.0 / (k + 1) for k in range(nbase)]
        picks = rng.choices(range(nbase), weights=weights, k=count)
        links = list(spec.links)
        out: List[List[Event]] = []
        for i, k in enumerate(picks):
            # uniqueness comes from the (link, tick-run) PAIR, not the
            # token amount: amount+i sends would drain a node's balance
            # below zero once count outgrows the initial funding
            src, dest = links[i % len(links)]
            out.append(list(library[k])
                       + [PassTokenEvent(src=src, dest=dest,
                                         tokens=amount),
                          TickEvent(1 + i // len(links))])
        return out
    if dup_rate:
        nuniq = max(1, round(count * (1.0 - dup_rate)))
        library = stream_jobs(spec, nuniq, seed, base_phases=base_phases,
                              tail_alpha=tail_alpha, max_phases=max_phases,
                              amount=amount,
                              snapshots_per_job=snapshots_per_job)
        rng = random.Random(seed + 0x5EED)
        weights = [1.0 / (k + 1) for k in range(nuniq)]
        picks = rng.choices(range(nuniq), weights=weights, k=count - nuniq)
        mix = list(library) + [library[k] for k in picks]
        rng.shuffle(mix)
        return mix
    rng = random.Random(seed)
    links = list(spec.links)
    node_ids = [nid for nid, _ in spec.nodes]
    jobs: List[List[Event]] = []
    for j in range(count):
        phases = min(max_phases,
                     max(1, int(base_phases * rng.paretovariate(tail_alpha))))
        snap_at = {min(1, phases - 1)}
        for k in range(1, snapshots_per_job):
            snap_at.add((k * phases) // snapshots_per_job)
        ev: List[Event] = []
        snaps_fired = 0
        for p in range(phases):
            src, dest = links[(j * 7 + p) % len(links)]
            ev.append(PassTokenEvent(src=src, dest=dest, tokens=amount))
            if p in snap_at:
                ev.append(SnapshotEvent(
                    node_id=node_ids[(j + snaps_fired) % len(node_ids)]))
                snaps_fired += 1
            ev.append(TickEvent(1))
        jobs.append(ev)
    return jobs


class ServeRequest(NamedTuple):
    """One job of an open-loop serving workload (serving/server.py): the
    event-list payload plus the service metadata the admission policy
    orders by. ``arrival_step``/``deadline_step`` are absolute stream-step
    clocks (the serve loop's arrival gauge), ``priority`` is
    higher-wins."""

    job: int            # index into the packed pool (== list position)
    arrival_step: int   # stream step the job becomes visible to admission
    tenant: int         # tenant id in [0, tenants)
    priority: int       # admission class, higher admitted first
    deadline_step: int  # absolute harvest-by step (arrival + slack)
    events: List[Event]


def serve_workload(spec: TopologySpec, count: int, seed: int,
                   rate: float = 1.0, tenants: int = 4,
                   priorities: int = 2,
                   deadline_slack: Tuple[int, int] = (64, 256),
                   dup_rate: float = 0.0, base_phases: int = 4,
                   tail_alpha: float = 1.1, max_phases: int = 64,
                   amount: int = 1, snapshots_per_job: int = 1,
                   ) -> List[ServeRequest]:
    """A seeded Poisson/Zipf open-loop serving trace: ``count`` jobs whose
    scripts are the ``stream_jobs`` heavy-tailed mix (``dup_rate``
    controls the Zipf duplicate share the memo plane serves for free),
    arriving at Poisson times — exponential inter-arrivals of mean
    ``1/rate`` jobs per stream step, accumulated and floored onto the
    integer step clock, so arrivals are independent of service (open
    loop). Tenants are assigned Zipf-style (weight 1/(t+1): tenant 0 is
    the heaviest, the multi-tenant fairness stress), priorities uniformly
    over ``priorities`` classes, and each job's absolute deadline is its
    arrival plus a uniform slack from ``deadline_slack``. Deterministic
    in ``seed``: two calls with equal arguments produce byte-identical
    traces (the serve kill->resume path replans from this property).
    Returned in arrival order (ties keep job order)."""
    if count < 1:
        raise ValueError("count must be >= 1")
    if rate <= 0.0:
        raise ValueError("rate must be > 0 (jobs per stream step)")
    if tenants < 1 or priorities < 1:
        raise ValueError("tenants and priorities must be >= 1")
    lo, hi = int(deadline_slack[0]), int(deadline_slack[1])
    if not 0 < lo <= hi:
        raise ValueError("deadline_slack must be 0 < lo <= hi")
    jobs = stream_jobs(spec, count, seed, base_phases=base_phases,
                       tail_alpha=tail_alpha, max_phases=max_phases,
                       amount=amount, snapshots_per_job=snapshots_per_job,
                       dup_rate=dup_rate)
    rng = random.Random(seed + 0x5E12E)
    tweights = [1.0 / (t + 1) for t in range(tenants)]
    clock = 0.0
    reqs: List[ServeRequest] = []
    for j, ev in enumerate(jobs):
        clock += rng.expovariate(rate)
        arrival = int(clock)
        reqs.append(ServeRequest(
            job=j, arrival_step=arrival,
            tenant=rng.choices(range(tenants), weights=tweights)[0],
            priority=rng.randrange(priorities),
            deadline_step=arrival + rng.randint(lo, hi),
            events=ev))
    return reqs


def burst_workload(spec: TopologySpec, count: int, seed: int,
                   rate: float = 1.0, burst_period: int = 32,
                   burst_factor: float = 8.0,
                   **kwargs) -> List[ServeRequest]:
    """A bursty open-loop serving trace for the fleet's load-shedding
    and degraded-mode scenarios: the ``serve_workload`` request mix with
    its Poisson arrivals re-timed by an ON/OFF modulated rate — during
    the first half of each ``burst_period`` steps arrivals come
    ``burst_factor``x faster than ``rate``, during the second half
    ``burst_factor``x slower, so backlog builds in deterministic waves
    instead of a smooth trickle. Per-request deadline SLACK is preserved
    (deadlines ride the re-timed arrivals), tenants/priorities/scripts
    are untouched. Deterministic in ``seed``; extra kwargs forward to
    ``serve_workload``."""
    if burst_period < 2:
        raise ValueError("burst_period must be >= 2 steps")
    if burst_factor <= 1.0:
        raise ValueError("burst_factor must be > 1")
    reqs = serve_workload(spec, count, seed, rate=rate, **kwargs)
    rng = random.Random(seed + 0xB125)
    clock = 0.0
    out: List[ServeRequest] = []
    for r in reqs:
        on = (int(clock) % int(burst_period)) < int(burst_period) // 2
        eff = rate * burst_factor if on else rate / burst_factor
        clock += rng.expovariate(eff)
        arrival = int(clock)
        slack = r.deadline_step - r.arrival_step
        out.append(r._replace(arrival_step=arrival,
                              deadline_step=arrival + slack))
    return out


def crash_schedule(kills: int, period_s: float,
                   start_s: float = 1.0) -> List[float]:
    """Deterministic worker-kill times (elapsed seconds) for
    serving/fleet.fleet_run's injected crash schedule — the degraded-
    mode SLO arm SIGKILLs one live worker at each returned instant:
    the first at ``start_s``, then every ``period_s``."""
    if kills < 0:
        raise ValueError("kills must be >= 0")
    if period_s <= 0 or start_s < 0:
        raise ValueError("period_s must be > 0 and start_s >= 0")
    return [start_s + k * period_s for k in range(kills)]


class StormProgram(NamedTuple):
    """Compiled storm traffic: T phases, each = bulk sends + snapshot
    initiations + one tick."""

    amounts: Any   # i32 [T, E] tokens to send on each edge this phase
    snap: Any      # i32 [T, J] initiator node index, -1 = none


def storm_program(topo: DenseTopology, phases: int, amount: int = 1,
                  snapshot_phases: Optional[Sequence[Tuple[int, int]]] = None,
                  ) -> StormProgram:
    """Every phase, every node sends ``amount`` on one outbound edge,
    cycling round-robin over its out-links; ``snapshot_phases`` is
    [(phase, node_index), ...]. Initial balances must cover phases*amount
    per node (generators default to 100; the storm runner checks the
    underflow flag)."""
    t, e, n = phases, topo.e, topo.n
    amounts = np.zeros((t, e), np.int32)
    out_edges = [list(row[row >= 0]) for row in topo.edge_table]
    for ph in range(t):
        for node in range(n):
            oe = out_edges[node]
            if oe:
                amounts[ph, oe[ph % len(oe)]] += amount
    sched = list(snapshot_phases or [])
    per_phase: List[List[int]] = [[] for _ in range(t)]
    for ph, node in sched:
        if not 0 <= ph < t:
            raise ValueError(
                f"snapshot scheduled at phase {ph}, but the program has "
                f"only {t} phases (raise phases or tighten the schedule)")
        per_phase[ph].append(node)
    j = max((len(p) for p in per_phase), default=0) or 1
    snap = np.full((t, j), -1, np.int32)
    for ph, nodes in enumerate(per_phase):
        snap[ph, :len(nodes)] = nodes
    return StormProgram(amounts, snap)


def staggered_snapshots(topo: DenseTopology, count: int,
                        start_phase: int = 0, stride: int = 1,
                        max_phases: Optional[int] = None,
                        ) -> List[Tuple[int, int]]:
    """The 10nodes.events pattern: snapshot k initiated by node k at phase
    start + k*stride. With ``max_phases``, the stride shrinks (floor 1) and
    the schedule wraps so every initiation fits a ``max_phases``-phase
    program."""
    if max_phases is not None:
        if max_phases < 1:
            raise ValueError("max_phases must be >= 1")
        start_phase = min(start_phase, max_phases - 1)
        if count > 1:
            stride = max(min(stride, (max_phases - 1 - start_phase)
                             // (count - 1)), 1)
    sched = [(start_phase + k * stride, k % topo.n) for k in range(count)]
    if max_phases is not None:
        sched = [(ph % max_phases, node) for ph, node in sched]
        # wrapping can alias two entries onto the same (phase, node); the
        # sync scheduler's boolean init mask would silently coalesce them
        # (and diverge from the exact scheduler, which injects a list) —
        # dedupe here so both schedulers see the identical schedule
        seen, unique = set(), []
        for item in sched:
            if item not in seen:
                seen.add(item)
                unique.append(item)
        sched = unique
    return sched
