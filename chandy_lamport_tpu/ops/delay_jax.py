"""JAX-side message-delay samplers for the dense/batched kernels.

The delay model is the seam between the bit-exact path and the fast batched
path (SURVEY.md §5): the reference's only nondeterminism is
``receiveTime = time + 1 + rand.Intn(maxDelay)`` drawn from Go's global PRNG
(reference sim.go:100-102, snapshot_test.go:20).

Each sampler carries its own state inside the simulation pytree and exposes
``draw(dstate, time) -> (receive_time, dstate)`` callable under jit:

  - GoExactJaxDelay    bit-exact Go stream (draw-order sensitive, needs x64)
  - FixedJaxDelay      constant delay (unit tests, docs)
  - UniformJaxDelay    counter-based threefry uniform {1..max_delay} — same
                       distribution as the reference, different stream
  - HashJaxDelay       counter-hash uniform {1..max_delay} — same
                       distribution again, but a few fused VPU ops instead
                       of a materialized threefry tensor; the default fast
                       path for batched/TPU runs (bench/storm --delay)

``from_host_model`` maps the host-side models (models/delay.py) to their JAX
twins so ``DenseSim`` accepts the same DelayModel objects as the parity
backend.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from chandy_lamport_tpu.config import MAX_DELAY
from chandy_lamport_tpu.models.delay import (
    DelayModel,
    FixedDelay,
    GoExactDelay,
)
from chandy_lamport_tpu.ops import gorand_jax


class JaxDelay:
    """Protocol: stateless sampler object, state lives in the sim pytree.
    ``max_delay`` bounds the sampled delay — it sizes the post-drain flush
    (test_common.go:135-137 flushes maxDelay+1 ticks)."""

    max_delay: int

    # True when a draw's VALUE depends only on its stream POSITION, not on
    # the wall-clock order positions are consumed in (a pure function of
    # (state, position)). The wave-exact tick (ops/tick._wave_tick) needs
    # this: it precomputes every marker-broadcast draw's fold-order
    # position at tick start and serves them out of order via
    # block_receive_times, which is only stream-identical to sequential
    # draw() calls for position-addressable samplers. False for the chained
    # generators (GoExact's vendored stream, Uniform's split chain).
    position_streams = False

    def init_state(self) -> Any:
        raise NotImplementedError

    def draw(self, dstate: Any, time: jnp.ndarray) -> Tuple[jnp.ndarray, Any]:
        raise NotImplementedError

    def block_receive_times(self, dstate: Any, time,
                            offsets: jnp.ndarray) -> jnp.ndarray:
        """Receive times for draws at stream positions ``current + offsets``
        (any shape, any order, duplicates allowed for masked-out elements)
        WITHOUT advancing the stream — pair with ``advance_draws``. Only
        meaningful when ``position_streams`` is True; bit-identical to
        issuing the draws sequentially in offset order."""
        raise NotImplementedError(
            f"{type(self).__name__} draws are order-dependent; "
            "block draws need a position-addressable sampler "
            "(FixedJaxDelay, HashJaxDelay)")

    def advance_draws(self, dstate: Any, count) -> Any:
        """Advance the stream past ``count`` draws served (or about to be
        served) by block_receive_times; bit-identical to the state after
        ``count`` sequential draw() calls."""
        raise NotImplementedError(
            f"{type(self).__name__} draws are order-dependent; "
            "block draws need a position-addressable sampler "
            "(FixedJaxDelay, HashJaxDelay)")

    def draw_many(self, dstate: Any, time, shape) -> Tuple[jnp.ndarray, Any]:
        """receive times of the given shape (int or tuple) at once — the bulk
        injection fast path. Default is a sequential scan of draw()
        preserving stream order; counter-based samplers override with one
        vectorized draw."""
        from jax import lax

        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        n = 1
        for d in shape:
            n *= d

        def step(d, _):
            rt, d = self.draw(d, time)
            return d, rt

        dstate, rts = lax.scan(step, dstate, None, length=n)
        return rts.reshape(shape), dstate

    def init_batch_state(self, batch: int) -> Any:
        """Per-lane state for a [batch]-wide vmap run. Default broadcasts
        one state to every lane (correct only for samplers whose stream is
        shared by design, e.g. the Go-exact conformance stream); samplers
        meant for independent lanes override this to derive a distinct
        stream per lane."""
        one = self.init_state()
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (batch,) + jnp.shape(x)), one)


class GoExactJaxDelay(JaxDelay):
    """Bit-exact reference delays (reference sim.go:100-102) under jit.

    Seeding happens on the host via ops/gorand.py (which owns the vendored
    rngCooked table); the seeded generator state is carried as
    ``(vec u64[607], tap, feed)``. Requires jax_enable_x64.
    """

    def __init__(self, host_rng_seed: int, max_delay: int = MAX_DELAY, **gorand_kwargs):
        from chandy_lamport_tpu.ops.gorand import GoRand

        self._host = GoRand(host_rng_seed, **gorand_kwargs)
        self.max_delay = max_delay

    def init_state(self):
        gorand_jax.require_x64()
        vec, tap, feed = self._host.state_arrays()
        return (jnp.asarray(vec, jnp.uint64), jnp.int32(tap), jnp.int32(feed))

    def draw(self, dstate, time):
        d, dstate = gorand_jax.intn(dstate, self.max_delay)
        return time + 1 + d, dstate


class FixedJaxDelay(JaxDelay):
    position_streams = True  # every position draws the same constant

    def __init__(self, delay: int = 1):
        if delay < 1:
            raise ValueError("delay must be >= 1")
        self.delay = delay
        self.max_delay = delay

    def init_state(self):
        return ()

    def draw(self, dstate, time):
        return time + self.delay, dstate

    def block_receive_times(self, dstate, time, offsets):
        return jnp.broadcast_to(jnp.asarray(time + self.delay, jnp.int32),
                                jnp.shape(offsets))

    def advance_draws(self, dstate, count):
        return dstate


class UniformJaxDelay(JaxDelay):
    """Uniform delay in {1..max_delay}, counter-based ``jax.random`` stream.

    Distribution-identical to the reference's ``1 + Intn(maxDelay)`` but a
    different stream — the fast path for batched TPU runs. vmap-safe: fold a
    distinct instance id into the seed per lane.
    """

    def __init__(self, seed: int, max_delay: int = MAX_DELAY):
        self.seed = seed
        self.max_delay = max_delay

    def init_state(self):
        return jax.random.PRNGKey(self.seed)

    def draw(self, dstate, time):
        key, sub = jax.random.split(dstate)
        d = jax.random.randint(sub, (), 0, self.max_delay, dtype=jnp.int32)
        return time + 1 + d, key

    def draw_many(self, dstate, time, shape):
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        key, sub = jax.random.split(dstate)
        d = jax.random.randint(sub, shape, 0, self.max_delay, dtype=jnp.int32)
        return time + 1 + d, key

    def init_batch_state(self, batch):
        base = jax.random.PRNGKey(self.seed)
        return jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(batch, dtype=jnp.uint32))


def _lowbias32(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer-style mixer (public-domain "lowbias32" constants) —
    3 shifts + 2 wrapping multiplies, vs threefry's 20 rounds. Quality is
    ample for a {1..max_delay} delay draw; it is NOT a crypto PRNG."""
    x = jnp.asarray(x, jnp.uint32)
    x ^= x >> 16
    x *= jnp.uint32(0x7FEB352D)
    x ^= x >> 15
    x *= jnp.uint32(0x846CA68B)
    x ^= x >> 16
    return x


class HashJaxDelay(JaxDelay):
    """Uniform delay in {1..max_delay} from a counter-based integer hash.

    Same distribution as UniformJaxDelay (modulo bias < 2^-29 for
    max_delay=5), different stream, ~an order of magnitude cheaper: the
    threefry path materializes a full [S, E] (or [B, S, E]) word tensor per
    tick through HBM, while this mixer is a handful of VPU ops that XLA
    fuses straight into the receive-time consumer — no intermediate tensor.

    State is ``(key u32, counter u32, epoch u32)``; a draw hashes the
    counter through two mix rounds with the key — XORed with the mixed
    epoch — injected between them (``mix(mix(ctr) ^ key ^ mix(epoch))``).
    Every element of every draw gets a distinct counter, so draws are
    reproducible; init_batch_state gives each vmap lane the key
    ``base_key ^ lane·odd`` — an injective map, so no two lanes can ever
    share a key (and hence a stream), and lane 0 reproduces the
    single-instance stream exactly.

    The epoch word extends the per-lane period beyond 2^32 draws: when the
    counter wraps, the epoch increments and re-keys the stream instead of
    silently replaying it (at the bench shape a lane draws ~(S+1)·E words
    per tick, so 2^32 is reachable on long runs). Elements of one
    draw_many that straddle the wrap get the post-wrap epoch, keeping
    every (epoch, counter) pair unique. ``mix(0) == 0``, so epoch 0 is
    stream-identical to the pre-epoch format.
    """

    _LANE_MULT = 0x85EBCA6B  # odd -> lane -> key is injective mod 2^32
    position_streams = True  # value = hash(key, counter, epoch) only

    def __init__(self, seed: int, max_delay: int = MAX_DELAY):
        self.seed = seed
        self.max_delay = max_delay

    def _base_key(self):
        # mask before uint32(): negative / wide Python ints raise
        # OverflowError under NumPy 2.x, and the CLI accepts any int seed
        return _lowbias32(jnp.uint32((self.seed ^ 0x9E3779B9) & 0xFFFFFFFF))

    def init_state(self):
        return (self._base_key(), jnp.uint32(0), jnp.uint32(0))

    def _delays(self, key, idx, epoch):
        return (_lowbias32(_lowbias32(idx) ^ key ^ _lowbias32(epoch))
                % jnp.uint32(self.max_delay)).astype(jnp.int32)

    def draw(self, dstate, time):
        key, ctr, epoch = dstate
        new_ctr = ctr + jnp.uint32(1)
        return (time + 1 + self._delays(key, ctr, epoch),
                (key, new_ctr, epoch + (new_ctr == 0)))

    def draw_many(self, dstate, time, shape):
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        key, ctr, epoch = dstate
        n = 1
        for dim in shape:
            n *= dim
        idx = ctr + jnp.arange(n, dtype=jnp.uint32).reshape(shape)
        # elements past a counter wrap belong to the next epoch, so every
        # (epoch, counter) pair stays unique across the wrap
        elem_epoch = epoch + (idx < ctr)
        new_ctr = ctr + jnp.uint32(n)
        return (time + 1 + self._delays(key, idx, elem_epoch),
                (key, new_ctr, epoch + (new_ctr < ctr)))

    def block_receive_times(self, dstate, time, offsets):
        # same (epoch, counter) assignment as draw_many's wrap rule, so
        # serving positions out of order cannot change any value
        key, ctr, epoch = dstate
        idx = ctr + jnp.asarray(offsets, jnp.uint32)
        elem_epoch = epoch + (idx < ctr)
        return time + 1 + self._delays(key, idx, elem_epoch)

    def advance_draws(self, dstate, count):
        key, ctr, epoch = dstate
        new_ctr = ctr + jnp.asarray(count, jnp.uint32)
        return (key, new_ctr, epoch + (new_ctr < ctr))

    def init_batch_state(self, batch):
        lane_key = self._base_key() ^ (
            jnp.arange(batch, dtype=jnp.uint32) * jnp.uint32(self._LANE_MULT))
        return (lane_key, jnp.zeros(batch, jnp.uint32),
                jnp.zeros(batch, jnp.uint32))


def make_fast_delay(name: str, seed: int,
                    max_delay: int = MAX_DELAY) -> JaxDelay:
    """The CLI/bench ``--delay`` choices: "uniform" (threefry) or "hash"
    (fused counter-hash)."""
    if name == "uniform":
        return UniformJaxDelay(seed, max_delay)
    if name == "hash":
        return HashJaxDelay(seed, max_delay)
    raise ValueError(f"unknown fast delay sampler {name!r}")


def from_host_model(model: DelayModel) -> JaxDelay:
    """Map a host-side DelayModel to its JAX twin (same stream where the
    model is reproducible: GoExactDelay re-seeds a fresh GoRand from the
    recorded seed, FixedDelay is stateless)."""
    if isinstance(model, GoExactDelay):
        return GoExactJaxDelay(model.seed, model.max_delay, **model.gorand_kwargs)
    if isinstance(model, FixedDelay):
        return FixedJaxDelay(model.delay)
    raise TypeError(
        f"no JAX twin for delay model {type(model).__name__}; "
        "pass a JaxDelay directly")
