"""Bit-exact reimplementation of Go's legacy ``math/rand`` generator.

The reference's only randomness is ``rand.Intn(5)`` draws from Go's global
PRNG seeded once per test (reference sim.go:100-102, snapshot_test.go:20).
Reproducing the 21 golden fixtures therefore requires this generator exactly:

  - ``rngSource``: 607-lag / 273-tap additive lagged-Fibonacci over Z/2^64;
    Uint64: tap--, feed-- (mod 607), vec[feed] += vec[tap], return vec[feed].
  - ``Seed``: Schrage-LCG chain (A=48271, Q=44488, R=3399, M=2^31-1), seed
    reduced mod M (0 -> 89482311), 20 warm-up draws, then per slot three
    draws packed ``x<<s1 ^ x<<s2 ^ x`` XORed with the 607-entry ``rngCooked``
    table.
  - ``Int63 = Uint64 & (2^63-1)``; ``Int31 = Int63 >> 32``; ``Int31n(n)``
    rejection-samples (reject v > 2^31-1 - 2^31%n) then ``v % n``;
    ``Intn(n) = Int31n(n)`` for n < 2^31.

``rngCooked`` is generated data, regenerated from scratch by
``tools/gen_cooked.py`` (matrix exponentiation of the linear recurrence) and
validated against the golden fixtures; the winning table is vendored at
``chandy_lamport_tpu/data/gorand_cooked.npy``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

_LEN = 607
_TAP = 273
_FEED0 = _LEN - _TAP  # 334
_MASK64 = (1 << 64) - 1
_MASK63 = (1 << 63) - 1
_A, _M, _Q, _R = 48271, (1 << 31) - 1, 44488, 3399

_COOKED_PATH = os.path.join(os.path.dirname(__file__), "..", "data", "gorand_cooked.npy")
_cooked_cache: Optional[Tuple[int, ...]] = None


def load_cooked_table() -> Tuple[int, ...]:
    """The vendored, golden-validated rngCooked table as python ints."""
    global _cooked_cache
    if _cooked_cache is None:
        arr = np.load(_COOKED_PATH)
        _cooked_cache = tuple(int(x) for x in arr)
    return _cooked_cache


def seedrand(x: int) -> int:
    """One Lehmer LCG step via Schrage's trick (x' = 48271*x mod 2^31-1)."""
    hi, lo = divmod(x, _Q)
    x = _A * lo - _R * hi
    if x < 0:
        x += _M
    return x


class GoRand:
    """Stateful generator matching Go ``math/rand`` bit for bit.

    ``cooked``/``seed_shifts`` are overridable only for the table-search
    tooling; normal use is ``GoRand(seed)``.
    """

    def __init__(
        self,
        seed: int,
        cooked: Optional[Sequence[int]] = None,
        seed_shifts: Tuple[int, int] = (40, 20),
    ):
        self._cooked = tuple(int(c) & _MASK64 for c in cooked) if cooked is not None \
            else load_cooked_table()
        self._shifts = seed_shifts
        self._vec = [0] * _LEN
        self._tap = 0
        self._feed = _FEED0
        self.seed(seed)

    def seed(self, seed: int) -> None:
        s1, s2 = self._shifts
        self._tap = 0
        self._feed = _FEED0
        # Go truncates then adds _M if negative; python floor-mod lands on the
        # same representative in [0, _M) directly.
        seed %= _M
        if seed == 0:
            seed = 89482311
        x = seed
        vec = self._vec
        cooked = self._cooked
        for i in range(-20, _LEN):
            x = seedrand(x)
            if i >= 0:
                u = (x << s1) & _MASK64
                x = seedrand(x)
                u ^= (x << s2) & _MASK64
                x = seedrand(x)
                u ^= x
                u ^= cooked[i]
                vec[i] = u

    def uint64(self) -> int:
        self._tap -= 1
        if self._tap < 0:
            self._tap += _LEN
        self._feed -= 1
        if self._feed < 0:
            self._feed += _LEN
        x = (self._vec[self._feed] + self._vec[self._tap]) & _MASK64
        self._vec[self._feed] = x
        return x

    def int63(self) -> int:
        return self.uint64() & _MASK63

    def int31(self) -> int:
        return self.int63() >> 32

    def int31n(self, n: int) -> int:
        if n <= 0:
            raise ValueError("invalid argument to int31n")
        if n & (n - 1) == 0:
            return self.int31() & (n - 1)
        vmax = (1 << 31) - 1 - (1 << 31) % n
        v = self.int31()
        while v > vmax:
            v = self.int31()
        return v % n

    def intn(self, n: int) -> int:
        if n <= 0:
            raise ValueError("invalid argument to intn")
        if n >= 1 << 31:
            raise NotImplementedError("intn for n >= 2^31 not needed by the framework")
        return self.int31n(n)

    def state_arrays(self) -> Tuple[np.ndarray, int, int]:
        """Export (vec, tap, feed) for the JAX kernel's PRNG-in-carry state."""
        return np.array(self._vec, dtype=np.uint64), self._tap, self._feed
