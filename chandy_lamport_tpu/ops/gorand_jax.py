"""Go ``math/rand`` as pure JAX functions — the PRNG-in-carry for jit kernels.

The host-side twin (ops/gorand.py) owns seeding and the vendored rngCooked
table; this module only advances an already-seeded state under ``jit``:
state = ``(vec u64[607], tap i32, feed i32)`` exported by
``GoRand.state_arrays()``.

Semantics replicated from the reference's randomness root (the only PRNG in
the system, reference sim.go:100-102):
  - Uint64: 607-lag/273-tap additive lagged Fibonacci over Z/2^64 — tap and
    feed decrement mod 607, ``vec[feed] += vec[tap]``, return ``vec[feed]``.
  - Int63 = Uint64 & (2^63-1); Int31 = Int63 >> 32.
  - Int31n(n): power-of-two fast path, else rejection-sample
    (reject v > 2^31-1 - 2^31 % n) then ``v % n``. For the reference's only
    call site, ``Intn(5)``, rejection fires with probability 3/2^31.

Requires ``jax_enable_x64`` (uint64 arithmetic). The fast batched path uses
counter-based ``jax.random`` instead (ops/delay_jax.py) and needs no x64.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

LEN = 607

GoRandState = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # (vec, tap, feed)


def require_x64() -> None:
    """The lagged-Fibonacci recurrence is over Z/2^64; without x64 JAX
    silently truncates to uint32 and the stream (and every golden) diverges."""
    import jax

    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "the bit-exact Go PRNG path requires 64-bit integers: call "
            "jax.config.update('jax_enable_x64', True) before building the "
            "kernel (the fast batched path, ops/delay_jax.UniformJaxDelay, "
            "does not need x64)")


def uint64(state: GoRandState) -> Tuple[jnp.ndarray, GoRandState]:
    """One lagged-Fibonacci step; uint64 addition wraps mod 2^64 natively."""
    vec, tap, feed = state
    tap = (tap - 1) % LEN
    feed = (feed - 1) % LEN
    x = vec[feed] + vec[tap]
    vec = vec.at[feed].set(x)
    return x, (vec, tap, feed)


def _int31(state: GoRandState) -> Tuple[jnp.ndarray, GoRandState]:
    x, state = uint64(state)
    v = ((x & jnp.uint64((1 << 63) - 1)) >> jnp.uint64(32)).astype(jnp.int32)
    return v, state


def intn(state: GoRandState, n: int) -> Tuple[jnp.ndarray, GoRandState]:
    """Go ``Intn(n)`` for static python ``0 < n < 2^31``."""
    if not 0 < n < (1 << 31):
        raise ValueError(f"intn requires 0 < n < 2^31, got {n}")
    if n & (n - 1) == 0:
        v, state = _int31(state)
        return v & (n - 1), state
    vmax = jnp.int32((1 << 31) - 1 - (1 << 31) % n)
    v, state = _int31(state)

    def cond(carry):
        v, _ = carry
        return v > vmax

    def body(carry):
        _, s = carry
        return _int31(s)

    v, state = lax.while_loop(cond, body, (v, state))
    return v % n, state
