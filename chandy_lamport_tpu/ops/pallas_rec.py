"""Pallas TPU kernel for the recorded-message append (SURVEY.md §7.2.7).

The sync tick appends at most one amount per (snapshot, edge) column of
``rec_data[S, E, M]`` per tick (HandleToken, reference node.go:174-185). The
XLA formulation is a dense masked select that rewrites the ENTIRE buffer
every tick — measured 5.3 ms/tick at the bench shape (17% of tick time,
BASELINE.md op profile) even though only ~N of the S*E columns can change.

XLA cannot skip data-dependently; Pallas can. This kernel:

  - tiles rec_data into [TILE_E, M] blocks that stay in HBM (no automatic
    block pipeline — the whole point is NOT moving clean blocks);
  - receives a scalar-prefetched per-(slot, tile) dirty bitmap, computed
    by the caller as a cheap [S, nTiles] any-reduction of the record mask;
  - aliases the input buffer to the output (in-place), so a clean block's
    grid step executes NOTHING — zero HBM traffic;
  - for dirty blocks, DMAs the block (and its [TILE_E] metadata slices)
    into VMEM, applies the one-hot append, and DMAs the block back.

A ragged edge count is handled by OVERLAPPING the last tile (start clamped
to E - TILE_E): the append is a pure idempotent assignment, so columns
covered by two tiles converge to the same value.

Traffic collapses from S*E*M*itemsize per tick to (dirty tiles) x block
size — at the bench shape the dirty column fraction is ~N/(S*E) ~ 4%.

Exposed via ``SimConfig.use_pallas_rec`` (opt-in; TPU or interpret mode).
Numerics validated against the jnp formulation in tests/test_pallas_rec.py
on the CPU mesh with interpret=True.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_i32 = jnp.int32


def _kernel(tile_e, e_dim, dirty_ref, pos_ref, mask_ref, amt_ref,
            rec_in_ref, rec_out_ref):
    s = pl.program_id(0)
    t = pl.program_id(1)
    m = rec_in_ref.shape[-1]
    start = jnp.minimum(t * tile_e, e_dim - tile_e)

    @pl.when(dirty_ref[s, t] != 0)
    def _():
        def inner(rec_v, pos_v, mask_v, amt_v, sem):
            pltpu.make_async_copy(
                rec_in_ref.at[s, pl.ds(start, tile_e), :], rec_v, sem).start()
            pltpu.make_async_copy(
                rec_in_ref.at[s, pl.ds(start, tile_e), :], rec_v, sem).wait()
            for src, dst in ((pos_ref.at[s, pl.ds(start, tile_e)], pos_v),
                             (mask_ref.at[s, pl.ds(start, tile_e)], mask_v),
                             (amt_ref.at[0, pl.ds(start, tile_e)], amt_v)):
                pltpu.make_async_copy(src, dst, sem).start()
                pltpu.make_async_copy(src, dst, sem).wait()
            m_idx = jax.lax.broadcasted_iota(_i32, (tile_e, m), 1)
            # Insert the minor dim on the i32 vectors BEFORE comparing:
            # Mosaic only supports non-no-op minor-dim insertion for 32-bit
            # types, so an i1 [:, None] fails to compile on real TPUs.
            hit = (mask_v[:][:, None] != 0) & (m_idx == pos_v[:][:, None])
            amt_b = jnp.broadcast_to(amt_v[:][:, None], (tile_e, m))
            rec_v[:] = jnp.where(hit, amt_b.astype(rec_v.dtype), rec_v[:])
            out = rec_out_ref.at[s, pl.ds(start, tile_e), :]
            pltpu.make_async_copy(rec_v, out, sem).start()
            pltpu.make_async_copy(rec_v, out, sem).wait()

        pl.run_scoped(
            inner,
            pltpu.VMEM((tile_e, m), rec_in_ref.dtype),
            pltpu.VMEM((tile_e,), _i32),
            pltpu.VMEM((tile_e,), _i32),
            pltpu.VMEM((tile_e,), _i32),
            pltpu.SemaphoreType.DMA(()),
        )


@functools.partial(jax.jit, static_argnames=("tile_e", "interpret"),
                   donate_argnums=0)
def rec_append(rec_data, rec_len, rec_mask, amt_e, *, tile_e: int = 512,
               interpret: bool = False):
    """In-place-append ``amt_e[e]`` at ``rec_data[s, e, rec_len[s, e]]`` for
    every (s, e) with ``rec_mask[s, e]`` — skipping clean [tile_e, M] blocks
    entirely. The caller advances rec_len and raises the overflow flags (the
    kernel clips like the jnp path, so flagged-overflow states stay
    bit-identical to it).

    Shapes: rec_data [S, E, M], rec_len/rec_mask [S, E], amt_e [E];
    E >= tile_e (shrink tile_e for tiny graphs).
    """
    s_dim, e_dim, m_dim = rec_data.shape
    if e_dim < tile_e:
        raise ValueError(f"E={e_dim} < tile_e={tile_e}; shrink tile_e")
    n_tiles = pl.cdiv(e_dim, tile_e)
    pos = jnp.clip(rec_len, 0, m_dim - 1).astype(_i32)
    mask_i = rec_mask.astype(_i32)
    pad = n_tiles * tile_e - e_dim
    dirty = jnp.any(
        jnp.pad(rec_mask, ((0, 0), (0, pad))).reshape(
            s_dim, n_tiles, tile_e), axis=-1).astype(_i32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_dim, n_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # pos (manual DMA)
            pl.BlockSpec(memory_space=pl.ANY),  # mask
            pl.BlockSpec(memory_space=pl.ANY),  # amt [1, E]
            pl.BlockSpec(memory_space=pl.ANY),  # rec_data (HBM, aliased)
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
    )
    return pl.pallas_call(
        functools.partial(_kernel, tile_e, e_dim),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(rec_data.shape, rec_data.dtype),
        # operand indices include the scalar-prefetch arg: dirty=0, pos=1,
        # mask=2, amt=3, rec_data=4 — alias rec_data to the single output
        input_output_aliases={4: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(dirty, pos, mask_i, amt_e.astype(_i32)[None, :], rec_data)


def rec_append_reference(rec_data, rec_len, rec_mask, amt_e):
    """The jnp formulation (what TickKernel._sync_tick inlines) — the
    numeric ground truth for the kernel tests."""
    m = rec_data.shape[-1]
    pos = jnp.clip(rec_len, 0, m - 1)
    hit = rec_mask[:, :, None] & (
        jnp.arange(m, dtype=_i32)[None, None, :] == pos[:, :, None])
    return jnp.where(hit, amt_e.astype(rec_data.dtype)[None, :, None],
                     rec_data)
