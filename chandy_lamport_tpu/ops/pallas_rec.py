"""Pallas TPU kernel for the recorded-message append (SURVEY.md §7.2.7).

The sync tick appends at most one amount per (snapshot, edge) column of
``rec_data[S, M, E]`` per tick (HandleToken, reference node.go:174-185). The
XLA formulation is a dense masked select that rewrites the ENTIRE buffer
every tick — measured 5.3 ms/tick at the bench shape (the top line of the
BASELINE.md op profile) even though only ~N of the S*E columns can change.

XLA cannot skip data-dependently; Pallas can. This kernel:

  - tiles rec_data into [M, TILE_E] blocks that stay in HBM (no automatic
    block pipeline — the whole point is NOT moving clean blocks);
  - receives a scalar-prefetched per-(slot, tile) dirty bitmap, computed
    by the caller as a cheap [S, nTiles] any-reduction of the record mask;
  - aliases the input buffer to the output (in-place), so a clean block's
    grid step executes NOTHING — zero HBM traffic;
  - for dirty blocks, DMAs the block (and its [TILE_E] metadata slices)
    into VMEM, applies the one-hot append, and DMAs the block back.

Layout and alignment (why [S, M, E] and not [S, E, M]): Mosaic requires a
manually-DMA'd HBM slice to be lane-aligned — the sliced minor dim must be
a multiple of 128 and slice starts provably divisible by the tiling. With
the edge axis minor, every block start is ``t * tile_e`` (tile_e a multiple
of 128) and every block width a multiple of 128; M rides the sublane axis
(full dim, no constraint beyond M % 8 == 0). The [S, E, M] layout is
uncompilable on hardware (M=16 lanes) AND wastes 7/8 of each vector
register in the XLA fallback. Edges past the last 128-aligned boundary
(E % 128 of them) are handled by the caller with the jnp formulation — a
sub-1% slice.

Traffic collapses from S*E*M*itemsize per tick to (dirty tiles) x block
size — at the bench shape the dirty column fraction is ~N/(S*E) ~ 4%.

Exposed via ``SimConfig.use_pallas_rec`` (opt-in; TPU or interpret mode).
Numerics validated against the jnp formulation in tests/test_pallas_rec.py
on the CPU mesh with interpret=True.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_i32 = jnp.int32
_LANE = 128  # TPU vector lane count — the kernel's edge-axis granularity


def _kernel(tile_e, e_kernel, dirty_ref, posm_ref, amt_ref,
            rec_in_ref, rec_out_ref):
    s = pl.program_id(0)
    t = pl.program_id(1)
    m = rec_in_ref.shape[1]
    n_full = e_kernel // tile_e
    tail = e_kernel - n_full * tile_e
    start = t * tile_e  # every block start is tile_e-aligned by construction

    def block(width):
        """Process edges [start, start+width) for a static width (tile_e
        for full blocks, the 128-aligned remainder for the final block)."""
        def inner(rec_v, sem):
            pltpu.make_async_copy(
                rec_in_ref.at[s, :, pl.ds(start, width)], rec_v, sem).start()
            pltpu.make_async_copy(
                rec_in_ref.at[s, :, pl.ds(start, width)], rec_v, sem).wait()
            # metadata arrives via the automatic BlockSpec pipeline (tiny
            # (1, tile_e) tiles — always fetched, ~1% of the rec block);
            # only the big rec buffer uses manual skipping DMA, because
            # Mosaic's manual-DMA alignment rules reject single-row slices
            # of sublane-tiled 2D arrays and sub-1024 slices of 1D arrays.
            posm_v = posm_ref[0, 0, pl.ds(0, width)]
            amt_v = amt_ref[0, pl.ds(0, width)]
            m_idx = jax.lax.broadcasted_iota(_i32, (m, width), 0)
            # [None, :] inserts a MAJOR (sublane) dim — cheap broadcast;
            # a minor-dim insertion on non-32-bit types fails Mosaic. The
            # mask is packed into posm as the sentinel M (m_idx < M never
            # matches), so one comparison does hit-and-mask at once.
            hit = m_idx == posm_v[None, :]
            amt_b = jnp.broadcast_to(amt_v[None, :], (m, width))
            rec_v[:] = jnp.where(hit, amt_b.astype(rec_v.dtype), rec_v[:])
            out = rec_out_ref.at[s, :, pl.ds(start, width)]
            pltpu.make_async_copy(rec_v, out, sem).start()
            pltpu.make_async_copy(rec_v, out, sem).wait()

        pl.run_scoped(
            inner,
            pltpu.VMEM((m, width), rec_in_ref.dtype),
            pltpu.SemaphoreType.DMA(()),
        )

    dirty = dirty_ref[s, t] != 0
    if n_full:
        @pl.when(dirty & (t < n_full))
        def _():
            block(tile_e)
    if tail:
        @pl.when(dirty & (t == n_full))
        def _():
            block(tail)


@functools.partial(jax.jit, static_argnames=("tile_e", "interpret"),
                   donate_argnums=0)
def rec_append(rec_data, rec_len, rec_mask, amt_e, *, tile_e: int = 512,
               interpret: bool = False):
    """In-place-append ``amt_e[e]`` at ``rec_data[s, rec_len[s, e], e]`` for
    every (s, e) with ``rec_mask[s, e]`` — skipping clean [M, tile_e] blocks
    entirely. The caller advances rec_len and raises the overflow flags (the
    kernel clips like the jnp path, so flagged-overflow states stay
    bit-identical to it).

    Shapes: rec_data [S, M, E], rec_len/rec_mask [S, E], amt_e [E]. Any E:
    the kernel covers the first E - E%128 edges (128-aligned blocks; tile_e
    must be a multiple of 128 for hardware); the ragged remainder goes
    through the jnp formulation. M must be a multiple of 8 (sublane tile).
    """
    s_dim, m_dim, e_dim = rec_data.shape
    if tile_e % _LANE:
        raise ValueError(f"tile_e={tile_e} must be a multiple of {_LANE}")
    if m_dim % 8:
        raise ValueError(
            f"max_recorded={m_dim} must be a multiple of 8 for the Pallas "
            "rec kernel; round it up or disable use_pallas_rec")
    e_kernel = (e_dim // _LANE) * _LANE
    pos = jnp.clip(rec_len, 0, m_dim - 1).astype(_i32)
    amt_i = amt_e.astype(_i32)

    if e_kernel:
        n_tiles = pl.cdiv(e_kernel, tile_e)
        pad = n_tiles * tile_e - e_kernel
        dirty = jnp.any(
            jnp.pad(rec_mask[:, :e_kernel], ((0, 0), (0, pad))).reshape(
                s_dim, n_tiles, tile_e), axis=-1).astype(_i32)
        # mask packed into pos via the sentinel M (m_idx < M never matches);
        # the singleton middle dim satisfies the block-shape rule (last two
        # block dims must divide 8/128 or equal the array dims)
        posm = jnp.where(rec_mask, pos, m_dim)[:, None, :]

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(s_dim, n_tiles),
            in_specs=[
                # metadata rides the automatic pipeline in tile_e-wide
                # tiles; index_map args: grid indices then scalar-prefetch
                pl.BlockSpec((1, 1, tile_e), lambda s, t, *_: (s, 0, t)),
                pl.BlockSpec((1, tile_e), lambda s, t, *_: (0, t)),
                pl.BlockSpec(memory_space=pl.ANY),  # rec_data (HBM, aliased)
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
        )
        rec_data = pl.pallas_call(
            functools.partial(_kernel, tile_e, e_kernel),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct(rec_data.shape, rec_data.dtype),
            # operand indices include the scalar-prefetch arg: dirty=0,
            # posm=1, amt=2, rec_data=3 — alias rec_data to the single
            # output
            input_output_aliases={3: 0},
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
            interpret=interpret,
        )(dirty, posm, amt_i[None, :], rec_data)

    if e_kernel < e_dim:
        # ragged remainder (< 128 edges): the jnp formulation on the tail
        # slice only — an in-place dynamic-update-slice under donation
        upd = rec_append_reference(rec_data[:, :, e_kernel:],
                                   rec_len[:, e_kernel:],
                                   rec_mask[:, e_kernel:],
                                   amt_e[e_kernel:])
        rec_data = rec_data.at[:, :, e_kernel:].set(upd)
    return rec_data


def rec_append_reference(rec_data, rec_len, rec_mask, amt_e):
    """The jnp formulation (what TickKernel._sync_tick inlines) — the
    numeric ground truth for the kernel tests. Shapes as in rec_append."""
    m = rec_data.shape[1]
    pos = jnp.clip(rec_len, 0, m - 1).astype(_i32)
    hit = rec_mask[:, None, :] & (
        jnp.arange(m, dtype=_i32)[None, :, None] == pos[:, None, :])
    return jnp.where(hit, amt_e.astype(rec_data.dtype)[None, None, :],
                     rec_data)
