"""The jitted simulation kernel: tick, event injection, drain.

This is the reference's hot loop (sim.go:71-95) plus the protocol handlers
(node.go:140-212) as one pure state transition over the dense arrays of
core/state.py. The five bit-exactness-critical rules (SURVEY.md §7.0) map to:

  R1 lexicographic order   -> node index = lexicographic rank; edges sorted
                              by (src, dest); all loops are index order.
  R2 one-delivery-per-source-per-tick, sequential fold with mid-tick marker
     cascades visible to later sources (sim.go:76-92)
                           -> ``lax.scan`` over source indices inside the
                              tick; within a source, the first eligible queue
                              head in dest order is a masked argmax over its
                              padded edge row (scan past ineligible heads,
                              deliver at most one — sim.go:82-92).
  R3 per-channel FIFO + head-of-line blocking
                           -> ring buffers popped only at q_head.
  R4 PRNG draw order       -> delay draws happen exactly where the reference
                              draws (one per send node.go:130; one per
                              outbound link in dest order on broadcast
                              node.go:98-107), sequenced by ``lax.fori_loop``
                              /``lax.cond`` so skipped branches draw nothing.
  R5 snapshot id = allocation order (sim.go:107-108)
                           -> slot index == snapshot id.

Everything is shape-static; the topology is baked into the jitted closures as
constants. Batched execution vmaps these same functions over a leading
instance axis (parallel/batch.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import (
    DenseState,
    DenseTopology,
    ERR_QUEUE_OVERFLOW,
    ERR_RECORD_OVERFLOW,
    ERR_SNAPSHOT_OVERFLOW,
    ERR_TICK_LIMIT,
    ERR_TOKEN_UNDERFLOW,
)
from chandy_lamport_tpu.ops.delay_jax import JaxDelay

_i32 = jnp.int32


class TickKernel:
    """Jitted closures over a fixed (topology, config, delay sampler).

    Public jitted entry points (all take/return DenseState):
      tick(s)                 one simulation tick (sim.go:71-95)
      run_ticks(s, n)         n ticks under one dispatch
      inject_send(s, e, amt)  PassTokenEvent on edge e (node.go:112-131)
      inject_snapshot(s, nd)  SnapshotEvent at node nd (sim.go:105-123)
      drain_and_flush(s)      tick until every started snapshot completes,
                              then max_delay+1 flush ticks (test_common.go:124-137)
    """

    def __init__(self, topo: DenseTopology, cfg: SimConfig, delay: JaxDelay):
        self.topo = topo
        self.cfg = cfg
        self.delay = delay
        # static topology constants baked into the traces
        self._edge_src = jnp.asarray(topo.edge_src)
        self._edge_dst = jnp.asarray(topo.edge_dst)
        self._edge_table = jnp.asarray(topo.edge_table)
        self._in_degree = jnp.asarray(topo.in_degree)

        self._rows_e = jnp.arange(topo.e, dtype=_i32)
        self.tick = jax.jit(self._tick, donate_argnums=0)
        self.run_ticks = jax.jit(self._run_ticks, donate_argnums=0)
        self.inject_send = jax.jit(self._inject_send, donate_argnums=0)
        self.inject_snapshot = jax.jit(self._inject_snapshot, donate_argnums=0)
        self.drain_and_flush = jax.jit(self._drain_and_flush, donate_argnums=0)

    # ---- queue primitives ------------------------------------------------

    def _push(self, s: DenseState, e, is_marker: bool, data) -> DenseState:
        """Append to edge e's ring buffer with one delay draw
        (node.go:126-130 / node.go:104-108)."""
        rtime, dstate = self.delay.draw(s.delay_state, s.time)
        C = self.cfg.queue_capacity
        pos = (s.q_head[e] + s.q_len[e]) % C
        err = s.error | jnp.where(s.q_len[e] >= C, ERR_QUEUE_OVERFLOW, 0).astype(_i32)
        return s._replace(
            q_marker=s.q_marker.at[e, pos].set(is_marker),
            q_data=s.q_data.at[e, pos].set(jnp.asarray(data, _i32)),
            q_rtime=s.q_rtime.at[e, pos].set(jnp.asarray(rtime, _i32)),
            q_len=s.q_len.at[e].add(1),
            delay_state=dstate,
            error=err,
        )

    # ---- protocol handlers (node.go) ------------------------------------

    def _create_local(self, s: DenseState, sid, node, exclude_edge) -> DenseState:
        """CreateLocalSnapshot (node.go:58-84): freeze tokens, record all
        inbound links except the marker's own (exclude_edge == -1 for the
        initiator case)."""
        E = self.topo.e
        inbound = self._edge_dst == node
        rec_mask = inbound & (jnp.arange(E, dtype=_i32) != exclude_edge)
        links = self._in_degree[node] - jnp.asarray(exclude_edge >= 0, _i32)
        return s._replace(
            has_local=s.has_local.at[sid, node].set(True),
            frozen=s.frozen.at[sid, node].set(s.tokens[node]),
            rem=s.rem.at[sid, node].set(links),
            recording=s.recording.at[sid].set(
                jnp.where(rec_mask, True, s.recording[sid])),
        )

    def _broadcast_markers(self, s: DenseState, node, sid) -> DenseState:
        """SendToNeighbors (node.go:97-109): marker on every outbound link in
        dest order, one delay draw per real link (padding slots draw nothing)."""
        def body(k, s):
            e = self._edge_table[node, k]
            return lax.cond(e >= 0,
                            lambda s: self._push(s, e, True, sid),
                            lambda s: s, s)
        return lax.fori_loop(0, self.topo.d, body, s)

    def _finalize_check(self, s: DenseState, sid, node) -> DenseState:
        """finalizeSnapshot + NotifyCompletedSnapshot when no links remain
        recording (node.go:165-170). The message flattening itself is a
        decode-time gather — rec_data is already per-edge in arrival order."""
        fire = (s.has_local[sid, node] & (s.rem[sid, node] == 0)
                & ~s.done_local[sid, node])
        return s._replace(
            done_local=s.done_local.at[sid, node].set(
                s.done_local[sid, node] | fire),
            completed=s.completed.at[sid].add(jnp.asarray(fire, _i32)),
        )

    def _handle_marker(self, s: DenseState, e, sid) -> DenseState:
        """HandleMarker (node.go:149-171). First marker for sid at this node:
        create the local snapshot excluding the marker's link, then re-broadcast
        (node.StartSnapshot, node.go:198-212). Repeat marker: stop recording
        that link. Either way, check finalization after (R8)."""
        dst = self._edge_dst[e]

        def first(s):
            s = self._create_local(s, sid, dst, e)
            return self._broadcast_markers(s, dst, sid)

        def repeat(s):
            return s._replace(
                recording=s.recording.at[sid, e].set(False),
                rem=s.rem.at[sid, dst].add(-1),
            )

        s = lax.cond(~s.has_local[sid, dst], first, repeat, s)
        return self._finalize_check(s, sid, dst)

    def _handle_token(self, s: DenseState, e, amount) -> DenseState:
        """HandleToken (node.go:174-185): credit the destination, then append
        the amount to every snapshot slot still recording this edge —
        vectorized over all S slots at once."""
        S, M = self.cfg.max_snapshots, self.cfg.max_recorded
        dst = self._edge_dst[e]
        cond = s.recording[:, e]                       # [S]
        pos = jnp.clip(s.rec_len[:, e], 0, M - 1)      # [S]
        rows = jnp.arange(S)
        col = s.rec_data[:, e, :]                      # [S, M]
        col = col.at[rows, pos].set(
            jnp.where(cond, jnp.asarray(amount, _i32), col[rows, pos]))
        err = s.error | jnp.where(
            jnp.any(cond & (s.rec_len[:, e] >= M)), ERR_RECORD_OVERFLOW, 0
        ).astype(_i32)
        return s._replace(
            tokens=s.tokens.at[dst].add(jnp.asarray(amount, _i32)),
            rec_data=s.rec_data.at[:, e, :].set(col),
            rec_len=s.rec_len.at[:, e].add(cond.astype(_i32)),
            error=err,
        )

    def _deliver(self, s: DenseState, e) -> DenseState:
        """Pop edge e's head and dispatch (HandlePacket, node.go:140-146)."""
        C = self.cfg.queue_capacity
        slot = s.q_head[e]
        is_marker = s.q_marker[e, slot]
        data = s.q_data[e, slot]
        s = s._replace(q_head=s.q_head.at[e].set((slot + 1) % C),
                       q_len=s.q_len.at[e].add(-1))
        return lax.cond(is_marker,
                        lambda s: self._handle_marker(s, e, data),
                        lambda s: self._handle_token(s, e, data), s)

    # ---- the tick (sim.go:71-95) ----------------------------------------

    def _tick(self, s: DenseState) -> DenseState:
        s = s._replace(time=s.time + 1)

        def per_source(s, n):
            edges = self._edge_table[n]                     # [D], -1 padded
            valid = edges >= 0
            safe = jnp.where(valid, edges, 0)
            heads = s.q_head[safe]
            rts = s.q_rtime[safe, heads]
            elig = valid & (s.q_len[safe] > 0) & (rts <= s.time)
            found = jnp.any(elig)
            e = safe[jnp.argmax(elig)]                      # first in dest order
            s = lax.cond(found, lambda s: self._deliver(s, e), lambda s: s, s)
            return s, None

        s, _ = lax.scan(per_source, s, jnp.arange(self.topo.n, dtype=_i32))
        return s

    # ---- the synchronous tick (fast-path scheduler) ----------------------

    def _sync_tick(self, s: DenseState) -> DenseState:
        """The production scheduler: every source delivers its first eligible
        head simultaneously, with 'all tokens before all markers' ordering
        within the tick. A different — still deterministic — scheduler from
        the reference's sequential fold (sim.go:71-95): the set of delivered
        messages per tick is identical (first eligible head per source in
        dest order, per-channel FIFO and head-of-line blocking intact);
        delivery *interleaving* corresponds to the sequential schedule
        'all token deliveries, then markers grouped by snapshot id' instead
        of source-rank order. Every tick is a valid Chandy-Lamport execution
        step, so all protocol invariants (conservation, completion,
        consistent cuts) hold; only bit-exact golden reproduction needs
        _tick. Cost: O(E + S·E) vectorized work, no N-step sequential fold —
        this is what makes 1M-instance batches fast on TPU.
        """
        N, E, C = self.topo.n, self.topo.e, self.cfg.queue_capacity
        S, M = self.cfg.max_snapshots, self.cfg.max_recorded
        time = s.time + 1
        s = s._replace(time=time)
        rows = self._rows_e

        # choose at most one eligible head per source (first in dest order)
        heads = s.q_head
        head_rt = s.q_rtime[rows, heads]
        elig_e = (s.q_len > 0) & (head_rt <= time)                # [E]
        et = self._edge_table                                     # [N, D]
        valid_t = et >= 0
        safe_t = jnp.where(valid_t, et, 0)
        elig_t = valid_t & elig_e[safe_t]                         # [N, D]
        found_n = jnp.any(elig_t, axis=1)
        first_k = jnp.argmax(elig_t, axis=1)
        chosen_e = safe_t[jnp.arange(N), first_k]                 # [N]
        deliver_e = jnp.zeros(E, bool).at[chosen_e].max(found_n)  # [E]

        # pop all chosen heads at once
        popped_marker = s.q_marker[rows, heads]
        popped_data = s.q_data[rows, heads]
        s = s._replace(
            q_head=jnp.where(deliver_e, (heads + 1) % C, heads),
            q_len=s.q_len - deliver_e.astype(_i32),
        )

        # token deliveries: credit + record into snapshots still recording
        # at tick start (HandleToken, node.go:174-185, vectorized)
        tok_e = deliver_e & ~popped_marker
        amt_e = jnp.where(tok_e, popped_data, 0)
        s = s._replace(tokens=s.tokens + jax.ops.segment_sum(
            amt_e, self._edge_dst, num_segments=N))
        rec_mask = s.recording & tok_e[None, :]                   # [S, E]
        err = s.error | jnp.where(jnp.any(rec_mask & (s.rec_len >= M)),
                                  ERR_RECORD_OVERFLOW, 0).astype(_i32)
        pos = jnp.clip(s.rec_len, 0, M - 1)
        # scatter-add one element per (snapshot, edge) — slots past rec_len
        # are zero, so += lands the amount in the first free slot
        s = s._replace(
            rec_data=s.rec_data.at[
                jnp.arange(S)[:, None], rows[None, :], pos].add(
                jnp.where(rec_mask, amt_e[None, :], 0)),
            rec_len=s.rec_len + rec_mask.astype(_i32),
            error=err,
        )

        # marker deliveries, grouped by snapshot id (HandleMarker,
        # node.go:149-171, vectorized over edges per slot)
        any_marker = jnp.any(deliver_e & popped_marker)

        def per_sid(sid, s):
            mk_e = deliver_e & popped_marker & (popped_data == sid)   # [E]
            arrivals = jax.ops.segment_sum(mk_e.astype(_i32),
                                           self._edge_dst, num_segments=N)
            had = s.has_local[sid]                                    # [N]
            created = (arrivals > 0) & ~had
            # stop recording marker channels; created nodes record all other
            # inbound channels (CreateLocalSnapshot, node.go:58-84 — with k
            # simultaneous markers the k arrival channels are all excluded)
            rec_row = s.recording[sid] & ~mk_e
            rec_row = rec_row | (created[self._edge_dst] & ~mk_e)
            rem_row = jnp.where(
                created, self._in_degree - arrivals,
                s.rem[sid] - jnp.where(had, arrivals, 0))
            has_row = had | created
            s = s._replace(
                recording=s.recording.at[sid].set(rec_row),
                frozen=s.frozen.at[sid].set(
                    jnp.where(created, s.tokens, s.frozen[sid])),
                rem=s.rem.at[sid].set(rem_row),
                has_local=s.has_local.at[sid].set(has_row),
            )
            # re-broadcast from every node that just created its local
            # snapshot (node.StartSnapshot, node.go:198-212)
            s = lax.cond(
                jnp.any(created),
                lambda s: self._bulk_push(s, created[self._edge_src], True, sid),
                lambda s: s, s)
            # finalize (node.go:165-170)
            fire = has_row & (rem_row == 0) & ~s.done_local[sid]
            return s._replace(
                done_local=s.done_local.at[sid].set(s.done_local[sid] | fire),
                completed=s.completed.at[sid].add(
                    jnp.sum(fire, dtype=_i32)),
            )

        return lax.cond(
            any_marker,
            lambda s: lax.fori_loop(0, S, per_sid, s),
            lambda s: s, s)

    def _run_ticks(self, s: DenseState, n) -> DenseState:
        """n is a traced i32 so every distinct ``tick N`` count shares one
        compilation (fori_loop lowers to while_loop for dynamic bounds)."""
        return lax.fori_loop(jnp.int32(0), jnp.asarray(n, _i32),
                             lambda _, s: self._tick(s), s)

    # ---- event injection (sim.go:58-68) ---------------------------------

    def _inject_send(self, s: DenseState, e, amount) -> DenseState:
        """PassTokenEvent -> SendTokens (node.go:112-131): debit at send time,
        one delay draw, enqueue."""
        src = self._edge_src[e]
        err = s.error | jnp.where(
            s.tokens[src] < amount, ERR_TOKEN_UNDERFLOW, 0).astype(_i32)
        s = s._replace(tokens=s.tokens.at[src].add(-jnp.asarray(amount, _i32)),
                       error=err)
        return self._push(s, e, False, amount)

    def _inject_snapshot(self, s: DenseState, node) -> DenseState:
        """SnapshotEvent -> sim.StartSnapshot (sim.go:105-123): allocate the
        next id, create the initiator's local snapshot recording ALL inbound
        links, broadcast markers. No finalize check here (the reference only
        checks on marker receipt)."""
        S = self.cfg.max_snapshots
        sid = s.next_sid
        err = s.error | jnp.where(sid >= S, ERR_SNAPSHOT_OVERFLOW, 0).astype(_i32)
        sid = jnp.clip(sid, 0, S - 1)
        s = s._replace(next_sid=s.next_sid + 1,
                       started=s.started.at[sid].set(True),
                       error=err)
        s = self._create_local(s, sid, node, jnp.int32(-1))
        return self._broadcast_markers(s, node, sid)

    def _bulk_push(self, s: DenseState, active, is_marker: bool, data
                   ) -> DenseState:
        """Vectorized enqueue: one message on every edge where ``active``,
        in a single scatter. Fast-path-only semantics: receive times are
        drawn for every edge in one vectorized draw (inactive edges' draws
        are discarded), so the stream does NOT match sequential per-event
        sends under the Go-exact sampler — use _push/_inject_send for
        bit-exact runs."""
        C = self.cfg.queue_capacity
        rts, dstate = self.delay.draw_many(s.delay_state, s.time, self.topo.e)
        err = s.error | jnp.where(jnp.any(active & (s.q_len >= C)),
                                  ERR_QUEUE_OVERFLOW, 0).astype(_i32)
        rows = self._rows_e
        pos = (s.q_head + s.q_len) % C
        return s._replace(
            q_marker=s.q_marker.at[rows, pos].set(
                jnp.where(active, is_marker, s.q_marker[rows, pos])),
            q_data=s.q_data.at[rows, pos].set(
                jnp.where(active, jnp.asarray(data, _i32), s.q_data[rows, pos])),
            q_rtime=s.q_rtime.at[rows, pos].set(
                jnp.where(active, jnp.asarray(rts, _i32), s.q_rtime[rows, pos])),
            q_len=s.q_len + active.astype(_i32),
            delay_state=dstate,
            error=err,
        )

    def _bulk_send(self, s: DenseState, amounts) -> DenseState:
        """Vectorized token injection: one message per edge with amounts[e]>0
        (the fast-path equivalent of a burst of PassTokenEvents at the same
        sim time). Debits every sender at send time (node.go:120)."""
        amounts = jnp.asarray(amounts, _i32)
        active = amounts > 0
        debits = jax.ops.segment_sum(amounts, self._edge_src,
                                     num_segments=self.topo.n)
        tokens = s.tokens - debits
        err = s.error | jnp.where(jnp.any(tokens < 0), ERR_TOKEN_UNDERFLOW, 0
                                  ).astype(_i32)
        s = s._replace(tokens=tokens, error=err)
        return self._bulk_push(s, active, False, amounts)

    # ---- drain (test_common.go:124-137) ---------------------------------

    def _pending(self, s: DenseState):
        return jnp.any(s.started & (s.completed < self.topo.n))

    def _drain_and_flush_with(self, s: DenseState, tick_fn) -> DenseState:
        """Tick until every started snapshot has completed on all nodes, then
        max_delay+1 flush ticks. Outcome-equivalent to the reference's
        goroutine drain loop (SURVEY.md §3.5), with a tick-budget guard in
        place of hanging on a non-strongly-connected graph."""
        limit = jnp.asarray(s.time + self.cfg.max_ticks, _i32)

        def cond(s):
            return self._pending(s) & (s.time < limit)

        s = lax.while_loop(cond, tick_fn, s)
        s = s._replace(error=s.error | jnp.where(
            self._pending(s), ERR_TICK_LIMIT, 0).astype(_i32))
        return lax.fori_loop(0, self.cfg.max_delay + 1,
                             lambda _, s: tick_fn(s), s)

    def _drain_and_flush(self, s: DenseState) -> DenseState:
        return self._drain_and_flush_with(s, self._tick)

    def _sync_drain_and_flush(self, s: DenseState) -> DenseState:
        return self._drain_and_flush_with(s, self._sync_tick)
