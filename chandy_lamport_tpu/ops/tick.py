"""The jitted simulation kernel: tick, event injection, drain.

This is the reference's hot loop (sim.go:71-95) plus the protocol handlers
(node.go:140-212) as one pure state transition over the dense arrays of
core/state.py. The five bit-exactness-critical rules (SURVEY.md §7.0) map to:

  R1 lexicographic order   -> node index = lexicographic rank; edges sorted
                              by (src, dest); all loops are index order.
  R2 one-delivery-per-source-per-tick, sequential fold with mid-tick marker
     cascades visible to later sources (sim.go:76-92)
                           -> ``lax.scan`` over source indices inside the
                              tick; within a source, the first eligible queue
                              head in dest order is a masked argmax over its
                              padded edge row (scan past ineligible heads,
                              deliver at most one — sim.go:82-92).
  R3 per-channel FIFO + head-of-line blocking
                           -> ring buffers popped only at q_head.
  R4 PRNG draw order       -> delay draws happen exactly where the reference
                              draws (one per send node.go:130; one per
                              outbound link in dest order on broadcast
                              node.go:98-107), sequenced by ``lax.fori_loop``
                              /``lax.cond`` so skipped branches draw nothing.
  R5 snapshot id = allocation order (sim.go:107-108)
                           -> slot index == snapshot id.

Everything is shape-static; the topology is baked into the jitted closures as
constants. Batched execution vmaps these same functions over a leading
instance axis (parallel/batch.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from chandy_lamport_tpu.config import ENGINE_KNOBS, SimConfig
from chandy_lamport_tpu.core.state import (
    DenseState,
    DenseTopology,
    ERR_FAULT_UNRECOVERED,
    ERR_QUEUE_OVERFLOW,
    ERR_RECORD_OVERFLOW,
    ERR_SNAPSHOT_OVERFLOW,
    ERR_SNAPSHOT_TIMEOUT,
    ERR_TICK_LIMIT,
    ERR_TOKEN_UNDERFLOW,
    ERR_VALUE_OVERFLOW,
    F32_EXACT_LIMIT,
    FC_CRASH,
    FC_DROP,
    FC_DUP,
    FC_JITTER,
    FC_MDROP,
    FC_MDUP,
    FC_MJITTER,
    RTIME_PACK_LIMIT,
    marker_data_epoch,
    marker_data_sid,
    meta_marker,
    meta_rtime,
    pack_marker_data,
    pack_meta,
)
from chandy_lamport_tpu.kernels import queue as plk_queue
from chandy_lamport_tpu.kernels import segment as plk_segment
from chandy_lamport_tpu.ops.delay_jax import JaxDelay
from chandy_lamport_tpu.utils.tracing import (
    EV_FAULT,
    EV_MRECV,
    EV_MSEND,
    EV_RECV,
    EV_SEND,
    EV_SNAP_END,
    EV_SNAP_START,
    EV_SUP_ABORT,
    EV_SUP_FAIL,
    EV_SUP_RETRY,
    trace_append_many,
    trace_append_one,
)

_i32 = jnp.int32

# bf16 holds integers exactly through this bound; count matmuls whose
# outputs provably stay within it may run in bf16 on the MXU
BF16_EXACT_COUNT = 256

# reduce_mode="auto" threshold: largest [N, E] incidence matrix worth keeping
# as an in-HLO constant (128 MB f32). Above it, per-node reductions switch to
# the O(E) segment-sum formulation — the 8k-node ladder config's ~800 MB
# matrix broke remote compilation and costs O(N*E) FLOPs every tick.
MATMUL_MAX_ELEMS = 32 * 2**20


def count_dtype(topo: DenseTopology, override: str = "auto",
                backend: str | None = None):
    """Dtype for 0/1 COUNT incidence matmuls (marker arrivals, created
    masks): bf16 on TPU when the graph's degree bound proves every output
    <= 256 (so bf16 is exact), else f32. Used by TickKernel's
    reduce_mode="matmul" formulation and the graph-sharded runner (whose
    per-shard incidence matmuls ride as sharded arguments); the segsum
    formulation needs no count gating — integer segment sums are exact.
    Token-AMOUNT reductions must never use this — they stay f32/int guarded
    by F32_EXACT_LIMIT.

    ``override`` (SimConfig.count_dtype): "auto" applies the gate;
    "bfloat16" forces the fast path (rejected when the degree bound breaks
    exactness); "float32" forces the safe path. ``backend`` defaults to the
    live jax backend — parameterized so CI can exercise the TPU decision
    (and the forced-bf16 numerics) on the CPU mesh."""
    degree_bound = max(int(topo.in_degree.max()) if topo.e else 0, topo.d)
    if override == "float32":
        return jnp.float32
    if override == "bfloat16":
        if degree_bound > BF16_EXACT_COUNT:
            raise ValueError(
                f"count_dtype=bfloat16 is not exact: degree bound "
                f"{degree_bound} > {BF16_EXACT_COUNT}")
        return jnp.bfloat16
    if backend is None:
        backend = jax.default_backend()
    if backend == "tpu" and degree_bound <= BF16_EXACT_COUNT:
        return jnp.bfloat16
    return jnp.float32


def resolve_queue_engine(engine: str, backend: str | None = None) -> str:
    """Resolve the ring-queue addressing knob (TickKernel / the sharded
    runner): "auto" picks "gather" on TPU — where the O(E) packed-plane
    gathers/scatters beat the O(E·C) one-hot traffic as capacity grows —
    and "mask" elsewhere: XLA:CPU lowers the vectorized ``.at[edge, pos]``
    append scatter to a serial update loop measured ~4x SLOWER than the
    SIMD one-hot select at the bench shapes (tools/profile_tick.py
    "queue ops" A/B), the same backend asymmetry count_dtype gates on.
    ``backend`` defaults to the live jax backend; parameterized so CI can
    pin the TPU decision from the CPU mesh."""
    if engine not in ENGINE_KNOBS["queue_engine"]:
        raise ValueError(f"unknown queue_engine {engine!r}")
    if engine != "auto":
        return engine
    if backend is None:
        backend = jax.default_backend()
    return "gather" if backend == "tpu" else "mask"


def resolve_comm_engine(engine: str, backend: str | None = None) -> str:
    """Resolve the graph-sharded cross-shard traffic knob
    (parallel/graphshard.GraphShardedRunner): "dense" keeps the full-plane
    psum/all_gather collectives plus the [N_local, Em] incidence matmuls;
    "sparse" runs the boundary-edge halo exchange — O(E_local) segment
    sums, then only the packed cut rows move, one ppermute per neighbor
    pair. "auto" resolves to "sparse" on every backend: its per-tick bytes
    scale with the partition cut (comm_bytes_model in utils/metrics.py)
    instead of N, its reductions are integer-exact adds in any order, and
    the CPU-mesh A/B in tools/profile_tick.py ("graphshard comm") shows it
    no slower even at small N where the dense planes still fit. "dense"
    is retained as the in-tree differential oracle. ``backend`` is
    accepted for symmetry with resolve_queue_engine / count_dtype should
    a backend ever want the dense plane back."""
    if engine not in ENGINE_KNOBS["comm_engine"]:
        raise ValueError(f"unknown comm_engine {engine!r}")
    if engine != "auto":
        return engine
    del backend  # same resolution everywhere, see docstring
    return "sparse"


def merge_keymult(max_snapshots: int) -> int:
    """Split-mode FIFO merge-key multiplier: m_key = tok_before * KEYMULT +
    marker_ord (DenseState docstring). marker_ord < S (each slot pushes each
    edge at most once, node.go:154-156), so the next power of two above S
    keeps keys unique per edge and sorted by push order. ONE definition for
    the dense and graph-sharded kernels."""
    return 1 << max(4, max_snapshots.bit_length())


def merge_key_limit(max_snapshots: int) -> int:
    """Largest tok_pushed for which a merge key fits int32; token-push sites
    flag ERR_VALUE_OVERFLOW at this bound so a key can never wrap."""
    return (1 << 31) // merge_keymult(max_snapshots) - 1


def log_append_masked(log_amt, rec_cnt, min_prot, app_e, amt_e,
                      rec_dtype, rec_limit, log_slots: int):
    """The shared-log write for a pre-computed append mask ``app_e`` (each
    edge appends at most once per tick, so ``rec_cnt % log_slots`` is the
    same whenever during the tick it is read). The cascade tick defers its
    per-chunk appends into one call here — the mask must capture the
    recording state at each token's fold position, NOT the end-of-tick
    state (a window opened after a token must not swallow it), which is
    why this takes the mask rather than re-deriving it."""
    pos_e = rec_cnt % log_slots
    ll = jnp.arange(log_slots, dtype=_i32)[:, None]
    new_cnt = rec_cnt + app_e.astype(_i32)
    err = (jnp.any(app_e & (new_cnt - min_prot > log_slots)).astype(_i32)
           * ERR_RECORD_OVERFLOW
           | jnp.any(app_e & (amt_e > rec_limit)).astype(_i32)
           * ERR_VALUE_OVERFLOW)
    log_amt = jnp.where(app_e[None, :] & (ll == pos_e[None, :]),
                        amt_e[None, :].astype(rec_dtype), log_amt)
    return log_amt, new_cnt, err


def log_append(log_amt, rec_cnt, min_prot, recording, tok_e, amt_e,
               rec_dtype, rec_limit, log_slots: int):
    """Shared-log append for one sync tick, vector form (DenseState
    "Recording as windows"): append ``amt_e[e]`` to edge e's ring log when
    a token delivered there (``tok_e``) and ANY slot records it. One
    definition serves both the dense and the graph-sharded sync tick so
    the two cannot drift. Returns (log_amt, rec_cnt, err_bits); the
    caller folds err_bits into its error channel (psum'd on the sharded
    path)."""
    return log_append_masked(log_amt, rec_cnt, min_prot,
                             tok_e & jnp.any(recording, axis=-2), amt_e,
                             rec_dtype, rec_limit, log_slots)


def window_update(s, started_se, stopped_se, rec_cnt):
    """Open/close recording windows at the given (post-append) counter:
    replaces rec_start where ``started_se``, rec_end where ``stopped_se``
    (pass None for start-only injection paths), and advances min_prot.
    Shared by the dense and sharded kernels; returns the field dict for
    ``state._replace``. Recorded amounts need no prefix-sum snapshots:
    decode reads them straight from the log window. The counter is cast
    to the plane dtype (window_dtype="uint16" stores it mod 2^16; decode
    and the overflow guard stay exact — SimConfig docstring); min_prot
    stays i32."""
    cnt_b = jnp.expand_dims(rec_cnt, -2).astype(s.rec_start.dtype)
    out = dict(
        rec_start=jnp.where(started_se, cnt_b, s.rec_start),
        min_prot=jnp.where(jnp.any(started_se, axis=-2),
                           jnp.minimum(s.min_prot, rec_cnt), s.min_prot),
    )
    if stopped_se is not None:
        out.update(rec_end=jnp.where(stopped_se, cnt_b, s.rec_end))
    return out


def _map_state(c, fn):
    """Apply ``fn`` to every DenseState inside a (possibly nested) tuple
    carry, leaving non-state leaves untouched. DenseState IS a tuple
    (NamedTuple), so the isinstance order matters."""
    if isinstance(c, DenseState):
        return fn(c)
    if isinstance(c, tuple):
        return tuple(_map_state(x, fn) for x in c)
    return c


def _state_of(c):
    """First DenseState inside a (possibly nested) tuple carry, or None."""
    if isinstance(c, DenseState):
        return c
    if isinstance(c, tuple):
        for x in c:
            st = _state_of(x)
            if st is not None:
                return st
    return None


class TickKernel:
    """Jitted closures over a fixed (topology, config, delay sampler).

    Public jitted entry points (all take/return DenseState):
      tick(s)                 one simulation tick (sim.go:71-95)
      run_ticks(s, n)         n ticks under one dispatch
      inject_send(s, e, amt)  PassTokenEvent on edge e (node.go:112-131)
      inject_snapshot(s, nd)  SnapshotEvent at node nd (sim.go:105-123)
      drain_and_flush(s)      tick until every started snapshot completes,
                              then max_delay+1 flush ticks (test_common.go:124-137)
    """

    def __init__(self, topo: DenseTopology, cfg: SimConfig, delay: JaxDelay,
                 marker_mode: str = "ring", exact_impl: str = "cascade",
                 megatick: int = 8, queue_engine: str = "auto",
                 kernel_engine: str | None = None,
                 faults=None, quarantine: bool = False, trace=None,
                 fused_tick: str | None = None,
                 fused_block_edges: int = 0,
                 fused_tile: str | None = None):
        """marker_mode selects the channel representation (DenseState
        docstring): "ring" = markers share the token ring buffers (required
        by the bit-exact scheduler, whose PRNG draw order is push order);
        "split" = markers live in [S, E] planes with FIFO order preserved
        by sequence numbers (the sync scheduler's fast path — ring content
        is then only written on token sends, not every tick).

        exact_impl selects the bit-exact tick's formulation: "cascade"
        (default) vectorizes token deliveries and folds only over marker
        deliveries (_cascade_tick — O(E) + one sequential step per marker
        delivered, instead of N scan steps per tick); "wave" goes further
        and processes every same-tick marker bound for a DISTINCT
        destination in one vectorized step (_wave_tick — one sequential
        step per marker-per-destination conflict; requires a
        position-addressable delay sampler, JaxDelay.position_streams);
        "fold" is the reference-literal N-step source scan (_tick), kept
        as the specification form the others are differentially tested
        against.

        megatick fuses the exact path's multi-tick loops: ``run_ticks``
        (and the exact drain) advance in scan-fused K-tick "megaticks"
        instead of one loop iteration per tick, with a cumulative
        quiescence mask — once a lane's rings are empty, every remaining
        tick is provably a pure time increment, so drained stretches
        fast-forward in O(1) (see _run_ticks). Semantics-preserving by
        construction; 1 disables the fusion (the reference-literal
        one-iteration-per-tick loops).

        queue_engine selects the ring-queue addressing, bit-identical
        either way (tests/test_queue_engine.py): "gather" reads heads
        with O(E) ``take_along_axis`` gathers (_head_fields) and appends
        with O(E) ``.at[edge, pos]`` scatters (_append_rows), so per-tick
        queue HBM traffic scales with EDGE COUNT; "mask" is the
        one-hot formulation — [E, C] mask reductions/selects whose
        traffic scales with queue CAPACITY, but SIMD-friendly where
        XLA serializes scatters. "auto" (default) resolves per backend
        (resolve_queue_engine: gather on TPU, mask elsewhere — the
        measured XLA:CPU scatter penalty); ``self.queue_engine`` holds
        the RESOLVED engine, and the non-default one stays available as
        the differential oracle and the tools/profile_tick.py
        "queue ops" A/B.

        faults (models/faults.JaxFaults or None) arms the deterministic
        fault adversary: message drop/duplicate/extra-delay-jitter per
        edge and crash/restart windows per node, every decision a
        stateless counter hash of (DenseState.fault_key, tick, index) so
        faulted runs replay bit-exactly. None (default) compiles the
        hooks away — the fault-free path is the UNINSTRUMENTED kernel,
        bit-identical to a build without this feature. A JaxFaults with
        all rates zero keeps the instrumentation in the trace with
        all-False masks (the differential oracle for the
        masked-adversary overhead, tools/profile_tick.py "faults"
        section). The reference-literal 'fold' formulation stays the
        uninjured specification form and refuses a fault engine.

        quarantine freezes a lane the moment its sticky error bits fire:
        the drain/flush loops treat ``error != 0`` exactly like the
        quiescence exit, so a poisoned lane stops ticking instead of
        corrupting aggregate metrics (parallel/batch.py extends the
        same gate to the storm phase scan).

        The snapshot SUPERVISOR is configured through cfg
        (SimConfig.snapshot_timeout / snapshot_retries /
        snapshot_every) and woven into the cascade, wave and sync ticks
        (_supervise): attempts carry deadlines; a timed-out attempt is
        aborted in trace and re-initiated under a bumped epoch (ring
        markers carry (sid, epoch) packed in their payload —
        state.pack_marker_data — and superseded arrivals are rejected
        as stale); exhausted retries raise ERR_SNAPSHOT_TIMEOUT. Both
        knobs at 0 (default) trace zero supervisor ops, and an
        armed-but-idle supervisor is bit-identical to the unsupervised
        kernel (tests/test_snapshot_supervisor.py).

        trace (utils/tracing.JaxTrace or None) arms the device flight
        recorder: every protocol event the reference Logger records —
        plus supervisor and fault events — is appended to the per-lane
        ring riding on DenseState (tr_* leaves) by cheap ``.at[]``
        scatters at the handler sites. None (default, the faults=None
        contract again) compiles the recorder away entirely: the kernels
        contain zero trace ops and lower bit-identically to an
        uninstrumented build (tests/test_trace.py asserts this on the
        goldens). cfg.trace_capacity must be > 0 for an armed recorder
        to have anywhere to write (runners bump it before building).

        fused_tick ("auto"/"on"/"off", None defers to cfg.fused_tick)
        selects the ONE-KERNEL megatick (kernels/megatick.py): with
        kernel_engine='pallas' and megatick K > 1, the whole K-tick scan
        of run_ticks / the exact drain executes inside a single Pallas
        kernel with every working plane VMEM-resident between ticks, the
        fault adversary riding along in-kernel as precomputed mask
        planes (_fault_planes). kernels.megatick.resolve_fused_tick is
        the gate ("auto" falls back to the split kernels whenever the
        fused form doesn't apply — supervisor or recorder armed, fold
        formulation, VMEM budget blown; "on" raises instead).
        ``self.fused`` holds the resolved "on"/"off" and
        ``self.fused_reason`` the reason. Bit-identical either way
        (tests/test_megatick_fused.py). fused_block_edges overrides the
        edge-block width of the fault-plane AND ring-plane DMA pipelines
        (0 = the plan_edge_blocks default; tests shrink it to force
        multi-block geometry on small graphs).

        fused_tile ("auto"/"on"/"off", None defers to cfg.fused_tile)
        selects the TILED fused-state layout (the megatick module
        docstring): the [E, C] ring planes stay in HBM and stream
        through the double-buffered block pipeline while every node
        plane stays VMEM-resident — heads pre-extracted once per step,
        appends deferred into [A, E] planes and committed block-by-block
        — which is what lets resolve_fused_tick accept working sets far
        past the VMEM budget. kernels.megatick.resolve_fused_tile is the
        gate ("auto" tiles exactly when the resident set overflows);
        ``self.fused_tile`` / ``self.fused_tile_reason`` hold the
        resolution. Bit-identical either way
        (tests/test_megatick_tiled.py)."""
        if marker_mode not in ("ring", "split"):
            raise ValueError(f"unknown marker_mode {marker_mode!r}")
        if (faults is not None and marker_mode == "ring"
                and exact_impl == "fold"):
            raise ValueError(
                "exact_impl='fold' is the reference-literal specification "
                "form and runs uninjured; use cascade/wave (or the sync "
                "scheduler) for fault injection")
        # the snapshot supervisor (cfg.snapshot_timeout / snapshot_every) is
        # woven into the cascade/wave/sync ticks; the fold stays the
        # unsupervised specification form for the same reason it refuses
        # the fault engine
        self._sup = bool(cfg.snapshot_timeout > 0 or cfg.snapshot_every > 0)
        if self._sup and marker_mode == "ring" and exact_impl == "fold":
            raise ValueError(
                "exact_impl='fold' is the reference-literal specification "
                "form and carries no snapshot supervisor; use cascade/wave "
                "(or the sync scheduler) with snapshot_timeout/"
                "snapshot_every")
        queue_engine = resolve_queue_engine(queue_engine)
        # kernel_engine routes the queue head/select/pop/append chain and
        # the edge->node reductions through the fused Pallas kernels
        # (chandy_lamport_tpu/kernels) instead of the stock-XLA
        # formulations below. None defers to cfg.kernel_engine; the
        # RESOLVED engine is stored ("auto" never picks the interpret-mode
        # emulation — kernels.resolve_kernel_engine). Bit-identical either
        # way (tests/test_pallas_kernels.py).
        from chandy_lamport_tpu.kernels import (
            pallas_interpret,
            resolve_kernel_engine,
        )

        self.kernel_engine = resolve_kernel_engine(
            cfg.kernel_engine if kernel_engine is None else kernel_engine)
        self._pl_interpret = pallas_interpret()
        if megatick < 1:
            raise ValueError(f"megatick must be >= 1, got {megatick}")
        if exact_impl not in ("cascade", "fold", "wave"):
            raise ValueError(f"unknown exact_impl {exact_impl!r}")
        # only the ring (exact-scheduler) representation ever runs the
        # exact tick; a split-mode kernel's sync path must not be refused
        # over a formulation it will never execute
        if (exact_impl == "wave" and marker_mode == "ring"
                and not delay.position_streams):
            raise ValueError(
                "exact_impl='wave' precomputes the tick's delay draws at "
                "their fold-order stream positions, which is only "
                f"stream-identical for position-addressable samplers; "
                f"{type(delay).__name__} draws are order-dependent — use "
                "FixedJaxDelay or HashJaxDelay (or exact_impl='cascade')")
        self.marker_mode = marker_mode
        self.exact_impl = exact_impl
        self.megatick = int(megatick)
        self.queue_engine = queue_engine
        self.faults = faults
        self.quarantine = bool(quarantine)
        # zero trace ops unless armed AND the ring has capacity — every
        # recorder site below is guarded on this static flag
        self._trace_on = trace is not None and cfg.trace_capacity > 0
        self.topo = topo
        self.cfg = cfg
        self.delay = delay
        self._keymult = merge_keymult(cfg.max_snapshots)
        self._key_limit = merge_key_limit(cfg.max_snapshots)
        # static topology constants baked into the traces
        self._edge_src = jnp.asarray(topo.edge_src, _i32)
        self._edge_dst = jnp.asarray(topo.edge_dst, _i32)
        self._edge_table = jnp.asarray(topo.edge_table)
        self._in_degree = jnp.asarray(topo.in_degree)

        self._rows_e = jnp.arange(topo.e, dtype=_i32)
        import numpy as _np

        n, e = topo.n, topo.e
        # first outbound-edge index of each edge's source: edges are sorted
        # by (src, dst) so edge_src is nondecreasing and searchsorted finds
        # each source's first edge. Powers the O(E) cumsum formulation of
        # "an earlier eligible edge of the same source exists" in _sync_tick
        # (the previous [E, E] strict-predecessor matmul was O(E^2) HBM —
        # ~2.4 GB of constant alone at the 8k-node ladder config).
        self._src_first = jnp.asarray(
            _np.searchsorted(topo.edge_src, topo.edge_src, side="left"), _i32)
        # Per-destination reductions (token credits, marker arrival counts)
        # have two formulations, selected by cfg.reduce_mode:
        #   "matmul" — [N, E] one-hot incidence matmuls on the MXU. Fastest
        #       at small/medium graphs (50M vs 38M node-ticks/s at the
        #       1k-node bench config) but O(N*E) FLOPs, and the constants
        #       embed into the HLO — ~1.6 GB at the 8k-node ladder config,
        #       which broke remote compilation outright (HTTP 413).
        #   "segsum" — prefix-sum segment sums over statically-known edge
        #       orderings: O(E) integer VPU work, exact at any scale, no
        #       large constants. The only choice for big graphs.
        # "auto" picks matmul while the incidence matrix stays small.
        # Static orderings for segsum (and the broadcasts both modes share):
        #   by_dst: edge permutation sorting by destination (stable, so
        #           src order is preserved within a destination group);
        #   dst_lo/dst_hi: each node's segment bounds in that permutation;
        #   src_lo/src_hi: each node's outbound-edge bounds (edges are
        #           already src-sorted, no permutation needed).
        self._by_dst = jnp.asarray(topo.by_dst, _i32)
        self._dst_lo = jnp.asarray(topo.dst_bounds[:-1], _i32)
        self._dst_hi = jnp.asarray(topo.dst_bounds[1:], _i32)
        src_bounds = _np.concatenate(
            [[0], _np.cumsum(_np.bincount(topo.edge_src, minlength=n))])
        self._src_lo = jnp.asarray(src_bounds[:-1], _i32)
        self._src_hi = jnp.asarray(src_bounds[1:], _i32)
        # wave-tick schedule constants (_wave_tick): the inverse of the
        # by_dst permutation (scatter segment-scan results back to edge
        # order), each by_dst position's segment start (per-destination
        # exclusive counts from one global cumsum), each edge's ordinal
        # among its source's outbound edges (edges are src-contiguous),
        # and each edge's DESTINATION out-degree (broadcast draw counts)
        self._inv_by_dst = jnp.asarray(_np.argsort(topo.by_dst,
                                                   kind="stable"), _i32)
        self._pos_seg_start = jnp.asarray(
            topo.dst_bounds[:-1][topo.edge_dst[topo.by_dst]], _i32)
        self._edge_ord_in_src = jnp.asarray(
            _np.arange(e) - src_bounds[:-1][topo.edge_src], _i32)
        outdeg = src_bounds[1:] - src_bounds[:-1]
        self._outdeg_dst_e = jnp.asarray(outdeg[topo.edge_dst], _i32)
        self._mode = cfg.reduce_mode
        if self._mode == "auto":
            self._mode = "matmul" if n * e <= MATMUL_MAX_ELEMS else "segsum"
        if self._mode == "matmul":
            a_in = _np.zeros((n, e), _np.float32)
            a_in[topo.edge_dst, _np.arange(e)] = 1.0
            a_out = _np.zeros((n, e), _np.float32)
            a_out[topo.edge_src, _np.arange(e)] = 1.0
            # counts may run in bf16 on the MXU when the degree bound
            # proves them exact (count_dtype); amounts stay f32 guarded by
            # F32_EXACT_LIMIT
            self._cnt = count_dtype(topo, cfg.count_dtype)
            self._A_in = jnp.asarray(a_in)
            self._A_in_c = (self._A_in if self._cnt == jnp.float32
                            else jnp.asarray(a_in, self._cnt))
            self._A_out_c = jnp.asarray(a_out, self._cnt)
        # recorded amounts beyond the record dtype's range must flag, not
        # silently truncate (record_dtype shrinks the log_amt[L, E] HBM)
        self._rec_dtype = jnp.dtype(cfg.record_dtype)
        self._rec_limit = jnp.iinfo(self._rec_dtype).max
        self._exact_tick = {"cascade": self._cascade_tick,
                            "wave": self._wave_tick,
                            "fold": self._tick}[exact_impl]
        # ---- one-kernel megatick resolution (kernels/megatick.py) ----
        from chandy_lamport_tpu.kernels import megatick as plk_megatick

        self.fused_tick = (cfg.fused_tick if fused_tick is None
                           else fused_tick)
        self.fused_tile_knob = (cfg.fused_tile if fused_tile is None
                                else fused_tile)
        self.fused_block_edges = int(fused_block_edges)
        vmem = tiled_vmem = 0
        self._ring_append_slots = 0
        if self.fused_tick != "off":
            # working-set arithmetic is only needed once the cheap gates
            # can pass; init_state is host-side numpy, built transiently
            from chandy_lamport_tpu.core.state import init_state

            self._state_bytes = plk_megatick.pytree_bytes(
                init_state(topo, cfg, None))
            vmem = plk_megatick.fused_vmem_bytes(
                self._state_bytes, topo.e, topo.n, self.megatick,
                faults is not None, self.fused_block_edges)
            # the tiled layout's deferred-append bound and working set —
            # what lets the budget arm accept shapes whose rings blow
            # the resident figure (megatick.ring_append_slots census)
            self._ring_append_slots = plk_megatick.ring_append_slots(
                max_snapshots=cfg.max_snapshots,
                max_in_degree=int(_np.max(_np.asarray(topo.in_degree))),
                timeout_armed=cfg.snapshot_timeout > 0,
                every_armed=cfg.snapshot_every > 0,
                faulted=faults is not None)
            tiled_vmem = plk_megatick.fused_vmem_bytes(
                self._state_bytes, topo.e, topo.n, self.megatick,
                faults is not None, self.fused_block_edges,
                tiled=True, queue_capacity=cfg.queue_capacity,
                append_slots=self._ring_append_slots)
        self.fused, self.fused_reason = plk_megatick.resolve_fused_tick(
            self.fused_tick,
            kernel_engine=self.kernel_engine, megatick=self.megatick,
            marker_mode=marker_mode, exact_impl=exact_impl,
            supervised=self._sup, traced=self._trace_on, vmem_bytes=vmem,
            tiled_vmem_bytes=(None if self.fused_tile_knob == "off"
                              else tiled_vmem))
        self.fused_tile, self.fused_tile_reason = (
            plk_megatick.resolve_fused_tile(
                self.fused_tile_knob, fused=self.fused, vmem_bytes=vmem,
                tiled_vmem_bytes=tiled_vmem))
        if self.fused == "on":
            # the tick body traced INSIDE the fused kernel: the same
            # TickKernel, pinned to the stock-XLA formulations (no nested
            # pallas_call) with segsum reductions (no [N, E] matmul
            # constants resident in VMEM — integer-exact, bit-identical),
            # the queue addressing inherited from the outer resolution.
            # Everything else (faults, quarantine, formulation, the
            # supervisor via the shared cfg, the flight recorder via the
            # shared trace handle — both masked lane ops over resident
            # planes, traced in-kernel) matches, so _exact_tick's jaxpr
            # is the one the split paths are differentially pinned
            # against.
            self._fused_inner = TickKernel(
                topo,
                dataclasses.replace(cfg, reduce_mode="segsum",
                                    kernel_engine="xla",
                                    fused_tick="off"),
                delay, marker_mode="ring", exact_impl=exact_impl,
                megatick=1, queue_engine=self.queue_engine,
                kernel_engine="xla", faults=faults, quarantine=quarantine,
                trace=trace)
        if marker_mode == "split":
            # a split-mode kernel carries markers in the [S, E] pending
            # planes, not the rings, so no bit-exact formulation can run on
            # it. Refuse loudly the moment the exact entry points are
            # touched (ADVICE r5 #1) — previously these stayed bound to the
            # exact tick and failed deep inside a trace (a late
            # NotImplementedError from the wave's sampler guard, or a
            # silent markers-missing run for cascade/fold).
            self.tick = self.run_ticks = self._split_mode_exact_stub
        else:
            self.tick = jax.jit(self._exact_tick, donate_argnums=0)
            self.run_ticks = jax.jit(self._run_ticks, donate_argnums=0)
        self.inject_send = jax.jit(self._inject_send, donate_argnums=0)
        self.inject_snapshot = jax.jit(self._inject_snapshot, donate_argnums=0)
        if marker_mode == "split":
            self.drain_and_flush = self._split_mode_exact_stub
        else:
            self.drain_and_flush = jax.jit(self._drain_and_flush,
                                           donate_argnums=0)

    def _split_mode_exact_stub(self, *_args, **_kwargs):
        """Bound over tick/run_ticks/drain_and_flush on split-mode kernels:
        one immediate, explanatory refusal instead of a late trace-time
        failure (ADVICE r5 #1)."""
        raise ValueError(
            "this kernel was built with marker_mode='split' (the sync "
            "scheduler's representation: markers live in the [S, E] "
            "pending planes, not the rings), so the bit-exact tick "
            "formulations cannot run on it — use the sync entry points "
            "(_sync_tick / _sync_drain_and_flush via BatchedRunner"
            "(scheduler='sync')), or build the kernel with "
            "marker_mode='ring' for tick/run_ticks/drain_and_flush")

    # ---- static-order segment reductions ---------------------------------

    @staticmethod
    def _segment_sums(xs, lo, hi):
        """[..., E] -> [..., N]: per-segment sums via an exclusive prefix sum
        and two static-index takes (``xs`` must already be in segment order)."""
        cs = jnp.cumsum(xs, axis=-1)
        cs0 = jnp.concatenate([jnp.zeros_like(cs[..., :1]), cs], axis=-1)
        return jnp.take(cs0, hi, axis=-1) - jnp.take(cs0, lo, axis=-1)

    def _sum_by_dst(self, x_e, amounts: bool):
        """Per-destination-node sums of a per-edge quantity. segsum mode is
        integer-exact; matmul mode routes token AMOUNTS through the f32
        incidence matrix (caller flags >= 2^24 values) and COUNTS through
        the count-dtype copy (bf16 when the degree bound proves it exact)."""
        if self.kernel_engine == "pallas":
            # safe against BOTH stock modes: every reduction here is an
            # exact integer (matmul is gated to exact regimes), and the
            # kernel keeps the segsum math verbatim
            return plk_segment.sum_by_perm(
                x_e, self._by_dst, self._dst_lo, self._dst_hi,
                interpret=self._pl_interpret)
        if self._mode == "segsum":
            xs = jnp.take(x_e.astype(_i32), self._by_dst, axis=-1)
            return self._segment_sums(xs, self._dst_lo, self._dst_hi)
        a = self._A_in if amounts else self._A_in_c
        return (x_e.astype(a.dtype) @ a.T).astype(_i32)

    def _sum_by_src(self, x_e):
        """Per-source-node sums (edges are already src-sorted)."""
        if self.kernel_engine == "pallas":
            return plk_segment.sum_segments(
                x_e, self._src_lo, self._src_hi,
                interpret=self._pl_interpret)
        return self._segment_sums(x_e, self._src_lo, self._src_hi)

    def _spread_dst(self, x_n):
        """[..., N] bool -> [..., E]: broadcast a per-node flag to its
        inbound edges. Matmul on the MXU in matmul mode (measured ~10%
        faster per tick than the gather at the 1k-node bench shape);
        static-index take in segsum mode (no [N, E] constants)."""
        if self.kernel_engine == "pallas":
            return plk_segment.spread(x_n, self._edge_dst,
                                      interpret=self._pl_interpret)
        if self._mode == "matmul":
            return (x_n.astype(self._cnt) @ self._A_in_c) > 0.5
        return jnp.take(x_n, self._edge_dst, axis=-1)

    def _spread_src(self, x_n):
        """[..., N] bool -> [..., E]: broadcast a per-node flag to its
        outbound edges (marker re-broadcast targets)."""
        if self.kernel_engine == "pallas":
            return plk_segment.spread(x_n, self._edge_src,
                                      interpret=self._pl_interpret)
        if self._mode == "matmul":
            return (x_n.astype(self._cnt) @ self._A_out_c) > 0.5
        return jnp.take(x_n, self._edge_src, axis=-1)

    # ---- fault adversary hooks (models/faults.py) ------------------------
    # Only ever called under ``if self.faults is not None`` — a fault-free
    # kernel traces zero adversary ops (the compiled-in, zero-cost-when-
    # disabled contract). One shared set of hooks serves the sync tick and
    # both vectorized exact formulations, so the fault semantics cannot
    # drift between schedulers.

    # Every hook takes an optional ``fmasks`` — the PRECOMPUTED mask
    # bundle for this tick (_fmasks_of), used by the fused megatick whose
    # in-kernel scan receives the whole adversary program as input planes
    # (kernels/megatick.py). The hash is stateless in (fault_key, time,
    # index) and fault_key is never advanced by a tick, so masks hashed
    # ahead of time are byte-identical to masks hashed at tick time; with
    # fmasks=None (every non-fused path) nothing changes.

    def _fault_edge_masks(self, s: DenseState, fmasks=None):
        """(drop, dup, jitter) bool [E] + dup receive times i32 [E] for the
        CURRENT tick (s.time must already be incremented). Dup delays come
        from the fault stream, folded into [1, max_delay], so the delay
        sampler's stream is fault-invariant and every duplicate lands
        inside the drain's max_delay+1 flush window."""
        if fmasks is not None:
            return fmasks["edge"]
        drop_e, dup_e, jit_e, dupw_e = self.faults.edge_masks(
            s.fault_key, s.time, self.topo.e)
        dup_rt = s.time + 1 + jnp.asarray(
            dupw_e % jnp.uint32(max(self.cfg.max_delay, 1)), _i32)
        return drop_e, dup_e, jit_e, dup_rt

    def _fault_marker_masks(self, s: DenseState, fmasks=None):
        """(drop, dup, jitter) bool [E] + dup receive times i32 [E] for
        this tick's MARKER deliveries (models/faults.marker_masks): the
        control-plane fault program the snapshot supervisor exists to
        survive. Stateless per-tick hash — callers may recompute it
        within a tick and read identical masks."""
        if fmasks is not None:
            return fmasks["marker"]
        md_e, mu_e, mj_e, mw_e = self.faults.marker_masks(
            s.fault_key, s.time, self.topo.e)
        mdup_rt = s.time + 1 + jnp.asarray(
            mw_e % jnp.uint32(max(self.cfg.max_delay, 1)), _i32)
        return md_e, mu_e, mj_e, mdup_rt

    def _fault_split_markers(self, s: DenseState, mk_pend, fmasks=None):
        """Split this tick's delivered-marker mask by the adversary's
        marker drop/dup program: a dropped marker vanishes on the wire
        (popped, never handled — exactly the loss that stalls a snapshot
        until the supervisor's timeout), a duplicated one is handled AND
        re-enqueued by the caller with a fault-stream receive time.
        Markers move no tokens, so no skew is booked. Returns
        (state, surviving-marker mask, dup mask, dup receive times)."""
        mdrop_e, mdup_e, _, mdup_rt = self._fault_marker_masks(s, fmasks)
        dropped = mk_pend & mdrop_e
        duped = mk_pend & mdup_e & ~dropped
        counts = s.fault_counts.at[FC_MDROP].add(
            jnp.sum(dropped, dtype=_i32)).at[FC_MDUP].add(
            jnp.sum(duped, dtype=_i32))
        s = s._replace(fault_counts=counts)
        if self._trace_on:
            s = trace_append_many(s, dropped, EV_FAULT, self._rows_e,
                                  FC_MDROP)
            s = trace_append_many(s, duped, EV_FAULT, self._rows_e, FC_MDUP)
        return s, mk_pend & ~dropped, duped, mdup_rt

    def _fault_gate_elig(self, s: DenseState, elig, jit_e, mjit_e=None,
                         marker_front=None, fmasks=None):
        """Apply the delivery-side fault gates to an eligibility mask:
        extra-delay jitter stalls the edge's front for this tick (with
        ``mjit_e``/``marker_front``, the marker-plane jitter program
        additionally stalls marker fronts), and a down (crashed)
        destination receives nothing — its in-flight messages WAIT
        (channels stay lossless; recovery is the point, not message
        loss). Returns (state with jitter events counted, elig)."""
        jblocked = elig & jit_e
        blocked = jblocked
        counts = s.fault_counts.at[FC_JITTER].add(
            jnp.sum(jblocked, dtype=_i32))
        mblocked = None
        if mjit_e is not None:
            mblocked = elig & marker_front & mjit_e
            counts = counts.at[FC_MJITTER].add(jnp.sum(mblocked, dtype=_i32))
            blocked = blocked | mblocked
        if fmasks is not None:
            down_n = fmasks["down_n"]
        else:
            down_n = self.faults.down_nodes(s.fault_key, s.time,
                                            self.topo.n)
        dead = elig & self._spread_dst(down_n)
        s = s._replace(fault_counts=counts)
        if self._trace_on:
            s = trace_append_many(s, jblocked, EV_FAULT, self._rows_e,
                                  FC_JITTER)
            if mblocked is not None:
                s = trace_append_many(s, mblocked, EV_FAULT, self._rows_e,
                                      FC_MJITTER)
        return s, elig & ~blocked & ~dead

    def _fault_split_tokens(self, s: DenseState, tok_e, amt_src, drop_e,
                            dup_e):
        """Split this tick's delivered-token mask by the adversary's drop/
        duplicate program and settle the books: dropped tokens vanish
        (popped, never credited or recorded), duplicated ones deliver AND
        re-enqueue (the caller appends them after its pops). Returns
        (state, surviving-token mask, dup mask)."""
        dropped = tok_e & drop_e
        duped = tok_e & dup_e & ~dropped   # a lost message cannot also fork
        skew = (jnp.sum(jnp.where(duped, amt_src, 0), dtype=_i32)
                - jnp.sum(jnp.where(dropped, amt_src, 0), dtype=_i32))
        counts = s.fault_counts.at[FC_DROP].add(
            jnp.sum(dropped, dtype=_i32)).at[FC_DUP].add(
            jnp.sum(duped, dtype=_i32))
        s = s._replace(fault_skew=s.fault_skew + skew, fault_counts=counts)
        if self._trace_on:
            s = trace_append_many(s, dropped, EV_FAULT, self._rows_e,
                                  FC_DROP)
            s = trace_append_many(s, duped, EV_FAULT, self._rows_e, FC_DUP)
        return s, tok_e & ~dropped, duped

    def _fault_restart(self, s: DenseState, fmasks=None) -> DenseState:
        """Crash-window restarts at tick start (s.time already incremented).
        'pause' mode only counts the event — node memory survived, resuming
        IS the recovery. 'lossy' mode is snapshot-rollback recovery: the
        restarting node's balance is restored from the last COMPLETED
        Chandy-Lamport snapshot's frozen value (slot ids are allocation-
        ordered, so the highest completed slot is the newest recovery
        line); with no completed snapshot the balance is genuinely gone —
        zeroed, ERR_FAULT_UNRECOVERED raised, quarantine's cue. Every
        balance delta lands in fault_skew so conservation stays exact."""
        f = self.faults
        n = self.topo.n
        if fmasks is not None:
            rs_n = fmasks["rs_n"]
        else:
            rs_n = f.restarted(s.fault_key, s.time, n)
        counts = s.fault_counts.at[FC_CRASH].add(jnp.sum(rs_n, dtype=_i32))
        if self._trace_on:
            # the only FAULT event whose actor is a NODE, not an edge
            s = trace_append_many(s, rs_n, EV_FAULT,
                                  jnp.arange(n, dtype=_i32), FC_CRASH)
        if f.crash_mode != "lossy":
            return s._replace(fault_counts=counts)
        S = self.cfg.max_snapshots
        done = s.started & (s.completed >= n)
        sid = jnp.max(jnp.where(done, jnp.arange(S, dtype=_i32), -1))
        have = sid >= 0
        frozen = s.frozen[jnp.clip(sid, 0, S - 1)]                 # [N]
        restored = jnp.where(rs_n, jnp.where(have, frozen, 0), s.tokens)
        err = jnp.where(jnp.any(rs_n) & ~have,
                        ERR_FAULT_UNRECOVERED, 0).astype(_i32)
        return s._replace(
            tokens=restored,
            fault_skew=s.fault_skew + jnp.sum(restored - s.tokens,
                                              dtype=_i32),
            fault_counts=counts,
            error=s.error | err)

    def _fault_planes(self, s: DenseState, K: int):
        """The adversary's whole next-K-ticks program as two dense i32
        planes — the fused megatick's input contract (kernels/megatick):
        edge plane [K, 8, E] with rows (drop, dup, jit, dup_rt, mdrop,
        mdup, mjit, mdup_rt), node plane [K, 2, N] with rows (down_n,
        rs_n). Row j holds the masks for tick time ``s.time + 1 + j`` —
        the time the j-th in-kernel step ticks at if every step before
        it ticked, which the megatick loops guarantee (their gates are
        monotone, so real ticks always form a step PREFIX; see
        _run_ticks / _drain_and_flush_with). The hash is stateless and
        fault_key is tick-invariant, so these are bit-identical to the
        masks the hooks would hash mid-tick."""
        f = self.faults
        e, n = self.topo.e, self.topo.n
        md = jnp.uint32(max(self.cfg.max_delay, 1))

        def row(t):
            drop, dup, jit, dupw = f.edge_masks(s.fault_key, t, e)
            mdrop, mdup, mjit, mw = f.marker_masks(s.fault_key, t, e)
            ep = jnp.stack([
                drop.astype(_i32), dup.astype(_i32), jit.astype(_i32),
                t + 1 + jnp.asarray(dupw % md, _i32),
                mdrop.astype(_i32), mdup.astype(_i32), mjit.astype(_i32),
                t + 1 + jnp.asarray(mw % md, _i32)])
            npl = jnp.stack([
                f.down_nodes(s.fault_key, t, n).astype(_i32),
                f.restarted(s.fault_key, t, n).astype(_i32)])
            return ep, npl

        times = s.time + 1 + jnp.arange(K, dtype=_i32)
        return jax.vmap(row)(times)            # [K, 8, E], [K, 2, N]

    @staticmethod
    def _fmasks_of(ep, npl):
        """One step's plane slices ([8, E], [2, N]) -> the ``fmasks``
        bundle every fault hook accepts in place of hashing."""
        def b(x):
            return x.astype(jnp.bool_)

        return {"edge": (b(ep[0]), b(ep[1]), b(ep[2]), ep[3]),
                "marker": (b(ep[4]), b(ep[5]), b(ep[6]), ep[7]),
                "down_n": b(npl[0]), "rs_n": b(npl[1])}

    # ---- snapshot supervisor (SimConfig.snapshot_timeout/_every) ---------
    # Traced only when self._sup (the faults=None zero-cost contract: an
    # unsupervised kernel contains zero supervisor ops). One shared scan/
    # abort core serves the ring (cascade/wave) and split (sync) paths;
    # only re-initiation differs by representation.

    def _marker_payload(self, s: DenseState, sid):
        """Ring-mode marker payload for slot ``sid``: (sid, epoch) packed
        as ``epoch * S + sid`` (state.pack_marker_data) when the
        supervisor is armed — epoch 0 packs to the bare sid, so an armed
        supervisor that never fires keeps ring content bit-identical to
        the unsupervised kernel — and the bare sid otherwise."""
        sid = jnp.asarray(sid, _i32)
        if not self._sup:
            return sid
        return pack_marker_data(sid, s.snap_epoch[sid],
                                self.cfg.max_snapshots)

    def _reject_stale(self, s: DenseState, mk_pend, head_data):
        """Delivery-side epoch check for popped ring markers: decode
        (sid, epoch) from the payload and reject arrivals whose epoch the
        supervisor has superseded — an aborted attempt's markers cannot be
        plucked out of the FIFO rings, so they drain naturally and die
        HERE, counted in ``stale_markers``, instead of corrupting the
        fresh attempt's cut. Returns (state, surviving markers, sid_e);
        with the supervisor off this is the identity and ``sid_e`` is the
        raw payload (bare sid)."""
        if not self._sup:
            return s, mk_pend, head_data
        S = self.cfg.max_snapshots
        sid_e = marker_data_sid(head_data, S)
        stale = mk_pend & (marker_data_epoch(head_data, S)
                           != s.snap_epoch[jnp.clip(sid_e, 0, S - 1)])
        s = s._replace(stale_markers=s.stale_markers
                       + jnp.sum(stale, dtype=_i32))
        return s, mk_pend & ~stale, sid_e

    def _sup_scan(self, s: DenseState):
        """Timeout scan: abort every snapshot attempt whose deadline
        passed — slot released (cut state cleared, recorded windows
        zeroed, channels un-frozen), epoch bumped so the dead attempt's
        in-flight markers are rejected as stale — then either schedule a
        re-initiation (retries left; deadline doubles per retry, capped
        at 16x) or mark the slot failed and raise ERR_SNAPSHOT_TIMEOUT.
        ``min_prot`` is left conservative (never raised): an aborted
        window's protection can only make ERR_RECORD_OVERFLOW fire
        early, never miss. Returns (state, retry mask [S])."""
        n = self.topo.n
        timed_out = (s.started & ~s.snap_failed & (s.completed < n)
                     & (s.snap_deadline > 0) & (s.time >= s.snap_deadline))
        can_retry = timed_out & (s.snap_retries
                                 < jnp.int32(self.cfg.snapshot_retries))
        failed = timed_out & ~can_retry
        t_b = timed_out[..., :, None]        # broadcasts over N and E dims
        new_retries = s.snap_retries + can_retry.astype(_i32)
        backoff = jnp.left_shift(
            jnp.int32(max(self.cfg.snapshot_timeout, 1)),
            jnp.minimum(new_retries, 4))
        s = s._replace(
            has_local=s.has_local & ~t_b,
            done_local=s.done_local & ~t_b,
            frozen=jnp.where(t_b, 0, s.frozen),
            rem=jnp.where(t_b, 0, s.rem),
            recording=s.recording & ~t_b,
            rec_start=jnp.where(t_b, jnp.zeros_like(s.rec_start),
                                s.rec_start),
            rec_end=jnp.where(t_b, jnp.zeros_like(s.rec_end), s.rec_end),
            completed=jnp.where(timed_out, 0, s.completed),
            # split representation: the dead attempt's pending markers are
            # wiped in place (ring markers die via the epoch check instead)
            m_pending=s.m_pending & ~t_b,
            snap_epoch=s.snap_epoch + timed_out.astype(_i32),
            snap_retries=new_retries,
            snap_failed=s.snap_failed | failed,
            snap_deadline=jnp.where(can_retry, s.time + backoff,
                                    jnp.where(failed, 0, s.snap_deadline)),
            error=s.error | jnp.where(jnp.any(failed),
                                      ERR_SNAPSHOT_TIMEOUT, 0).astype(_i32),
        )
        if self._trace_on:
            # the supervisor's decisions in decision order: every timed-out
            # attempt aborts, then either retries (the re-initiation's
            # marker-sends follow from _sup_reinitiate_*) or fails for good
            init_n = jnp.clip(s.snap_initiator, 0, self.topo.n - 1)
            slot = jnp.arange(self.cfg.max_snapshots, dtype=_i32)
            s = trace_append_many(s, timed_out, EV_SUP_ABORT, init_n, slot)
            s = trace_append_many(s, can_retry, EV_SUP_RETRY, init_n, slot)
            s = trace_append_many(s, failed, EV_SUP_FAIL, init_n, slot)
        return s, can_retry

    def _sup_reinitiate_ring(self, s: DenseState, retry) -> DenseState:
        """Re-initiate each retried slot from its remembered initiator
        (slot order = draw order), under the already-bumped epoch: a
        fresh CreateLocalSnapshot recording ALL inbound links plus a
        marker broadcast tagged with the new epoch. A zero-retry tick
        runs zero loop iterations and draws nothing — the golden-parity
        property for an armed-but-idle supervisor."""
        S = self.cfg.max_snapshots

        def body(carry):
            s, m = carry
            sid = jnp.argmax(m).astype(_i32)
            node = jnp.clip(s.snap_initiator[sid], 0, self.topo.n - 1)
            s = self._create_local(s, sid, node, jnp.int32(-1))
            s = self._broadcast_markers(s, node, sid)
            return s, m & (jnp.arange(S, dtype=_i32) != sid)

        s, _ = lax.while_loop(lambda c: jnp.any(c[1]), body, (s, retry))
        return s

    def _sup_reinitiate_split(self, s: DenseState, retry) -> DenseState:
        """Split-mode re-initiation: one vectorized create+broadcast over
        the retried slots' initiators, gated so its (S, E) delay draws
        only happen on ticks where a retry actually fires."""
        created = retry[..., :, None] & (
            jnp.arange(self.topo.n, dtype=_i32)
            == jnp.clip(s.snap_initiator, 0, self.topo.n - 1)[..., :, None])
        return lax.cond(jnp.any(retry),
                        lambda s: self._create_and_broadcast(s, created),
                        lambda s: s, s)

    def _sup_daemon(self, s: DenseState) -> DenseState:
        """The snapshot_every daemon: initiate a snapshot from a rotating
        initiator every K ticks while free slots remain, so lossy crashes
        always find a recent recovery line. Gated under lax.cond — an
        idle tick draws nothing."""
        every = self.cfg.snapshot_every
        S = self.cfg.max_snapshots
        node = (s.time // every) % self.topo.n
        fire = (s.time % every == 0) & (s.time > 0) & (s.next_sid < S)
        if self.marker_mode == "ring":
            return lax.cond(fire,
                            lambda s: self._inject_snapshot(s, node),
                            lambda s: s, s)
        mask = fire & (jnp.arange(self.topo.n, dtype=_i32) == node)
        return lax.cond(fire, lambda s: self._bulk_snapshots(s, mask),
                        lambda s: s, s)

    def _supervise(self, s: DenseState) -> DenseState:
        """The per-tick supervisor step, run at tick start (after the
        time increment and crash restarts, before delivery selection) in
        the cascade, wave and sync ticks: the daemon, then the timeout
        scan + re-initiation. Re-initiated markers carry receive times
        > time, so the tick's delivery selection is untouched."""
        if self.cfg.snapshot_every:
            s = self._sup_daemon(s)
        if self.cfg.snapshot_timeout:
            s, retry = self._sup_scan(s)
            if self.marker_mode == "ring":
                s = self._sup_reinitiate_ring(s, retry)
            else:
                s = self._sup_reinitiate_split(s, retry)
        return s

    def _stamp_done(self, s: DenseState) -> DenseState:
        """Record each snapshot's completion tick (once, at the tick it
        reached all nodes) — the recovery-line-age metric's source
        (utils/metrics.snapshot_lifecycle). Traced unconditionally: one
        [S] where per tick, identical across supervised and unsupervised
        kernels."""
        newly = (s.started & (s.completed >= self.topo.n)
                 & (s.snap_done_time < 0))
        return s._replace(
            snap_done_time=jnp.where(newly, s.time, s.snap_done_time))

    # ---- queue primitives ------------------------------------------------

    # tiled-megatick ring indirection (kernels/megatick module docstring):
    # while the TILED fused kernel traces a tick, this flag reroutes the
    # tick's only two ring-content touch points — the [E, C] rings live
    # in HBM, and the state carry's q_meta/q_data slots are repurposed as
    # q_meta [2, A+1, E] (rows :A = deferred-append (pos, meta) buffers,
    # row A = the step's pre-extracted (head_meta, head_data) vectors)
    # and q_data [A, E] (append payloads). _head_fields reads the head
    # row, _append_rows defers into the buffer rows AND patches the head
    # row for head-slot appends. The heads ride the STATE — not Python
    # side-state — so the patch flows through lax.cond/while_loop traces
    # (the supervisor's re-initiation appends live inside them) as plain
    # dataflow. False (always, outside a tiled trace) compiles the
    # indirection away entirely.
    _ring_defer = False

    def _head_fields(self, s: DenseState):
        """Every ring head's (rtime, is_marker, data), addressed by
        ``queue_engine``: ONE [E] gather per packed plane
        (``take_along_axis`` at q_head), or the legacy [E, C] one-hot mask
        reductions. Heads of empty queues read their stale slot either way
        (callers gate on q_len > 0), so the engines are bit-identical.
        kernel_engine="pallas" overrides both with the fused VMEM pass;
        a tiled fused trace (``_ring_defer``) serves the pre-extracted
        head row of the repurposed q_meta instead — gathered by the
        previous step's in-kernel commit pass (or ring_heads outside the
        kernel for step 0) and patched by any same-tick head-slot append,
        so the values are exactly what a live read here would return."""
        if self._ring_defer:
            head_meta, head_data = s.q_meta[0, -1], s.q_meta[1, -1]
            return meta_rtime(head_meta), meta_marker(head_meta), head_data
        if self.kernel_engine == "pallas":
            return plk_queue.head_fields(s.q_meta, s.q_data, s.q_head,
                                         interpret=self._pl_interpret)
        if self.queue_engine == "gather":
            head_meta = jnp.take_along_axis(
                s.q_meta, s.q_head[:, None], axis=-1)[..., 0]
            head_data = jnp.take_along_axis(
                s.q_data, s.q_head[:, None], axis=-1)[..., 0]
        else:
            cc = jnp.arange(self.cfg.queue_capacity, dtype=_i32)[None, :]
            head_hit = cc == s.q_head[:, None]                    # [E, C]
            head_meta = jnp.sum(jnp.where(head_hit, s.q_meta, 0), axis=-1,
                                dtype=_i32)
            head_data = jnp.sum(jnp.where(head_hit, s.q_data, 0), axis=-1,
                                dtype=_i32)
        return meta_rtime(head_meta), meta_marker(head_meta), head_data

    def _append_rows(self, s: DenseState, active, rt_e, mk_e,
                     data_e) -> DenseState:
        """THE batched ring append: one message on every edge where
        ``active`` (at most one per edge — callers are per-source-row,
        per-wave or per-phase chunks), with receive times ``rt_e`` already
        drawn by the caller (so every draw-order discipline routes through
        one write primitive). Addressing by ``queue_engine``: a single
        vectorized ``.at[edge, pos]`` scatter per packed plane (inactive
        rows aim at column C and drop — no read-modify-write of old
        slots), or the legacy [E, C] one-hot selects. Flags queue/merge-key
        overflow exactly like the scalar push, plus the packed-rtime bound
        (RTIME_PACK_LIMIT)."""
        C = self.cfg.queue_capacity
        rt_e = jnp.asarray(rt_e, _i32)
        data_e = jnp.broadcast_to(jnp.asarray(data_e, _i32), active.shape)
        meta_e = pack_meta(rt_e, mk_e)
        if self._ring_defer:
            return self._append_rows_deferred(s, active, rt_e, meta_e,
                                              data_e)
        if self.kernel_engine == "pallas":
            q_meta, q_data, err = plk_queue.append_rows(
                s.q_meta, s.q_data, s.q_head, s.q_len, s.tok_pushed,
                active,
                jnp.broadcast_to(meta_e, active.shape),
                jnp.broadcast_to(rt_e, active.shape), data_e,
                capacity=C, key_limit=self._key_limit,
                interpret=self._pl_interpret)
            return s._replace(
                q_meta=q_meta,
                q_data=q_data,
                q_len=s.q_len + active.astype(_i32),
                tok_pushed=s.tok_pushed + active.astype(_i32),
                error=s.error | err[0],
            )
        err = (jnp.any(active & (s.q_len >= C)).astype(_i32)
               * ERR_QUEUE_OVERFLOW
               | (jnp.any(active & (s.tok_pushed >= self._key_limit))
                  | jnp.any(active & (rt_e >= RTIME_PACK_LIMIT))
                  ).astype(_i32) * ERR_VALUE_OVERFLOW)
        pos = (s.q_head + s.q_len) % C
        if self.queue_engine == "gather":
            tgt = jnp.where(active, pos, C)   # inactive -> OOB, dropped
            q_meta = s.q_meta.at[self._rows_e, tgt].set(
                jnp.broadcast_to(meta_e, active.shape),
                mode="drop", unique_indices=True)
            q_data = s.q_data.at[self._rows_e, tgt].set(
                data_e, mode="drop", unique_indices=True)
        else:
            hit = active[:, None] & (jnp.arange(C, dtype=_i32)[None, :]
                                     == pos[:, None])             # [E, C]
            q_meta = jnp.where(hit, jnp.broadcast_to(
                meta_e, active.shape)[:, None], s.q_meta)
            q_data = jnp.where(hit, data_e[:, None], s.q_data)
        return s._replace(
            q_meta=q_meta,
            q_data=q_data,
            q_len=s.q_len + active.astype(_i32),
            tok_pushed=s.tok_pushed + active.astype(_i32),
            error=s.error | err,
        )

    def _append_rows_deferred(self, s: DenseState, active, rt_e, meta_e,
                              data_e) -> DenseState:
        """_append_rows for a TILED fused trace (``_ring_defer`` armed):
        the [E, C] rings live in HBM, so instead of scattering into them
        the append is recorded into the dense [A, E] buffer planes riding
        the carry in ``q_meta``/``q_data``'s place — ``q_meta[0]`` the
        target ring column per ordinal (−1 = unused slot), ``q_meta[1]``
        the packed meta word, ``q_data`` the payload — and the in-kernel
        commit pass (megatick.RingStream.commit_and_heads) replays them
        against the streamed blocks in ordinal order at step end, which
        reproduces the eager path's write order exactly (overflow-wrap
        clobbers included). Everything ELSE is the eager append verbatim:
        the error folds, the q_len/tok_pushed bumps, the captured ring
        column (q_head/q_len are live [E] vectors in the carry).

        Two invariants keep this bit-identical:
          * the ordinal cursor is the count of used buffer slots — NOT
            derived from q_len deltas, which supervisor appends that
            precede the tick's pops would skew;
          * an append landing on an edge's HEAD slot (empty queue, or a
            capacity wrap — pos == q_head either way) also patches the
            head row, so the single head read at _select_and_pop sees
            exactly what a live ring read would (the supervisor appends
            before selection; stale pre-extracted content would
            otherwise leak into the eligibility math). The patch is a
            state write, so it threads through the supervisor's
            lax.cond/while_loop wrappers as ordinary carry dataflow.
        A cursor past A means ring_append_slots' census was violated —
        flagged ERR_QUEUE_OVERFLOW (loud), never silently dropped."""
        C = self.cfg.queue_capacity
        meta_e = jnp.broadcast_to(meta_e, active.shape)
        err = (jnp.any(active & (s.q_len >= C)).astype(_i32)
               * ERR_QUEUE_OVERFLOW
               | (jnp.any(active & (s.tok_pushed >= self._key_limit))
                  | jnp.any(active & (rt_e >= RTIME_PACK_LIMIT))
                  ).astype(_i32) * ERR_VALUE_OVERFLOW)
        pos = (s.q_head + s.q_len) % C
        buf_pos, buf_meta = s.q_meta[0, :-1], s.q_meta[1, :-1]     # [A, E]
        head_meta, head_data = s.q_meta[0, -1], s.q_meta[1, -1]    # [E]
        buf_data = s.q_data
        a = buf_pos.shape[0]
        cursor = jnp.sum((buf_pos >= 0).astype(_i32), axis=0,
                         dtype=_i32)                               # [E]
        err = err | (jnp.any(active & (cursor >= a)).astype(_i32)
                     * ERR_QUEUE_OVERFLOW)
        hit = active[None, :] & (jnp.arange(a, dtype=_i32)[:, None]
                                 == cursor[None, :])               # [A, E]
        buf_pos = jnp.where(hit, pos[None, :], buf_pos)
        buf_meta = jnp.where(hit, meta_e[None, :], buf_meta)
        buf_data = jnp.where(hit, data_e[None, :], buf_data)
        at_head = active & (pos == s.q_head)
        head_meta = jnp.where(at_head, meta_e, head_meta)
        head_data = jnp.where(at_head, data_e, head_data)
        q_meta = jnp.concatenate(
            [jnp.stack([buf_pos, buf_meta]),
             jnp.stack([head_meta, head_data])[:, None, :]], axis=1)
        return s._replace(
            q_meta=q_meta,
            q_data=buf_data,
            q_len=s.q_len + active.astype(_i32),
            tok_pushed=s.tok_pushed + active.astype(_i32),
            error=s.error | err,
        )

    def _push(self, s: DenseState, e, is_marker: bool, data) -> DenseState:
        """Append to edge e's ring buffer with one delay draw
        (node.go:126-130 / node.go:104-108)."""
        rtime, dstate = self.delay.draw(s.delay_state, s.time)
        C = self.cfg.queue_capacity
        rtime = jnp.asarray(rtime, _i32)
        pos = (s.q_head[e] + s.q_len[e]) % C
        err = s.error | jnp.where(s.q_len[e] >= C, ERR_QUEUE_OVERFLOW, 0).astype(_i32)
        err = err | jnp.where((s.tok_pushed[e] >= self._key_limit)
                              | (rtime >= RTIME_PACK_LIMIT),
                              ERR_VALUE_OVERFLOW, 0).astype(_i32)
        # split-mode rings never hold markers (_push is token-only there),
        # so the packed marker bit is correct in both modes
        return s._replace(
            q_meta=s.q_meta.at[e, pos].set(pack_meta(rtime, is_marker)),
            q_data=s.q_data.at[e, pos].set(jnp.asarray(data, _i32)),
            q_len=s.q_len.at[e].add(1),
            # split-mode merge-order counter; meaningless (but harmless) in
            # ring mode, where _push also carries markers and FIFO order is
            # the ring itself
            tok_pushed=s.tok_pushed.at[e].add(1),
            delay_state=dstate,
            error=err,
        )

    def _push_marker(self, s: DenseState, e, sid) -> DenseState:
        """Scalar marker enqueue, routed by marker_mode: into the ring
        (exact scheduler; payload = the epoch-tagged word when the
        supervisor is armed) or the [S, E] pending planes (split mode,
        where the plane index is the id and aborts clear in place — no
        epoch storage needed). One delay draw either way, so the sampler
        stream is mode-invariant."""
        if self._trace_on:
            # the trace carries the RAW sid; the epoch-packed word is a
            # wire encoding (state.pack_marker_data), not an event fact
            s = trace_append_one(s, True, EV_MSEND, e, sid)
        if self.marker_mode == "ring":
            return self._push(s, e, True, self._marker_payload(s, sid))
        rtime, dstate = self.delay.draw(s.delay_state, s.time)
        return s._replace(
            m_pending=s.m_pending.at[sid, e].set(True),
            m_rtime=s.m_rtime.at[sid, e].set(jnp.asarray(rtime, _i32)),
            m_key=s.m_key.at[sid, e].set(
                s.tok_pushed[e] * self._keymult + s.mk_cnt[e]),
            mk_cnt=s.mk_cnt.at[e].add(1),
            delay_state=dstate,
        )

    # ---- protocol handlers (node.go) ------------------------------------

    def _create_local(self, s: DenseState, sid, node, exclude_edge,
                      cnt_extra=0) -> DenseState:
        """CreateLocalSnapshot (node.go:58-84): freeze tokens, record all
        inbound links except the marker's own (exclude_edge == -1 for the
        initiator case). ``cnt_extra`` ([E] i32 or 0) compensates for the
        cascade tick's deferred log appends: windows must open at the
        counter each edge WILL have once this tick's earlier-rank appends
        land (0 from the fold/injection paths, whose rec_cnt is live)."""
        E = self.topo.e
        inbound = self._edge_dst == node
        rec_mask = inbound & (jnp.arange(E, dtype=_i32) != exclude_edge)
        links = self._in_degree[node] - jnp.asarray(exclude_edge >= 0, _i32)
        cnt = s.rec_cnt + cnt_extra
        return s._replace(
            has_local=s.has_local.at[sid, node].set(True),
            frozen=s.frozen.at[sid, node].set(s.tokens[node]),
            rem=s.rem.at[sid, node].set(links),
            recording=s.recording.at[sid].set(
                jnp.where(rec_mask, True, s.recording[sid])),
            # window start: this slot records the edge's arrivals from here
            rec_start=s.rec_start.at[sid].set(
                jnp.where(rec_mask, cnt.astype(s.rec_start.dtype),
                          s.rec_start[sid])),
            min_prot=jnp.where(rec_mask,
                               jnp.minimum(s.min_prot, cnt),
                               s.min_prot),
        )

    def _broadcast_markers(self, s: DenseState, node, sid) -> DenseState:
        """SendToNeighbors (node.go:97-109): marker on every outbound link in
        dest order, one delay draw per real link (padding slots draw
        nothing). Ring mode enqueues the whole row through ONE batched
        append (_append_rows) instead of D scalar pushes: the delay draws
        keep their sequential dest-order stream positions (served
        positionally for position-addressable samplers, by a scan that
        threads only the sampler state otherwise), and the ring writes —
        distinct edges, order-free — land as one vectorized scatter."""
        if self.marker_mode == "split":
            def body(k, s):
                e = self._edge_table[node, k]
                return lax.cond(e >= 0,
                                lambda s: self._push_marker(s, e, sid),
                                lambda s: s, s)
            return lax.fori_loop(0, self.topo.d, body, s)
        row = self._edge_table[node]                        # [D], -1 padded
        valid = row >= 0
        if self.delay.position_streams:
            # draw k's stream position = its rank among the row's real
            # links (same positions sequential draws would consume)
            off = jnp.cumsum(valid.astype(_i32)) - valid
            rts_k = jnp.asarray(self.delay.block_receive_times(
                s.delay_state, s.time, off), _i32)
            dstate = self.delay.advance_draws(
                s.delay_state, jnp.sum(valid, dtype=_i32))
        else:
            # order-dependent sampler (GoExact): the draws stay a
            # sequential scan, but it carries only the sampler state —
            # the [E, C] ring writes move out of the loop
            def step(dstate, e):
                def real(d):
                    rt, d2 = self.delay.draw(d, s.time)
                    return d2, jnp.asarray(rt, _i32)

                return lax.cond(e >= 0, real,
                                lambda d: (d, _i32(0)), dstate)

            dstate, rts_k = lax.scan(step, s.delay_state, row)
        s = s._replace(delay_state=dstate)
        tgt = jnp.where(valid, row, self.topo.e)            # pads drop
        active = jnp.zeros(self.topo.e, jnp.bool_).at[tgt].set(
            True, mode="drop")
        rt_e = jnp.zeros(self.topo.e, _i32).at[tgt].set(rts_k, mode="drop")
        s = self._append_rows(s, active, rt_e, True,
                              self._marker_payload(s, sid))
        if self._trace_on:
            # active edges are the broadcaster's outbound row in edge
            # (= dest) order — the ranked append preserves it, matching
            # the reference's sorted-dest send loop (node.go:98)
            s = trace_append_many(s, active, EV_MSEND, self._rows_e,
                                  jnp.asarray(sid, _i32))
        return s

    def _finalize_check(self, s: DenseState, sid, node) -> DenseState:
        """finalizeSnapshot + NotifyCompletedSnapshot when no links remain
        recording (node.go:165-170). The message flattening itself is a
        decode-time gather — the per-edge log is already in arrival order."""
        fire = (s.has_local[sid, node] & (s.rem[sid, node] == 0)
                & ~s.done_local[sid, node])
        if self._trace_on:
            s = trace_append_one(s, fire, EV_SNAP_END, node, sid)
        return s._replace(
            done_local=s.done_local.at[sid, node].set(
                s.done_local[sid, node] | fire),
            completed=s.completed.at[sid].add(jnp.asarray(fire, _i32)),
        )

    def _handle_marker(self, s: DenseState, e, sid, cnt_extra=0) -> DenseState:
        """HandleMarker (node.go:149-171). First marker for sid at this node:
        create the local snapshot excluding the marker's link, then re-broadcast
        (node.StartSnapshot, node.go:198-212). Repeat marker: stop recording
        that link. Either way, check finalization after (R8). ``cnt_extra``
        threads the cascade's deferred-append compensation to
        _create_local; the repeat branch needs none (edge e delivered this
        marker, so its own count has no pending append this tick)."""
        dst = self._edge_dst[e]
        if self._trace_on:
            # receipt recorded before handling, like the reference
            # (node.go:141 logs before dispatch)
            s = trace_append_one(s, True, EV_MRECV, e, sid)

        def first(s):
            s = self._create_local(s, sid, dst, e, cnt_extra=cnt_extra)
            return self._broadcast_markers(s, dst, sid)

        def repeat(s):
            # close the window at the current append counter. Without
            # marker faults a repeat always finds the channel recording
            # (each id crosses an edge once; the excluded channel consumed
            # the FIRST marker); a DUPLICATED marker can re-arrive after
            # the close, so the rem decrement and window close are gated
            # on the channel actually still recording
            was = s.recording[sid, e]
            return s._replace(
                recording=s.recording.at[sid, e].set(False),
                rem=s.rem.at[sid, dst].add(-was.astype(_i32)),
                rec_end=s.rec_end.at[sid, e].set(
                    jnp.where(was, s.rec_cnt[e].astype(s.rec_end.dtype),
                              s.rec_end[sid, e])),
            )

        s = lax.cond(~s.has_local[sid, dst], first, repeat, s)
        return self._finalize_check(s, sid, dst)

    def _handle_token(self, s: DenseState, e, amount) -> DenseState:
        """HandleToken (node.go:174-185): credit the destination; if ANY
        snapshot slot is recording this edge, append the amount once to the
        edge's shared arrival log — every recording slot's window covers
        it (DenseState "Recording as windows")."""
        if self._trace_on:
            s = trace_append_one(s, True, EV_RECV, e, amount)
        L = self.cfg.max_recorded
        dst = self._edge_dst[e]
        rec = jnp.any(s.recording[:, e])
        pos = s.rec_cnt[e] % L
        amount_i = jnp.asarray(amount, _i32)
        new_cnt = s.rec_cnt[e] + jnp.asarray(rec, _i32)
        err = s.error | jnp.where(rec & (new_cnt - s.min_prot[e] > L),
                                  ERR_RECORD_OVERFLOW, 0).astype(_i32)
        err = err | jnp.where(rec & (amount_i > self._rec_limit),
                              ERR_VALUE_OVERFLOW, 0).astype(_i32)
        return s._replace(
            tokens=s.tokens.at[dst].add(amount_i),
            log_amt=s.log_amt.at[pos, e].set(
                jnp.where(rec, jnp.asarray(amount, self._rec_dtype),
                          s.log_amt[pos, e])),
            rec_cnt=s.rec_cnt.at[e].set(new_cnt),
            error=err,
        )

    def _deliver(self, s: DenseState, e) -> DenseState:
        """Pop edge e's head and dispatch (HandlePacket, node.go:140-146)."""
        C = self.cfg.queue_capacity
        slot = s.q_head[e]
        is_marker = meta_marker(s.q_meta[e, slot])
        data = s.q_data[e, slot]
        s = s._replace(q_head=s.q_head.at[e].set((slot + 1) % C),
                       q_len=s.q_len.at[e].add(-1))
        return lax.cond(is_marker,
                        lambda s: self._handle_marker(s, e, data),
                        lambda s: self._handle_token(s, e, data), s)

    # ---- the tick (sim.go:71-95) ----------------------------------------

    def _tick(self, s: DenseState) -> DenseState:
        s = s._replace(time=s.time + 1)

        def per_source(s, n):
            edges = self._edge_table[n]                     # [D], -1 padded
            valid = edges >= 0
            safe = jnp.where(valid, edges, 0)
            heads = s.q_head[safe]
            rts = meta_rtime(s.q_meta[safe, heads])
            elig = valid & (s.q_len[safe] > 0) & (rts <= s.time)
            found = jnp.any(elig)
            e = safe[jnp.argmax(elig)]                      # first in dest order
            s = lax.cond(found, lambda s: self._deliver(s, e), lambda s: s, s)
            return s, None

        s, _ = lax.scan(per_source, s, jnp.arange(self.topo.n, dtype=_i32))
        return self._stamp_done(s)

    # ---- shared tick-start machinery for the vectorized exact forms -----

    def _select_and_pop(self, s: DenseState, fmasks=None):
        """Tick-start delivery selection shared by the cascade and wave
        formulations (fact 1 in _cascade_tick's docstring: selection is
        invariant over the fold, so every selected head can be popped up
        front with its payload captured). ``s.time`` must already be the
        new tick's time. Head reads are queue_engine-addressed
        (_head_fields): O(E) gathers of the packed planes, or the legacy
        O(E·C) one-hot reductions. Returns (s, tok_pend, mk_pend,
        head_data)."""
        C = self.cfg.queue_capacity
        if self.kernel_engine == "pallas" and self.faults is None:
            # the fully fused form: head gather + eligibility + selection
            # + pop in one VMEM pass (the fault path below splits at the
            # eligibility gates so adversary semantics stay byte-for-byte)
            tok_pend, mk_pend, head_data, new_head, new_len = (
                plk_queue.queue_step(
                    s.q_meta, s.q_data, s.q_head, s.q_len, s.time,
                    self._src_first, capacity=C,
                    interpret=self._pl_interpret))
            return (s._replace(q_head=new_head, q_len=new_len),
                    tok_pend, mk_pend, head_data)
        head_rt, head_mk, head_data = self._head_fields(s)
        elig = (s.q_len > 0) & (head_rt <= s.time)
        if self.faults is not None:
            # delivery-side fault gates: jitter stalls the front (the
            # marker-plane jitter program stalls marker fronts on top),
            # a down destination receives nothing (messages wait,
            # lossless)
            _, _, jit_e, _ = self._fault_edge_masks(s, fmasks)
            _, _, mjit_e, _ = self._fault_marker_masks(s, fmasks)
            s, elig = self._fault_gate_elig(s, elig, jit_e, mjit_e, head_mk,
                                            fmasks)
        if self.kernel_engine == "pallas":
            sel, new_head, new_len = plk_queue.select_pop(
                s.q_head, s.q_len, elig, self._src_first, capacity=C,
                interpret=self._pl_interpret)
            s = s._replace(q_head=new_head, q_len=new_len)
            return s, sel & ~head_mk, sel & head_mk, head_data
        # first eligible edge per source in dest order (same O(E) prefix-
        # count formulation as _sync_tick; edges are per-source contiguous)
        elig_i = elig.astype(_i32)
        before = jnp.cumsum(elig_i) - elig_i
        sel = elig & (before == before[self._src_first])
        # pop every selected head now: selection is invariant (fact 1), and
        # the captured head_data/head_mk carry the payloads
        s = s._replace(q_head=(s.q_head + sel) % C,
                       q_len=s.q_len - sel.astype(_i32))
        return s, sel & ~head_mk, sel & head_mk, head_data

    def _credit(self, s: DenseState, mask, amt_e) -> DenseState:
        """HandleToken's balance half (node.go:175), vectorized: cheap
        [E] -> [N] integer segment sums, applied eagerly per chunk so
        _create_local freezes the right balances (node.go:77)."""
        if self.kernel_engine == "pallas":
            return s._replace(tokens=s.tokens + plk_segment.sum_by_perm(
                jnp.where(mask, amt_e, 0), self._by_dst, self._dst_lo,
                self._dst_hi, interpret=self._pl_interpret))
        xs = jnp.take(jnp.where(mask, amt_e, 0), self._by_dst, axis=-1)
        return s._replace(tokens=s.tokens + self._segment_sums(
            xs, self._dst_lo, self._dst_hi))

    def _seg_excl(self, x_d):
        """Per-destination-segment EXCLUSIVE running sums of an [..., E]
        operand already permuted into by_dst order: one global exclusive
        cumsum rebased at each position's (static) segment start."""
        cs0 = jnp.cumsum(x_d, axis=-1) - x_d
        return cs0 - jnp.take(cs0, self._pos_seg_start, axis=-1)

    # ---- the cascade tick: bit-exact semantics without the N-step fold ---

    def _cascade_tick(self, s: DenseState, fmasks=None) -> DenseState:
        """Bit-identical to ``_tick`` (the reference fold, sim.go:71-95) but
        O(E) vector work + one sequential step per MARKER delivered, instead
        of an N-step scan per tick.

        Why this is exact. The reference scans sources in sorted order,
        delivering each source's first eligible head (sim.go:76-92). Three
        facts make the N-step fold collapsible:

        1. **Delivery selection is fixed at tick start.** Mid-tick pushes
           carry ``receiveTime = time + 1 + delay > time`` (sim.go:100-102),
           so they are never same-tick eligible, never change an existing
           eligible head (pushes append at the tail), and an ineligible new
           head of a previously-empty queue is scanned past exactly like the
           empty queue was (sim.go:82-84 continues either way). Pops only
           touch the delivering source's own queues. Hence the per-source
           "first eligible head in dest order" is invariant over the fold
           and can be computed once, vectorized.
        2. **Token deliveries commute with each other.** Each selected edge
           delivers at most one message (one delivery per source, distinct
           edges), token credits are integer sums, and the shared-log append
           touches each edge at most once per tick. Tokens draw no PRNG.
        3. **Only markers need the fold.** Ordering-sensitive interactions
           are marker→marker (has_local/rem, cascade re-broadcasts and
           their PRNG draw order), marker→token (recording windows opened/
           closed mid-tick), and token→marker (CreateLocalSnapshot freezes
           the live balance, node.go:77). All three are preserved by
           processing markers one at a time in source-rank order and
           applying every pending token delivery with source rank < the
           next marker's rank (vectorized) before it.

        Edges are (src, dst)-sorted, so ascending edge index == the
        reference's scan order, and at most one selected edge per source
        means the pending-marker mask's first True edge IS the next marker
        in fold order. A tick with no marker deliveries — the vast majority
        — runs zero fold iterations. This is what makes the bit-exact
        scheduler usable at N=8192 (the N-step scan program faulted the
        device) and at production batch widths (VERDICT r3 #2/#3).

        Transient-capacity edge vs the fold: the fold still holds a
        not-yet-delivered selected head when an earlier marker's cascade
        pushes onto the same ring, so at exactly-full capacity it flags
        ERR_QUEUE_OVERFLOW (and clobbers the head) where this form — which
        pops every selected head up front — still fits. The reference's
        queues are unbounded (queue.go), so the cascade form is the more
        faithful one at equal C; whenever neither impl flags, they are
        bit-identical. Size C with SimConfig.for_workload as always.
        """
        s = s._replace(time=s.time + 1)
        dup_pend = dup_rt = mk_dup = mdup_rt = None
        if self.faults is not None:
            s = self._fault_restart(s, fmasks)
        if self._sup:
            s = self._supervise(s)
        s, tok_pend, mk_pend, head_data = self._select_and_pop(s, fmasks)
        if self.faults is not None:
            # drop/dup act on the popped token set; the marker fold below
            # never sees a dropped token (it vanished on the wire), and
            # duplicates re-enqueue after the fold so this tick's selection
            # is untouched (their receive times are > time anyway). The
            # marker-plane program does the same to the popped markers —
            # a dropped marker is exactly the control-plane loss the
            # supervisor's timeout recovers from
            drop_e, dup_e, _, dup_rt = self._fault_edge_masks(s, fmasks)
            s, tok_pend, dup_pend = self._fault_split_tokens(
                s, tok_pend, head_data, drop_e, dup_e)
            s, mk_pend, mk_dup, mdup_rt = self._fault_split_markers(
                s, mk_pend, fmasks)
        # superseded-epoch markers die here (counted), and sid_e becomes
        # the decoded slot id (the raw payload when unsupervised)
        s, mk_pend, sid_e = self._reject_stale(s, mk_pend, head_data)
        amt_e = jnp.where(tok_pend, head_data, 0)
        rows = self._rows_e

        def credit(s, mask):
            return self._credit(s, mask, amt_e)

        # HandleToken's recording half is DEFERRED: each edge appends at
        # most once per tick (at a fixed log position), so the heavy [L, E]
        # log write happens once at the end under the accumulated mask —
        # but the mask itself must be taken per chunk, against the
        # recording state at that fold position (a window opened by a
        # later marker must not swallow an earlier token), and
        # _create_local opens windows at rec_cnt + pending appends.
        def cond(carry):
            return jnp.any(carry[1])

        def body(carry):
            s, mk, tok, app = carry
            found = jnp.any(mk)
            # lowest edge = lowest source. Formulated as a min-over-mask
            # rather than argmax: argmax yields the platform int (i64
            # under x64) and the index feeds [E]-plane compares; the
            # found=False sentinel mirrors argmax's 0 so batched inactive
            # lanes trace identically.
            e = jnp.min(jnp.where(mk, self._rows_e, _i32(self.topo.e)))
            e = jnp.where(jnp.any(mk), e, _i32(0))
            r = jnp.where(found, self._edge_src[e], _i32(self.topo.n))
            tmask = tok & (self._edge_src < r)
            s = credit(s, tmask)
            if self._trace_on:
                # the chunk's RECVs in ascending edge (= ascending source)
                # order, before the marker that bounds it — exactly the
                # reference fold's interleaving
                s = trace_append_many(s, tmask, EV_RECV, self._rows_e,
                                      amt_e)
            app = app | (tmask & jnp.any(s.recording, axis=-2))
            s = lax.cond(found,
                         lambda s: self._handle_marker(
                             s, e, sid_e[e], cnt_extra=app.astype(_i32)),
                         lambda s: s, s)
            return s, mk & (rows != e), tok & ~tmask, app

        s, _, tok_pend, app = lax.while_loop(
            cond, body, (s, mk_pend, tok_pend, jnp.zeros_like(tok_pend)))
        s = credit(s, tok_pend)
        if self._trace_on:
            s = trace_append_many(s, tok_pend, EV_RECV, self._rows_e, amt_e)
        app = app | (tok_pend & jnp.any(s.recording, axis=-2))
        log, cnt, err = log_append_masked(
            s.log_amt, s.rec_cnt, s.min_prot, app, amt_e,
            self._rec_dtype, self._rec_limit, self.cfg.max_recorded)
        s = s._replace(log_amt=log, rec_cnt=cnt, error=s.error | err)
        if self.faults is not None:
            # duplicated tokens AND markers re-enter their channel at the
            # tail (disjoint edge sets: an edge delivered one or the
            # other), receive times from the fault streams (the delay
            # sampler never sees a fault), marker duplicates keeping their
            # epoch-tagged payload, overflow flagged by the shared append
            # primitive
            s = self._append_rows(s, dup_pend | mk_dup,
                                  jnp.where(mk_dup, mdup_rt, dup_rt),
                                  mk_dup, head_data)
        return self._stamp_done(s)

    # ---- the wave tick: the cascade with cross-destination parallelism --

    def _wave_tick(self, s: DenseState, fmasks=None) -> DenseState:
        """Bit-identical to ``_cascade_tick`` for position-addressable delay
        samplers (JaxDelay.position_streams), but each sequential step
        processes EVERY pending marker bound for a distinct destination at
        once — sequential steps per tick drop from "markers delivered" to
        "max markers per single destination", the conflict depth.

        Why cross-destination markers commute. handle_marker(e, sid) at
        dst = dst(e) touches only per-(sid, dst) cells (has_local, frozen,
        rem, done_local), per-inbound-edge-of-dst planes (recording,
        rec_start/rec_end, min_prot — min is order-free), per-outbound-
        edge-of-dst ring slots (the re-broadcast pushes), and commutative
        accumulators (completed). Distinct destinations have disjoint
        inbound/outbound edge sets and disjoint (sid, dst) cells, so the
        ONLY cross-destination coupling in the fold is the delay sampler's
        draw order — and for a sampler whose draw value depends only on
        its stream position, every broadcast draw's fold-order position is
        computable at tick start (whether a pending marker is a FIRST
        receipt — the only kind that draws — depends on has_local plus
        earlier same-(sid, dst) pending markers, both tick-start facts),
        so waves can serve the draws out of order, bit-identically.

        Token interleaving is per-destination too: a token on edge t only
        couples to markers at dst(t) (its credit feeds frozen[.., dst(t)];
        its append mask reads recording[:, t], which only markers at
        dst(t) or on edge t itself can change). Each wave applies, per
        destination, exactly the tokens whose fold rank precedes that
        destination's current marker — the same prefix the cascade's
        one-marker steps apply, reassociated across commuting credits.

        Same-destination markers (the genuinely sequential interactions:
        has_local/rem evolution, window closes, frozen balances between
        two same-dst markers) stay ordered: wave k takes each
        destination's k-th pending marker in edge (= fold) order.

        Capacity semantics match the cascade exactly (heads popped up
        front; the documented fold divergence at exactly-full C applies
        unchanged). Reference semantics carried: node.go:149-185 (the
        handlers), sim.go:76-92 (the fold this reassociates).
        """
        C = self.cfg.queue_capacity
        S, E = self.cfg.max_snapshots, self.topo.e
        s = s._replace(time=s.time + 1)
        dup_pend = dup_rt = mk_dup = mdup_rt = None
        if self.faults is not None:
            s = self._fault_restart(s, fmasks)
        if self._sup:
            s = self._supervise(s)
        time = s.time
        s, tok_pend, mk_pend, head_data = self._select_and_pop(s, fmasks)
        if self.faults is not None:
            # same drop/dup discipline as the cascade (one shared hook
            # set), token and marker planes alike
            drop_e, dup_e, _, dup_rt = self._fault_edge_masks(s, fmasks)
            s, tok_pend, dup_pend = self._fault_split_tokens(
                s, tok_pend, head_data, drop_e, dup_e)
            s, mk_pend, mk_dup, mdup_rt = self._fault_split_markers(
                s, mk_pend, fmasks)
        s, mk_pend, sid_e = self._reject_stale(s, mk_pend, head_data)
        amt_e = jnp.where(tok_pend, head_data, 0)
        rank_e = self._rows_e                   # fold rank == edge index
        onehot_se = jnp.arange(S, dtype=_i32)[:, None] == sid_e[None, :]

        # ---- tick-start schedule: which pending markers are FIRST
        # receipts (they alone draw delays and broadcast), and each one's
        # fold-order draw-counter base
        pend_se = onehot_se & mk_pend[None, :]                     # [S, E]
        earlier_d = self._seg_excl(
            jnp.take(pend_se.astype(_i32), self._by_dst, axis=-1))
        earlier_se = jnp.take(earlier_d, self._inv_by_dst, axis=-1)
        earlier_same = jnp.sum(jnp.where(pend_se, earlier_se, 0), axis=-2,
                               dtype=_i32)
        hl_e = jnp.any(onehot_se & jnp.take(s.has_local, self._edge_dst,
                                            axis=-1), axis=-2)     # [E]
        first_e = mk_pend & ~hl_e & (earlier_same == 0)
        draws_e = jnp.where(first_e, self._outdeg_dst_e, 0)
        base_e = jnp.cumsum(draws_e, axis=-1) - draws_e            # [E]
        # the stream advances past the whole tick's draws up front; waves
        # read their slices positionally from the frozen pre-tick state
        dstate0 = s.delay_state
        s = s._replace(delay_state=self.delay.advance_draws(
            dstate0, jnp.sum(draws_e, axis=-1, dtype=_i32)))
        # wave number: each pending marker's rank among its destination's
        # pending markers (fold order within the destination, ANY sid) —
        # computed ONCE per tick; wave k just masks wnum == k
        wnum_e = jnp.take(
            self._seg_excl(jnp.take(mk_pend.astype(_i32), self._by_dst,
                                    axis=-1)),
            self._inv_by_dst, axis=-1)                             # [E]
        sid_rows = jnp.arange(S, dtype=_i32)[:, None]              # [S, 1]

        def cond(carry):
            return jnp.any(carry[1])

        def body(carry):
            s, mk_rem, tok_rem, app, k = carry
            # this wave: each destination's k-th pending marker
            wm = mk_rem & (wnum_e == k)
            # the wave marker's per-edge facts, scattered to [N] per
            # destination in ONE stacked integer segment sum (at most one
            # marker per destination per wave; f32 matmuls are out — the
            # draw bases exceed the f32-exact range)
            stacked = jnp.stack(
                [wm.astype(_i32),
                 jnp.where(wm, sid_e, 0),
                 jnp.where(wm, rank_e, 0),
                 (wm & first_e).astype(_i32),
                 jnp.where(wm, base_e, 0)], axis=-2)               # [5, E]
            per_dst = self._segment_sums(
                jnp.take(stacked, self._by_dst, axis=-1),
                self._dst_lo, self._dst_hi)                        # [5, N]
            wdst = per_dst[..., 0, :] > 0                          # [N]
            wsid_n = per_dst[..., 1, :]
            wexcl_n = per_dst[..., 2, :]    # the marker's own edge, per dst
            wrank_n = jnp.where(wdst, wexcl_n, E)    # no marker -> +inf
            wfirst_n = per_dst[..., 3, :] > 0                      # [N]
            wbase_n = per_dst[..., 4, :]
            # tokens whose fold rank precedes their destination's marker
            tmask = tok_rem & (rank_e < jnp.take(wrank_n, self._edge_dst,
                                                 axis=-1))
            s = self._credit(s, tmask, amt_e)
            if self._trace_on:
                # wave-order events (per-destination interleaving is
                # reassociated vs the fold — TickKernel docstring; the
                # per-tick event SET is identical)
                s = trace_append_many(s, tmask, EV_RECV, self._rows_e,
                                      amt_e)
                s = trace_append_many(s, wm, EV_MRECV, self._rows_e, sid_e)
            app = app | (tmask & jnp.any(s.recording, axis=-2))
            tok_rem = tok_rem & ~tmask
            # repeat markers: close their own channel's window (node.go:
            # 160-164); rec_cnt[e] is live — a marker edge has no pending
            # append this tick. 0/1 counts ride the reduce_mode="auto"
            # selection the sync tick uses (_sum_by_dst): MXU incidence
            # matmuls while the [N, E] matrix is small, O(E) integer
            # segment sums at large N — unlike the stacked rank/base sums
            # above, whose values exceed the f32-exact range
            rep_se = onehot_se & (wm & ~first_e)[None, :]          # [S, E]
            # a DUPLICATED repeat can arrive after its channel's window
            # already closed — only live closes decrement rem / stamp
            # rec_end (the cascade's `was` gate, vectorized)
            rep_live = rep_se & s.recording
            rep_sn = self._sum_by_dst(rep_live, amounts=False)     # [S, N]
            first_sn = (sid_rows == wsid_n[None, :]) & wfirst_n[None, :]
            # first markers: CreateLocalSnapshot excluding the marker's
            # link (node.go:58-84), windows opened at the counter each edge
            # will have once this tick's earlier-rank appends land; the
            # bool node->edge broadcasts are mode-aware too (_spread_dst /
            # _spread_src: MXU in matmul mode, static-index take in segsum)
            open_e = (self._spread_dst(wfirst_n)
                      & (rank_e != jnp.take(wexcl_n, self._edge_dst,
                                            axis=-1)))
            open_se = ((sid_rows == jnp.take(wsid_n, self._edge_dst,
                                             axis=-1)[None, :])
                       & open_e[None, :])                          # [S, E]
            cnt_open = s.rec_cnt + app.astype(_i32)
            s = s._replace(
                recording=(s.recording | open_se) & ~rep_se,
                rec_end=jnp.where(
                    rep_live, s.rec_cnt[None, :].astype(s.rec_end.dtype),
                    s.rec_end),
                rec_start=jnp.where(
                    open_se, cnt_open[None, :].astype(s.rec_start.dtype),
                    s.rec_start),
                min_prot=jnp.where(open_e,
                                   jnp.minimum(s.min_prot, cnt_open),
                                   s.min_prot),
                has_local=s.has_local | first_sn,
                frozen=jnp.where(first_sn, s.tokens[None, :], s.frozen),
                rem=jnp.where(first_sn,
                              self._in_degree[None, :] - 1,
                              s.rem - rep_sn),
            )
            # re-broadcast (node.go:97-109): one marker per outbound edge
            # of each first-receipt destination, receive times served from
            # the tick-start stream positions, enqueued through the one
            # batched append primitive (engine-addressed scatter)
            push_g = self._spread_src(wfirst_n)                    # [E]
            sid_g = jnp.take(wsid_n, self._edge_src, axis=-1)
            off_g = (jnp.take(wbase_n, self._edge_src, axis=-1)
                     + self._edge_ord_in_src)
            rt_g = self.delay.block_receive_times(dstate0, time, off_g)
            s = self._append_rows(s, push_g, rt_g, True, sid_g)
            if self._trace_on:
                s = trace_append_many(s, push_g, EV_MSEND, self._rows_e,
                                      sid_g)
            # finalize after every receipt (R8, node.go:165-170)
            wm_sn = (sid_rows == wsid_n[None, :]) & wdst[None, :]  # [S, N]
            fire = wm_sn & s.has_local & (s.rem == 0) & ~s.done_local
            if self._trace_on:
                nn = jnp.arange(self.topo.n, dtype=_i32)
                s = trace_append_many(
                    s, fire, EV_SNAP_END,
                    jnp.broadcast_to(nn[None, :], fire.shape),
                    jnp.broadcast_to(sid_rows, fire.shape))
            s = s._replace(
                done_local=s.done_local | fire,
                completed=s.completed + jnp.sum(fire, axis=-1, dtype=_i32))
            return s, mk_rem & ~wm, tok_rem, app, k + 1

        s, _, tok_rem, app, _ = lax.while_loop(
            cond, body, (s, mk_pend, tok_pend, jnp.zeros_like(tok_pend),
                         jnp.int32(0)))
        s = self._credit(s, tok_rem, amt_e)
        if self._trace_on:
            s = trace_append_many(s, tok_rem, EV_RECV, self._rows_e, amt_e)
        app = app | (tok_rem & jnp.any(s.recording, axis=-2))
        log, cnt, err = log_append_masked(
            s.log_amt, s.rec_cnt, s.min_prot, app, amt_e,
            self._rec_dtype, self._rec_limit, self.cfg.max_recorded)
        s = s._replace(log_amt=log, rec_cnt=cnt, error=s.error | err)
        if self.faults is not None:
            s = self._append_rows(s, dup_pend | mk_dup,
                                  jnp.where(mk_dup, mdup_rt, dup_rt),
                                  mk_dup, head_data)
        return self._stamp_done(s)

    # ---- the synchronous tick (fast-path scheduler) ----------------------

    def _sync_tick(self, s: DenseState) -> DenseState:
        """The production scheduler: every source delivers its first eligible
        head simultaneously, with 'all tokens before all markers' ordering
        within the tick. A different — still deterministic — scheduler from
        the reference's sequential fold (sim.go:71-95): the set of delivered
        messages per tick is identical (first eligible head per source in
        dest order, per-channel FIFO and head-of-line blocking intact);
        delivery *interleaving* corresponds to the sequential schedule
        'all token deliveries, then markers grouped by snapshot id' instead
        of source-rank order. Every tick is a valid Chandy-Lamport execution
        step, so all protocol invariants (conservation, completion,
        consistent cuts) hold; only bit-exact golden reproduction needs
        _tick. Cost: O(E + S·E) vectorized work, no N-step sequential fold —
        this is what makes 1M-instance batches fast on TPU.

        Requires marker_mode="split" (DenseState docstring): tokens live in
        the ring, markers in the [S, E] pending planes, and the merged
        channel's front is the min-merge-key pending marker when all
        tokens pushed before it have been popped, else the ring head —
        identical delivery schedule to the unified ring, but a tick
        touches no [E, C] ring content (the dense per-tick rewrite was
        >50% of tick time on TPU).
        """
        if self.marker_mode != "split":
            raise ValueError("_sync_tick requires marker_mode='split'")
        N, E, C = self.topo.n, self.topo.e, self.cfg.queue_capacity
        S, M = self.cfg.max_snapshots, self.cfg.max_recorded
        time = s.time + 1
        s = s._replace(time=time)
        if self.faults is not None:
            s = self._fault_restart(s)
        if self._sup:
            s = self._supervise(s)
        BIG = jnp.int32(jnp.iinfo(jnp.int32).max)

        # ---- channel fronts: token head via queue_engine-addressed reads
        # (_head_fields: O(E) packed-plane gathers, or the legacy [E, C]
        # one-hot reductions); marker front = the pending marker with the
        # smallest merge key (DenseState docstring: key = tokens-pushed-
        # before x KEYMULT + marker ord, unique per edge, sorted by push
        # order). The marker front is the CHANNEL front iff every token
        # pushed before it has been popped; head-of-line blocking
        # (queue.go semantics) applies to that front's receive time.
        head_rt, _, head_amt = self._head_fields(s)
        tok_live = s.q_len > 0
        tok_popped = s.tok_pushed - s.q_len                       # [E]
        m_key_live = jnp.where(s.m_pending, s.m_key, BIG)         # [S, E]
        m_front_key = jnp.min(m_key_live, axis=-2)                # [E]
        m_is_front = s.m_pending & (
            m_key_live == jnp.expand_dims(m_front_key, -2))       # [S, E]
        m_front_rt = jnp.sum(jnp.where(m_is_front, s.m_rtime, 0),
                             axis=-2, dtype=_i32)                 # [E]
        front_is_marker = (m_front_key < BIG) & (
            m_front_key // self._keymult <= tok_popped)           # [E]
        front_rt = jnp.where(front_is_marker, m_front_rt, head_rt)
        elig_e = (tok_live | front_is_marker) & (front_rt <= time)
        dup_e_mask = dup_rt = None
        if self.faults is not None:
            # delivery-side gates first (jitter stalls the merged front —
            # marker or token alike; the marker-plane jitter program
            # stalls marker fronts on top; a down destination receives
            # nothing), then the drop/dup programs on what does deliver
            drop_e, dup_e_mask, jit_e, dup_rt = self._fault_edge_masks(s)
            mdrop_e, mdup_e, mjit_e, mdup_rt = self._fault_marker_masks(s)
            s, elig_e = self._fault_gate_elig(s, elig_e, jit_e, mjit_e,
                                              front_is_marker)
        # at most one delivery per source: first eligible edge in dest
        # order, via an exclusive prefix count re-based at each source's
        # first edge (edges are per-source contiguous) — O(E)
        elig_i = elig_e.astype(_i32)
        before = jnp.cumsum(elig_i) - elig_i                      # exclusive
        deliver_e = elig_e & (before == before[self._src_first])
        tok_e = deliver_e & ~front_is_marker
        mk_e = deliver_e & front_is_marker
        s = s._replace(
            q_head=(s.q_head + tok_e) % C,
            q_len=s.q_len - tok_e.astype(_i32),
        )
        dup_tok = None
        if self.faults is not None:
            s, tok_e, dup_tok = self._fault_split_tokens(
                s, tok_e, head_amt, drop_e, dup_e_mask)

        # ---- token deliveries: credit via per-destination segment sums +
        # record into snapshots still recording at tick start (HandleToken,
        # node.go:174-185; 'all tokens before all markers' ordering)
        amt_e = jnp.where(tok_e, head_amt, 0)                     # [E]
        credit = self._sum_by_dst(amt_e, amounts=True)            # [N] i32
        # integer segment sums are exact through the full i32 range; the
        # 2^24 value-range contract is retained so a workload's validity
        # does not depend on which scheduler (or sharded runner, whose f32
        # incidence matmuls genuinely need it) executed it
        toobig = (jnp.any(amt_e >= F32_EXACT_LIMIT)
                  | jnp.any(credit >= F32_EXACT_LIMIT))
        s = s._replace(
            tokens=s.tokens + credit,
            error=s.error | jnp.where(toobig, ERR_VALUE_OVERFLOW, 0).astype(_i32))
        if self._trace_on:
            # 'all tokens before all markers' is this scheduler's real
            # intra-tick order, so the trace records it as such
            s = trace_append_many(s, tok_e, EV_RECV, self._rows_e, amt_e)
        # shared-log append (DenseState "Recording as windows"): one [L, E]
        # one-hot write instead of the former dense [S, M, E] rewrite (the
        # top line of the device profile at 5.2 ms/tick, 8x this write)
        log, cnt, err_bits = log_append(
            s.log_amt, s.rec_cnt, s.min_prot, s.recording,
            tok_e, amt_e, self._rec_dtype, self._rec_limit, M)
        s = s._replace(log_amt=log, rec_cnt=cnt, error=s.error | err_bits)
        if self.faults is not None:
            # duplicated tokens re-enter their ring at the tail (receive
            # times from the fault stream; tok_pushed advances, so this
            # tick's marker merge keys order after the duplicate — any
            # consistent order is legal, the reference never forks)
            s = self._append_rows(s, dup_tok, dup_rt, False, head_amt)

        # ---- marker deliveries, all snapshot slots at once (HandleMarker,
        # node.go:149-171). The consumed marker per delivering edge is its
        # front pending entry — the plane index IS the snapshot id, so
        # mk_se needs no payload decode. With k simultaneous markers for
        # one (slot, node) all k channels are excluded from recording
        # (CreateLocalSnapshot, node.go:58-84).
        mk_all_se = m_is_front & jnp.expand_dims(mk_e, -2)         # [S, E]
        # every delivering front is CONSUMED from the pending planes —
        # including ones the marker-plane adversary then drops on the wire
        # (the loss that stalls the snapshot until the supervisor's
        # timeout); only the surviving set is handled below
        s = s._replace(m_pending=s.m_pending & ~mk_all_se)
        mk_dup_e = None
        if self.faults is not None:
            mk_drop_e = mk_e & mdrop_e
            mk_dup_e = mk_e & mdup_e & ~mk_drop_e
            s = s._replace(fault_counts=s.fault_counts.at[FC_MDROP].add(
                jnp.sum(mk_drop_e, dtype=_i32)).at[FC_MDUP].add(
                jnp.sum(mk_dup_e, dtype=_i32)))
            if self._trace_on:
                s = trace_append_many(s, mk_drop_e, EV_FAULT, self._rows_e,
                                      FC_MDROP)
                s = trace_append_many(s, mk_dup_e, EV_FAULT, self._rows_e,
                                      FC_MDUP)
            mk_e = mk_e & ~mk_drop_e
        mk_se = m_is_front & jnp.expand_dims(mk_e, -2)             # [S, E]
        if self._trace_on:
            # the consumed front's plane index IS the snapshot id
            sid_e = jnp.sum(jnp.where(
                mk_se, jnp.arange(S, dtype=_i32)[:, None], 0), axis=-2,
                dtype=_i32)
            s = trace_append_many(s, mk_e, EV_MRECV, self._rows_e, sid_e)
        arrivals = self._sum_by_dst(mk_se, amounts=False)          # [S, N]
        had = s.has_local                                          # [S, N]
        created = (arrivals > 0) & ~had
        created_dst_se = self._spread_dst(created)                 # [S, E]
        stopped = mk_se & s.recording                              # [S, E]
        started_se = created_dst_se & ~mk_se                       # [S, E]
        recording = (s.recording | created_dst_se) & ~mk_se
        if self.faults is not None:
            # a DUPLICATED marker can re-arrive on a channel whose window
            # already closed — only live closes may decrement rem (the
            # fault-free path keeps arrivals: without dups every arrival
            # at a has_local node finds its channel recording)
            closed_sn = self._sum_by_dst(stopped, amounts=False)   # [S, N]
        else:
            closed_sn = arrivals
        rem = jnp.where(created, self._in_degree[None, :] - arrivals,
                        s.rem - jnp.where(had, closed_sn, 0))
        has_local = had | created
        # window open/close at the POST-append counters (tokens deliver
        # before markers within the tick, and a delivering edge carries
        # either a token or a marker, never both)
        s = s._replace(
            recording=recording,
            frozen=jnp.where(created, s.tokens[None, :], s.frozen),
            rem=rem,
            has_local=has_local,
            **window_update(s, started_se, stopped, s.rec_cnt),
        )

        # ---- re-broadcast from every node that just created its local
        # snapshot (node.StartSnapshot, node.go:198-212): set the pending
        # planes — no ring content is touched
        push_se = self._spread_src(created)                        # [S, E]
        s = self._push_markers_split(s, push_se)
        if self.faults is not None:
            # duplicated markers re-arm their pending-plane entry with a
            # fault-stream receive time and a fresh merge key (at most one
            # front per edge delivered, so at most one dup per edge; the
            # re-broadcast above never targets the same (slot, edge) —
            # its source already had has_local when it pushed this front)
            dup_se = m_is_front & jnp.expand_dims(mk_dup_e, -2)    # [S, E]
            key_e = s.tok_pushed * self._keymult + s.mk_cnt
            s = s._replace(
                m_pending=s.m_pending | dup_se,
                m_rtime=jnp.where(dup_se, jnp.expand_dims(mdup_rt, -2),
                                  s.m_rtime),
                m_key=jnp.where(dup_se, jnp.expand_dims(key_e, -2),
                                s.m_key),
                mk_cnt=s.mk_cnt + mk_dup_e.astype(_i32))

        # ---- finalize (node.go:165-170)
        fire = has_local & (rem == 0) & ~s.done_local
        if self._trace_on:
            s = trace_append_many(
                s, fire, EV_SNAP_END,
                jnp.broadcast_to(jnp.arange(N, dtype=_i32)[None, :],
                                 fire.shape),
                jnp.broadcast_to(jnp.arange(S, dtype=_i32)[:, None],
                                 fire.shape))
        return self._stamp_done(s._replace(
            done_local=s.done_local | fire,
            completed=s.completed + jnp.sum(fire, axis=-1, dtype=_i32),
        ))

    # ---- fused multi-tick dispatch (the megatick engine) -----------------

    def _quiescent(self, s: DenseState):
        """Nothing in flight: every ring is empty (ring mode carries
        markers in the rings too, so empty rings mean NO pending message
        of either kind). A quiescent exact tick is provably a pure
        ``time += 1``: delivery selection finds no eligible head, the
        marker fold runs zero steps, no PRNG draw happens (draws occur
        only on marker broadcast, which needs a delivery), and the
        deferred log append is all-masked. Quiescence is also monotone
        under ticking — a tick can only create messages by delivering a
        marker, which needs a non-empty ring — which is what lets
        drained stretches fast-forward. Ring-mode only: the split
        representation's sync tick draws (S, E) delays every tick, so it
        is never a pure time increment.

        A crash-capable fault adversary voids the proof: a lossy restart
        mutates balances (and counts events) on a drained lane too, so
        empty rings no longer make a tick the identity — quiescence is
        statically False then and every tick runs for real. The snapshot
        supervisor narrows it the same way: the snapshot_every daemon can
        initiate on any tick (never quiescent), and with snapshot_timeout
        armed a lane with a PENDING snapshot and empty rings is exactly a
        stalled attempt that must keep ticking to reach its deadline —
        only pending-free lanes fast-forward."""
        if self.faults is not None and self.faults.crashes:
            return jnp.zeros(s.time.shape, bool)
        if self.cfg.snapshot_every:
            return jnp.zeros(s.time.shape, bool)
        quiet = ~jnp.any(s.q_len > 0, axis=-1)
        if self.cfg.snapshot_timeout:
            quiet = quiet & ~self._pending(s)
        return quiet

    def _run_ticks(self, s: DenseState, n) -> DenseState:
        """n ticks under one dispatch; n is a traced i32 so every distinct
        ``tick N`` count shares one compilation.

        Every variant carries the quiescence fast-forward: the loop
        condition exits as soon as a lane has nothing in flight, and the
        remaining ticks land as one vectorized ``time += n - i`` (per
        lane under vmap — the while batching rule freezes a finished
        lane's carry, so each lane's ``i`` records where IT drained).
        Drained stretches therefore cost O(1) regardless of length.

        With ``megatick`` K > 1 the live stretch advances K ticks per
        iteration via a ``lax.scan``-fused body with a cumulative
        quiescence mask (ticks after a mid-scan drain collapse to the
        time increment the real tick would have been). The ``n % K``
        remainder runs first as plain ticks so every megatick is FULL —
        no step is ever masked by the tick count. Fusion pays on the
        dispatch-bound single-instance path (fewer loop-condition
        evaluations, real branch skipping); under vmap a masked
        ``lax.cond`` computes both branches and selects over the whole
        state — a measured 5.7x drain slowdown at the sf-256 B=64 CPU
        gauge — so the batched runner defaults to megatick=1
        (parallel/batch.py) while DenseSim keeps the fused default.
        Bit-exact either way, by the _quiescent argument.

        Quarantine rides the same exits: an errored lane halts the loop
        like quiescence, but is FROZEN — its clock does not fast-forward
        (a quarantined lane's time records where it was poisoned)."""
        n = jnp.asarray(n, _i32)
        K = self.megatick

        def halted(s):
            if self.quarantine:
                return self._quiescent(s) | (s.error != 0)
            return self._quiescent(s)

        def credit(s, i):
            # drained lanes' remaining ticks are pure time increments;
            # a quarantined lane stays frozen at its poisoning tick
            rest = n - i
            if self.quarantine:
                rest = jnp.where(s.error != 0, 0, rest)
            return s._replace(time=s.time + rest)

        def live(c):
            return (c[1] < n) & ~halted(c[0])

        def plain(c):
            return self._exact_tick(c[0]), c[1] + 1

        if K <= 1:
            s, i = lax.while_loop(live, plain, (s, jnp.int32(0)))
            return credit(s, i)

        rem = n % K
        s, i = lax.while_loop(
            lambda c: (c[1] < rem) & ~halted(c[0]),
            plain, (s, jnp.int32(0)))

        def bump(u):
            if self.quarantine:
                return u._replace(
                    time=u.time + jnp.where(u.error != 0, 0, 1))
            return u._replace(time=u.time + 1)

        def step(carry, _):
            t, quiet = carry
            quiet = quiet | halted(t)
            t = lax.cond(quiet, bump, self._exact_tick, t)
            return (t, quiet), None

        if self.fused == "on":
            # the same K-step scan, executed INSIDE one Pallas kernel
            # with the whole carry VMEM-resident (kernels/megatick). The
            # quiet mask is monotone (quiet |= halted), so ticks run on a
            # step prefix and the fault planes' row/time correspondence
            # holds: a quiet step bumps time WITHOUT consuming its row.
            def mega(c):
                t = self._fused_mega_ticks(c[0], halted, bump)
                return t, c[1] + K
        else:
            def mega(c):
                (t, _), _ = lax.scan(
                    step, (c[0], jnp.bool_(False)), None, length=K)
                return t, c[1] + K

        s, i = lax.while_loop(live, mega, (s, i))
        return credit(s, i)

    def _fused_call(self, step, carry, s: DenseState, length: int):
        """Dispatch ``step`` through kernels.megatick.fused_scan with the
        fault planes (when armed) and the inner tick body's loop-invariant
        arrays — topology tables, permutations, CSR bounds — riding as
        kernel operands. A Pallas body cannot close over arrays, so the
        inner kernel's jax.Array attributes are swapped for their
        operand-read values for the duration of the in-kernel trace and
        restored after (the swap only exists while fused_scan traces).

        fused_tile="on" reroutes the [E, C] ring planes: they leave the
        VMEM carry for HBM ANY operands, the carry's q_meta/q_data slots
        are repurposed as dense [A, E] append buffers (A =
        ring_append_slots), per-step appends go through
        _append_rows_deferred, and each step ends with one streamed
        double-buffered block pass (RingStream.commit_and_heads) that
        replays the appends in ordinal order AND gathers the next step's
        head rows — the rings are read/written once per step, never
        resident. Step 0's heads are gathered outside the kernel
        (megatick.ring_heads). The commit pass runs unconditionally every
        step: a quiet (bumped) step commits an all-inactive buffer, which
        writes back identical bytes, so the cond stays DMA-free."""
        from chandy_lamport_tpu.kernels.megatick import fused_scan, ring_heads

        fm_e = fm_n = None
        if self.faults is not None:
            fm_e, fm_n = self._fault_planes(s, length)
        inner = self._fused_inner
        cvals = {n: v for n, v in sorted(vars(inner).items())
                 if isinstance(v, jax.Array)}

        if self.fused_tile != "on":
            def step_c(c, ep, ax, cv):
                for n, v in cv.items():
                    setattr(inner, n, v)
                try:
                    return step(c, ep, ax)
                finally:
                    # restore BEFORE the in-kernel trace is finalized: the
                    # kernel jaxpr is leak-checked the moment pallas_call
                    # finishes tracing, which is before the outer finally
                    # below runs — operand tracers left on ``inner`` there
                    # trip jax.checking_leaks (the runtime sentry's regime)
                    for n, v in cvals.items():
                        setattr(inner, n, v)

            try:
                return fused_scan(step_c, carry, fm_e, fm_n, length=length,
                                  interpret=self._pl_interpret,
                                  block_edges=self.fused_block_edges,
                                  consts=cvals)
            finally:
                for n, v in cvals.items():
                    setattr(inner, n, v)

        A, E = self._ring_append_slots, self.topo.e

        def swap_bufs(c, head_meta, head_data):
            # fresh all-inactive append buffers + this step's head row
            # (repurposed q_meta layout — see the _ring_defer comment)
            bm = jnp.concatenate(
                [jnp.stack([jnp.full((A, E), -1, _i32),
                            jnp.zeros((A, E), _i32)]),
                 jnp.stack([head_meta, head_data])[:, None, :]], axis=1)
            bd = jnp.zeros((A, E), _i32)
            return _map_state(c, lambda t: t._replace(q_meta=bm, q_data=bd))

        ring = (jnp.asarray(s.q_meta, _i32), jnp.asarray(s.q_data, _i32))
        hm0, hd0 = ring_heads(ring[0], ring[1], s.q_head)
        kcarry = swap_bufs(carry, hm0, hd0)

        def step_t(c, ep, ax, cv, rs):
            for n, v in cv.items():
                setattr(inner, n, v)
            inner._ring_defer = True
            try:
                c_out = step(c, ep, ax)
            finally:
                inner._ring_defer = False
                for n, v in cvals.items():
                    setattr(inner, n, v)
            st = _state_of(c_out)
            hm2, hd2 = rs.commit_and_heads(st.q_meta[0, :-1],
                                           st.q_meta[1, :-1],
                                           st.q_data, st.q_head)
            return swap_bufs(c_out, hm2, hd2)

        try:
            c_out, (qm2, qd2) = fused_scan(
                step_t, kcarry, fm_e, fm_n, length=length,
                interpret=self._pl_interpret,
                block_edges=self.fused_block_edges,
                consts=cvals, ring=ring)
        finally:
            inner._ring_defer = False
            for n, v in cvals.items():
                setattr(inner, n, v)
        return _map_state(c_out,
                          lambda t: t._replace(q_meta=qm2, q_data=qd2))

    def _fused_mega_ticks(self, s: DenseState, halted, bump) -> DenseState:
        """One fused megatick for the run_ticks loop: K ticks in one
        kernel dispatch, cumulative-quiescence semantics identical to the
        plain scan body above."""
        inner = self._fused_inner

        def step(carry, ep, ax):
            t, quiet = carry
            quiet = quiet | halted(t)
            fmk = None if ep is None else self._fmasks_of(ep, ax)

            def run(u):
                return inner._exact_tick(u, fmk)

            t = lax.cond(quiet, bump, run, t)
            return t, quiet

        t, _ = self._fused_call(step, (s, jnp.bool_(False)), s,
                                self.megatick)
        return t

    # ---- event injection (sim.go:58-68) ---------------------------------

    def _inject_send(self, s: DenseState, e, amount) -> DenseState:
        """PassTokenEvent -> SendTokens (node.go:112-131): debit at send time,
        one delay draw, enqueue."""
        if self._trace_on:
            s = trace_append_one(s, True, EV_SEND, e, amount)
        src = self._edge_src[e]
        err = s.error | jnp.where(
            s.tokens[src] < amount, ERR_TOKEN_UNDERFLOW, 0).astype(_i32)
        s = s._replace(tokens=s.tokens.at[src].add(-jnp.asarray(amount, _i32)),
                       error=err)
        return self._push(s, e, False, amount)

    def _inject_snapshot(self, s: DenseState, node) -> DenseState:
        """SnapshotEvent -> sim.StartSnapshot (sim.go:105-123): allocate the
        next id, create the initiator's local snapshot recording ALL inbound
        links, broadcast markers. No finalize check here (the reference only
        checks on marker receipt)."""
        S = self.cfg.max_snapshots
        sid = s.next_sid
        err = s.error | jnp.where(sid >= S, ERR_SNAPSHOT_OVERFLOW, 0).astype(_i32)
        sid = jnp.clip(sid, 0, S - 1)
        s = s._replace(next_sid=s.next_sid + 1,
                       started=s.started.at[sid].set(True),
                       error=err)
        if self._trace_on:
            s = trace_append_one(s, True, EV_SNAP_START, node, sid)
        if self._sup:
            # remember the initiator (the supervisor's re-initiation
            # target) and arm the first attempt's deadline
            s = s._replace(
                snap_initiator=s.snap_initiator.at[sid].set(
                    jnp.asarray(node, _i32)))
            if self.cfg.snapshot_timeout:
                s = s._replace(snap_deadline=s.snap_deadline.at[sid].set(
                    s.time + self.cfg.snapshot_timeout))
        s = self._create_local(s, sid, node, jnp.int32(-1))
        return self._broadcast_markers(s, node, sid)

    def _bulk_push(self, s: DenseState, active, is_marker: bool, data
                   ) -> DenseState:
        """Vectorized enqueue: one message on every edge where ``active``,
        written by the shared batched append primitive (_append_rows —
        engine-addressed: O(E) scatters, or the legacy [E, C] one-hot
        selects). Fast-path-only semantics: receive times are drawn for
        every edge in one vectorized draw (inactive edges' draws are
        discarded), so the stream does NOT match sequential per-event sends
        under the Go-exact sampler — use _push/_inject_send for bit-exact
        runs."""
        rts, dstate = self.delay.draw_many(s.delay_state, s.time, self.topo.e)
        s = s._replace(delay_state=dstate)
        return self._append_rows(s, active, rts, is_marker, data)

    def _bulk_send(self, s: DenseState, amounts) -> DenseState:
        """Vectorized token injection: one message per edge with amounts[e]>0
        (the fast-path equivalent of a burst of PassTokenEvents at the same
        sim time). Debits every sender at send time (node.go:120)."""
        amounts = jnp.asarray(amounts, _i32)
        active = amounts > 0
        debits = self._sum_by_src(amounts)
        tokens = s.tokens - debits
        err = s.error | jnp.where(jnp.any(tokens < 0), ERR_TOKEN_UNDERFLOW, 0
                                  ).astype(_i32)
        s = s._replace(tokens=tokens, error=err)
        if self._trace_on:
            s = trace_append_many(s, active, EV_SEND, self._rows_e, amounts)
        return self._bulk_push(s, active, False, amounts)

    def _push_markers_split(self, s: DenseState, push_se) -> DenseState:
        """Marker multi-push in split mode: set the per-(slot, edge) pending
        planes — no [E, C] ring content is touched. Merge keys (DenseState
        docstring) are allocated in slot order for markers pushed on the
        same edge this tick (matching the ring representation's stacking
        order), so the merged-FIFO delivery schedule is identical. One
        vectorized delay draw per (slot, edge) with inactive draws
        discarded (fast-path semantics). Cannot overflow the planes: each
        (snapshot, edge) pair pushes at most once ever (first-receipt
        broadcast only, node.go:154-156)."""
        S = self.cfg.max_snapshots
        rts_se, dstate = self.delay.draw_many(s.delay_state, s.time,
                                              (S, self.topo.e))
        off_se = jnp.cumsum(push_se, axis=-2, dtype=_i32) - push_se  # [S, E]
        k_e = jnp.sum(push_se, axis=-2, dtype=_i32)                  # [E]
        key_se = (jnp.expand_dims(s.tok_pushed * self._keymult
                                  + s.mk_cnt, -2) + off_se)
        s = s._replace(
            m_pending=s.m_pending | push_se,
            m_rtime=jnp.where(push_se, jnp.asarray(rts_se, _i32), s.m_rtime),
            m_key=jnp.where(push_se, key_se, s.m_key),
            mk_cnt=s.mk_cnt + k_e,
            delay_state=dstate,
        )
        if self._trace_on:
            s = trace_append_many(
                s, push_se, EV_MSEND,
                jnp.broadcast_to(self._rows_e[None, :], push_se.shape),
                jnp.broadcast_to(jnp.arange(S, dtype=_i32)[:, None],
                                 push_se.shape))
        return s

    def _create_and_broadcast(self, s: DenseState, created) -> DenseState:
        """Dense CreateLocalSnapshot + marker broadcast for every True
        (slot, node) of ``created`` [S, N] (node.go:58-84 + node.go:97-109):
        freeze balances, record all inbound channels, push one marker per
        outbound edge per created slot."""
        created_dst_se = self._spread_dst(created)                 # [S, E]
        s = s._replace(
            recording=s.recording | created_dst_se,
            frozen=jnp.where(created, s.tokens[None, :], s.frozen),
            rem=jnp.where(created, self._in_degree[None, :], s.rem),
            has_local=s.has_local | created,
            **window_update(s, created_dst_se, None, s.rec_cnt),
        )
        push_se = self._spread_src(created)                        # [S, E]
        return self._push_markers_split(s, push_se)

    def _bulk_snapshots(self, s: DenseState, init_mask) -> DenseState:
        """Vectorized sim.StartSnapshot (sim.go:105-123) for every node in
        ``init_mask`` [N] at once: ids allocated in node-index order from
        next_sid; the initiator records ALL inbound links and broadcasts.
        Fast-path twin of _inject_snapshot (which stays scalar for the
        bit-exact scheduler)."""
        S = self.cfg.max_snapshots
        count = jnp.sum(init_mask, dtype=_i32)
        rank = jnp.cumsum(init_mask, dtype=_i32) - 1               # [N]
        sid_n = s.next_sid + rank
        created = init_mask[None, :] & (
            sid_n[None, :] == jnp.arange(S, dtype=_i32)[:, None])  # [S, N]
        err = s.error | jnp.where(s.next_sid + count > S,
                                  ERR_SNAPSHOT_OVERFLOW, 0).astype(_i32)
        s = s._replace(
            next_sid=s.next_sid + count,
            started=s.started | jnp.any(created, axis=1),
            error=err,
        )
        if self._sup:
            any_c = jnp.any(created, axis=-1)
            init_n = jnp.argmax(created, axis=-1).astype(_i32)
            s = s._replace(snap_initiator=jnp.where(any_c, init_n,
                                                    s.snap_initiator))
            if self.cfg.snapshot_timeout:
                s = s._replace(snap_deadline=jnp.where(
                    any_c, s.time + self.cfg.snapshot_timeout,
                    s.snap_deadline))
        if self._trace_on:
            s = trace_append_many(s, init_mask, EV_SNAP_START,
                                  jnp.arange(self.topo.n, dtype=_i32),
                                  sid_n)
        return self._create_and_broadcast(s, created)

    # ---- drain (test_common.go:124-137) ---------------------------------

    def _pending(self, s: DenseState):
        # a supervisor-failed slot (retries exhausted, ERR_SNAPSHOT_TIMEOUT
        # raised) no longer gates the drain — without the exclusion a dead
        # attempt would grind the loop to ERR_TICK_LIMIT on top
        return jnp.any(s.started & ~s.snap_failed
                       & (s.completed < self.topo.n))

    def _drain_and_flush_with(self, s: DenseState, tick_fn,
                              megatick: int = 1,
                              fused_ok: bool = False) -> DenseState:
        """Tick until every started snapshot has completed on all nodes, then
        max_delay+1 flush ticks. Outcome-equivalent to the reference's
        goroutine drain loop (SURVEY.md §3.5), with a tick-budget guard in
        place of hanging on a non-strongly-connected graph.

        ``megatick`` K > 1 fuses K drain ticks per while iteration, each
        scan step re-checking the drain condition so exactly the same tick
        sequence executes (a step past completion is the identity — the
        drain stops ticking, it does not time-advance).

        With ``quarantine`` on, ``error != 0`` halts a lane exactly like
        the completion exit — a poisoned lane freezes (flush ticks
        included) instead of grinding its corrupt state forward, and it is
        NOT charged ERR_TICK_LIMIT for the ticks quarantine denied it.

        ``fused_ok`` (only ever True from _drain_and_flush, the exact
        path) lets a ``fused == 'on'`` kernel execute the K-tick drain
        body and the flush loop inside the one-kernel megatick. The
        drain condition is monotone non-increasing within a megatick
        for the only reason that matters: a condition-false step is the
        IDENTITY (no pops, no supervisor, no time advance), so once the
        condition goes false it can never flip back true — supervisor
        armed or not (a supervisor tick can flip ``pending`` either way,
        but only on steps where the condition was already true). Real
        ticks therefore form a step prefix and the precomputed fault
        planes' row/time correspondence holds; the traced ``limit``
        rides in the kernel carry rather than being closed over."""
        fused = fused_ok and self.fused == "on"
        limit = jnp.asarray(s.time + self.cfg.max_ticks, _i32)

        def cond_at(s, lim):
            c = self._pending(s) & (s.time < lim)
            if self.quarantine:
                c = c & (s.error == 0)
            return c

        def cond(s):
            return cond_at(s, limit)

        if fused:
            def body(s):
                return self._fused_drain_mega(s, limit, cond_at)
        elif megatick > 1:
            def body(s):
                def step(s, _):
                    return lax.cond(cond(s), tick_fn, lambda t: t, s), None

                s, _ = lax.scan(step, s, None, length=megatick)
                return s
        else:
            body = tick_fn
        s = lax.while_loop(cond, body, s)
        budget_blown = self._pending(s)
        if self.quarantine:
            budget_blown = budget_blown & (s.error == 0)
        s = s._replace(error=s.error | jnp.where(
            budget_blown, ERR_TICK_LIMIT, 0).astype(_i32))
        if fused:
            return self._fused_flush(s)
        flush = tick_fn
        if self.quarantine:
            def flush(s):
                return lax.cond(s.error == 0, tick_fn, lambda t: t, s)
        return lax.fori_loop(0, self.cfg.max_delay + 1,
                             lambda _, s: flush(s), s)

    def _fused_drain_mega(self, s: DenseState, limit, cond_at) -> DenseState:
        """One fused K-tick drain body: the megatick>1 scan above, inside
        the kernel, re-checking the drain condition per step."""
        inner = self._fused_inner

        def step(carry, ep, ax):
            t, lim = carry
            fmk = None if ep is None else self._fmasks_of(ep, ax)

            def run(u):
                return inner._exact_tick(u, fmk)

            t = lax.cond(cond_at(t, lim), run, lambda u: u, t)
            return t, lim

        t, _ = self._fused_call(step, (s, limit), s, self.megatick)
        return t

    def _fused_flush(self, s: DenseState) -> DenseState:
        """The max_delay+1 flush ticks in one kernel. Flush ticks run
        unconditionally (time advances every step), so the fault planes
        align row j with flush tick j; under quarantine an errored lane
        freezes — error is sticky, the identity steps are a suffix."""
        inner = self._fused_inner
        quarantine = self.quarantine

        def step(t, ep, ax):
            fmk = None if ep is None else self._fmasks_of(ep, ax)

            def run(u):
                return inner._exact_tick(u, fmk)

            if quarantine:
                return lax.cond(t.error == 0, run, lambda u: u, t)
            return run(t)

        return self._fused_call(step, s, s, self.cfg.max_delay + 1)

    def _fused_stream_drain(self, s: DenseState, in_drain, limit,
                            chunk: int) -> DenseState:
        """The streaming engine's per-lane drain slice (parallel/batch
        lane_pass stage 2), fused: ``chunk`` conditional drain ticks as
        megatick-sized kernel dispatches plus a plain-scan remainder.
        Same monotone-cond argument as _drain_and_flush_with — a
        condition-false step is the identity, so once false it stays
        false and real ticks form a step prefix. The traced ``in_drain``
        gate and per-lane ``limit`` ride in the kernel carry rather than
        being closed over (a Pallas body cannot close over arrays)."""
        inner = self._fused_inner

        def cond_at(t, dr, lim):
            c = dr & self._pending(t) & (t.time < lim)
            if self.quarantine:
                c = c & (t.error == 0)
            return c

        def step(carry, ep, ax):
            t, dr, lim = carry
            fmk = None if ep is None else self._fmasks_of(ep, ax)

            def run(u):
                return inner._exact_tick(u, fmk)

            t = lax.cond(cond_at(t, dr, lim), run, lambda u: u, t)
            return t, dr, lim

        in_drain = jnp.asarray(in_drain, jnp.bool_)
        limit = jnp.asarray(limit, _i32)
        K = self.megatick
        nmega, rem = divmod(int(chunk), K)
        if nmega:
            def mega(t, _):
                t2, _, _ = self._fused_call(
                    step, (t, in_drain, limit), t, K)
                return t2, None

            s, _ = lax.scan(mega, s, None, length=nmega)
        if rem:
            def one(t, _):
                return lax.cond(cond_at(t, in_drain, limit),
                                self._exact_tick, lambda u: u, t), None

            s, _ = lax.scan(one, s, None, length=rem)
        return s

    def _drain_and_flush(self, s: DenseState) -> DenseState:
        return self._drain_and_flush_with(s, self._exact_tick,
                                          megatick=self.megatick,
                                          fused_ok=True)

    def _sync_drain_and_flush(self, s: DenseState) -> DenseState:
        return self._drain_and_flush_with(s, self._sync_tick)


# ---- streaming-engine primitives (parallel/batch.run_stream) ------------
#
# The streaming driver retires finished lanes and admits queued jobs into
# their slots in place, inside the jitted step. These two primitives are its
# state surgery: ``harvest_lane_summaries`` reads every per-job result field
# out of a batched state as [B] reductions (scattered into the results ring
# by the caller BEFORE the slot is recycled), and ``reset_lanes`` scatters a
# fresh ``init_state`` into the masked lanes of the donated batch leaves —
# jnp.where per leaf against the unbatched template, so an admitted job
# starts from EXACTLY the state a static run's init_batch would give it
# (the stream-vs-static bit-exactness oracle rests on this).


def harvest_lane_summaries(state: DenseState, num_nodes: int) -> dict:
    """Per-lane job summary fields of a lead-batched state, each [B]:
    the final token balances plus every counter the per-job results ring
    (parallel/batch.StreamState) carries. Read BEFORE reset_lanes wipes
    the slot; decoding error bits to names stays a host-side concern
    (state.decode_error_bits on the harvested ints)."""
    complete = state.started & (state.completed >= num_nodes)
    return {
        "tokens": state.tokens,                                   # [B, N]
        "time": state.time,                                       # [B]
        "error": state.error,                                     # [B]
        "snap_started": jnp.sum(state.started, axis=-1,
                                dtype=_i32),                      # [B]
        "snap_completed": jnp.sum(complete, axis=-1, dtype=_i32),  # [B]
        "snap_failed": jnp.sum(state.snap_failed, axis=-1,
                               dtype=_i32),                       # [B]
        "fault_skew": state.fault_skew,                           # [B]
        "fault_events": jnp.sum(state.fault_counts, axis=-1,
                                dtype=_i32),                      # [B]
    }


def reset_lanes(state: DenseState, mask, topo: DenseTopology,
                cfg: SimConfig) -> DenseState:
    """Scatter a fresh ``init_state`` into every lane where ``mask`` [B] is
    True: each simulation leaf becomes ``where(mask, fresh, old)`` against
    the unbatched template, so a recycled slot is bit-identical to a lane
    of a fresh init_batch. The per-job stream identities — ``delay_state``,
    ``fault_key`` and the job_id/prog_cursor/admit_tick leaves — are left
    UNTOUCHED (the admission step overwrites them from the job pool; a
    bare reset would wrongly replay lane-indexed streams)."""
    from chandy_lamport_tpu.core.state import init_state

    fresh = init_state(topo, cfg, None)._replace(delay_state=())
    # the flight-recorder ring is a LANE artifact, not a job artifact: it
    # spans job admissions (lane-harvest/lane-admit events are exactly the
    # boundaries), so a recycled slot keeps its event history
    keep = {"delay_state": state.delay_state, "fault_key": state.fault_key,
            "job_id": state.job_id, "prog_cursor": state.prog_cursor,
            "admit_tick": state.admit_tick,
            "tr_meta": state.tr_meta, "tr_data": state.tr_data,
            "tr_tick": state.tr_tick, "tr_count": state.tr_count,
            "tr_on": state.tr_on}
    flat = state._replace(delay_state=())

    def mix(old, tpl):
        old = jnp.asarray(old)
        m = jnp.reshape(mask, mask.shape + (1,) * (old.ndim - mask.ndim))
        return jnp.where(m, jnp.asarray(tpl)[None], old)

    out = jax.tree_util.tree_map(mix, flat, fresh)
    return out._replace(**keep)


def fork_lanes(state: DenseState, mask, bank: DenseState,
               src) -> DenseState:
    """Scatter checkpointed prefix states into admitted lanes: lane b
    where ``mask`` [B] is True takes every SIMULATION leaf from bank row
    ``src[b]`` (``bank`` is a DenseState with an [F] lead axis — the
    decoded prefix-checkpoint bank), so the lane resumes from the phase
    boundary the checkpoint captured instead of from reset_lanes' fresh
    template. The dual of reset_lanes' keep-set shrinks by one:
    ``delay_state`` IS forked (the sampler's counters advanced during
    the prefix — scattering the pool's fresh row would replay the
    prefix's delay draws in the tail), while ``fault_key`` stays from
    admission (it is part of the prefix digest, so pool row == bank row
    by construction and the admitted value is already right). The
    job_id/prog_cursor/admit_tick and flight-recorder leaves stay lane
    bookkeeping exactly as in reset_lanes; the admission step aims
    prog_cursor past the forked prefix itself."""
    keep = ("fault_key", "job_id", "prog_cursor", "admit_tick",
            "tr_meta", "tr_data", "tr_tick", "tr_count", "tr_on")
    srci = jnp.asarray(src, jnp.int32)

    def mix(old, row):
        old = jnp.asarray(old)
        m = jnp.reshape(mask, mask.shape + (1,) * (old.ndim - mask.ndim))
        return jnp.where(m, jnp.asarray(row)[srci], old)

    updates = {
        name: jax.tree_util.tree_map(
            mix, getattr(state, name), getattr(bank, name))
        for name in state._fields if name not in keep}
    return state._replace(**updates)
