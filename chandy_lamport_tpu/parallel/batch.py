"""Batched execution: vmap over independent simulation instances.

This is the framework's data-parallel axis (SURVEY.md §2.5): the reference
simulates ONE system per process; here a whole event script — sends,
snapshot initiations, ticks, drain, flush — compiles into a single XLA
program executed over B instances in lockstep by ``vmap``. Per-instance
divergence (different delay streams → different delivery schedules →
different drain lengths) is handled by the batching rules of
``lax.while_loop``/``lax.cond``: lanes that finish early idle until the
slowest lane converges.

Script compilation (``compile_events``): the reference executes events
imperatively between ticks (test_common.go:79-140). Here the script becomes
dense op tensors — ``kind/arg0/arg1 [T, K]`` where each phase t carries up to
K ops (0=nop, 1=send(edge, amount), 2=snapshot(node)) followed by exactly one
tick — and the whole run is ``lax.scan`` over phases. Op order within a phase
is preserved (script order = PRNG draw order = bit-exactness rule R4).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import (
    Event,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.core.state import (
    DenseState,
    DenseTopology,
    ERR_CONSERVATION,
    ERR_TICK_LIMIT,
    init_state,
)
from chandy_lamport_tpu.ops.delay_jax import JaxDelay
from chandy_lamport_tpu.ops.tick import (
    TickKernel,
    fork_lanes,
    harvest_lane_summaries,
    reset_lanes,
)
from chandy_lamport_tpu.utils.guards import (
    armed,
    guarded_get,
    guarded_put,
    relaxed_site,
)
from chandy_lamport_tpu.utils.memocache import (
    MemoCacheError,
    PrefixCache,
    PrefixCacheError,
    SummaryCache,
    job_digest,
    prefix_extend,
    prefix_seed_digest,
    resolve_memo,
)
from chandy_lamport_tpu.utils.tracing import (
    EV_LANE_ADMIT,
    EV_LANE_COALESCE,
    EV_LANE_HARVEST,
    EV_MEMO_HIT,
    EV_PREFIX_FORK,
    EV_SERVE_ADMIT,
    EV_SERVE_MISS,
    JaxTrace,
    trace_append_lanes,
    trace_counts,
)
from chandy_lamport_tpu.utils.fixtures import TopologySpec
from chandy_lamport_tpu.utils.layouts import (
    HAVE_LAYOUTS,
    array_format,
    auto_format,
    format_layout,
    input_formats,
)

OP_NOP, OP_SEND, OP_SNAPSHOT = 0, 1, 2

# With memo != "off", every MEMO_SHADOW_EVERY-th job that would be served
# without execution (persistent-cache hit or coalesced follower) ALSO runs
# solo on a lane, and its harvested summary is compared bit-for-bit
# against the served one — a standing audit that memoized answers stay
# exact (a mismatch raises MemoCacheError naming the digest).
# run_stream(shadow_every=...) overrides it (0 disables; tests tighten it
# to 1 for full coverage). With memo == "prefix" the same cadence ALSO
# audits forked jobs: every shadow_every-th fork admission re-runs its
# job cold in a solo side-stream at finalize and byte-compares the
# summaries (a prefix checkpoint that drifted from cold execution raises
# PrefixCacheError naming the digest and depth).
MEMO_SHADOW_EVERY = 16

# DenseState leaves a prefix checkpoint does NOT capture — fork_lanes'
# keep-set (ops/tick): lane bookkeeping (job_id/prog_cursor/admit_tick),
# the per-lane flight-recorder ring (a LANE artifact spanning
# admissions), and fault_key (part of the chain identity, so the
# admitted pool row already equals the producer's). Everything else —
# time, tokens, both queue planes, snapshot/supervisor books,
# delay-sampler state (its counters ADVANCED during the prefix), fault
# books, sig, error — is captured byte-losslessly, so a forked lane is
# bit-identical to a cold lane whose cursor just crossed the boundary.
_PREFIX_KEEP_LEAVES = frozenset((
    "fault_key", "job_id", "prog_cursor", "admit_tick",
    "tr_meta", "tr_data", "tr_tick", "tr_count", "tr_on"))

# DenseState leaves EXCLUDED from the per-lane state signature: ``time``
# deliberately (fast-forwarding asks "is this state invariant under the
# tick MODULO time?"), observability-only leaves (the trace ring planes +
# arm flag), the signature itself, and ``admit_tick`` (a stream-step
# stamp, not simulation state). Everything else — tokens, both queue
# engines' message planes, snapshot/supervisor books, delay-sampler
# state, fault books, job/cursor — is hashed, so a signature recurrence
# means the lane's semantic state truly recurred.
_SIG_SKIP_LEAVES = frozenset((
    "time", "admit_tick", "tr_meta", "tr_data", "tr_tick", "tr_count",
    "tr_on", "sig"))


def _sig_words(leaf):
    """One leaf flattened to u32 words for the signature hash. 8-byte
    dtypes split into lo/hi halves and floats go through a bitcast, so no
    bit of any leaf is dropped (a sum-of-casts that truncated would alias
    states that differ only in high bits)."""
    x = jnp.reshape(jnp.asarray(leaf), (-1,))
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32)
    if jnp.issubdtype(x.dtype, jnp.floating):
        nbits = x.dtype.itemsize * 8
        x = lax.bitcast_convert_type(
            x, {16: jnp.uint16, 32: jnp.uint32, 64: jnp.uint64}[nbits])
    if x.dtype.itemsize == 8:
        u = x.astype(jnp.uint64)
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return jnp.concatenate([lo, hi])
    return x.astype(jnp.uint32)


def _lane_signature(s) -> Any:
    """u32 rolling fingerprint of ONE lane's semantic state (vmapped over
    the batch by the caller). Per leaf: a position-weighted u32 sum
    (multiplicative weights keep permuted contents from colliding), then
    an FNV-style combine across leaves with a per-leaf salt so equal
    leaf hashes at different positions don't cancel. Pure elementwise +
    reductions — a few fused ops per step, cheap next to a tick. Equal
    states always hash equal; the (vanishingly unlikely) 32-bit collision
    is the accepted residual risk that the shadow re-execution audit
    (MEMO_SHADOW_EVERY) exists to catch."""
    acc = jnp.uint32(0x9E3779B9)
    idx = 0
    for name, val in s._asdict().items():
        if name in _SIG_SKIP_LEAVES:
            continue
        for leaf in jax.tree_util.tree_leaves(val):
            w = _sig_words(leaf)
            h = jnp.sum(
                w * (jnp.arange(w.size, dtype=jnp.uint32)
                     * jnp.uint32(2654435761) + jnp.uint32(0x85EBCA6B)),
                dtype=jnp.uint32)
            acc = (acc * jnp.uint32(1000003)) ^ (
                h + jnp.uint32((idx * 0x9E3779B9) & 0xFFFFFFFF))
            idx += 1
    return acc


def _ring_rows(stream) -> List[dict]:
    """Decode a StreamState's harvested results ring into per-job dict
    rows (host side; only the newest ``capacity`` rows survive wrap)."""
    from chandy_lamport_tpu.core.state import decode_error_bits

    host = jax.device_get(stream)
    rcap = int(np.shape(host.res_job)[0])
    rows = []
    for i in range(min(int(host.res_count), rcap)):
        err = int(host.res_error[i])
        rows.append({
            "job": int(host.res_job[i]),
            "time": int(host.res_time[i]),
            "error": err,
            "errors_decoded": decode_error_bits(err),
            "snapshots_started": int(host.res_snap_started[i]),
            "snapshots_completed": int(host.res_snap_completed[i]),
            "snapshots_failed": int(host.res_snap_failed[i]),
            "fault_skew": int(host.res_fault_skew[i]),
            "fault_events": int(host.res_fault_events[i]),
            "admit_step": int(host.res_admit_step[i]),
            "tokens": np.asarray(host.res_tokens[i]).astype(int).tolist(),
        })
    return rows


def _formats_match(tree, formats) -> bool:
    """True iff every leaf's live device format already equals the compiled
    program's expectation (states built by ``init_batch_device(formats=...)``
    qualify) — then the relayout dispatch can be skipped entirely."""
    xs = jax.tree_util.tree_leaves(tree)
    # a DCE'd input's format is None (stages._input_layouts_flat) — keep it
    # as a leaf so the two flattenings stay aligned; the executable never
    # reads a DCE'd input, so None matches anything
    fs = jax.tree_util.tree_leaves(formats, is_leaf=lambda v: v is None)
    if len(xs) != len(fs):
        return False
    for x, f in zip(xs, fs):
        if f is None:
            continue
        cur = array_format(x)
        if (cur is None or format_layout(cur) != format_layout(f)
                or cur.sharding != f.sharding):
            return False
    return True


class ScriptOps(NamedTuple):
    """A compiled event script: T phases of up to K ops, each phase followed
    by ``do_tick`` ticks (0 only for a synthetic trailing phase). Multi-tick
    stretches are carried as COUNTS and executed by the runner's fused
    multi-tick dispatch (TickKernel._run_ticks on the exact path, with its
    quiescence fast-forward) instead of the former one-empty-phase-per-tick
    expansion — a ``tick 200`` event costs one phase, not 200."""

    kind: Any      # i32 [T, K]
    arg0: Any      # i32 [T, K]  edge index (send) | node index (snapshot)
    arg1: Any      # i32 [T, K]  token amount (send)
    do_tick: Any   # i32 [T]     ticks after the phase's ops

    @property
    def num_phases(self) -> int:
        return self.kind.shape[0]


def compile_events(topo: DenseTopology, events: List[Event]) -> ScriptOps:
    """Events -> dense op tensors. Each ``tick n`` closes the current phase
    with a tick count of n (consecutive tick events merge into one phase);
    trailing non-tick events get a final synthetic phase with ``do_tick=0``,
    so no-drain runs stop exactly where the single-instance backend does
    (injected but unticked) while drained runs are unaffected (the drain
    loop supplies the tick, SURVEY.md §3.5)."""
    phases: List[Tuple[List[tuple], int]] = []
    cur: List[tuple] = []
    for ev in events:
        if isinstance(ev, PassTokenEvent):
            src, dest = topo.index[ev.src], topo.index[ev.dest]
            e = topo.edge_index.get((src, dest))
            if e is None:
                raise ValueError(f"no link {ev.src} -> {ev.dest}")
            cur.append((OP_SEND, e, ev.tokens))
        elif isinstance(ev, SnapshotEvent):
            cur.append((OP_SNAPSHOT, topo.index[ev.node_id], 0))
        elif isinstance(ev, TickEvent):
            if not cur and phases and phases[-1][1]:
                phases[-1] = (phases[-1][0], phases[-1][1] + ev.n)
            else:
                phases.append((cur, ev.n))
                cur = []
        else:
            raise TypeError(f"unknown event: {ev!r}")
    if cur:  # trailing non-tick events: a synthetic unticked final phase
        phases.append((cur, 0))
    if not phases:  # empty script: one bare tick (the pre-count behavior)
        phases.append(([], 1))
    t = len(phases)
    k = max((len(p) for p, _ in phases), default=0) or 1
    kind = np.zeros((t, k), np.int32)
    arg0 = np.zeros((t, k), np.int32)
    arg1 = np.zeros((t, k), np.int32)
    do_tick = np.array([n for _, n in phases], np.int32)
    for i, (ops, _) in enumerate(phases):
        for j, (op, a0, a1) in enumerate(ops):
            kind[i, j], arg0[i, j], arg1[i, j] = op, a0, a1
    return ScriptOps(kind, arg0, arg1, do_tick)


class JobPool(NamedTuple):
    """J compiled jobs packed into one pooled phase table, indexed by each
    lane's ``prog_cursor`` (core/state.py streaming leaves). Rows
    ``job_start[j]..job_end[j]`` are job j's phases (shorter phases padded
    to the pool-wide K with OP_NOP — semantically free, a nop draws no
    PRNG); ``job_limit[j]`` is the drain tick budget measured from tick 0
    (= job j's total scripted ticks + SimConfig.max_ticks — scripted phases
    advance time by exactly their tick counts, so this equals the static
    drain's entry-time-relative ``time + max_ticks`` limit bit-exactly).
    ``fault_key[j]``/``delay_state[j]`` are the PER-JOB stream identities
    (models/faults + ops/delay_jax ``init_batch_state(J)``): admission
    copies job j's row into the lane, so job j replays the same fault and
    delay streams whichever lane runs it, whenever it was admitted — the
    stream-vs-static parity oracle.

    ``digest[j]`` is the job's content address (utils/memocache.job_digest
    over topology + script + stream identities + resolved knobs + config)
    as raw sha256 bytes — all-zero rows when the runner's memo plane is
    off (pack_jobs computes digests only under ``content_keys``, where
    duplicate scripts share stream identities and therefore digests).

    ``prefix_digest`` (memo="prefix" only, else None) is the rolling
    prefix-digest CHAIN, row-aligned with the pooled phase table:
    ``prefix_digest[job_start[j] + i]`` = sha256 chain link over job j's
    script-free identity (topology + fault/delay row + resolved knobs +
    config — utils/memocache.prefix_seed_digest) extended by its first
    i+1 pooled phase rows (prefix_extend). Two jobs share link d iff
    they share identity AND their first d compiled phases — the content
    address of "the lane state at phase boundary d". Host-side planning
    data only; never shipped to the device."""

    kind: Any        # i32 [P, K]  pooled phase ops (batch.compile_events)
    arg0: Any        # i32 [P, K]
    arg1: Any        # i32 [P, K]
    do_tick: Any     # i32 [P]     tick count closing each phase
    job_start: Any   # i32 [J]     first pooled row of job j
    job_end: Any     # i32 [J]     one past job j's last row
    job_limit: Any   # i32 [J]     drain budget: total script ticks + max_ticks
    fault_key: Any   # u32 [J]     per-job adversary key (0 = disarmed)
    digest: Any      # u8 [J, 32]  sha256 content address (0s when memo off)
    delay_state: Any  # pytree, leaves [J, ...]: per-job delay stream rows
    prefix_digest: Any = None  # u8 [P, 32] phase-boundary chain (prefix mode)

    @property
    def num_jobs(self) -> int:
        return int(np.shape(self.job_start)[0])


class StreamState(NamedTuple):
    """The streaming driver's carry beside the lane batch (run_stream):
    admission bookkeeping + occupancy accounting + the device-side per-job
    results ring the harvest step scatters retired lanes into. Saved
    TOGETHER with the lane state by streaming checkpoints (the combined
    ``(state, stream)`` pytree through utils/checkpoint.save_state), so a
    resumed run continues mid-queue bit-exactly."""

    next_job: Any          # i32 []  jobs admitted so far (with the memo
    #                        plane on this indexes the EXEC ORDER, not the
    #                        pool: admission maps it through run_stream's
    #                        deduplicated order array)
    jobs_done: Any         # i32 []  jobs harvested into the ring
    steps: Any             # i32 []  stream steps executed
    refills: Any           # i32 []  admissions into a RECYCLED slot
    lane_steps_live: Any   # i32 []  lane-substeps that advanced a live job
    lane_steps_total: Any  # i32 []  lane-substeps charged (occupancy denom)
    # memo-plane accounting (checkpoint format v8 counters): cache_hits/
    # coalesced_jobs/shadow_checks are host-stamped once the run retires
    # (they are properties of the admission plan); ff_skipped_ticks
    # accumulates on-device in _ff_apply, so a kill mid-stream resumes
    # the skipped-tick books bit-exactly
    cache_hits: Any        # i32 []  jobs served from the persistent cache
    coalesced_jobs: Any    # i32 []  duplicate jobs served by a rep lane
    ff_skipped_ticks: Any  # i32 []  ticks credited by fast-forward
    shadow_checks: Any     # i32 []  served summaries re-proven by shadow
    # prefix-fork books (checkpoint format v10, memo="prefix"):
    # forked_jobs/fork_depth_sum accumulate ON-DEVICE at admission (the
    # fork scatter counts itself), so a kill mid-stream resumes the fork
    # accounting bit-exactly; prefix_hits is host-stamped at finalize
    # from the admission plan (the planned forks — equal to forked_jobs
    # on a completed run, the books-balance invariant chaos_smoke pins)
    prefix_hits: Any       # i32 []  jobs planned to fork from a checkpoint
    forked_jobs: Any       # i32 []  fork admissions the device performed
    fork_depth_sum: Any    # i32 []  total phases skipped by forks
    # serving-plane books (checkpoint format v9): deadline_misses and
    # tenant_served accumulate on-device at harvest in the serve step
    # (serving/server.py); tenant_quota is the admission cap the server
    # was configured with, carried so a resumed run re-derives the same
    # refusal decisions. Plain stream runs carry T=1 zeros.
    deadline_misses: Any   # i32 []  jobs harvested past their deadline
    tenant_served: Any     # i32 [T] jobs harvested per tenant
    tenant_quota: Any      # i32 [T] admission cap per tenant (0 = none)
    res_count: Any         # i32 []  results written (ring wraps past R)
    res_job: Any            # i32 [R]    job id (-1 = empty slot)
    res_time: Any           # i32 [R]    final lane clock
    res_error: Any          # i32 [R]    sticky error bits at harvest
    res_snap_started: Any   # i32 [R]    snapshots initiated
    res_snap_completed: Any  # i32 [R]   snapshots completed on all nodes
    res_snap_failed: Any    # i32 [R]    supervisor-failed attempts
    res_fault_skew: Any     # i32 [R]    adversary token delta
    res_fault_events: Any   # i32 [R]    adversary events, all classes
    res_admit_step: Any     # i32 [R]    stream step the job was admitted at
    res_tokens: Any         # i32 [R, N] final node balances


class BatchedRunner:
    """Runs a compiled script over B vmapped instances, fully under one jit.

    The delay sampler should be per-instance (UniformJaxDelay and
    HashJaxDelay derive a distinct stream per lane in init_batch_state); a
    shared GoExact stream would make every lane identical — valid for
    testing, pointless for throughput.
    """

    def __init__(self, topology: TopologySpec, config: Optional[SimConfig],
                 delay: JaxDelay, batch: int, scheduler: str = "exact",
                 check_every: int = 0, exact_impl: str = "cascade",
                 auto_layouts: bool = False, megatick: int = 1,
                 queue_engine: str = "auto",
                 kernel_engine: Optional[str] = None, faults=None,
                 quarantine: bool = False, trace=None,
                 memo: str = "off", memo_cache: Optional[str] = None,
                 memo_cache_entries: int = 0, memo_cache_bytes: int = 0,
                 prefix_cache: Optional[str] = None,
                 prefix_cache_entries: int = 0,
                 prefix_cache_bytes: int = 0,
                 guards=None, fused_tick: Optional[str] = None,
                 fused_block_edges: int = 0,
                 fused_tile: Optional[str] = None):
        """scheduler: 'exact' = the reference's delivery semantics
        (bit-exact; the default 'cascade' formulation is O(E) vector work
        + one sequential step per marker delivered — ops/tick._cascade_tick
        — 'wave' parallelizes same-tick markers across destinations on top
        of that, bit-identical for position-addressable samplers, and
        exact_impl='fold' is the reference-literal N-step source
        scan kept as the specification form); 'sync' = simultaneous
        delivery (deterministic, protocol-equivalent, O(E) vectorized work
        per tick — the production/benchmark path, ops/tick._sync_tick).

        check_every: if > 0, evaluate the token-conservation invariant
        (the reference's checkTokens, test_common.go:298-328) INSIDE the
        jitted storm run every K phases and once after drain, setting the
        sticky ERR_CONSERVATION bit on any lane where node balances +
        in-flight ring tokens drift from the initial total (SURVEY.md §5:
        the jit-compatible sanitizer evaluated every K ticks).

        auto_layouts: let XLA choose parameter/result layouts for the
        storm runs instead of forcing row-major at the jit boundary.
        The TPU tick computes several ``[B, S, E]`` planes in a transposed
        ({0,2,1}) layout; with default boundary layouts every dispatch
        pays transpose copies on entry and exit (22% of a bare tick,
        BASELINE.md round-3 profile). Mechanism (the JAX AOT layout
        workflow — jit with ``Layout.AUTO`` rejects concrete arrays):
        ``run_storm`` lowers with ShapeDtypeStructs, compiles once,
        queries ``input_formats``, relayouts any mismatched input leaf,
        and calls the compiled object directly; fresh timed states built
        via ``init_batch_device(formats=storm_state_formats())`` are BORN
        in the compiled layouts, so steady-state dispatches are
        boundary-copy-free. Identity on CPU (XLA:CPU picks row-major).
        Default OFF: the perf paths (bench --layouts auto,
        tools/profile_tick.py) opt in; mesh-sharded states
        (parallel/mesh.shard_batch) use the plain jits.

        megatick: K-tick fusion depth for multi-tick dispatch on the
        exact path (TickKernel docstring) — script ``tick n`` stretches
        and the exact drain advance K fused ticks per loop iteration.
        Default 1 HERE (vs DenseSim's fused 8): under vmap every masked
        ``lax.cond`` computes both branches and selects over the whole
        batched state, which measured 5.7x SLOWER on the sf-256 B=64
        CPU drain than the plain per-tick loop — fusion only pays on the
        dispatch-bound single-instance path. The quiescence fast-forward
        (drained stretches in O(1)) applies at every K, including 1.
        Semantics-preserving knob either way; bench --megatick exposes
        it for the on-device A/B.

        fused_tick: the one-kernel megatick knob ("auto"/"on"/"off",
        kernels/megatick.resolve_fused_tick) — None (default) defers to
        the config's knob. When it resolves "on" the exact path's
        multi-tick/drain/flush loops run as single Pallas kernels whose
        bodies scan K full ticks with the whole DenseState VMEM-resident
        (TickKernel docstring); bit-identical either way, and because
        the runner binds ``kernel._run_ticks``/``kernel._drain_and_flush``
        directly, the fused dispatch propagates to the storm/stream
        engines with no code here. ``self.fused`` exposes the resolution
        ("on"/"off") and ``self.fused_reason`` the why; bench
        --fused-tick stamps the row. ``fused_block_edges`` overrides the
        fault-plane DMA block width (0 = default).

        fused_tile: the tiled-state extension of the fused megatick
        ("auto"/"on"/"off", kernels/megatick.resolve_fused_tile) — with
        it the [E, C] ring planes stream HBM->VMEM per step instead of
        living in the carry, so fused execution survives DenseStates
        past the 12 MB VMEM budget. None defers to the config's knob;
        "auto" engages exactly when the resident layout would not fit.
        The streaming engine's drain slice and flush pass additionally
        route through the fused kernel when the resolution is "on"
        (TickKernel._fused_stream_drain/_fused_flush) — the stream/serve
        steady-state step is then one kernel dispatch per stage, the
        ISSUE-16 "fuse the production path" move. ``self.fused_tile`` /
        ``self.fused_tile_reason`` expose the resolution; bench
        --fused-tile stamps the row.

        queue_engine: ring-queue addressing (ops/tick.TickKernel): "gather"
        = O(E) head gathers + append scatters over the packed planes,
        "mask" = the O(E·C) one-hot formulation, "auto" (default) =
        backend-resolved (ops/tick.resolve_queue_engine: gather on TPU,
        mask on CPU where XLA serializes the scatters). Bit-identical
        results; ``self.queue_engine`` holds the resolved engine, and
        bench --queue-engine exposes the A/B and stamps the row.

        faults: models/faults.JaxFaults — the deterministic fault
        adversary, armed per lane through an injective nonzero
        ``fault_key`` ramp (init_batch_state), so every lane suffers an
        independent replayable fault stream (zero a lane's key to disarm
        just that lane). None (default) compiles the hooks away.

        quarantine: freeze a lane the moment its sticky error bits fire —
        the storm phase scan, multi-tick stretches, drain and flush all
        treat ``error != 0`` like the quiescence exit, so one poisoned
        lane stops ticking (its time freezes at the poisoning tick)
        instead of corrupting aggregate metrics; healthy lanes are
        bit-unaffected. summarize() reports the decode.

        trace: utils/tracing.JaxTrace — arm the per-lane device flight
        recorder: the tick kernels append packed event words (send/recv,
        marker traffic, snapshot lifecycle, supervisor actions, fault
        firings) into the DenseState trace ring, and the streaming engine
        stamps lane admissions/harvests. When the config leaves
        ``trace_capacity`` at 0, it is bumped to the trace's capacity here
        so the ring planes exist. None (default) compiles every trace op
        away — the kernels are bit-identical to a build without the
        feature (the faults=None contract).

        memo: the memoization plane over run_stream (config.ENGINE_KNOBS;
        utils/memocache docstring). "off" (default) keeps the stream step
        bit-identical to the pre-memo engine — no digesting, no
        signature ops, no admission indirection. "admit" turns on
        content-addressed admission: pack_jobs derives per-job stream
        identities by script CONTENT (duplicates share fault/delay rows,
        so their digests — and summaries — coincide), run_stream
        coalesces in-pool duplicates onto one representative lane and
        serves digests resident in the persistent cache without burning
        a lane at all. "full" additionally maintains the per-lane state
        signature leaf inside the jitted step and fast-forwards lanes
        whose signature recurs (run_stream docstring). Every served
        summary is audited by periodic shadow re-execution
        (MEMO_SHADOW_EVERY). ``memo_cache``: path of the persistent
        JSON-lines summary cache (memocache.SummaryCache; None keeps the
        cache in-memory per run, so only coalescing and fast-forwarding
        apply across one call). ``memo_cache_entries``/
        ``memo_cache_bytes``: LRU capacity bounds for that cache
        (SummaryCache docstring; 0 = unbounded).

        memo == "prefix" layers the fork plane on the admit contract:
        pack_jobs additionally derives each job's stream identity from
        its FIRST compiled phase row (so near-duplicates sharing a
        prefix share fault/delay streams — exact duplicates still share
        full digests and coalesce) and stamps the rolling
        phase-boundary digest chain; run_stream checkpoints hot
        boundaries (shared in-pool, or previously seen in the
        PrefixCache) via a produce pass and admits chain-sharing jobs
        by FORKING the checkpointed lane state at the divergence cursor
        (ops/tick.fork_lanes; EV_PREFIX_FORK traced; rows carry
        ``served_from="prefix:<depth>"``). ``prefix_cache``: path of
        the persistent checkpoint store (memocache.PrefixCache; None
        keeps it in-memory on the runner, persisting across run_stream
        calls in-process). ``prefix_cache_entries``/
        ``prefix_cache_bytes``: its LRU bounds (0 = unbounded; bytes
        is the one that matters — checkpoints are KBs, not the
        SummaryCache's ~200 B rows).

        guards: utils/guards.RuntimeGuards — opt-in runtime contract
        sentry. When set, ``run_stream`` arms transfer_guard/leak
        checking/the compile counter around its steady-state device
        loop, and every intentional host sync goes through a named
        site (``guards.books()``). None (default) is the unguarded
        engine — identical code path, no accounting."""
        self.topo = DenseTopology(topology)
        self.config = config or SimConfig()
        self.guards = guards
        self.memo = resolve_memo(memo)
        self.memo_cache_path = memo_cache
        self.memo_cache_entries = int(memo_cache_entries)
        self.memo_cache_bytes = int(memo_cache_bytes)
        # eviction books of the most recent run's cache (the capacity-
        # bounded LRU satellite): summarize_stream surfaces them
        self._memo_cache_stats = {"cache_evictions": 0,
                                  "cache_evicted_bytes": 0}
        # prefix plane (memo="prefix"): checkpoint store config + the
        # most recent run's fork books (summarize_stream surfaces them)
        self.prefix_cache_path = prefix_cache
        self.prefix_cache_entries = int(prefix_cache_entries)
        self.prefix_cache_bytes = int(prefix_cache_bytes)
        self._prefix_cache_handle: Optional[PrefixCache] = None
        self._prefix_stats = {"prefix_evictions": 0,
                              "prefix_evicted_bytes": 0,
                              "prefix_store_entries": 0}
        self._fork_depths: List[int] = []
        self._produce_jits: dict = {}
        # per-run rows served without execution (job -> result row);
        # stream_results merges them with the harvested ring
        self._memo_rows: dict = {}
        self._topo_spec = topology
        self.delay = delay
        self.batch = batch
        # flush length must cover the sampler's actual max delay
        # (test_common.go:135-137 flushes maxDelay+1 ticks)
        if self.delay.max_delay != self.config.max_delay:
            import dataclasses

            self.config = dataclasses.replace(
                self.config, max_delay=self.delay.max_delay)
        self.trace = trace
        if trace is not None and self.config.trace_capacity == 0:
            import dataclasses

            # the ring planes are sized by the config; an armed trace with
            # the knob left at its 0 default gets the trace's capacity
            self.config = dataclasses.replace(
                self.config,
                trace_capacity=getattr(trace, "capacity", 0)
                or JaxTrace.DEFAULT_CAPACITY)
        if scheduler not in ("exact", "sync"):
            raise ValueError(f"unknown scheduler {scheduler!r}")
        # sync uses the split marker representation (ring content untouched
        # by ticks); exact needs the unified ring for push-order PRNG draws
        self.kernel = TickKernel(
            self.topo, self.config, self.delay,
            marker_mode="split" if scheduler == "sync" else "ring",
            exact_impl=exact_impl, megatick=megatick,
            queue_engine=queue_engine, kernel_engine=kernel_engine,
            faults=faults, quarantine=quarantine, trace=trace,
            fused_tick=fused_tick, fused_block_edges=fused_block_edges,
            fused_tile=fused_tile)
        self.queue_engine = self.kernel.queue_engine
        self.kernel_engine = self.kernel.kernel_engine
        self.fused = self.kernel.fused
        self.fused_reason = self.kernel.fused_reason
        self.fused_tile = self.kernel.fused_tile
        self.fused_tile_reason = self.kernel.fused_tile_reason
        self.faults = faults
        self.quarantine = bool(quarantine)
        self._trace_on = self.kernel._trace_on
        if scheduler == "exact":
            self._tick_fn = self.kernel._exact_tick
            self._drain_fn = self.kernel._drain_and_flush
            # fused multi-tick dispatch: megatick scan + quiescence
            # fast-forward (TickKernel._run_ticks)
            self._ticks_fn = self.kernel._run_ticks
        else:
            self._tick_fn = self.kernel._sync_tick
            self._drain_fn = self.kernel._sync_drain_and_flush
            # the sync tick draws (S, E) delays every tick, so it is never
            # a pure time increment — no quiescence fast-forward; multi-
            # tick script stretches still run under one fused loop
            self._ticks_fn = lambda s, n: lax.fori_loop(
                jnp.int32(0), jnp.asarray(n, jnp.int32),
                lambda _, t: self.kernel._sync_tick(t), s)
        self.scheduler = scheduler
        self.megatick = int(megatick)
        if check_every < 0:
            raise ValueError("check_every must be >= 0 (0 = off)")
        self.check_every = int(check_every)
        self.auto_layouts = auto_layouts
        # set the first time the AOT path's executable rejects our layouts
        # (the axon PJRT plugin's ``input_formats`` can disagree with the
        # executable's true parameter layouts for some programs); once
        # tripped, every storm run rides the plain row-major jits and
        # ``layouts_effective`` reports the degradation. Also pre-tripped
        # when the jax build has no layout API at all (utils/layouts) —
        # the round-5 exact bench died on that ImportError mid-warmup
        self._auto_unavailable = bool(auto_layouts) and not HAVE_LAYOUTS
        self._auto_broken = self._auto_unavailable
        self._storm_aot = {}   # (drain, prog shapes) -> (compiled, relayout)
        self._storm_prog_placed = {}  # same key -> (host values, placed prog)
        self._storm_rejected = set()  # keys whose AOT call was rejected
        self._storm_state_formats = None
        self._run = jax.jit(
            jax.vmap(self._run_single, in_axes=(0, None)), donate_argnums=0)
        self._run_no_drain = jax.jit(
            jax.vmap(self._run_single_no_drain, in_axes=(0, None)),
            donate_argnums=0)
        self._run_storm = jax.jit(
            jax.vmap(self._run_storm_single, in_axes=(0, None)),
            donate_argnums=0)
        self._run_storm_no_drain = jax.jit(
            jax.vmap(self._run_storm_phases, in_axes=(0, None)),
            donate_argnums=0)

    # -- state construction ------------------------------------------------

    def init_batch(self) -> DenseState:
        """Fresh batched state: sim arrays broadcast over B, delay state
        built per-lane. Host-side (numpy) — jit transfers it on first use;
        prefer init_batch_device for timed runs."""
        single = init_state(self.topo, self.config, None)
        batched = jax.tree_util.tree_map(
            lambda x: np.broadcast_to(np.asarray(x), (self.batch,) + np.shape(x)).copy(),
            single._replace(delay_state=()))
        if self.faults is not None:
            batched = batched._replace(
                fault_key=np.asarray(self.faults.init_batch_state(self.batch)))
        return batched._replace(delay_state=self._batched_delay_state())

    @property
    def layouts_effective(self) -> str:
        """The boundary-layout mode runs are actually using: 'auto' while
        the AOT path is live, 'default' when auto_layouts is off, and
        'default(auto-rejected)' after the executable rejected the
        ``input_formats``-derived layouts and the runner degraded to the
        row-major jits (bench rows record this, so a fallback can never
        masquerade as an auto-layout measurement); 'default(auto-unavailable)'
        when this jax build exposes no layout API at all."""
        if not self.auto_layouts:
            return "default"
        if self._auto_unavailable:
            return "default(auto-unavailable)"
        if self._auto_broken:
            return "default(auto-rejected)"
        if self._storm_rejected:
            # per-key degradation: only the rejecting shape bucket fell
            # back; other compiled buckets stay warm on the AOT path
            if not self._storm_aot:
                return "default(auto-rejected)"
            return f"auto(+{len(self._storm_rejected)} rejected)"
        return "auto"

    def storm_state_formats(self):
        """The compiled storm program's state input Formats (layout +
        sharding per leaf), available after the first ``run_storm`` under
        ``auto_layouts``. Hand to ``init_batch_device(formats=...)`` so
        fresh timed states enter the next dispatch with zero relayout
        copies (VERDICT r4 #6: the {0,2,1}<->{0,1,2} boundary
        transposes). None before the first auto run (or without
        auto_layouts) — init then builds default-layout states."""
        return self._storm_state_formats

    def init_batch_device(self, formats=None) -> DenseState:
        """Fresh batched state constructed ON the device by a jitted builder
        — no host->device transfer of the (multi-GB) state.

        This matters enormously when the chip is remote: the round-2 bench
        measured 2.2M node-ticks/s because each timed repeat shipped the
        ~4.6 GB numpy state of init_batch through the device tunnel
        (~16 s) inside the timed region; the tick itself runs in ~34 ms.
        Everything in the initial state is zeros except the token balances
        (a [N] broadcast) and the per-lane PRNG keys, so XLA materializes it
        in microseconds.

        ``formats``: optional pytree of device Formats (``state_formats``)
        the builder emits directly — the state is born in the consuming
        program's layouts, never relayouted (and never double-resident the
        way a post-hoc device_put would transiently be).
        """
        if getattr(self, "_init_device_formats", None) is not formats:
            # formats changed (identity check): drop the cached builder
            self._init_device_formats = formats
            if hasattr(self, "_init_device"):
                del self._init_device
        if not hasattr(self, "_init_device"):
            build = self._state_builder()
            # cached: a fresh jit closure per call would retrace every time
            self._init_device = (jax.jit(build, out_shardings=formats)
                                 if formats is not None else jax.jit(build))
        return self._init_device()

    def _state_builder(self):
        """The fresh-batched-state constructor as a traceable zero-arg
        function (shared by ``init_batch_device`` and ``prepare_storm``'s
        ``eval_shape``)."""
        if not hasattr(self, "_build_fn"):
            single = init_state(self.topo, self.config, None)
            template = single._replace(delay_state=())
            tokens0 = jnp.asarray(self.topo.tokens0)

            def build():
                st = jax.tree_util.tree_map(
                    lambda x: jnp.zeros((self.batch,) + np.shape(x),
                                        np.asarray(x).dtype), template)
                st = st._replace(
                    tokens=jnp.broadcast_to(
                        tokens0, (self.batch,) + tokens0.shape),
                    # the non-zero inits beside tokens (state.init_state):
                    # "no protected window yet" = int32 max, the
                    # supervisor's "unset" initiator/completion-tick = -1,
                    # and the streaming engine's "idle slot" job id = -1
                    min_prot=jnp.full_like(st.min_prot,
                                           jnp.iinfo(jnp.int32).max),
                    snap_initiator=jnp.full_like(st.snap_initiator, -1),
                    snap_done_time=jnp.full_like(st.snap_done_time, -1),
                    job_id=jnp.full_like(st.job_id, -1),
                    # the flight recorder is born armed (state.init_state);
                    # a zeroed tr_on would silently disarm device-built
                    # states
                    tr_on=jnp.ones_like(st.tr_on))
                if self.faults is not None:
                    st = st._replace(
                        fault_key=self.faults.init_batch_state(self.batch))
                return st._replace(delay_state=self._batched_delay_state())

            self._build_fn = build
        return self._build_fn

    def prepare_storm(self, program, drain: bool = True):
        """AOT-compile the storm program from shapes alone and return the
        state input Formats (or None without ``auto_layouts``). Callers
        that build states AFTER this — ``init_batch_device(formats=...)``
        — get arrays born in the executable's layouts, so even the very
        first ``run_storm`` dispatch skips the relayout step entirely and
        the multi-GB state is never transiently double-resident (the
        bench's warmup does this; near-HBM-limit shapes such as the
        maxbatch probes depend on it)."""
        if not self.auto_layouts or self._auto_broken:
            return None
        prog = tuple(jnp.asarray(x) for x in program)
        key = (drain, tuple((tuple(x.shape), str(x.dtype)) for x in prog))
        if key in self._storm_rejected:
            return None
        abstract_state = jax.eval_shape(self._state_builder())
        comp, _ = self._storm_compiled(abstract_state, prog, drain)
        return input_formats(comp)[0][0]

    def _batched_delay_state(self):
        return self.delay.init_batch_state(self.batch)

    # -- execution ---------------------------------------------------------

    def _quarantine_gate(self, phase_fn):
        """Wrap a per-lane phase body so a lane with sticky error bits is
        frozen for the whole phase — the scan-path extension of the
        kernel's drain/flush quarantine exits. Identity when quarantine is
        off (no cond in the trace)."""
        if not self.quarantine:
            return phase_fn

        def gated(s, *xs):
            return lax.cond(s.error == 0,
                            lambda s: phase_fn(s, *xs), lambda s: s, s)

        return gated

    def _apply_phase(self, s: DenseState, ops) -> DenseState:
        kind, arg0, arg1, do_tick = ops

        def body(j, s):
            return lax.switch(kind[j], [
                lambda s: s,
                lambda s: self.kernel._inject_send(s, arg0[j], arg1[j]),
                lambda s: self.kernel._inject_snapshot(s, arg0[j]),
            ], s)

        def run(s):
            # i32 bounds pin the induction var: a Python-int bound makes j
            # the platform int under x64 and drags the kind/arg gathers'
            # index arithmetic up to i64
            s = lax.fori_loop(jnp.int32(0), jnp.int32(kind.shape[0]),
                              body, s)
            # do_tick is a COUNT (compile_events): the whole stretch runs
            # under the fused multi-tick engine, one phase per stretch
            return lax.cond(do_tick != 0,
                            lambda s: self._ticks_fn(s, do_tick),
                            lambda s: s, s)

        return self._quarantine_gate(lambda s: run(s))(s)

    def _run_single_no_drain(self, s: DenseState, script: ScriptOps) -> DenseState:
        def phase(s, ops):
            return self._apply_phase(s, ops), None

        s, _ = lax.scan(phase, s, tuple(script))
        return s

    def _run_single(self, s: DenseState, script: ScriptOps) -> DenseState:
        s = self._run_single_no_drain(s, script)
        return self._drain_fn(s)

    def run(self, state: DenseState, script: ScriptOps,
            drain: bool = True) -> DenseState:
        """One dispatch: inject + tick every phase, then (optionally) drain
        until all lanes' snapshots complete + flush."""
        fn = self._run if drain else self._run_no_drain
        return fn(state, ScriptOps(*map(jnp.asarray, script)))

    def run_ticks(self, state: DenseState, n) -> DenseState:
        """Advance every lane n ticks under one dispatch via the fused
        multi-tick engine (megatick scan + quiescence fast-forward on the
        exact path; a fused loop of sync ticks otherwise)."""
        if not hasattr(self, "_run_ticks_jit"):
            self._run_ticks_jit = jax.jit(
                jax.vmap(self._ticks_fn, in_axes=(0, None)),
                donate_argnums=0)
        return self._run_ticks_jit(state, jnp.asarray(n, jnp.int32))

    # -- storm programs (models/workloads.py): bulk vectorized sends ------

    def storm_phase(self, s: DenseState, amounts, snaps) -> DenseState:
        """One storm phase for one instance: bulk sends + scheduled snapshot
        initiations + one tick. This is the framework's 'forward step'.
        Under quarantine the whole phase freezes on a poisoned lane
        (_run_storm_phases wraps it in the per-lane gate)."""
        s = self.kernel._bulk_send(s, amounts)
        if self.scheduler == "sync":
            # dense initiation (ids allocated in node-index order == the
            # schedule builder's order); the scalar path below would run its
            # scatter-heavy broadcast under vmap's select semantics every
            # phase even when no snapshot fires
            init_mask = jnp.any(
                jnp.arange(self.topo.n, dtype=jnp.int32)[None, :]
                == snaps[:, None], axis=0)
            s = self.kernel._bulk_snapshots(s, init_mask)
        else:
            def body(j, s):
                return lax.cond(snaps[j] >= 0,
                                lambda s: self.kernel._inject_snapshot(s, snaps[j]),
                                lambda s: s, s)

            s = lax.fori_loop(0, snaps.shape[-1], body, s)
        return self._tick_fn(s)

    def _check_conservation(self, s: DenseState) -> DenseState:
        from chandy_lamport_tpu.utils.metrics import conservation_delta

        delta = conservation_delta(s, self.config,
                                   int(self.topo.tokens0.sum()))
        return s._replace(error=s.error | jnp.where(
            delta != 0, ERR_CONSERVATION, 0).astype(jnp.int32))

    def _run_storm_phases(self, s: DenseState, program) -> DenseState:
        amounts, snap = program
        k = self.check_every
        gated_phase = self._quarantine_gate(self.storm_phase)

        def phase(s, xs):
            s = gated_phase(s, xs[0], xs[1])
            if k:
                s = lax.cond((xs[2] + 1) % k == 0,
                             self._check_conservation, lambda s: s, s)
            return s, None

        idx = jnp.arange(amounts.shape[0], dtype=jnp.int32)
        s, _ = lax.scan(phase, s, (amounts, snap, idx))
        # a no-drain run must not end between check points with a clean bit
        # misread as "verified through end of run"
        return self._check_conservation(s) if k else s

    def _run_storm_single(self, s: DenseState, program) -> DenseState:
        s = self._run_storm_phases(s, program)
        s = self._drain_fn(s)
        return self._check_conservation(s) if self.check_every else s

    def drain(self, state: DenseState) -> DenseState:
        """Drain + flush every lane (and the final conservation check when
        check_every is on) as its own dispatch — the tail step of a
        chunked/checkpointed storm run (cli storm --checkpoint-every runs
        phases in chunks with ``run_storm(..., drain=False)`` and finishes
        here; bit-identical to the single-dispatch ``run_storm`` because
        the per-tick math and the state-carried streams are unchanged)."""
        if not hasattr(self, "_drain_jit"):
            def fn(s):
                s = self._drain_fn(s)
                return (self._check_conservation(s) if self.check_every
                        else s)

            self._drain_jit = jax.jit(jax.vmap(fn), donate_argnums=0)
        return self._drain_jit(state)

    def run_storm(self, state: DenseState, program,
                  drain: bool = True) -> DenseState:
        """Execute a StormProgram (bulk per-edge sends + scheduled snapshot
        initiations + one tick per phase) over all lanes in one dispatch.
        Under ``auto_layouts``, dispatches the AOT-compiled executable with
        XLA-chosen boundary layouts (constructor docstring)."""
        prog = tuple(jnp.asarray(x) for x in program)
        key = (drain, tuple((tuple(x.shape), str(x.dtype)) for x in prog))
        if (not self.auto_layouts or self._auto_broken
                or key in self._storm_rejected):
            fn = self._run_storm if drain else self._run_storm_no_drain
            return fn(state, prog)
        comp, relayout = self._storm_compiled(state, prog, drain)
        # benches pass the same program values every timed repeat, but each
        # ``jnp.asarray`` lands in the default layout — when the executable
        # chose a non-default program layout that would force the relayout
        # dispatch into every timed region. Reuse the placed copy by value
        # (the tensors are tiny; the state is the thing we must not copy).
        cached = self._storm_prog_placed.get(key)
        if cached is not None and all(
                np.array_equal(a, np.asarray(b))
                for a, b in zip(cached[0], prog)):
            prog = cached[1]
        if not _formats_match((state, prog), input_formats(comp)[0]):
            # Relayout through a COMPILED identity whose output formats are
            # pinned to the storm executable's input formats. A plain
            # ``jax.device_put(x, format)`` is not reliable here: the axon
            # TPU backend was observed producing its shape-preferred layout
            # instead of the requested one, after which the AOT call's
            # layout check rejects the arrays. An executable's output
            # layouts, by contrast, are enforced by XLA itself, and the
            # call-time check compares against the same ``_xla_in_layouts``
            # list ``input_formats`` is built from — so this dispatch
            # satisfies it by construction. Donated + aliased: leaves whose
            # layout already matches pass through without a copy, so the
            # multi-GB state is never double-resident.
            host_prog = tuple(np.asarray(x) for x in prog)
            state, prog = relayout(state, prog)
            self._storm_prog_placed[key] = (host_prog, prog)
        try:
            return comp(state, prog)
        except ValueError as exc:
            if "layouts" not in str(exc):
                raise
            # still rejected: degrade THIS shape bucket permanently to the
            # row-major jit boundaries (the measured round-3 path) rather
            # than fail the run — other compiled buckets stay warm on the
            # AOT path (a serving process must not re-pay every tenant's
            # compile because one odd topology's layouts were refused).
            # The rejection fires before execution, so the donated buffers
            # are still alive.
            import warnings

            warnings.warn(
                "auto-layout AOT call rejected executable-produced "
                f"layouts; falling back to default boundary layouts for "
                f"this program shape: {exc}")
            self._storm_rejected.add(key)
            self._storm_aot.pop(key, None)  # dead executable; free its prog
            self._storm_prog_placed.pop(key, None)
            if not self._storm_aot:
                # no live bucket left to vouch for the formats feedback
                self._storm_state_formats = None
            fn = self._run_storm if drain else self._run_storm_no_drain
            return fn(state, prog)

    def _storm_compiled(self, state, prog, drain: bool):
        """AOT-compile the storm run with AUTO in/out layouts (cached per
        program shape), plus a donated identity jit whose output formats
        are pinned to the storm executable's chosen input formats (the
        run_storm relayout step). Lowering takes abstract
        ShapeDtypeStructs — the only arg form ``Layout.AUTO`` accepts —
        so this is the one compile the run needs, not an extra one (the
        identity is a trivial aliasing program)."""
        key = (drain, tuple((tuple(x.shape), str(x.dtype)) for x in prog))
        entry = self._storm_aot.get(key)
        if entry is None:
            fmt = auto_format()
            fn = jax.jit(
                jax.vmap(self._run_storm_single if drain
                         else self._run_storm_phases, in_axes=(0, None)),
                donate_argnums=0, in_shardings=fmt, out_shardings=fmt)
            # x may be a live array OR already a ShapeDtypeStruct (the
            # prepare_storm compile-from-shapes path)
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype),
                (state, prog))
            comp = fn.lower(*abstract).compile()
            # donate the (multi-GB) state so matching leaves alias through
            # copy-free; the program tensors are tiny, copying them keeps
            # caller-held arrays valid
            relayout = jax.jit(lambda s, p: (s, p), donate_argnums=0,
                               out_shardings=input_formats(comp)[0])
            entry = (comp, relayout)
            self._storm_aot[key] = entry
            self._storm_state_formats = input_formats(comp)[0][0]
        return entry

    # -- streaming job engine (continuous lane scheduling) -----------------
    #
    # run() amortizes ONE script over B lanes; every lane retires together,
    # so a heavy-tailed job mix pays the whole batch's wall clock for its
    # slowest member (summarize()'s straggler_waste measures the hole).
    # run_stream() instead drives a QUEUE of J jobs through the B slots:
    # a jitted step advances every lane a bounded stretch through a
    # per-lane stage machine (script phases -> drain -> flush, the exact
    # sequence run() executes), harvests retired lanes into a device-side
    # results ring, and admits the next queued jobs into the freed slots in
    # place — donated buffers, no host round trip beyond the scalar
    # termination check. Per-job summaries are bit-identical to running
    # each job in a static batch (tests/test_stream.py holds this across
    # schedulers, faults and quarantine).

    def pack_jobs(self, jobs, fault_armed=None,
                  content_keys: Optional[bool] = None) -> JobPool:
        """Compile + pack J jobs (event lists or pre-compiled ScriptOps)
        into one pooled phase table. ``fault_armed``: optional [J] bools —
        when the runner carries a fault adversary, arms exactly those jobs
        (per-JOB keys from faults.init_batch_state(J), zeroed where
        disarmed); default arms all. Without an adversary all keys are 0.

        ``content_keys`` (default: on iff the runner's memo plane is on):
        derive the per-job fault/delay stream identities by script CONTENT
        rank instead of pool index — jobs with byte-identical compiled
        scripts share the same ``init_batch_state`` row, so exact
        duplicates run the identical computation on identical operands and
        their content digests (``JobPool.digest``) coincide. The memo
        plane requires this (index-derived rows would give every
        duplicate a distinct stream and nothing would ever coalesce);
        the default off-path keeps the pre-memo index contract
        bit-exactly. Digests are computed only under ``content_keys``."""
        scripts = [j if isinstance(j, ScriptOps)
                   else compile_events(self.topo, j) for j in jobs]
        if not scripts:
            raise ValueError("pack_jobs: empty job list")
        jcount = len(scripts)
        kmax = max(s.kind.shape[1] for s in scripts)
        total = sum(s.num_phases for s in scripts)
        kind = np.zeros((total, kmax), np.int32)
        arg0 = np.zeros((total, kmax), np.int32)
        arg1 = np.zeros((total, kmax), np.int32)
        do_tick = np.zeros(total, np.int32)
        start = np.zeros(jcount, np.int32)
        end = np.zeros(jcount, np.int32)
        limit = np.zeros(jcount, np.int32)
        row = 0
        for j, s in enumerate(scripts):
            t, k = s.kind.shape
            start[j], end[j] = row, row + t
            kind[row:row + t, :k] = np.asarray(s.kind)
            arg0[row:row + t, :k] = np.asarray(s.arg0)
            arg1[row:row + t, :k] = np.asarray(s.arg1)
            do_tick[row:row + t] = np.asarray(s.do_tick)
            # the static drain's limit is entry-relative (time + max_ticks,
            # TickKernel._drain_and_flush_with) and a scripted lane enters
            # the drain at time == its total scripted ticks exactly
            # (_run_ticks always credits the full stretch), so the absolute
            # budget is precomputable per job
            limit[j] = int(np.sum(np.asarray(s.do_tick))) + \
                self.config.max_ticks
            row += t
        if content_keys is None:
            content_keys = self.memo != "off"
        if content_keys:
            # content rank: first-appearance index of each distinct
            # compiled script (bytes of the padded op tensors — two jobs
            # get the same rank iff their pooled rows are identical)
            u_of: dict = {}
            u_index = np.zeros(jcount, np.int64)
            for j, s in enumerate(scripts):
                sig = (s.kind.shape,
                       np.asarray(s.kind).tobytes(),
                       np.asarray(s.arg0).tobytes(),
                       np.asarray(s.arg1).tobytes(),
                       np.asarray(s.do_tick).tobytes())
                u_index[j] = u_of.setdefault(sig, len(u_of))
            nuniq = len(u_of)
        else:
            u_index = np.arange(jcount)
            nuniq = jcount
        if content_keys and self.memo == "prefix":
            # prefix identity rank: first-appearance index of each
            # distinct FIRST pooled phase row. Full-script rank would
            # hand jobs that differ only in their tails distinct
            # fault/delay streams, making every prefix checkpoint
            # single-use; keying the stream identity on phase 0 makes
            # chain-sharing jobs share streams (so a checkpoint forks
            # into all of them) while exact duplicates — same first
            # row a fortiori — still share full digests and coalesce.
            # Identity stays content-derived, so summaries remain pure
            # functions of job content (the fleet bit-identity bar).
            f_of: dict = {}
            ident_index = np.zeros(jcount, np.int64)
            for j in range(jcount):
                r = int(start[j])
                fsig = (kind[r].tobytes(), arg0[r].tobytes(),
                        arg1[r].tobytes(), int(do_tick[r]))
                ident_index[j] = f_of.setdefault(fsig, len(f_of))
            nident = len(f_of)
        else:
            ident_index, nident = u_index, nuniq
        if self.faults is not None:
            keys = np.asarray(
                self.faults.init_batch_state(nident))[ident_index]
            if fault_armed is not None:
                keys = np.where(np.asarray(fault_armed, bool), keys,
                                keys.dtype.type(0))
        else:
            keys = np.zeros(jcount, np.uint32)
        prefix_digest = None
        if content_keys:
            delay_rows = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[ident_index],
                self.delay.init_batch_state(nident))
            digests = self._job_digests(scripts, u_index, keys, delay_rows)
            if self.memo == "prefix":
                prefix_digest = self._prefix_chains(
                    kind, arg0, arg1, do_tick, start, end, ident_index,
                    keys, delay_rows)
        else:
            # the pre-memo path, untouched: index-derived rows handed to
            # the pool as built (stream-vs-static parity depends on it)
            delay_rows = self.delay.init_batch_state(jcount)
            digests = np.zeros((jcount, 32), np.uint8)
        return JobPool(kind, arg0, arg1, do_tick, start, end, limit, keys,
                       digests, delay_rows, prefix_digest)

    def _job_digests(self, scripts, u_index, keys, delay_rows) -> np.ndarray:
        """[J, 32] sha256 content addresses (utils/memocache.job_digest):
        everything that determines job j's summary bit-for-bit — topology,
        its compiled script, its fault/delay stream rows, and the runner's
        resolved execution identity (scheduler, engines, semantic config).
        Duplicate (script, fault key) pairs hash once."""
        cfg_fields, knobs = self._digest_identity()
        leaves, treedef = jax.tree_util.tree_flatten(
            jax.device_get(delay_rows))
        leaves = [np.asarray(x) for x in leaves]
        out = np.zeros((len(scripts), 32), np.uint8)
        seen: dict = {}
        for j, s in enumerate(scripts):
            # same content rank + same armed key -> same digest, hash once
            memo_key = (int(u_index[j]), int(keys[j]))
            hx = seen.get(memo_key)
            if hx is None:
                hx = job_digest(
                    topo_spec=self._topo_spec,
                    script=(np.asarray(s.kind), np.asarray(s.arg0),
                            np.asarray(s.arg1), np.asarray(s.do_tick)),
                    fault_key=int(keys[j]),
                    delay_row={"treedef": str(treedef),
                               "leaves": [lv[j] for lv in leaves]},
                    scheduler=self.scheduler, knobs=knobs,
                    config_fields=cfg_fields)
                seen[memo_key] = hx
            out[j] = np.frombuffer(bytes.fromhex(hx), np.uint8)
        return out

    def _digest_identity(self):
        """The runner's execution identity as digest ingredients: the
        semantics-affecting SimConfig fields and the RESOLVED engine
        knobs — shared by the whole-job digest (_job_digests) and the
        prefix-chain seed (_prefix_chains), so the two planes can never
        drift on what "same computation" means."""
        import dataclasses

        cfg_fields = dataclasses.asdict(self.config)
        # trace_capacity changes only observability (the flight-recorder
        # ring), never a summary — the one excluded field
        cfg_fields.pop("trace_capacity")
        knobs = {
            "queue_engine": self.queue_engine,
            "kernel_engine": self.kernel_engine,
            "fused_tick": self.fused,
            "fused_tile": self.fused_tile,
            "exact_impl": self.kernel.exact_impl,
            "megatick": self.megatick,
            "check_every": self.check_every,
            "quarantine": self.quarantine,
            "delay_kind": type(self.delay).__name__,
            "faults": (None if self.faults is None
                       else sorted(vars(self.faults).items())),
        }
        return cfg_fields, knobs

    def _prefix_chains(self, kind, arg0, arg1, do_tick, start, end,
                       ident_index, keys, delay_rows) -> np.ndarray:
        """[P, 32] rolling phase-boundary digest chains over the pooled
        phase table (JobPool.prefix_digest docstring): per job, link 0
        is the script-free identity seed (prefix_seed_digest over the
        same ingredients as job_digest minus the script) and each pooled
        phase row extends it (prefix_extend), written at its row. Seeds
        dedup by (identity rank, armed key) — chain-sharing jobs share
        seeds by construction."""
        cfg_fields, knobs = self._digest_identity()
        leaves, treedef = jax.tree_util.tree_flatten(
            jax.device_get(delay_rows))
        leaves = [np.asarray(x) for x in leaves]
        out = np.zeros((kind.shape[0], 32), np.uint8)
        seeds: dict = {}
        for j in range(len(start)):
            seed_key = (int(ident_index[j]), int(keys[j]))
            c = seeds.get(seed_key)
            if c is None:
                c = prefix_seed_digest(
                    topo_spec=self._topo_spec,
                    fault_key=int(keys[j]),
                    delay_row={"treedef": str(treedef),
                               "leaves": [lv[j] for lv in leaves]},
                    scheduler=self.scheduler, knobs=knobs,
                    config_fields=cfg_fields)
                seeds[seed_key] = c
            for r in range(int(start[j]), int(end[j])):
                c = prefix_extend(
                    c, (kind[r], arg0[r], arg1[r], int(do_tick[r])))
                out[r] = np.frombuffer(c, np.uint8)
        return out

    def init_stream(self, pool: JobPool,
                    results_capacity: Optional[int] = None,
                    tenants: int = 1,
                    tenant_quota=None) -> StreamState:
        """Fresh stream carry for ``pool``: zero counters + an empty results
        ring of ``results_capacity`` slots (default: one per job, so
        nothing is ever evicted; smaller rings wrap, keeping the newest).
        ``tenants``/``tenant_quota`` size the serving-plane books (v9
        leaves) — plain stream runs keep the default single zero row."""
        r = int(results_capacity) if results_capacity else pool.num_jobs
        if r < 1:
            raise ValueError("results_capacity must be >= 1")
        t = max(1, int(tenants))
        quota = (np.zeros(t, np.int32) if tenant_quota is None
                 else np.asarray(tenant_quota, np.int32))
        if quota.shape != (t,):
            raise ValueError(
                f"tenant_quota must be one cap per tenant ([{t}]), "
                f"got shape {quota.shape}")
        i = np.int32

        def z(*sh):
            return np.zeros(sh, np.int32)

        return StreamState(
            next_job=i(0), jobs_done=i(0), steps=i(0), refills=i(0),
            lane_steps_live=i(0), lane_steps_total=i(0),
            cache_hits=i(0), coalesced_jobs=i(0), ff_skipped_ticks=i(0),
            shadow_checks=i(0), prefix_hits=i(0), forked_jobs=i(0),
            fork_depth_sum=i(0), deadline_misses=i(0),
            tenant_served=z(t), tenant_quota=quota, res_count=i(0),
            res_job=np.full(r, -1, np.int32), res_time=z(r), res_error=z(r),
            res_snap_started=z(r), res_snap_completed=z(r),
            res_snap_failed=z(r), res_fault_skew=z(r), res_fault_events=z(r),
            res_admit_step=z(r), res_tokens=z(r, self.topo.n))

    def _stream_step(self, stretch: int, drain_chunk: int, gang: bool,
                     serve: bool = False, memo: Optional[str] = None):
        if not hasattr(self, "_stream_jits"):
            self._stream_jits = {}
        if memo is None:
            # serve handles coalescing host-side, so its step compiles
            # the memo-off admission — EXCEPT under "prefix", whose fork
            # scatter must live inside the jitted admission. An explicit
            # ``memo`` overrides (the cold solo side-runs of the fork
            # shadow audit compile the off step on a memoized runner).
            memo = (self.memo if (not serve or self.memo == "prefix")
                    else "off")
        key = (int(stretch), int(drain_chunk), bool(gang),
               memo, bool(serve))
        fn = self._stream_jits.get(key)
        if fn is None:
            fn = jax.jit(self._build_stream_step(*key),
                         donate_argnums=(0, 1))
            self._stream_jits[key] = fn
        return fn

    def _build_stream_step(self, stretch: int, drain_chunk: int, gang: bool,
                           memo: str = "off", serve: bool = False):
        """One jitted streaming step: harvest retired lanes -> admit queued
        jobs into the freed slots -> advance every lane through the
        per-lane stage machine. The stage machine replays run()'s exact
        sequence per lane — script phases via _apply_phase (one pooled row
        per substep), then the drain under the same per-tick condition as
        TickKernel._drain_and_flush_with, then the max_delay+1 flush —
        encoded in ``prog_cursor``: rows [start, end) are the script,
        end = draining, end+1 = flushing, end+2 = retired.

        Pass structure per step: ``stretch`` script substeps (one phase
        each), then ONE ``drain_chunk``-tick drain slice, then ONE flush
        pass. Under vmap a masked branch computes and selects for every
        lane regardless of its stage, so the expensive passes are paid
        once per STEP, not once per substep — a lane that finishes its
        script mid-step still enters its drain (and possibly its flush)
        in the same step, so short jobs retire in one step while the step
        cost stays ~(stretch + drain_chunk + max_delay) batched ticks."""
        kern = self.kernel
        cfg = self.config
        n = self.topo.n
        quarantine = self.quarantine

        def lane_pass(s, pool):
            jmax = pool.job_start.shape[0] - 1

            def stage_of(s):
                end = pool.job_end[jnp.clip(s.job_id, 0, jmax)]
                ok = (s.error == 0) if quarantine else jnp.bool_(True)
                run = (s.job_id >= 0) & ok
                cur = s.prog_cursor
                return jnp.where(run & (cur < end), 1,
                                 jnp.where(run & (cur == end), 2,
                                           jnp.where(run & (cur == end + 1),
                                                     3, 0)))

            def script(s):
                c = jnp.clip(s.prog_cursor, 0, pool.kind.shape[0] - 1)
                ops = (pool.kind[c], pool.arg0[c], pool.arg1[c],
                       pool.do_tick[c])
                s = self._apply_phase(s, ops)
                return s._replace(prog_cursor=s.prog_cursor + 1)

            def sub(s, _):
                return lax.cond(stage_of(s) == 1, script,
                                lambda u: u, s), None

            s, _ = lax.scan(sub, s, None, length=stretch)

            # drain slice: the cursor pins the stage for the whole pass
            # (only the completion bookkeeping below advances it), so the
            # entry mask is loop-invariant; error bits fired mid-slice
            # still stop a quarantined lane via more()'s per-tick check
            in_drain = stage_of(s) == 2
            limit = pool.job_limit[jnp.clip(s.job_id, 0, jmax)]

            def more(t):
                p = in_drain & kern._pending(t) & (t.time < limit)
                return (p & (t.error == 0)) if quarantine else p

            # fused stream/serve steady state: with the one-kernel
            # megatick resolved "on" (exact path only — kern.fused
            # already encodes that), the drain slice and the flush pass
            # below each run as fused kernel dispatches instead of
            # drain_chunk/max_delay+1 scanned cond-ticks — bit-identical
            # (TickKernel._fused_stream_drain docstring)
            use_fused = self.scheduler == "exact" and kern.fused == "on"
            if use_fused:
                s = kern._fused_stream_drain(s, in_drain, limit,
                                             drain_chunk)
            else:
                def one(t, _):
                    return lax.cond(more(t), self._tick_fn,
                                    lambda u: u, t), None

                s, _ = lax.scan(one, s, None, length=drain_chunk)
            done = in_drain & ~more(s)
            blown = kern._pending(s)
            if quarantine:
                blown = blown & (s.error == 0)
            s = s._replace(
                error=s.error | jnp.where(done & blown, ERR_TICK_LIMIT,
                                          0).astype(jnp.int32),
                prog_cursor=jnp.where(done, s.prog_cursor + 1,
                                      s.prog_cursor))

            def flush(s):
                if use_fused:
                    s = kern._fused_flush(s)
                else:
                    tick = self._tick_fn
                    if quarantine:
                        def tick(t):
                            return lax.cond(t.error == 0, self._tick_fn,
                                            lambda u: u, t)
                    s = lax.fori_loop(0, cfg.max_delay + 1,
                                      lambda _, t: tick(t), s)
                return s._replace(prog_cursor=s.prog_cursor + 1)

            s = lax.cond(stage_of(s) == 3, flush, lambda u: u, s)
            if memo == "full":
                # memo plane: refresh the rolling state signature once per
                # pass; the host fast-forward keys on (job, cursor, sig)
                # recurrence across steps (run_stream)
                s = s._replace(sig=_lane_signature(s))
            return s

        def step(state, stream, pool, order=None, followers=None,
                 limit=None, tenant_of=None, arrival_of=None,
                 deadline_of=None, bank=None, fork_src=None,
                 fork_depth=None):
            jcount = pool.job_start.shape[0]
            jmax = jcount - 1
            rcap = stream.res_job.shape[0]
            # -- harvest: scatter retired lanes into the results ring ------
            jid = state.job_id
            has_job = jid >= 0
            fin = has_job & (state.prog_cursor
                             >= pool.job_end[jnp.clip(jid, 0, jmax)] + 2)
            if quarantine:
                # a poisoned lane is frozen forever — retire it with its
                # error bits in the summary and recycle the slot
                fin = fin | (has_job & (state.error != 0))
            h = harvest_lane_summaries(state, n)
            rank = jnp.cumsum(fin.astype(jnp.int32)) - 1
            pos = (stream.res_count + rank) % rcap
            widx = jnp.where(fin, pos, rcap)  # rcap is OOB -> row dropped

            def put(ring, vals):
                return ring.at[widx].set(
                    jnp.asarray(vals).astype(ring.dtype), mode="drop")

            nfin = jnp.sum(fin, dtype=jnp.int32)
            stream = stream._replace(
                res_job=put(stream.res_job, jid),
                res_time=put(stream.res_time, h["time"]),
                res_error=put(stream.res_error, h["error"]),
                res_snap_started=put(stream.res_snap_started,
                                     h["snap_started"]),
                res_snap_completed=put(stream.res_snap_completed,
                                       h["snap_completed"]),
                res_snap_failed=put(stream.res_snap_failed,
                                    h["snap_failed"]),
                res_fault_skew=put(stream.res_fault_skew, h["fault_skew"]),
                res_fault_events=put(stream.res_fault_events,
                                     h["fault_events"]),
                res_admit_step=put(stream.res_admit_step, state.admit_tick),
                res_tokens=put(stream.res_tokens, h["tokens"]),
                res_count=stream.res_count + nfin,
                jobs_done=stream.jobs_done + nfin)
            if serve:
                # serving-plane books (v9): a lane harvested at a stream
                # step past its job's absolute deadline is a miss; tenant
                # service counts scatter-add with the OOB-drop idiom so
                # idle lanes charge nothing
                jc = jnp.clip(jid, 0, jmax)
                tcap = stream.tenant_served.shape[0]
                late = stream.steps - deadline_of[jc]
                missed = fin & (late > 0)
                t_of = jnp.clip(tenant_of[jc], 0, tcap - 1)
                stream = stream._replace(
                    deadline_misses=stream.deadline_misses
                    + jnp.sum(missed, dtype=jnp.int32),
                    tenant_served=stream.tenant_served.at[
                        jnp.where(fin, t_of, tcap)].add(1, mode="drop"))
                if self._trace_on:
                    state = trace_append_lanes(
                        state, missed, EV_SERVE_MISS,
                        jnp.maximum(late, 0))
            # -- admit: reset freed slots, copy in per-job identities ------
            idle_lane = fin | ~has_job
            arank = jnp.cumsum(idle_lane.astype(jnp.int32)) - 1
            # gang admission = the static-batching baseline on the SAME
            # executable: refill only when every lane is idle, so whole
            # cohorts run and retire together (bench's fair comparison)
            gate = jnp.all(idle_lane) if gang else jnp.bool_(True)
            if serve:
                # serving admission: like the memoized arm, next_job walks
                # a host-maintained EXEC ORDER — but only up to ``limit``,
                # the dynamic count of positions the server has marked
                # admissible this step (arrived + quota-eligible, sorted by
                # the admission policy). The bound is a traced scalar, so
                # re-sorting the un-admitted suffix or extending the
                # admissible prefix never retraces.
                uexec = order.shape[0]
                avail = jnp.maximum(
                    jnp.minimum(jnp.asarray(limit, jnp.int32), uexec)
                    - stream.next_job, 0)
                admit = idle_lane & (arank < avail) & gate
                epos = jnp.clip(stream.next_job + arank, 0, uexec - 1)
                new_jid = jnp.where(admit, order[epos], -1)
            elif memo == "off":
                avail = jcount - stream.next_job
                admit = idle_lane & (arank < avail) & gate
                new_jid = stream.next_job + arank
            else:
                # memoized admission: next_job walks the deduplicated EXEC
                # ORDER (one representative lane per distinct digest, plus
                # the shadow re-executions), not the raw pool — the pool
                # row actually admitted is order[pos]. followers[pos]
                # counts the coalesced duplicates this representative also
                # serves; run_stream fans its summary out at finalize.
                uexec = order.shape[0]
                avail = uexec - stream.next_job
                admit = idle_lane & (arank < avail) & gate
                epos = jnp.clip(stream.next_job + arank, 0, uexec - 1)
                new_jid = jnp.where(admit, order[epos], -1)
            new_jidc = jnp.clip(new_jid, 0, jmax)
            reset = fin | admit
            if self._trace_on:
                # stamp the retiring job ids BEFORE the reset; the trace
                # ring is a lane artifact (reset_lanes carries it across
                # job boundaries), so the harvest event survives the wipe
                state = trace_append_lanes(state, fin, EV_LANE_HARVEST, jid)
            state = reset_lanes(state, reset, self.topo, self.config)

            def pick(p, old):
                # admitted -> the job's pooled row; reset-but-idle -> zeros;
                # otherwise untouched (reset_lanes leaves these leaves to us)
                old = jnp.asarray(old)
                extra = (1,) * (old.ndim - 1)
                ma = jnp.reshape(admit, admit.shape + extra)
                mr = jnp.reshape(reset, reset.shape + extra)
                return jnp.where(ma, jnp.asarray(p)[new_jidc],
                                 jnp.where(mr, jnp.zeros_like(old), old))

            state = state._replace(
                delay_state=jax.tree_util.tree_map(
                    pick, pool.delay_state, state.delay_state),
                fault_key=pick(pool.fault_key, state.fault_key),
                job_id=jnp.where(admit, new_jid, jnp.where(fin, -1, jid)),
                prog_cursor=jnp.where(admit, pool.job_start[new_jidc],
                                      jnp.where(reset, 0,
                                                state.prog_cursor)),
                admit_tick=jnp.where(admit, stream.steps,
                                     jnp.where(reset, 0, state.admit_tick)))
            if memo == "prefix":
                # speculative fork: an admitted lane whose exec position
                # the host plan mapped to a checkpoint bank row takes
                # the checkpointed state (fork_lanes overwrites every
                # semantic leaf INCLUDING delay_state — pick() just
                # copied the pool's fresh row, which would replay the
                # prefix's delay draws) and resumes at the divergence
                # cursor. fork_src is JOB-indexed (-1 = cold admission;
                # the duplicate-shadow jobs stay -1 by construction), so
                # a serving host re-sorting its un-admitted exec-order
                # suffix never invalidates the fork plan.
                fmax = bank.time.shape[0] - 1
                fsrc = fork_src[new_jidc]
                fdep = fork_depth[new_jidc]
                is_fork = admit & (fsrc >= 0)
                state = fork_lanes(state, is_fork, bank,
                                   jnp.clip(fsrc, 0, fmax))
                state = state._replace(
                    prog_cursor=jnp.where(
                        is_fork, pool.job_start[new_jidc] + fdep,
                        state.prog_cursor))
                stream = stream._replace(
                    forked_jobs=stream.forked_jobs
                    + jnp.sum(is_fork, dtype=jnp.int32),
                    fork_depth_sum=stream.fork_depth_sum
                    + jnp.sum(jnp.where(is_fork, fdep, 0),
                              dtype=jnp.int32))
                if self._trace_on:
                    state = trace_append_lanes(state, is_fork,
                                               EV_PREFIX_FORK, fdep)
            if self._trace_on:
                state = trace_append_lanes(state, admit, EV_LANE_ADMIT,
                                           new_jid)
            if self._trace_on and serve:
                # admit latency in stream steps (arrival -> admission),
                # stamped device-side so the flight recorder carries the
                # serving queue's wait distribution
                state = trace_append_lanes(
                    state, admit, EV_SERVE_ADMIT,
                    jnp.maximum(stream.steps - arrival_of[new_jidc], 0))
            if self._trace_on and memo != "off" and followers is not None:
                fcnt = followers[epos]
                state = trace_append_lanes(state, admit & (fcnt > 0),
                                           EV_LANE_COALESCE, fcnt)
            stream = stream._replace(
                next_job=stream.next_job + jnp.sum(admit, dtype=jnp.int32),
                refills=stream.refills + jnp.sum(admit & fin,
                                                 dtype=jnp.int32))
            # -- advance: every lane runs one pass of the stage machine ----
            # occupancy accounting first: a lane is live this step iff it
            # holds a job after admission; the denominator charges the
            # full batch whenever ANY lane is live (idle slots beside
            # running ones are exactly the waste being measured), and the
            # trailing all-idle step before the host notices completion
            # charges nothing
            live = jnp.sum(state.job_id >= 0, dtype=jnp.int32)
            stream = stream._replace(
                steps=stream.steps + 1,
                lane_steps_live=stream.lane_steps_live + live,
                lane_steps_total=stream.lane_steps_total + jnp.where(
                    live > 0, jnp.int32(self.batch), jnp.int32(0)))
            state = jax.vmap(lane_pass, in_axes=(0, None))(state, pool)
            return state, stream

        return step

    def _ff_step(self):
        """The jitted fast-forward credit: apply per-lane tick skips the
        host computed from a signature recurrence (_ff_host). The device
        re-checks eligibility as defense in depth: no armed fault
        adversary (its stream is time-indexed, models/faults._word), no
        message in flight in either queue engine (a message at a future
        rtime would be jumped over), no armed supervisor deadline (it
        compares against the clock), no error. For an eligible lane every
        remaining drain tick is provably pure ``time += 1``, so the jump
        lands on exactly the state a tick-by-tick run would reach."""
        fn = getattr(self, "_ff_jit", None)
        if fn is None:
            cfg = self.config

            def apply(state, stream, skips):
                eligible = ((state.fault_key == jnp.uint32(0))
                            & (state.error == 0)
                            & ~jnp.any(state.q_len > 0, axis=-1)
                            & ~jnp.any(state.m_pending, axis=(-2, -1)))
                if cfg.snapshot_timeout > 0:
                    eligible = eligible & ~jnp.any(
                        state.snap_deadline > 0, axis=-1)
                skip = jnp.where(eligible, skips, 0).astype(jnp.int32)
                state = state._replace(time=state.time + skip)
                if self._trace_on:
                    state = trace_append_lanes(state, skip > 0,
                                               EV_MEMO_HIT, skip)
                stream = stream._replace(
                    ff_skipped_ticks=stream.ff_skipped_ticks
                    + jnp.sum(skip, dtype=jnp.int32))
                return state, stream

            fn = jax.jit(apply, donate_argnums=(0, 1))
            self._ff_jit = fn
        return fn

    def _ff_host(self, state, stream, pool, seen):
        """Host half of transition fast-forwarding (memo='full'): watch
        each lane's (job, cursor, signature) across steps. A recurrence
        at the SAME drain cursor with time strictly advanced means the
        lane's semantic state is invariant under the tick — the
        generalization of TickKernel._run_ticks' quiescence fast-forward
        from "ring empty" to "state fixed point" — so the remaining wait
        to its tick limit is credited in one jump: whole multiples of the
        observed period, stopping short of the limit so the
        ERR_TICK_LIMIT edge replays tick-exactly. ``seen`` maps lane ->
        (key, time at last sighting) and persists across steps; any
        cursor/job change resets the watch."""
        jid, cur, sig, tnow = guarded_get(
            self.guards, "memo-fastforward",
            (state.job_id, state.prog_cursor, state.sig, state.time))
        jend = np.asarray(pool.job_end)
        jlim = np.asarray(pool.job_limit)
        skips = np.zeros(self.batch, np.int32)
        fire = False
        for lane in range(self.batch):
            j = int(jid[lane])
            # only the drain stage can cycle (script rows and the flush
            # are fixed-length), so anything else resets the watch
            if j < 0 or int(cur[lane]) != int(jend[j]):
                seen.pop(lane, None)
                continue
            key = (j, int(cur[lane]), int(sig[lane]))
            t = int(tnow[lane])
            prev = seen.get(lane)
            if prev is not None and prev[0] == key and t > prev[1]:
                dt = t - prev[1]
                k = (int(jlim[j]) - t - 1) // dt
                if k > 0:
                    skips[lane] = k * dt
                    fire = True
            seen[lane] = (key, t)
        if fire:
            state, stream = self._ff_step()(state, stream,
                                            jnp.asarray(skips))
        return state, stream

    def _summary_cache(self) -> SummaryCache:
        """The runner's persistent summary cache, opened with its LRU
        capacity bounds (constructor knobs; 0 = unbounded)."""
        return SummaryCache(self.memo_cache_path,
                            max_entries=self.memo_cache_entries,
                            max_bytes=self.memo_cache_bytes)

    def _memo_plan(self, pool: JobPool, shadow_every: Optional[int]) -> dict:
        """Host-side admission plan for a memoized run: classify every
        pool job by digest into leader (executes on a lane), coalesced
        follower (served from its leader's harvest) or persistent-cache
        hit (served without any lane at all), and pick the shadow
        re-executions (every ``shadow_every``-th served job also runs
        solo for the bit-exactness audit). Deterministic for a given
        (pool, cache file) — and the cache file only changes at the END
        of a run (SummaryCache.flush), so a killed run re-plans
        identically on resume and the checkpointed stream carry stays
        consistent with the exec order."""
        digests = [bytes(bytearray(np.asarray(pool.digest[j], np.uint8)
                                   .tolist())).hex()
                   for j in range(pool.num_jobs)]
        if pool.num_jobs and all(d == "0" * 64 for d in digests):
            raise ValueError(
                "memo != 'off' needs a content-addressed pool — pack_jobs "
                "on a memo-enabled runner (or content_keys=True) stamps "
                "the job digests")
        cache = self._summary_cache()
        se = MEMO_SHADOW_EVERY if shadow_every is None else int(shadow_every)
        leader: dict = {}       # digest -> ("exec", job) | ("cache", summary)
        exec_jobs: List[int] = []   # pool indices in admission order
        fcounts: dict = {}          # exec job -> coalesced follower count
        served: List[tuple] = []    # (job, "cache"|"coalesce", digest, ref)
        shadows: set = set()
        nserved = 0

        def maybe_shadow(j):
            nonlocal nserved
            nserved += 1
            if se and (nserved - 1) % se == 0:
                shadows.add(j)
                exec_jobs.append(j)
                fcounts.setdefault(j, 0)

        for j, dg in enumerate(digests):
            led = leader.get(dg)
            if led is None:
                hit = cache.get(dg)
                if hit is not None:
                    leader[dg] = ("cache", dict(hit))
                    served.append((j, "cache", dg, dict(hit)))
                    maybe_shadow(j)
                else:
                    leader[dg] = ("exec", j)
                    exec_jobs.append(j)
                    fcounts[j] = 0
            else:
                kind, ref = led
                if kind == "exec":
                    fcounts[ref] += 1
                    served.append((j, "coalesce", dg, ref))
                else:
                    served.append((j, "cache", dg, dict(ref)))
                maybe_shadow(j)
        return {"digests": digests, "cache": cache, "exec": exec_jobs,
                "follower_counts": [fcounts[e] for e in exec_jobs],
                "served": served, "shadows": shadows}

    def _memo_finalize(self, state, stream, plan: dict):
        """After the device loop drains the exec order: write executed
        leaders' summaries back to the cache (atomic flush), materialize
        every served row (follower / cache hit) with provenance stamps,
        run the shadow audit, and set the host-side memo counters."""
        ring = {r["job"]: r for r in _ring_rows(stream)}
        cache = plan["cache"]
        digests = plan["digests"]

        def summary_of(row):
            return {k: v for k, v in row.items()
                    if k not in ("job", "admit_step")}

        for e in plan["exec"]:
            r = ring.get(e)
            if r is not None:
                cache.put(digests[e], summary_of(r))
        nshadow = 0
        for j, src, dg, ref in plan["served"]:
            if src == "cache":
                summ = dict(ref)
            else:
                r = ring.get(ref)
                if r is None:
                    # leader evicted from an undersized results ring — the
                    # follower cannot be served (summarize_stream reports
                    # the eviction; the default capacity never evicts)
                    continue
                summ = summary_of(r)
            if j in plan["shadows"]:
                solo = ring.get(j)
                if solo is not None:
                    nshadow += 1
                    if summary_of(solo) != summ:
                        raise MemoCacheError(
                            f"shadow re-execution of job {j} (digest {dg}) "
                            f"disagrees with its served summary — the "
                            f"memoized result is not bit-exact; refusing "
                            f"to serve it")
            row = dict(summ)
            row["job"] = j
            row["admit_step"] = -1        # never held a lane
            row["digest"] = dg            # provenance: producer's address
            row["served_from"] = src
            self._memo_rows[j] = row
        cache.flush()
        self._memo_cache_stats = {"cache_evictions": cache.evictions,
                                  "cache_evicted_bytes": cache.evicted_bytes}
        ncache = sum(1 for it in plan["served"] if it[1] == "cache")
        ncoal = sum(1 for it in plan["served"] if it[1] == "coalesce")
        stream = stream._replace(cache_hits=np.int32(ncache),
                                 coalesced_jobs=np.int32(ncoal),
                                 shadow_checks=np.int32(nshadow))
        return state, stream

    # -- memo="prefix": speculative fork from checkpointed prefixes -------

    def _prefix_cache(self) -> PrefixCache:
        """The prefix-checkpoint store this run plans against. File-backed
        (``prefix_cache`` knob): a FRESH handle per run, so checkpoints
        other processes flushed are visible to the next plan. No file:
        one persistent in-memory handle per runner — repeats of the same
        pool (bench warmup -> timed reps) fork from the checkpoints the
        first run produced."""
        if self.prefix_cache_path is not None:
            return PrefixCache(self.prefix_cache_path,
                               max_entries=self.prefix_cache_entries,
                               max_bytes=self.prefix_cache_bytes)
        if self._prefix_cache_handle is None:
            self._prefix_cache_handle = PrefixCache(
                None, max_entries=self.prefix_cache_entries,
                max_bytes=self.prefix_cache_bytes)
        return self._prefix_cache_handle

    def _prefix_produce_step(self, nsub: int):
        """Jitted prefix producer: vmapped cold replay of each lane's
        script rows up to a per-lane stop cursor — the streaming step's
        script stage verbatim (same pooled-row addressing, same
        _apply_phase composition), with the stage test replaced by the
        stop cursor because a prefix never enters its drain. Keyed by
        scan length; _prefix_produce rounds chunks up to the next power
        of two, bounding compiles to O(log max prefix depth)."""
        fn = self._produce_jits.get(nsub)
        if fn is None:
            def body(s, pool, stop):
                def script(u):
                    c = jnp.clip(u.prog_cursor, 0,
                                 pool.kind.shape[0] - 1)
                    ops = (pool.kind[c], pool.arg0[c], pool.arg1[c],
                           pool.do_tick[c])
                    u = self._apply_phase(u, ops)
                    return u._replace(prog_cursor=u.prog_cursor + 1)

                def sub(u, _):
                    return lax.cond(u.prog_cursor < stop, script,
                                    lambda v: v, u), None

                s, _ = lax.scan(sub, s, None, length=nsub)
                return s

            fn = jax.jit(jax.vmap(body, in_axes=(0, None, 0)),
                         donate_argnums=0)
            self._produce_jits[nsub] = fn
        return fn

    def _prefix_produce(self, pool: JobPool, pool_dev, cands,
                        pcache: PrefixCache) -> None:
        """Run every candidate prefix cold (one producer dispatch per
        B-sized chunk) and checkpoint the boundary states. A producer
        lane is EXACTLY a streaming lane at admission — fresh init
        template + the job's pooled identity rows (fault_key + delay
        row) + the job's start cursor — so the captured state is
        bit-identical to what a cold stream lane holds at the boundary
        cursor. Captured leaves: every DenseState field outside
        _PREFIX_KEEP_LEAVES (the admission-owned identity/trace leaves
        fork_lanes preserves), with the delay pytree flattened row-wise
        (the fork bank rebuilds it with the template treedef).
        ``cands``: (digest_hex, job, depth) triples."""
        if not cands:
            return
        B = self.batch
        starts = np.asarray(pool.job_start)
        capture = [f for f in DenseState._fields
                   if f not in _PREFIX_KEEP_LEAVES
                   and f != "delay_state"]
        for lo in range(0, len(cands), B):
            chunk = cands[lo:lo + B]
            pad = B - len(chunk)
            idx = np.asarray([j for _, j, _ in chunk]
                             + [chunk[-1][1]] * pad, np.int64)
            deps = np.asarray([d for _, _, d in chunk] + [0] * pad,
                              np.int32)
            st = self.init_batch()
            st = st._replace(
                delay_state=jax.tree_util.tree_map(
                    lambda p: np.ascontiguousarray(np.asarray(p)[idx]),
                    pool.delay_state),
                fault_key=np.asarray(pool.fault_key)[idx].astype(
                    np.asarray(st.fault_key).dtype),
                prog_cursor=starts[idx].astype(np.int32),
                job_id=idx.astype(np.int32))
            stops = (starts[idx] + deps).astype(np.int32)
            nsub = 1 << (max(1, int(deps.max())) - 1).bit_length()
            out = jax.device_get(self._prefix_produce_step(nsub)(
                jax.tree_util.tree_map(jnp.asarray, st), pool_dev,
                jnp.asarray(stops)))
            ds_leaves = [np.asarray(x) for x in
                         jax.tree_util.tree_leaves(out.delay_state)]
            for i, (dg, _j, d) in enumerate(chunk):
                leaves = {f: np.asarray(getattr(out, f))[i]
                          for f in capture}
                leaves["delay_state"] = tuple(x[i] for x in ds_leaves)
                pcache.put_ckpt(dg, int(d), leaves)

    def _prefix_plan(self, pool: JobPool, pool_dev, plan: dict,
                     shadow_every: Optional[int]) -> dict:
        """Host-side speculative-fork plan over _memo_plan's exec order:
        for each executing leader, find the DEEPEST phase boundary whose
        chain digest either already has a checkpoint (fork free) or is
        hot enough to produce one now (>= 2 leaders cross it this run,
        or a previous run bumped its seen counter); run the producer for
        the chosen boundaries; decode every fork source through the
        cache codec (in-run and cross-run forks share one decode path)
        into a power-of-two bank; and stamp fork_src/fork_depth per JOB
        (-1 = cold admission; _memo_plan's exact-duplicate shadows stay
        cold by construction — they are follower job ids, never the
        leader's — so the memo shadow audit also cross-checks forked
        leaders). Every checkpoint-less boundary
        walked gets its seen counter bumped, so the NEXT run — or the
        next request on a serving fleet's shared cache — checkpoints
        what this one only crossed.

        Deterministic in (pool, plan, cache state at entry); and because
        a fork is bit-exact, a cache file advanced by another writer
        between a checkpoint save and its resume only changes WHERE
        lanes fork, never what any job computes."""
        if pool.prefix_digest is None:
            raise ValueError(
                "memo='prefix' needs a prefix-chained pool — pack_jobs on "
                "the prefix runner (content_keys on) stamps the "
                "phase-boundary digest chain")
        chains = np.asarray(pool.prefix_digest)
        starts = np.asarray(pool.job_start)
        ends = np.asarray(pool.job_end)
        pcache = self._prefix_cache()
        exec_jobs = plan["exec"]
        shadows = plan["shadows"]
        leaders = [j for j in exec_jobs if j not in shadows]

        def chex(j, d):
            return bytes(bytearray(
                chains[int(starts[j]) + d - 1].tolist())).hex()

        counts: dict = {}
        for j in leaders:
            for d in range(1, int(ends[j] - starts[j]) + 1):
                dg = chex(j, d)
                counts[dg] = counts.get(dg, 0) + 1
        fork_of: dict = {}    # leader job -> (digest_hex, depth)
        produce: dict = {}    # digest_hex -> (job, depth) to produce
        for j in leaders:
            for d in range(int(ends[j] - starts[j]), 0, -1):
                dg = chex(j, d)
                if pcache.has_ckpt(dg) or dg in produce:
                    fork_of[j] = (dg, d)
                    break
                if counts.get(dg, 0) >= 2 or pcache.seen(dg) >= 1:
                    # the first leader through seeds the checkpoint and
                    # forks from it itself — the prefix runs ONCE (in
                    # the producer) either way, so this is never slower
                    # than cold, and every later leader forks free
                    produce[dg] = (j, d)
                    fork_of[j] = (dg, d)
                    break
        bumped: set = set()
        for j in leaders:
            for d in range(1, int(ends[j] - starts[j]) + 1):
                dg = chex(j, d)
                if dg not in bumped and dg not in produce \
                        and not pcache.has_ckpt(dg):
                    bumped.add(dg)
                    pcache.bump_seen(dg, d)
        self._prefix_produce(
            pool, pool_dev,
            [(dg, j, d) for dg, (j, d) in produce.items()], pcache)
        lane0 = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[0].copy(), self.init_batch())
        ds_treedef = jax.tree_util.tree_structure(lane0.delay_state)
        rows: List[Any] = []
        bank_index: dict = {}
        fork_src = np.full(pool.num_jobs, -1, np.int32)
        fork_depth = np.zeros(pool.num_jobs, np.int32)
        for j in exec_jobs:
            if j in shadows or j not in fork_of:
                continue
            dg, d = fork_of[j]
            ri = bank_index.get(dg)
            if ri is None:
                got = pcache.get_ckpt(dg)
                if got is None:
                    # produced-then-evicted under a tight byte cap —
                    # this leader falls back to cold admission
                    del fork_of[j]
                    continue
                depth, leaves = got
                if int(depth) != int(d):
                    raise PrefixCacheError(
                        f"prefix cache entry {dg[:12]}… claims depth "
                        f"{int(depth)} but the pool's chain puts this "
                        f"digest at depth {int(d)} — refusing the fork")
                ds = jax.tree_util.tree_unflatten(
                    ds_treedef, list(leaves.pop("delay_state")))
                ri = len(rows)
                rows.append(lane0._replace(delay_state=ds, **leaves))
                bank_index[dg] = ri
            fork_src[j] = ri
            fork_depth[j] = np.int32(d)
        se = (MEMO_SHADOW_EVERY if shadow_every is None
              else int(shadow_every))
        forked = [j for j in exec_jobs
                  if j in fork_of and j not in shadows]
        fork_shadows = ([j for k, j in enumerate(forked)
                         if k % se == 0] if se else [])
        self._fork_depths = [int(fork_of[j][1]) for j in forked]
        nbank = 1 << ((len(rows) - 1).bit_length() if rows else 0)
        while len(rows) < nbank:
            rows.append(lane0)
        bank = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *rows)
        return {"cache": pcache, "fork_of": fork_of,
                "fork_shadows": fork_shadows,
                "produced": sorted(produce),
                "bank_dev": jax.tree_util.tree_map(jnp.asarray, bank),
                "fork_src_dev": jnp.asarray(fork_src),
                "fork_depth_dev": jnp.asarray(fork_depth)}

    def _run_cold_jobs(self, pool: JobPool, js, stretch: int,
                       drain_chunk: int) -> dict:
        """The audited jobs re-executed cold, together — the fork shadow
        audit's reference: a sub-pool of exactly those jobs (the FULL
        pooled phase table is kept so cursor addressing is unchanged)
        driven through the memo-off streaming step in ONE multi-lane
        run. Per-job results are lane-independent (admission rebuilds a
        lane entirely from the job's pool identity rows), so batching
        the shadows is bit-identical to re-running each alone while
        costing ~1/B of the device steps — without it the audit would
        hand back most of the fork plane's win. Returns {job: ring row}
        keyed by ORIGINAL pool job index."""
        idx = np.asarray([int(j) for j in js], np.int64)
        sub = pool._replace(
            job_start=np.ascontiguousarray(np.asarray(pool.job_start)[idx]),
            job_end=np.ascontiguousarray(np.asarray(pool.job_end)[idx]),
            job_limit=np.ascontiguousarray(
                np.asarray(pool.job_limit)[idx]),
            fault_key=np.ascontiguousarray(
                np.asarray(pool.fault_key)[idx]),
            digest=np.zeros((len(idx), 32), np.uint8),
            delay_state=jax.tree_util.tree_map(
                lambda x: np.ascontiguousarray(np.asarray(x)[idx]),
                pool.delay_state),
            prefix_digest=None)
        step = self._stream_step(stretch, drain_chunk, False,
                                 serve=False, memo="off")
        sub_dev = jax.tree_util.tree_map(jnp.asarray, sub)
        state = self.init_batch()
        stream = self.init_stream(sub)
        for _ in range(1_000_000):
            state, stream = step(state, stream, sub_dev)
            if int(jax.device_get(stream.jobs_done)) >= len(idx):
                break
        else:
            raise RuntimeError(
                f"cold re-execution of jobs {list(js)} failed to retire")
        return {int(idx[r["job"]]): dict(r, job=int(idx[r["job"]]))
                for r in _ring_rows(stream)}

    def _prefix_finalize(self, state, stream, plan: dict, pplan: dict,
                         pool: JobPool, stretch: int, drain_chunk: int):
        """After the device loop and _memo_finalize: run the fork shadow
        audit (the chosen forked leaders re-executed cold in one batched
        run, byte-compared against their forked harvests), stamp fork
        provenance
        on every forked leader's results row, flush the prefix cache and
        set the host-side prefix books. prefix_hits (host count of
        planned forks) == forked_jobs (device-accumulated at admission)
        is the books-balance invariant the chaos drill checks."""
        ring = {r["job"]: r for r in _ring_rows(stream)}
        digests = plan["digests"]

        def summary_of(row):
            return {k: v for k, v in row.items()
                    if k not in ("job", "admit_step")}

        audited = [j for j in pplan["fork_shadows"] if j in ring]
        cold_rows = (self._run_cold_jobs(pool, audited, stretch,
                                         drain_chunk) if audited else {})
        nshadow = 0
        for j in audited:
            dg, d = pplan["fork_of"][j]
            cold = cold_rows.get(j)
            nshadow += 1
            if cold is None or summary_of(cold) != summary_of(ring[j]):
                raise PrefixCacheError(
                    f"fork shadow: job {j}, forked at depth {d} from "
                    f"prefix {dg[:12]}…, disagrees with its cold "
                    f"re-execution — the checkpointed prefix is not "
                    f"bit-exact; refusing to serve forks from it")
        for j, (dg, d) in pplan["fork_of"].items():
            r = ring.get(j)
            if r is None:
                continue
            row = dict(r)
            row["digest"] = digests[j]
            row["served_from"] = f"prefix:{d}"
            self._memo_rows[j] = row
        pcache = pplan["cache"]
        pcache.flush()
        self._prefix_stats = {
            "prefix_evictions": pcache.evictions,
            "prefix_evicted_bytes": pcache.evicted_bytes,
            "prefix_store_entries": len(pcache)}
        stream = stream._replace(
            prefix_hits=np.int32(len(pplan["fork_of"])),
            shadow_checks=stream.shadow_checks + np.int32(nshadow))
        return state, stream

    def run_stream(self, jobs, *, stretch: int = 4, drain_chunk: int = 32,
                   admission: str = "stream",
                   results_capacity: Optional[int] = None,
                   state: Optional[DenseState] = None,
                   stream: Optional[StreamState] = None,
                   max_steps: int = 1_000_000, checkpoint: Optional[str] = None,
                   checkpoint_every: int = 0,
                   kill_after_saves: Optional[int] = None,
                   shadow_every: Optional[int] = None):
        """Drive a queue of jobs through the B lane slots; returns the final
        ``(state, stream)``. ``jobs``: a JobPool (pack_jobs) or a list of
        event lists / ScriptOps. ``admission``: 'stream' (default) refills
        slots the moment they retire; 'gang' only refills when EVERY slot
        is idle — the static-batching baseline on the same executable.

        Progress per host iteration is one jitted step (harvest + admit +
        ``stretch`` script phases, one ``drain_chunk``-tick drain slice
        and one flush pass per lane, donated carry); the only device reads
        are the termination scalars. Every running lane provably advances
        each step (script rows and the flush are fixed-length; the drain
        budget is finite), so the queue terminates; ``max_steps`` merely
        guards against misconfiguration.

        Checkpointing: with ``checkpoint`` + ``checkpoint_every`` k, every
        k-th step atomically saves the combined ``(state, stream)`` pytree
        (utils/checkpoint.save_state — format v8). Resume by loading with
        ``like=(runner.init_batch(), runner.init_stream(pool))`` and
        passing ``state=``/``stream=`` back in; the continuation is
        bit-exact because admission order, per-job streams and the results
        ring all live in the saved carry (and, under memo, the admission
        plan is a pure function of (pool, cache file), which only changes
        at the END of a completed run). ``kill_after_saves``: stop right
        after that many saves (preemption drills; tests).

        Memoization (``memo`` runner knob): with memo != 'off' only one
        representative per distinct job digest is admitted; duplicate
        followers and persistent-cache hits are served their
        representative's summary at the end (stream_results rows carry
        ``digest`` + ``served_from`` provenance). With memo == 'full',
        lanes whose state signature recurs mid-drain are fast-forwarded
        to their tick limit (_ff_host). With memo == 'prefix', executing
        leaders additionally fork from the deepest checkpointed phase
        boundary their digest chain shares with the prefix cache
        (_prefix_plan), skipping the shared prefix entirely; forked rows
        carry ``served_from="prefix:<depth>"``. ``shadow_every``
        overrides MEMO_SHADOW_EVERY for BOTH bit-exactness audits — the
        duplicate shadow lanes and the cold solo re-runs of forked
        leaders (0 disables)."""
        from chandy_lamport_tpu.utils.checkpoint import save_state

        if admission not in ("stream", "gang"):
            raise ValueError(f"unknown admission mode {admission!r}")
        if stretch < 1 or drain_chunk < 1:
            raise ValueError("stretch and drain_chunk must be >= 1")
        pool = jobs if isinstance(jobs, JobPool) else self.pack_jobs(jobs)
        jcount = pool.num_jobs
        memo = self.memo
        self._memo_rows = {}
        self._fork_depths = []
        self._prefix_stats = {"prefix_evictions": 0,
                              "prefix_evicted_bytes": 0,
                              "prefix_store_entries": 0}
        if memo == "off":
            plan = order_dev = followers_dev = None
            target = jcount
        else:
            plan = self._memo_plan(pool, shadow_every)
            target = len(plan["exec"])
            order_dev = jnp.asarray(np.asarray(plan["exec"], np.int32))
            followers_dev = jnp.asarray(
                np.asarray(plan["follower_counts"], np.int32))
        if state is None:
            state = self.init_batch()
        if stream is None:
            stream = self.init_stream(pool, results_capacity)
        step = self._stream_step(stretch, drain_chunk, admission == "gang")
        pool_dev = jax.tree_util.tree_map(jnp.asarray, pool)
        pplan = None
        if memo == "prefix":
            # fork plan + producer dispatches run BEFORE the armed loop:
            # planning is host work and the producer is ordinary
            # (unguarded) device traffic
            pplan = self._prefix_plan(pool, pool_dev, plan, shadow_every)
        # fast-forward needs signature recurrence to imply a frozen lane;
        # periodic re-initiation is clock-driven, so it is fenced off here
        # (the armed-deadline fence in _ff_step covers snapshot_timeout)
        ff = memo == "full" and self.config.snapshot_every == 0
        ff_seen: dict = {}
        guards = self.guards
        # the carry enters the device through an explicit named bulk
        # upload (init_batch builds host numpy leaves; the armed loop
        # forbids the implicit h2d the first dispatch used to do)
        state, stream = guarded_put(guards, "stream-carry-upload",
                                    (state, stream))
        saves = 0
        done = int(guarded_get(guards, "stream-termination-scalars",
                               stream.jobs_done))
        if done < target:
            # the steady-state device loop runs armed when guards are on:
            # implicit transfers raise, compiles are booked as retraces,
            # and the only host syncs are the named sites below
            with armed(guards):
                for _ in range(int(max_steps)):
                    if memo == "off":
                        state, stream = step(state, stream, pool_dev)
                    elif memo == "prefix":
                        state, stream = step(
                            state, stream, pool_dev, order_dev,
                            followers_dev, None, None, None, None,
                            pplan["bank_dev"], pplan["fork_src_dev"],
                            pplan["fork_depth_dev"])
                    else:
                        state, stream = step(state, stream, pool_dev,
                                             order_dev, followers_dev)
                    if ff:
                        state, stream = self._ff_host(state, stream, pool,
                                                      ff_seen)
                    done, steps_now = (int(x) for x in guarded_get(
                        guards, "stream-termination-scalars",
                        (stream.jobs_done, stream.steps)))
                    if (checkpoint and checkpoint_every
                            and steps_now % int(checkpoint_every) == 0):
                        # save_state numpy-ifies the whole carry; an
                        # intentional bulk transfer, booked by site
                        with relaxed_site(guards, "checkpoint-save"):
                            save_state(checkpoint, (state, stream),
                                       meta={"stream_steps": steps_now,
                                             "jobs_done": done})
                        saves += 1
                        if kill_after_saves is not None \
                                and saves >= int(kill_after_saves):
                            return state, stream
                    if done >= target:
                        break
                else:
                    raise RuntimeError(
                        f"run_stream: {target - done} of {target} executed "
                        f"jobs unfinished after {max_steps} steps — raise "
                        f"max_steps (or a lane is stuck, which the stage "
                        f"machine should make impossible)")
        if memo != "off":
            state, stream = self._memo_finalize(state, stream, plan)
        if memo == "prefix":
            state, stream = self._prefix_finalize(
                state, stream, plan, pplan, pool, stretch, drain_chunk)
        return state, stream

    def stream_results(self, stream: StreamState) -> List[dict]:
        """The results as host-side per-job rows, sorted by job id
        (completion order is admission-dependent; the sort makes
        stream-vs-static comparison direct): the harvested ring overlaid
        with the rows the memo plane served without execution (those
        carry ``digest`` + ``served_from`` provenance keys and
        ``admit_step`` -1). A ring smaller than the executed-job count
        keeps only the newest rows — the oldest ``res_count - capacity``
        are evicted; summarize_stream reports the count."""
        rows = {r["job"]: r for r in _ring_rows(stream)}
        rows.update(getattr(self, "_memo_rows", None) or {})
        return sorted(rows.values(), key=lambda r: r["job"])

    def summarize_stream(self, stream: StreamState) -> dict:
        """Host-side stream counters (utils/metrics.stream_counters:
        occupancy, refills, straggler-wasted substeps) + results-ring
        accounting."""
        from chandy_lamport_tpu.utils.metrics import stream_counters

        host = jax.device_get(stream)
        d = stream_counters(host)
        rcap = int(np.shape(host.res_job)[0])
        d["results_capacity"] = rcap
        d["results_evicted"] = max(0, int(host.res_count) - rcap)
        # LRU eviction books of the most recent memoized run's cache
        d.update(getattr(self, "_memo_cache_stats", None)
                 or {"cache_evictions": 0, "cache_evicted_bytes": 0})
        # prefix-plane books (memo="prefix"): checkpoint-store LRU
        # pressure + resident entry count after the last flush
        d.update(getattr(self, "_prefix_stats", None)
                 or {"prefix_evictions": 0, "prefix_evicted_bytes": 0,
                     "prefix_store_entries": 0})
        return d

    # -- aggregate metrics (jit-friendly reductions; under a sharded batch
    #    axis these lower to XLA collectives over ICI) --------------------

    @staticmethod
    def summarize(state: DenseState, stream: Optional[StreamState] = None
                  ) -> dict:
        from chandy_lamport_tpu.core.state import decode_error_bits
        from chandy_lamport_tpu.utils.metrics import (
            or_reduce,
            snapshot_lifecycle,
            straggler_waste,
        )

        bits = int(or_reduce(state.error))
        fc = jnp.sum(state.fault_counts, axis=0)
        tr_rec, tr_drop = trace_counts(state)
        out = {
            "instances": int(state.time.shape[0]),
            "total_ticks": int(jnp.sum(state.time)),
            "max_time": int(jnp.max(state.time)),
            # fraction of the batch's lane-tick budget burned waiting for
            # the slowest lane (utils/metrics.straggler_waste) — the hole
            # run_stream's continuous admission exists to reclaim
            "straggler_waste": round(float(straggler_waste(state)), 4),
            "error_lanes": int(jnp.sum(state.error != 0)),
            # which bits fired across ALL lanes (int(max) would drop bits);
            # the short names ride along so no consumer has to decode the
            # raw int by hand — the round-2 bench zeroed the perf axis
            # without ever reporting WHICH flag fired
            "error_bits": bits,
            "errors_decoded": decode_error_bits(bits),
            "snapshots_started": int(jnp.sum(state.started)),
            "snapshots_completed": int(jnp.sum(
                jnp.sum(state.started & (state.completed >= state.has_local.shape[-1]),
                        axis=-1))),
            "total_tokens_resident": int(jnp.sum(state.tokens)),
            # adversary books (models/faults.py): events per class + the
            # injected token delta conservation_delta subtracts
            "fault_events": {"drops": int(fc[0]), "dups": int(fc[1]),
                             "jitters": int(fc[2]), "crashes": int(fc[3]),
                             "marker_drops": int(fc[4]),
                             "marker_dups": int(fc[5]),
                             "marker_jitters": int(fc[6])},
            "fault_skew": int(jnp.sum(state.fault_skew)),
            # flight-recorder books (utils/tracing.trace_counts): events
            # resident in the rings + events overwritten by wraparound —
            # the overflow is surfaced here, never silent
            "trace_events": int(tr_rec),
            "trace_dropped": int(tr_drop),
            # supervisor lifecycle (utils/metrics.snapshot_lifecycle):
            # initiated / completed / retried / failed / aborted /
            # stale_markers + recovery-line age, summed over lanes
            "snapshot_lifecycle": {
                k: int(v) for k, v in snapshot_lifecycle(
                    state, state.has_local.shape[-1]).items()},
        }
        if stream is not None:
            # memo-plane accounting rides along when the caller passes the
            # stream carry (utils/metrics.stream_counters does the math)
            from chandy_lamport_tpu.utils.metrics import stream_counters

            sc = stream_counters(jax.device_get(stream))
            out["memo"] = {k: sc[k] for k in (
                "cache_hits", "coalesced_jobs", "ff_skipped_ticks",
                "shadow_checks", "memo_hit_rate")}
            # serving-plane books (v9 leaves): the per-tenant fairness/
            # quota accounting and deadline misses ride along the same way
            out["serve"] = {k: sc[k] for k in (
                "deadline_misses", "tenant_served", "tenant_quota")}
        return out
