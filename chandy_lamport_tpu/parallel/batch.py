"""Batched execution: vmap over independent simulation instances.

This is the framework's data-parallel axis (SURVEY.md §2.5): the reference
simulates ONE system per process; here a whole event script — sends,
snapshot initiations, ticks, drain, flush — compiles into a single XLA
program executed over B instances in lockstep by ``vmap``. Per-instance
divergence (different delay streams → different delivery schedules →
different drain lengths) is handled by the batching rules of
``lax.while_loop``/``lax.cond``: lanes that finish early idle until the
slowest lane converges.

Script compilation (``compile_events``): the reference executes events
imperatively between ticks (test_common.go:79-140). Here the script becomes
dense op tensors — ``kind/arg0/arg1 [T, K]`` where each phase t carries up to
K ops (0=nop, 1=send(edge, amount), 2=snapshot(node)) followed by exactly one
tick — and the whole run is ``lax.scan`` over phases. Op order within a phase
is preserved (script order = PRNG draw order = bit-exactness rule R4).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.spec import (
    Event,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.core.state import (
    DenseState,
    DenseTopology,
    ERR_CONSERVATION,
    init_state,
)
from chandy_lamport_tpu.ops.delay_jax import JaxDelay
from chandy_lamport_tpu.ops.tick import TickKernel
from chandy_lamport_tpu.utils.fixtures import TopologySpec
from chandy_lamport_tpu.utils.layouts import (
    HAVE_LAYOUTS,
    array_format,
    auto_format,
    format_layout,
    input_formats,
)

OP_NOP, OP_SEND, OP_SNAPSHOT = 0, 1, 2


def _formats_match(tree, formats) -> bool:
    """True iff every leaf's live device format already equals the compiled
    program's expectation (states built by ``init_batch_device(formats=...)``
    qualify) — then the relayout dispatch can be skipped entirely."""
    xs = jax.tree_util.tree_leaves(tree)
    # a DCE'd input's format is None (stages._input_layouts_flat) — keep it
    # as a leaf so the two flattenings stay aligned; the executable never
    # reads a DCE'd input, so None matches anything
    fs = jax.tree_util.tree_leaves(formats, is_leaf=lambda v: v is None)
    if len(xs) != len(fs):
        return False
    for x, f in zip(xs, fs):
        if f is None:
            continue
        cur = array_format(x)
        if (cur is None or format_layout(cur) != format_layout(f)
                or cur.sharding != f.sharding):
            return False
    return True


class ScriptOps(NamedTuple):
    """A compiled event script: T phases of up to K ops, each phase followed
    by ``do_tick`` ticks (0 only for a synthetic trailing phase). Multi-tick
    stretches are carried as COUNTS and executed by the runner's fused
    multi-tick dispatch (TickKernel._run_ticks on the exact path, with its
    quiescence fast-forward) instead of the former one-empty-phase-per-tick
    expansion — a ``tick 200`` event costs one phase, not 200."""

    kind: Any      # i32 [T, K]
    arg0: Any      # i32 [T, K]  edge index (send) | node index (snapshot)
    arg1: Any      # i32 [T, K]  token amount (send)
    do_tick: Any   # i32 [T]     ticks after the phase's ops

    @property
    def num_phases(self) -> int:
        return self.kind.shape[0]


def compile_events(topo: DenseTopology, events: List[Event]) -> ScriptOps:
    """Events -> dense op tensors. Each ``tick n`` closes the current phase
    with a tick count of n (consecutive tick events merge into one phase);
    trailing non-tick events get a final synthetic phase with ``do_tick=0``,
    so no-drain runs stop exactly where the single-instance backend does
    (injected but unticked) while drained runs are unaffected (the drain
    loop supplies the tick, SURVEY.md §3.5)."""
    phases: List[Tuple[List[tuple], int]] = []
    cur: List[tuple] = []
    for ev in events:
        if isinstance(ev, PassTokenEvent):
            src, dest = topo.index[ev.src], topo.index[ev.dest]
            e = topo.edge_index.get((src, dest))
            if e is None:
                raise ValueError(f"no link {ev.src} -> {ev.dest}")
            cur.append((OP_SEND, e, ev.tokens))
        elif isinstance(ev, SnapshotEvent):
            cur.append((OP_SNAPSHOT, topo.index[ev.node_id], 0))
        elif isinstance(ev, TickEvent):
            if not cur and phases and phases[-1][1]:
                phases[-1] = (phases[-1][0], phases[-1][1] + ev.n)
            else:
                phases.append((cur, ev.n))
                cur = []
        else:
            raise TypeError(f"unknown event: {ev!r}")
    if cur:  # trailing non-tick events: a synthetic unticked final phase
        phases.append((cur, 0))
    if not phases:  # empty script: one bare tick (the pre-count behavior)
        phases.append(([], 1))
    t = len(phases)
    k = max((len(p) for p, _ in phases), default=0) or 1
    kind = np.zeros((t, k), np.int32)
    arg0 = np.zeros((t, k), np.int32)
    arg1 = np.zeros((t, k), np.int32)
    do_tick = np.array([n for _, n in phases], np.int32)
    for i, (ops, _) in enumerate(phases):
        for j, (op, a0, a1) in enumerate(ops):
            kind[i, j], arg0[i, j], arg1[i, j] = op, a0, a1
    return ScriptOps(kind, arg0, arg1, do_tick)


class BatchedRunner:
    """Runs a compiled script over B vmapped instances, fully under one jit.

    The delay sampler should be per-instance (UniformJaxDelay and
    HashJaxDelay derive a distinct stream per lane in init_batch_state); a
    shared GoExact stream would make every lane identical — valid for
    testing, pointless for throughput.
    """

    def __init__(self, topology: TopologySpec, config: Optional[SimConfig],
                 delay: JaxDelay, batch: int, scheduler: str = "exact",
                 check_every: int = 0, exact_impl: str = "cascade",
                 auto_layouts: bool = False, megatick: int = 1,
                 queue_engine: str = "auto", faults=None,
                 quarantine: bool = False):
        """scheduler: 'exact' = the reference's delivery semantics
        (bit-exact; the default 'cascade' formulation is O(E) vector work
        + one sequential step per marker delivered — ops/tick._cascade_tick
        — 'wave' parallelizes same-tick markers across destinations on top
        of that, bit-identical for position-addressable samplers, and
        exact_impl='fold' is the reference-literal N-step source
        scan kept as the specification form); 'sync' = simultaneous
        delivery (deterministic, protocol-equivalent, O(E) vectorized work
        per tick — the production/benchmark path, ops/tick._sync_tick).

        check_every: if > 0, evaluate the token-conservation invariant
        (the reference's checkTokens, test_common.go:298-328) INSIDE the
        jitted storm run every K phases and once after drain, setting the
        sticky ERR_CONSERVATION bit on any lane where node balances +
        in-flight ring tokens drift from the initial total (SURVEY.md §5:
        the jit-compatible sanitizer evaluated every K ticks).

        auto_layouts: let XLA choose parameter/result layouts for the
        storm runs instead of forcing row-major at the jit boundary.
        The TPU tick computes several ``[B, S, E]`` planes in a transposed
        ({0,2,1}) layout; with default boundary layouts every dispatch
        pays transpose copies on entry and exit (22% of a bare tick,
        BASELINE.md round-3 profile). Mechanism (the JAX AOT layout
        workflow — jit with ``Layout.AUTO`` rejects concrete arrays):
        ``run_storm`` lowers with ShapeDtypeStructs, compiles once,
        queries ``input_formats``, relayouts any mismatched input leaf,
        and calls the compiled object directly; fresh timed states built
        via ``init_batch_device(formats=storm_state_formats())`` are BORN
        in the compiled layouts, so steady-state dispatches are
        boundary-copy-free. Identity on CPU (XLA:CPU picks row-major).
        Default OFF: the perf paths (bench --layouts auto,
        tools/profile_tick.py) opt in; mesh-sharded states
        (parallel/mesh.shard_batch) use the plain jits.

        megatick: K-tick fusion depth for multi-tick dispatch on the
        exact path (TickKernel docstring) — script ``tick n`` stretches
        and the exact drain advance K fused ticks per loop iteration.
        Default 1 HERE (vs DenseSim's fused 8): under vmap every masked
        ``lax.cond`` computes both branches and selects over the whole
        batched state, which measured 5.7x SLOWER on the sf-256 B=64
        CPU drain than the plain per-tick loop — fusion only pays on the
        dispatch-bound single-instance path. The quiescence fast-forward
        (drained stretches in O(1)) applies at every K, including 1.
        Semantics-preserving knob either way; bench --megatick exposes
        it for the on-device A/B.

        queue_engine: ring-queue addressing (ops/tick.TickKernel): "gather"
        = O(E) head gathers + append scatters over the packed planes,
        "mask" = the O(E·C) one-hot formulation, "auto" (default) =
        backend-resolved (ops/tick.resolve_queue_engine: gather on TPU,
        mask on CPU where XLA serializes the scatters). Bit-identical
        results; ``self.queue_engine`` holds the resolved engine, and
        bench --queue-engine exposes the A/B and stamps the row.

        faults: models/faults.JaxFaults — the deterministic fault
        adversary, armed per lane through an injective nonzero
        ``fault_key`` ramp (init_batch_state), so every lane suffers an
        independent replayable fault stream (zero a lane's key to disarm
        just that lane). None (default) compiles the hooks away.

        quarantine: freeze a lane the moment its sticky error bits fire —
        the storm phase scan, multi-tick stretches, drain and flush all
        treat ``error != 0`` like the quiescence exit, so one poisoned
        lane stops ticking (its time freezes at the poisoning tick)
        instead of corrupting aggregate metrics; healthy lanes are
        bit-unaffected. summarize() reports the decode."""
        self.topo = DenseTopology(topology)
        self.config = config or SimConfig()
        self.delay = delay
        self.batch = batch
        # flush length must cover the sampler's actual max delay
        # (test_common.go:135-137 flushes maxDelay+1 ticks)
        if self.delay.max_delay != self.config.max_delay:
            import dataclasses

            self.config = dataclasses.replace(
                self.config, max_delay=self.delay.max_delay)
        if scheduler not in ("exact", "sync"):
            raise ValueError(f"unknown scheduler {scheduler!r}")
        # sync uses the split marker representation (ring content untouched
        # by ticks); exact needs the unified ring for push-order PRNG draws
        self.kernel = TickKernel(
            self.topo, self.config, self.delay,
            marker_mode="split" if scheduler == "sync" else "ring",
            exact_impl=exact_impl, megatick=megatick,
            queue_engine=queue_engine, faults=faults,
            quarantine=quarantine)
        self.queue_engine = self.kernel.queue_engine
        self.faults = faults
        self.quarantine = bool(quarantine)
        if scheduler == "exact":
            self._tick_fn = self.kernel._exact_tick
            self._drain_fn = self.kernel._drain_and_flush
            # fused multi-tick dispatch: megatick scan + quiescence
            # fast-forward (TickKernel._run_ticks)
            self._ticks_fn = self.kernel._run_ticks
        else:
            self._tick_fn = self.kernel._sync_tick
            self._drain_fn = self.kernel._sync_drain_and_flush
            # the sync tick draws (S, E) delays every tick, so it is never
            # a pure time increment — no quiescence fast-forward; multi-
            # tick script stretches still run under one fused loop
            self._ticks_fn = lambda s, n: lax.fori_loop(
                jnp.int32(0), jnp.asarray(n, jnp.int32),
                lambda _, t: self.kernel._sync_tick(t), s)
        self.scheduler = scheduler
        self.megatick = int(megatick)
        if check_every < 0:
            raise ValueError("check_every must be >= 0 (0 = off)")
        self.check_every = int(check_every)
        self.auto_layouts = auto_layouts
        # set the first time the AOT path's executable rejects our layouts
        # (the axon PJRT plugin's ``input_formats`` can disagree with the
        # executable's true parameter layouts for some programs); once
        # tripped, every storm run rides the plain row-major jits and
        # ``layouts_effective`` reports the degradation. Also pre-tripped
        # when the jax build has no layout API at all (utils/layouts) —
        # the round-5 exact bench died on that ImportError mid-warmup
        self._auto_unavailable = bool(auto_layouts) and not HAVE_LAYOUTS
        self._auto_broken = self._auto_unavailable
        self._storm_aot = {}   # (drain, prog shapes) -> (compiled, relayout)
        self._storm_prog_placed = {}  # same key -> (host values, placed prog)
        self._storm_state_formats = None
        self._run = jax.jit(
            jax.vmap(self._run_single, in_axes=(0, None)), donate_argnums=0)
        self._run_no_drain = jax.jit(
            jax.vmap(self._run_single_no_drain, in_axes=(0, None)),
            donate_argnums=0)
        self._run_storm = jax.jit(
            jax.vmap(self._run_storm_single, in_axes=(0, None)),
            donate_argnums=0)
        self._run_storm_no_drain = jax.jit(
            jax.vmap(self._run_storm_phases, in_axes=(0, None)),
            donate_argnums=0)

    # -- state construction ------------------------------------------------

    def init_batch(self) -> DenseState:
        """Fresh batched state: sim arrays broadcast over B, delay state
        built per-lane. Host-side (numpy) — jit transfers it on first use;
        prefer init_batch_device for timed runs."""
        single = init_state(self.topo, self.config, None)
        batched = jax.tree_util.tree_map(
            lambda x: np.broadcast_to(np.asarray(x), (self.batch,) + np.shape(x)).copy(),
            single._replace(delay_state=()))
        if self.faults is not None:
            batched = batched._replace(
                fault_key=np.asarray(self.faults.init_batch_state(self.batch)))
        return batched._replace(delay_state=self._batched_delay_state())

    @property
    def layouts_effective(self) -> str:
        """The boundary-layout mode runs are actually using: 'auto' while
        the AOT path is live, 'default' when auto_layouts is off, and
        'default(auto-rejected)' after the executable rejected the
        ``input_formats``-derived layouts and the runner degraded to the
        row-major jits (bench rows record this, so a fallback can never
        masquerade as an auto-layout measurement); 'default(auto-unavailable)'
        when this jax build exposes no layout API at all."""
        if not self.auto_layouts:
            return "default"
        if self._auto_unavailable:
            return "default(auto-unavailable)"
        return "default(auto-rejected)" if self._auto_broken else "auto"

    def storm_state_formats(self):
        """The compiled storm program's state input Formats (layout +
        sharding per leaf), available after the first ``run_storm`` under
        ``auto_layouts``. Hand to ``init_batch_device(formats=...)`` so
        fresh timed states enter the next dispatch with zero relayout
        copies (VERDICT r4 #6: the {0,2,1}<->{0,1,2} boundary
        transposes). None before the first auto run (or without
        auto_layouts) — init then builds default-layout states."""
        return self._storm_state_formats

    def init_batch_device(self, formats=None) -> DenseState:
        """Fresh batched state constructed ON the device by a jitted builder
        — no host->device transfer of the (multi-GB) state.

        This matters enormously when the chip is remote: the round-2 bench
        measured 2.2M node-ticks/s because each timed repeat shipped the
        ~4.6 GB numpy state of init_batch through the device tunnel
        (~16 s) inside the timed region; the tick itself runs in ~34 ms.
        Everything in the initial state is zeros except the token balances
        (a [N] broadcast) and the per-lane PRNG keys, so XLA materializes it
        in microseconds.

        ``formats``: optional pytree of device Formats (``state_formats``)
        the builder emits directly — the state is born in the consuming
        program's layouts, never relayouted (and never double-resident the
        way a post-hoc device_put would transiently be).
        """
        if getattr(self, "_init_device_formats", None) is not formats:
            # formats changed (identity check): drop the cached builder
            self._init_device_formats = formats
            if hasattr(self, "_init_device"):
                del self._init_device
        if not hasattr(self, "_init_device"):
            build = self._state_builder()
            # cached: a fresh jit closure per call would retrace every time
            self._init_device = (jax.jit(build, out_shardings=formats)
                                 if formats is not None else jax.jit(build))
        return self._init_device()

    def _state_builder(self):
        """The fresh-batched-state constructor as a traceable zero-arg
        function (shared by ``init_batch_device`` and ``prepare_storm``'s
        ``eval_shape``)."""
        if not hasattr(self, "_build_fn"):
            single = init_state(self.topo, self.config, None)
            template = single._replace(delay_state=())
            tokens0 = jnp.asarray(self.topo.tokens0)

            def build():
                st = jax.tree_util.tree_map(
                    lambda x: jnp.zeros((self.batch,) + np.shape(x),
                                        np.asarray(x).dtype), template)
                st = st._replace(
                    tokens=jnp.broadcast_to(
                        tokens0, (self.batch,) + tokens0.shape),
                    # the non-zero inits beside tokens (state.init_state):
                    # "no protected window yet" = int32 max, and the
                    # supervisor's "unset" initiator/completion-tick = -1
                    min_prot=jnp.full_like(st.min_prot,
                                           jnp.iinfo(jnp.int32).max),
                    snap_initiator=jnp.full_like(st.snap_initiator, -1),
                    snap_done_time=jnp.full_like(st.snap_done_time, -1))
                if self.faults is not None:
                    st = st._replace(
                        fault_key=self.faults.init_batch_state(self.batch))
                return st._replace(delay_state=self._batched_delay_state())

            self._build_fn = build
        return self._build_fn

    def prepare_storm(self, program, drain: bool = True):
        """AOT-compile the storm program from shapes alone and return the
        state input Formats (or None without ``auto_layouts``). Callers
        that build states AFTER this — ``init_batch_device(formats=...)``
        — get arrays born in the executable's layouts, so even the very
        first ``run_storm`` dispatch skips the relayout step entirely and
        the multi-GB state is never transiently double-resident (the
        bench's warmup does this; near-HBM-limit shapes such as the
        maxbatch probes depend on it)."""
        if not self.auto_layouts or self._auto_broken:
            return None
        prog = tuple(jnp.asarray(x) for x in program)
        abstract_state = jax.eval_shape(self._state_builder())
        comp, _ = self._storm_compiled(abstract_state, prog, drain)
        return input_formats(comp)[0][0]

    def _batched_delay_state(self):
        return self.delay.init_batch_state(self.batch)

    # -- execution ---------------------------------------------------------

    def _quarantine_gate(self, phase_fn):
        """Wrap a per-lane phase body so a lane with sticky error bits is
        frozen for the whole phase — the scan-path extension of the
        kernel's drain/flush quarantine exits. Identity when quarantine is
        off (no cond in the trace)."""
        if not self.quarantine:
            return phase_fn

        def gated(s, *xs):
            return lax.cond(s.error == 0,
                            lambda s: phase_fn(s, *xs), lambda s: s, s)

        return gated

    def _apply_phase(self, s: DenseState, ops) -> DenseState:
        kind, arg0, arg1, do_tick = ops

        def body(j, s):
            return lax.switch(kind[j], [
                lambda s: s,
                lambda s: self.kernel._inject_send(s, arg0[j], arg1[j]),
                lambda s: self.kernel._inject_snapshot(s, arg0[j]),
            ], s)

        def run(s):
            s = lax.fori_loop(0, kind.shape[0], body, s)
            # do_tick is a COUNT (compile_events): the whole stretch runs
            # under the fused multi-tick engine, one phase per stretch
            return lax.cond(do_tick != 0,
                            lambda s: self._ticks_fn(s, do_tick),
                            lambda s: s, s)

        return self._quarantine_gate(lambda s: run(s))(s)

    def _run_single_no_drain(self, s: DenseState, script: ScriptOps) -> DenseState:
        def phase(s, ops):
            return self._apply_phase(s, ops), None

        s, _ = lax.scan(phase, s, tuple(script))
        return s

    def _run_single(self, s: DenseState, script: ScriptOps) -> DenseState:
        s = self._run_single_no_drain(s, script)
        return self._drain_fn(s)

    def run(self, state: DenseState, script: ScriptOps,
            drain: bool = True) -> DenseState:
        """One dispatch: inject + tick every phase, then (optionally) drain
        until all lanes' snapshots complete + flush."""
        fn = self._run if drain else self._run_no_drain
        return fn(state, ScriptOps(*map(jnp.asarray, script)))

    def run_ticks(self, state: DenseState, n) -> DenseState:
        """Advance every lane n ticks under one dispatch via the fused
        multi-tick engine (megatick scan + quiescence fast-forward on the
        exact path; a fused loop of sync ticks otherwise)."""
        if not hasattr(self, "_run_ticks_jit"):
            self._run_ticks_jit = jax.jit(
                jax.vmap(self._ticks_fn, in_axes=(0, None)),
                donate_argnums=0)
        return self._run_ticks_jit(state, jnp.asarray(n, jnp.int32))

    # -- storm programs (models/workloads.py): bulk vectorized sends ------

    def storm_phase(self, s: DenseState, amounts, snaps) -> DenseState:
        """One storm phase for one instance: bulk sends + scheduled snapshot
        initiations + one tick. This is the framework's 'forward step'.
        Under quarantine the whole phase freezes on a poisoned lane
        (_run_storm_phases wraps it in the per-lane gate)."""
        s = self.kernel._bulk_send(s, amounts)
        if self.scheduler == "sync":
            # dense initiation (ids allocated in node-index order == the
            # schedule builder's order); the scalar path below would run its
            # scatter-heavy broadcast under vmap's select semantics every
            # phase even when no snapshot fires
            init_mask = jnp.any(
                jnp.arange(self.topo.n, dtype=jnp.int32)[None, :]
                == snaps[:, None], axis=0)
            s = self.kernel._bulk_snapshots(s, init_mask)
        else:
            def body(j, s):
                return lax.cond(snaps[j] >= 0,
                                lambda s: self.kernel._inject_snapshot(s, snaps[j]),
                                lambda s: s, s)

            s = lax.fori_loop(0, snaps.shape[-1], body, s)
        return self._tick_fn(s)

    def _check_conservation(self, s: DenseState) -> DenseState:
        from chandy_lamport_tpu.utils.metrics import conservation_delta

        delta = conservation_delta(s, self.config,
                                   int(self.topo.tokens0.sum()))
        return s._replace(error=s.error | jnp.where(
            delta != 0, ERR_CONSERVATION, 0).astype(jnp.int32))

    def _run_storm_phases(self, s: DenseState, program) -> DenseState:
        amounts, snap = program
        k = self.check_every
        gated_phase = self._quarantine_gate(self.storm_phase)

        def phase(s, xs):
            s = gated_phase(s, xs[0], xs[1])
            if k:
                s = lax.cond((xs[2] + 1) % k == 0,
                             self._check_conservation, lambda s: s, s)
            return s, None

        idx = jnp.arange(amounts.shape[0], dtype=jnp.int32)
        s, _ = lax.scan(phase, s, (amounts, snap, idx))
        # a no-drain run must not end between check points with a clean bit
        # misread as "verified through end of run"
        return self._check_conservation(s) if k else s

    def _run_storm_single(self, s: DenseState, program) -> DenseState:
        s = self._run_storm_phases(s, program)
        s = self._drain_fn(s)
        return self._check_conservation(s) if self.check_every else s

    def drain(self, state: DenseState) -> DenseState:
        """Drain + flush every lane (and the final conservation check when
        check_every is on) as its own dispatch — the tail step of a
        chunked/checkpointed storm run (cli storm --checkpoint-every runs
        phases in chunks with ``run_storm(..., drain=False)`` and finishes
        here; bit-identical to the single-dispatch ``run_storm`` because
        the per-tick math and the state-carried streams are unchanged)."""
        if not hasattr(self, "_drain_jit"):
            def fn(s):
                s = self._drain_fn(s)
                return (self._check_conservation(s) if self.check_every
                        else s)

            self._drain_jit = jax.jit(jax.vmap(fn), donate_argnums=0)
        return self._drain_jit(state)

    def run_storm(self, state: DenseState, program,
                  drain: bool = True) -> DenseState:
        """Execute a StormProgram (bulk per-edge sends + scheduled snapshot
        initiations + one tick per phase) over all lanes in one dispatch.
        Under ``auto_layouts``, dispatches the AOT-compiled executable with
        XLA-chosen boundary layouts (constructor docstring)."""
        prog = tuple(jnp.asarray(x) for x in program)
        if not self.auto_layouts or self._auto_broken:
            fn = self._run_storm if drain else self._run_storm_no_drain
            return fn(state, prog)
        comp, relayout = self._storm_compiled(state, prog, drain)
        # benches pass the same program values every timed repeat, but each
        # ``jnp.asarray`` lands in the default layout — when the executable
        # chose a non-default program layout that would force the relayout
        # dispatch into every timed region. Reuse the placed copy by value
        # (the tensors are tiny; the state is the thing we must not copy).
        key = (drain, tuple((tuple(x.shape), str(x.dtype)) for x in prog))
        cached = self._storm_prog_placed.get(key)
        if cached is not None and all(
                np.array_equal(a, np.asarray(b))
                for a, b in zip(cached[0], prog)):
            prog = cached[1]
        if not _formats_match((state, prog), input_formats(comp)[0]):
            # Relayout through a COMPILED identity whose output formats are
            # pinned to the storm executable's input formats. A plain
            # ``jax.device_put(x, format)`` is not reliable here: the axon
            # TPU backend was observed producing its shape-preferred layout
            # instead of the requested one, after which the AOT call's
            # layout check rejects the arrays. An executable's output
            # layouts, by contrast, are enforced by XLA itself, and the
            # call-time check compares against the same ``_xla_in_layouts``
            # list ``input_formats`` is built from — so this dispatch
            # satisfies it by construction. Donated + aliased: leaves whose
            # layout already matches pass through without a copy, so the
            # multi-GB state is never double-resident.
            host_prog = tuple(np.asarray(x) for x in prog)
            state, prog = relayout(state, prog)
            self._storm_prog_placed[key] = (host_prog, prog)
        try:
            return comp(state, prog)
        except ValueError as exc:
            if "layouts" not in str(exc):
                raise
            # still rejected: degrade permanently to the row-major jit
            # boundaries (the measured round-3 path) rather than fail the
            # run. The rejection fires before execution, so the donated
            # buffers are still alive.
            import warnings

            warnings.warn(
                "auto-layout AOT call rejected executable-produced "
                f"layouts; falling back to default boundary layouts: {exc}")
            self._auto_broken = True
            self._storm_state_formats = None
            self._storm_aot.clear()  # dead executables; free their programs
            self._storm_prog_placed.clear()
            fn = self._run_storm if drain else self._run_storm_no_drain
            return fn(state, prog)

    def _storm_compiled(self, state, prog, drain: bool):
        """AOT-compile the storm run with AUTO in/out layouts (cached per
        program shape), plus a donated identity jit whose output formats
        are pinned to the storm executable's chosen input formats (the
        run_storm relayout step). Lowering takes abstract
        ShapeDtypeStructs — the only arg form ``Layout.AUTO`` accepts —
        so this is the one compile the run needs, not an extra one (the
        identity is a trivial aliasing program)."""
        key = (drain, tuple((tuple(x.shape), str(x.dtype)) for x in prog))
        entry = self._storm_aot.get(key)
        if entry is None:
            fmt = auto_format()
            fn = jax.jit(
                jax.vmap(self._run_storm_single if drain
                         else self._run_storm_phases, in_axes=(0, None)),
                donate_argnums=0, in_shardings=fmt, out_shardings=fmt)
            # x may be a live array OR already a ShapeDtypeStruct (the
            # prepare_storm compile-from-shapes path)
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype),
                (state, prog))
            comp = fn.lower(*abstract).compile()
            # donate the (multi-GB) state so matching leaves alias through
            # copy-free; the program tensors are tiny, copying them keeps
            # caller-held arrays valid
            relayout = jax.jit(lambda s, p: (s, p), donate_argnums=0,
                               out_shardings=input_formats(comp)[0])
            entry = (comp, relayout)
            self._storm_aot[key] = entry
            self._storm_state_formats = input_formats(comp)[0][0]
        return entry

    # -- aggregate metrics (jit-friendly reductions; under a sharded batch
    #    axis these lower to XLA collectives over ICI) --------------------

    @staticmethod
    def summarize(state: DenseState) -> dict:
        from chandy_lamport_tpu.core.state import decode_error_bits
        from chandy_lamport_tpu.utils.metrics import (
            or_reduce,
            snapshot_lifecycle,
        )

        bits = int(or_reduce(state.error))
        fc = jnp.sum(state.fault_counts, axis=0)
        return {
            "instances": int(state.time.shape[0]),
            "total_ticks": int(jnp.sum(state.time)),
            "max_time": int(jnp.max(state.time)),
            "error_lanes": int(jnp.sum(state.error != 0)),
            # which bits fired across ALL lanes (int(max) would drop bits);
            # the short names ride along so no consumer has to decode the
            # raw int by hand — the round-2 bench zeroed the perf axis
            # without ever reporting WHICH flag fired
            "error_bits": bits,
            "errors_decoded": decode_error_bits(bits),
            "snapshots_started": int(jnp.sum(state.started)),
            "snapshots_completed": int(jnp.sum(
                jnp.sum(state.started & (state.completed >= state.has_local.shape[-1]),
                        axis=-1))),
            "total_tokens_resident": int(jnp.sum(state.tokens)),
            # adversary books (models/faults.py): events per class + the
            # injected token delta conservation_delta subtracts
            "fault_events": {"drops": int(fc[0]), "dups": int(fc[1]),
                             "jitters": int(fc[2]), "crashes": int(fc[3]),
                             "marker_drops": int(fc[4]),
                             "marker_dups": int(fc[5]),
                             "marker_jitters": int(fc[6])},
            "fault_skew": int(jnp.sum(state.fault_skew)),
            # supervisor lifecycle (utils/metrics.snapshot_lifecycle):
            # initiated / completed / retried / failed / aborted /
            # stale_markers + recovery-line age, summed over lanes
            "snapshot_lifecycle": {
                k: int(v) for k, v in snapshot_lifecycle(
                    state, state.has_local.shape[-1]).items()},
        }
