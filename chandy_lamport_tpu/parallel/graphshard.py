"""Graph-sharded execution: one giant simulation instance spread over a mesh.

This is the framework's tensor-parallel analogue (SURVEY.md §2.5): where the
instance axis (parallel/batch.py) scales the number of independent
simulations, this module scales the SIZE of a single simulated system —
node and edge state sharded over a ``graph`` mesh axis, cross-shard effects
carried by XLA collectives over ICI (psum / all_gather), exactly the role the
reference's in-process "network" would need a real communication backend for
at scale.

Partitioning invariants that make the sync scheduler shard-local:
  - nodes are split into P contiguous index blocks (node i -> shard i // (N/P));
  - every edge lives on its SOURCE node's shard, so "first eligible head per
    source" (the per-tick delivery choice) and all queue state are local;
  - per-(slot, node) snapshot state (frozen/rem/has/done) lives on the
    node's shard; per-(slot, edge) recording state lives with the edge.

Cross-shard traffic per tick comes in two engines (``comm_engine``,
SimConfig / runner kwarg, resolved by ops/tick.resolve_comm_engine):
  - "dense": psum of per-node token credits [N], psum of per-(slot, node)
    marker arrivals [S, N], all_gather of created-this-tick [S, N_local]
    -> [S, N] so source shards can update recording flags and enqueue
    re-broadcast markers for remote creators — plus [N_local, Em]
    incidence matmuls to spread the gathered planes back onto edges;
  - "sparse" (the default resolution): a boundary-edge halo exchange —
    local contributions reduce in O(E_local) with the segment-sum
    machinery from ops/tick.py over partition-time tables
    (parallel/mesh.boundary_tables), then ONLY the packed cut rows move,
    one lax.ppermute per ring distance d = 1..P-1 forward (credits +
    marker arrivals) and one back (created flags), scattered into the
    local planes with static index tables. All exchanged quantities are
    integer adds / boolean ORs, so accumulation order cannot perturb
    results: both engines are bit-identical to the unsharded kernel.
  - either way: psum of per-slot finalization counts and the error
    bitmask (the latter amortized to phase/megatick boundaries).

Per-shard topology constants ride in as sharded ARGUMENTS (stacked on the
shard axis) rather than closure constants, so one shard_map body serves every
shard. The scheduler semantics are exactly `_sync_tick`'s (ops/tick.py):
differential tests require bit-identical results to the unsharded kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.utils.guards import armed
from chandy_lamport_tpu.core.state import (
    ERR_CONSERVATION,
    ERR_QUEUE_OVERFLOW,
    ERR_RECORD_OVERFLOW,
    ERR_SNAPSHOT_OVERFLOW,
    ERR_SNAPSHOT_TIMEOUT,
    ERR_TICK_LIMIT,
    ERR_TOKEN_UNDERFLOW,
    ERR_VALUE_OVERFLOW,
    F32_EXACT_LIMIT,
    NUM_ERROR_BITS,
    RTIME_PACK_LIMIT,
    DenseTopology,
    meta_rtime,
    pack_meta,
)
from chandy_lamport_tpu.ops.tick import (
    log_append,
    merge_key_limit,
    merge_keymult,
    resolve_queue_engine,
    window_update,
)
from chandy_lamport_tpu.utils.tracing import (
    EV_SNAP_END,
    EV_SNAP_START,
    EV_SUP_ABORT,
    EV_SUP_FAIL,
    EV_SUP_RETRY,
    JaxTrace,
    trace_append_many,
)
from chandy_lamport_tpu.utils.fixtures import TopologySpec

_i32 = jnp.int32
_f32 = jnp.float32


class ShardedTopology(NamedTuple):
    """Per-shard topology constants, stacked on the leading shard axis.
    ``a_in`` stays f32 for the token-amount credit matmul; the ``_c`` copies
    carry the count-matmul dtype (bf16 on TPU when the degree bound proves
    counts exact, else aliases of the f32 arrays) so no cast sits inside the
    scanned tick body."""

    edge_src: Any    # i32 [P, Em]  global src node id, -1 pad
    edge_dst: Any    # i32 [P, Em]  global dst node id, -1 pad
    a_in: Any        # f32 [P, N, Em]  one-hot dst incidence (0 for pads;
    #                  [P, 1, 1] zeros when comm_engine="sparse" — the
    #                  halo exchange never reads the dense planes, so the
    #                  O(N * Em) constants are not materialized)
    a_in_c: Any      # cnt [P, N, Em]
    a_src_c: Any     # cnt [P, N, Em]  one-hot src incidence (0 for pads)
    src_first: Any   # i32 [P, Em] local index of each edge's source's first
    #                  edge (pads point at themselves) — O(Em) same-source
    #                  predecessor test via prefix counts, replacing the old
    #                  O(Em^2) strict-predecessor matrix
    # sparse halo-exchange tables (parallel/mesh.BoundaryTables docstring
    # for the layout; [P, 0-size] when comm_engine="dense" or cut is empty)
    dst_seg: Any     # i32 [P, Em]        combined segment / flags index
    seg_perm: Any    # i32 [P, Em]        stable sort into segment order
    seg_lo: Any      # i32 [P, Nl+R+1]    segment bounds
    seg_hi: Any      # i32 [P, Nl+R+1]
    recv_idx: Any    # i32 [P, P-1, H]    scatter (fwd) / gather (rev) rows
    in_degree: Any   # i32 [N] (replicated)


class ShardedScript(NamedTuple):
    """An event script compiled for sharded execution — all leaves
    REPLICATED (each shard masks send ops by owning shard):
      kind  i32 [T, K]  0=nop, 1=send, 2=snapshot
      shard i32 [T, K]  owning shard of a send's edge, -1 otherwise
      loc   i32 [T, K]  send: local edge index on the owning shard;
                        snapshot: global node index
      arg   i32 [T, K]  send: token amount
      do_tick i32 [T]   ticks after the phase (0 only for a synthetic
                        trailing phase; multi-tick stretches are counts)
    """

    kind: Any
    shard: Any
    loc: Any
    arg: Any
    do_tick: Any


class ShardedState(NamedTuple):
    """One giant instance, sharded on the leading axis of every leaf except
    the replicated scalars. Channel state uses the split representation
    (core/state.DenseState docstring): rings carry tokens only; markers
    live in the [S, Em] pending planes with FIFO order preserved by the
    per-edge merge keys. Everything marker/queue is local to the edge's
    (= its source node's) shard, so the split adds no collectives."""

    time: Any        # i32 [] (replicated)
    tokens: Any      # i32 [P, Nl]
    q_data: Any      # i32 [P, Em, C]
    q_meta: Any      # i32 [P, Em, C]  packed rtime << 1 | marker
    #                  (state.pack_meta; the marker bit is never set here —
    #                  the sharded runner is split-only)
    q_head: Any      # i32 [P, Em]
    q_len: Any       # i32 [P, Em]
    tok_pushed: Any  # i32 [P, Em]
    mk_cnt: Any      # i32 [P, Em]
    m_pending: Any   # bool [P, S, Em]
    m_rtime: Any     # i32 [P, S, Em]
    m_key: Any       # i32 [P, S, Em]  (merge key, DenseState docstring)
    next_sid: Any    # i32 [] (replicated)
    started: Any     # bool [S] (replicated)
    has_local: Any   # bool [P, S, Nl]
    frozen: Any      # i32 [P, S, Nl]
    rem: Any         # i32 [P, S, Nl]
    done_local: Any  # bool [P, S, Nl]
    recording: Any   # bool [P, S, Em]
    # shared per-edge recording log + per-(slot, edge) windows — the same
    # representation as DenseState ("Recording as windows"); everything is
    # edge-local, so it shards cleanly with the edges
    rec_cnt: Any     # i32 [P, Em]
    min_prot: Any    # i32 [P, Em]
    log_amt: Any     # i32 [P, L, Em]
    rec_start: Any   # window dtype [P, S, Em] (SimConfig.window_dtype)
    rec_end: Any     # window dtype [P, S, Em]
    completed: Any   # i32 [S] (replicated)
    # snapshot-supervisor state (SimConfig.snapshot_timeout/_every) — all
    # replicated: the timeout scan / abort decision is global, so every
    # shard computes it identically and the gating conds stay SPMD-uniform
    # (the split representation clears its pending planes on abort, so no
    # epoch storage or stale accounting is needed here; marker-fault
    # INJECTION stays a dense/batched-path feature)
    snap_epoch: Any      # i32 [S] (replicated)
    snap_deadline: Any   # i32 [S] (replicated; 0 = unarmed)
    snap_retries: Any    # i32 [S] (replicated)
    snap_initiator: Any  # i32 [S] (replicated; -1 = unset)
    snap_failed: Any     # bool [S] (replicated)
    snap_done_time: Any  # i32 [S] (replicated; -1 until completed)
    # flight-recorder ring (utils/tracing; core/state.DenseState tr_*) —
    # REPLICATED: every shard appends the same replicated-event stream
    # (snapshot lifecycle, supervisor actions) with replicated operands, so
    # the rings stay bit-identical across shards. Per-node/per-edge events
    # (sends, marker traffic) are shard-LOCAL facts; appending them would
    # diverge the replicated ring, so the sharded recorder captures the
    # global protocol timeline only.
    tr_meta: Any     # i32 [K] (replicated)
    tr_data: Any     # i32 [K] (replicated)
    tr_tick: Any     # i32 [K] (replicated)
    tr_count: Any    # i32 [] (replicated)
    tr_on: Any       # i32 [] (replicated)
    delay_key: Any   # u32 [P, 2] per-shard counter-based key
    error: Any       # i32 [] (replicated)


def shard_topology(topo: DenseTopology, shards: int, cnt_dtype=None,
                   incidence: bool = True):
    """Partition nodes into contiguous blocks and edges by source shard;
    pad per-shard edge arrays to the max local count. ``cnt_dtype`` is the
    count-matmul dtype for the ``_c`` constants (default f32).
    ``incidence=False`` (the sparse comm engine) replaces the O(N * Em)
    one-hot incidence constants with [P, 1, 1] zeros — the halo exchange
    never reads them, and at giant N they would dominate HBM.

    Returns (ShardedTopology, Em, parallel/mesh.BoundaryTables) — the
    boundary tables are always built (cheap host numpy) so cut statistics
    and the comm-bytes model are available under either engine."""
    from chandy_lamport_tpu.parallel.mesh import boundary_tables

    n, e = topo.n, topo.e
    if n % shards:
        raise ValueError(f"nodes ({n}) must divide evenly into {shards} shards")
    nl = n // shards
    shard_of = topo.edge_src // nl
    counts = np.bincount(shard_of, minlength=shards)
    em = int(counts.max()) if e else 1
    edge_src = np.full((shards, em), -1, np.int32)
    edge_dst = np.full((shards, em), -1, np.int32)
    fill = np.zeros(shards, np.int64)
    for i in range(e):  # edge order preserved within shard (src,dst sorted)
        p = shard_of[i]
        edge_src[p, fill[p]] = topo.edge_src[i]
        edge_dst[p, fill[p]] = topo.edge_dst[i]
        fill[p] += 1
    ishape = (shards, n, em) if incidence else (shards, 1, 1)
    a_in = np.zeros(ishape, np.float32)
    a_src = np.zeros(ishape, np.float32)
    src_first = np.tile(np.arange(em, dtype=np.int32), (shards, 1))
    for p in range(shards):
        if incidence:
            for j in range(int(counts[p])):
                a_in[p, edge_dst[p, j], j] = 1.0
                a_src[p, edge_src[p, j], j] = 1.0
        # local edges keep global (src, dst) order, so src is nondecreasing
        # over the real prefix; pads (tail) keep the identity default
        row = edge_src[p, :int(counts[p])]
        src_first[p, :int(counts[p])] = np.searchsorted(row, row, side="left")
    bt = boundary_tables(edge_src, edge_dst, shards, nl)
    a_in_f = jnp.asarray(a_in)
    cnt = jnp.dtype(cnt_dtype) if cnt_dtype is not None else jnp.dtype(jnp.float32)
    return ShardedTopology(
        edge_src=jnp.asarray(edge_src), edge_dst=jnp.asarray(edge_dst),
        a_in=a_in_f,
        a_in_c=a_in_f if cnt == jnp.float32 else jnp.asarray(a_in, cnt),
        a_src_c=jnp.asarray(a_src, cnt),
        src_first=jnp.asarray(src_first),
        dst_seg=jnp.asarray(bt.dst_seg), seg_perm=jnp.asarray(bt.seg_perm),
        seg_lo=jnp.asarray(bt.seg_lo), seg_hi=jnp.asarray(bt.seg_hi),
        recv_idx=jnp.asarray(bt.recv_idx),
        in_degree=jnp.asarray(topo.in_degree),
    ), em, bt


class GraphShardedRunner:
    """Storm-program execution for a single giant instance over a graph mesh.

    Semantics are identical to BatchedRunner(scheduler='sync') with batch=1 —
    verified bit-exactly by tests/test_graphshard.py — but every array is
    sharded over the ``graph`` axis of the mesh and the tick communicates via
    collectives instead of living on one device.
    """

    def __init__(self, topology: TopologySpec, config: Optional[SimConfig],
                 mesh: Mesh, axis: str = "graph", seed: int = 0,
                 max_delay: int = 5, fixed_delay: Optional[int] = None,
                 check_every: int = 0, queue_engine: str = "auto",
                 comm_engine: Optional[str] = None,
                 kernel_engine: Optional[str] = None, megatick: int = 1,
                 quarantine: bool = False, trace=None, guards=None,
                 fused_tick: Optional[str] = None,
                 fused_block_edges: int = 0,
                 fused_tile: Optional[str] = None):
        """fixed_delay: constant delay instead of the per-shard uniform
        stream — lets differential tests demand bit-equality with the
        unsharded kernel (counter-based streams differ by construction).

        check_every: if > 0, evaluate the token-conservation invariant
        every K storm phases and after drain (one psum of the per-shard
        balances + in-flight ring tokens vs the initial total), setting
        the replicated sticky ERR_CONSERVATION bit — the sharded twin of
        BatchedRunner's sanitizer.

        queue_engine: ring-queue addressing, the sharded twin of
        TickKernel's knob (ops/tick.py): "gather" = O(Em) head gathers +
        append scatters over the packed planes, "mask" = the [Em, C]
        one-hot formulation, "auto" (default) = backend-resolved
        (ops/tick.resolve_queue_engine). All ring state is shard-local,
        so the choice changes no collective.

        comm_engine: cross-shard traffic engine (module docstring):
        "dense" = full-plane psum/all_gather + incidence matmuls,
        "sparse" = boundary-edge halo exchange over lax.ppermute with
        O(E_local) segment reductions, "auto" = ops/tick.
        resolve_comm_engine. None (default) defers to
        SimConfig.comm_engine. Bit-identical either way.

        megatick: K >= 1 — the drain loop advances K cond-gated ticks
        per while_loop body via an in-shard lax.scan, so host dispatch
        and the psum that folds the shard-local deferred error bits into
        the replicated sticky mask amortize to the K boundary (the same
        cadence idea as check_every). Each scanned tick is gated on the
        live drain predicate (pending & budget, replicated), so K never
        overshoots: results are bit-identical for ANY K. The one caveat
        is quarantine: its freeze reads the replicated error mask, which
        under K > 1 is up to K-1 ticks stale, so an ERRORING quarantined
        run may freeze later than with K=1 (clean runs are unaffected).

        quarantine: freeze the instance the moment its (replicated)
        sticky error bits fire — storm phases, drain and flush all treat
        ``error != 0`` like the completion exit. The predicate is
        replicated, so the gating conds stay uniform across shards (same
        SPMD discipline as the conservation-check cond); in the batched
        data x graph mode the gate applies per lane under vmap. Fault
        INJECTION stays a dense/batched-path feature — ShardedState
        carries no adversary leaves.

        guards: utils/guards.RuntimeGuards — opt-in runtime contract
        sentry (BatchedRunner docstring): arms transfer_guard / leak
        checking / the compile counter around the storm and script
        dispatches. None (default) changes nothing.

        trace: utils/tracing.JaxTrace — arm the replicated flight
        recorder: snapshot lifecycle (start/end) and supervisor actions
        (abort/retry/fail) append to the replicated trace ring (the
        ShardedState tr_* docstring explains why per-node/per-edge events
        stay out). None (default) compiles the trace ops away.

        fused_tick: the one-kernel megatick knob (kernels/megatick.py).
        Accepted for knob-surface uniformity (bench stamps every runner
        row with it) but the sharded tick can never fuse: every tick
        body crosses shard boundaries — the halo exchange / psum between
        the send half and the delivery half — and a Pallas kernel body
        cannot contain collectives over the graph mesh. "auto" and "off"
        both resolve "off" here; "on" raises, naming the constraint.
        ``fused_block_edges`` is accepted and ignored for the same
        reason; ``fused_tile`` (the tiled-state layout of the fused
        kernel, kernels/megatick.resolve_fused_tile) resolves "off" for
        the same reason — there is no fused kernel here to tile."""
        self.topo = DenseTopology(topology)
        self.config = config or SimConfig()
        self.guards = guards
        self.mesh = mesh
        self.axis = axis
        self.shards = mesh.shape[axis]
        self.seed = seed
        if check_every < 0:
            raise ValueError("check_every must be >= 0 (0 = off)")
        self.check_every = int(check_every)
        self.quarantine = bool(quarantine)
        self.queue_engine = resolve_queue_engine(queue_engine)
        from chandy_lamport_tpu.ops.tick import resolve_comm_engine

        self.comm_engine = resolve_comm_engine(
            self.config.comm_engine if comm_engine is None else comm_engine)
        # tick-kernel engine (chandy_lamport_tpu.kernels): None defers to
        # the config knob, same contract as comm_engine; bit-identical
        from chandy_lamport_tpu.kernels import (
            pallas_interpret,
            resolve_kernel_engine,
        )
        self.kernel_engine = resolve_kernel_engine(
            self.config.kernel_engine if kernel_engine is None
            else kernel_engine)
        self._pl_interpret = pallas_interpret()
        # the fused-megatick knob resolves "off" unconditionally here
        # (docstring above); validate the spelling + honor an explicit
        # "on" with a loud refusal rather than a silent downgrade
        ft = self.config.fused_tick if fused_tick is None else fused_tick
        from chandy_lamport_tpu.config import ENGINE_KNOBS
        if ft not in ENGINE_KNOBS["fused_tick"]:
            raise ValueError(f"unknown fused_tick {ft!r}")
        if ft == "on":
            raise ValueError(
                "fused_tick='on' impossible: the sharded tick exchanges "
                "boundary rows (halo/psum) inside every tick body, which "
                "a single Pallas kernel cannot contain")
        self.fused = "off"
        self.fused_reason = ("sharded tick crosses shard boundaries "
                             "inside the tick body")
        from chandy_lamport_tpu.kernels.megatick import resolve_fused_tile
        self.fused_tile, self.fused_tile_reason = resolve_fused_tile(
            self.config.fused_tile if fused_tile is None else fused_tile,
            fused=self.fused, vmem_bytes=0, tiled_vmem_bytes=0)
        if megatick < 1:
            raise ValueError("megatick must be >= 1")
        self.megatick = int(megatick)
        # snapshot supervisor (SimConfig.snapshot_timeout/_every): the
        # sharded twin of TickKernel._supervise — replicated scan/abort
        # state, shard-local plane clears, cond-gated re-initiation
        self._sup = bool(self.config.snapshot_timeout > 0
                         or self.config.snapshot_every > 0)
        self.max_delay = fixed_delay if fixed_delay is not None else max_delay
        self.fixed_delay = fixed_delay
        if self.config.max_delay != self.max_delay:
            self.config = dataclasses.replace(self.config,
                                              max_delay=self.max_delay)
        self.trace = trace
        if trace is not None and self.config.trace_capacity == 0:
            self.config = dataclasses.replace(
                self.config,
                trace_capacity=getattr(trace, "capacity", 0)
                or JaxTrace.DEFAULT_CAPACITY)
        self._trace_on = (trace is not None
                          and self.config.trace_capacity > 0)
        # shared numeric-exactness gate + recording helpers with TickKernel
        from chandy_lamport_tpu.ops.tick import count_dtype

        self._cnt = count_dtype(self.topo, self.config.count_dtype)
        self._rec_dtype = jnp.dtype(self.config.record_dtype)
        self._rec_limit = jnp.iinfo(self._rec_dtype).max
        self._keymult = merge_keymult(self.config.max_snapshots)
        self._key_limit = merge_key_limit(self.config.max_snapshots)
        self.stopo, self.em, self._bt = shard_topology(
            self.topo, self.shards, cnt_dtype=self._cnt,
            incidence=self.comm_engine == "dense")
        self.nl = self.topo.n // self.shards
        self.halo = self._bt.halo  # max boundary rows per neighbor pair

        # global edge -> (owning shard, local slot) in shard fill order;
        # used by shard_program and the event-script compiler
        shard_of = self.topo.edge_src // self.nl
        self.edge_shard = shard_of.astype(np.int32)
        self.edge_local = np.zeros(self.topo.e, np.int32)
        fill = np.zeros(self.shards, np.int64)
        for i in range(self.topo.e):
            self.edge_local[i] = fill[shard_of[i]]
            fill[shard_of[i]] += 1

        spec_sharded = P(axis)
        spec_rep = P()
        topo_specs = ShardedTopology(
            edge_src=spec_sharded, edge_dst=spec_sharded, a_in=spec_sharded,
            a_in_c=spec_sharded, a_src_c=spec_sharded, src_first=spec_sharded,
            dst_seg=spec_sharded, seg_perm=spec_sharded,
            seg_lo=spec_sharded, seg_hi=spec_sharded, recv_idx=spec_sharded,
            in_degree=spec_rep)
        state_specs = ShardedState(
            time=spec_rep, tokens=spec_sharded, q_data=spec_sharded, q_meta=spec_sharded,
            q_head=spec_sharded, q_len=spec_sharded,
            tok_pushed=spec_sharded, mk_cnt=spec_sharded,
            m_pending=spec_sharded, m_rtime=spec_sharded, m_key=spec_sharded,
            next_sid=spec_rep, started=spec_rep,
            has_local=spec_sharded, frozen=spec_sharded, rem=spec_sharded,
            done_local=spec_sharded, recording=spec_sharded,
            rec_cnt=spec_sharded,
            min_prot=spec_sharded, log_amt=spec_sharded,
            rec_start=spec_sharded, rec_end=spec_sharded, completed=spec_rep,
            snap_epoch=spec_rep, snap_deadline=spec_rep,
            snap_retries=spec_rep, snap_initiator=spec_rep,
            snap_failed=spec_rep, snap_done_time=spec_rep,
            tr_meta=spec_rep, tr_data=spec_rep, tr_tick=spec_rep,
            tr_count=spec_rep, tr_on=spec_rep,
            delay_key=spec_sharded, error=spec_rep)
        self._state_specs = state_specs

        from functools import partial

        # version-tolerant shard_map (utils/shardmap): jax.shard_map with
        # check_vma on current releases, the jax.experimental spelling
        # with check_rep on 0.4.x — one surface either way
        from chandy_lamport_tpu.utils.shardmap import shard_map

        smap = partial(shard_map, mesh=mesh)
        self._topo_specs = topo_specs
        self._run = jax.jit(smap(
            self._run_storm_body,
            # program = (amounts [T, P, Em] sharded on the shard axis,
            #            snapshot schedule replicated)
            in_specs=(state_specs, topo_specs, (P(None, axis), spec_rep)),
            out_specs=state_specs))
        script_specs = ShardedScript(*(spec_rep,) * 5)
        self._run_script = jax.jit(smap(
            self._run_script_body,
            in_specs=(state_specs, topo_specs, script_specs),
            out_specs=state_specs))
        self._run_batched_cache = {}

    # -- state construction ------------------------------------------------

    def init_state(self) -> ShardedState:
        cfg, topo = self.config, self.topo
        p, em, nl = self.shards, self.em, self.nl
        c, s, m = cfg.queue_capacity, cfg.max_snapshots, cfg.max_recorded
        tokens = topo.tokens0.reshape(p, nl).copy()
        base = jax.random.PRNGKey(self.seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(p, dtype=jnp.uint32))
        state = ShardedState(
            time=np.int32(0),
            tokens=tokens,
            q_data=np.zeros((p, em, c), np.int32),
            q_meta=np.zeros((p, em, c), np.int32),
            q_head=np.zeros((p, em), np.int32),
            q_len=np.zeros((p, em), np.int32),
            tok_pushed=np.zeros((p, em), np.int32),
            mk_cnt=np.zeros((p, em), np.int32),
            m_pending=np.zeros((p, s, em), np.bool_),
            m_rtime=np.zeros((p, s, em), np.int32),
            m_key=np.zeros((p, s, em), np.int32),
            next_sid=np.int32(0),
            started=np.zeros(s, np.bool_),
            has_local=np.zeros((p, s, nl), np.bool_),
            frozen=np.zeros((p, s, nl), np.int32),
            rem=np.zeros((p, s, nl), np.int32),
            done_local=np.zeros((p, s, nl), np.bool_),
            recording=np.zeros((p, s, em), np.bool_),
            rec_cnt=np.zeros((p, em), np.int32),
            min_prot=np.full((p, em), np.iinfo(np.int32).max, np.int32),
            log_amt=np.zeros((p, m, em), np.dtype(self.config.record_dtype)),
            rec_start=np.zeros((p, s, em), np.dtype(cfg.window_dtype)),
            rec_end=np.zeros((p, s, em), np.dtype(cfg.window_dtype)),
            completed=np.zeros(s, np.int32),
            snap_epoch=np.zeros(s, np.int32),
            snap_deadline=np.zeros(s, np.int32),
            snap_retries=np.zeros(s, np.int32),
            snap_initiator=np.full(s, -1, np.int32),
            snap_failed=np.zeros(s, np.bool_),
            snap_done_time=np.full(s, -1, np.int32),
            tr_meta=np.zeros(cfg.trace_capacity, np.int32),
            tr_data=np.zeros(cfg.trace_capacity, np.int32),
            tr_tick=np.zeros(cfg.trace_capacity, np.int32),
            tr_count=np.int32(0),
            tr_on=np.int32(1),
            delay_key=keys,
            error=np.int32(0),
        )
        return jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(jnp.asarray(x),
                                         NamedSharding(self.mesh, sp)),
            state, self._state_specs)

    def shard_program(self, amounts: np.ndarray, snap: np.ndarray):
        """Split a StormProgram's [T, E] amounts into per-shard [T, P, Em]
        (sharded on axis 1); the snapshot schedule stays replicated."""
        t = amounts.shape[0]
        out = np.zeros((t, self.shards, self.em), np.int32)
        out[:, self.edge_shard, self.edge_local] = amounts
        amounts_s = jax.device_put(
            jnp.asarray(out), NamedSharding(self.mesh, P(None, self.axis)))
        snap_r = jax.device_put(jnp.asarray(snap),
                                NamedSharding(self.mesh, P()))
        return amounts_s, snap_r

    def compile_script(self, events) -> ShardedScript:
        """Compile an event script (the reference .events shape) for sharded
        execution: reuse the dense compiler (parallel/batch.compile_events),
        then remap each send's global edge index to (owning shard, local
        slot). All leaves replicated — ops are masked per shard at run
        time so every shard executes one identical collective schedule."""
        from chandy_lamport_tpu.parallel.batch import (
            OP_SEND,
            OP_SNAPSHOT,
            compile_events,
        )

        ops = compile_events(self.topo, events)
        kind = np.asarray(ops.kind)
        arg0 = np.asarray(ops.arg0)
        arg1 = np.asarray(ops.arg1)
        # arg0 holds NODE indices for snapshot ops — clip before the eager
        # edge-table lookup (a node index can exceed the edge count)
        e_safe = np.clip(arg0, 0, max(self.topo.e - 1, 0))
        shard = np.where(kind == OP_SEND, self.edge_shard[e_safe], -1)
        loc = np.where(kind == OP_SEND, self.edge_local[e_safe],
                       np.where(kind == OP_SNAPSHOT, arg0, 0))
        rep = NamedSharding(self.mesh, P())
        return ShardedScript(
            *(jax.device_put(jnp.asarray(x, jnp.int32), rep)
              for x in (kind, shard, loc, arg1, np.asarray(ops.do_tick))))

    # -- collective helpers ------------------------------------------------

    def _my_slice(self, arr_n):
        """Local [.., Nl] block of a replicated [.., N] array."""
        idx = lax.axis_index(self.axis) * self.nl
        return lax.dynamic_slice_in_dim(arr_n, idx, self.nl, axis=-1)

    def _por(self, mask):
        """Bitwise-OR reduction of an error bitmask across shards. lax.pmax
        is NOT a bitwise OR: with ERR_TOKEN_UNDERFLOW on one shard and
        ERR_QUEUE_OVERFLOW on another in the same update, max would drop the
        smaller bit and decode_errors would mislabel the cause. Per-bit
        psum>0 preserves every flag."""
        mask = jnp.asarray(mask, _i32)
        shifts = jnp.arange(NUM_ERROR_BITS, dtype=_i32)
        bits = (mask[..., None] >> shifts) & 1
        any_bit = lax.psum(bits, self.axis) > 0
        return jnp.sum(any_bit.astype(_i32) << shifts, axis=-1, dtype=_i32)

    # -- kernel pieces (run inside shard_map; shapes are per-shard) --------

    def _head_fields(self, s: ShardedState):
        """Every local ring head's (rtime, amount) by ``queue_engine``:
        one [Em] gather per packed plane, or the legacy [Em, C] one-hot
        reductions (TickKernel._head_fields' shard-local twin; the split
        ring's marker bit is always 0 so only rtime/amount are decoded).
        kernel_engine="pallas" overrides both with the fused VMEM pass."""
        if self.kernel_engine == "pallas":
            from chandy_lamport_tpu.kernels import queue as plk_queue

            rt, _, amt = plk_queue.head_fields(
                s.q_meta, s.q_data, s.q_head, interpret=self._pl_interpret)
            return rt, amt
        if self.queue_engine == "gather":
            head_meta = jnp.take_along_axis(
                s.q_meta, s.q_head[:, None], axis=-1)[..., 0]
            head_amt = jnp.take_along_axis(
                s.q_data, s.q_head[:, None], axis=-1)[..., 0]
        else:
            cc = jnp.arange(self.config.queue_capacity, dtype=_i32)[None, :]
            head_hit = cc == s.q_head[:, None]                  # [Em, C]
            head_meta = jnp.sum(jnp.where(head_hit, s.q_meta, 0),
                                axis=-1, dtype=_i32)
            head_amt = jnp.sum(jnp.where(head_hit, s.q_data, 0),
                               axis=-1, dtype=_i32)
        return meta_rtime(head_meta), head_amt

    def _append_active(self, s: ShardedState, active, rt_e, data_e):
        """Batched shard-local ring append (TickKernel._append_rows' twin,
        tokens only — the packed marker bit stays 0): one vectorized
        ``.at[edge, pos]`` scatter per plane under the gather engine
        (inactive rows aim at column C and drop), the legacy [Em, C]
        one-hot selects under "mask". Returns (state, local error bits) —
        the caller psums the bits so every shard's SPMD schedule stays
        aligned. Pad edges are never active (their amounts are 0)."""
        C = self.config.queue_capacity
        rt_e = jnp.asarray(rt_e, _i32)
        data_e = jnp.asarray(data_e, _i32)
        if self.kernel_engine == "pallas":
            from chandy_lamport_tpu.kernels import queue as plk_queue

            # queue overflow is booked by the dense differential, not
            # here (pad edges never fill) — gate that bit off so the err
            # word matches the stock formulation below exactly
            q_meta, q_data, err = plk_queue.append_rows(
                s.q_meta, s.q_data, s.q_head, s.q_len, s.tok_pushed,
                active,
                jnp.broadcast_to(pack_meta(rt_e, False), active.shape),
                jnp.broadcast_to(rt_e, active.shape),
                jnp.broadcast_to(data_e, active.shape),
                capacity=C, key_limit=self._key_limit,
                flag_queue_overflow=False, interpret=self._pl_interpret)
            return s._replace(
                q_meta=q_meta,
                q_data=q_data,
                q_len=s.q_len + active.astype(_i32),
                tok_pushed=s.tok_pushed + active.astype(_i32),
            ), err[0]
        err = (jnp.any(active & (s.tok_pushed >= self._key_limit))
               | jnp.any(active & (rt_e >= RTIME_PACK_LIMIT))
               ).astype(_i32) * ERR_VALUE_OVERFLOW
        pos = (s.q_head + s.q_len) % C
        meta_e = pack_meta(rt_e, False)
        if self.queue_engine == "gather":
            rows = jnp.arange(active.shape[-1], dtype=_i32)
            tgt = jnp.where(active, pos, C)   # inactive -> OOB, dropped
            q_meta = s.q_meta.at[rows, tgt].set(meta_e, mode="drop",
                                                unique_indices=True)
            q_data = s.q_data.at[rows, tgt].set(data_e, mode="drop",
                                                unique_indices=True)
        else:
            hit = active[:, None] & (jnp.arange(C, dtype=_i32)[None, :]
                                     == pos[:, None])           # [Em, C]
            q_meta = jnp.where(hit, meta_e[:, None], s.q_meta)
            q_data = jnp.where(hit, data_e[:, None], s.q_data)
        return s._replace(
            q_meta=q_meta,
            q_data=q_data,
            q_len=s.q_len + active.astype(_i32),
            tok_pushed=s.tok_pushed + active.astype(_i32),
        ), err

    def _draw_many(self, key, time, shape):
        if self.fixed_delay is not None:
            return jnp.full(shape, time + self.fixed_delay, _i32), key
        key, sub = jax.random.split(key)
        d = jax.random.randint(sub, shape, 0, self.max_delay, dtype=_i32)
        return time + 1 + d, key

    def _push_markers_split(self, s: ShardedState, st: ShardedTopology,
                            push_se) -> ShardedState:
        """Local twin of TickKernel._push_markers_split: set the pending
        planes, allocating merge keys (DenseState docstring) in slot order
        per edge — no [Em, C] ring content is touched and no collective is
        needed (every marker lives on its edge's shard). Cannot overflow
        the planes: each (snapshot, edge) pushes at most once
        (node.go:154-156)."""
        rts_se, key = self._draw_many(s.delay_key, s.time, push_se.shape)
        off_se = jnp.cumsum(push_se, axis=0, dtype=_i32) - push_se
        k_e = jnp.sum(push_se, axis=0, dtype=_i32)
        key_se = (s.tok_pushed * self._keymult + s.mk_cnt)[None, :] + off_se
        return s._replace(
            m_pending=s.m_pending | push_se,
            m_rtime=jnp.where(push_se, jnp.asarray(rts_se, _i32), s.m_rtime),
            m_key=jnp.where(push_se, key_se, s.m_key),
            mk_cnt=s.mk_cnt + k_e,
            delay_key=key,
        )

    def _create_and_broadcast(self, s: ShardedState, st: ShardedTopology,
                              created_global) -> ShardedState:
        """created_global [S, N] replicated: freeze/record/broadcast for
        every created (slot, node); remote creators reach this shard's
        recording flags + queues through the replicated created matrix.
        No collective either way — under "sparse" the O(S * N * Em)
        incidence matmuls become O(S * Em) gathers on the edge endpoints
        (the incidence constants are not even materialized then)."""
        S = self.config.max_snapshots
        if self.comm_engine == "sparse":
            valid = st.edge_src >= 0
            created_dst_se = jnp.take(
                created_global, jnp.clip(st.edge_dst, 0, self.topo.n - 1),
                axis=-1) & valid[None, :]                    # [S, Em]
            push_se = jnp.take(
                created_global, jnp.clip(st.edge_src, 0, self.topo.n - 1),
                axis=-1) & valid[None, :]
        else:
            created_f = created_global.astype(self._cnt)
            created_dst_se = (created_f @ st.a_in_c) > 0.5   # [S, Em]
            push_se = (created_f @ st.a_src_c) > 0.5
        created_l = self._my_slice(created_global)           # [S, Nl]
        s = s._replace(
            recording=s.recording | created_dst_se,
            frozen=jnp.where(created_l, s.tokens[None, :], s.frozen),
            rem=jnp.where(created_l,
                          self._my_slice(st.in_degree[None, :]), s.rem),
            has_local=s.has_local | created_l,
            **window_update(s, created_dst_se, None, s.rec_cnt),
        )
        return self._push_markers_split(s, st, push_se)

    def _fold_err(self, s: ShardedState, erl) -> ShardedState:
        """Union the shard-local deferred error bits into the replicated
        sticky mask (one 9-bit psum). Callers accumulate into ``erl``
        through a phase / megatick block and fold at its boundary; the
        replicated ``s.error`` is the ONLY mask SPMD gating predicates
        may read, so deferral never de-syncs the shards."""
        return s._replace(error=s.error | self._por(erl))

    def _bulk_send(self, s: ShardedState, st: ShardedTopology,
                   amounts, erl):
        """amounts [Em] local (sends originate on this shard's sources).
        Returns (state, erl) — local error bits accumulate into ``erl``
        for the caller's boundary _fold_err instead of psumming here."""
        amounts = jnp.asarray(amounts, _i32)
        active = amounts > 0
        # debit senders with an exact integer segment sum over local edges
        # (every edge lives on its source's shard); pad edges carry amount 0.
        # The f32 twin guards the aggregate: a hub summing >2^31 would wrap
        # the i32 debit silently (and >=2^24 already breaks the later credit
        # matmul), so totals at the limit flag ERR_VALUE_OVERFLOW.
        base = lax.axis_index(self.axis) * self.nl
        src_l = jnp.clip(st.edge_src - base, 0, self.nl - 1)
        debits = jax.ops.segment_sum(amounts, src_l, num_segments=self.nl)
        debits_f = jax.ops.segment_sum(amounts.astype(_f32), src_l,
                                       num_segments=self.nl)
        tokens = s.tokens - debits
        err_local = (jnp.any(tokens < 0).astype(_i32) * ERR_TOKEN_UNDERFLOW
                     | (jnp.any(active & (s.q_len >= self.config.queue_capacity))
                        .astype(_i32) * ERR_QUEUE_OVERFLOW)
                     | (jnp.any(amounts >= F32_EXACT_LIMIT)
                        | jnp.any(debits_f >= F32_EXACT_LIMIT)
                        ).astype(_i32) * ERR_VALUE_OVERFLOW)
        s = s._replace(tokens=tokens)
        rts, key = self._draw_many(s.delay_key, s.time, active.shape)
        s, err = self._append_active(s._replace(delay_key=key),
                                     active, rts, amounts)
        return s, erl | err_local | err

    def _bulk_snapshots(self, s: ShardedState, st: ShardedTopology,
                        init_mask_n) -> ShardedState:
        """init_mask_n [N] replicated; ids in node-index order (the
        _bulk_snapshots contract, ops/tick.py)."""
        S = self.config.max_snapshots
        count = jnp.sum(init_mask_n, dtype=_i32)
        rank = jnp.cumsum(init_mask_n, dtype=_i32) - 1
        sid_n = s.next_sid + rank
        created = init_mask_n[None, :] & (
            sid_n[None, :] == jnp.arange(S, dtype=_i32)[:, None])  # [S, N]
        err = jnp.where(s.next_sid + count > S, ERR_SNAPSHOT_OVERFLOW, 0)
        s = s._replace(next_sid=s.next_sid + count,
                       started=s.started | jnp.any(created, axis=1),
                       error=s.error | err.astype(_i32))
        if self._sup:
            # remember initiators + arm deadlines (replicated math — the
            # created matrix is replicated, so every shard agrees)
            any_c = jnp.any(created, axis=-1)
            init_n = jnp.argmax(created, axis=-1).astype(_i32)
            s = s._replace(snap_initiator=jnp.where(any_c, init_n,
                                                    s.snap_initiator))
            if self.config.snapshot_timeout:
                s = s._replace(snap_deadline=jnp.where(
                    any_c, s.time + self.config.snapshot_timeout,
                    s.snap_deadline))
        if self._trace_on:
            # replicated operands only (created is replicated), so every
            # shard appends the identical event and the ring stays uniform
            s = trace_append_many(
                s, created, EV_SNAP_START,
                jnp.broadcast_to(jnp.arange(self.topo.n, dtype=_i32)[None, :],
                                 created.shape),
                jnp.broadcast_to(jnp.arange(S, dtype=_i32)[:, None],
                                 created.shape))
        return self._create_and_broadcast(s, st, created)

    def _inject_send_local(self, s: ShardedState, st: ShardedTopology,
                           eloc, amt, active, erl):
        """One script send op, masked: only the shard owning the edge debits
        and enqueues; every shard runs the same code so the SPMD schedules
        stay aligned. Returns (state, erl) — error bits accumulate for the
        caller's boundary _fold_err. Mirrors TickKernel._inject_send
        semantics (debit at send time, node.go:112-131)."""
        C = self.config.queue_capacity
        e = jnp.clip(eloc, 0, self.em - 1)
        amt_i = jnp.asarray(amt, _i32)
        base = lax.axis_index(self.axis) * self.nl
        src_l = jnp.clip(st.edge_src[e] - base, 0, self.nl - 1)
        a = jnp.asarray(active, _i32)
        err_local = (
            (active & (s.tokens[src_l] < amt_i)).astype(_i32) * ERR_TOKEN_UNDERFLOW
            | (active & (s.q_len[e] >= C)).astype(_i32) * ERR_QUEUE_OVERFLOW
            | (active & (amt_i >= F32_EXACT_LIMIT)).astype(_i32)
            * ERR_VALUE_OVERFLOW)
        rt, key = self._draw_many(s.delay_key, s.time, ())
        rt = jnp.asarray(rt, _i32)
        pos = (s.q_head[e] + s.q_len[e]) % C

        def sel(old, new):
            return jnp.where(active, new, old)

        # every index is in bounds by construction (src_l clipped, pos
        # taken mod C, e a live edge id), so the scatters may skip XLA's
        # out-of-bounds select
        return s._replace(
            tokens=s.tokens.at[src_l].add(-amt_i * a,
                                          mode="promise_in_bounds"),
            q_data=s.q_data.at[e, pos].set(sel(s.q_data[e, pos], amt_i),
                                           mode="promise_in_bounds"),
            q_meta=s.q_meta.at[e, pos].set(
                sel(s.q_meta[e, pos], pack_meta(rt, False)),
                mode="promise_in_bounds"),
            q_len=s.q_len.at[e].add(a, mode="promise_in_bounds"),
            tok_pushed=s.tok_pushed.at[e].add(a, mode="promise_in_bounds"),
            delay_key=key,
        ), erl | err_local | (
            (a & ((s.tok_pushed[e] >= self._key_limit)
                  | (rt >= RTIME_PACK_LIMIT))).astype(_i32)
            * ERR_VALUE_OVERFLOW)

    def _supervise(self, s: ShardedState, st: ShardedTopology) -> ShardedState:
        """The sharded snapshot supervisor (TickKernel._supervise's twin):
        daemon initiation, then the timeout scan — abort (shard-local
        plane clears driven by the replicated timed-out mask), retry
        re-initiation through the collective create+broadcast under a
        replicated cond, or ERR_SNAPSHOT_TIMEOUT on exhaustion. Every
        predicate is replicated, so the conds (whose true branches carry
        collectives) stay SPMD-uniform."""
        cfg = self.config
        S, n = cfg.max_snapshots, self.topo.n
        if cfg.snapshot_every:
            every = cfg.snapshot_every
            node = (s.time // every) % n
            fire = (s.time % every == 0) & (s.time > 0) & (s.next_sid < S)
            mask = fire & (jnp.arange(n, dtype=_i32) == node)
            s = lax.cond(fire,
                         lambda s: self._bulk_snapshots(s, st, mask),
                         lambda s: s, s)
        if not cfg.snapshot_timeout:
            return s
        timed_out = (s.started & ~s.snap_failed & (s.completed < n)
                     & (s.snap_deadline > 0) & (s.time >= s.snap_deadline))
        can_retry = timed_out & (s.snap_retries
                                 < jnp.int32(cfg.snapshot_retries))
        failed = timed_out & ~can_retry
        t_b = timed_out[:, None]
        new_retries = s.snap_retries + can_retry.astype(_i32)
        backoff = jnp.left_shift(jnp.int32(max(cfg.snapshot_timeout, 1)),
                                 jnp.minimum(new_retries, 4))
        s = s._replace(
            has_local=s.has_local & ~t_b,
            done_local=s.done_local & ~t_b,
            frozen=jnp.where(t_b, 0, s.frozen),
            rem=jnp.where(t_b, 0, s.rem),
            recording=s.recording & ~t_b,
            rec_start=jnp.where(t_b, jnp.zeros_like(s.rec_start),
                                s.rec_start),
            rec_end=jnp.where(t_b, jnp.zeros_like(s.rec_end), s.rec_end),
            completed=jnp.where(timed_out, 0, s.completed),
            m_pending=s.m_pending & ~t_b,
            snap_epoch=s.snap_epoch + timed_out.astype(_i32),
            snap_retries=new_retries,
            snap_failed=s.snap_failed | failed,
            snap_deadline=jnp.where(can_retry, s.time + backoff,
                                    jnp.where(failed, 0, s.snap_deadline)),
            error=s.error | jnp.where(jnp.any(failed),
                                      ERR_SNAPSHOT_TIMEOUT, 0).astype(_i32),
        )
        if self._trace_on:
            # replicated masks + initiators: uniform appends across shards
            init_n = jnp.clip(s.snap_initiator, 0, n - 1)
            slot = jnp.arange(S, dtype=_i32)
            s = trace_append_many(s, timed_out, EV_SUP_ABORT, init_n, slot)
            s = trace_append_many(s, can_retry, EV_SUP_RETRY, init_n, slot)
            s = trace_append_many(s, failed, EV_SUP_FAIL, init_n, slot)
        created = can_retry[:, None] & (
            jnp.arange(n, dtype=_i32)
            == jnp.clip(s.snap_initiator, 0, n - 1)[:, None])  # [S, N] rep
        return lax.cond(jnp.any(can_retry),
                        lambda s: self._create_and_broadcast(s, st, created),
                        lambda s: s, s)

    def _sparse_reduce_exchange(self, st: ShardedTopology, amt, mk_se):
        """The sparse engine's forward half: one fused [S+1, Em] payload
        (row 0 = token amounts, rows 1.. = marker-arrival counts) reduced
        into the combined segment space — local destinations first, then
        the packed per-neighbor boundary rows — with the O(E_local)
        cumsum segment machinery (TickKernel._segment_sums), then ONE
        lax.ppermute per ring distance d moving only the [S+1, H] cut
        rows, scattered into the local planes through the static
        recv_idx table (pad rows index Nl and drop). Integer adds only,
        so accumulation order cannot perturb the result: returns exactly
        the (credit [Nl], arrivals [S, Nl]) the dense psums produce."""
        from chandy_lamport_tpu.ops.tick import TickKernel

        nl, h, p = self.nl, self.halo, self.shards
        payload = jnp.concatenate(
            [amt[None, :], mk_se.astype(_i32)], axis=0)       # [S+1, Em]
        ordered = jnp.take(payload, st.seg_perm, axis=-1)
        segs = TickKernel._segment_sums(ordered, st.seg_lo, st.seg_hi)
        credit_l = segs[0, :nl]                               # [Nl]
        arrivals_l = segs[1:, :nl]                            # [S, Nl]
        if p > 1 and h:                                       # static elision
            out = segs[:, nl:nl + (p - 1) * h].reshape(-1, p - 1, h)
            for d in range(1, p):
                recv = lax.ppermute(
                    out[:, d - 1], self.axis,
                    perm=[(i, (i + d) % p) for i in range(p)])  # [S+1, H]
                idx = st.recv_idx[d - 1]
                credit_l = credit_l.at[idx].add(recv[0], mode="drop")
                arrivals_l = arrivals_l.at[:, idx].add(recv[1:], mode="drop")
        return credit_l, arrivals_l

    def _sparse_created_spread(self, st: ShardedTopology, created_l):
        """The reverse half: each shard owes its neighbors the created
        flags of exactly the rows it received credit for, so the SAME
        recv_idx table gathers the [S, H] outgoing block for distance d
        (pad rows read False) and the reversed ppermute returns it to
        the sender; dst_seg then reads every edge's destination flag out
        of [local flags ++ received blocks ++ one zero column] — the
        sparse stand-in for all_gather + the a_in_c matmul. The source
        spread needs no communication at all: every edge lives on its
        source's shard."""
        nl, h, p = self.nl, self.halo, self.shards
        blocks = [created_l]
        if p > 1 and h:
            for d in range(1, p):
                idx = st.recv_idx[d - 1]
                send = (jnp.take(created_l, jnp.minimum(idx, nl - 1),
                                 axis=-1)
                        & (idx < nl)[None, :])                # [S, H]
                blocks.append(lax.ppermute(
                    send, self.axis,
                    perm=[(i, (i - d) % p) for i in range(p)]))
        flags = jnp.concatenate(
            blocks + [jnp.zeros_like(created_l[:, :1])], axis=-1)
        created_dst_se = jnp.take(flags, st.dst_seg, axis=-1)  # [S, Em]
        base = lax.axis_index(self.axis) * nl
        src_l = jnp.clip(st.edge_src - base, 0, nl - 1)
        push_se = (jnp.take(created_l, src_l, axis=-1)
                   & (st.edge_src >= 0)[None, :])              # [S, Em]
        return created_dst_se, push_se

    def _sync_tick(self, s: ShardedState, st: ShardedTopology, erl):
        """The sync scheduler with the cross-shard steps as collectives
        (dense plane) or the boundary halo exchange (sparse). Returns
        (state, erl): local error bits defer to the caller's boundary
        _fold_err."""
        cfg = self.config
        C, S, M = cfg.queue_capacity, cfg.max_snapshots, cfg.max_recorded
        time = s.time + 1
        s = s._replace(time=time)
        if self._sup:
            s = self._supervise(s, st)

        # channel fronts under the split representation (mirrors
        # TickKernel._sync_tick): token head via queue_engine-addressed
        # reads (_head_fields: O(Em) packed-plane gathers, or the legacy
        # one-hot reductions), marker front = min-seq pending plane entry;
        # the merged FIFO's front is whichever has the smaller sequence
        # number. All per-edge state is local to this shard — no
        # collective in the front selection.
        BIG = jnp.int32(jnp.iinfo(jnp.int32).max)
        head_rt, head_amt = self._head_fields(s)
        tok_live = s.q_len > 0
        tok_popped = s.tok_pushed - s.q_len
        m_key_live = jnp.where(s.m_pending, s.m_key, BIG)        # [S, Em]
        m_front_key = jnp.min(m_key_live, axis=0)                # [Em]
        m_is_front = s.m_pending & (m_key_live == m_front_key[None, :])
        m_front_rt = jnp.sum(jnp.where(m_is_front, s.m_rtime, 0),
                             axis=0, dtype=_i32)
        front_is_marker = (m_front_key < BIG) & (
            m_front_key // self._keymult <= tok_popped)
        front_rt = jnp.where(front_is_marker, m_front_rt, head_rt)
        elig = (tok_live | front_is_marker) & (front_rt <= time)
        elig_i = elig.astype(_i32)
        before = jnp.cumsum(elig_i) - elig_i
        deliver = elig & (before == before[st.src_first])
        tok = deliver & ~front_is_marker
        mk = deliver & front_is_marker
        s = s._replace(q_head=(s.q_head + tok) % C,
                       q_len=s.q_len - tok.astype(_i32))

        # the consumed marker per delivering edge is its front pending
        # entry (plane index == snapshot id); computed up front because
        # the sparse engine fuses the marker-arrival rows into the credit
        # exchange payload
        mk_se = m_is_front & mk[None, :]
        amt = jnp.where(tok, head_amt, 0)
        if self.comm_engine == "sparse":
            # one fused segment reduction + boundary-row halo exchange
            credit_l, arrivals_l = self._sparse_reduce_exchange(
                st, amt, mk_se)
            # the i32 segment sums are exact at any magnitude, but the
            # guard must flag the SAME global condition as the unsharded
            # kernel's f32-exactness check — per-node credit is identical
            # either way, so testing the local slice and letting the
            # boundary _fold_err union it reproduces the dense verdict
            inexact = (jnp.any(amt >= F32_EXACT_LIMIT)
                       | jnp.any(credit_l >= F32_EXACT_LIMIT)).astype(_i32)
            s = s._replace(tokens=s.tokens + credit_l)
        else:
            # tokens: cross-shard credit via psum of per-node partials;
            # f32 reductions exact only below 2^24 (same guard as the
            # unsharded sync tick); psum makes the check see the global
            # credit
            credit_n = lax.psum(st.a_in @ amt.astype(_f32), self.axis)
            inexact = (jnp.any(amt >= F32_EXACT_LIMIT)
                       | jnp.any(credit_n >= F32_EXACT_LIMIT)).astype(_i32)
            s = s._replace(
                tokens=s.tokens
                + self._my_slice(credit_n[None, :])[0].astype(_i32))
        erl = erl | inexact * ERR_VALUE_OVERFLOW
        # shared-log append, shard-local (one definition with the dense
        # kernel: ops/tick.log_append); error bits defer to the fold
        log, cnt, err_bits = log_append(
            s.log_amt, s.rec_cnt, s.min_prot, s.recording,
            tok, amt, self._rec_dtype, self._rec_limit, M)
        s = s._replace(log_amt=log, rec_cnt=cnt)
        erl = erl | err_bits

        s = s._replace(m_pending=s.m_pending & ~mk_se)
        had_l = s.has_local
        if self.comm_engine == "sparse":
            created_l = (arrivals_l > 0) & ~had_l
            created_dst_se, push_se = self._sparse_created_spread(
                st, created_l)
        else:
            # arrivals via psum, creations via all_gather
            arrivals_n = lax.psum(mk_se.astype(self._cnt) @ st.a_in_c.T,
                                  self.axis).astype(_i32)      # [S, N]
            arrivals_l = self._my_slice(arrivals_n)            # [S, Nl]
            created_l = (arrivals_l > 0) & ~had_l
            created_n = lax.all_gather(created_l, self.axis, axis=1,
                                       tiled=True)             # [S, N]
            created_f = created_n.astype(self._cnt)
            created_dst_se = (created_f @ st.a_in_c) > 0.5
            push_se = (created_f @ st.a_src_c) > 0.5
        stopped = mk_se & s.recording                           # [S, Em]
        started_se = created_dst_se & ~mk_se
        s = s._replace(
            recording=(s.recording | created_dst_se) & ~mk_se,
            frozen=jnp.where(created_l, s.tokens[None, :], s.frozen),
            rem=jnp.where(created_l,
                          self._my_slice(st.in_degree[None, :]) - arrivals_l,
                          s.rem - jnp.where(had_l, arrivals_l, 0)),
            has_local=had_l | created_l,
            **window_update(s, started_se, stopped, s.rec_cnt),
        )
        s = self._push_markers_split(s, st, push_se)

        fire = s.has_local & (s.rem == 0) & ~s.done_local
        fired = lax.psum(jnp.sum(fire, axis=-1, dtype=_i32), self.axis)  # [S]
        completed = s.completed + fired
        # completion-tick stamp (recovery-line age metric) — replicated,
        # every shard computes the same value
        newly = (s.started & (completed >= self.topo.n)
                 & (s.snap_done_time < 0))
        if self._trace_on:
            # one GLOBAL completion event per snapshot (the per-node fire
            # mask is shard-local and cannot touch the replicated ring);
            # actor = the remembered initiator when the supervisor runs,
            # node 0 otherwise
            s = trace_append_many(
                s, newly, EV_SNAP_END,
                jnp.clip(s.snap_initiator, 0, self.topo.n - 1),
                jnp.arange(S, dtype=_i32))
        return s._replace(done_local=s.done_local | fire,
                          completed=completed,
                          snap_done_time=jnp.where(newly, s.time,
                                                   s.snap_done_time)), erl

    # -- program execution -------------------------------------------------

    def _pending(self, s: ShardedState):
        # supervisor-failed slots (ERR_SNAPSHOT_TIMEOUT) no longer gate the
        # drain — same exclusion as TickKernel._pending
        return jnp.any(s.started & ~s.snap_failed
                       & (s.completed < self.topo.n))

    def _check_conservation(self, s: ShardedState) -> ShardedState:
        """The sharded twin of BatchedRunner._check_conservation: one psum
        of per-shard (balances + in-flight ring tokens) vs the initial
        total; pad edges have q_len 0 so they contribute nothing."""
        from chandy_lamport_tpu.utils.metrics import _occupied

        occ = _occupied(s, self.config)
        local = jnp.sum(s.tokens) + jnp.sum(jnp.where(occ, s.q_data, 0))
        total = lax.psum(local, self.axis)
        return s._replace(error=s.error | jnp.where(
            total != int(self.topo.tokens0.sum()),
            ERR_CONSERVATION, 0).astype(_i32))

    def _unwrap(self, tree, specs):
        """Inside shard_map the sharded leading axis arrives as a singleton;
        strip it so the kernel sees per-shard logical shapes."""
        sharded = P(self.axis)
        return jax.tree_util.tree_map(
            lambda x, sp: x[0] if sp == sharded else x, tree, specs,
            is_leaf=lambda x: x is None)

    def _wrap(self, tree, specs):
        sharded = P(self.axis)
        return jax.tree_util.tree_map(
            lambda x, sp: x[None] if sp == sharded else x, tree, specs,
            is_leaf=lambda x: x is None)

    def _storm_scan(self, s: ShardedState, st: ShardedTopology,
                    amounts, snap) -> ShardedState:
        """Scan the storm phases with the conservation-check cadence, then
        drain + final check — ONE definition for the single-mesh and
        data-batched bodies so their invariant coverage cannot drift."""
        k = self.check_every

        def phase(s, xs):
            if self.quarantine:
                # replicated predicate -> uniform cond across shards (the
                # same discipline as the conservation-check cond below)
                s = lax.cond(s.error == 0,
                             lambda s: self._storm_phase(s, st, xs[0],
                                                         xs[1]),
                             lambda s: s, s)
            else:
                s = self._storm_phase(s, st, xs[0], xs[1])
            if k:
                # the predicate is replicated, so the cond (whose true
                # branch psums) stays uniform across shards
                s = lax.cond((xs[2] + 1) % k == 0,
                             self._check_conservation, lambda s: s, s)
            return s, None

        idx = jnp.arange(amounts.shape[0], dtype=_i32)
        s, _ = lax.scan(phase, s, (amounts, snap, idx))
        s = self._drain_flush(s, st)
        return self._check_conservation(s) if k else s

    def _run_storm_body(self, s: ShardedState, st: ShardedTopology,
                        program) -> ShardedState:
        wrap_specs = self._state_specs
        s = self._unwrap(s, wrap_specs)
        st = self._unwrap(st, self._topo_specs)
        amounts, snap = program  # [T, 1, Em] shard slice, [T, J] replicated
        amounts = amounts[:, 0, :]
        return self._wrap(self._storm_scan(s, st, amounts, snap), wrap_specs)

    def _storm_phase(self, s: ShardedState, st: ShardedTopology,
                     amts, snaps) -> ShardedState:
        """One storm phase: bulk sends + scheduled snapshot initiations +
        one sync tick (shared by the single-instance and batched bodies).
        Local error bits from all three steps fold in ONE boundary psum
        (was one per error site)."""
        erl = jnp.int32(0)
        s, erl = self._bulk_send(s, st, amts, erl)
        init_mask = jnp.any(
            jnp.arange(self.topo.n, dtype=_i32)[None, :]
            == snaps[:, None], axis=0)
        s = self._bulk_snapshots(s, st, init_mask)
        s, erl = self._sync_tick(s, st, erl)
        return self._fold_err(s, erl)

    def _drain_flush(self, s: ShardedState, st: ShardedTopology) -> ShardedState:
        """Tick until every started snapshot completes (budgeted), then
        max_delay+1 flush ticks (test_common.go:124-137). With quarantine
        on, the replicated error bits halt the instance like completion
        (no ERR_TICK_LIMIT charge for quarantine-denied ticks)."""
        limit = jnp.asarray(s.time + self.config.max_ticks, _i32)

        def gate(s):
            g = self._pending(s) & (s.time < limit)
            if self.quarantine:
                g = g & (s.error == 0)
            return g

        def live_anywhere(s):
            # mesh-global OR of the per-lane gate. In the combined
            # data x graph mode the lanes drain for different tick counts
            # (per-lane delay streams), but ppermute — unlike the
            # subgrouped psum/all_gather — rendezvouses across the WHOLE
            # device set on the CPU backend, so every device must run the
            # same number of drain blocks or the sparse engine deadlocks.
            # Early-finished lanes are frozen by the per-tick gate inside
            # block() (cond -> select under the lane vmap), so the global
            # trip count changes no state bit.
            return lax.psum(gate(s).astype(_i32), self.mesh.axis_names) > 0

        def block(s):
            # the graphshard MEGATICK: K cond-gated ticks per while body
            # via an in-shard scan, so host dispatch and the deferred
            # error fold amortize to the K boundary. Every scanned tick
            # re-evaluates the live (replicated) drain gate, so K never
            # overshoots — bit-identical for any K. Under quarantine the
            # gate reads the replicated error mask, stale by < K ticks
            # for ERRORING runs only (__init__ docstring).
            def one(carry, _):
                return lax.cond(gate(carry[0]),
                                lambda c: self._sync_tick(c[0], st, c[1]),
                                lambda c: c, carry), None

            (s, erl), _ = lax.scan(one, (s, jnp.int32(0)), None,
                                   length=self.megatick)
            return self._fold_err(s, erl)

        s = lax.while_loop(live_anywhere, block, s)
        budget_blown = self._pending(s)
        if self.quarantine:
            budget_blown = budget_blown & (s.error == 0)
        s = s._replace(error=s.error | jnp.where(
            budget_blown, ERR_TICK_LIMIT, 0).astype(_i32))

        def flush(_, s):
            erl0 = jnp.int32(0)
            if self.quarantine:
                s, erl = lax.cond(
                    s.error == 0,
                    lambda c: self._sync_tick(c[0], st, c[1]),
                    lambda c: c, (s, erl0))
            else:
                s, erl = self._sync_tick(s, st, erl0)
            return self._fold_err(s, erl)

        return lax.fori_loop(0, self.config.max_delay + 1, flush, s)

    def _run_script_body(self, s: ShardedState, st: ShardedTopology,
                         script: ShardedScript) -> ShardedState:
        """Event-script execution: per phase, apply up to K ops in script
        order, then tick. Both op kinds run every slot as masked dense
        updates (a no-op slot still executes its collectives), keeping one
        uniform SPMD schedule across shards."""
        from chandy_lamport_tpu.parallel.batch import OP_SEND, OP_SNAPSHOT

        wrap_specs = self._state_specs
        s = self._unwrap(s, wrap_specs)
        st = self._unwrap(st, self._topo_specs)
        my = lax.axis_index(self.axis)
        nn = jnp.arange(self.topo.n, dtype=_i32)

        def phase(s, xs):
            kind, shard, loc, arg, do_tick = xs

            def op(j, carry):
                s, erl = carry
                send = kind[j] == OP_SEND
                s, erl = self._inject_send_local(s, st, loc[j], arg[j],
                                                 send & (shard[j] == my),
                                                 erl)
                snap_mask = (kind[j] == OP_SNAPSHOT) & (nn == loc[j])
                return self._bulk_snapshots(s, st, snap_mask), erl

            s, erl = lax.fori_loop(0, kind.shape[0], op, (s, jnp.int32(0)))
            s = self._fold_err(s, erl)

            # do_tick is a replicated COUNT (batch.compile_events carries
            # multi-tick stretches as counts now), so the cond branch and
            # its tick loop (which contain collectives) are uniform across
            # shards; per-tick error bits fold once after the stretch
            def ticks(s):
                s, erl = lax.fori_loop(
                    0, do_tick,
                    lambda _, c: self._sync_tick(c[0], st, c[1]),
                    (s, jnp.int32(0)))
                return self._fold_err(s, erl)

            return lax.cond(do_tick != 0, ticks, lambda s: s, s), None

        s, _ = lax.scan(phase, s, tuple(script))
        s = self._drain_flush(s, st)
        if self.check_every:
            s = self._check_conservation(s)
        return self._wrap(s, wrap_specs)

    def run_script(self, state: ShardedState, events) -> ShardedState:
        """Execute an event script (reference .events semantics under the
        sync scheduler) + drain + flush, SPMD over the graph mesh. With
        fixed_delay this is bit-comparable to the unsharded sync backend
        (tests/test_graphshard_script.py)."""
        script = self.compile_script(events)
        stopo = self.stopo_device()
        with armed(self.guards):
            return self._run_script(state, stopo, script)

    def run_storm(self, state: ShardedState, amounts: np.ndarray,
                  snap: np.ndarray) -> ShardedState:
        """amounts [T, E] (global edge order), snap [T, J]: runs the full
        program + drain + flush SPMD over the graph mesh. The dispatch
        runs armed when ``guards`` is set (utils/guards): program shards
        are device_put by shard_program/stopo_device BEFORE arming, so a
        steady storm cadence is transfer- and retrace-silent."""
        amounts_s, snap_r = self.shard_program(np.asarray(amounts),
                                               np.asarray(snap))
        stopo = self.stopo_device()
        with armed(self.guards):
            return self._run(state, stopo, (amounts_s, snap_r))

    # -- combined data x graph mode: B lanes of giant sharded instances ----

    def init_batch(self, batch: int, data_axis: str = "data") -> ShardedState:
        """Batched state: every leaf gains a leading lane axis sharded over
        ``data_axis``; graph-sharded leaves keep their shard axis second
        ([B, P, ...] with spec P(data, graph)). Per-(lane, shard) delay
        keys."""
        single = jax.device_get(self.init_state())
        p = self.shards
        base = jax.random.PRNGKey(self.seed)
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
            jnp.arange(batch * p, dtype=jnp.uint32)).reshape(batch, p, -1)
        batched = jax.tree_util.tree_map(
            lambda x: np.broadcast_to(np.asarray(x),
                                      (batch,) + np.shape(x)).copy(),
            single._replace(delay_key=np.zeros((p, 1), np.uint32)))
        batched = batched._replace(delay_key=keys)
        return jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(
                jnp.asarray(x),
                NamedSharding(self.mesh, self._batched_spec(sp, data_axis))),
            batched, self._state_specs)

    @staticmethod
    def _batched_spec(sp, data_axis):
        return (P(data_axis, *sp) if sp else P(data_axis))

    def run_storm_batched(self, state: ShardedState, amounts: np.ndarray,
                          snap: np.ndarray,
                          data_axis: str = "data") -> ShardedState:
        """B independent lanes, each a full graph-sharded instance: the
        combined data x graph 2-D-mesh mode. The lane axis shards over
        ``data_axis`` (zero cross-lane communication); within each lane the
        per-tick collectives ride the ``graph`` axis exactly as in
        run_storm."""
        if data_axis not in self._run_batched_cache:
            from functools import partial

            state_specs = jax.tree_util.tree_map(
                lambda sp: self._batched_spec(sp, data_axis),
                self._state_specs)
            from chandy_lamport_tpu.utils.shardmap import shard_map

            smap = partial(shard_map, mesh=self.mesh)
            self._run_batched_cache[data_axis] = jax.jit(smap(
                self._run_storm_body_batched,
                in_specs=(state_specs, self._topo_specs,
                          (P(None, self.axis), P())),
                out_specs=state_specs))
        amounts_s, snap_r = self.shard_program(np.asarray(amounts),
                                               np.asarray(snap))
        return self._run_batched_cache[data_axis](
            state, self.stopo_device(), (amounts_s, snap_r))

    def _run_storm_body_batched(self, s: ShardedState, st: ShardedTopology,
                                program) -> ShardedState:
        sharded = P(self.axis)
        st = self._unwrap(st, self._topo_specs)
        amounts, snap = program          # [T, 1, Em] local slice, [T, J]
        amounts = amounts[:, 0, :]

        # strip the graph-shard singleton (now axis 1, after the local lane
        # block) so the per-lane kernel sees per-shard logical shapes
        s = jax.tree_util.tree_map(
            lambda x, sp: x[:, 0] if sp == sharded else x,
            s, self._state_specs)

        def one_lane(s):
            return self._storm_scan(s, st, amounts, snap)

        s = jax.vmap(one_lane)(s)
        return jax.tree_util.tree_map(
            lambda x, sp: x[:, None] if sp == sharded else x,
            s, self._state_specs)

    # -- metrics / profiling surfaces --------------------------------------

    def comm_model(self) -> dict:
        """Analytic per-shard per-tick cross-shard bytes, dense vs sparse
        (utils/metrics.comm_bytes_model), instantiated with this
        partition's measured cut (parallel/mesh.boundary_tables)."""
        from chandy_lamport_tpu.utils.metrics import comm_bytes_model

        return comm_bytes_model(
            self.topo.n, self.config.max_snapshots, self.shards, self.halo,
            cut_edges=self._bt.cut_edges, cut_rows=self._bt.cut_rows,
            count_bytes=jnp.dtype(self._cnt).itemsize)

    def summarize(self, final: ShardedState) -> dict:
        """Host-side result digest (BatchedRunner.summarize's sharded twin,
        single instance or a run_storm_batched batch): error decode,
        snapshot lifecycle counts, and the comm engine's byte model."""
        from chandy_lamport_tpu.core.state import decode_error_bits
        from chandy_lamport_tpu.utils.metrics import or_reduce

        h = jax.device_get(final)
        bits = int(or_reduce(jnp.asarray(h.error).reshape(-1)))
        started = np.asarray(h.started)
        completed = np.asarray(h.completed)
        return {
            "nodes": self.topo.n,
            "edges": self.topo.e,
            "shards": self.shards,
            "comm_engine": self.comm_engine,
            "queue_engine": self.queue_engine,
            "kernel_engine": self.kernel_engine,
            "fused_tick": self.fused,
            "megatick": self.megatick,
            "total_ticks": int(np.sum(np.asarray(h.time))),
            "error_bits": bits,
            "errors_decoded": decode_error_bits(bits),
            "snapshots_started": int(np.sum(started)),
            "snapshots_completed": int(
                np.sum(started & (completed >= self.topo.n))),
            "comm_bytes_model": self.comm_model(),
        }

    def jit_tick(self):
        """The compiled single-sync-tick dispatch (state, stopo_device())
        -> state — the unit tools/profile_tick.py's "graphshard comm"
        section times for the dense/sparse A/B. Deferred error bits fold
        at the tick boundary so the result is a complete, self-consistent
        state."""
        if not hasattr(self, "_jit_tick"):
            from functools import partial

            from chandy_lamport_tpu.utils.shardmap import shard_map

            def body(s, st):
                s = self._unwrap(s, self._state_specs)
                st = self._unwrap(st, self._topo_specs)
                s, erl = self._sync_tick(s, st, jnp.int32(0))
                return self._wrap(self._fold_err(s, erl), self._state_specs)

            smap = partial(shard_map, mesh=self.mesh)
            self._jit_tick = jax.jit(smap(
                body, in_specs=(self._state_specs, self._topo_specs),
                out_specs=self._state_specs))
        return self._jit_tick

    def gather_dense(self, final: ShardedState):
        """De-shard a finished ShardedState into a host DenseState (global
        node/edge order) — the reference's CollectSnapshot gather
        (sim.go:134-173) as pure numpy reindexing. The result feeds
        core.state.decode_snapshot and differential comparisons against the
        unsharded backends."""
        from chandy_lamport_tpu.core.state import DenseState

        h = jax.device_get(final)
        p, es, el = self.shards, self.edge_shard, self.edge_local

        def nodes(x):   # [P, .., Nl] -> [.., N]
            return np.concatenate([x[i] for i in range(p)], axis=-1)

        def edges(x):   # [P, Em, ...] -> [E, ...]
            return np.asarray(x)[es, el]

        def slot_edges(x):  # [P, S, Em] -> [S, E]
            return np.moveaxis(np.asarray(x)[es, :, el], 1, 0)

        def log_edges(x):  # [P, L, Em] -> [L, E]
            return np.moveaxis(np.asarray(x)[es, :, el], 1, 0)

        return DenseState(
            time=np.asarray(h.time),
            tokens=nodes(h.tokens),
            # the sharded runner is split-only: the ring never holds
            # markers, so the packed q_meta marker bits are all 0 — the
            # reassembled plane carries straight over
            q_meta=edges(h.q_meta),
            q_data=edges(h.q_data),
            q_head=edges(h.q_head),
            q_len=edges(h.q_len),
            tok_pushed=edges(h.tok_pushed),
            mk_cnt=edges(h.mk_cnt),
            m_pending=slot_edges(h.m_pending),
            m_rtime=slot_edges(h.m_rtime),
            m_key=slot_edges(h.m_key),
            next_sid=np.asarray(h.next_sid),
            started=np.asarray(h.started),
            has_local=nodes(h.has_local),
            frozen=nodes(h.frozen),
            rem=nodes(h.rem),
            done_local=nodes(h.done_local),
            recording=slot_edges(h.recording),
            rec_cnt=edges(h.rec_cnt),
            min_prot=edges(h.min_prot),
            log_amt=log_edges(h.log_amt),
            rec_start=slot_edges(h.rec_start),
            rec_end=slot_edges(h.rec_end),
            completed=np.asarray(h.completed),
            delay_state=(),
            # the sharded runner carries no fault adversary (its class
            # docstring); the reassembled dense state is fault-clean
            fault_key=np.uint32(0),
            fault_skew=np.int32(0),
            fault_counts=np.zeros(7, np.int32),
            # supervisor leaves carry over replicated; the split
            # representation clears pending planes on abort, so no stale
            # markers can exist to tally
            snap_epoch=np.asarray(h.snap_epoch),
            snap_deadline=np.asarray(h.snap_deadline),
            snap_retries=np.asarray(h.snap_retries),
            snap_initiator=np.asarray(h.snap_initiator),
            snap_failed=np.asarray(h.snap_failed),
            snap_done_time=np.asarray(h.snap_done_time),
            stale_markers=np.int32(0),
            # the replicated flight-recorder ring carries straight over
            # (global protocol events only — the ShardedState docstring)
            tr_meta=np.asarray(h.tr_meta),
            tr_data=np.asarray(h.tr_data),
            tr_tick=np.asarray(h.tr_tick),
            tr_count=np.asarray(h.tr_count),
            tr_on=np.asarray(h.tr_on),
            # the sharded runner simulates one instance end to end — no job
            # streaming; reassemble with the idle-lane defaults
            job_id=np.int32(-1),
            prog_cursor=np.int32(0),
            admit_tick=np.int32(0),
            # no memo plane on the sharded runner either
            sig=np.uint32(0),
            error=np.asarray(h.error),
        )

    def stopo_device(self) -> ShardedTopology:
        if not hasattr(self, "_stopo_dev"):
            self._stopo_dev = jax.tree_util.tree_map(
                lambda x, sp: jax.device_put(x, NamedSharding(self.mesh, sp)),
                self.stopo, self._topo_specs)
        return self._stopo_dev
