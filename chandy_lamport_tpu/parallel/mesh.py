"""Device mesh + sharding for batched simulation — the distributed backend.

The reference's "network" is simulated in-process and its only real
concurrency is goroutine fan-out at collection (SURVEY.md §2.5); the
TPU-native equivalent of its scale-out story is SPMD over a
``jax.sharding.Mesh``:

  - the **instance axis** (leading batch dim of every DenseState leaf) shards
    over the ``"data"`` mesh axis — instances are embarrassingly parallel, so
    the steady state needs zero communication and collectives appear only in
    result aggregation (``BatchedRunner.summarize`` reductions lower to
    psum/all-reduce over ICI within a slice, DCN across slices under the
    standard JAX multi-host runtime);
  - giant single graphs (node/edge axes too big for one device) are the
    tensor-parallel analogue — implemented in ``parallel/graphshard.py``:
    node/edge state sharded over a ``"graph"`` mesh axis with psum/all_gather
    collectives per tick, bit-equal to the unsharded sync scheduler.

Everything here works identically on a real TPU slice and on the CPU
``--xla_force_host_platform_device_count`` virtual mesh the tests use.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chandy_lamport_tpu.core.state import DenseState


def instance_mesh(n_devices: Optional[int] = None,
                  axis_name: str = "data") -> Mesh:
    """1-D mesh over the first n devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(state: DenseState, mesh: Mesh,
                axis_name: str = "data") -> DenseState:
    """Place a batched DenseState with its leading instance axis sharded over
    the mesh. Every leaf (including per-lane delay PRNG state) carries the
    batch axis first, so one PartitionSpec covers the whole pytree; jit'd
    kernels then run SPMD with no resharding."""
    spec = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, spec), state)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree (e.g. compiled ScriptOps) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), tree)
