"""Device mesh + sharding for batched simulation — the distributed backend.

The reference's "network" is simulated in-process and its only real
concurrency is goroutine fan-out at collection (SURVEY.md §2.5); the
TPU-native equivalent of its scale-out story is SPMD over a
``jax.sharding.Mesh``:

  - the **instance axis** (leading batch dim of every DenseState leaf) shards
    over the ``"data"`` mesh axis — instances are embarrassingly parallel, so
    the steady state needs zero communication and collectives appear only in
    result aggregation (``BatchedRunner.summarize`` reductions lower to
    psum/all-reduce over ICI within a slice, DCN across slices under the
    standard JAX multi-host runtime);
  - giant single graphs (node/edge axes too big for one device) are the
    tensor-parallel analogue — implemented in ``parallel/graphshard.py``:
    node/edge state sharded over a ``"graph"`` mesh axis with psum/all_gather
    collectives per tick, bit-equal to the unsharded sync scheduler.

Everything here works identically on a real TPU slice and on the CPU
``--xla_force_host_platform_device_count`` virtual mesh the tests use.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from chandy_lamport_tpu.core.state import DenseState


class BoundaryTables(NamedTuple):
    """Partition-time constants for the graph-sharded runner's sparse halo
    exchange (parallel/graphshard comm_engine="sparse"): everything the
    in-tick exchange needs, precomputed from the contiguous-block node
    partition so the shard_map body does only O(E_local) segment sums,
    P-1 boundary-row ppermutes, and static-index scatters.

    Layout. For an ordered shard pair (p, q) let B[p][q] be the sorted set
    of nodes OWNED by q that some edge on p targets — the rows p must send
    q each tick. ``halo`` H is the max |B[p][q]| over all pairs (one static
    pad width for every ppermute payload); R = (P-1)*H. Shard p's combined
    segment space has Nl + R + 1 slots: local destinations [0, Nl), then
    P-1 neighbor blocks of H rows ordered by ring distance d (block d-1
    holds B[p][(p+d) % P]), then one trash slot for pad edges. ``dst_seg``
    maps each local edge to its slot; the SAME index doubles as the read
    position in the created-flags concat (local flags ++ received blocks
    ++ one zero column), because the reverse exchange delivers block d-1
    from shard (p+d) % P.

      dst_seg   i32 [P, Em]       combined segment / flags index per edge
      seg_perm  i32 [P, Em]       stable permutation into segment order
      seg_lo    i32 [P, Nl+R+1]   segment bounds in the permuted order
      seg_hi    i32 [P, Nl+R+1]
      recv_idx  i32 [P, P-1, H]   step d: local node slots of B[(p-d)%P][p]
                                  (pad rows = Nl, dropped by the scatter);
                                  the same table is the GATHER list for the
                                  reverse created-flags send — the rows p
                                  receives credit for are exactly the rows
                                  whose flags p owes back
      halo      int               H (0 = zero-cut partition, no exchange)
      cut_edges int               edges whose destination is remote
      cut_rows  int               sum of |B[p][q]| over all pairs
    """

    dst_seg: np.ndarray
    seg_perm: np.ndarray
    seg_lo: np.ndarray
    seg_hi: np.ndarray
    recv_idx: np.ndarray
    halo: int
    cut_edges: int
    cut_rows: int


def boundary_tables(edge_src: np.ndarray, edge_dst: np.ndarray,
                    shards: int, nl: int) -> BoundaryTables:
    """Build the sparse-exchange tables from the per-shard padded edge
    arrays ([P, Em] global node ids, -1 pads) of a contiguous-block
    partition (node i -> shard i // nl)."""
    p_, em = edge_src.shape
    # B[p][q] per ring distance d: sorted unique remote destinations
    pair = {}
    for p in range(p_):
        dst = edge_dst[p]
        valid = dst >= 0
        owner = np.where(valid, dst // max(nl, 1), p)
        for d in range(1, p_):
            q = (p + d) % p_
            pair[(p, d)] = np.unique(dst[valid & (owner == q)])
    halo = max((len(v) for v in pair.values()), default=0)
    r = (p_ - 1) * halo
    nseg = nl + r + 1
    dst_seg = np.full((p_, em), nl + r, np.int32)
    seg_perm = np.zeros((p_, em), np.int32)
    seg_lo = np.zeros((p_, nseg), np.int32)
    seg_hi = np.zeros((p_, nseg), np.int32)
    recv_idx = np.full((p_, max(p_ - 1, 0), halo), nl, np.int32)
    cut_edges = 0
    for p in range(p_):
        dst = edge_dst[p]
        valid = dst >= 0
        owner = np.where(valid, dst // max(nl, 1), p)
        seg = np.full(em, nl + r, np.int64)
        local = valid & (owner == p)
        seg[local] = dst[local] - p * nl
        for d in range(1, p_):
            q = (p + d) % p_
            remote = valid & (owner == q)
            cut_edges += int(remote.sum())
            seg[remote] = (nl + (d - 1) * halo
                           + np.searchsorted(pair[(p, d)], dst[remote]))
            # receive side of forward step d: the block arriving from
            # shard (p-d)%P carries that shard's rows for p's nodes
            src_shard = (p - d) % p_
            rows = pair[(src_shard, d)]
            recv_idx[p, d - 1, :len(rows)] = rows - p * nl
        order = np.argsort(seg, kind="stable")
        seg_perm[p] = order.astype(np.int32)
        bounds = np.concatenate(
            [[0], np.cumsum(np.bincount(seg, minlength=nseg))])
        seg_lo[p] = bounds[:-1].astype(np.int32)
        seg_hi[p] = bounds[1:].astype(np.int32)
        dst_seg[p] = seg.astype(np.int32)
    return BoundaryTables(
        dst_seg=dst_seg, seg_perm=seg_perm, seg_lo=seg_lo, seg_hi=seg_hi,
        recv_idx=recv_idx, halo=int(halo), cut_edges=cut_edges,
        cut_rows=sum(len(v) for v in pair.values()))


def instance_mesh(n_devices: Optional[int] = None,
                  axis_name: str = "data") -> Mesh:
    """1-D mesh over the first n devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_batch(state: DenseState, mesh: Mesh,
                axis_name: str = "data") -> DenseState:
    """Place a batched DenseState with its leading instance axis sharded over
    the mesh. Every leaf (including per-lane delay PRNG state) carries the
    batch axis first, so one PartitionSpec covers the whole pytree; jit'd
    kernels then run SPMD with no resharding."""
    spec = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, spec), state)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree (e.g. compiled ScriptOps) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), tree)
