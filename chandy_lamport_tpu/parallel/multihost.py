"""Multi-host (multi-slice / DCN) execution support.

The reference is strictly single-process (SURVEY.md §2.4 — its only
"distributed backend" is in-process goroutines); the TPU framework's scale
story crosses hosts: a v5e pod slice gives each host a process and 4-8 local
chips, slices connect over DCN, and JAX's multi-controller runtime makes
``jax.devices()`` span all of them after ``jax.distributed.initialize``.

How the framework's axes map onto that fabric:

  - **instance axis (data parallel)** — embarrassingly parallel; shard it
    across EVERYTHING (all hosts, all slices). Cross-device traffic is zero
    in steady state and one psum at metric collection, so DCN's lower
    bandwidth vs ICI is irrelevant. This is the intended multi-host scaling
    path for 1M-instance runs (BASELINE.md config 5).
  - **graph axis (the TP analogue, parallel/graphshard.py)** — per-tick
    psum/all_gather traffic; keep it INSIDE a slice so collectives ride ICI.
    On a 2-D (data x graph) mesh put ``data`` outermost (across
    hosts/slices) and ``graph`` innermost (within a slice) — exactly the
    hybrid-mesh recipe for DCN-connected slices.

Usage (one process per host, e.g. under SLURM/GKE):

    from chandy_lamport_tpu.parallel import multihost
    multihost.initialize()                 # env-driven; no-op single-process
    mesh = multihost.hybrid_mesh(graph=4)  # data spans hosts, graph intra-slice

Everything degrades gracefully to single-process: ``initialize()`` without a
coordinator is a no-op, and ``hybrid_mesh`` falls back to all local devices.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Bring up JAX's multi-controller runtime (one call per host process,
    before any backend use). Arguments default from the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, or the
    cluster auto-detection jax.distributed supports natively). Returns True
    if distributed mode was initialized, False for the single-process
    no-op."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False  # single-process: nothing to do
    import jax

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def hybrid_mesh(graph: int = 1, data_axis: str = "data",
                graph_axis: str = "graph"):
    """2-D (data x graph) mesh over ALL devices (all hosts after
    initialize()): ``graph`` is the innermost axis so its per-tick
    collectives stay on ICI within a host/slice; ``data`` spans the rest of
    the fabric including DCN. ``graph`` must divide the per-process device
    count so no graph group crosses a process boundary."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    local = len(jax.local_devices())
    if graph < 1 or len(devs) % graph:
        raise ValueError(f"graph={graph} must divide {len(devs)} devices")
    if local % graph:
        raise ValueError(
            f"graph={graph} must divide the {local} per-process devices so "
            f"graph collectives stay inside one host's ICI domain")
    arr = np.array(devs).reshape(len(devs) // graph, graph)
    return Mesh(arr, (data_axis, graph_axis))


def process_info() -> dict:
    """Host-side observability: this process's rank/size and device split."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
