"""clsim-serve: the online serving front-end over the stream engine.

Layers (each its own module, host-side unless noted):

  admission    policy knob resolution (``serve_policy`` in
               config.ENGINE_KNOBS), the EDF/fifo queue ordering, and
               the deterministic ingest plan (quota refusal, memo
               cache hits, duplicate coalescing — all decided in
               arrival order, never against device timing).
  executables  the shape-bucketed serve-step executable cache: memory,
               then jax.export artifacts on disk, then a fresh
               trace+compile — a restarted server skips the cold
               compile at any seen shape bucket.
  server       ``serve_run``: the double-buffered host loop driving
               BatchedRunner's serving-mode stream step (the device
               half lives in parallel/batch.py behind ``serve=True``).
  spool        the write-ahead admission spool: fsync-appended journal
               of admit/lease/done records arbitrating exactly-once
               serving across worker crashes (``WAL_SCHEMA_VERSION``
               stamps every record; a stale journal is refused with
               ``SpoolError``).
  fleet        clsim-serve-ha: the multi-process worker fleet over the
               spool — supervisor (lease reclaim, doubling-backoff
               restart, poison quarantine, deadline-aware shedding)
               plus the worker serve loop.

``SERVE_SCHEMA_VERSION`` stamps every serve telemetry record
(``serve_schema`` key) and checkpoint meta; bump it when the serve
row shape changes (tools/staticcheck's AST plane enforces that it
stays a single named constant).
"""

from chandy_lamport_tpu.serving.admission import (
    admission_key,
    order_eligible,
    plan_ingest,
    resolve_serve_policy,
    shed_order,
)
from chandy_lamport_tpu.serving.executables import (
    EXEC_CACHE_SCHEMA_VERSION,
    ExecutableCache,
)
from chandy_lamport_tpu.serving.fleet import (
    fleet_run,
    recipe_runner,
    worker_serve,
)
from chandy_lamport_tpu.serving.server import SERVE_SCHEMA_VERSION, serve_run
from chandy_lamport_tpu.serving.spool import (
    WAL_SCHEMA_VERSION,
    AdmissionSpool,
    SpoolError,
    request_digest,
)

__all__ = [
    "EXEC_CACHE_SCHEMA_VERSION",
    "ExecutableCache",
    "SERVE_SCHEMA_VERSION",
    "WAL_SCHEMA_VERSION",
    "AdmissionSpool",
    "SpoolError",
    "admission_key",
    "fleet_run",
    "order_eligible",
    "plan_ingest",
    "recipe_runner",
    "request_digest",
    "resolve_serve_policy",
    "serve_run",
    "shed_order",
    "worker_serve",
]
