"""Serving admission: policy resolution, queue ordering, the ingest plan.

Everything here is deliberately a PURE function of (requests, cache
file, quotas) or of the books the server re-derives from the device
carry — that purity is the whole kill->resume story. The server
(serving/server.py) recomputes the eligible ordering from scratch every
step, so admission decisions are memoryless: a resumed run that
reconstructs the same pending set and tenant books makes bit-identical
decisions without replaying the dead process's trajectory.

**Ingest plan** (``plan_ingest``): requests are classified in arrival
order. A tenant whose ``quota`` (0 = unlimited) is already filled by
earlier ACCEPTED requests has this request refused outright — refusal
is an ingest-time admission-control decision on the deterministic
arrival order, NOT a service-time race, so it never depends on how fast
the device happened to drain (and never starves the other tenants,
whose books are independent). Accepted requests then follow the memo
plane's classification: first appearance of a digest with a warm
``SummaryCache`` entry is served from the cache without ever burning a
lane; the first cold appearance becomes the digest's EXEC leader; later
appearances coalesce onto that leader and are served its harvested
summary.

**Queue ordering** (``order_eligible``): "edf" sorts by priority class
(higher first), then earliest absolute deadline, then arrival, then job
id — EDF within priority class; "fifo" is pure arrival order, the
baseline the bench A/Bs against.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from chandy_lamport_tpu.config import ENGINE_KNOBS
from chandy_lamport_tpu.models.workloads import ServeRequest
from chandy_lamport_tpu.utils.memocache import SummaryCache


def resolve_serve_policy(policy: str) -> str:
    """Validate the ``serve_policy`` engine knob (config.ENGINE_KNOBS).
    Like ``memo`` there is no backend-dependent "auto": the spellings
    are explicit policies, so resolution is pure validation."""
    allowed = ENGINE_KNOBS["serve_policy"]
    if policy not in allowed:
        raise ValueError(
            f"serve_policy must be one of {', '.join(map(repr, allowed))}, "
            f"got {policy!r}")
    return policy


def admission_key(req: ServeRequest, policy: str):
    """The sort key one eligible request is ordered by. Total (job id is
    the final tiebreak), so the eligible ordering — and with it the whole
    serve trajectory — is deterministic."""
    if policy == "edf":
        return (-req.priority, req.deadline_step, req.arrival_step, req.job)
    return (req.arrival_step, req.job)


def order_eligible(eligible: Sequence[ServeRequest],
                   policy: str) -> List[ServeRequest]:
    """Order the arrived, quota-accepted, not-yet-admitted requests for
    the next stream step's admissible prefix."""
    policy = resolve_serve_policy(policy)
    return sorted(eligible, key=lambda r: admission_key(r, policy))


def shed_order(candidates: Sequence[ServeRequest]) -> List[ServeRequest]:
    """Deadline-aware load-shedding order: who to drop FIRST when the
    fleet must shrink its backlog (serving/fleet.py under quota pressure
    or worker loss). The mirror image of the EDF admission key — the
    lowest priority class goes first, and within a class the LATEST
    deadline (the job with the most slack left, i.e. the least urgent
    investment) is dropped before tighter ones; latest arrival, then
    highest job id, break ties so the order is total and a resumed
    supervisor sheds the identical victims."""
    return sorted(candidates,
                  key=lambda r: (r.priority, -r.deadline_step,
                                 -r.arrival_step, -r.job))


def plan_ingest(requests: Sequence[ServeRequest], digests: Sequence[str],
                cache: SummaryCache,
                quotas: Optional[Sequence[int]] = None) -> dict:
    """Classify every request (module docstring) into
    ``exec`` (digest leader, runs on a lane), ``cache`` (served from the
    persistent summary cache at ingest), ``follower`` (coalesced onto an
    in-run leader) or ``refused`` (tenant over quota at its arrival).

    Returns a dict of parallel books:
      ``status``      [J] one of the four classifications
      ``leader_of``   [J] the follower's leader job (else -1)
      ``cache_hit``   {job: summary} for cache-served requests
      ``exec``        leader job ids, arrival order
      ``followers``   {leader: [follower jobs]}
      ``accepted``    {tenant: count accepted (not refused)}
      ``refused``     {tenant: count refused}
    Deterministic for a given (requests, cache file, quotas) — the cache
    file only changes at the END of a completed run (SummaryCache.flush),
    so a killed serve run re-plans identically on resume.
    """
    jcount = len(requests)
    if len(digests) != jcount:
        raise ValueError("one digest per request required")
    quotas = list(quotas) if quotas is not None else []
    status = ["exec"] * jcount
    leader_of = [-1] * jcount
    cache_hit: Dict[int, dict] = {}
    exec_jobs: List[int] = []
    followers: Dict[int, List[int]] = {}
    accepted: Dict[int, int] = {}
    refused: Dict[int, int] = {}
    leader: Dict[str, tuple] = {}   # digest -> ("exec", job)|("cache", summ)
    for r in requests:
        j, t = r.job, r.tenant
        quota = quotas[t] if t < len(quotas) else 0
        if quota and accepted.get(t, 0) >= quota:
            status[j] = "refused"
            refused[t] = refused.get(t, 0) + 1
            continue
        accepted[t] = accepted.get(t, 0) + 1
        dg = digests[j]
        led = leader.get(dg)
        if led is None:
            hit = cache.get(dg)
            if hit is not None:
                leader[dg] = ("cache", dict(hit))
                status[j] = "cache"
                cache_hit[j] = dict(hit)
            else:
                leader[dg] = ("exec", j)
                exec_jobs.append(j)
                followers[j] = []
        else:
            kind, ref = led
            if kind == "exec":
                status[j] = "follower"
                leader_of[j] = ref
                followers[ref].append(j)
            else:
                status[j] = "cache"
                cache_hit[j] = dict(ref)
    return {"status": status, "leader_of": leader_of,
            "cache_hit": cache_hit, "exec": exec_jobs,
            "followers": followers, "accepted": accepted,
            "refused": refused}
