"""Shape-bucketed serve-step executable cache.

The serve step's compile is the dominant cold-start cost of a server
process (BENCH_r02 measured 136s of warmup at sf-256), and the traced
program is a pure function of a small identity: the runner's resolved
execution identity (the same ingredient list as the memo plane's
job_digest, minus per-job content), the step shape parameters
(stretch/drain_chunk) and the abstract shapes/dtypes of every operand —
batch width, pool phase-table height, results-ring capacity, tenant
count, exec-order length. That identity is the BUCKET: two serve runs
in the same bucket can share one executable.

Two planes, consulted in order:

  memory  — a per-process dict of AOT-compiled executables; a second
            serve run in the same process at a seen bucket skips
            compilation outright.
  disk    — ``jax.export`` artifacts (serialized StableHLO) under the
            cache directory, one file per bucket digest; a RESTARTED
            server deserializes the lowered program and only pays XLA's
            backend compile, skipping the trace+lower half of warmup.
            NOTE: this deliberately persists the *lowered* program, not
            the backend-compiled executable — compiled-executable
            deserialization is unsound across processes on this jaxlib
            (see tests/conftest.py) while the StableHLO artifact is a
            stable, versioned format.
  fresh   — trace + lower + compile from the runner, then best-effort
            export to disk for the next process.

Every ``step_for`` records what happened (bucket, source, warmup
seconds, persistence outcome) in ``self.last`` so the server can put
the measured warmup in its telemetry — the acceptance evidence for the
restart-skips-recompile claim.

All disk failures (unreadable artifact, refused export, version skew)
degrade to the fresh path — the cache can never make a serve run fail,
only make it warm up faster.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import asdict
from typing import Any, Optional

import jax
import numpy as np
from jax import export as jax_export

from chandy_lamport_tpu.utils.atomicio import (
    crash_failpoint,
    fsync_dir,
    fsync_file,
)
from chandy_lamport_tpu.utils.filelock import locked
from chandy_lamport_tpu.utils.memocache import _canon

EXEC_CACHE_SCHEMA_VERSION = 1

_registered = False


def _register_serialization() -> None:
    """jax.export refuses pytrees with unregistered custom node types;
    the serve-step operands carry the engine's NamedTuples. Registration
    is global and once-per-process; the serialized names are stable
    spellings a future process must reuse to deserialize."""
    global _registered
    if _registered:
        return
    from chandy_lamport_tpu.core.state import DenseState
    from chandy_lamport_tpu.parallel.batch import (
        JobPool,
        ScriptOps,
        StreamState,
    )
    for cls in (DenseState, StreamState, JobPool, ScriptOps):
        try:
            jax_export.register_namedtuple_serialization(
                cls, serialized_name=f"clsim.{cls.__name__}")
        except ValueError:
            pass  # a previous cache instance already registered it
    _registered = True


def _abstract(tree):
    """ShapeDtypeStructs mirroring a pytree of concrete arrays (None
    subtrees pass through untouched — tree_map never sees them)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        tree)


class ExecutableCache:
    """See module docstring. ``path`` is a DIRECTORY (created lazily);
    ``path=None`` keeps the memory plane only."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mem: dict = {}
        # books of the most recent step_for: {"bucket", "source",
        # "warmup_s", "persisted", "disk_error"?}
        self.last: Optional[dict] = None

    # -- bucket identity -------------------------------------------------

    def bucket_digest(self, runner, stretch: int, drain_chunk: int,
                      abstract_args) -> str:
        """sha256 over everything that determines the traced serve-step
        program: jax version (trace rules), the runner's resolved
        identity (same recipe as memocache.job_digest's runner half),
        the step shape knobs and the flattened operand avals."""
        cfg = asdict(runner.config)
        avals = [(str(a.dtype), list(a.shape)) if a is not None else None
                 for a in jax.tree_util.tree_leaves(
                     abstract_args, is_leaf=lambda v: v is None)]
        payload = {
            "schema": EXEC_CACHE_SCHEMA_VERSION,
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "nodes": _canon(sorted((str(k), int(v))
                                   for k, v in runner._topo_spec.nodes)),
            "links": _canon(sorted((str(s), str(d))
                                   for s, d in runner._topo_spec.links)),
            "scheduler": str(runner.scheduler),
            "knobs": _canon({
                "queue_engine": runner.queue_engine,
                "kernel_engine": runner.kernel_engine,
                "exact_impl": runner.kernel.exact_impl,
                "megatick": runner.megatick,
                "check_every": runner.check_every,
                "quarantine": runner.quarantine,
                "delay_kind": type(runner.delay).__name__,
                "faults": (None if runner.faults is None
                           else sorted(vars(runner.faults).items())),
            }),
            "config": _canon(cfg),
            "batch": int(runner.batch),
            "stretch": int(stretch),
            "drain_chunk": int(drain_chunk),
            "avals": avals,
        }
        blob = json.dumps(payload, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()

    def _artifact_path(self, key: str) -> Optional[str]:
        if not self.path:
            return None
        return os.path.join(self.path, f"serve-step-{key}.jaxexport")

    # -- the cache lookup ------------------------------------------------

    def step_for(self, runner, stretch: int, drain_chunk: int,
                 example_args):
        """The AOT-compiled serve step for this bucket, ready to call
        with operands shaped like ``example_args``. Compilation (or
        deserialization) happens eagerly HERE so the caller's warmup
        timing brackets the true cost; ``self.last`` records the books."""
        abstract = _abstract(example_args)
        key = self.bucket_digest(runner, stretch, drain_chunk, abstract)
        t0 = time.perf_counter()
        call = self._mem.get(key)
        if call is not None:
            self.last = {"bucket": key, "source": "memory",
                         "warmup_s": time.perf_counter() - t0,
                         "persisted": False}
            return call
        source, disk_error, persisted = "fresh", None, False
        apath = self._artifact_path(key)
        if apath and os.path.exists(apath):
            try:
                _register_serialization()
                with locked(apath, shared=True):
                    with open(apath, "rb") as f:
                        blob = bytearray(f.read())
                exported = jax_export.deserialize(blob)
                fn = jax.jit(exported.call, donate_argnums=(0, 1))
                call = fn.lower(*abstract).compile()
                source = "disk"
            except Exception as exc:  # degrade, never fail the run
                call, disk_error = None, f"{type(exc).__name__}: {exc}"
        if call is None:
            # serve compiles the memo-off admission (coalescing is host
            # work at ingest) EXCEPT under memo="prefix", whose fork
            # scatter lives inside the jitted step; the prefix variant
            # takes three extra operands (bank, fork_src, fork_depth),
            # so its avals — and therefore its bucket — can never
            # collide with a 9-operand artifact
            fn = jax.jit(
                runner._build_stream_step(
                    stretch, drain_chunk, False,
                    "prefix" if runner.memo == "prefix" else "off",
                    True),
                donate_argnums=(0, 1))
            call = fn.lower(*abstract).compile()
            if apath:
                persisted, disk_error = self._persist(apath, fn, abstract)
        self._mem[key] = call
        self.last = {"bucket": key, "source": source,
                     "warmup_s": time.perf_counter() - t0,
                     "persisted": persisted}
        if disk_error:
            self.last["disk_error"] = disk_error
        return call

    @staticmethod
    def _persist(apath: str, fn, abstract) -> tuple:
        """Best-effort export of the lowered program, written atomically
        (tmp + rename) under an exclusive advisory lock (utils/filelock)
        so a killed server never leaves a torn artifact and two servers
        exporting the same bucket never race the rename."""
        try:
            _register_serialization()
            exported = jax_export.export(fn)(*abstract)
            blob = exported.serialize()
            os.makedirs(os.path.dirname(apath) or ".", exist_ok=True)
            tmp = apath + ".tmp"
            with locked(apath):
                with open(tmp, "wb") as f:
                    f.write(blob)
                    fsync_file(f)
                crash_failpoint("execcache-replace")
                os.replace(tmp, apath)
                fsync_dir(apath)
            return True, None
        except Exception as exc:
            return False, f"{type(exc).__name__}: {exc}"
