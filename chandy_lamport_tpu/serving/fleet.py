"""clsim-serve-ha: the crash-tolerant multi-worker serving fleet.

One supervisor process + N host-side server workers (``multiprocessing``
spawn — NOT ``jax.distributed``: this environment's CPU backend forbids
multiprocess XLA, and the cross-process plumbing the fleet needs was
already proven by the flock-merged SummaryCache) share three durable
artifacts: the write-ahead admission spool (serving/spool.py), the
persistent SummaryCache (utils/memocache.py) and the executable cache.

**Division of labor.**

* ``fleet_run`` (the supervisor) durably admits every request into the
  spool BEFORE any worker exists, spawns the workers, and then only
  watches books: it reclaims expired leases (redelivery — the takeover
  path), declares workers dead by exit code and requeues their leases
  immediately with the decoded provenance, restarts dead workers with
  doubling backoff, quarantines repeat offenders as poison, and sheds
  the lowest-priority/latest-deadline pending work
  (admission.shed_order) whenever the backlog outruns the live fleet's
  capacity. It never executes a request itself.

* ``worker_serve`` (one worker's loop; importable in-process for the
  runtime sentry and the in-process differential tests, wrapped by the
  spawn entry ``_worker_main`` in production) leases a chunk, renews
  the heartbeat, serves warm digests straight from the shared
  SummaryCache, runs the cold remainder through the stream engine
  (``run_stream`` — the same jitted step the solo server dispatches, so
  fleet summaries are bit-identical to solo execution), and commits
  each summary through the spool's exactly-once ``complete``. A worker
  whose lease was taken over gets ``False`` back and discards its late
  result — execution is at-least-once, serving is exactly-once.

**Failure model** (see also the README's "Serving fleet & failure
model"): a SIGKILL at ANY point loses nothing — un-acked requests were
never admitted (the caller retries admit, which is idempotent by
digest), acked requests are durable in the spool, and in-flight leases
expire and are redelivered. The chaos harness (tools/chaos_smoke.py
fleet scenarios) kills workers mid-step and pins all of it: zero lost,
zero double-served (WAL audit), summaries bit-identical to solo.

Workers are rebuilt from a picklable ``recipe`` dict rather than a
pickled runner (jax objects don't survive spawn): ``recipe_runner``
maps it to a BatchedRunner, or to the jax-free *null executor*
(``kind="null"``) that serves deterministic stub summaries — the
control-plane-only arm the poison/shed chaos scenarios and the
host-logic tests use, so they pay no compile on the 1-core CI box.

Telemetry rows (kinds ``fleet_interval``/``fleet_run``) extend the
serve schema with the fleet books — shed/retry/takeover counts, worker
deaths and restarts — stamped with the imported SERVE_SCHEMA_VERSION.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from chandy_lamport_tpu.models.workloads import ServeRequest
from chandy_lamport_tpu.serving.admission import shed_order
from chandy_lamport_tpu.serving.server import SERVE_SCHEMA_VERSION
from chandy_lamport_tpu.serving.spool import (
    AdmissionSpool,
    request_digest,
)
from chandy_lamport_tpu.utils.filelock import locked


def recipe_runner(recipe: Optional[dict]):
    """Build a worker's engine from a picklable recipe dict. ``None`` or
    ``{"kind": "null"}`` selects the jax-free null executor (returns
    None); ``{"kind": "ring-stream", ...}`` builds a BatchedRunner over
    a ring topology with the stream engine's tiny-shape defaults. The
    recipe — not a pickled runner — crosses the spawn boundary, so every
    worker (and a restarted worker) reconstructs the IDENTICAL engine,
    which is what makes fleet summaries bit-identical to solo runs."""
    if not recipe or recipe.get("kind", "null") == "null":
        return None
    if recipe["kind"] != "ring-stream":
        raise ValueError(f"unknown worker recipe kind {recipe['kind']!r}")
    from chandy_lamport_tpu.config import SimConfig
    from chandy_lamport_tpu.models.workloads import ring_topology
    from chandy_lamport_tpu.ops.delay_jax import make_fast_delay
    from chandy_lamport_tpu.parallel.batch import BatchedRunner
    return BatchedRunner(
        ring_topology(int(recipe.get("n", 8)),
                      tokens=int(recipe.get("tokens", 16))),
        SimConfig.for_workload(
            snapshots=int(recipe.get("snapshots", 2)),
            max_recorded=int(recipe.get("max_recorded", 32))),
        make_fast_delay(recipe.get("delay", "hash"),
                        int(recipe.get("delay_seed", 7))),
        int(recipe.get("batch", 2)),
        scheduler=recipe.get("scheduler", "sync"),
        # memo stays "off" unless the recipe opts in: the worker loop
        # already serves exact duplicates from the shared SummaryCache
        # itself. memo="prefix" + a shared ``prefix_cache`` path makes
        # each cold execution fork from the deepest boundary ANY worker
        # checkpointed: singleton pools pin content rank 0, so the chain
        # digests agree fleet-wide — request 1 bumps the seen heat,
        # request 2 produces the checkpoint (and forks from it), every
        # later near-duplicate forks free, across worker restarts.
        memo=recipe.get("memo", "off"),
        memo_cache=recipe.get("memo_cache"),
        prefix_cache=recipe.get("prefix_cache"),
        prefix_cache_entries=int(recipe.get("prefix_cache_entries", 0)),
        prefix_cache_bytes=int(recipe.get("prefix_cache_bytes", 0)))


def _chaos_maybe_kill(chaos: Optional[dict], leased_jobs) -> None:
    """Deterministic chaos hook: SIGKILL THIS worker the moment it
    leases ``chaos["kill_on_job"]``, at most ``kill_limit`` times
    fleet-wide — a shared counter file (under the advisory lock) makes
    "kill the first holder once" (the takeover proof) and "kill every
    holder" (the crash-loop that must end in poison quarantine) both
    expressible. No-op without a chaos config."""
    if not chaos or chaos.get("kill_on_job") not in leased_jobs:
        return
    cpath = chaos["counter_path"]
    with locked(cpath):
        try:
            with open(cpath, "r", encoding="utf-8") as f:
                count = int(f.read().strip() or 0)
        except (OSError, ValueError):
            count = 0
        if count >= int(chaos.get("kill_limit", 1)):
            return
        with open(cpath, "w", encoding="utf-8") as f:
            f.write(str(count + 1))
    os.kill(os.getpid(), signal.SIGKILL)


def _null_summary(req: ServeRequest) -> dict:
    """The null executor's deterministic stub summary — a pure function
    of the request, so redelivered executions commit identical bytes."""
    return {"served_from": "null", "events": len(req.events),
            "tenant": int(req.tenant), "priority": int(req.priority)}


def worker_serve(worker_id: str, spool: AdmissionSpool, runner=None, *,
                 stretch: int = 2, drain_chunk: int = 8,
                 lease_limit: int = 2, chaos: Optional[dict] = None,
                 poll_s: float = 0.05, max_wall_s: float = 120.0) -> dict:
    """One worker's serve loop (module docstring); returns its books.
    Runs until every admitted request is terminal, or ``max_wall_s``.
    With ``runner=None`` it is the jax-free null executor."""
    books = {"leased": 0, "served": 0, "late_rejected": 0,
             "cache_served": 0, "batches": 0, "idle_polls": 0}
    t0 = time.monotonic()
    while time.monotonic() - t0 < max_wall_s:
        reqs = spool.lease(worker_id, lease_limit)
        if not reqs:
            if spool.finished():
                break
            books["idle_polls"] += 1
            time.sleep(poll_s)
            continue
        books["leased"] += len(reqs)
        books["batches"] += 1
        _chaos_maybe_kill(chaos, {r.job for r in reqs})
        # heartbeat covering the lease -> execute window; production
        # tuning keeps lease_ttl above the batch's execution time, and a
        # slower-than-the-ttl worker is handled by the commit check, not
        # the heartbeat (complete() refuses a reclaimed lease)
        spool.renew(worker_id, [r.job for r in reqs])
        rows: Dict[int, dict] = {}
        if runner is None:
            for r in reqs:
                rows[r.job] = _null_summary(r)
        else:
            # a FRESH cache handle per batch: other workers' flushed
            # entries become visible, so a digest one worker already
            # served is answered from the shared cache without a lane
            cache = runner._summary_cache()
            dirty = False
            for r in reqs:
                # each cold request executes as its OWN singleton pool:
                # under content_keys the fault/delay stream identity is
                # the job's content RANK within its pool, so a job's
                # trajectory (and its harvested ``time``) would shift
                # with its leased companions. A singleton pool pins rank
                # 0 always, making every execution a pure function of
                # the request content — bit-identical across workers,
                # redeliveries and restarts, and to a solo ``run_stream``
                # of that request (the chaos harness's identity proof)
                spool_ = runner.pack_jobs([r.events], content_keys=True)
                dg = bytes(bytearray(np.asarray(
                    spool_.digest[0], np.uint8).tolist())).hex()
                hit = cache.get(dg)
                if hit is not None:
                    rows[r.job] = {**hit, "digest": dg,
                                   "served_from": "fleet-cache"}
                    books["cache_served"] += 1
                    continue
                _, stream = runner.run_stream(spool_, stretch=stretch,
                                              drain_chunk=drain_chunk)
                (row,) = runner.stream_results(stream)
                # under memo="prefix" the executed row can carry fork
                # provenance (digest/served_from="prefix:<d>"); the
                # committed summary must stay provenance-free so forked
                # and cold executions commit identical bytes
                summ = {k: v for k, v in row.items()
                        if k not in ("job", "admit_step", "digest",
                                     "served_from")}
                cache.put(dg, summ)
                dirty = True
                rows[r.job] = {**summ, "digest": dg,
                               "served_from": "fleet-exec"}
            if dirty:
                cache.flush()
        for j, summ in rows.items():
            if spool.complete(j, worker_id, summ):
                books["served"] += 1
            else:
                # the lease was reclaimed mid-run and redelivered — the
                # takeover's copy owns the serve; discard ours
                books["late_rejected"] += 1
    return books


def _worker_main(worker_id: str, spool_path: str, wcfg: dict) -> None:
    """Spawn entry: rebuild the spool handle and the engine from the
    picklable config and run the serve loop. Forces the CPU backend
    before jax loads — each worker owns a PRIVATE single-process XLA
    runtime (the whole reason the fleet is processes, not
    jax.distributed)."""
    if not os.environ.get("CLSIM_KEEP_PLATFORM"):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    spool = AdmissionSpool(spool_path,
                           lease_ttl=wcfg.get("lease_ttl", 10.0),
                           max_attempts=wcfg.get("max_attempts", 3))
    runner = recipe_runner(wcfg.get("recipe"))
    worker_serve(worker_id, spool, runner,
                 stretch=wcfg.get("stretch", 2),
                 drain_chunk=wcfg.get("drain_chunk", 8),
                 lease_limit=wcfg.get("lease_limit", 2),
                 chaos=wcfg.get("chaos"),
                 poll_s=wcfg.get("poll_s", 0.05),
                 max_wall_s=wcfg.get("max_wall_s", 120.0))


def _exit_provenance(code: Optional[int]) -> str:
    """Decode a Process.exitcode into human provenance for the WAL."""
    if code is None:
        return "still running"
    if code < 0:
        try:
            name = signal.Signals(-code).name
        except ValueError:
            name = f"signal {-code}"
        return f"killed by {name}"
    return f"exited with code {code}"


def _latency_percentiles(lat: Sequence[float]) -> dict:
    if not lat:
        return {"lat_p50_s": None, "lat_p99_s": None, "lat_max_s": None}
    a = np.asarray(lat, np.float64)
    return {"lat_p50_s": round(float(np.percentile(a, 50)), 4),
            "lat_p99_s": round(float(np.percentile(a, 99)), 4),
            "lat_max_s": round(float(a.max()), 4)}


def fleet_run(requests: List[ServeRequest], *, spool_path: str,
              workers: int = 2, recipe: Optional[dict] = None,
              lease_ttl: float = 10.0, max_attempts: int = 3,
              lease_limit: int = 2, stretch: int = 2,
              drain_chunk: int = 8, shed_backlog: int = 0,
              crash_schedule: Sequence[float] = (),
              chaos: Optional[dict] = None,
              restart_backoff: float = 0.2, max_restarts: int = 3,
              poll_s: float = 0.05, max_wall_s: float = 120.0,
              telemetry=None, telemetry_every: int = 20) -> dict:
    """Run the fleet over a request list until every request is terminal
    (served, poisoned or shed); returns the report (module docstring).

    ``shed_backlog``: pending-queue capacity PER LIVE WORKER (0 = never
    shed) — when the backlog exceeds ``shed_backlog * live_workers``,
    the excess is dropped in admission.shed_order (lowest priority,
    latest deadline first). Worker loss therefore shrinks capacity and
    sheds MORE, which is the graceful-degradation contract the bench's
    degraded-mode row measures. ``crash_schedule``: elapsed-seconds at
    which the supervisor SIGKILLs a live worker (the injected-crash SLO
    arm; models/workloads.crash_schedule builds one). ``chaos`` is
    passed through to the workers' deterministic kill hook.
    ``telemetry``: a utils.tracing.TelemetryWriter — one
    ``fleet_interval`` row per ``telemetry_every`` supervision polls
    plus a final ``fleet_run`` row."""
    if workers < 1:
        raise ValueError("workers must be >= 1")
    spool = AdmissionSpool(spool_path, lease_ttl=lease_ttl,
                           max_attempts=max_attempts)
    for r in requests:
        spool.admit(r, request_digest(r))

    books = {"takeovers": 0, "poisoned": 0, "shed": 0, "restarts": 0,
             "worker_deaths": 0, "injected_kills": 0}

    def absorb(res: dict) -> None:
        books["takeovers"] += len(res["requeued"])
        books["poisoned"] += len(res["poisoned"])

    def shed_pass(live: int) -> None:
        if not shed_backlog:
            return
        pending = spool.pending()
        cap = int(shed_backlog) * max(live, 1)
        excess = len(pending) - cap
        if excess > 0:
            victims = shed_order([spool.requests[j]
                                  for j in pending])[:excess]
            done = spool.shed_jobs(
                [v.job for v in victims],
                f"backlog {len(pending)} over capacity {cap} "
                f"({live} live worker(s))")
            books["shed"] += len(done)

    # admission-time pressure control: one shed pass BEFORE any worker
    # exists, so a burst arriving faster than the fleet can even start
    # is trimmed deterministically rather than raced
    shed_pass(workers)

    wcfg = {"recipe": recipe, "lease_ttl": lease_ttl,
            "max_attempts": max_attempts, "lease_limit": lease_limit,
            "stretch": stretch, "drain_chunk": drain_chunk,
            "chaos": chaos, "poll_s": poll_s, "max_wall_s": max_wall_s}
    ctx = mp.get_context("spawn")
    procs: Dict[int, Optional[mp.Process]] = {}
    incarnation = {w: 0 for w in range(workers)}
    backoff = {w: float(restart_backoff) for w in range(workers)}
    next_start = {w: 0.0 for w in range(workers)}
    restarts = {w: 0 for w in range(workers)}

    def spawn(w: int) -> None:
        name = f"w{w}i{incarnation[w]}"
        incarnation[w] += 1
        p = ctx.Process(target=_worker_main,
                        args=(name, spool_path, wcfg), daemon=True)
        p.start()
        procs[w] = p

    t0 = time.monotonic()
    for w in range(workers):
        spawn(w)
    kills = sorted(float(t) for t in crash_schedule)
    polls = 0
    timed_out = False
    while True:
        spool.refresh()
        if spool.finished():
            break
        elapsed = time.monotonic() - t0
        if elapsed >= max_wall_s:
            timed_out = True
            break
        # injected crash schedule (the degraded-mode bench arm)
        while kills and elapsed >= kills[0]:
            kills.pop(0)
            live = [p for p in procs.values()
                    if p is not None and p.exitcode is None]
            if live:
                os.kill(live[0].pid, signal.SIGKILL)
                books["injected_kills"] += 1
        live_count = 0
        for w in range(workers):
            p = procs.get(w)
            if p is not None and p.exitcode is not None:
                # direct evidence of death: requeue its leases NOW with
                # decoded provenance instead of waiting out the ttl
                books["worker_deaths"] += 1
                absorb(spool.requeue_worker(
                    f"w{w}i{incarnation[w] - 1}",
                    f"worker w{w} {_exit_provenance(p.exitcode)}"))
                procs[w] = None
                p = None
                if restarts[w] < max_restarts:
                    next_start[w] = elapsed + backoff[w]
                    backoff[w] *= 2.0   # doubling backoff per slot
                    restarts[w] += 1
                else:
                    next_start[w] = float("inf")
            if p is None and elapsed >= next_start[w] \
                    and next_start[w] != float("inf") \
                    and not spool.finished():
                books["restarts"] += 1
                spawn(w)
                p = procs[w]
            if p is not None and p.exitcode is None:
                live_count += 1
        # leases whose worker died silently (or stalled past the ttl)
        absorb(spool.reclaim_expired())
        shed_pass(live_count)
        if live_count == 0 and all(ns == float("inf")
                                   for ns in next_start.values()):
            break   # restart budget exhausted everywhere — report it
        polls += 1
        if telemetry is not None and telemetry_every \
                and polls % int(telemetry_every) == 0:
            telemetry.write("fleet_interval", {
                "serve_schema": SERVE_SCHEMA_VERSION,
                "elapsed_s": round(elapsed, 3), "live_workers": live_count,
                **spool.counters(), **books})
        time.sleep(poll_s)

    for p in procs.values():
        if p is None:
            continue
        p.join(timeout=5.0)
        if p.exitcode is None:
            p.kill()
            p.join(timeout=5.0)
    wall_s = time.monotonic() - t0

    spool.refresh()
    audit = spool.audit()
    lat = [spool.done_t[j] - spool.admit_t[j] for j in spool.done]
    admitted = len(spool.requests)
    report = {
        "serve_schema": SERVE_SCHEMA_VERSION,
        "workers": workers, "requests": admitted,
        "served": len(spool.done), "poisoned": dict(spool.poisoned),
        "shed": dict(spool.shed),
        "stranded": len(spool.pending()) + len(spool.leases),
        "goodput": round(len(spool.done) / max(admitted, 1), 4),
        "timed_out": timed_out, "wall_s": round(wall_s, 3),
        "books": {**books, **spool.counters()},
        "audit": audit, **_latency_percentiles(lat),
    }
    if telemetry is not None:
        telemetry.write("fleet_run", dict(report))
    report["results"] = spool.results()
    return report
