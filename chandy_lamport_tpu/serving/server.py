"""The serve loop: an online, multi-tenant front-end over the stream step.

``serve_run`` drives a timed open-loop request schedule
(models/workloads.serve_workload) through BatchedRunner's serving-mode
stream step. The division of labor with run_stream's batch loop:

* The DEVICE runs the identical harvest -> admit -> advance step, plus
  the serving-plane books (deadline misses, per-tenant service counts —
  v9 StreamState leaves). Admission walks a host-maintained exec-order
  array up to a dynamic ``limit`` scalar, so the device program never
  retraces as the queue reorders.

* The HOST owns time-aware admission: each iteration it re-sorts the
  arrived-but-unadmitted requests under the ``serve_policy`` knob (EDF
  within priority class, or fifo), rewrites the un-admitted suffix of
  the exec order, and raises ``limit`` to the admissible prefix length.
  Ingestion is double-buffered against the device: the step for host
  time S is dispatched asynchronously, the arrivals for S+1 are packed
  while it runs, and only then does the host touch the step's output
  scalars (the one sync point per iteration).

Memo digests are taken at INGEST (admission.plan_ingest): a request
whose digest is warm in the persistent SummaryCache is served its
summary the moment it arrives, without ever burning a lane; duplicate
requests coalesce onto the first accepted leader and are fanned out at
finalize exactly like run_stream's memo plane. Quota refusal happens at
ingest too, against the deterministic arrival order — never against the
device's drain speed.

Kill -> resume is bit-exact because every host decision is a memoryless
function of state the resumed process can reconstruct: the ingest plan
is pure in (requests, cache file, quotas); the pending set is "arrived
and not admitted", where the admitted set is recoverable from the saved
carry (results ring + in-flight lane job ids); and the eligible
ordering is re-sorted from scratch each step. Positions of the exec
order below ``next_job`` are never re-read by the device, so their
content need not survive the crash. Admit-latency percentiles are
process-local observability (they reset on resume); every RESULT row
and every carried counter is identical to the uninterrupted run.

Compilation warmup goes through serving.executables.ExecutableCache —
a restarted server at a seen shape bucket deserializes the lowered
program from disk instead of re-tracing (``warmup_source`` in the
report/telemetry records which plane served it).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from chandy_lamport_tpu.models.workloads import ServeRequest
from chandy_lamport_tpu.parallel.batch import JobPool, _ring_rows
from chandy_lamport_tpu.serving.admission import (
    order_eligible,
    plan_ingest,
    resolve_serve_policy,
)
from chandy_lamport_tpu.serving.executables import ExecutableCache
from chandy_lamport_tpu.utils.guards import (
    armed,
    guarded_get,
    guarded_put,
    relaxed_site,
)

SERVE_SCHEMA_VERSION = 1


def _percentiles(lat: Sequence[int]) -> dict:
    if not lat:
        return {"admit_p50": None, "admit_p99": None, "admit_max": None}
    a = np.asarray(lat)
    return {"admit_p50": float(np.percentile(a, 50)),
            "admit_p99": float(np.percentile(a, 99)),
            "admit_max": int(a.max())}


def serve_run(runner, requests: List[ServeRequest], *,
              policy: str = "edf",
              quotas: Optional[Sequence[int]] = None,
              stretch: int = 4, drain_chunk: int = 32,
              results_capacity: Optional[int] = None,
              state=None, stream=None,
              max_steps: int = 1_000_000,
              checkpoint: Optional[str] = None,
              checkpoint_every: int = 0,
              kill_after_saves: Optional[int] = None,
              telemetry=None, telemetry_interval: int = 64,
              exec_cache: Optional[ExecutableCache] = None,
              guards=None):
    """Serve a timed request schedule; returns ``(state, stream, report)``.

    ``requests`` must be serve_workload-style: ``job`` equal to list
    index, arrivals non-decreasing. ``quotas``: per-tenant admission
    caps (0/absent = unlimited), enforced at ingest. Checkpointing and
    ``kill_after_saves`` mirror run_stream (a killed run returns early
    with ``report["killed"] = True``; resume by passing the loaded
    ``state=``/``stream=`` back with the SAME requests/quotas/policy).
    ``telemetry``: a utils.tracing.TelemetryWriter — one
    ``serve_interval`` row per ``telemetry_interval`` steps and a final
    ``serve_run`` row, each stamped with SERVE_SCHEMA_VERSION. Results
    come from ``runner.stream_results(stream)`` as usual; refused
    requests get no row (the report carries per-tenant refusal counts).
    ``guards``: utils/guards.RuntimeGuards arming the device loop
    (defaults to the runner's own ``guards``); every intentional
    host<->device transfer in the loop goes through a named site.
    """
    from chandy_lamport_tpu.utils.checkpoint import save_state

    policy = resolve_serve_policy(policy)
    if guards is None:
        guards = getattr(runner, "guards", None)
    if stretch < 1 or drain_chunk < 1:
        raise ValueError("stretch and drain_chunk must be >= 1")
    total = len(requests)
    for i, r in enumerate(requests):
        if r.job != i:
            raise ValueError("requests must be indexed by arrival order "
                             f"(request {i} has job id {r.job})")
        if i and r.arrival_step < requests[i - 1].arrival_step:
            raise ValueError("request arrivals must be non-decreasing")
    tenants = max([r.tenant for r in requests] or [0]) + 1
    if quotas is not None:
        tenants = max(tenants, len(quotas))
    quota_arr = np.zeros(tenants, np.int32)
    if quotas is not None:
        quota_arr[:len(quotas)] = np.asarray(quotas, np.int32)

    # ingest plan: pure in (requests, cache file, quotas) — see module
    # docstring for why that purity is the resume story
    pool = runner.pack_jobs([r.events for r in requests],
                            content_keys=True)
    digests = [bytes(bytearray(np.asarray(pool.digest[j], np.uint8)
                               .tolist())).hex()
               for j in range(pool.num_jobs)]
    cache = runner._summary_cache()
    plan = plan_ingest(requests, digests, cache, quota_arr.tolist())
    n_exec = len(plan["exec"])
    rcap = int(results_capacity) if results_capacity else pool.num_jobs
    if rcap < n_exec:
        raise ValueError(
            f"serve needs results_capacity >= executed jobs ({n_exec}): "
            f"followers are fanned out from leaders' ring rows and resume "
            f"reconstructs the admitted set from the ring")

    if state is None:
        state = runner.init_batch()
    resuming = stream is not None
    if stream is None:
        stream = runner.init_stream(pool, rcap, tenants=tenants,
                                    tenant_quota=quota_arr)
    runner._memo_rows = {}
    runner._fork_depths = []
    runner._prefix_stats = {"prefix_evictions": 0,
                            "prefix_evicted_bytes": 0,
                            "prefix_store_entries": 0}

    arrival_host = np.asarray([r.arrival_step for r in requests], np.int32)
    tenant_dev = jnp.asarray([r.tenant for r in requests], np.int32)
    arrival_dev = jnp.asarray(arrival_host)
    deadline_dev = jnp.asarray([r.deadline_step for r in requests],
                               np.int32)
    pool_dev = jax.tree_util.tree_map(jnp.asarray, pool)
    exec_order = np.full(max(n_exec, 1), -1, np.int32)

    # prefix plane (runner memo="prefix"): plan speculative forks over
    # the ingest plan's exec set — near-duplicate requests fork from the
    # deepest checkpointed phase boundary instead of admitting cold. The
    # fork arrays are JOB-indexed, so the loop's per-step re-sort of the
    # un-admitted exec-order suffix never invalidates them. Runs before
    # the armed loop (the producer is ordinary device traffic); a shared
    # file-backed PrefixCache (runner ``prefix_cache`` knob) lets fleet
    # workers fork from checkpoints their siblings flushed.
    pplan = None
    if runner.memo == "prefix":
        pplan = runner._prefix_plan(
            pool, pool_dev, {"exec": list(plan["exec"]), "shadows": set()},
            None)

    # -- host books ------------------------------------------------------
    admitted: set = set()
    pending: set = set()
    arr_ptr = 0
    books = {"cache_served": 0, "refused_seen": 0}
    admit_all: List[int] = []
    admit_window: List[int] = []

    def ingest_upto(step_bound: int) -> None:
        """Admit arrivals with arrival_step <= step_bound into the host
        books; cache hits are served on the spot, followers wait for
        their leader's harvest (finalize), refused requests are only
        counted."""
        nonlocal arr_ptr
        while (arr_ptr < total
               and requests[arr_ptr].arrival_step <= step_bound):
            r = requests[arr_ptr]
            arr_ptr += 1
            st = plan["status"][r.job]
            if st == "exec":
                if r.job not in admitted:
                    pending.add(r.job)
            elif st == "cache":
                row = dict(plan["cache_hit"][r.job])
                row.update(job=r.job, admit_step=-1,
                           digest=digests[r.job], served_from="cache")
                runner._memo_rows[r.job] = row
                books["cache_served"] += 1
            elif st == "refused":
                books["refused_seen"] += 1
            # followers: nothing to do until their leader harvests

    consumed, steps_now, done_exec = (
        (int(x) for x in jax.device_get(
            (stream.next_job, stream.steps, stream.jobs_done)))
        if resuming else (0, 0, 0))
    if resuming:
        # reconstruct the admitted set from the carry: every admission
        # landed either in the results ring or on a still-running lane
        host = jax.device_get((stream.res_job, stream.res_count,
                               state.job_id))
        ring_jobs, res_count, lane_jobs = host
        admitted = {int(j) for j in
                    np.asarray(ring_jobs)[:min(int(res_count),
                                               len(ring_jobs))]
                    if int(j) >= 0}
        admitted |= {int(j) for j in np.asarray(lane_jobs) if int(j) >= 0}
        if len(admitted) != consumed:
            raise ValueError(
                f"resume carry inconsistent: next_job={consumed} but "
                f"{len(admitted)} admitted jobs reconstructed — was the "
                f"checkpoint taken with the same requests and capacity?")
        # order content below next_job is never re-read by the device;
        # any fixed deterministic fill keeps the array well-formed
        exec_order[:consumed] = np.asarray(sorted(admitted), np.int32)
    ingest_upto(steps_now)

    # -- executable warmup (serving.executables) -------------------------
    warm = {"warmup_s": 0.0, "source": None, "persisted": False}
    call = None
    fork_ops = (() if pplan is None
                else (pplan["bank_dev"], pplan["fork_src_dev"],
                      pplan["fork_depth_dev"]))
    if n_exec and done_exec < n_exec:
        exec_cache = exec_cache or ExecutableCache(None)
        call = exec_cache.step_for(
            runner, stretch, drain_chunk,
            (state, stream, pool_dev, jnp.asarray(exec_order), None,
             np.int32(0), tenant_dev, arrival_dev, deadline_dev)
            + fork_ops)
        warm = {"warmup_s": round(exec_cache.last["warmup_s"], 3),
                "source": exec_cache.last["source"],
                "persisted": exec_cache.last["persisted"]}

    def telemetry_row(kind: str, extra: dict) -> None:
        if telemetry is None:
            return
        host = jax.device_get((stream.deadline_misses,
                               stream.tenant_served,
                               stream.lane_steps_live,
                               stream.lane_steps_total))
        miss, served_t, live, lane_total = host
        row = {"serve_schema": SERVE_SCHEMA_VERSION, "step": steps_now,
               "arrived": arr_ptr, "admitted": consumed,
               "harvested": done_exec, "pending": len(pending),
               "occupancy": round(int(live) / max(int(lane_total), 1), 4),
               "deadline_misses": int(miss),
               "memo_hits": books["cache_served"],
               # share of the requests seen so far that the warm summary
               # cache served at ingest (coalesce service only counts in
               # the final report — followers are materialized at
               # finalize, after their leader's harvest)
               "memo_hit_rate": round(
                   books["cache_served"] / max(arr_ptr, 1), 4),
               "refused": books["refused_seen"],
               "tenant_served": np.asarray(served_t).astype(int).tolist(),
               "tenant_quota": quota_arr.astype(int).tolist()}
        row.update(extra)
        telemetry.write(kind, row)

    # -- the device loop -------------------------------------------------
    # armed when guards are on: the AOT step never retraces (shape-
    # bucketed executable), the exec-order/limit operands go to device
    # through named put sites, and the one sync per iteration is a named
    # get site — anything else raises under transfer_guard("disallow").
    # The carry enters the device through an explicit named bulk upload
    # first (a fresh start builds host numpy leaves).
    state, stream = guarded_put(guards, "serve-carry-upload",
                                (state, stream))
    saves = 0
    t_loop = time.perf_counter()
    with armed(guards):
        while done_exec < n_exec:
            if steps_now >= max_steps:
                raise RuntimeError(
                    f"serve_run: {n_exec - done_exec} of {n_exec} executed "
                    f"jobs unfinished after {max_steps} steps — raise "
                    f"max_steps")
            elig = order_eligible([requests[j] for j in sorted(pending)],
                                  policy)
            exec_order[consumed:consumed + len(elig)] = \
                np.asarray([r.job for r in elig], np.int32)
            limit = consumed + len(elig)
            # dispatch is async; the arrivals for the NEXT host time are
            # ingested while the device steps (double buffering), and only
            # the scalar read below synchronizes
            state, stream = call(
                state, stream, pool_dev,
                guarded_put(guards, "serve-admission-order", exec_order),
                None,
                guarded_put(guards, "serve-admission-limit",
                            np.int32(limit)),
                tenant_dev, arrival_dev, deadline_dev, *fork_ops)
            ingest_upto(steps_now + 1)
            prev = consumed
            consumed, steps_now, done_exec = (int(x) for x in guarded_get(
                guards, "serve-progress-scalars",
                (stream.next_job, stream.steps, stream.jobs_done)))
            for pos in range(prev, consumed):
                j = int(exec_order[pos])
                admitted.add(j)
                pending.discard(j)
                lat = (steps_now - 1) - int(arrival_host[j])
                admit_all.append(lat)
                admit_window.append(lat)
            if (telemetry is not None and telemetry_interval
                    and steps_now % int(telemetry_interval) == 0):
                telemetry_row("serve_interval", _percentiles(admit_window))
                admit_window = []
            if (checkpoint and checkpoint_every
                    and steps_now % int(checkpoint_every) == 0):
                # save_state numpy-ifies the whole carry — an intentional
                # bulk device read, booked by site
                with relaxed_site(guards, "checkpoint-save"):
                    save_state(checkpoint, (state, stream),
                               meta={"stream_steps": steps_now,
                                     "jobs_done": done_exec,
                                     "serve_schema": SERVE_SCHEMA_VERSION})
                saves += 1
                if kill_after_saves is not None \
                        and saves >= int(kill_after_saves):
                    return state, stream, {
                        "serve_schema": SERVE_SCHEMA_VERSION,
                        "killed": True, "steps": steps_now,
                        "saves": saves,
                        "fused_tick": runner.fused,
                        "fused_tile": runner.fused_tile,
                        "fused_emulated": bool(
                            runner.fused == "on"
                            and runner.kernel._pl_interpret),
                        **warm}
    wall_s = time.perf_counter() - t_loop

    # tail arrivals past the last harvest never need the device: the
    # plan guarantees they are cache hits, followers or refusals
    ingest_upto(np.iinfo(np.int32).max)

    # -- finalize: write-back, follower fan-out, books -------------------
    ring = {r["job"]: r for r in _ring_rows(stream)}

    def summary_of(row):
        return {k: v for k, v in row.items()
                if k not in ("job", "admit_step")}

    for e in plan["exec"]:
        r = ring.get(e)
        if r is not None:
            cache.put(digests[e], summary_of(r))
    ncoal = 0
    for leader, fls in plan["followers"].items():
        r = ring.get(leader)
        if r is None or not fls:
            continue
        summ = summary_of(r)
        for j in fls:
            row = dict(summ)
            row.update(job=j, admit_step=-1, digest=digests[j],
                       served_from="coalesce")
            runner._memo_rows[j] = row
            ncoal += 1
    cache.flush()
    runner._memo_cache_stats = {"cache_evictions": cache.evictions,
                                "cache_evicted_bytes": cache.evicted_bytes}
    stream = stream._replace(
        cache_hits=np.int32(books["cache_served"]),
        coalesced_jobs=np.int32(ncoal))
    pref_books = {"prefix_hits": 0, "forked_jobs": 0,
                  "fork_depth_mean": 0.0}
    if pplan is not None:
        # fork provenance + shadow audit + prefix-cache flush, exactly
        # run_stream's finalize arm (only plan["digests"] is consulted)
        state, stream = runner._prefix_finalize(
            state, stream, {"digests": digests}, pplan, pool,
            stretch, drain_chunk)
        fj, fds = (int(x) for x in jax.device_get(
            (stream.forked_jobs, stream.fork_depth_sum)))
        pref_books = {"prefix_hits": int(stream.prefix_hits),
                      "forked_jobs": fj,
                      "fork_depth_mean": round(fds / fj, 4) if fj
                      else 0.0}

    host = jax.device_get((stream.deadline_misses, stream.tenant_served,
                           stream.lane_steps_live,
                           stream.lane_steps_total))
    miss, served_t, live, lane_total = host
    nserved = n_exec + books["cache_served"] + ncoal
    report = {
        "serve_schema": SERVE_SCHEMA_VERSION, "killed": False,
        "policy": policy, "requests": total, "tenants": tenants,
        "steps": steps_now, "exec_jobs": n_exec,
        "served_cache": books["cache_served"], "served_coalesced": ncoal,
        "served_total": nserved, "refused_total": books["refused_seen"],
        "refused_by_tenant": {str(t): int(c)
                              for t, c in sorted(plan["refused"].items())},
        "occupancy": round(int(live) / max(int(lane_total), 1), 4),
        "deadline_misses": int(miss),
        "memo_hit_rate": round(
            (books["cache_served"] + ncoal) / max(nserved, 1), 4),
        "tenant_served": np.asarray(served_t).astype(int).tolist(),
        "tenant_quota": quota_arr.astype(int).tolist(),
        "wall_s": round(wall_s, 3), **pref_books, **_percentiles(admit_all),
        "warmup_s": warm["warmup_s"], "warmup_source": warm["source"],
        "warmup_persisted": warm["persisted"],
        # serve honesty: which kernel served the run, and whether the
        # fused dispatches ran interpret-mode Pallas (CPU gauge, not a
        # TPU win — the tunnel is dead, TPU-blind since r03)
        "fused_tick": runner.fused,
        "fused_tile": runner.fused_tile,
        "fused_emulated": bool(runner.fused == "on"
                               and runner.kernel._pl_interpret),
    }
    if telemetry is not None:
        telemetry.write("serve_run", dict(report))
    return state, stream, report
