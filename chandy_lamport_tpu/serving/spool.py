"""The write-ahead admission spool: crash-tolerant exactly-once intake.

clsim-serve-ha's durability core. A fleet of server workers (see
serving/fleet.py) shares one append-only journal of admission records;
every request is fsync-appended (utils/atomicio.fsync_append — the
``wal-append`` AST rule pins all writes here to that helper, under the
exclusive utils/filelock lock) BEFORE the admit call returns, so an
acknowledged request survives any worker or supervisor SIGKILL.

**Record kinds** (JSON lines, each stamped ``wal_schema``):

  admit    the request payload + its content digest + wall-clock stamp —
           the durable acknowledgement. Re-admitting an identical
           (job, digest) is an idempotent no-op; the same job with a
           DIFFERENT digest is an aliasing bug and raises SpoolError.
  lease    job -> worker with an absolute expiry and the attempt number.
  renew    heartbeat: extends a live lease's expiry (same worker only).
  done     the exactly-once commit point: the served summary, accepted
           only while the writer still holds the lease. A journal with
           two done records for one job is a double-serve and replay
           refuses it loudly.
  fail     a worker-reported execution error; releases the lease and
           records provenance.
  requeue  a reclaimed lease (expiry, or the supervisor declaring the
           worker dead) — the job returns to the pending pool and the
           reason joins its provenance trail.
  poison   quarantine after the attempt budget: the job leaves the
           pending pool forever, carrying its full decoded error
           provenance instead of crash-looping the fleet.
  shed     deadline-aware load shedding (serving/admission.shed_order):
           dropped under backlog pressure, with the reason recorded.

**Concurrency + crash model.** Every mutating operation runs the same
transaction under the exclusive lock: incrementally replay the journal
tail (other processes may have appended since we last looked), decide
against the replayed state, append, apply. Appends are whole fsynced
lines, so the only torn shape a SIGKILL can leave is a newline-less
prefix at EOF — replay truncates it away and counts it
(``torn_tail_truncated``), mirroring utils/tracing.read_telemetry's
torn-line handling. Damage anywhere else — unparsable records mid-file,
a missing/foreign ``wal_schema`` — raises ``SpoolError`` naming the
path: a spool that guessed would re-serve or drop requests silently.

**Exactly-once.** Execution is at-least-once (a reclaimed lease's job
runs again elsewhere), but *serving* is exactly-once: ``complete`` is
the only path to a done record, it verifies lease ownership under the
lock, and replay rejects a second done structurally. A slow-but-alive
worker whose lease was taken over gets ``False`` back and discards its
late result. ``audit()`` re-derives the whole ledger from byte zero and
proves the conservation law: admitted == served + poisoned + shed +
still-pending + still-leased, with zero double-serves.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

from chandy_lamport_tpu.core.spec import (
    Event,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.models.workloads import ServeRequest
from chandy_lamport_tpu.utils.atomicio import crash_failpoint, fsync_append
from chandy_lamport_tpu.utils.filelock import locked

# THE spool journal schema version: one named registry constant, bumped
# on any breaking change of the record layout (an old journal must be
# refused, not misread — it arbitrates exactly-once serving).
WAL_SCHEMA_VERSION = 1


class SpoolError(ValueError):
    """The admission spool journal could not be read, validated or
    safely appended to. Always carries the path; raised instead of
    guessing — a spool that guesses loses or double-serves requests."""


# ---------------------------------------------------------------------------
# request (de)serialization — the WAL payload encoding


def encode_events(events: Sequence[Event]) -> List[list]:
    """Event list -> JSON-able rows (``["pass", src, dest, n]``,
    ``["snap", node]``, ``["tick", n]``)."""
    rows: List[list] = []
    for ev in events:
        if isinstance(ev, PassTokenEvent):
            rows.append(["pass", ev.src, ev.dest, int(ev.tokens)])
        elif isinstance(ev, SnapshotEvent):
            rows.append(["snap", ev.node_id])
        elif isinstance(ev, TickEvent):
            rows.append(["tick", int(ev.n)])
        else:
            raise SpoolError(
                f"cannot journal event {ev!r}: unknown event type "
                f"{type(ev).__name__}")
    return rows


def decode_events(rows: Sequence[list]) -> List[Event]:
    """Inverse of encode_events; unknown tags raise SpoolError."""
    out: List[Event] = []
    for row in rows:
        tag = row[0] if row else None
        if tag == "pass":
            out.append(PassTokenEvent(src=row[1], dest=row[2],
                                      tokens=int(row[3])))
        elif tag == "snap":
            out.append(SnapshotEvent(node_id=row[1]))
        elif tag == "tick":
            out.append(TickEvent(int(row[1])))
        else:
            raise SpoolError(f"cannot decode journaled event row {row!r}")
    return out


def encode_request(req: ServeRequest) -> dict:
    return {"job": int(req.job), "arrival_step": int(req.arrival_step),
            "tenant": int(req.tenant), "priority": int(req.priority),
            "deadline_step": int(req.deadline_step),
            "events": encode_events(req.events)}


def decode_request(d: dict) -> ServeRequest:
    return ServeRequest(job=int(d["job"]),
                        arrival_step=int(d["arrival_step"]),
                        tenant=int(d["tenant"]),
                        priority=int(d["priority"]),
                        deadline_step=int(d["deadline_step"]),
                        events=decode_events(d["events"]))


def request_digest(req: ServeRequest) -> str:
    """The spool's content address for one request: sha256 over the
    canonical journal encoding. jax-free on purpose (the supervisor must
    admit without building an engine); distinct from the memo plane's
    job_digest, which additionally folds in topology/config/knobs — this
    digest arbitrates WAL idempotency, that one arbitrates summary
    reuse."""
    blob = json.dumps({"wal_schema": WAL_SCHEMA_VERSION,
                       "request": encode_request(req)},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


# ---------------------------------------------------------------------------
# the spool


class AdmissionSpool:
    """One process's handle on the shared journal (module docstring).

    ``lease_ttl`` is the heartbeat horizon in seconds — a worker that
    neither renews nor completes within it is presumed dead and its
    jobs are redelivered. ``max_attempts`` bounds redelivery before
    quarantine. ``clock`` is injectable for deterministic tests; it must
    be a wall clock shared across cooperating processes (the default).
    """

    def __init__(self, path: str, *, lease_ttl: float = 10.0,
                 max_attempts: int = 3,
                 clock: Callable[[], float] = time.time):
        if lease_ttl <= 0:
            raise ValueError("lease_ttl must be > 0 seconds")
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.path = path
        self.lease_ttl = float(lease_ttl)
        self.max_attempts = int(max_attempts)
        self.clock = clock
        self._offset = 0
        self.requests: Dict[int, ServeRequest] = {}
        self.digests: Dict[int, str] = {}
        self.admit_t: Dict[int, float] = {}
        self.leases: Dict[int, dict] = {}     # job -> {worker, expires}
        self.attempts: Dict[int, int] = {}
        self.done: Dict[int, dict] = {}       # job -> summary
        self.done_by: Dict[int, str] = {}
        self.done_t: Dict[int, float] = {}
        self.errors: Dict[int, List[str]] = {}
        self.poisoned: Dict[int, dict] = {}   # job -> {attempts, errors}
        self.shed: Dict[int, str] = {}        # job -> reason
        self.books = {"torn_tail_truncated": 0, "requeues": 0,
                      "leases": 0, "renews": 0}
        if os.path.exists(path):
            with locked(path):
                self._replay()

    # -- journal mechanics (always under the exclusive lock) -------------

    def _replay(self) -> None:
        """Incrementally scan the journal from the last consumed offset.
        MUST run under the exclusive lock (it may truncate a torn tail,
        and the decisions layered on it assume no concurrent append)."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                blob = f.read()
        except FileNotFoundError:
            return
        pos = 0
        while pos < len(blob):
            nl = blob.find(b"\n", pos)
            if nl < 0:
                # a crashed writer's partial append: fsynced whole lines
                # mean a prefix-without-newline at EOF is the ONLY legal
                # torn shape — truncate it so the next append lands on a
                # record boundary (telemetry's torn-line discipline)
                os.truncate(self.path, self._offset + pos)
                self.books["torn_tail_truncated"] += 1
                self._offset += pos
                return
            line = blob[pos:nl]
            at = self._offset + pos
            pos = nl + 1
            if not line.strip():
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as exc:
                raise SpoolError(
                    f"admission spool {self.path}: corrupt record at "
                    f"byte {at} ({exc}) — damage before the final record "
                    f"cannot be a torn append; refusing to guess") from exc
            if not isinstance(rec, dict) or "kind" not in rec \
                    or "wal_schema" not in rec:
                raise SpoolError(
                    f"admission spool {self.path}: record at byte {at} "
                    f"has no kind/wal_schema keys — not a spool record")
            if rec["wal_schema"] != WAL_SCHEMA_VERSION:
                raise SpoolError(
                    f"admission spool {self.path}: record at byte {at} "
                    f"has wal_schema {rec['wal_schema']!r}; this build "
                    f"reads only v{WAL_SCHEMA_VERSION} — a stale or "
                    f"future journal must not arbitrate exactly-once "
                    f"serving; migrate or remove it")
            self._apply(rec)
        self._offset += pos

    def _apply(self, rec: dict) -> None:
        kind = rec["kind"]
        j = int(rec["job"])
        if kind == "admit":
            if j in self.requests:
                if self.digests[j] != rec["digest"]:
                    raise SpoolError(
                        f"admission spool {self.path}: job {j} admitted "
                        f"twice with different digests — job ids alias "
                        f"two distinct requests")
                return
            self.requests[j] = decode_request(rec["request"])
            self.digests[j] = rec["digest"]
            self.admit_t[j] = float(rec["t"])
            self.errors.setdefault(j, [])
            return
        if j not in self.requests:
            raise SpoolError(
                f"admission spool {self.path}: {kind} record for job {j} "
                f"which was never admitted")
        if kind == "lease":
            self.leases[j] = {"worker": rec["worker"],
                              "expires": float(rec["expires"])}
            self.attempts[j] = int(rec["attempt"])
            self.books["leases"] += 1
        elif kind == "renew":
            lease = self.leases.get(j)
            if lease is not None and lease["worker"] == rec["worker"]:
                lease["expires"] = float(rec["expires"])
            self.books["renews"] += 1
        elif kind == "done":
            if j in self.done:
                raise SpoolError(
                    f"admission spool {self.path}: two done records for "
                    f"job {j} — a double-serve reached the journal")
            self.done[j] = rec["summary"]
            self.done_by[j] = rec["worker"]
            self.done_t[j] = float(rec["t"])
            self.leases.pop(j, None)
        elif kind == "fail":
            self.errors.setdefault(j, []).append(rec["error"])
            lease = self.leases.get(j)
            if lease is not None and lease["worker"] == rec["worker"]:
                self.leases.pop(j)
        elif kind == "requeue":
            self.errors.setdefault(j, []).append(rec["reason"])
            self.leases.pop(j, None)
            self.books["requeues"] += 1
        elif kind == "poison":
            self.poisoned[j] = {"attempts": int(rec["attempts"]),
                                "errors": list(rec["errors"])}
            self.leases.pop(j, None)
        elif kind == "shed":
            self.shed[j] = rec["reason"]
            self.leases.pop(j, None)
        else:
            raise SpoolError(
                f"admission spool {self.path}: unknown record kind "
                f"{kind!r}")

    def _append(self, rec: dict) -> None:
        """Durably append one record and apply it. Lock must be held;
        the fsync completes before return, so callers may acknowledge."""
        rec = {"wal_schema": WAL_SCHEMA_VERSION, **rec}
        line = (json.dumps(rec, sort_keys=True, separators=(",", ":"))
                + "\n").encode("utf-8")
        crash_failpoint("spool-append")
        with open(self.path, "ab") as f:
            self._offset += fsync_append(f, line)
        self._apply(rec)

    # -- views (of the last replayed state; call refresh() first when
    #    cross-process freshness matters) --------------------------------

    def refresh(self) -> None:
        """Fold in records other processes appended since we last
        looked."""
        with locked(self.path):
            self._replay()

    def pending(self) -> List[int]:
        """Admitted jobs currently owned by no one — leasable."""
        return sorted(j for j in self.requests
                      if j not in self.leases and j not in self.done
                      and j not in self.poisoned and j not in self.shed)

    def finished(self) -> bool:
        """Every admitted request reached a terminal state (served,
        poisoned or shed)."""
        return (len(self.done) + len(self.poisoned) + len(self.shed)
                == len(self.requests))

    def results(self) -> Dict[int, dict]:
        return dict(self.done)

    def counters(self) -> dict:
        """Telemetry snapshot of the replayed ledger."""
        return {"admitted": len(self.requests), "served": len(self.done),
                "poisoned": len(self.poisoned), "shed": len(self.shed),
                "pending": len(self.pending()), "leased": len(self.leases),
                **self.books}

    # -- transactions ----------------------------------------------------

    def admit(self, req: ServeRequest, digest: Optional[str] = None,
              now: Optional[float] = None) -> bool:
        """Durably admit one request; returns True when this call wrote
        the record, False when an identical admit already exists (the
        idempotent re-send after a crashed ack). The fsync completes
        before return — returning IS the acknowledgement."""
        digest = digest if digest is not None else request_digest(req)
        with locked(self.path):
            self._replay()
            if req.job in self.requests:
                if self.digests[req.job] != digest:
                    raise SpoolError(
                        f"admission spool {self.path}: job {req.job} "
                        f"already admitted with a different digest — "
                        f"refusing to alias two requests onto one id")
                return False
            self._append({"kind": "admit", "job": int(req.job),
                          "digest": digest,
                          "request": encode_request(req),
                          "t": self.clock() if now is None else now})
            return True

    def lease(self, worker: str, limit: int = 1,
              now: Optional[float] = None) -> List[ServeRequest]:
        """Take up to ``limit`` pending jobs for ``worker``, in
        deterministic (arrival, job) order, each with an fsynced lease
        record expiring ``lease_ttl`` from now."""
        with locked(self.path):
            self._replay()
            now = self.clock() if now is None else now
            out: List[ServeRequest] = []
            order = sorted(self.pending(),
                           key=lambda j: (self.requests[j].arrival_step, j))
            for j in order[:max(int(limit), 0)]:
                self._append({"kind": "lease", "job": j, "worker": worker,
                              "expires": now + self.lease_ttl,
                              "attempt": self.attempts.get(j, 0) + 1,
                              "t": now})
                out.append(self.requests[j])
            return out

    def renew(self, worker: str, jobs: Sequence[int],
              now: Optional[float] = None) -> List[int]:
        """Heartbeat: extend the expiry of the leases ``worker`` still
        holds. Returns the jobs actually renewed — a job missing from
        the return was reclaimed (or finished) and the worker should
        abandon it."""
        with locked(self.path):
            self._replay()
            now = self.clock() if now is None else now
            renewed: List[int] = []
            for j in jobs:
                lease = self.leases.get(int(j))
                if lease is not None and lease["worker"] == worker:
                    self._append({"kind": "renew", "job": int(j),
                                  "worker": worker,
                                  "expires": now + self.lease_ttl,
                                  "t": now})
                    renewed.append(int(j))
            return renewed

    def complete(self, job: int, worker: str, summary: dict,
                 now: Optional[float] = None) -> bool:
        """The exactly-once commit: record the served summary iff
        ``worker`` still holds the lease and the job has no terminal
        record. Returns False (result must be discarded) when the lease
        was reclaimed — the redelivered copy owns the serve now."""
        with locked(self.path):
            self._replay()
            job = int(job)
            if job in self.done or job in self.poisoned or job in self.shed:
                return False
            lease = self.leases.get(job)
            if lease is None or lease["worker"] != worker:
                return False
            self._append({"kind": "done", "job": job, "worker": worker,
                          "summary": summary,
                          "t": self.clock() if now is None else now})
            return True

    def fail(self, job: int, worker: str, error: str,
             now: Optional[float] = None) -> None:
        """Record a worker-reported execution failure and release the
        lease; the job returns to the pending pool (or is poisoned at
        the next reclaim if its attempt budget is spent)."""
        with locked(self.path):
            self._replay()
            lease = self.leases.get(int(job))
            if lease is None or lease["worker"] != worker:
                return
            self._append({"kind": "fail", "job": int(job), "worker": worker,
                          "error": str(error),
                          "t": self.clock() if now is None else now})

    def _requeue_or_poison(self, j: int, reason: str, now: float) -> str:
        if self.attempts.get(j, 0) >= self.max_attempts:
            self._append({"kind": "poison", "job": j,
                          "attempts": self.attempts.get(j, 0),
                          "errors": self.errors.get(j, []) + [reason],
                          "t": now})
            return "poisoned"
        self._append({"kind": "requeue", "job": j, "reason": reason,
                      "from_worker": self.leases[j]["worker"], "t": now})
        return "requeued"

    def reclaim_expired(self, now: Optional[float] = None) -> dict:
        """Redeliver every job whose lease expired without a heartbeat:
        requeue (the takeover path) or poison once the attempt budget is
        spent. Returns ``{"requeued": [...], "poisoned": [...]}``."""
        with locked(self.path):
            self._replay()
            now = self.clock() if now is None else now
            out = {"requeued": [], "poisoned": []}
            for j in sorted(self.leases):
                lease = self.leases[j]
                if lease["expires"] <= now:
                    verdict = self._requeue_or_poison(
                        j, f"lease expired on worker {lease['worker']} "
                           f"(attempt {self.attempts.get(j, 0)}"
                           f"/{self.max_attempts})", now)
                    out[verdict].append(j)
            return out

    def requeue_worker(self, worker: str, reason: str,
                       now: Optional[float] = None) -> dict:
        """Redeliver every lease ``worker`` holds, without waiting for
        expiry — the supervisor's fast path when it has direct evidence
        of death (exit code / signal), which becomes the provenance."""
        with locked(self.path):
            self._replay()
            now = self.clock() if now is None else now
            out = {"requeued": [], "poisoned": []}
            for j in sorted(self.leases):
                if self.leases[j]["worker"] == worker:
                    out[self._requeue_or_poison(j, reason, now)].append(j)
            return out

    def shed_jobs(self, jobs: Sequence[int], reason: str,
                  now: Optional[float] = None) -> List[int]:
        """Drop pending (never in-flight) jobs under load pressure; the
        caller picks victims with serving/admission.shed_order."""
        with locked(self.path):
            self._replay()
            now = self.clock() if now is None else now
            pend = set(self.pending())
            out: List[int] = []
            for j in jobs:
                if int(j) in pend:
                    self._append({"kind": "shed", "job": int(j),
                                  "reason": reason, "t": now})
                    out.append(int(j))
            return out

    # -- the audit -------------------------------------------------------

    def audit(self) -> dict:
        """Re-derive the ledger from byte zero in a fresh handle and
        prove the conservation law (module docstring): no admitted
        request is lost, none is double-served. Replay itself refuses a
        journal with two done records, so a returned audit always
        carries ``double_served == 0``; ``lost`` counts admits with no
        surviving state of any kind (impossible unless the journal was
        tampered with — it is the invariant the chaos harness pins)."""
        fresh = AdmissionSpool(self.path, lease_ttl=self.lease_ttl,
                               max_attempts=self.max_attempts,
                               clock=self.clock)
        accounted = (len(fresh.done) + len(fresh.poisoned)
                     + len(fresh.shed) + len(fresh.pending())
                     + len(fresh.leases))
        return {"admitted": len(fresh.requests), "served": len(fresh.done),
                "poisoned": len(fresh.poisoned), "shed": len(fresh.shed),
                "pending": len(fresh.pending()),
                "leased": len(fresh.leases),
                "lost": len(fresh.requests) - accounted,
                "double_served": 0,
                "torn_tail_truncated": fresh.books["torn_tail_truncated"],
                "digests_ok": all(
                    fresh.digests[j] == request_digest(fresh.requests[j])
                    for j in fresh.requests)}
