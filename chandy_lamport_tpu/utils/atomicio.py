"""Durable write primitives shared by every on-disk artifact writer.

The atomic-rename discipline (tmp-then-``os.replace``, checkpoint.py)
protects readers from a *killed writer*: the old complete file survives
any SIGKILL. It does NOT, by itself, protect against the page cache: an
``os.replace`` whose tmp bytes were never fsynced can commit a name that
points at data the kernel has not written back, so a power cut (or a
container teardown) after the rename leaves the NEW name holding torn
bytes — exactly the artifact the rename was supposed to make impossible.
The serve-ha write-ahead spool (serving/spool.py) raises the stakes: its
append IS the acknowledgement, so an un-fsynced ack is a lost request.

This module is the one home for the missing fsync coverage:

``fsync_append(f, data)``
    THE named append helper for write-ahead logs (the ``wal-append`` AST
    rule pins serving/spool.py's writes to it): write + flush +
    ``os.fsync`` before returning, so the record is on stable storage
    the moment the caller acks. ``f`` must be opened in binary append
    mode; returns the byte count so callers can track file offsets.

``fsync_file(f)`` / ``fsync_dir(path)``
    flush+fsync an open handle; fsync the parent directory so the
    *rename itself* is durable (POSIX leaves directory entries to their
    own writeback). Directory fsync is best-effort — some filesystems
    refuse it — because it only widens the power-cut window, never the
    kill window.

``crash_failpoint(name)``
    the kill-in-the-window test hook: SIGKILL this process iff the
    ``CLSIM_IO_FAILPOINT`` env var names this site. Writers place it in
    their tmp-write -> replace window (and the spool before its append)
    so tests can prove a killed writer leaves the previous file loadable
    and the journal on a record boundary. A no-op in production (env
    unset).
"""

from __future__ import annotations

import os
import signal

# set to a site name ("memocache-replace", "checkpoint-replace",
# "execcache-replace", "spool-append") to SIGKILL the process at that
# site — the chaos/recovery tests' deterministic mid-write kill
FAILPOINT_ENV = "CLSIM_IO_FAILPOINT"


def crash_failpoint(name: str) -> None:
    """Die by SIGKILL iff the failpoint env var names this site."""
    if os.environ.get(FAILPOINT_ENV) == name:
        os.kill(os.getpid(), signal.SIGKILL)


def fsync_file(f) -> None:
    """Flush python buffers and fsync the OS file — the caller's bytes
    are on stable storage when this returns."""
    f.flush()
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """Best-effort fsync of ``path``'s parent directory, making a just-
    committed rename durable across power loss. Filesystems that refuse
    directory fsync degrade silently — the kill-safety story does not
    depend on it."""
    parent = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(parent, os.O_RDONLY)
    except OSError:
        return
    try:
        try:
            os.fsync(fd)
        except OSError:
            pass
    finally:
        os.close(fd)


def fsync_append(f, data: bytes) -> int:
    """Durably append ``data`` to the open binary handle ``f``: the
    write, a flush and an ``os.fsync`` complete before return, so a
    record appended here may be acknowledged to the caller. The one
    legal torn shape a mid-append kill can leave is a proper PREFIX of
    ``data`` at EOF (the spool's replay truncates it away). Returns
    ``len(data)`` for offset bookkeeping."""
    f.write(data)
    f.flush()
    os.fsync(f.fileno())
    return len(data)
