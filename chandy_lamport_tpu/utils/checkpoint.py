"""Checkpoint / resume / snapshot-rollback recovery for simulation state.

The reference has no checkpointing — the *product* is the checkpoint
primitive (a Chandy-Lamport snapshot is a consistent global checkpoint,
GlobalSnapshot common.go:13-17). Here the simulator's own state is a pytree
of arrays, so checkpointing falls out for free (SURVEY.md §5): worth having
because 1M-instance storm runs are long and the hardware running them is
preemptible.

Preemption safety: ``save_state`` writes tmp-then-``os.replace``, so a kill
at ANY instant leaves either the previous complete checkpoint or the new
complete checkpoint on disk — never a truncated file a resume would trip
over. ``load_state`` wraps every way a file can be damaged (truncated zip,
garbage bytes, missing header) in ``CheckpointError`` naming the path.
``restore_from_snapshot`` is the protocol-level recovery line: it rebuilds a
runnable state from a COMPLETED Chandy-Lamport snapshot's consistent cut
(frozen balances + recorded in-flight messages), which is how a crashed
lane rolls back without any framework checkpoint at all.

Format: one ``.npz`` per checkpoint holding every DenseState leaf plus the
delay-state leaves, with a tiny JSON header validating shape compatibility on
restore. Works for single-instance and batched (any batch axis) states alike.
Orbax is available in this image but is deliberately not used: the state is a
flat NamedTuple of dense arrays, np.savez is loss-free, dependency-free and
inspectable.
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib
from typing import Tuple

import jax
import numpy as np

from chandy_lamport_tpu.core.state import CHECKPOINT_FORMAT_VERSION, DenseState
from chandy_lamport_tpu.utils.atomicio import (
    crash_failpoint,
    fsync_dir,
    fsync_file,
)

# The version history table lives beside the state plan it versions:
# core/state.py CHECKPOINT_FORMAT_HISTORY, one row per breaking layout
# change with what changed and why an older file errors instead of
# misaligning leaves. This binding is literal-free on purpose — bumping
# the format means appending a history row there, and staticcheck's
# ckpt-version-literal rule flags any restated version literal here.
_FORMAT_VERSION = CHECKPOINT_FORMAT_VERSION
# every layout change so far has been breaking (leaves added or reshaped),
# so exactly one version is live; kept as a range so a future
# backward-compatible revision can widen the floor without touching the
# error message
_MIN_SUPPORTED_VERSION = _FORMAT_VERSION


class CheckpointError(ValueError):
    """A checkpoint file could not be read or validated. Always carries the
    path; raised instead of leaking numpy/zipfile tracebacks from a
    truncated or corrupt file (the exact artifact a mid-write kill used to
    leave behind, before writes were atomic)."""


def save_state(path: str, state: DenseState, meta: dict | None = None) -> None:
    """Serialize a (possibly batched) DenseState to ``path`` (.npz),
    atomically: the bytes land in ``path + '.tmp'`` and are renamed over
    ``path`` only once complete, so a kill mid-write can never destroy the
    previous checkpoint or leave a truncated one."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    host = [np.asarray(x) for x in jax.device_get(leaves)]
    header = {
        "format_version": _FORMAT_VERSION,
        "num_leaves": len(host),
        "treedef": str(treedef),
        "meta": meta or {},
    }
    arrays = {f"leaf_{i}": a for i, a in enumerate(host)}
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    tmp = path + ".tmp"
    try:
        # an open file handle (not a bare path) stops np.savez appending
        # ".npz" to the tmp name, which would break the rename
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            fsync_file(f)
        crash_failpoint("checkpoint-replace")
        os.replace(tmp, path)
        fsync_dir(path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_state(path: str, like: DenseState) -> Tuple[DenseState, dict]:
    """Restore a DenseState saved by save_state. ``like`` supplies the pytree
    structure (build it with the same topology/config/delay as the saved
    run); shapes are validated leaf by leaf. Every failure mode — unreadable
    file, truncated/corrupt zip, missing header, version/structure/shape
    mismatch — raises CheckpointError naming the path."""
    try:
        with np.load(path) as z:
            if "__header__" not in z.files:
                raise CheckpointError(
                    f"checkpoint {path}: no __header__ entry — truncated "
                    f"write or not a clsim checkpoint")
            header = json.loads(bytes(z["__header__"]).decode())
            version = header["format_version"]
            if not _MIN_SUPPORTED_VERSION <= version <= _FORMAT_VERSION:
                raise CheckpointError(
                    f"checkpoint {path}: unsupported format version "
                    f"{version} (this build reads the supported version "
                    f"range v{_MIN_SUPPORTED_VERSION}..v{_FORMAT_VERSION}; "
                    f"see CHECKPOINT_FORMAT_HISTORY in core/state.py)")
            leaves = [z[f"leaf_{i}"] for i in range(header["num_leaves"])]
    except CheckpointError:
        raise
    except (OSError, ValueError, KeyError, zipfile.BadZipFile, zlib.error,
            EOFError, UnicodeDecodeError) as exc:
        # json.JSONDecodeError is a ValueError; truncated members surface
        # as BadZipFile/zlib.error/EOFError depending on where the zip
        # was cut; garbage bytes as ValueError from np.load
        raise CheckpointError(
            f"checkpoint {path}: unreadable or corrupt "
            f"({type(exc).__name__}: {exc})") from exc
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if header["treedef"] != str(treedef):
        raise CheckpointError(
            f"checkpoint {path}: treedef {header['treedef']!r} != expected "
            f"{str(treedef)!r} — different state structure (backend/delay "
            f"model mismatch?)")
    if len(like_leaves) != len(leaves):
        raise CheckpointError(
            f"checkpoint {path}: has {len(leaves)} leaves, expected "
            f"{len(like_leaves)} — topology/config mismatch?")
    for i, (a, b) in enumerate(zip(leaves, like_leaves)):
        if np.shape(a) != np.shape(b):
            raise CheckpointError(
                f"checkpoint {path}: leaf {i} shape {np.shape(a)} != "
                f"expected {np.shape(b)} — topology/config/batch mismatch?")
        if np.dtype(np.asarray(a).dtype) != np.dtype(np.asarray(b).dtype):
            raise CheckpointError(
                f"checkpoint {path}: leaf {i} dtype {np.asarray(a).dtype} "
                f"!= expected {np.asarray(b).dtype}")
    return jax.tree_util.tree_unflatten(treedef, leaves), header["meta"]


def restore_from_snapshot(topo, cfg, host: DenseState, sid: int,
                          delay_state=(), fault_key: int = 0) -> DenseState:
    """Snapshot-rollback recovery: rebuild a runnable single-instance state
    from COMPLETED snapshot ``sid``'s consistent cut — the protocol's own
    artifact as the recovery line (a Chandy-Lamport snapshot IS a
    consistent global checkpoint, GlobalSnapshot common.go:13-17).

    The cut: node balances = the snapshot's frozen values; channel state =
    exactly the recorded in-flight messages, re-enqueued in their recorded
    (FIFO) order with receive time 1, so replaying the restored state
    delivers precisely the messages the cut counted as in flight. When no
    traffic follows the cut, replay-to-quiescence reproduces the original
    run's final balances bit-exactly (tests/test_faults.py validates this
    against an uninterrupted run); conservation across the cut holds by
    the consistency of the cut itself.

    ``host`` must be a single-instance host-side state (pick one lane of a
    batched run with ``tree_map(lambda x: x[i], state)`` first). Raises
    CheckpointError when ``sid`` never completed (an incomplete snapshot
    is not a consistent cut) or the recorded backlog of some edge exceeds
    the queue capacity of ``cfg``.
    """
    from chandy_lamport_tpu.core.state import (
        init_state,
        pack_meta,
        recorded_window,
    )

    host = jax.device_get(host)
    n = topo.n
    started = bool(np.asarray(host.started)[sid])
    completed = int(np.asarray(host.completed)[sid])
    if not started or completed < n:
        raise CheckpointError(
            f"snapshot {sid} is not a completed recovery line "
            f"(started={started}, completed={completed}/{n}) — a partial "
            f"snapshot is not a consistent cut")
    fresh = init_state(topo, cfg, delay_state, fault_key=fault_key)
    tokens = np.asarray(host.frozen)[sid].astype(np.int32).copy()
    q_meta = np.asarray(fresh.q_meta).copy()
    q_data = np.asarray(fresh.q_data).copy()
    q_len = np.asarray(fresh.q_len).copy()
    tok_pushed = np.asarray(fresh.tok_pushed).copy()
    c = cfg.queue_capacity
    for e in range(topo.e):
        amts = recorded_window(host, sid, e)
        if len(amts) > c:
            raise CheckpointError(
                f"snapshot {sid}: edge {e} recorded {len(amts)} in-flight "
                f"messages > queue_capacity {c} — restore with a larger "
                f"SimConfig.queue_capacity")
        for k, amt in enumerate(amts):
            q_meta[e, k] = pack_meta(1, False)   # deliverable from tick 1
            q_data[e, k] = amt
        q_len[e] = len(amts)
        tok_pushed[e] = len(amts)
    return fresh._replace(tokens=tokens, q_meta=q_meta, q_data=q_data,
                          q_len=q_len, tok_pushed=tok_pushed)
