"""Checkpoint / resume for simulation state.

The reference has no checkpointing — the *product* is the checkpoint
primitive (a Chandy-Lamport snapshot is a consistent global checkpoint,
GlobalSnapshot common.go:13-17). Here the simulator's own state is a pytree
of arrays, so checkpointing falls out for free (SURVEY.md §5): worth having
because 1M-instance storm runs are long.

Format: one ``.npz`` per checkpoint holding every DenseState leaf plus the
delay-state leaves, with a tiny JSON header validating shape compatibility on
restore. Works for single-instance and batched (any batch axis) states alike.
Orbax is available in this image but is deliberately not used: the state is a
flat NamedTuple of dense arrays, np.savez is loss-free, dependency-free and
inspectable.
"""

from __future__ import annotations

import json
from typing import Tuple

import jax
import numpy as np

from chandy_lamport_tpu.core.state import DenseState

# version history:
#   1 — round-2 DenseState (q_seq/seq_next/m_seq/rec_len/rec_data leaves)
#   2 — round-3 window-log/merge-key state (tok_pushed/mk_cnt/m_key/rec_cnt/
#       min_prot/log_amt/rec_start/rec_end) + round-4 three-word hash-delay
#       state; old checkpoints get the unsupported-version error instead of
#       a misleading leaf-count mismatch
#   3 — PR-2 packed ring slots: the q_marker/q_data/q_rtime planes became
#       q_meta (rtime << 1 | is_marker) + q_data (core/state.py "Packed
#       ring slots"); a version-2 checkpoint's separate marker/rtime leaves
#       cannot be reinterpreted, so they error here rather than misdecode
_FORMAT_VERSION = 3


def save_state(path: str, state: DenseState, meta: dict | None = None) -> None:
    """Serialize a (possibly batched) DenseState to ``path`` (.npz)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    host = [np.asarray(x) for x in jax.device_get(leaves)]
    header = {
        "format_version": _FORMAT_VERSION,
        "num_leaves": len(host),
        "treedef": str(treedef),
        "meta": meta or {},
    }
    arrays = {f"leaf_{i}": a for i, a in enumerate(host)}
    arrays["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


def load_state(path: str, like: DenseState) -> Tuple[DenseState, dict]:
    """Restore a DenseState saved by save_state. ``like`` supplies the pytree
    structure (build it with the same topology/config/delay as the saved
    run); shapes are validated leaf by leaf."""
    with np.load(path) as z:
        header = json.loads(bytes(z["__header__"]).decode())
        if header["format_version"] != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version "
                             f"{header['format_version']}")
        leaves = [z[f"leaf_{i}"] for i in range(header["num_leaves"])]
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if header["treedef"] != str(treedef):
        raise ValueError(
            f"checkpoint treedef {header['treedef']!r} != expected "
            f"{str(treedef)!r} — different state structure (backend/delay "
            f"model mismatch?)")
    if len(like_leaves) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves, expected "
            f"{len(like_leaves)} — topology/config mismatch?")
    for i, (a, b) in enumerate(zip(leaves, like_leaves)):
        if np.shape(a) != np.shape(b):
            raise ValueError(
                f"leaf {i}: checkpoint shape {np.shape(a)} != expected "
                f"{np.shape(b)} — topology/config/batch mismatch?")
        if np.dtype(np.asarray(a).dtype) != np.dtype(np.asarray(b).dtype):
            raise ValueError(
                f"leaf {i}: checkpoint dtype {np.asarray(a).dtype} != "
                f"expected {np.asarray(b).dtype}")
    return jax.tree_util.tree_unflatten(treedef, leaves), header["meta"]
