"""Golden snapshot comparison + token-conservation invariant.

Ports the exact comparison semantics of the reference test harness:
  - assert_snapshots_equal   test_common.go:222-285
  - sort_snapshots           test_common.go:288-294
  - check_tokens             test_common.go:298-328
"""

from __future__ import annotations

from typing import Dict, List

from chandy_lamport_tpu.core.spec import GlobalSnapshot, MsgSnapshot


class SnapshotMismatch(AssertionError):
    pass


def sort_snapshots(snaps: List[GlobalSnapshot]) -> List[GlobalSnapshot]:
    """Ascending by snapshot id (test_common.go:288-294)."""
    return sorted(snaps, key=lambda s: s.id)


def assert_snapshots_equal(expected: GlobalSnapshot, actual: GlobalSnapshot) -> None:
    """Equality up to cross-destination message interleaving.

    Per the reference (test_common.go:253-284): ids, token maps and total
    message counts must match exactly; messages are bucketed by destination
    and each destination's sequence must match exactly in order, while
    interleaving across destinations is ignored (collection order across
    nodes is nondeterministic in the reference, sim.go:146-166).
    """
    if expected.id != actual.id:
        raise SnapshotMismatch(f"snapshot ids differ: {expected.id} != {actual.id}")
    if len(expected.token_map) != len(actual.token_map):
        raise SnapshotMismatch(
            f"snapshot {expected.id}: node counts differ: "
            f"{sorted(expected.token_map)} vs {sorted(actual.token_map)}"
        )
    if len(expected.messages) != len(actual.messages):
        raise SnapshotMismatch(
            f"snapshot {expected.id}: message counts differ: "
            f"{_msgs_str(expected.messages)} vs {_msgs_str(actual.messages)}"
        )
    for nid, tok in expected.token_map.items():
        if actual.token_map.get(nid) != tok:
            raise SnapshotMismatch(
                f"snapshot {expected.id}: tokens on {nid} differ: "
                f"{tok} != {actual.token_map.get(nid)}"
            )
    exp_by_dest = _bucket_by_dest(expected.messages)
    act_by_dest = _bucket_by_dest(actual.messages)
    for dest, ems in exp_by_dest.items():
        ams = act_by_dest.get(dest, [])
        if ems != ams:
            raise SnapshotMismatch(
                f"snapshot {expected.id}: messages at {dest} differ:\n"
                f"expected: {_msgs_str(ems)}\nactual:   {_msgs_str(ams)}"
            )


def _bucket_by_dest(messages: List[MsgSnapshot]) -> Dict[str, List[MsgSnapshot]]:
    out: Dict[str, List[MsgSnapshot]] = {}
    for m in messages:
        out.setdefault(m.dest, []).append(m)
    return out


def _msgs_str(messages: List[MsgSnapshot]) -> str:
    return "[" + ", ".join(f"{m.src}->{m.dest}: {m.message}" for m in messages) + "]"


def check_tokens(current_node_tokens: Dict[str, int], snapshots: List[GlobalSnapshot]) -> None:
    """Token conservation (test_common.go:298-328): for every snapshot,
    sum(frozen node balances) + sum(non-marker recorded message tokens)
    must equal the simulator's current total token count."""
    expected = sum(current_node_tokens.values())
    for snap in snapshots:
        got = sum(snap.token_map.values()) + sum(
            m.message.data for m in snap.messages if not m.message.is_marker
        )
        if got != expected:
            raise SnapshotMismatch(
                f"snapshot {snap.id}: simulator has {expected} tokens, snapshot has {got}"
            )


def dense_state_mismatches(a, b) -> List[str]:
    """Field names where two DenseState pytrees are not bit-equal — every
    leaf compared with exact array equality (rings, shared log, recording
    windows, sticky error mask, and the delay sampler's stream position
    included). The oracle check behind the exact-tick differentials
    (tests/test_wave.py, tools/wave_sweep.py): an empty result means the
    two formulations produced indistinguishable simulations."""
    import jax
    import numpy as np

    bad = []
    for name in a._fields:
        xs = jax.tree_util.tree_leaves(getattr(a, name))
        ys = jax.tree_util.tree_leaves(getattr(b, name))
        if len(xs) != len(ys) or any(
                not np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(xs, ys)):
            bad.append(name)
    return bad
