"""Advisory cross-process file locking for shared on-disk caches.

The summary cache (``utils/memocache.py``) and the serving executable
cache (``serving/executables.py``) are shared mutable files: several
server processes pointed at one cache path race their atomic-rename
flushes, and last-writer-wins silently drops the other writers' entries
(ROADMAP item 4's "cross-process cache sharing with file locks"
headroom). ``locked(path)`` takes an advisory ``fcntl.flock`` on a
sidecar ``<path>.lock`` file — exclusive for read-merge-write flushes,
shared for loads — so cooperating processes serialize around the same
path without ever locking the data file itself (the data file is still
replaced atomically, so non-cooperating readers keep working).

On platforms without ``fcntl`` (or exotic filesystems rejecting flock)
the lock degrades to a no-op, preserving the old single-process
behavior; the AST lint rule ``cache-lock`` only demands the call sites
go through here.
"""

from __future__ import annotations

import contextlib
import os

try:  # pragma: no cover - fcntl is always present on the POSIX CI hosts
    import fcntl as _fcntl
except ImportError:  # pragma: no cover
    _fcntl = None


def lock_path(path: str) -> str:
    """Sidecar lock-file path for a cache data file."""
    return path + ".lock"


@contextlib.contextmanager
def locked(path: str, shared: bool = False):
    """Hold an advisory flock on ``lock_path(path)`` for the block.

    shared=True takes a read (LOCK_SH) lock — concurrent loads may
    overlap each other but not an exclusive flush. Blocks until granted.
    Yields True when a real lock is held, False when degraded to no-op.
    """
    if _fcntl is None or not path:
        yield False
        return
    lp = lock_path(path)
    parent = os.path.dirname(lp)
    if parent:
        os.makedirs(parent, exist_ok=True)
    try:
        fd = os.open(lp, os.O_RDWR | os.O_CREAT, 0o644)
    except OSError:
        yield False
        return
    try:
        try:
            _fcntl.flock(fd, _fcntl.LOCK_SH if shared else _fcntl.LOCK_EX)
        except OSError:
            yield False
            return
        yield True
        # flock drops with the fd; no explicit LOCK_UN needed
    finally:
        os.close(fd)
