"""Parsers for the ``.top`` / ``.events`` / ``.snap`` fixture formats.

Format specs (reference test_common.go:22-28, 70-78, 142-148):
  .top     first non-comment line = N; next N lines ``nodeId numTokens``;
           remaining lines ``src dest`` unidirectional links; ``#`` comments.
  .events  commands ``send SRC DEST K``, ``snapshot NODE``, ``tick [N]``
           (default N=1). Events between ticks share the same sim time.
  .snap    1 field = snapshot id; 2 fields = ``nodeId numTokens``;
           3 fields = ``src dest token(K)``. Goldens never contain markers
           (test_common.go:176-187 only parses token messages).

Unlike the reference, parsing is separated from execution: these functions
return pure data; backends execute it. Note the reference's .events comment
filter is inert due to swapped HasPrefix arguments (test_common.go:90) — no
fixture uses comments there, and we support them properly.
"""

from __future__ import annotations

import re
from typing import List, Tuple

from chandy_lamport_tpu.core.spec import (
    Event,
    GlobalSnapshot,
    Message,
    MsgSnapshot,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)


class TopologySpec:
    """Parsed topology: node ids with initial tokens + directed links."""

    def __init__(self, nodes: List[Tuple[str, int]], links: List[Tuple[str, str]]):
        self.nodes = nodes
        self.links = links

    @property
    def node_ids(self) -> List[str]:
        return [n for n, _ in self.nodes]


def _lines(path: str) -> List[str]:
    with open(path) as f:
        return [ln for ln in (raw.strip() for raw in f) if ln and not ln.startswith("#")]


def read_topology_file(path: str) -> TopologySpec:
    """Parse a ``.top`` file (reference test_common.go:29-68)."""
    lines = _lines(path)
    n = int(lines[0])
    nodes: List[Tuple[str, int]] = []
    links: List[Tuple[str, str]] = []
    for ln in lines[1:]:
        parts = ln.split()
        if len(parts) != 2:
            raise ValueError(f"expected 2 fields in line: {ln!r}")
        if len(nodes) < n:
            nodes.append((parts[0], int(parts[1])))
        else:
            links.append((parts[0], parts[1]))
    if len(nodes) != n:
        raise ValueError(f"expected {n} nodes, got {len(nodes)}")
    return TopologySpec(nodes, links)


def read_events_file(path: str) -> List[Event]:
    """Parse a ``.events`` file into a typed event list
    (reference test_common.go:79-121, execution factored out)."""
    events: List[Event] = []
    for ln in _lines(path):
        parts = ln.split()
        cmd = parts[0]
        if cmd == "send":
            events.append(PassTokenEvent(parts[1], parts[2], int(parts[3])))
        elif cmd == "snapshot":
            events.append(SnapshotEvent(parts[1]))
        elif cmd == "tick":
            events.append(TickEvent(int(parts[1]) if len(parts) > 1 else 1))
        else:
            raise ValueError(f"unknown event command: {cmd!r}")
    return events


_TOKEN_RE = re.compile(r"[0-9]+")


def read_snapshot_file(path: str) -> GlobalSnapshot:
    """Parse a ``.snap`` golden file (reference test_common.go:149-193)."""
    snap = GlobalSnapshot(0, {}, [])
    for ln in _lines(path):
        parts = ln.split()
        if len(parts) == 1:
            snap.id = int(parts[0])
        elif len(parts) == 2:
            snap.token_map[parts[0]] = int(parts[1])
        elif len(parts) == 3:
            if "token" not in parts[2]:
                raise ValueError(f"unknown message: {parts[2]!r}")
            m = _TOKEN_RE.findall(parts[2])
            if len(m) != 1:
                raise ValueError(f"unable to parse token message: {parts[2]!r}")
            snap.messages.append(
                MsgSnapshot(parts[0], parts[1], Message(is_marker=False, data=int(m[0])))
            )
        else:
            raise ValueError(f"bad snapshot line: {ln!r}")
    return snap
