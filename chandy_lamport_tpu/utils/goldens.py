"""The reference's 7 golden test cases (snapshot_test.go:46-108), shared by
the pytest suite, the table-search tool, and the CLI's ``test`` command."""

import os
from typing import List, Tuple

DATA_DIR = os.path.join(os.path.dirname(__file__), "..", "data", "test_data")

# (topology file, events file, golden snapshot files)
REFERENCE_TESTS: List[Tuple[str, str, List[str]]] = [
    ("2nodes.top", "2nodes-simple.events", ["2nodes-simple.snap"]),
    ("2nodes.top", "2nodes-message.events", ["2nodes-message.snap"]),
    ("3nodes.top", "3nodes-simple.events", ["3nodes-simple.snap"]),
    ("3nodes.top", "3nodes-bidirectional-messages.events",
     ["3nodes-bidirectional-messages.snap"]),
    ("8nodes.top", "8nodes-sequential-snapshots.events",
     [f"8nodes-sequential-snapshots{i}.snap" for i in range(2)]),
    ("8nodes.top", "8nodes-concurrent-snapshots.events",
     [f"8nodes-concurrent-snapshots{i}.snap" for i in range(5)]),
    ("10nodes.top", "10nodes.events", [f"10nodes{i}.snap" for i in range(10)]),
]


def fixture_path(name: str) -> str:
    return os.path.join(DATA_DIR, name)
