"""Runtime contract guards: no retraces, no un-sited transfers.

The streaming and serving loops claim a vectorized-MCMC-style discipline
(PAPERS.md): compile the step once, then never sync or retrace in steady
state. Nothing enforced it — a stray ``int(device_scalar)`` in the loop,
a numpy operand handed to a compiled call, or a shape-dependent
re-lowering all degrade silently. ``RuntimeGuards`` arms the three JAX
runtime contracts around the jitted loops:

  transfers   ``jax.transfer_guard("disallow")`` — implicit host<->device
              transfers raise at the offending line. Explicit transfers
              (``jax.device_get``/``device_put``/``jnp.asarray``) stay
              legal, and the engine routes every intentional one through
              a NAMED SITE (``guarded_get``/``guarded_put``) so the books
              record exactly which sync points fired and how often.
  leaks       ``jax.checking_leaks()`` — tracer leaks out of any trace
              started inside the armed region raise instead of deferring
              a crash to some unrelated later line.
  retraces    a ``jax.monitoring`` listener counts backend-compile events
              while armed; any compile after warmup is a retrace (new
              shapes, new static args, a rebuilt jit) and shows up in
              ``books()["compiles"]``.

Opt-in wiring: ``BatchedRunner(..., guards=RuntimeGuards())`` arms the
``run_stream`` loop, ``GraphShardedRunner(..., guards=...)`` the storm
dispatch, and ``serve_run(..., guards=...)`` the serve loop (defaulting
to the runner's). ``tools/staticcheck --plane runtime`` drives tiny
shapes per engine-knob row through warm loops under these guards and
fails on any retrace or un-sited transfer; the per-path site allowlists
live there, declaratively, not as a global off switch.

The module-level helpers are no-ops when ``guards`` is None, so the
default path pays nothing (the explicit ``device_get``/``device_put``
they always perform is what the hot loops should do anyway).
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional, Set

# guards currently inside an ``armed()`` region; the process-wide
# monitoring listener (installed once, on first arming) fans compile
# events out to every member. jax.monitoring has no unregister, so a
# dispatch set is the only clean lifetime model.
_ACTIVE: Set["RuntimeGuards"] = set()
_LISTENER_INSTALLED = False


def _on_compile_event(event: str, *args, **kwargs) -> None:
    if "backend_compile" not in event:
        return
    for g in tuple(_ACTIVE):
        g._compiles += 1


def _install_listener() -> bool:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring
        # backend_compile is a duration event in this jax; listen on both
        # channels so a future move between them cannot silently zero the
        # retrace counter
        monitoring.register_event_listener(
            lambda event, **kw: _on_compile_event(event))
        monitoring.register_event_duration_secs_listener(
            lambda event, duration, **kw: _on_compile_event(event))
        _LISTENER_INSTALLED = True
    except Exception:
        _LISTENER_INSTALLED = False
    return _LISTENER_INSTALLED


class RuntimeGuards:
    """Armable runtime contract checker (module docstring). One instance
    per drive; ``reset()`` between a warmup pass and the guarded pass
    separates compile noise from steady-state retraces."""

    def __init__(self, transfers: str = "disallow", leaks: bool = True):
        if transfers not in ("allow", "log", "disallow"):
            raise ValueError(
                f"transfers must be allow|log|disallow, got {transfers!r}")
        self.transfers = transfers
        self.leaks = bool(leaks)
        self._compiles = 0
        self._transfer_counts: Dict[str, int] = {}
        self._armed_regions = 0

    # -- books -----------------------------------------------------------

    def reset(self) -> None:
        self._compiles = 0
        self._transfer_counts = {}
        self._armed_regions = 0

    def books(self) -> dict:
        """JSON-able guard books: compile (retrace) events observed while
        armed, per-site explicit transfer counts, armed-region count."""
        return {
            "compiles": int(self._compiles),
            "transfers": dict(sorted(self._transfer_counts.items())),
            "armed_regions": int(self._armed_regions),
        }

    def count(self, site: str) -> None:
        self._transfer_counts[site] = self._transfer_counts.get(site, 0) + 1

    # -- arming ----------------------------------------------------------

    @contextlib.contextmanager
    def armed(self):
        """Arm transfer_guard + leak checking + the compile counter for a
        region (the steady-state device loop)."""
        import jax
        _install_listener()
        self._armed_regions += 1
        _ACTIVE.add(self)
        try:
            with jax.transfer_guard(self.transfers):
                if self.leaks:
                    with jax.checking_leaks():
                        yield self
                else:
                    yield self
        finally:
            _ACTIVE.discard(self)

    @contextlib.contextmanager
    def relaxed(self, site: str):
        """Temporarily re-allow implicit transfers for one named site
        (e.g. a checkpoint save that numpy-ifies the whole carry). Counted
        like any other site so the books still show it fired."""
        import jax
        self.count(site)
        with jax.transfer_guard("allow"):
            yield


def guarded_get(guards: Optional[RuntimeGuards], site: str, tree):
    """Explicit device->host transfer through a named site. With guards
    None this is exactly ``jax.device_get`` — the hot loops use it
    unconditionally, so arming changes accounting, never behavior."""
    import jax
    if guards is not None:
        guards.count(site)
    return jax.device_get(tree)


def guarded_put(guards: Optional[RuntimeGuards], site: str, tree):
    """Explicit host->device transfer through a named site (the serve
    loop's exec-order/limit operands; implicit numpy operands to a
    compiled call raise under an armed guard)."""
    import jax
    if guards is not None:
        guards.count(site)
    return jax.device_put(tree)


def armed(guards: Optional[RuntimeGuards]):
    """``guards.armed()`` or a null context when guards is None."""
    return guards.armed() if guards is not None else contextlib.nullcontext()


def relaxed_site(guards: Optional[RuntimeGuards], site: str):
    """``guards.relaxed(site)`` or a null context when guards is None."""
    return (guards.relaxed(site) if guards is not None
            else contextlib.nullcontext())
