"""Version-tolerant access to jax's experimental layout API.

The AOT boundary-layout machinery (parallel/batch.BatchedRunner
auto_layouts, tools/profile_tick.py --layouts auto) was written against
the ``Format(Layout.AUTO)`` spelling; older jax releases (such as the
0.4.x line this image ships) expose the same workflow as
``Layout(DeviceLocalLayout.AUTO)`` with ``Compiled.input_layouts`` and
``jax.Array.layout``. This module maps both spellings onto one surface so
the perf paths work — and the bench keeps RUNNING — on either, and
degrades honestly (``HAVE_LAYOUTS = False`` -> row-major boundaries with
a labeled ``layouts_effective``) when neither exists, instead of the
round-5 behavior where one ImportError in the warmup zeroed the whole
exact-bench axis.

Surface:
  HAVE_LAYOUTS        whether any layout API is importable
  auto_format()       the AUTO boundary format for jit in/out_shardings
  input_formats(comp) a Compiled's input formats, (args, kwargs) pytrees
  array_format(x)     a live array's format (None for non-device values)
  format_layout(f)    the device-local layout component of a format
  concrete_format(major_to_minor, sharding)  a concrete format (tests)
"""

from __future__ import annotations

try:  # current spelling: Format(Layout.AUTO) / comp.input_formats / x.format
    from jax.experimental.layout import Format as _Format  # type: ignore
    from jax.experimental.layout import Layout as _Layout  # type: ignore
except ImportError:
    try:  # jax 0.4.x spelling: Layout(DeviceLocalLayout.AUTO) /
        # comp.input_layouts / x.layout — same workflow, renamed since
        from jax.experimental.layout import (  # type: ignore
            DeviceLocalLayout as _Layout,
            Layout as _Format,
        )
    except ImportError:  # no layout API at all: auto-layouts unavailable
        _Format = _Layout = None

HAVE_LAYOUTS = _Format is not None


def auto_format():
    """The AUTO format object accepted by jit in_shardings/out_shardings."""
    if not HAVE_LAYOUTS:
        raise ImportError("jax.experimental.layout is unavailable in this "
                          "jax build; auto boundary layouts cannot be used")
    return _Format(_Layout.AUTO)


def input_formats(compiled):
    """A Compiled executable's input formats as ((args...), {kwargs})."""
    fmts = getattr(compiled, "input_formats", None)
    if fmts is None:
        fmts = compiled.input_layouts
    return fmts


def array_format(x):
    """The live device format of an array (None for host/numpy values)."""
    fmt = getattr(x, "format", None)
    if fmt is None:
        fmt = getattr(x, "layout", None)
    return fmt


def format_layout(fmt):
    """The device-local layout component of a Format/Layout pair."""
    dl = getattr(fmt, "device_local_layout", None)
    return dl if dl is not None else getattr(fmt, "layout", None)


def concrete_format(major_to_minor, sharding):
    """A concrete (non-AUTO) format for the given axis order + sharding."""
    if not HAVE_LAYOUTS:
        raise ImportError("jax.experimental.layout is unavailable")
    return _Format(_Layout(major_to_minor=tuple(major_to_minor)), sharding)
