"""Content-addressed job-summary memoization (the memo plane's host side).

"Supercharging Packet-level Network Simulation via Memoization and
Fast-Forwarding" (PAPERS.md) observes that production traffic repeats a
small scenario library, so the biggest multiplier is not ticking faster
but not ticking at all: content-address whole jobs and serve exact
repeats from a summary cache. This module is that cache plus the digest
recipe and the ``memo`` knob resolution; the lane-coalescing and
transition fast-forwarding halves live in ``parallel/batch.py``.

**Digest recipe** (``job_digest``): sha256 over a canonical JSON
encoding of everything that determines a job's summary bit-for-bit —
the topology spec (sorted node ids + balances + sorted links), the
job's compiled script rows (kind/arg0/arg1/do_tick), its fault
adversary key, its delay-sampler state row, the scheduler, the RESOLVED
engine knobs (queue/comm/kernel — "auto" is resolved before hashing so
a digest means the same thing on every backend), and the
semantics-affecting SimConfig fields (everything except
``trace_capacity``, which changes only observability). Two jobs with
equal digests run the identical jitted computation on identical
operands, so their summaries are interchangeable.

**Cache file format**: JSON lines, one entry per line —
``{"schema": MEMOCACHE_SCHEMA_VERSION, "digest": <64 hex>,
"summary": {...}}`` — content-addressed by digest (last write wins on
re-insert). Discipline mirrors utils/checkpoint.py, not the lenient
telemetry reader: writes are atomic (tmp-then-``os.replace``, tmp
unlinked on any failure), and a load REJECTS a poisoned, truncated or
stale-schema file with ``MemoCacheError`` naming the path — a cache
that silently skipped a torn line could silently serve a stale summary
forever.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from chandy_lamport_tpu.config import ENGINE_KNOBS
from chandy_lamport_tpu.utils.atomicio import (
    crash_failpoint,
    fsync_dir,
    fsync_file,
)
from chandy_lamport_tpu.utils.filelock import locked

# THE memocache schema version: one named registry constant, bumped on
# any breaking change of the cache line layout or the digest recipe (a
# recipe change MUST bump it — old digests would alias different
# computations). tools/staticcheck's memo-schema rule pins this to a
# single int-literal assignment here and keeps restated literals out of
# the schema-stamping dicts below.
MEMOCACHE_SCHEMA_VERSION = 1

_DIGEST_HEX_LEN = 64   # sha256


class MemoCacheError(ValueError):
    """A memo cache file could not be read or validated, or a shadow
    re-execution contradicted a served summary. Always carries the path
    (or the digest, for shadow mismatches); raised instead of silently
    skipping damage — a summary cache that guesses serves stale answers
    forever."""


def resolve_memo(memo: str) -> str:
    """Validate the ``memo`` engine knob (config.ENGINE_KNOBS). Unlike
    the backend-resolved knobs there is no "auto": the spellings are an
    explicit opt-in ladder (off < admit < full), so resolution is pure
    validation."""
    allowed = ENGINE_KNOBS["memo"]
    if memo not in allowed:
        raise ValueError(
            f"memo must be one of {', '.join(map(repr, allowed))}, "
            f"got {memo!r}")
    return memo


def _canon(x: Any) -> Any:
    """Canonical JSON-able form of a digest ingredient: numpy arrays
    become (dtype, shape, values) triples, scalars become python ints/
    floats, tuples become lists — stable across processes and numpy
    versions (json.dumps with sort_keys does the rest)."""
    if isinstance(x, np.ndarray):
        return ["ndarray", str(x.dtype), list(x.shape),
                x.reshape(-1).tolist()]
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, (list, tuple)):
        return [_canon(v) for v in x]
    if isinstance(x, dict):
        return {str(k): _canon(v) for k, v in sorted(x.items())}
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    # last resort: a stable repr (delay treedefs reach here as strings
    # already; anything else unexpected still digests deterministically)
    return repr(x)


def job_digest(*, topo_spec, script, fault_key, delay_row, scheduler: str,
               knobs: Dict[str, str], config_fields: Dict[str, Any]) -> str:
    """The content address of one stream job (module docstring recipe).

    ``topo_spec`` is a utils.fixtures.TopologySpec; ``script`` the job's
    compiled (kind, arg0, arg1, do_tick) row arrays; ``delay_row`` the
    job's delay-sampler state pytree (leaves + treedef string);
    ``knobs`` the RESOLVED engine knob spellings; ``config_fields`` the
    semantics-affecting SimConfig fields. Every ingredient goes through
    the canonical encoding so the digest is process- and
    platform-stable.
    """
    payload = {
        "schema": MEMOCACHE_SCHEMA_VERSION,
        "nodes": _canon(sorted((str(k), int(v)) for k, v in topo_spec.nodes)),
        "links": _canon(sorted((str(s), str(d)) for s, d in topo_spec.links)),
        "script": _canon(list(script)),
        "fault_key": _canon(fault_key),
        "delay_row": _canon(delay_row),
        "scheduler": str(scheduler),
        "knobs": _canon(knobs),
        "config": _canon(config_fields),
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _read_entries(path: str) -> "OrderedDict[str, dict]":
    """Strict parse of a memo cache file (module docstring format) into
    an OrderedDict in file order. Raises MemoCacheError on any damage."""
    out: "OrderedDict[str, dict]" = OrderedDict()
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    except OSError as exc:
        raise MemoCacheError(
            f"memo cache {path}: unreadable ({exc})") from exc
    for lineno, line in enumerate(raw.splitlines(), start=1):
        if not line.strip():
            continue
        try:
            entry = json.loads(line)
        except ValueError as exc:
            raise MemoCacheError(
                f"memo cache {path}: line {lineno} is not valid JSON "
                f"(poisoned or truncated write: {exc})") from exc
        if not isinstance(entry, dict) or not {
                "schema", "digest", "summary"} <= set(entry):
            raise MemoCacheError(
                f"memo cache {path}: line {lineno} is missing the "
                f"schema/digest/summary keys — not a memo cache entry")
        if entry["schema"] != MEMOCACHE_SCHEMA_VERSION:
            raise MemoCacheError(
                f"memo cache {path}: line {lineno} has schema version "
                f"{entry['schema']!r}; this build reads only "
                f"v{MEMOCACHE_SCHEMA_VERSION} (a schema bump changes "
                f"the digest recipe — stale entries must not be "
                f"served; delete the file to rebuild it)")
        digest = entry["digest"]
        if (not isinstance(digest, str)
                or len(digest) != _DIGEST_HEX_LEN
                or any(c not in "0123456789abcdef" for c in digest)):
            raise MemoCacheError(
                f"memo cache {path}: line {lineno} digest "
                f"{digest!r} is not a sha256 hex string")
        if not isinstance(entry["summary"], dict):
            raise MemoCacheError(
                f"memo cache {path}: line {lineno} summary is not an "
                f"object")
        out[digest] = entry["summary"]
    return out


class SummaryCache:
    """The persistent content-addressed summary store (module docstring
    format). In-memory dict keyed by digest; ``load`` is strict,
    ``flush`` is atomic. An entry's summary is the per-job result row as
    plain JSON scalars/lists (parallel/batch.stream_results row minus
    the job index, which is pool-relative, plus the producer's digest so
    telemetry can prove provenance).

    **Capacity bounds** (``max_entries``/``max_bytes``, 0 = unbounded):
    the store is an LRU — ``get`` hits and ``put`` inserts refresh
    recency; crossing either cap evicts the least-recently-used entries
    (counted in ``evictions``/``evicted_bytes``, surfaced through the
    memo books in ``summarize_stream``). The byte charge per entry is
    its serialized cache-line length, so ``max_bytes`` bounds the FILE
    the flush writes. ``flush`` persists in recency order, meaning
    recency survives restarts: a reloaded cache evicts the same entries
    a long-lived one would have."""

    def __init__(self, path: Optional[str], max_entries: int = 0,
                 max_bytes: int = 0):
        if max_entries < 0 or max_bytes < 0:
            raise ValueError("cache capacity bounds must be >= 0")
        self.path = path
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.evictions = 0
        self.evicted_bytes = 0
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._nbytes: Dict[str, int] = {}
        self._total_bytes = 0
        self._dirty = False
        if path is not None and os.path.exists(path):
            self._load(path)
            self._evict()

    @staticmethod
    def _line_bytes(digest: str, summary: dict) -> int:
        return len(json.dumps(
            {"schema": MEMOCACHE_SCHEMA_VERSION, "digest": digest,
             "summary": summary}, sort_keys=True)) + 1

    def _charge(self, digest: str, summary: dict) -> None:
        self._total_bytes -= self._nbytes.get(digest, 0)
        nb = self._line_bytes(digest, summary)
        self._nbytes[digest] = nb
        self._total_bytes += nb

    def _evict(self) -> None:
        while self._entries and (
                (self.max_entries
                 and len(self._entries) > self.max_entries)
                or (self.max_bytes
                    and self._total_bytes > self.max_bytes)):
            digest, _ = self._entries.popitem(last=False)
            nb = self._nbytes.pop(digest)
            self._total_bytes -= nb
            self.evictions += 1
            self.evicted_bytes += nb
            self._dirty = True

    def _load(self, path: str) -> None:
        with locked(path, shared=True):
            entries = _read_entries(path)
        # file order is recency order (flush writes LRU-first), so a
        # straight insert reconstructs the recency chain
        for digest, summary in entries.items():
            self._entries[digest] = summary
            self._entries.move_to_end(digest)
            self._charge(digest, summary)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, digest: str) -> bool:
        return digest in self._entries

    def get(self, digest: str) -> Optional[dict]:
        hit = self._entries.get(digest)
        if hit is not None:
            self._entries.move_to_end(digest)
        return hit

    def put(self, digest: str, summary: dict) -> None:
        self._entries[digest] = summary
        self._entries.move_to_end(digest)
        self._charge(digest, summary)
        self._dirty = True
        self._evict()

    def flush(self) -> None:
        """Atomically persist every entry (tmp-then-``os.replace``,
        checkpoint.py discipline): a kill at any instant leaves either
        the previous complete file or the new complete file, never a
        torn one. No-op without a path or pending writes.

        Cross-process safe: the whole read-merge-write runs under an
        exclusive advisory lock (utils/filelock). Entries another
        process flushed since our load are folded back in as
        older-than-ours before the rewrite, so concurrent writers to a
        shared cache path all survive instead of last-writer-wins."""
        if self.path is None or not self._dirty:
            return
        tmp = self.path + ".tmp"
        with locked(self.path):
            if os.path.exists(self.path):
                disk = _read_entries(self.path)
                for digest in reversed(disk):
                    if digest not in self._entries:
                        self._entries[digest] = disk[digest]
                        self._entries.move_to_end(digest, last=False)
                        self._charge(digest, disk[digest])
                self._evict()
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    for digest, summary in self._entries.items():
                        f.write(json.dumps(
                            {"schema": MEMOCACHE_SCHEMA_VERSION,
                             "digest": digest, "summary": summary},
                            sort_keys=True) + "\n")
                    # the tmp bytes must be on stable storage BEFORE the
                    # rename commits the name to them, or a power cut
                    # after the replace leaves the new name torn
                    fsync_file(f)
                crash_failpoint("memocache-replace")
                os.replace(tmp, self.path)
                fsync_dir(self.path)
                self._dirty = False
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise


# ---------------------------------------------------------------------------
# prefix plane: rolling phase-boundary digests + checkpointed lane states
# ---------------------------------------------------------------------------

# THE prefix-cache schema version: one named registry constant, same
# discipline as MEMOCACHE_SCHEMA_VERSION above (tools/staticcheck's
# prefix-schema rule pins it to this single int-literal assignment and
# keeps restated literals out of the stamping dicts). Bumped on any
# breaking change of the prefix entry layout, the leaf encoding, or the
# chain recipe — a recipe change MUST bump it, or old chain digests
# would alias checkpoints of different computations.
PREFIXCACHE_SCHEMA_VERSION = 1


class PrefixCacheError(MemoCacheError):
    """A prefix cache file could not be read or validated, or a forked
    job's shadow re-execution contradicted its cold run. Subclasses
    MemoCacheError (same refusal philosophy: a checkpoint store that
    guesses forks lanes into the wrong simulation)."""


def prefix_seed_digest(*, topo_spec, fault_key, delay_row, scheduler: str,
                       knobs: Dict[str, str],
                       config_fields: Dict[str, Any]) -> bytes:
    """Link zero of a job's prefix-digest chain: sha256 over the job's
    SCRIPT-FREE identity — exactly the ``job_digest`` recipe minus the
    script rows, plus a plane tag so a seed digest can never alias a
    whole-job digest. Two jobs share chain link d iff they share this
    identity AND their first d compiled script rows are byte-equal, so
    a checkpoint produced under one job's identity forks bit-exactly
    into any chain-sharing job."""
    payload = {
        "schema": PREFIXCACHE_SCHEMA_VERSION,
        "plane": "prefix",
        "nodes": _canon(sorted((str(k), int(v)) for k, v in topo_spec.nodes)),
        "links": _canon(sorted((str(s), str(d)) for s, d in topo_spec.links)),
        "fault_key": _canon(fault_key),
        "delay_row": _canon(delay_row),
        "scheduler": str(scheduler),
        "knobs": _canon(knobs),
        "config": _canon(config_fields),
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).digest()


def prefix_extend(prev: bytes, row) -> bytes:
    """One chain step: c_{i+1} = sha256(c_i || canon(script row i)).
    ``row`` is the (kind, arg0, arg1, do_tick) tuple of ONE compiled
    phase. Rolling rather than hash-of-prefix so pack_jobs pays O(rows)
    per job, not O(rows^2)."""
    blob = json.dumps(_canon(list(row)), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(prev + blob.encode()).digest()


def _enc_leaf(x: Any) -> dict:
    """Exact JSON encoding of a checkpoint leaf: ndarrays become
    (dtype, shape, base64 raw bytes) — byte-lossless, unlike _canon's
    tolist (which exists for digesting, not round-tripping) — and
    tuples/lists recurse (delay-sampler states are tuples of arrays)."""
    if isinstance(x, (tuple, list)):
        return {"t": [_enc_leaf(v) for v in x]}
    a = np.asarray(x)
    return {"d": str(a.dtype), "s": list(a.shape),
            "b": base64.b64encode(a.tobytes()).decode("ascii")}


def _dec_leaf(node: Any, path: str) -> Any:
    """Strict inverse of _enc_leaf; raises PrefixCacheError on any
    malformed node (naming ``path``) instead of guessing."""
    if not isinstance(node, dict):
        raise PrefixCacheError(
            f"prefix cache {path}: checkpoint leaf is not an object")
    if "t" in node:
        if not isinstance(node["t"], list):
            raise PrefixCacheError(
                f"prefix cache {path}: checkpoint tuple node is not a "
                f"list")
        return tuple(_dec_leaf(v, path) for v in node["t"])
    try:
        dtype = np.dtype(node["d"])
        shape = tuple(int(s) for s in node["s"])
        raw = base64.b64decode(node["b"], validate=True)
        arr = np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
    except (KeyError, TypeError, ValueError) as exc:
        raise PrefixCacheError(
            f"prefix cache {path}: checkpoint array node is damaged "
            f"({exc})") from exc
    return arr


def _read_prefix_entries(path: str) -> "OrderedDict[str, dict]":
    """Strict parse of a prefix cache file into an OrderedDict in file
    order (file order is recency order, like SummaryCache). Entry
    layout: ``{"schema": PREFIXCACHE_SCHEMA_VERSION, "digest": <64
    hex>, "depth": <phases>, "seen": <count>, "ckpt": null |
    {"leaves": {...}}}``. Raises PrefixCacheError on any damage."""
    out: "OrderedDict[str, dict]" = OrderedDict()
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    except OSError as exc:
        raise PrefixCacheError(
            f"prefix cache {path}: unreadable ({exc})") from exc
    for lineno, line in enumerate(raw.splitlines(), start=1):
        if not line.strip():
            continue
        try:
            entry = json.loads(line)
        except ValueError as exc:
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} is not valid JSON "
                f"(poisoned or truncated write: {exc})") from exc
        if not isinstance(entry, dict) or not {
                "schema", "digest", "depth", "seen", "ckpt"} <= set(entry):
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} is missing the "
                f"schema/digest/depth/seen/ckpt keys — not a prefix "
                f"cache entry")
        if entry["schema"] != PREFIXCACHE_SCHEMA_VERSION:
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} has schema version "
                f"{entry['schema']!r}; this build reads only "
                f"v{PREFIXCACHE_SCHEMA_VERSION} (a schema bump changes "
                f"the chain recipe or the leaf encoding — stale "
                f"checkpoints must not be forked from; delete the file "
                f"to rebuild it)")
        digest = entry["digest"]
        if (not isinstance(digest, str)
                or len(digest) != _DIGEST_HEX_LEN
                or any(c not in "0123456789abcdef" for c in digest)):
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} digest "
                f"{digest!r} is not a sha256 hex string")
        if not isinstance(entry["depth"], int) or entry["depth"] < 1:
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} depth "
                f"{entry['depth']!r} is not a positive phase count")
        if not isinstance(entry["seen"], int) or entry["seen"] < 0:
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} seen count "
                f"{entry['seen']!r} is not a non-negative int")
        ckpt = entry["ckpt"]
        if ckpt is not None and not (
                isinstance(ckpt, dict)
                and isinstance(ckpt.get("leaves"), dict)):
            raise PrefixCacheError(
                f"prefix cache {path}: line {lineno} ckpt is neither "
                f"null nor a leaves object")
        out[digest] = {"depth": entry["depth"], "seen": entry["seen"],
                       "ckpt": ckpt}
    return out


class PrefixCache:
    """The persistent prefix-checkpoint store (memo="prefix" plane's
    host side), beside SummaryCache with the same discipline: strict
    load, atomic locked flush, LRU by entries AND bytes. Content
    address = a chain digest (prefix_seed_digest + prefix_extend per
    phase row); an entry carries the boundary ``depth``, a ``seen``
    counter (how many admissions crossed this boundary without a
    checkpoint existing yet — the heat signal that promotes a boundary
    to checkpointed on its next encounter), and optionally the ``ckpt``
    itself: the lane's semantic DenseState leaves at the boundary,
    byte-losslessly encoded (_enc_leaf). ``max_bytes`` matters here far
    more than for summaries — one ring-8 checkpoint is tens of KB, so
    the LRU is the line between "cache" and "unbounded state dump"."""

    def __init__(self, path: Optional[str], max_entries: int = 0,
                 max_bytes: int = 0):
        if max_entries < 0 or max_bytes < 0:
            raise ValueError("cache capacity bounds must be >= 0")
        self.path = path
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.evictions = 0
        self.evicted_bytes = 0
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._nbytes: Dict[str, int] = {}
        self._total_bytes = 0
        self._dirty = False
        if path is not None and os.path.exists(path):
            self._load(path)
            self._evict()

    @staticmethod
    def _line_bytes(digest: str, entry: dict) -> int:
        return len(json.dumps(
            {"schema": PREFIXCACHE_SCHEMA_VERSION, "digest": digest,
             "depth": entry["depth"], "seen": entry["seen"],
             "ckpt": entry["ckpt"]}, sort_keys=True)) + 1

    def _charge(self, digest: str, entry: dict) -> None:
        self._total_bytes -= self._nbytes.get(digest, 0)
        nb = self._line_bytes(digest, entry)
        self._nbytes[digest] = nb
        self._total_bytes += nb

    def _evict(self) -> None:
        while self._entries and (
                (self.max_entries
                 and len(self._entries) > self.max_entries)
                or (self.max_bytes
                    and self._total_bytes > self.max_bytes)):
            digest, _ = self._entries.popitem(last=False)
            nb = self._nbytes.pop(digest)
            self._total_bytes -= nb
            self.evictions += 1
            self.evicted_bytes += nb
            self._dirty = True

    def _load(self, path: str) -> None:
        with locked(path, shared=True):
            entries = _read_prefix_entries(path)
        for digest, entry in entries.items():
            self._entries[digest] = entry
            self._entries.move_to_end(digest)
            self._charge(digest, entry)

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, digest: str) -> bool:
        return digest in self._entries

    def seen(self, digest: str) -> int:
        entry = self._entries.get(digest)
        return int(entry["seen"]) if entry is not None else 0

    def bump_seen(self, digest: str, depth: int) -> None:
        """Record one checkpoint-less crossing of a boundary. Does NOT
        refresh LRU recency — heat alone must not out-compete real
        checkpoints for residency."""
        entry = self._entries.get(digest)
        if entry is None:
            entry = {"depth": int(depth), "seen": 1, "ckpt": None}
            self._entries[digest] = entry
            self._entries.move_to_end(digest, last=False)
        else:
            entry["seen"] = int(entry["seen"]) + 1
        self._charge(digest, entry)
        self._dirty = True
        self._evict()

    def has_ckpt(self, digest: str) -> bool:
        entry = self._entries.get(digest)
        return entry is not None and entry["ckpt"] is not None

    def get_ckpt(self, digest: str) -> Optional[Tuple[int, Dict[str, Any]]]:
        """(depth, decoded leaves dict) of a checkpointed boundary, or
        None. A hit refreshes LRU recency."""
        entry = self._entries.get(digest)
        if entry is None or entry["ckpt"] is None:
            return None
        self._entries.move_to_end(digest)
        leaves = {
            str(name): _dec_leaf(node, self.path or "<memory>")
            for name, node in entry["ckpt"]["leaves"].items()}
        return int(entry["depth"]), leaves

    def put_ckpt(self, digest: str, depth: int,
                 leaves: Dict[str, Any]) -> None:
        prev = self._entries.get(digest)
        entry = {"depth": int(depth),
                 "seen": int(prev["seen"]) if prev else 0,
                 "ckpt": {"leaves": {str(k): _enc_leaf(v)
                                     for k, v in leaves.items()}}}
        self._entries[digest] = entry
        self._entries.move_to_end(digest)
        self._charge(digest, entry)
        self._dirty = True
        self._evict()

    def flush(self) -> None:
        """Atomic locked read-merge-write, SummaryCache.flush's
        discipline verbatim, plus a prefix-specific merge rule for
        digests both sides hold: a checkpoint beats a seen-only entry
        (never downgrade a boundary another process promoted), and
        ``seen`` merges as max — a heat signal should survive
        concurrent writers, not reset to the last writer's count."""
        if self.path is None or not self._dirty:
            return
        tmp = self.path + ".tmp"
        with locked(self.path):
            if os.path.exists(self.path):
                disk = _read_prefix_entries(self.path)
                for digest in reversed(disk):
                    mine = self._entries.get(digest)
                    if mine is None:
                        self._entries[digest] = disk[digest]
                        self._entries.move_to_end(digest, last=False)
                        self._charge(digest, disk[digest])
                        continue
                    theirs = disk[digest]
                    mine["seen"] = max(int(mine["seen"]),
                                       int(theirs["seen"]))
                    if mine["ckpt"] is None and theirs["ckpt"] is not None:
                        mine["ckpt"] = theirs["ckpt"]
                        mine["depth"] = theirs["depth"]
                    self._charge(digest, mine)
                self._evict()
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    for digest, entry in self._entries.items():
                        f.write(json.dumps(
                            {"schema": PREFIXCACHE_SCHEMA_VERSION,
                             "digest": digest, "depth": entry["depth"],
                             "seen": entry["seen"],
                             "ckpt": entry["ckpt"]},
                            sort_keys=True) + "\n")
                    fsync_file(f)
                crash_failpoint("prefixcache-replace")
                os.replace(tmp, self.path)
                fsync_dir(self.path)
                self._dirty = False
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
