"""Jit-compatible observability: invariants + aggregate counters.

The reference's only observability is the debug event log (logger.go) and the
test-side token-conservation check (test_common.go:298-328). The array
backends expose two TPU-friendly layers:

  - aggregate counters (this module): ``in_flight_tokens`` /
    ``conservation_delta`` evaluate the conservation invariant as pure array
    reductions, runnable under jit every K ticks; ``progress_counters``
    gives queue depths, snapshot lifecycle counts and error bits — cheap
    reductions whose cross-device lowering is the collective path when the
    batch axis is sharded;
  - per-event capture (utils/tracing.py): the device flight recorder — a
    fixed-capacity ring of packed event words written by ``.at[]`` scatters
    inside the jitted tick paths, decoded host-side into the reference
    Logger's format. Per-event capture at the reference's granularity IS
    jit-compatible once the log is a bounded dense ring instead of a
    growing list; what stays host-side is only the decode.

All functions take a DenseState with ANY batching (none, leading axis,
trailing axis): reductions run over the structural axes only where needed and
otherwise reduce everything.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from chandy_lamport_tpu.config import SimConfig
from chandy_lamport_tpu.core.state import DenseState


def _occupied(state: DenseState, cfg: SimConfig):
    """bool mask of live ring-buffer slots: positions head..head+len-1
    (dense modular-interval test, no gathers). Works unbatched ([E, C]
    queues) and lead-batched ([B, E, C]) — the capacity axis is last."""
    c = cfg.queue_capacity
    cc = jnp.arange(c, dtype=jnp.int32)
    return ((cc - state.q_head[..., None]) % c) < state.q_len[..., None]


def in_flight_tokens(state: DenseState, cfg: SimConfig) -> jnp.ndarray:
    """Total tokens inside channels (non-marker live slots, read from the
    packed q_meta marker bit), all instances."""
    occ = _occupied(state, cfg)
    return jnp.sum(jnp.where(occ & ((state.q_meta & 1) == 0),
                             state.q_data, 0))


def total_tokens(state: DenseState, cfg: SimConfig) -> jnp.ndarray:
    """Node balances + in-flight tokens — the conserved quantity
    (test_common.go:298-328 counts both)."""
    return jnp.sum(state.tokens) + in_flight_tokens(state, cfg)


def conservation_delta(state: DenseState, cfg: SimConfig,
                       expected_total: int) -> jnp.ndarray:
    """0 iff conservation holds (expected_total = initial tokens summed over
    however many instances the state carries). The fault adversary's
    injected token delta (``fault_skew``: duplicates - drops +
    crash-restore deltas, models/faults.py) is subtracted, so conservation
    stays an exact invariant on faulted lanes too — a nonzero delta always
    means the SIMULATOR leaked tokens, never that the adversary was on."""
    return (total_tokens(state, cfg) - expected_total
            - jnp.sum(state.fault_skew))


def snapshot_lifecycle(state, num_nodes: int) -> Dict[str, jnp.ndarray]:
    """Snapshot-supervisor lifecycle counters (works for DenseState with
    any leading batching AND for ShardedState, whose supervisor leaves are
    replicated): attempts initiated / completed / retried / failed /
    aborted (= retried + failed — every abort either re-initiates or
    fails), stale-epoch marker rejections, and the recovery-line age —
    ticks since the NEWEST completed snapshot (the rollback line a lossy
    crash would restore from; models/faults.py), -1 when no lane has a
    completed snapshot yet. ``recovery_line_age_max`` is the worst lane's
    age, the number an operator alarms on."""
    started = state.started
    complete = started & (state.completed >= num_nodes)
    done_t = jnp.where(complete, state.snap_done_time, -1)
    any_done = jnp.any(complete, axis=-1)
    age = jnp.where(any_done, state.time - jnp.max(done_t, axis=-1), -1)
    retried = jnp.sum(state.snap_retries)
    failed = jnp.sum(state.snap_failed)
    return {
        "initiated": jnp.sum(started),
        "completed": jnp.sum(complete),
        "retried": retried,
        "failed": failed,
        "aborted": retried + failed,
        "stale_markers": jnp.sum(
            jnp.asarray(getattr(state, "stale_markers", 0))),
        "recovery_line_age_max": jnp.max(age),
    }


def progress_counters(state: DenseState, cfg: SimConfig,
                      num_nodes: int) -> Dict[str, jnp.ndarray]:
    """Aggregate lifecycle counters; under a sharded batch axis these
    reductions lower to XLA collectives."""
    started = state.started
    complete = started & (state.completed >= num_nodes)
    return {
        "time_total": jnp.sum(state.time),
        "time_max": jnp.max(state.time),
        # ring tokens + split-mode pending markers (which occupy no ring
        # slots) — either term is zero in the mode that doesn't use it
        "queued_messages": jnp.sum(state.q_len) + jnp.sum(state.m_pending),
        "snapshots_started": jnp.sum(started),
        "snapshots_completed": jnp.sum(complete),
        "snapshots_pending": jnp.sum(started & ~complete),
        "nodes_finalized": jnp.sum(state.done_local),
        # per-(slot, edge) recorded count = its window length in the shared
        # per-edge log (live windows extend to the current append counter);
        # the subtraction runs in the window dtype, where uint16's modular
        # wrap recovers the true length (bounded by L — state.py decode)
        "recorded_messages": jnp.sum(
            (jnp.where(state.recording,
                       jnp.expand_dims(
                           state.rec_cnt.astype(state.rec_start.dtype), -2),
                       state.rec_end)
             - state.rec_start).astype(jnp.int32)),
        # bitwise OR over instances (jnp.max would drop bits when different
        # lanes carry different error flags)
        "error_bits": or_reduce(state.error),
    }


def straggler_waste(state: DenseState) -> jnp.ndarray:
    """Fraction of the batch's tick capacity burned waiting for the slowest
    lane: ``1 - mean(time) / max(time)`` over whatever batching the state
    carries (0.0 for a single instance, or when nothing ticked). Every
    dispatch runs until the slowest lane converges, so a batch whose lanes
    quiesce at a mean of 85 ticks but whose max is 105 spent ~19% of its
    lane-tick budget re-checking finished lanes — the dispersion the
    streaming engine (parallel/batch.run_stream) exists to reclaim by
    refilling retired lanes in place."""
    t = jnp.asarray(state.time, jnp.float32)
    mx = jnp.max(t)
    return jnp.where(mx > 0, 1.0 - jnp.mean(t) / jnp.maximum(mx, 1.0), 0.0)


def stream_occupancy(stream) -> float:
    """Fraction of lane-steps that held a live job during a ``run_stream``
    drive (StreamState counters; one lane-step = one slot for one stream
    step): 1.0 means every slot held working jobs the whole run; gang
    (static-batch) admission of heavy-tailed jobs shows the straggler
    hole directly here."""
    total = int(stream.lane_steps_total)
    return float(int(stream.lane_steps_live)) / total if total else 0.0


def stream_counters(stream) -> Dict[str, Any]:
    """Host-side scalars of a StreamState (parallel/batch.run_stream):
    jobs admitted/harvested, refill count (admissions into a recycled
    slot, i.e. beyond each lane's first job), occupancy, and the
    straggler-wasted lane-steps the occupancy complement counts."""
    total = int(stream.lane_steps_total)
    live = int(stream.lane_steps_live)
    done = int(stream.jobs_done)
    hits = int(stream.cache_hits)
    coalesced = int(stream.coalesced_jobs)
    served = done + hits + coalesced
    return {
        "steps": int(stream.steps),
        "jobs_admitted": int(stream.next_job),
        "jobs_done": done,
        "refills": int(stream.refills),
        "occupancy": round(live / total, 4) if total else 0.0,
        "lane_steps_live": live,
        "lane_steps_total": total,
        "straggler_wasted_steps": total - live,
        # memo plane (parallel/batch memo="admit|full"): jobs served from
        # the persistent summary cache without burning a lane, duplicate
        # jobs coalesced onto a representative lane, ticks the signature
        # fast-forward credited instead of re-ticking, and shadow
        # re-executions that proved a served summary bit-exact. The hit
        # rate is (cache + coalesce) over everything served — 0.0 with
        # memo="off".
        "cache_hits": hits,
        "coalesced_jobs": coalesced,
        "ff_skipped_ticks": int(stream.ff_skipped_ticks),
        "shadow_checks": int(stream.shadow_checks),
        "memo_hit_rate": round((hits + coalesced) / served, 4) if served else 0.0,
        # prefix plane (parallel/batch memo="prefix"): near-duplicate
        # leaders served from a checkpointed prefix. prefix_hits is the
        # host plan's fork count, forked_jobs the device admission
        # counter — equality is the books-balance invariant; the depth
        # mean is over the device-accumulated fork_depth_sum.
        "prefix_hits": int(stream.prefix_hits),
        "forked_jobs": int(stream.forked_jobs),
        "fork_depth_sum": int(stream.fork_depth_sum),
        "fork_depth_mean": round(
            int(stream.fork_depth_sum) / int(stream.forked_jobs), 4)
        if int(stream.forked_jobs) else 0.0,
        # serving plane (serving/server.py over the v9 leaves): jobs
        # harvested past their absolute deadline, and the per-tenant
        # service/quota books the serve step maintains at harvest
        "deadline_misses": int(stream.deadline_misses),
        "tenant_served": np.asarray(stream.tenant_served)
        .astype(int).tolist(),
        "tenant_quota": np.asarray(stream.tenant_quota)
        .astype(int).tolist(),
    }


def instance_footprint_bytes(num_nodes: int, num_edges: int,
                             cfg: SimConfig) -> int:
    """Per-instance HBM bytes of a DenseState (excluding delay state):
    the capacity-planning formula behind BASELINE.md's max-batch numbers.

    footprint = 8·E·C + (24 + rec·L)·E + 4·N + S·(22 + 10·N + (10+2·win)·E)
                + 12·K + 12
    with rec = itemsize of SimConfig.record_dtype (4 default, 2 for int16),
    win = itemsize of SimConfig.window_dtype (4 default, 2 for uint16),
    and L = cfg.max_recorded (shared per-edge log slots). The 8·E·C term
    is the two packed int32 ring planes (q_meta = rtime<<1|marker, q_data;
    core/state.py "Packed ring slots" — the former separate bool marker
    plane is folded into q_meta). The 12·K + 12 term is the
    flight-recorder ring (three i32 planes of K = cfg.trace_capacity
    slots plus the tr_count / tr_on scalars, utils/tracing.py) and the
    memo plane's u32 ``sig`` signature scalar; the default trace-off
    configuration pays only the 12 counter bytes (K = 0).

    Dominant terms at bench shapes are the [S, E] recording/window/marker
    planes and the per-edge log ``log_amt[L, E]`` — size S and L to the
    workload, not to the worst case.
    """
    import numpy as np

    n, e = num_nodes, num_edges
    c, s, m = cfg.queue_capacity, cfg.max_snapshots, cfg.max_recorded
    rec = np.dtype(cfg.record_dtype).itemsize
    win = np.dtype(cfg.window_dtype).itemsize
    # q_* rings (packed meta + data) + head/len/tok_pushed/mk_cnt
    queues = e * c * (4 + 4) + e * (4 + 4 + 4 + 4)
    nodes = 4 * n                                       # tokens
    # per-edge recording log: rec_cnt/min_prot + log_amt[L, E]
    rec_log = e * (4 + 4) + rec * m * e
    # per slot: started + [S,N] planes + recording + window counters
    # (start/end) + split-marker planes m_pending/m_rtime/m_key + the
    # supervisor's epoch/deadline/retries/initiator/done_time (i32) and
    # failed (bool) leaves
    snaps = s * (1 + n * (1 + 4 + 4 + 1)
                 + e * (1 + win * 2) + e * (1 + 4 + 4)
                 + 5 * 4 + 1)
    # time/next_sid/error + fault_key/fault_skew/fault_counts[7] +
    # stale_markers, completed, the streaming-engine job identity
    # (job_id/prog_cursor/admit_tick), and the memo plane's sig scalar
    scalars = 4 * 3 + 4 * 10 + s * 4 + 4 * 3 + 4
    # flight-recorder ring: tr_meta/tr_data/tr_tick[K] + tr_count/tr_on
    trace = 12 * cfg.trace_capacity + 8
    return queues + nodes + rec_log + snaps + scalars + trace


def max_batch_estimate(num_nodes: int, num_edges: int, cfg: SimConfig,
                       hbm_bytes: int, working_set_factor: float = 2.0) -> int:
    """Instances that fit one chip's HBM: capacity / (footprint × factor).
    ``working_set_factor`` accounts for XLA's double-buffering of the loop
    carry (donation halves it; 2.0 is the observed-safe default)."""
    per = instance_footprint_bytes(num_nodes, num_edges, cfg)
    return max(1, int(hbm_bytes / (per * working_set_factor)))


def comm_bytes_model(num_nodes: int, max_snapshots: int, shards: int,
                     halo_rows: int, cut_edges: int | None = None,
                     cut_rows: int | None = None,
                     count_bytes: int = 4) -> Dict[str, Any]:
    """Per-shard, per-tick cross-shard payload bytes for the graph-sharded
    runner's two comm engines (parallel/graphshard module docstring):

      dense  = 4*N   credit psum (f32 per-node partials)
             + cb*S*N marker-arrival psum (count dtype, ``count_bytes``)
             + S*N   created-flags all_gather (bool)
             + 4*S   finalization psum
      sparse = (P-1) * ( (S+1)*H*4  forward rows: credit + arrivals, i32
                       +  S*H       reverse rows: created flags, bool )
             + 4*S   finalization psum

    where H = ``halo_rows`` (max boundary rows per neighbor pair,
    parallel/mesh.BoundaryTables) — so sparse scales with the partition
    CUT while dense scales with N. Error-bit folds are identical on both
    sides and amortized to phase/megatick boundaries, so they are left
    out. ``cut_edges``/``cut_rows`` ride along when known (summarize,
    bench rows)."""
    n, s, p, h = num_nodes, max_snapshots, shards, halo_rows
    neighbors = max(p - 1, 0)
    dense = 4 * n + count_bytes * s * n + s * n + 4 * s
    sparse = neighbors * ((s + 1) * h * 4 + s * h) + 4 * s
    out: Dict[str, Any] = {
        "dense_bytes_per_tick": int(dense),
        "sparse_bytes_per_tick": int(sparse),
        "halo_rows": int(h),
        "neighbors": int(neighbors),
        "sparse_over_dense": round(sparse / dense, 4) if dense else 0.0,
    }
    if cut_edges is not None:
        out["cut_edges"] = int(cut_edges)
    if cut_rows is not None:
        out["cut_rows"] = int(cut_rows)
    return out


def tick_cost_model(num_nodes: int, num_edges: int, cfg: SimConfig,
                    batch: int = 1,
                    queue_engine: str = "gather") -> Dict[str, Any]:
    """Analytic per-tick cost of the dense engine at a bench shape: the
    static side of the roofline the bench rows report measured
    node-ticks/sec against (tools/staticcheck/hlo_cost.py pins the
    compiled-HLO counterpart per entry arm).

      hbm_bytes_per_tick   2 x instance_footprint_bytes x batch — every
                           carry leaf is read and written once per tick
                           (donation keeps it at one live copy, but the
                           traffic is still read + write).
      elem_ops_per_tick    the queue-engine head touch: 'gather' reads
                           and re-scatters one slot per edge ring
                           (~4 x E element ops: meta + data, read +
                           write); 'mask' sweeps both full [E, C] ring
                           planes (~2 x E x C). The C/2 ratio IS the
                           queue_engine knob's pitch.

    Per-instance state costs are batch-linear by construction (vmap over
    identical lanes), so both numbers just scale by ``batch``.
    """
    per = instance_footprint_bytes(num_nodes, num_edges, cfg)
    e, c = num_edges, cfg.queue_capacity
    elem = (2 * e * c if queue_engine == "mask" else 4 * e) * batch
    return {
        "instance_bytes": int(per),
        "hbm_bytes_per_tick": int(2 * per * batch),
        "elem_ops_per_tick": int(elem),
        "queue_engine": queue_engine,
        "batch": int(batch),
    }


def or_reduce(mask) -> jnp.ndarray:
    """Bitwise-OR reduction of an integer bitmask over all axes."""
    mask = jnp.asarray(mask)
    bits = jnp.iinfo(mask.dtype).bits
    shifts = jnp.arange(bits, dtype=mask.dtype)
    any_bit = jnp.any((mask[..., None] >> shifts) & 1,
                      axis=tuple(range(mask.ndim)))
    return jnp.sum(jnp.where(any_bit, 1, 0).astype(mask.dtype) << shifts)
