"""Random workload generators shared by the differential test suite and the
soak battery (tools/soak.py).

The reference has only 7 hand-written cases (snapshot_test.go:46-108); the
randomized suites need topologies that are strongly connected (snapshot
completion requires it, reference sim.go:116-117) and scripts whose sends can
never trip the insufficient-balance fatal (node.go:113-116), so any observed
divergence is a real kernel bug.
"""

from __future__ import annotations

import random
from typing import List

from chandy_lamport_tpu.core.spec import (
    Event,
    PassTokenEvent,
    SnapshotEvent,
    TickEvent,
)
from chandy_lamport_tpu.utils.fixtures import TopologySpec


def random_strongly_connected(rng: random.Random, n: int) -> TopologySpec:
    """Ring (guarantees strong connectivity) + random extra arcs; node ids
    deliberately collide lexicographically (N1, N10, N2...) to exercise the
    sort rule R1."""
    ids = [f"N{i + 1}" for i in range(n)]
    nodes = [(nid, rng.randrange(50, 200)) for nid in ids]
    order = ids[:]
    rng.shuffle(order)
    links = {(order[i], order[(i + 1) % n]) for i in range(n)}
    for _ in range(rng.randrange(0, 2 * n)):
        a, b = rng.sample(ids, 2)
        links.add((a, b))
    return TopologySpec(nodes, sorted(links))


def random_script(rng: random.Random, topo: TopologySpec,
                  n_events: int) -> List[Event]:
    """Random sends/snapshots/ticks. Send amounts stay within a pessimistic
    balance floor (credits ignored) so the reference's insufficient-balance
    fatal (node.go:113-116) can never fire."""
    floor = {nid: tok for nid, tok in topo.nodes}
    out = {}
    for s, d in topo.links:
        out.setdefault(s, []).append(d)
    events: List[Event] = []
    snapshots = 0
    for _ in range(n_events):
        r = rng.random()
        if r < 0.5:
            src = rng.choice(list(out))
            dest = rng.choice(out[src])
            amt = rng.randrange(1, 4)
            if floor[src] >= amt:
                floor[src] -= amt
                events.append(PassTokenEvent(src, dest, amt))
        elif r < 0.7 and snapshots < 12:
            events.append(SnapshotEvent(rng.choice([n for n, _ in topo.nodes])))
            snapshots += 1
        else:
            events.append(TickEvent(rng.randrange(1, 4)))
    if snapshots == 0:
        events.append(SnapshotEvent(topo.nodes[0][0]))
    return events
