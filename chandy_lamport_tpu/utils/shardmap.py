"""Version-tolerant access to jax's shard_map (the utils/layouts.py pattern).

The graph-sharded runner (parallel/graphshard.GraphShardedRunner) was
written against the current spelling ``jax.shard_map(..., check_vma=...)``;
older jax releases (the 0.4.x line this image ships) expose the same
transform as ``jax.experimental.shard_map.shard_map`` with the replication
check named ``check_rep``. This module maps both spellings onto one surface
so the sharded runners construct — and the graphshard/multihost tier-1
suites RUN — on either, instead of dying on an AttributeError at
``jax.shard_map`` (the 22 pre-seed failures).

Surface:
  HAVE_SHARD_MAP   whether any shard_map implementation is importable
  SHARD_MAP_SPELLING  where it was found ("jax.shard_map" /
                   "jax.experimental.shard_map.shard_map" / None)
  shard_map(f, mesh, in_specs, out_specs, check=False)
                   the transform with the replication/VMA check knob
                   normalized to ``check`` (False matches the runners'
                   check_vma=False / check_rep=False intent)
"""

from __future__ import annotations

import inspect

import jax

try:  # current spelling: jax.shard_map(..., check_vma=...)
    _impl = jax.shard_map  # type: ignore[attr-defined]
    SHARD_MAP_SPELLING = "jax.shard_map"
except AttributeError:
    try:  # jax 0.4.x spelling: experimental module, check_rep kwarg
        from jax.experimental.shard_map import shard_map as _impl  # type: ignore

        SHARD_MAP_SPELLING = "jax.experimental.shard_map.shard_map"
    except ImportError:  # no shard_map at all: sharded runners unavailable
        _impl = None
        SHARD_MAP_SPELLING = None

HAVE_SHARD_MAP = _impl is not None

if HAVE_SHARD_MAP:
    try:
        _params = inspect.signature(_impl).parameters
    except (TypeError, ValueError):  # C-level / wrapped callable: assume new
        _params = {"check_vma": None}
    # the replication checker has been renamed across releases; resolve the
    # kwarg once at import so call sites never branch on jax versions
    _CHECK_KW = next((kw for kw in ("check_vma", "check_rep")
                      if kw in _params), None)


def shard_map(f, mesh, in_specs, out_specs, check: bool = False):
    """The shard_map transform under either spelling. ``check`` feeds the
    replication/VMA checker (``check_vma`` on current jax, ``check_rep``
    on 0.4.x); the runners pass False — their bodies use collectives whose
    replication the checker cannot always prove."""
    if not HAVE_SHARD_MAP:
        raise ImportError(
            "no shard_map implementation in this jax build (looked for "
            "jax.shard_map and jax.experimental.shard_map.shard_map); "
            "the graph-sharded/multihost runners cannot be used")
    kw = {_CHECK_KW: check} if _CHECK_KW else {}
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
