"""Epoch-bucketed event trace — the parity backend's observability hook.

Replicates the reference Logger (logger.go:12-76): events bucketed per time
step, each capturing the node's token balance at record time (logger.go:74 —
note sends record the balance *before* the debit, node.go:118-120). Pretty
printing matches the reference's record strings (common.go:75-122).

For the JAX backend, structured per-event capture is incompatible with jit;
its equivalents are (a) aggregate counters reduced from DenseState
(utils/metrics.py progress_counters) and (b) ``jax.profiler`` traces via
``bench --profile`` for kernel-level timing (SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
from typing import List

from chandy_lamport_tpu.core.spec import Message


@dataclasses.dataclass
class TraceEvent:
    node_id: str
    node_tokens: int  # balance when recorded (logger.go:18-23)
    text: str


class EpochTrace:
    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.epochs: List[List[TraceEvent]] = []

    def new_epoch(self) -> None:
        if self.enabled:
            self.epochs.append([])

    def _record(self, node, text: str) -> None:
        if self.enabled:
            self.epochs[-1].append(TraceEvent(node.id, node.tokens, text))

    def sent(self, node, dest: str, msg: Message) -> None:
        if not self.enabled:
            return
        if msg.is_marker:
            self._record(node, f"{node.id} sent marker({msg.data}) to {dest}")
        else:
            self._record(node, f"{node.id} sent {msg.data} tokens to {dest}")

    def received(self, node, src: str, msg: Message) -> None:
        if not self.enabled:
            return
        if msg.is_marker:
            self._record(node, f"{node.id} received marker({msg.data}) from {src}")
        else:
            self._record(node, f"{node.id} received {msg.data} tokens from {src}")

    def start_snapshot(self, node, snapshot_id: int) -> None:
        self._record(node, f"{node.id} startSnapshot({snapshot_id})")

    def end_snapshot(self, node, snapshot_id: int) -> None:
        self._record(node, f"{node.id} endSnapshot({snapshot_id})")

    def pretty(self) -> str:
        out = []
        for t, events in enumerate(self.epochs):
            if events:
                out.append(f"Time {t}:")
                out.extend(f"\t{e.node_id}: {e.text}" for e in events)
        return "\n".join(out)
