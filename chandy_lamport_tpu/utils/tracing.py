"""Event tracing: the parity backend's epoch log AND the device-side
flight recorder shared by every JAX tick path.

Two capture mechanisms, one event vocabulary:

* ``EpochTrace`` replicates the reference Logger (logger.go:12-76): events
  bucketed per time step, each capturing the node's token balance at record
  time (logger.go:74 — note sends record the balance *before* the debit,
  node.go:118-120). Pretty printing matches the reference's record strings
  (common.go:75-122). Host-side, parity backend only.

* The DEVICE TRACE RING: a fixed-capacity per-lane ring of packed int32
  event words written by cheap ``.at[]`` scatters *inside* the jitted tick
  kernels (ops/tick.py), at the same sites the reference Logger records.
  Three i32 planes of ``SimConfig.trace_capacity`` slots ride on DenseState
  (``tr_meta`` = actor << 5 | kind, ``tr_data`` = payload, ``tr_tick``)
  plus a monotonic total-events counter ``tr_count`` (write position =
  count % K; dropped = max(0, count - K) — overflow wraps, never silently
  truncates) and a runtime arm flag ``tr_on``. With ``trace=None`` the
  kernels contain zero trace ops and lower bit-identically to the
  uninstrumented build (the ``faults=None`` pattern, models/faults.py).

Host-side, ``decode_trace`` unrolls the ring chronologically;
``trace_pretty`` renders the reference Logger's exact record strings (the
golden-parity surface against ``EpochTrace``); ``trace_to_perfetto`` emits
Chrome/Perfetto trace-event JSON (one track per node, snapshot attempts as
async spans, faults as instants); ``TelemetryWriter`` streams
schema-versioned JSONL metrics records for tools/analyze.py.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, NamedTuple, Optional

from chandy_lamport_tpu.core.spec import Message


@dataclasses.dataclass
class TraceEvent:
    node_id: str
    node_tokens: int  # balance when recorded (logger.go:18-23)
    text: str


class EpochTrace:
    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.epochs: List[List[TraceEvent]] = []

    def new_epoch(self) -> None:
        if self.enabled:
            self.epochs.append([])

    def _record(self, node, text: str) -> None:
        if self.enabled:
            self.epochs[-1].append(TraceEvent(node.id, node.tokens, text))

    def sent(self, node, dest: str, msg: Message) -> None:
        if not self.enabled:
            return
        if msg.is_marker:
            self._record(node, f"{node.id} sent marker({msg.data}) to {dest}")
        else:
            self._record(node, f"{node.id} sent {msg.data} tokens to {dest}")

    def received(self, node, src: str, msg: Message) -> None:
        if not self.enabled:
            return
        if msg.is_marker:
            self._record(node, f"{node.id} received marker({msg.data}) from {src}")
        else:
            self._record(node, f"{node.id} received {msg.data} tokens from {src}")

    def start_snapshot(self, node, snapshot_id: int) -> None:
        self._record(node, f"{node.id} startSnapshot({snapshot_id})")

    def end_snapshot(self, node, snapshot_id: int) -> None:
        self._record(node, f"{node.id} endSnapshot({snapshot_id})")

    def pretty(self) -> str:
        out = []
        for t, events in enumerate(self.epochs):
            if events:
                out.append(f"Time {t}:")
                out.extend(f"\t{e.node_id}: {e.text}" for e in events)
        return "\n".join(out)


# ---------------------------------------------------------------------------
# device trace ring: event vocabulary + packing
# ---------------------------------------------------------------------------

# Event kinds (5 bits of the packed meta word). The actor field is an EDGE
# index for the four message events (an edge names both endpoints), a NODE
# index for snapshot/supervisor/crash events, and 0 for the lane events
# (the lane is implicit — each lane owns its own ring).
EV_SEND = 0          # payload = token amount          actor = edge
EV_RECV = 1          # payload = token amount          actor = edge
EV_MSEND = 2         # payload = snapshot id           actor = edge
EV_MRECV = 3         # payload = snapshot id           actor = edge
EV_SNAP_START = 4    # payload = snapshot id           actor = node
EV_SNAP_END = 5      # payload = snapshot id           actor = node
EV_SUP_ABORT = 6     # payload = snapshot slot         actor = initiator node
EV_SUP_RETRY = 7     # payload = snapshot slot         actor = initiator node
EV_SUP_FAIL = 8      # payload = snapshot slot         actor = initiator node
EV_FAULT = 9         # payload = FC_* class            actor = edge (node for
#                                                      FC_CRASH)
EV_LANE_ADMIT = 10   # payload = job id                actor = 0
EV_LANE_HARVEST = 11  # payload = job id               actor = 0
EV_LANE_COALESCE = 12  # payload = follower count      actor = 0
EV_MEMO_HIT = 13     # payload = ticks fast-forwarded  actor = 0
EV_SERVE_ADMIT = 14  # payload = admit wait (steps)    actor = 0
EV_SERVE_MISS = 15   # payload = lateness (steps)      actor = 0
EV_PREFIX_FORK = 16  # payload = fork depth (phases)   actor = 0

EVENT_KIND_NAMES = (
    "send", "recv", "marker-send", "marker-recv", "snapshot-start",
    "snapshot-end", "supervisor-abort", "supervisor-retry",
    "supervisor-fail", "fault", "lane-admit", "lane-harvest",
    "lane-coalesce", "memo-hit", "serve-admit", "serve-miss",
    "prefix-fork")

_KIND_BITS = 5          # 17 kinds defined, headroom to 31
_KIND_MASK = (1 << _KIND_BITS) - 1


def pack_event(actor, kind):
    """meta word = actor << 5 | kind (plain arithmetic so it works on
    Python ints, numpy and jax arrays alike)."""
    return actor * (1 << _KIND_BITS) + kind


def unpack_event(meta):
    return meta >> _KIND_BITS, meta & _KIND_MASK


class JaxTrace:
    """Arming object for the device flight recorder (the ``JaxFaults``
    shape): pass ``trace=JaxTrace()`` to DenseSim / BatchedRunner /
    GraphShardedRunner to compile the event scatters into the tick
    kernels. ``capacity`` overrides ``SimConfig.trace_capacity`` when the
    config leaves it 0 (the runner bumps the config before building
    state)."""

    DEFAULT_CAPACITY = 4096

    def __init__(self, capacity: int = 0):
        if capacity < 0:
            raise ValueError("trace capacity must be >= 0")
        self.capacity = int(capacity)

    def describe(self) -> Dict[str, Any]:
        return {"capacity": self.capacity or self.DEFAULT_CAPACITY}


# ---------------------------------------------------------------------------
# jit-side append helpers (operate on the tr_* leaves of any state
# NamedTuple carrying them; statically the identity when capacity == 0)
# ---------------------------------------------------------------------------


def trace_append_many(s, mask, kind, actor, payload):
    """Ranked multi-event append: every True row of ``mask`` (any shape —
    flattened here) appends one event, in flattened row order, at
    consecutive ring positions. The scatter uses the OOB-drop idiom
    (ops/tick._append_rows): inactive rows aim past the ring and drop.
    Within one call the targets are (count + rank) % K for consecutive
    ranks, injective mod K whenever mask.size <= K — ``unique_indices``
    is only claimed under that static proof."""
    k = s.tr_meta.shape[-1]
    if k == 0:
        return s
    import jax.numpy as jnp

    i32 = jnp.int32
    mask = jnp.reshape(jnp.asarray(mask), (-1,))
    on = mask & (s.tr_on > 0)
    oni = on.astype(i32)
    # dtype pinned: under x64 the numpy-style accumulator promotion would
    # widen the ring counter to int64 and break while_loop carry typing
    rank = jnp.cumsum(oni, dtype=i32) - oni
    tgt = jnp.where(on, (s.tr_count + rank) % k, k)
    meta = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(pack_event(actor, kind), i32), (-1,)),
        mask.shape)
    data = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(payload, i32), (-1,)), mask.shape)
    unique = mask.size <= k
    return s._replace(
        tr_meta=s.tr_meta.at[tgt].set(meta, mode="drop",
                                      unique_indices=unique),
        tr_data=s.tr_data.at[tgt].set(data, mode="drop",
                                      unique_indices=unique),
        tr_tick=s.tr_tick.at[tgt].set(
            jnp.broadcast_to(jnp.asarray(s.time, i32), mask.shape),
            mode="drop", unique_indices=unique),
        tr_count=s.tr_count + jnp.sum(oni, dtype=i32),
    )


def trace_append_one(s, fire, kind, actor, payload):
    """Scalar conditional append: one event when ``fire`` is True."""
    k = s.tr_meta.shape[-1]
    if k == 0:
        return s
    import jax.numpy as jnp

    i32 = jnp.int32
    on = jnp.asarray(fire) & (s.tr_on > 0)
    tgt = jnp.where(on, s.tr_count % k, k)
    return s._replace(
        tr_meta=s.tr_meta.at[tgt].set(
            jnp.asarray(pack_event(actor, kind), i32), mode="drop"),
        tr_data=s.tr_data.at[tgt].set(jnp.asarray(payload, i32),
                                      mode="drop"),
        tr_tick=s.tr_tick.at[tgt].set(jnp.asarray(s.time, i32),
                                      mode="drop"),
        tr_count=s.tr_count + on.astype(i32),
    )


def trace_append_lanes(s, mask_b, kind, payload_b):
    """Per-lane conditional append on a BATCHED state ([B] leading axis on
    every tr_* leaf): lane b appends one event (actor 0 — the lane is the
    ring) when mask_b[b]. Used by the streaming engine's harvest/admit
    hooks (parallel/batch._build_stream_step)."""
    k = s.tr_meta.shape[-1]
    if k == 0:
        return s
    import jax.numpy as jnp

    i32 = jnp.int32
    on = jnp.asarray(mask_b) & (s.tr_on > 0)
    rows = jnp.arange(on.shape[0], dtype=i32)
    tgt = jnp.where(on, s.tr_count % k, k)
    meta = jnp.broadcast_to(jnp.asarray(pack_event(0, kind), i32), on.shape)
    return s._replace(
        tr_meta=s.tr_meta.at[rows, tgt].set(meta, mode="drop",
                                            unique_indices=True),
        tr_data=s.tr_data.at[rows, tgt].set(
            jnp.asarray(payload_b, i32), mode="drop", unique_indices=True),
        tr_tick=s.tr_tick.at[rows, tgt].set(
            jnp.asarray(s.time, i32), mode="drop", unique_indices=True),
        tr_count=s.tr_count + on.astype(i32),
    )


# ---------------------------------------------------------------------------
# host-side decoding + exporters
# ---------------------------------------------------------------------------


class TraceRecord(NamedTuple):
    tick: int
    kind: int
    actor: int
    payload: int

    @property
    def kind_name(self) -> str:
        return (EVENT_KIND_NAMES[self.kind]
                if 0 <= self.kind < len(EVENT_KIND_NAMES)
                else f"kind{self.kind}")


def trace_counts(state, capacity: Optional[int] = None):
    """(recorded, dropped) totals over however many lanes the state
    carries: recorded = min(count, K) summed, dropped = max(0, count - K)
    summed — the overflow policy's never-silent surface."""
    import numpy as np

    count = np.asarray(state.tr_count, dtype=np.int64)
    k = int(state.tr_meta.shape[-1] if capacity is None else capacity)
    recorded = np.minimum(count, k).sum()
    dropped = np.maximum(count - k, 0).sum()
    return int(recorded), int(dropped)


def decode_trace(state, lane: Optional[int] = None) -> List[TraceRecord]:
    """Unroll a state's trace ring chronologically. ``state`` is a host
    (numpy) state NamedTuple carrying tr_* leaves; pass ``lane`` to select
    one lane of a batched state. Events lost to ring wrap are simply
    absent (their count survives in tr_count — trace_counts)."""
    import numpy as np

    meta, data, tick, count = (state.tr_meta, state.tr_data,
                               state.tr_tick, state.tr_count)
    if lane is not None:
        meta, data, tick, count = (meta[lane], data[lane],
                                   tick[lane], count[lane])
    meta = np.asarray(meta)
    if meta.ndim != 1:
        raise ValueError("batched trace state needs an explicit lane=")
    data, tick = np.asarray(data), np.asarray(tick)
    k = meta.shape[0]
    count = int(count)
    live = min(count, k)
    out = []
    for i in range(count - live, count):
        pos = i % k
        actor, kind = unpack_event(int(meta[pos]))
        out.append(TraceRecord(int(tick[pos]), int(kind), int(actor),
                               int(data[pos])))
    return out


def _event_line(ev: TraceRecord, topo) -> str:
    """One decoded event in the reference Logger's record string format
    (common.go:75-122) prefixed with the acting node — the line shape
    EpochTrace.pretty() emits, so dense and parity traces diff cleanly."""
    ids = topo.ids
    if ev.kind in (EV_SEND, EV_RECV, EV_MSEND, EV_MRECV):
        src = ids[int(topo.edge_src[ev.actor])]
        dst = ids[int(topo.edge_dst[ev.actor])]
        if ev.kind == EV_SEND:
            return f"\t{src}: {src} sent {ev.payload} tokens to {dst}"
        if ev.kind == EV_RECV:
            return f"\t{dst}: {dst} received {ev.payload} tokens from {src}"
        if ev.kind == EV_MSEND:
            return f"\t{src}: {src} sent marker({ev.payload}) to {dst}"
        return f"\t{dst}: {dst} received marker({ev.payload}) from {src}"
    nid = ids[ev.actor] if 0 <= ev.actor < len(ids) else str(ev.actor)
    if ev.kind == EV_SNAP_START:
        return f"\t{nid}: {nid} startSnapshot({ev.payload})"
    if ev.kind == EV_SNAP_END:
        return f"\t{nid}: {nid} endSnapshot({ev.payload})"
    if ev.kind in (EV_SUP_ABORT, EV_SUP_RETRY, EV_SUP_FAIL):
        verb = {EV_SUP_ABORT: "supervisorAbort", EV_SUP_RETRY:
                "supervisorRetry", EV_SUP_FAIL: "supervisorFail"}[ev.kind]
        return f"\t{nid}: {nid} {verb}(slot {ev.payload})"
    if ev.kind == EV_FAULT:
        return f"\t{nid}: fault(class {ev.payload})"
    if ev.kind == EV_LANE_ADMIT:
        return f"\tlane: admit(job {ev.payload})"
    if ev.kind == EV_LANE_HARVEST:
        return f"\tlane: harvest(job {ev.payload})"
    if ev.kind == EV_LANE_COALESCE:
        return f"\tlane: coalesce({ev.payload} followers)"
    if ev.kind == EV_MEMO_HIT:
        return f"\tlane: memo-hit(fast-forwarded {ev.payload} ticks)"
    if ev.kind == EV_SERVE_ADMIT:
        return f"\tlane: serve-admit(waited {ev.payload} steps)"
    if ev.kind == EV_SERVE_MISS:
        return f"\tlane: serve-miss({ev.payload} steps late)"
    return f"\t?: {ev.kind_name}({ev.payload})"


def trace_pretty(events: List[TraceRecord], topo) -> str:
    """Render decoded events in EpochTrace.pretty()'s exact format:
    ``Time {t}:`` headers (non-empty ticks only) with one tab-indented
    record line per event. On a fault-free, supervisor-free run this is
    byte-comparable to the parity backend's trace
    (tests/test_trace.py)."""
    out: List[str] = []
    last_tick = None
    for ev in events:
        if ev.tick != last_tick:
            out.append(f"Time {ev.tick}:")
            last_tick = ev.tick
        out.append(_event_line(ev, topo))
    return "\n".join(out)


def trace_to_perfetto(events: List[TraceRecord], topo,
                      lane: int = 0, tick_us: int = 1000) -> Dict[str, Any]:
    """Chrome/Perfetto trace-event JSON for one lane's decoded events:
    one track (pid=lane, tid=node) per node, message/lane events as
    instants, snapshot attempts as async spans (ph 'b'/'e' keyed by
    snapshot id), faults as instants. Load in ui.perfetto.dev or
    chrome://tracing next to a ``jax.profiler`` xplane capture. Ticks are
    scaled to ``tick_us`` microseconds so the discrete timeline is
    scrubbable."""
    ids = topo.ids
    tev: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": lane,
         "args": {"name": f"lane {lane}"}}]
    for i, nid in enumerate(ids):
        tev.append({"name": "thread_name", "ph": "M", "pid": lane,
                    "tid": i, "args": {"name": f"node {nid}"}})
    sup_tid = len(ids)
    tev.append({"name": "thread_name", "ph": "M", "pid": lane,
                "tid": sup_tid, "args": {"name": "lane/supervisor"}})
    for ev in events:
        ts = ev.tick * tick_us
        if ev.kind in (EV_SEND, EV_MSEND):
            tid = int(topo.edge_src[ev.actor])
        elif ev.kind in (EV_RECV, EV_MRECV):
            tid = int(topo.edge_dst[ev.actor])
        elif ev.kind in (EV_SNAP_START, EV_SNAP_END, EV_SUP_ABORT,
                         EV_SUP_RETRY, EV_SUP_FAIL):
            tid = ev.actor if 0 <= ev.actor < len(ids) else sup_tid
        elif ev.kind == EV_FAULT:
            tid = ev.actor if 0 <= ev.actor < len(ids) else sup_tid
        else:
            tid = sup_tid
        base = {"pid": lane, "tid": tid, "ts": ts,
                "cat": ev.kind_name,
                "args": {"actor": ev.actor, "payload": ev.payload,
                         "tick": ev.tick}}
        if ev.kind == EV_SNAP_START:
            tev.append({**base, "name": f"snapshot {ev.payload}",
                        "ph": "b", "id": ev.payload, "cat": "snapshot"})
        elif ev.kind == EV_SNAP_END:
            tev.append({**base, "name": f"snapshot {ev.payload}",
                        "ph": "e", "id": ev.payload, "cat": "snapshot"})
        elif ev.kind == EV_FAULT:
            tev.append({**base, "name": f"fault class {ev.payload}",
                        "ph": "i", "s": "t"})
        else:
            tev.append({**base, "name": ev.kind_name, "ph": "i", "s": "t"})
    return {"traceEvents": tev, "displayTimeUnit": "ms"}


TELEMETRY_SCHEMA_VERSION = 1


class TelemetryWriter:
    """Structured JSONL telemetry: one self-describing record per line,
    each stamped with the schema version so tools/analyze.py (and any
    downstream consumer) can evolve safely. ``kind`` partitions the
    stream (run metadata vs per-step metrics vs final summary)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "w")

    def write(self, kind: str, record: Dict[str, Any]) -> None:
        row = {"schema": TELEMETRY_SCHEMA_VERSION, "kind": kind, **record}
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_telemetry(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL file, skipping unparseable lines (a killed
    writer can leave a torn tail) and rejecting records from a NEWER
    schema than this reader understands."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("schema", 0) > TELEMETRY_SCHEMA_VERSION:
                raise ValueError(
                    f"telemetry schema v{row['schema']} is newer than this "
                    f"reader (v{TELEMETRY_SCHEMA_VERSION})")
            out.append(row)
    return out
